"""Frontier detection / clustering / assignment tests on toy maps."""

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.config import FrontierConfig, GridConfig
from jax_mapping.ops import frontier as F


@pytest.fixture()
def gcfg():
    return GridConfig(size_cells=128, patch_cells=64, max_range_m=2.0,
                      align_rows=8, align_cols=8)


@pytest.fixture()
def fcfg():
    return FrontierConfig(downsample=2, max_clusters=8, min_cluster_cells=2,
                          label_prop_iters=64, bfs_iters=256)


def toy_logodds(gcfg):
    """64x64-coarse world: free square room in the middle, unknown outside,
    an occupied wall on the room's right edge with a gap (the frontier should
    appear on the open edges, not through the wall)."""
    n = gcfg.size_cells
    lo = np.zeros((n, n), np.float32)            # unknown everywhere
    lo[40:90, 40:90] = -2.0                      # free room
    lo[40:90, 88:90] = 2.0                       # right wall (occupied)
    lo[60:66, 88:90] = -2.0                      # gap in the wall
    return lo


def test_coarsen_masks(gcfg, fcfg):
    lo = toy_logodds(gcfg)
    free, occ, unknown = F.coarsen(fcfg, gcfg, jnp.asarray(lo))
    free, occ, unknown = map(np.asarray, (free, occ, unknown))
    n = gcfg.size_cells // fcfg.downsample
    assert free.shape == (n, n)
    assert free[30, 30] and not occ[30, 30]      # room interior
    assert occ[25, 44]                           # wall
    assert unknown[5, 5]                         # outside
    # Exclusive.
    assert not (free & occ).any() and not (free & unknown).any()


def test_frontier_mask_on_boundary(gcfg, fcfg):
    lo = toy_logodds(gcfg)
    free, occ, unknown = F.coarsen(fcfg, gcfg, jnp.asarray(lo))
    mask = np.asarray(F.frontier_mask(free, unknown))
    # Frontier on the room's left edge (free touching unknown).
    assert mask[25, 20]
    # No frontier inside the room.
    assert not mask[25, 30]
    # The wall itself is not frontier.
    assert not mask[25, 44]


def test_label_components_two_regions(fcfg):
    mask = np.zeros((32, 32), bool)
    mask[2:5, 2:5] = True         # blob A
    mask[20:24, 20:22] = True     # blob B
    labels = np.asarray(F.label_components(fcfg, jnp.asarray(mask)))
    la = labels[3, 3]
    lb = labels[21, 21]
    assert la >= 0 and lb >= 0 and la != lb
    assert (labels[2:5, 2:5] == la).all()
    assert (labels[20:24, 20:22] == lb).all()
    assert (labels[~mask] == -1).all()


def test_summarize_clusters_centroids(gcfg, fcfg):
    n = gcfg.size_cells // fcfg.downsample
    mask = np.zeros((n, n), bool)
    mask[10:12, 10:12] = True     # 4 cells
    mask[40:46, 40:41] = True     # 6 cells
    labels = F.label_components(fcfg, jnp.asarray(mask))
    centroids, targets, sizes, slots = F.summarize_clusters(fcfg, gcfg, labels)
    sizes = np.asarray(sizes)
    assert sorted(sizes[sizes > 0].tolist()) == [4, 6]
    # Biggest first via top_k.
    assert sizes[0] == 6
    # Centroid of the 6-cell blob: rows 40..45, col 40.
    c = np.asarray(centroids[0])
    res = gcfg.resolution_m * fcfg.downsample
    ox, oy = gcfg.origin_m
    assert c[0] == pytest.approx((40 + 0.5) * res + ox, abs=res)
    assert c[1] == pytest.approx((42.5 + 0.5) * res + oy, abs=res)


def test_cost_to_go_walls_block(fcfg):
    n = 32
    passable = np.ones((n, n), bool)
    passable[:, 16] = False       # vertical wall
    passable[0, 16] = True        # gap at top
    seeds = jnp.array([[16, 2]])
    dist = np.asarray(F.cost_to_go(fcfg, jnp.asarray(passable), seeds,
                                   jnp.array([True])))
    assert dist[16, 2] == 0
    # Right of the wall is reachable only through the top gap -> much longer
    # than the straight-line distance.
    straight = 28 - 2
    assert dist[16, 28] > straight * 1.3
    assert dist[16, 28] < 1e8     # but reachable
    # Wall cells unreachable.
    assert dist[5, 16] >= 1e8


def test_compute_frontiers_end_to_end(gcfg, fcfg):
    lo = toy_logodds(gcfg)
    # Robots inside the room (world coords: cell ~ (x/res + n/2)).
    res = gcfg.resolution_m
    n = gcfg.size_cells
    def world(row, col):
        return ((col - n / 2) * res, (row - n / 2) * res)
    x0, y0 = world(65, 65)
    x1, y1 = world(45, 45)
    robots = jnp.asarray(np.array([[x0, y0, 0.0], [x1, y1, 0.0]], np.float32))
    out = F.compute_frontiers(fcfg, gcfg, jnp.asarray(lo), robots)
    sizes = np.asarray(out.sizes)
    assert (sizes > 0).sum() >= 1          # found frontier(s)
    assign = np.asarray(out.assignment)
    assert (assign >= 0).all()             # both robots got a target
    costs = np.asarray(out.costs)
    for r in range(2):
        assert costs[r, assign[r]] < 1e8


def test_compute_frontiers_none_on_closed_map(gcfg, fcfg):
    n = gcfg.size_cells
    lo = np.full((n, n), -2.0, np.float32)   # everything known-free
    lo[0:2, :] = 2.0; lo[-2:, :] = 2.0; lo[:, 0:2] = 2.0; lo[:, -2:] = 2.0
    robots = jnp.zeros((1, 3))
    out = F.compute_frontiers(fcfg, gcfg, jnp.asarray(lo), robots)
    assert (np.asarray(out.sizes) == 0).all()
    assert int(out.assignment[0]) == -1


def test_euclidean_cost_mode(gcfg, fcfg):
    import dataclasses
    cheap = dataclasses.replace(fcfg, obstacle_aware=False)
    lo = toy_logodds(gcfg)
    robots = jnp.zeros((2, 3))
    out = F.compute_frontiers(cheap, gcfg, jnp.asarray(lo), robots)
    assert (np.asarray(out.sizes) > 0).sum() >= 1
    assert (np.asarray(out.assignment) >= 0).all()


def test_hierarchical_clustering_matches_exact(gcfg, fcfg):
    """cluster_downsample=2 finds the same clusters on the toy map (sizes in
    fine cells, targets on real fine frontier cells, both robots assigned)."""
    import dataclasses
    hier = dataclasses.replace(fcfg, cluster_downsample=2)
    lo = toy_logodds(gcfg)
    robots = jnp.asarray(np.array([[0.1, 0.1, 0.0], [-0.4, -0.4, 0.0]],
                                  np.float32))
    exact = F.compute_frontiers(fcfg, gcfg, jnp.asarray(lo), robots)
    fast = F.compute_frontiers(hier, gcfg, jnp.asarray(lo), robots)
    # Same total frontier mass in the kept slots (toy clusters are far
    # apart, so no merging happens at this scale).
    assert int(np.asarray(fast.sizes).sum()) == \
        int(np.asarray(exact.sizes).sum())
    assert ((np.asarray(fast.sizes) > 0).sum()
            == (np.asarray(exact.sizes) > 0).sum())
    # Targets are real fine frontier cells.
    mask = np.asarray(fast.mask)
    res = gcfg.resolution_m * fcfg.downsample
    ox, oy = gcfg.origin_m
    for k in range(int((np.asarray(fast.sizes) > 0).sum())):
        tx, ty = np.asarray(fast.targets)[k]
        r = int((ty - oy) / res)
        cc = int((tx - ox) / res)
        assert mask[r, cc], f"slot {k} target not on a fine frontier cell"
    assert (np.asarray(fast.assignment) >= 0).all()
    # Label/slot maps only on fine frontier cells.
    assert (np.asarray(fast.labels)[~mask] == -1).all()
    assert (np.asarray(fast.slots)[~mask] == -1).all()


def test_hierarchical_euclidean_mode(gcfg, fcfg):
    import dataclasses
    cfg = dataclasses.replace(fcfg, cluster_downsample=2,
                              obstacle_aware=False)
    lo = toy_logodds(gcfg)
    robots = jnp.zeros((3, 3))
    out = F.compute_frontiers(cfg, gcfg, jnp.asarray(lo), robots)
    assert (np.asarray(out.sizes) > 0).sum() >= 1
    assert (np.asarray(out.assignment) >= 0).all()


def test_summarize_dense_segment_parity(gcfg, fcfg, monkeypatch):
    """The dense one-hot/MXU slot formulation and the segment/gather
    fallback (chosen by _SUMMARIZE_DENSE_BYTES) must agree exactly."""
    lo = toy_logodds(gcfg)
    free, _occ, unknown = F.coarsen(fcfg, gcfg, jnp.asarray(lo))
    mask = F.frontier_mask(free, unknown)
    labels = F.label_components(fcfg, mask)

    dense = F._summarize(fcfg, gcfg, labels, None, 1)
    monkeypatch.setattr(F, "_SUMMARIZE_DENSE_BYTES", 0)
    seg = F._summarize(fcfg, gcfg, labels, None, 1)
    for a, b in zip(dense, seg):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_label_prop_pallas_parity(fcfg):
    """The Pallas label-propagation kernel (interpret mode off-TPU) matches
    the XLA fori_loop path on an irregular multi-component mask."""
    rng = np.random.default_rng(3)
    mask = jnp.asarray(rng.random((32, 32)) < 0.3)
    n = 32
    seed = jnp.where(mask, jnp.arange(n * n, dtype=jnp.int32).reshape(n, n),
                     jnp.int32(-1))
    got = F._label_prop_pallas(mask, seed, fcfg.label_prop_iters)

    import jax
    want = jax.lax.fori_loop(
        0, fcfg.label_prop_iters,
        lambda _, lab: F._neighbor_max_sweep(
            F._neighbor_max_sweep(lab, mask), mask),
        F._neighbor_max_sweep(seed, mask))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
