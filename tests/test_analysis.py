"""Fixture tests for the static-analysis checkers (`jax_mapping.analysis`).

Each checker (A1-A4, B1-B3) gets at least one known-bad snippet it must
flag and one known-clean snippet it must stay silent on — the contract
ISSUE 1 gates on. Snippets are analyzed in-memory via
`SourceModule.from_source`, so these tests never touch the real package
(that is `test_analysis_selfcheck.py`'s job) and stay immune to
unrelated repo edits.
"""

import json
import textwrap
import threading

from jax_mapping.analysis import jax_hazards, lock_discipline
from jax_mapping.analysis.core import (
    Baseline, Finding, SourceModule, analyze_modules,
)
from jax_mapping.analysis.lockwatch import LockWatch


def run_checker(checker, src, path="jax_mapping/ops/snippet.py"):
    mod = SourceModule.from_source(textwrap.dedent(src), path=path)
    return list(checker.run([mod]))


def ids(findings):
    return [f.checker for f in findings]


# ---------------------------------------------------------------- A1

def test_a1_flags_np_asarray_on_traced_value_inside_jit():
    findings = run_checker(jax_hazards.HostSyncChecker(), """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def fuse(scan):
            host = np.asarray(scan)
            return jnp.sum(host)
        """)
    assert ids(findings) == ["A1-host-sync"]
    assert findings[0].severity == "error"
    assert findings[0].symbol == "fuse"


def test_a1_flags_item_and_float_on_traced_values():
    findings = run_checker(jax_hazards.HostSyncChecker(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def score(x):
            s = jnp.sum(x)
            return s.item()

        @jax.jit
        def scale(x):
            return float(x) * 2.0
        """)
    assert ids(findings) == ["A1-host-sync", "A1-host-sync"]
    assert {f.symbol for f in findings} == {"score", "scale"}


def test_a1_flags_sync_chained_on_call_result():
    """`jnp.sum(x).item()` — the most common one-line form: the traced
    result never gets a name, so the receiver chain is call-rooted and
    must be judged by the expression itself."""
    findings = run_checker(jax_hazards.HostSyncChecker(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def total(x):
            return jnp.sum(x).item()

        @jax.jit
        def as_host(x):
            return float(jnp.max(x))
        """)
    assert ids(findings) == ["A1-host-sync", "A1-host-sync"]
    assert {f.symbol for f in findings} == {"total", "as_host"}


def test_a1_silent_on_pure_jit_and_host_side_numpy():
    findings = run_checker(jax_hazards.HostSyncChecker(), """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def fuse(scan):
            return jnp.sum(scan * 2.0)

        def host_prep(raw_list):
            # host value, never traced: converting it is fine anywhere
            return np.asarray(raw_list)
        """)
    assert findings == []


def test_a1_flags_sync_on_jit_result_in_timer_hot_path():
    src = """
        import functools
        import jax
        import numpy as np

        @functools.partial(jax.jit, static_argnums=(1,))
        def step(state, n):
            return state + n

        class MapperNode:
            def __init__(self, cfg):
                self.state = None
                self.create_timer(0.1, self.tick)

            def tick(self):
                out = step(self.state, 3)
                return float(out)
        """
    findings = run_checker(jax_hazards.HostSyncChecker(), src,
                           path="jax_mapping/bridge/snippet.py")
    assert ids(findings) == ["A1-host-sync"]
    assert findings[0].severity == "warning"
    assert findings[0].symbol == "MapperNode.tick"


def test_a1_silent_in_hot_path_without_device_values():
    src = """
        import numpy as np

        class StatusNode:
            def __init__(self, cfg):
                self.rows = []
                self.create_timer(1.0, self.tick)

            def tick(self):
                # plain host data: np.asarray here is not a device sync
                return np.asarray(self.rows)
        """
    findings = run_checker(jax_hazards.HostSyncChecker(), src,
                           path="jax_mapping/bridge/snippet.py")
    assert findings == []


# ---------------------------------------------------------------- A2

def test_a2_flags_python_if_on_traced_value():
    findings = run_checker(jax_hazards.JitHygieneChecker(), """
        import jax

        @jax.jit
        def clip(x):
            if x > 0:
                return x
            return -x
        """)
    assert ids(findings) == ["A2-jit-hygiene"]
    assert "if" in findings[0].message


def test_a2_flags_for_over_traced_range_and_bad_static_argnums():
    findings = run_checker(jax_hazards.JitHygieneChecker(), """
        import functools
        import jax

        @jax.jit
        def unroll(x, n):
            acc = x
            for i in range(n):
                acc = acc + i
            return acc

        @functools.partial(jax.jit, static_argnums=(5,))
        def lonely(x):
            return x
        """)
    assert sorted(ids(findings)) == ["A2-jit-hygiene", "A2-jit-hygiene"]
    messages = " | ".join(f.message for f in findings)
    assert "range" in messages and "out of range" in messages


def test_a2_flags_unhashable_literal_in_static_position():
    findings = run_checker(jax_hazards.JitHygieneChecker(), """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def reshape(x, shape):
            return x.reshape(shape)

        def caller(x):
            return reshape(x, [4, 4])
        """)
    assert ids(findings) == ["A2-jit-hygiene"]
    assert findings[0].symbol == "caller"


def test_a2_silent_on_static_branch_and_hashable_static_args():
    findings = run_checker(jax_hazards.JitHygieneChecker(), """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=(1,))
        def fuse(x, n_windows):
            if n_windows > 2:          # static: plain Python int
                x = x * 2.0
            for _ in range(n_windows):  # static range: fixed unroll
                x = x + 1.0
            return jnp.where(x > 0, x, -x)

        def caller(x):
            return fuse(x, 4)
        """)
    assert findings == []


# ---------------------------------------------------------------- A3

def test_a3_flags_float64_and_dtypeless_float_arrays_in_tpu_path():
    findings = run_checker(jax_hazards.DtypeDriftChecker(), """
        import numpy as np

        def make_scale():
            return np.float64(3.0)

        def make_offsets():
            return np.array([0.5, 1.5])

        def make_field(n):
            return np.full(n, 0.0, dtype=float)
        """)
    assert ids(findings) == ["A3-dtype-drift"] * 3
    assert {f.symbol for f in findings} == \
        {"make_scale", "make_offsets", "make_field"}


def test_a3_silent_with_explicit_float32_or_outside_tpu_path():
    clean = """
        import numpy as np

        def make_offsets():
            return np.array([0.5, 1.5], np.float32)

        def make_index():
            return np.array([1, 2, 3])
        """
    assert run_checker(jax_hazards.DtypeDriftChecker(), clean) == []
    # float64 is fine in modules that never feed the device path
    host_only = """
        import numpy as np

        def exact_millimetres(r):
            return np.float64(r) * 1000.0
        """
    assert run_checker(jax_hazards.DtypeDriftChecker(), host_only,
                       path="jax_mapping/analysis/snippet.py") == []


# ---------------------------------------------------------------- A4

def test_a4_flags_time_call_and_self_mutation_under_jit():
    findings = run_checker(jax_hazards.ImpureJitChecker(), """
        import time
        import jax

        @jax.jit
        def stamp(x):
            return x * time.time()

        class Model:
            @jax.jit
            def step(self, x):
                self.cache = x
                return x
        """)
    assert ids(findings) == ["A4-impure-jit"] * 2
    messages = " | ".join(f.message for f in findings)
    assert "trace time" in messages and "self" in messages


def test_a4_flags_impurity_in_transitive_callee():
    findings = run_checker(jax_hazards.ImpureJitChecker(), """
        import random
        import jax

        def jitter(x):
            return x + random.random()

        @jax.jit
        def step(x):
            return jitter(x)
        """)
    assert ids(findings) == ["A4-impure-jit"]
    assert findings[0].symbol == "jitter"


def test_a4_silent_on_jax_random_and_host_side_time():
    findings = run_checker(jax_hazards.ImpureJitChecker(), """
        import time
        import jax
        import jax.numpy as jnp

        @jax.jit
        def noisy(x, key):
            return x + jax.random.normal(key, x.shape)

        def wall_clock():
            # never reached from a jit site
            return time.time()
        """)
    assert findings == []


# ---------------------------------------------------------------- B1

_B1_BAD = """
    import threading

    class Pipeline:
        def __init__(self):
            self._head = threading.Lock()
            self._tail = threading.Lock()

        def forward(self):
            with self._head:
                with self._tail:
                    pass

        def backward(self):
            with self._tail:
                with self._head:
                    pass
    """


def test_b1_flags_lock_order_cycle():
    findings = run_checker(lock_discipline.LockOrderChecker(), _B1_BAD,
                           path="jax_mapping/bridge/snippet.py")
    assert len(findings) == 2          # both edges of the cycle reported
    assert set(ids(findings)) == {"B1-lock-order"}
    assert all("Pipeline._head" in f.message and "Pipeline._tail"
               in f.message for f in findings)


def test_b1_sees_nesting_through_method_calls():
    findings = run_checker(lock_discipline.LockOrderChecker(), """
        import threading

        class Pipeline:
            def __init__(self):
                self._head = threading.Lock()
                self._tail = threading.Lock()

            def _drain(self):
                with self._tail:
                    pass

            def forward(self):
                with self._head:
                    self._drain()       # head -> tail, hidden in a call

            def backward(self):
                with self._tail:
                    with self._head:
                        pass
        """, path="jax_mapping/bridge/snippet.py")
    assert len(findings) == 2
    assert set(ids(findings)) == {"B1-lock-order"}


def test_b1_silent_on_consistent_order_and_condition_aliases():
    findings = run_checker(lock_discipline.LockOrderChecker(), """
        import threading

        class Pipeline:
            def __init__(self):
                self._head = threading.Lock()
                self._tail = threading.Lock()
                # Condition over _head IS _head, not a third lock
                self._ready = threading.Condition(self._head)

            def forward(self):
                with self._head:
                    with self._tail:
                        pass

            def flush(self):
                with self._ready:
                    with self._tail:
                        pass
        """, path="jax_mapping/bridge/snippet.py")
    assert findings == []


# ---------------------------------------------------------------- B2

def test_b2_flags_callback_and_publish_under_lock():
    findings = run_checker(lock_discipline.CallbackUnderLockChecker(), """
        import threading

        class Topic:
            def __init__(self):
                self._lock = threading.Lock()
                self._subs = []
                self.pub = None

            def deliver(self, msg):
                with self._lock:
                    for sub in self._subs:
                        sub.callback(msg)

            def forward(self, msg):
                with self._lock:
                    self.pub.publish(msg)
        """, path="jax_mapping/bridge/snippet.py")
    assert ids(findings) == ["B2-callback-lock"] * 2
    assert all("Topic._lock" in f.message for f in findings)


def test_b2_silent_when_snapshot_taken_then_lock_released():
    findings = run_checker(lock_discipline.CallbackUnderLockChecker(), """
        import threading

        class Topic:
            def __init__(self):
                self._lock = threading.Lock()
                self._subs = []

            def deliver(self, msg):
                with self._lock:
                    subs = list(self._subs)
                for sub in subs:
                    sub.callback(msg)

            def wake(self):
                with self._lock:
                    self._lock.release()   # lock protocol, not a callback
                    self._lock.acquire()
        """, path="jax_mapping/bridge/snippet.py")
    assert findings == []


# ---------------------------------------------------------------- B3

def test_b3_flags_unguarded_write_to_lock_protected_state():
    findings = run_checker(lock_discipline.UnguardedWriteChecker(), """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = None

            def get(self):
                with self._lock:
                    return self.value

            def set_fast(self, v):
                self.value = v          # racing get()'s guarded read
        """, path="jax_mapping/bridge/snippet.py")
    assert ids(findings) == ["B3-unguarded-write"]
    assert findings[0].symbol == "Cache.set_fast"
    assert "self.value" in findings[0].message


def test_b3_silent_when_writes_guarded_or_state_never_shared():
    findings = run_checker(lock_discipline.UnguardedWriteChecker(), """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = None
                self.n_sets = 0         # never accessed under the lock

            def get(self):
                with self._lock:
                    return self.value

            def set(self, v):
                with self._lock:
                    self.value = v
                self.n_sets += 1
        """, path="jax_mapping/bridge/snippet.py")
    assert findings == []


def test_b3_locked_helper_convention_is_interprocedural():
    """A private helper whose EVERY same-class call site holds the lock
    (lexically, or one hop up through another such helper) runs
    lock-held at runtime: its writes are guarded, with no `with` in its
    own body. This is the `_locked` suffix contract the tenancy control
    plane relies on (its zero-suppression tier forbids baselining)."""
    findings = run_checker(lock_discipline.UnguardedWriteChecker(), """
        import threading

        class Plane:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = None
                self.n_folds = 0

            def step(self):
                with self._lock:
                    self._fold_locked()

            def _fold_locked(self):
                self.value = 1          # guarded via step()'s lock
                self._install_locked()

            def _install_locked(self):
                self.n_folds += 1       # guarded two hops up
        """, path="jax_mapping/bridge/snippet.py")
    assert findings == []


def test_b3_helper_with_any_unlocked_entry_still_flags():
    """One unlocked call site — or escaping as a callback value —
    disqualifies a helper: the write CAN race the guarded readers."""
    findings = run_checker(lock_discipline.UnguardedWriteChecker(), """
        import threading

        class Plane:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = None
                self.count = 0

            def read(self):
                with self._lock:
                    return self.value, self.count

            def step(self):
                with self._lock:
                    self._fold()

            def fast_path(self):
                self._fold()            # unlocked entry

            def arm(self, timer):
                with self._lock:
                    timer.cb = self._escapes   # callback: unlocked entry

            def _fold(self):
                self.value = 1

            def _escapes(self):
                self.count += 1
        """, path="jax_mapping/bridge/snippet.py")
    assert sorted((f.symbol, f.checker) for f in findings) == [
        ("Plane._escapes", "B3-unguarded-write"),
        ("Plane._fold", "B3-unguarded-write"),
    ]


def test_b3_public_and_uncalled_methods_never_qualify():
    findings = run_checker(lock_discipline.UnguardedWriteChecker(), """
        import threading

        class Plane:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = None
                self.other = None

            def read(self):
                with self._lock:
                    return self.value, self.other

            def step(self):
                with self._lock:
                    self.apply()        # public: outside callers exist

            def apply(self):
                self.value = 1

            def _never_called(self):
                self.other = 2
        """, path="jax_mapping/bridge/snippet.py")
    assert sorted(f.symbol for f in findings) == [
        "Plane._never_called", "Plane.apply"]


# ------------------------------------------------------- baseline plumbing

def test_baseline_suppresses_and_reports_unused(tmp_path):
    mod = SourceModule.from_source(textwrap.dedent("""
        import jax
        import numpy as np

        @jax.jit
        def fuse(scan):
            return np.asarray(scan)
        """), path="jax_mapping/ops/snippet.py")
    checkers = [jax_hazards.HostSyncChecker()]
    raw = analyze_modules([mod], baseline=None, checkers=checkers)
    assert len(raw.findings) == 1

    # Accept the finding, add one stale suppression on top (same file,
    # so the run has full context — a line that no longer exists).
    path = str(tmp_path / "baseline.json")
    Baseline.dump(raw.findings, path)
    data = json.load(open(path))
    data["suppressions"].append({
        "checker": "A1-host-sync", "path": mod.path,
        "symbol": "fuse", "code": "x = np.asarray(y_removed)"})
    json.dump(data, open(path, "w"))

    res = analyze_modules([mod], baseline=Baseline.load(path),
                          checkers=checkers)
    assert res.findings == []
    assert len(res.baselined) == 1
    assert len(res.unused_suppressions) == 1
    assert res.unused_suppressions[0]["code"] == "x = np.asarray(y_removed)"


def test_unused_reporting_needs_full_context(tmp_path):
    """A path-subset run finds strictly less than the package-wide pass
    (the A checkers build a cross-module jit registry), so it must not
    call other files' — or even its own file's — suppressions stale."""
    mod = SourceModule.from_source(textwrap.dedent("""
        import numpy as np

        def harmless():
            return np.zeros(3, np.float32)
        """), path="jax_mapping/ops/snippet.py")
    base = Baseline([{
        "checker": "A1-host-sync", "path": "jax_mapping/ops/other.py",
        "symbol": "f", "code": "x = np.asarray(y)", "note": "boundary"}])
    res = analyze_modules([mod], baseline=base,
                          checkers=[jax_hazards.HostSyncChecker()])
    assert res.findings == []
    assert res.unused_suppressions == []


def test_finding_key_survives_line_moves():
    a = Finding("A1-host-sync", "error", "p.py", 10, "f", "m", "x = 1")
    b = Finding("A1-host-sync", "error", "p.py", 99, "f", "m", "x = 1")
    assert a.key == b.key


# ------------------------------------------------------------- lockwatch

class _Box:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.RLock()


def test_lockwatch_records_edges_and_detects_cycles():
    box = _Box()
    watch = LockWatch()
    assert watch.watch(box, "_a") == "_Box._a"
    watch.watch(box, "_b")
    with box._a:
        with box._b:
            pass
    assert watch.cycle() is None
    with box._b:
        with box._a:
            pass
    watch.unwatch_all()
    assert ("_Box._a", "_Box._b") in watch.edges()
    assert ("_Box._b", "_Box._a") in watch.edges()
    cycle = watch.cycle()
    assert cycle is not None and set(cycle) >= {"_Box._a", "_Box._b"}


def test_lockwatch_reentrant_rlock_is_not_a_self_edge():
    box = _Box()
    watch = LockWatch()
    watch.watch(box, "_b")
    with box._b:
        with box._b:                   # RLock re-acquire on same thread
            pass
    watch.unwatch_all()
    assert watch.edges() == set()
    assert watch.cycle() is None


def test_lockwatch_unwatch_restores_real_locks():
    box = _Box()
    watch = LockWatch()
    watch.watch(box, "_a")
    watch.unwatch_all()
    assert isinstance(box._a, type(threading.Lock()))


def test_lockwatch_check_against_static_reports_missed_edges():
    box = _Box()
    watch = LockWatch()
    watch.watch(box, "_a")
    watch.watch(box, "_b")
    with box._b:
        with box._a:
            pass
    watch.unwatch_all()
    static = {("_Box._a", "_Box._b")}
    assert watch.check_against_static(static) == {("_Box._b", "_Box._a")}
    # edges touching locks the static graph never saw are ignored
    assert watch.check_against_static({("Other.x", "Other.y")}) == set()


# ------------------------------------------------------------- C1

def test_c1_flags_revision_read_after_content():
    """The PR 4 voxel serving_snapshot inversion: grid snapshotted
    first, revision stamped second — a fusion between the reads stamps
    OLD content with the NEW revision, served as current forever."""
    from jax_mapping.analysis.revision_order import RevisionOrderChecker
    findings = run_checker(RevisionOrderChecker(), """
        import numpy as np

        class VoxelMapperNode:
            def serving_snapshot(self):
                grid = self.voxel_grid()
                hm = np.asarray(self._V.height_map(self.cfg.voxel, grid))
                rev = self.n_images_fused + self.map_revision
                return rev, hm
        """)
    assert ids(findings) == ["C1-revision-order"]
    assert findings[0].symbol == "VoxelMapperNode.serving_snapshot"
    assert "map_revision" in findings[0].code


def test_c1_clean_revision_before_content_and_recheck():
    """Revision-first passes; so does the cache-validate idiom that
    RE-reads the revision after content (the first read came first)."""
    from jax_mapping.analysis.revision_order import RevisionOrderChecker
    findings = run_checker(RevisionOrderChecker(), """
        import numpy as np

        class VoxelMapperNode:
            def serving_snapshot(self):
                rev = self.n_images_fused + self.map_revision
                grid = self.voxel_grid()
                return rev, np.asarray(self._V.height_map(self.cfg, grid))

            def cached_build(self):
                rev = self.map_revision
                grid = self.voxel_grid()
                if self.map_revision != rev:     # staleness re-check
                    return None
                return rev, grid
        """)
    assert findings == []


def test_c1_flags_cross_object_planner_ordering():
    """The PR 6 planner-tick hazard: the mapper's grid read before its
    revision, on a receiver OTHER than self."""
    from jax_mapping.analysis.revision_order import RevisionOrderChecker
    findings = run_checker(RevisionOrderChecker(), """
        class PlannerNode:
            def _planning_grid(self):
                lo = self.mapper.merged_grid()
                lo_rev = self.mapper.serving_revision()
                return lo_rev, lo
        """)
    assert ids(findings) == ["C1-revision-order"]


def test_c1_lock_atomic_snapshot_is_exempt():
    """Reads under a held lock are atomic with respect to writers of
    that lock — order inside the region is irrelevant (C2's territory
    is tears ACROSS regions)."""
    from jax_mapping.analysis.revision_order import RevisionOrderChecker
    findings = run_checker(RevisionOrderChecker(), """
        class MapperNode:
            def serving_snapshot(self):
                with self._state_lock:
                    grid = self.shared_grid
                    rev = self.map_revision
                return rev, grid
        """)
    assert findings == []


# ------------------------------------------------------------- C2

TEAR_PROTECTION = None


def _tear_protection():
    from jax_mapping.analysis.protection import group
    return [group("MapperNode", "_state_lock",
                  ["states", "shared_grid"],
                  lockfree_ok=["map_revision"])]


def test_c2_flags_publish_frontiers_tear():
    """The historical pose/grid tear: poses under the lock, the grid
    via a self-method that LOCKS INTERNALLY — two atomic sections, a
    writer between them pairs state no writer produced."""
    from jax_mapping.analysis.snapshot_tear import SnapshotTearChecker
    findings = run_checker(SnapshotTearChecker(_tear_protection()), """
        import threading
        import numpy as np

        class MapperNode:
            def __init__(self):
                self._state_lock = threading.Lock()
                self.states = []
                self.shared_grid = None

            def merged_grid(self):
                with self._state_lock:
                    return self.shared_grid

            def publish_frontiers(self):
                with self._state_lock:
                    poses = np.stack([s.pose for s in self.states])
                lo = self.merged_grid()
                return poses, lo
        """)
    assert ids(findings) == ["C2-snapshot-tear"]
    assert findings[0].symbol == "MapperNode.publish_frontiers"
    assert "shared_grid" in findings[0].message


def test_c2_clean_single_section_and_cas_paths():
    """One consistent region passes; so do read-compute-reinstall
    writers (their second region re-reads the group to VALIDATE — the
    tear defense, not the tear)."""
    from jax_mapping.analysis.snapshot_tear import SnapshotTearChecker
    findings = run_checker(SnapshotTearChecker(_tear_protection()), """
        import threading
        import numpy as np

        class MapperNode:
            def __init__(self):
                self._state_lock = threading.Lock()
                self.states = []
                self.shared_grid = None
                self.map_revision = 0

            def publish_frontiers(self):
                with self._state_lock:
                    poses = np.stack([s.pose for s in self.states])
                    lo = self.shared_grid
                return poses, lo

            def step(self, fused):
                with self._state_lock:
                    base_grid = self.shared_grid
                    base_rev = self.map_revision
                out = fused(base_grid)
                with self._state_lock:
                    if self.shared_grid is not base_grid:
                        return
                    self.shared_grid = out
                    self.map_revision += 1
        """)
    assert findings == []


def test_c2_rereading_same_fields_is_not_a_tear():
    """A second region re-reading the SAME fields (freshness re-check)
    adds no inconsistent pairing."""
    from jax_mapping.analysis.snapshot_tear import SnapshotTearChecker
    findings = run_checker(SnapshotTearChecker(_tear_protection()), """
        import threading

        class MapperNode:
            def __init__(self):
                self._state_lock = threading.Lock()
                self.shared_grid = None

            def poll(self):
                with self._state_lock:
                    g0 = self.shared_grid
                with self._state_lock:
                    changed = self.shared_grid is not g0
                return changed
        """)
    assert findings == []


def test_c2_condition_alias_counts_as_the_lock():
    """A Condition constructed over the group lock IS the lock: reading
    group fields under `with self._not_empty:` is one section of the
    same group."""
    from jax_mapping.analysis.snapshot_tear import SnapshotTearChecker
    from jax_mapping.analysis.protection import group
    prot = [group("Q", "_lock", ["_queue", "_closed"])]
    findings = run_checker(SnapshotTearChecker(prot), """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._not_empty = threading.Condition(self._lock)
                self._queue = []
                self._closed = False

            def peek(self):
                with self._not_empty:
                    q = list(self._queue)
                with self._lock:
                    closed = self._closed
                return q, closed
        """)
    assert ids(findings) == ["C2-snapshot-tear"]


# ------------------------------------------------------------- C3

def test_c3_flags_write_into_asarray_of_jitted_result():
    """The PR 6 gotcha: np.asarray of a device array is a zero-copy
    READ-ONLY view; the in-place write raises only on the branch that
    reaches it."""
    from jax_mapping.analysis.device_views import DeviceViewMutationChecker
    findings = run_checker(DeviceViewMutationChecker(), """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def refresh_tiles(masks):
            return jnp.sum(masks)

        class Pipeline:
            def step(self, dirty, obs_f, ndirty):
                obs = np.asarray(refresh_tiles(obs_f))
                self._tile_observed[dirty] = obs[:ndirty]   # read: fine
                obs[0] = True                               # write: boom
                return obs
        """)
    assert ids(findings) == ["C3-device-view"]
    assert "obs[0]" in findings[0].code


def test_c3_view_taint_propagates_and_copies_sanitize():
    """Slices of a read-only stack are read-only views; np.array /
    .copy() reassignments clear the taint. Flags in-place methods and
    np.copyto destinations too."""
    from jax_mapping.analysis.device_views import DeviceViewMutationChecker
    ops_mod = SourceModule.from_source(textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def height_map(cfg, grid):
            return jnp.max(grid, axis=0)
        """), path="jax_mapping/ops/voxel.py")
    node_mod = SourceModule.from_source(textwrap.dedent("""
        import numpy as np
        from jax_mapping.ops import voxel as V

        class Node:
            def __init__(self):
                self._V = V

            def export(self, grid):
                hm = np.asarray(self._V.height_map(self.cfg, grid))
                row = hm[0]
                row.fill(0)
                np.copyto(hm, 1.0)
                return hm

            def export_fixed(self, grid):
                hm = np.array(self._V.height_map(self.cfg, grid))
                hm[0] = 1
                view = np.asarray(self._V.height_map(self.cfg, grid))
                view = view.copy()
                view[0] = 2
                return hm, view
        """), path="jax_mapping/bridge/node2.py")
    findings = list(DeviceViewMutationChecker().run([ops_mod, node_mod]))
    assert ids(findings) == ["C3-device-view", "C3-device-view"]
    assert all(f.symbol == "Node.export" for f in findings)


def test_c3_host_asarray_is_clean():
    """np.asarray over plain host data is writable — no device source,
    no finding (the checker degrades to silence, not false positives)."""
    from jax_mapping.analysis.device_views import DeviceViewMutationChecker
    findings = run_checker(DeviceViewMutationChecker(), """
        import numpy as np

        def embed(occupancy):
            occ = np.asarray(occupancy, np.int8)
            out = np.full(occ.shape, -1, np.int8)
            out[occ == 0] = 1
            return out
        """)
    assert findings == []


# ------------------------------------------------------------- C4

def test_c4_flags_unbucketed_static_arg_and_slice():
    from jax_mapping.analysis.shape_churn import ShapeChurnChecker
    findings = run_checker(ShapeChurnChecker(), """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=(1,))
        def fuse(ranges, n):
            return jnp.sum(ranges[:n])

        def tick(scans):
            n = len(scans)
            return fuse(jnp.asarray(scans[:n]), n)
        """)
    assert ids(findings) == ["C4-shape-churn", "C4-shape-churn"]
    assert {f.symbol for f in findings} == {"tick"}


def test_c4_bucketing_sanitizes():
    """pow2 bucketing (named helper OR explicit 2**k / 1<<k arithmetic)
    before the boundary is the sanctioned fix."""
    from jax_mapping.analysis.shape_churn import ShapeChurnChecker
    findings = run_checker(ShapeChurnChecker(), """
        import functools
        import jax
        import jax.numpy as jnp

        def next_pow2(n):
            return 1 << max(0, (n - 1)).bit_length()

        @functools.partial(jax.jit, static_argnums=(1,))
        def fuse(ranges, n):
            return jnp.sum(ranges[:n])

        def tick(scans):
            n = next_pow2(len(scans))
            return fuse(jnp.asarray(scans[:n]), n)

        def tick_inline(scans):
            n = 2 ** max(1, len(scans)).bit_length()
            return fuse(jnp.asarray(scans[:n]), n)
        """)
    assert findings == []


def test_c4_static_kwarg_and_config_values_clean():
    """Config-derived and constant static args are not dynamic; a
    dynamic static KEYWORD is flagged through static_argnames."""
    from jax_mapping.analysis.shape_churn import ShapeChurnChecker
    findings = run_checker(ShapeChurnChecker(), """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("span",))
        def crop(grid, span=8):
            return grid[:span, :span]

        def good(self, grid):
            return crop(grid, span=self.cfg.grid.patch_cells)

        def bad(self, grid, mask):
            return crop(grid, span=int(mask.sum()))
        """)
    assert ids(findings) == ["C4-shape-churn"]
    assert findings[0].symbol == "bad"


def test_c4_jitted_bodies_are_exempt():
    """Inside jit, .shape reads are trace-static Python ints — churn is
    a caller-side hazard only."""
    from jax_mapping.analysis.shape_churn import ShapeChurnChecker
    findings = run_checker(ShapeChurnChecker(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def inner(ranges):
            n = ranges.shape[0]
            return jnp.sum(ranges[:n])

        @jax.jit
        def outer(ranges):
            return inner(ranges[: ranges.shape[0] // 2])
        """)
    assert findings == []


# ------------------------------------------------------------- racewatch

def _drive_two_threads(fn_a, fn_b, n=60):
    import time

    def loop(fn):
        for i in range(n):
            fn(i)
            time.sleep(0.0005)

    ts = [threading.Thread(target=loop, args=(fn_a,)),
          threading.Thread(target=loop, args=(fn_b,))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


class _RacyStore:
    """Fixture: `revision`+`tiles` declared under _lock, but the writer
    takes _wrong — the seeded race the detector must catch."""

    def __init__(self):
        self._lock = threading.Lock()
        self._wrong = threading.Lock()
        self.tiles = {}
        self.revision = 0

    def read(self):
        with self._lock:
            return dict(self.tiles), self.revision

    def install_ok(self, rev):
        with self._lock:
            self.tiles[rev] = b"x"
            self.revision = rev

    def install_racy(self, rev):
        with self._wrong:
            self.tiles[rev] = b"x"
            self.revision = rev


def _store_group():
    from jax_mapping.analysis.protection import group
    return group("_RacyStore", "_lock", ["tiles", "revision"])


def test_racewatch_flags_write_under_wrong_lock():
    from jax_mapping.analysis.racewatch import RaceWatch
    w = RaceWatch()
    s = _RacyStore()
    w.watch_lock(s, "_wrong")
    w.watch_object(s, _store_group(), name="store")
    _drive_two_threads(s.install_racy, lambda _i: s.read())
    w.unwatch_all()
    reports = w.reports()
    assert any("revision" in r.field for r in reports), \
        [r.field for r in reports]
    assert "candidate lockset EMPTY" in reports[0].message


def test_racewatch_correct_lock_is_clean_and_refined():
    from jax_mapping.analysis.racewatch import RaceWatch
    w = RaceWatch()
    s = _RacyStore()
    w.watch_object(s, _store_group(), name="store")
    _drive_two_threads(s.install_ok, lambda _i: s.read())
    w.unwatch_all()
    assert w.reports() == []
    st = w.field_states()["_RacyStore.revision@store"]
    # Eraser refinement converged on exactly the declared lock.
    assert st.state == "shared-modified"
    assert st.candidate == frozenset({"_RacyStore._lock@store"})


def test_racewatch_single_thread_init_is_exempt():
    """Eraser's EXCLUSIVE state: lock-free single-owner setup (the
    constructor pattern) never refines, so it cannot report."""
    from jax_mapping.analysis.racewatch import RaceWatch
    w = RaceWatch()
    s = _RacyStore()
    w.watch_object(s, _store_group(), name="store")
    for i in range(10):
        s.tiles[i] = b"y"            # lock-free, one thread: fine
        s.revision = i
    w.unwatch_all()
    assert w.reports() == []
    assert w.field_states()["_RacyStore.revision@store"].state \
        == "exclusive"


def test_racewatch_unwatch_restores_class_and_locks():
    from jax_mapping.analysis.racewatch import RaceWatch
    w = RaceWatch()
    s = _RacyStore()
    w.watch_object(s, _store_group(), name="store")
    assert type(s).__name__ == "Raced_RacyStore"
    w.unwatch_all()
    assert type(s) is _RacyStore
    assert isinstance(s._lock, type(threading.Lock()))


# ------------------------------------------------------------- budget

def test_compile_budget_check_logic(tmp_path):
    """Over-budget, unknown and stale entries are three distinct
    violation classes; a matching measurement is clean."""
    from jax_mapping.analysis.compilebudget import Budget

    path = str(tmp_path / "budget.json")
    Budget.dump({"m.f": 2, "m.g": 1}, path,
                notes={"m.f": "window + single paths"})
    b = Budget.load(path)
    over, unknown, stale = b.check({"m.f": 2, "m.g": 1})
    assert (over, unknown, stale) == ([], [], [])
    over, unknown, stale = b.check({"m.f": 3, "m.h": 1})
    assert len(over) == 1 and "m.f" in over[0]
    assert len(unknown) == 1 and "m.h" in unknown[0]
    assert len(stale) == 1 and "m.g" in stale[0]


def test_compile_budget_rejects_wrong_version(tmp_path):
    import pytest
    from jax_mapping.analysis.compilebudget import Budget

    p = tmp_path / "b.json"
    p.write_text('{"version": 99, "budgets": []}')
    with pytest.raises(ValueError):
        Budget.load(str(p))


def test_snapshot_cache_sizes_sees_jitted_functions():
    """The introspection finds package jit sites by their DEFINING
    module (stable across from-import aliases)."""
    from jax_mapping.analysis.compilebudget import snapshot_cache_sizes
    from jax_mapping.ops import grid as G  # noqa: F401 — ensure imported

    sizes = snapshot_cache_sizes()
    assert any(k.startswith("jax_mapping.ops.grid.") for k in sizes), \
        sorted(sizes)[:10]


def test_racewatch_chains_over_a_foreign_lock_proxy():
    """A lock already proxied by ANOTHER watch (the lockwatch+racewatch
    double-instrumentation pattern) must be chained, not skipped —
    skipping would leave this watch's held-set empty on every access
    and report spurious empty-lockset races for correctly-locked
    code."""
    from jax_mapping.analysis.racewatch import RaceWatch

    lw = LockWatch()
    rw = RaceWatch()
    s = _RacyStore()
    lw.watch(s, "_lock")                 # foreign proxy first
    rw.watch_object(s, _store_group(), name="store")
    _drive_two_threads(s.install_ok, lambda _i: s.read())
    rw.unwatch_all()
    lw.unwatch_all()
    assert rw.reports() == []
    st = rw.field_states()["_RacyStore.revision@store"]
    assert st.state == "shared-modified"
    assert st.candidate == frozenset({"_RacyStore._lock@store"})
    # restore order held: the raw lock is back.
    assert isinstance(s._lock, type(threading.Lock()))


def test_compile_budget_check_fails_fast_on_missing_budget(tmp_path):
    """--check with a missing/corrupt budget exits 2 BEFORE running the
    ~30 s measurement scenario (the lint CLI's fail-fast contract)."""
    import time

    from jax_mapping.analysis.compilebudget import main

    t0 = time.monotonic()
    assert main(["--check", "--budget", str(tmp_path / "nope.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert main(["--check", "--budget", str(bad)]) == 2
    assert main(["--write-budget", "--budget", str(bad)]) == 2
    assert bad.read_text() == "{not json"      # untouched
    assert time.monotonic() - t0 < 5.0, "preflight ran the scenario"


def test_failure_guard_does_not_count_skips_as_ran():
    """A pinned known-failure that gets SKIPPED must not be reported as
    FIXED (ratcheting the pin out would misreport the next full run)."""
    import conftest

    class R:
        def __init__(self, when, outcome):
            self.when = when
            self.outcome = outcome
            self.nodeid = "tests/test_x.py::test_pinned"
            self.failed = outcome == "failed"

    saved = {k: set(v) for k, v in conftest._guard_state.items()}
    try:
        conftest._guard_state["ran"].clear()
        conftest._guard_state["failed"].clear()
        conftest.pytest_runtest_logreport(R("setup", "skipped"))
        assert conftest._guard_state["ran"] == set()
        conftest.pytest_runtest_logreport(R("setup", "failed"))
        assert conftest._guard_state["ran"] == {R("setup", "failed").nodeid}
        conftest.pytest_runtest_logreport(R("call", "passed"))
        assert R("call", "passed").nodeid in conftest._guard_state["ran"]
    finally:
        conftest._guard_state["ran"] = saved["ran"]
        conftest._guard_state["failed"] = saved["failed"]
