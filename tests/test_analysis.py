"""Fixture tests for the static-analysis checkers (`jax_mapping.analysis`).

Each checker (A1-A4, B1-B3) gets at least one known-bad snippet it must
flag and one known-clean snippet it must stay silent on — the contract
ISSUE 1 gates on. Snippets are analyzed in-memory via
`SourceModule.from_source`, so these tests never touch the real package
(that is `test_analysis_selfcheck.py`'s job) and stay immune to
unrelated repo edits.
"""

import json
import textwrap
import threading

from jax_mapping.analysis import jax_hazards, lock_discipline
from jax_mapping.analysis.core import (
    Baseline, Finding, SourceModule, analyze_modules,
)
from jax_mapping.analysis.lockwatch import LockWatch


def run_checker(checker, src, path="jax_mapping/ops/snippet.py"):
    mod = SourceModule.from_source(textwrap.dedent(src), path=path)
    return list(checker.run([mod]))


def ids(findings):
    return [f.checker for f in findings]


# ---------------------------------------------------------------- A1

def test_a1_flags_np_asarray_on_traced_value_inside_jit():
    findings = run_checker(jax_hazards.HostSyncChecker(), """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def fuse(scan):
            host = np.asarray(scan)
            return jnp.sum(host)
        """)
    assert ids(findings) == ["A1-host-sync"]
    assert findings[0].severity == "error"
    assert findings[0].symbol == "fuse"


def test_a1_flags_item_and_float_on_traced_values():
    findings = run_checker(jax_hazards.HostSyncChecker(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def score(x):
            s = jnp.sum(x)
            return s.item()

        @jax.jit
        def scale(x):
            return float(x) * 2.0
        """)
    assert ids(findings) == ["A1-host-sync", "A1-host-sync"]
    assert {f.symbol for f in findings} == {"score", "scale"}


def test_a1_flags_sync_chained_on_call_result():
    """`jnp.sum(x).item()` — the most common one-line form: the traced
    result never gets a name, so the receiver chain is call-rooted and
    must be judged by the expression itself."""
    findings = run_checker(jax_hazards.HostSyncChecker(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def total(x):
            return jnp.sum(x).item()

        @jax.jit
        def as_host(x):
            return float(jnp.max(x))
        """)
    assert ids(findings) == ["A1-host-sync", "A1-host-sync"]
    assert {f.symbol for f in findings} == {"total", "as_host"}


def test_a1_silent_on_pure_jit_and_host_side_numpy():
    findings = run_checker(jax_hazards.HostSyncChecker(), """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def fuse(scan):
            return jnp.sum(scan * 2.0)

        def host_prep(raw_list):
            # host value, never traced: converting it is fine anywhere
            return np.asarray(raw_list)
        """)
    assert findings == []


def test_a1_flags_sync_on_jit_result_in_timer_hot_path():
    src = """
        import functools
        import jax
        import numpy as np

        @functools.partial(jax.jit, static_argnums=(1,))
        def step(state, n):
            return state + n

        class MapperNode:
            def __init__(self, cfg):
                self.state = None
                self.create_timer(0.1, self.tick)

            def tick(self):
                out = step(self.state, 3)
                return float(out)
        """
    findings = run_checker(jax_hazards.HostSyncChecker(), src,
                           path="jax_mapping/bridge/snippet.py")
    assert ids(findings) == ["A1-host-sync"]
    assert findings[0].severity == "warning"
    assert findings[0].symbol == "MapperNode.tick"


def test_a1_silent_in_hot_path_without_device_values():
    src = """
        import numpy as np

        class StatusNode:
            def __init__(self, cfg):
                self.rows = []
                self.create_timer(1.0, self.tick)

            def tick(self):
                # plain host data: np.asarray here is not a device sync
                return np.asarray(self.rows)
        """
    findings = run_checker(jax_hazards.HostSyncChecker(), src,
                           path="jax_mapping/bridge/snippet.py")
    assert findings == []


# ---------------------------------------------------------------- A2

def test_a2_flags_python_if_on_traced_value():
    findings = run_checker(jax_hazards.JitHygieneChecker(), """
        import jax

        @jax.jit
        def clip(x):
            if x > 0:
                return x
            return -x
        """)
    assert ids(findings) == ["A2-jit-hygiene"]
    assert "if" in findings[0].message


def test_a2_flags_for_over_traced_range_and_bad_static_argnums():
    findings = run_checker(jax_hazards.JitHygieneChecker(), """
        import functools
        import jax

        @jax.jit
        def unroll(x, n):
            acc = x
            for i in range(n):
                acc = acc + i
            return acc

        @functools.partial(jax.jit, static_argnums=(5,))
        def lonely(x):
            return x
        """)
    assert sorted(ids(findings)) == ["A2-jit-hygiene", "A2-jit-hygiene"]
    messages = " | ".join(f.message for f in findings)
    assert "range" in messages and "out of range" in messages


def test_a2_flags_unhashable_literal_in_static_position():
    findings = run_checker(jax_hazards.JitHygieneChecker(), """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def reshape(x, shape):
            return x.reshape(shape)

        def caller(x):
            return reshape(x, [4, 4])
        """)
    assert ids(findings) == ["A2-jit-hygiene"]
    assert findings[0].symbol == "caller"


def test_a2_silent_on_static_branch_and_hashable_static_args():
    findings = run_checker(jax_hazards.JitHygieneChecker(), """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=(1,))
        def fuse(x, n_windows):
            if n_windows > 2:          # static: plain Python int
                x = x * 2.0
            for _ in range(n_windows):  # static range: fixed unroll
                x = x + 1.0
            return jnp.where(x > 0, x, -x)

        def caller(x):
            return fuse(x, 4)
        """)
    assert findings == []


# ---------------------------------------------------------------- A3

def test_a3_flags_float64_and_dtypeless_float_arrays_in_tpu_path():
    findings = run_checker(jax_hazards.DtypeDriftChecker(), """
        import numpy as np

        def make_scale():
            return np.float64(3.0)

        def make_offsets():
            return np.array([0.5, 1.5])

        def make_field(n):
            return np.full(n, 0.0, dtype=float)
        """)
    assert ids(findings) == ["A3-dtype-drift"] * 3
    assert {f.symbol for f in findings} == \
        {"make_scale", "make_offsets", "make_field"}


def test_a3_silent_with_explicit_float32_or_outside_tpu_path():
    clean = """
        import numpy as np

        def make_offsets():
            return np.array([0.5, 1.5], np.float32)

        def make_index():
            return np.array([1, 2, 3])
        """
    assert run_checker(jax_hazards.DtypeDriftChecker(), clean) == []
    # float64 is fine in modules that never feed the device path
    host_only = """
        import numpy as np

        def exact_millimetres(r):
            return np.float64(r) * 1000.0
        """
    assert run_checker(jax_hazards.DtypeDriftChecker(), host_only,
                       path="jax_mapping/analysis/snippet.py") == []


# ---------------------------------------------------------------- A4

def test_a4_flags_time_call_and_self_mutation_under_jit():
    findings = run_checker(jax_hazards.ImpureJitChecker(), """
        import time
        import jax

        @jax.jit
        def stamp(x):
            return x * time.time()

        class Model:
            @jax.jit
            def step(self, x):
                self.cache = x
                return x
        """)
    assert ids(findings) == ["A4-impure-jit"] * 2
    messages = " | ".join(f.message for f in findings)
    assert "trace time" in messages and "self" in messages


def test_a4_flags_impurity_in_transitive_callee():
    findings = run_checker(jax_hazards.ImpureJitChecker(), """
        import random
        import jax

        def jitter(x):
            return x + random.random()

        @jax.jit
        def step(x):
            return jitter(x)
        """)
    assert ids(findings) == ["A4-impure-jit"]
    assert findings[0].symbol == "jitter"


def test_a4_silent_on_jax_random_and_host_side_time():
    findings = run_checker(jax_hazards.ImpureJitChecker(), """
        import time
        import jax
        import jax.numpy as jnp

        @jax.jit
        def noisy(x, key):
            return x + jax.random.normal(key, x.shape)

        def wall_clock():
            # never reached from a jit site
            return time.time()
        """)
    assert findings == []


# ---------------------------------------------------------------- B1

_B1_BAD = """
    import threading

    class Pipeline:
        def __init__(self):
            self._head = threading.Lock()
            self._tail = threading.Lock()

        def forward(self):
            with self._head:
                with self._tail:
                    pass

        def backward(self):
            with self._tail:
                with self._head:
                    pass
    """


def test_b1_flags_lock_order_cycle():
    findings = run_checker(lock_discipline.LockOrderChecker(), _B1_BAD,
                           path="jax_mapping/bridge/snippet.py")
    assert len(findings) == 2          # both edges of the cycle reported
    assert set(ids(findings)) == {"B1-lock-order"}
    assert all("Pipeline._head" in f.message and "Pipeline._tail"
               in f.message for f in findings)


def test_b1_sees_nesting_through_method_calls():
    findings = run_checker(lock_discipline.LockOrderChecker(), """
        import threading

        class Pipeline:
            def __init__(self):
                self._head = threading.Lock()
                self._tail = threading.Lock()

            def _drain(self):
                with self._tail:
                    pass

            def forward(self):
                with self._head:
                    self._drain()       # head -> tail, hidden in a call

            def backward(self):
                with self._tail:
                    with self._head:
                        pass
        """, path="jax_mapping/bridge/snippet.py")
    assert len(findings) == 2
    assert set(ids(findings)) == {"B1-lock-order"}


def test_b1_silent_on_consistent_order_and_condition_aliases():
    findings = run_checker(lock_discipline.LockOrderChecker(), """
        import threading

        class Pipeline:
            def __init__(self):
                self._head = threading.Lock()
                self._tail = threading.Lock()
                # Condition over _head IS _head, not a third lock
                self._ready = threading.Condition(self._head)

            def forward(self):
                with self._head:
                    with self._tail:
                        pass

            def flush(self):
                with self._ready:
                    with self._tail:
                        pass
        """, path="jax_mapping/bridge/snippet.py")
    assert findings == []


# ---------------------------------------------------------------- B2

def test_b2_flags_callback_and_publish_under_lock():
    findings = run_checker(lock_discipline.CallbackUnderLockChecker(), """
        import threading

        class Topic:
            def __init__(self):
                self._lock = threading.Lock()
                self._subs = []
                self.pub = None

            def deliver(self, msg):
                with self._lock:
                    for sub in self._subs:
                        sub.callback(msg)

            def forward(self, msg):
                with self._lock:
                    self.pub.publish(msg)
        """, path="jax_mapping/bridge/snippet.py")
    assert ids(findings) == ["B2-callback-lock"] * 2
    assert all("Topic._lock" in f.message for f in findings)


def test_b2_silent_when_snapshot_taken_then_lock_released():
    findings = run_checker(lock_discipline.CallbackUnderLockChecker(), """
        import threading

        class Topic:
            def __init__(self):
                self._lock = threading.Lock()
                self._subs = []

            def deliver(self, msg):
                with self._lock:
                    subs = list(self._subs)
                for sub in subs:
                    sub.callback(msg)

            def wake(self):
                with self._lock:
                    self._lock.release()   # lock protocol, not a callback
                    self._lock.acquire()
        """, path="jax_mapping/bridge/snippet.py")
    assert findings == []


# ---------------------------------------------------------------- B3

def test_b3_flags_unguarded_write_to_lock_protected_state():
    findings = run_checker(lock_discipline.UnguardedWriteChecker(), """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = None

            def get(self):
                with self._lock:
                    return self.value

            def set_fast(self, v):
                self.value = v          # racing get()'s guarded read
        """, path="jax_mapping/bridge/snippet.py")
    assert ids(findings) == ["B3-unguarded-write"]
    assert findings[0].symbol == "Cache.set_fast"
    assert "self.value" in findings[0].message


def test_b3_silent_when_writes_guarded_or_state_never_shared():
    findings = run_checker(lock_discipline.UnguardedWriteChecker(), """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = None
                self.n_sets = 0         # never accessed under the lock

            def get(self):
                with self._lock:
                    return self.value

            def set(self, v):
                with self._lock:
                    self.value = v
                self.n_sets += 1
        """, path="jax_mapping/bridge/snippet.py")
    assert findings == []


# ------------------------------------------------------- baseline plumbing

def test_baseline_suppresses_and_reports_unused(tmp_path):
    mod = SourceModule.from_source(textwrap.dedent("""
        import jax
        import numpy as np

        @jax.jit
        def fuse(scan):
            return np.asarray(scan)
        """), path="jax_mapping/ops/snippet.py")
    checkers = [jax_hazards.HostSyncChecker()]
    raw = analyze_modules([mod], baseline=None, checkers=checkers)
    assert len(raw.findings) == 1

    # Accept the finding, add one stale suppression on top (same file,
    # so the run has full context — a line that no longer exists).
    path = str(tmp_path / "baseline.json")
    Baseline.dump(raw.findings, path)
    data = json.load(open(path))
    data["suppressions"].append({
        "checker": "A1-host-sync", "path": mod.path,
        "symbol": "fuse", "code": "x = np.asarray(y_removed)"})
    json.dump(data, open(path, "w"))

    res = analyze_modules([mod], baseline=Baseline.load(path),
                          checkers=checkers)
    assert res.findings == []
    assert len(res.baselined) == 1
    assert len(res.unused_suppressions) == 1
    assert res.unused_suppressions[0]["code"] == "x = np.asarray(y_removed)"


def test_unused_reporting_needs_full_context(tmp_path):
    """A path-subset run finds strictly less than the package-wide pass
    (the A checkers build a cross-module jit registry), so it must not
    call other files' — or even its own file's — suppressions stale."""
    mod = SourceModule.from_source(textwrap.dedent("""
        import numpy as np

        def harmless():
            return np.zeros(3, np.float32)
        """), path="jax_mapping/ops/snippet.py")
    base = Baseline([{
        "checker": "A1-host-sync", "path": "jax_mapping/ops/other.py",
        "symbol": "f", "code": "x = np.asarray(y)", "note": "boundary"}])
    res = analyze_modules([mod], baseline=base,
                          checkers=[jax_hazards.HostSyncChecker()])
    assert res.findings == []
    assert res.unused_suppressions == []


def test_finding_key_survives_line_moves():
    a = Finding("A1-host-sync", "error", "p.py", 10, "f", "m", "x = 1")
    b = Finding("A1-host-sync", "error", "p.py", 99, "f", "m", "x = 1")
    assert a.key == b.key


# ------------------------------------------------------------- lockwatch

class _Box:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.RLock()


def test_lockwatch_records_edges_and_detects_cycles():
    box = _Box()
    watch = LockWatch()
    assert watch.watch(box, "_a") == "_Box._a"
    watch.watch(box, "_b")
    with box._a:
        with box._b:
            pass
    assert watch.cycle() is None
    with box._b:
        with box._a:
            pass
    watch.unwatch_all()
    assert ("_Box._a", "_Box._b") in watch.edges()
    assert ("_Box._b", "_Box._a") in watch.edges()
    cycle = watch.cycle()
    assert cycle is not None and set(cycle) >= {"_Box._a", "_Box._b"}


def test_lockwatch_reentrant_rlock_is_not_a_self_edge():
    box = _Box()
    watch = LockWatch()
    watch.watch(box, "_b")
    with box._b:
        with box._b:                   # RLock re-acquire on same thread
            pass
    watch.unwatch_all()
    assert watch.edges() == set()
    assert watch.cycle() is None


def test_lockwatch_unwatch_restores_real_locks():
    box = _Box()
    watch = LockWatch()
    watch.watch(box, "_a")
    watch.unwatch_all()
    assert isinstance(box._a, type(threading.Lock()))


def test_lockwatch_check_against_static_reports_missed_edges():
    box = _Box()
    watch = LockWatch()
    watch.watch(box, "_a")
    watch.watch(box, "_b")
    with box._b:
        with box._a:
            pass
    watch.unwatch_all()
    static = {("_Box._a", "_Box._b")}
    assert watch.check_against_static(static) == {("_Box._b", "_Box._a")}
    # edges touching locks the static graph never saw are ignored
    assert watch.check_against_static({("Other.x", "Other.y")}) == set()
