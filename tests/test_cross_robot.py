"""Cross-robot loop closure and inter-robot map consistency.

The reference gets inter-robot consistency for free: one SLAM node fuses
every scan into one graph (`pc_server.launch.py:14-19`). Here graphs are
per-robot (models/fleet.py), so a drifted robot relocalises against a
fleet-mate's chain map (`_cross_candidates` + the cross branch of
`_verify_and_optimize`). Pinned here:

  * candidate search semantics (nearest other-established-chain pose,
    radius gate, self-exclusion);
  * a drifted robot B verifying against robot A's chain snaps to its true
    pose (drift beyond the online matcher's window);
  * map consistency: fusing B's scans at the corrected poses yields ONE
    wall, while the uncorrected poses ghost it into two.
"""

import dataclasses
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.models import fleet as FM
from jax_mapping.ops import grid as G
from jax_mapping.ops import posegraph as PG
from jax_mapping.sim import lidar
from tests.conftest import *  # noqa: F401,F403


@pytest.fixture()
def cfg(tiny_cfg):
    return dataclasses.replace(
        tiny_cfg,
        loop=dataclasses.replace(tiny_cfg.loop, max_poses=64, max_edges=256,
                                 gn_iters=4, coarse_downsample=2,
                                 min_chain_size=6))


def _world(res):
    """12.8 m world with one long wall and a perpendicular stub (the
    symmetry breaker a correlative matcher needs)."""
    w = np.zeros((256, 256), bool)
    w[150:152, 60:200] = True      # wall at y ~= +1.1 m
    w[120:150, 98:100] = True      # stub south of it at x ~= -1.5 m
    return jnp.asarray(w)


def _scan_at(cfg, world, pose):
    n_samples = int(cfg.scan.range_max_m / (cfg.grid.resolution_m * 0.5))
    return lidar.simulate_scans(cfg.scan, world, cfg.grid.resolution_m,
                                n_samples, jnp.asarray(pose)[None])[0]


def _chain_along_wall(cfg, world, n=8, y=0.0, x0=-1.2, dx=0.35):
    """Robot A's graph: n key poses driving east under the wall, scans
    simulated at the TRUE poses (A is well-localised)."""
    g = PG.empty_graph(cfg.loop)
    ring = jnp.zeros((cfg.loop.max_poses, cfg.scan.padded_beams), jnp.float32)
    poses = []
    for i in range(n):
        pose = jnp.asarray(np.array([x0 + i * dx, y, 0.0], np.float32))
        poses.append(pose)
        g = PG.add_pose_if(g, pose, jnp.bool_(True))
        ring = ring.at[i].set(_scan_at(cfg, world, pose))
    return g, ring, poses


def test_cross_candidates_semantics(cfg):
    R = 3
    graphs = jax.vmap(lambda _: PG.empty_graph(cfg.loop))(jnp.arange(R))
    # Robot 0: an established chain near the origin. Robot 1: too short.
    def fill(g, n, ox):
        for i in range(n):
            g = PG.add_pose_if(
                g, jnp.array([ox + 0.3 * i, 0.0, 0.0]), jnp.bool_(True))
        return g
    g0 = fill(jax.tree.map(lambda x: x[0], graphs), 8, 0.0)
    g1 = fill(jax.tree.map(lambda x: x[1], graphs), 3, 5.0)
    graphs = jax.tree.map(
        lambda full, a, b: full.at[0].set(a).at[1].set(b),
        graphs, g0, g1)
    est = jnp.asarray(np.array([[0.0, 0.0, 0.0],      # robot 0
                                [0.5, 0.4, 0.0],      # robot 1: near 0's chain
                                [50.0, 50.0, 0.0]],   # robot 2: far away
                               np.float32))
    xr, xc, found = FM._cross_candidates(cfg, graphs, est)
    xr, xc, found = map(np.asarray, (xr, xc, found))
    assert found[1] and xr[1] == 0, "robot 1 should find robot 0's chain"
    assert not found[2], "far robot must find nothing"
    # Robot 0 must not match its own chain; robot 1's chain is too short
    # to be a target, so robot 0 finds nothing.
    assert not found[0]


def test_drifted_robot_relocalises_against_fleet_mate(cfg):
    world = _world(cfg.grid.resolution_m)
    R = 2
    gA, ringA, _ = _chain_along_wall(cfg, world)

    graphs = jax.vmap(lambda _: PG.empty_graph(cfg.loop))(jnp.arange(R))
    graphs = jax.tree.map(lambda full, a: full.at[0].set(a), graphs, gA)
    rings = jnp.zeros((R, cfg.loop.max_poses, cfg.scan.padded_beams),
                      jnp.float32)
    rings = rings.at[0].set(ringA)

    # Robot B's TRUE pose sits inside A's mapped region; B's estimate has
    # drifted 0.7 m — beyond the online matcher's +-0.5 m window, inside
    # the loop search radius.
    true_B = jnp.asarray(np.array([-0.5, 0.3, 0.4], np.float32))
    est_B = true_B + jnp.asarray(np.array([0.55, -0.45, 0.0], np.float32))
    scan_B = _scan_at(cfg, world, true_B)

    # B has one node in its own graph (its current key pose).
    gB = PG.add_pose_if(jax.tree.map(lambda x: x[1], graphs), est_B,
                        jnp.bool_(True))
    graphs = jax.tree.map(lambda full, b: full.at[1].set(b), graphs, gB)

    est = jnp.stack([jnp.zeros(3), est_B])
    scans = jnp.stack([jnp.zeros_like(scan_B), scan_B])
    k_idx = jnp.array([99, 0], jnp.int32)     # B's node slot (A's unused)
    attempt = jnp.array([False, False])
    xr, xc, xfound = FM._cross_candidates(cfg, graphs, est)
    assert bool(xfound[1]) and int(xr[1]) == 0
    xattempt = jnp.array([False, True])

    graphs3, est2, closed = FM._verify_and_optimize(
        cfg, graphs, rings, est, scans, k_idx,
        jnp.zeros(R, jnp.int32), attempt, xr, xc, xattempt)
    assert bool(closed[1]), "cross verification should accept"
    err = float(jnp.linalg.norm(est2[1, :2] - true_B[:2]))
    assert err < 0.1, f"relocalised pose off by {err:.3f} m"
    dth = float(jnp.abs(est2[1, 2] - true_B[2]))
    assert dth < 0.1


def test_map_consistency_one_wall_not_two(cfg):
    """Fuse B's scans at corrected vs drifted poses on top of A's map: the
    corrected merge keeps one wall, the drifted merge ghosts it."""
    world = _world(cfg.grid.resolution_m)
    gA, ringA, posesA = _chain_along_wall(cfg, world)
    g = cfg.grid

    # A's map: fuse its chain.
    grid = G.empty_grid(g)
    for i, p in enumerate(posesA):
        grid = G.fuse_scans(g, cfg.scan, grid, ringA[i][None], p[None])

    # Enough drifted scans that the displaced wall overcomes the free-space
    # evidence A already fused there (log-odds fusion suppresses a few
    # inconsistent hits by design — ghosting needs sustained drift).
    drift = jnp.asarray(np.array([0.55, -0.45, 0.0], np.float32))
    true_Bs = [jnp.asarray(np.array([-0.9 + 0.2 * i, 0.25, 0.5], np.float32))
               for i in range(10)]
    scans_B = jnp.stack([_scan_at(cfg, world, p) for p in true_Bs])

    good = bad = grid
    for i, p in enumerate(true_Bs):
        good = G.fuse_scans(g, cfg.scan, good, scans_B[i][None], p[None])
        bad = G.fuse_scans(g, cfg.scan, bad, scans_B[i][None],
                           (p + drift)[None])

    # Ghost metric against world truth: occupied grid cells farther than
    # 2 cells from ANY true wall cell. (A plain occupied-cell count hides
    # ghosting: the drifted rays carve the true wall down while painting
    # the displaced copy, so totals barely move.)
    world_np = np.asarray(_world(g.resolution_m))
    # world cell (r, c) -> grid cell: same resolution, different origins.
    wr, wc = np.nonzero(world_np)
    wy = (wr - 128 + 0.5) * g.resolution_m
    wx = (wc - 128 + 0.5) * g.resolution_m
    gr_r = ((wy - g.origin_m[1]) / g.resolution_m).astype(int)
    gr_c = ((wx - g.origin_m[0]) / g.resolution_m).astype(int)
    true_wall = np.zeros((g.size_cells, g.size_cells), bool)
    true_wall[gr_r, gr_c] = True

    def ghosts(gr_arr):
        occ = np.asarray(gr_arr) > g.occ_threshold
        near = true_wall.copy()
        for _ in range(2):   # dilate truth by 2 cells
            near = (near | np.roll(near, 1, 0) | np.roll(near, -1, 0)
                    | np.roll(near, 1, 1) | np.roll(near, -1, 1))
        return int((occ & ~near).sum())

    g_good = ghosts(good)
    g_bad = ghosts(bad)
    assert g_good <= 3, f"consistent fusion ghosted {g_good} cells"
    assert g_bad > 30, f"drifted fusion should ghost (got {g_bad})"
