"""Odometry and SE(2) helper tests vs the reference math oracle."""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.config import RobotConfig, sign_extend_16bit
from jax_mapping.ops import odometry as O
from tests.oracle import rk2_odometry_np


def test_rk2_step_matches_reference_math(rng):
    robot = RobotConfig()
    pose = np.array([0.1, -0.2, 0.4])
    x, y, yaw = pose
    jpose = jnp.asarray(pose, jnp.float32)
    for _ in range(20):
        l = float(rng.integers(-200, 200))
        r = float(rng.integers(-200, 200))
        dt = float(rng.uniform(0.05, 0.15))
        x, y, yaw = rk2_odometry_np(robot, x, y, yaw, l, r, dt)
        jpose = O.rk2_step(robot, jpose, jnp.float32(l), jnp.float32(r),
                           jnp.float32(dt))
    np.testing.assert_allclose(np.asarray(jpose), [x, y, yaw], atol=1e-4)


def test_integrate_equals_stepping(rng):
    robot = RobotConfig()
    T = 50
    l = rng.integers(-150, 150, T).astype(np.float32)
    r = rng.integers(-150, 150, T).astype(np.float32)
    dts = np.full(T, 0.1, np.float32)
    traj = np.asarray(O.integrate(robot, jnp.zeros(3), jnp.asarray(l),
                                  jnp.asarray(r), jnp.asarray(dts)))
    pose = jnp.zeros(3)
    for t in range(T):
        pose = O.rk2_step(robot, pose, l[t], r[t], dts[t])
    np.testing.assert_allclose(traj[-1], np.asarray(pose), atol=1e-5)
    assert traj.shape == (T, 3)


def test_straight_line_and_pivot():
    robot = RobotConfig()
    # Equal speeds -> straight along +x from origin.
    T = 10
    sp = jnp.full(T, 100.0)
    dts = jnp.full(T, 0.1)
    traj = np.asarray(O.integrate(robot, jnp.zeros(3), sp, sp, dts))
    expect_x = 100 * robot.speed_coeff_m_per_unit_s * 1.0
    np.testing.assert_allclose(traj[-1], [expect_x, 0, 0], atol=1e-6)
    # Opposite speeds -> pure pivot, no translation.
    traj = np.asarray(O.integrate(robot, jnp.zeros(3), -sp, sp, dts))
    np.testing.assert_allclose(traj[-1][:2], [0, 0], atol=1e-6)
    assert traj[-1][2] > 0.5  # turned left (right wheel forward)


def test_integrate_fleet_matches_single(rng):
    robot = RobotConfig()
    R, T = 3, 20
    l = rng.integers(-100, 100, (R, T)).astype(np.float32)
    r = rng.integers(-100, 100, (R, T)).astype(np.float32)
    dts = np.full((R, T), 0.1, np.float32)
    p0 = rng.uniform(-1, 1, (R, 3)).astype(np.float32)
    fleet = np.asarray(O.integrate_fleet(robot, jnp.asarray(p0),
                                         jnp.asarray(l), jnp.asarray(r),
                                         jnp.asarray(dts)))
    for i in range(R):
        single = np.asarray(O.integrate(robot, jnp.asarray(p0[i]),
                                        jnp.asarray(l[i]), jnp.asarray(r[i]),
                                        jnp.asarray(dts[i])))
        np.testing.assert_allclose(fleet[i], single, atol=1e-6)


def test_twist_roundtrip():
    robot = RobotConfig()
    l, r = O.twist_to_wheel_units(robot, jnp.float32(0.1), jnp.float32(0.5))
    v, w = O.wheel_velocities(robot, l, r)
    assert float(v) == pytest.approx(0.1, abs=1e-5)
    assert float(w) == pytest.approx(0.5, abs=1e-4)


def test_pose_compose_between_roundtrip(rng):
    a = jnp.asarray(rng.uniform(-2, 2, 3).astype(np.float32))
    b = jnp.asarray(rng.uniform(-2, 2, 3).astype(np.float32))
    rel = O.pose_between(a, b)
    back = O.pose_compose(a, rel)
    got = np.asarray(back)
    want = np.asarray(b)
    np.testing.assert_allclose(got[:2], want[:2], atol=1e-5)
    assert abs(math.remainder(float(got[2] - want[2]), 2 * math.pi)) < 1e-5


def test_sign_extend_16bit_variants():
    # Reference semantics (server main.py:101-102).
    assert sign_extend_16bit(100) == 100
    assert sign_extend_16bit(65436) == -100
    out = sign_extend_16bit(np.array([100, 65436], dtype=np.uint16))
    np.testing.assert_array_equal(out, [100, -100])
    out = sign_extend_16bit(jnp.array([100, 65436], dtype=jnp.uint16))
    np.testing.assert_array_equal(np.asarray(out), [100, -100])
