"""Pallas window-fusion kernel vs the XLA classify path and NumPy oracle.

On CPU the kernel runs in interpret mode (same code path the TPU compiles);
semantics must match `ops/grid.classify_patch` summed over the batch.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.ops import grid as G
from jax_mapping.ops import sensor_kernel as SK
from tests.oracle import classify_patch_np


def _window(rng, tiny_cfg, B=3):
    s = tiny_cfg.scan
    t = np.linspace(0, 1.0, B).astype(np.float32)
    poses = np.stack([0.2 * np.cos(t), 0.2 * np.sin(t), t], 1).astype(np.float32)
    ranges = rng.uniform(0.3, 2.5, (B, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    ranges[0, 5] = 0.0       # outlier
    ranges[1, 7] = 50.0      # beyond max range
    return ranges, poses


def test_window_delta_matches_classify_sum(tiny_cfg, rng):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    ranges, poses = _window(rng, tiny_cfg)
    origin = G.patch_origin(g, jnp.asarray(poses[:, :2].mean(0)))
    assert bool(SK.window_fits(g, jnp.asarray(poses), origin))

    got = np.asarray(SK.window_delta(g, s, jnp.asarray(ranges),
                                     jnp.asarray(poses), origin))
    want = sum(
        np.asarray(G.classify_patch(g, s, jnp.asarray(ranges[i]),
                                    jnp.asarray(poses[i]), origin))
        for i in range(len(poses)))
    # Identical math modulo op ordering: tiny float slack only.
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_window_delta_matches_numpy_oracle(tiny_cfg, rng):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    ranges, poses = _window(rng, tiny_cfg, B=2)
    origin_j = G.patch_origin(g, jnp.asarray(poses[:, :2].mean(0)))
    origin = np.asarray(origin_j)
    got = np.asarray(SK.window_delta(g, s, jnp.asarray(ranges),
                                     jnp.asarray(poses), origin_j))
    want = sum(classify_patch_np(g, s, ranges[i], poses[i], origin)
               for i in range(len(poses)))
    agree = np.mean(np.abs(got - want) < 1e-5)
    assert agree > 0.995, f"only {agree:.4f} of cells agree with oracle"


def test_fuse_scans_window_updates_grid(tiny_cfg, rng):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    ranges, poses = _window(rng, tiny_cfg)
    grid0 = G.empty_grid(g)
    grid1 = G.fuse_scans_window(g, s, grid0, jnp.asarray(ranges),
                                jnp.asarray(poses))
    arr = np.asarray(grid1)
    assert (arr > 0).any() and (arr < 0).any()
    assert arr.min() >= g.logodds_min and arr.max() <= g.logodds_max
    # Cells outside the patch untouched.
    origin = np.asarray(G.patch_origin(g, jnp.asarray(poses[:, :2].mean(0))))
    mask = np.ones_like(arr, bool)
    mask[origin[0]:origin[0] + g.patch_cells,
         origin[1]:origin[1] + g.patch_cells] = False
    assert (arr[mask] == 0).all()


def test_window_fits_rejects_far_pose(tiny_cfg):
    g = tiny_cfg.grid
    poses = np.array([[0.0, 0.0, 0.0],
                      [g.patch_cells * g.resolution_m, 0.0, 0.0]], np.float32)
    origin = G.patch_origin(g, jnp.asarray(poses[:1, :2].mean(0)))
    assert not bool(SK.window_fits(g, jnp.asarray(poses), origin))


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="needs a real TPU: asserts Mosaic lowering")
def test_window_delta_lowers_on_tpu(rng):
    """The flagship kernel must compile (not interpret) on the chip.

    Guards the round-2 regression where Mosaic rejected the SMEM pose
    BlockSpec and every caller silently ran the XLA fallback. Full-size
    config on purpose: the production shapes are the ones that must lower.
    """
    from jax_mapping.config import SlamConfig
    cfg = SlamConfig()
    g, s = cfg.grid, cfg.scan
    B = 8
    ranges = rng.uniform(0.1, 8.0, (B, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    poses = np.tile(np.array([2.0, 1.5, 0.3], np.float32), (B, 1))
    origin_j = G.patch_origin(g, jnp.asarray(poses[:, :2].mean(0)))
    out = SK.window_delta(g, s, jnp.asarray(ranges), jnp.asarray(poses),
                          origin_j)
    out.block_until_ready()          # raises if Mosaic rejects the kernel
    assert np.isfinite(np.asarray(out)).all()
    # Parity with the XLA classify path on the same chip.
    want = sum(
        np.asarray(G.classify_patch(g, s, jnp.asarray(ranges[i]),
                                    jnp.asarray(poses[i]), origin_j))
        for i in range(B))
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)


def test_scan_deltas_per_scan_origin_matches_classify(tiny_cfg, rng):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    # Scattered poses: each scan gets its own patch origin.
    poses = np.array([[0.5, 0.5, 0.3], [-1.5, 1.0, 2.0]], np.float32)
    ranges = rng.uniform(0.3, 2.5, (2, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    origins = jax.vmap(lambda p: G.patch_origin(g, p[:2]))(jnp.asarray(poses))
    got = np.asarray(SK.scan_deltas(g, s, jnp.asarray(ranges),
                                    jnp.asarray(poses), origins))
    for i in range(2):
        want = np.asarray(G.classify_patch(
            g, s, jnp.asarray(ranges[i]), jnp.asarray(poses[i]), origins[i]))
        np.testing.assert_allclose(got[i], want, atol=1e-5)


def test_raster_mode_matches_xla_raster(tiny_cfg, rng):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    ranges = rng.uniform(0.3, 2.5, (2, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    poses = np.array([[0.1, -0.2, 0.4], [0.13, -0.18, 0.42]], np.float32)
    origins = jax.vmap(lambda p: G.patch_origin(g, p[:2]))(jnp.asarray(poses))
    got = np.asarray(SK.scan_rasters(g, s, jnp.asarray(ranges),
                                     jnp.asarray(poses), origins))
    for i in range(2):
        want = np.asarray(G.raster_patch(g, s, jnp.asarray(ranges[i]),
                                         jnp.asarray(poses[i]), origins[i]))
        np.testing.assert_allclose(got[i], want, atol=5e-5)
    assert got.max() > 0.5   # hit bands present


def test_per_scan_call_batch_split_parity(tiny_cfg, rng, monkeypatch):
    """B above _MAX_B_PER_CALL splits across pallas calls; per-scan outputs
    must concatenate bitwise-identically, and window_delta subtotals must
    agree with the single-call sum to float tolerance."""
    from jax_mapping.ops import sensor_kernel as SK
    g, s = tiny_cfg.grid, tiny_cfg.scan
    B = 5
    ranges = rng.uniform(0.3, 2.8, (B, s.padded_beams)).astype(np.float32)
    poses = np.stack([rng.uniform(-0.2, 0.2, B), rng.uniform(-0.2, 0.2, B),
                      rng.uniform(-3, 3, B)], axis=1).astype(np.float32)
    origins = np.zeros((B, 2), np.int32)
    whole = SK.scan_deltas(g, s, jnp.asarray(ranges), jnp.asarray(poses),
                           jnp.asarray(origins))
    monkeypatch.setattr(SK, "_MAX_B_PER_CALL", 2)
    SK.scan_deltas.clear_cache()
    SK._per_scan_call.clear_cache()
    split = SK.scan_deltas(g, s, jnp.asarray(ranges), jnp.asarray(poses),
                           jnp.asarray(origins))
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(split))

    SK.window_delta.clear_cache()
    w_whole = SK.window_delta(g, s, jnp.asarray(ranges), jnp.asarray(poses),
                              jnp.asarray(origins[0]))
    np.testing.assert_allclose(np.asarray(w_whole), np.asarray(whole).sum(0),
                               rtol=1e-5, atol=1e-5)
    # drop the traces compiled under the patched split so later tests in
    # this process don't silently reuse split-at-2 executables
    SK.scan_deltas.clear_cache()
    SK._per_scan_call.clear_cache()
    SK.window_delta.clear_cache()
