"""Warm-restart resilience (ISSUE 12): persistent compile cache, AOT
executable snapshots, the staged warm-up state machine, the cache_wipe
fault kind, and the devprof warm-process recompile baseline.

Everything here is IN-PROCESS and cheap: one real jitted entry point
(`fuse_scans_masked` at tiny config) proves the AOT serialize →
deserialize → warm-dispatch ladder bit-identically; the cross-process
economics are the restart bench's job (`bench.py --suite restart`,
BENCH_RESTART_r01.json — on the CPU builder the AOT tier degrades by
design and the persistent cache carries the speedup)."""

import json
import os
import sys
import threading
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import (ColdStartConfig, DevProfConfig,
                                FrontierConfig, GridConfig, tiny_config)
from jax_mapping.io.compile_cache import (CompileCacheManager, WarmPool,
                                          cache_fingerprint,
                                          materialize_zeros)
from jax_mapping.resilience.warmup import (StagedWarmup, warmup_class,
                                           warmup_order)


# ---------------------------------------------------------------- fingerprint

def test_fingerprint_keys_and_infra_normalization():
    """Same config → same fingerprint; a state-shape change → a new
    one; flipping bit-inert infra (obs, cold_start itself) → the SAME
    one, so arming telemetry never orphans a snapshot set."""
    cfg = tiny_config()
    fp = cache_fingerprint(cfg.to_json())
    assert fp == cache_fingerprint(cfg.to_json())
    assert fp != cache_fingerprint(tiny_config(n_robots=3).to_json())
    from jax_mapping.config import ObsConfig
    traced = cfg.replace(obs=ObsConfig(enabled=True),
                         cold_start=ColdStartConfig(enabled=True,
                                                    cache_dir="/x"))
    assert fp == cache_fingerprint(traced.to_json())


# ------------------------------------------------------------- priority order

def test_warmup_priority_fusion_then_match_then_frontier():
    names = ["jax_mapping.ops.frontier.compute_frontiers",
             "jax_mapping.sim.lidar.simulate_scans",
             "jax_mapping.ops.scan_match.match_scan",
             "jax_mapping.models.slam.slam_step",
             "jax_mapping.ops.grid.fuse_scans_masked",
             "jax_mapping.ops.costfield.cost_fields"]
    ordered = warmup_order(names)
    classes = [warmup_class(n) for n in ordered]
    assert classes == sorted(classes)
    assert ordered[0] in ("jax_mapping.models.slam.slam_step",
                          "jax_mapping.ops.grid.fuse_scans_masked")
    # Fusion tier strictly precedes matching, matching precedes
    # exploration, unclassified (sim) comes last.
    assert ordered.index("jax_mapping.ops.grid.fuse_scans_masked") \
        < ordered.index("jax_mapping.ops.scan_match.match_scan") \
        < ordered.index("jax_mapping.ops.frontier.compute_frontiers") \
        < ordered.index("jax_mapping.sim.lidar.simulate_scans")


def test_materialize_zeros_concretizes_only_arrays():
    sig = ((jax.ShapeDtypeStruct((2, 3), jnp.float32), 7, "static"),
           {"m": jax.ShapeDtypeStruct((2,), jnp.bool_)})
    args, kwargs = materialize_zeros(sig)
    assert args[0].shape == (2, 3) and args[1] == 7 and args[2] == "static"
    assert kwargs["m"].dtype == jnp.bool_
    assert not np.asarray(args[0]).any()


# ------------------------------------------------- the AOT ladder, in-process

@pytest.fixture(scope="module")
def aot_workspace(tiny_cfg, tmp_path_factory):
    """ONE snapshot pass shared by the ladder tests (tier-1 wall-clock
    is the scarce resource — each save pays an export + two validation
    compiles): a profiled fuse dispatch captures the signature, a
    manager saves the snapshot set, the profiler uninstalls. Yields
    (cache_root, signatures, live args, live output). Tests that
    mutate files copy the root first."""
    from jax_mapping.obs.devprof import DispatchProfiler
    from jax_mapping.ops import grid as G
    prof = DispatchProfiler(DevProfConfig(enabled=True))
    prof.install()
    try:
        gcfg, scfg = tiny_cfg.grid, tiny_cfg.scan
        args = (gcfg, scfg, G.empty_grid(gcfg),
                jnp.ones((4, scfg.padded_beams), jnp.float32),
                jnp.zeros((4, 3), jnp.float32), jnp.ones((4,), bool))
        out = G.fuse_scans_masked(*args)
        sigs = prof.signatures()
        name = "jax_mapping.ops.grid.fuse_scans_masked"
        if name not in sigs:
            # Warm process (an earlier test already compiled this
            # variant, so the profiler saw no cache growth): synthesize
            # the capture — byte-identical to what a cold process's
            # profiler records.
            from jax_mapping.obs.devprof import abstract_signature
            sigs = {name: [abstract_signature(args, {})]}
        root = str(tmp_path_factory.mktemp("aot_ws") / "cache")
        mgr = CompileCacheManager(
            ColdStartConfig(enabled=True, cache_dir=root), root,
            config_json=tiny_cfg.to_json())
        rep = mgr.save_aot(sigs, resolve=prof.raw_fn)
        assert rep["n_saved"] >= 1 and rep["n_failed"] == 0
    finally:
        # Uninstall BEFORE yielding: the profiler was only needed for
        # the capture, and a module-scoped install would collide with
        # tests that arm their own (install is process-exclusive).
        prof.uninstall()
    yield root, sigs, args, np.asarray(out)


def test_aot_snapshot_roundtrip_and_warm_dispatch(tiny_cfg,
                                                  aot_workspace):
    """The whole warm tier on one entry point: load the saved snapshot
    in-process, install the warm pool, and the next live call is
    SERVED from the deserialized program — bit-identical output, zero
    jit-cache growth, clean uninstall."""
    from jax_mapping.io.compile_cache import resolve_entry_point
    from jax_mapping.ops import grid as G
    root, _sigs, args, out_cold = aot_workspace
    mgr = CompileCacheManager(
        ColdStartConfig(enabled=True, cache_dir=root), root,
        config_json=tiny_cfg.to_json())
    manifest = mgr.load_aot()
    assert manifest["n_loaded"] >= 1 and manifest["n_corrupt"] == 0
    assert mgr.pool.install() >= 1
    try:
        raw = resolve_entry_point("jax_mapping.ops.grid.fuse_scans_masked")
        cache_before = int(raw._cache_size())
        out_warm = G.fuse_scans_masked(*args)
        stats = mgr.pool.stats()
        assert stats["n_served"] >= 1
        np.testing.assert_array_equal(np.asarray(out_warm), out_cold)
        # A warm-served call never grows the jit cache — the recompile
        # counter stays honest for AOT-loaded variants by construction.
        assert int(raw._cache_size()) == cache_before
    finally:
        mgr.pool.uninstall()
    assert not mgr.pool.installed


def test_aot_corrupt_and_fingerprint_mismatch_degrade(tiny_cfg, tmp_path,
                                                      aot_workspace):
    """The fallback ladder's two upper failure modes: a truncated
    snapshot file counts corrupt and is skipped; a different config's
    fingerprint directory is counted and never read — both degrade,
    neither raises, and the degraded entry still yields its signature
    for the persistent-cache pre-warm."""
    import shutil
    root, _sigs, _args, _out = aot_workspace
    copy = str(tmp_path / "cache")
    shutil.copytree(root, copy)
    mgr = CompileCacheManager(
        ColdStartConfig(enabled=True, cache_dir=copy), copy,
        config_json=tiny_cfg.to_json())
    mgr.fingerprint = cache_fingerprint(tiny_cfg.to_json())
    victim = sorted(f for f in os.listdir(mgr.aot_dir())
                    if f.endswith(".aot"))[0]
    with open(os.path.join(mgr.aot_dir(), victim), "r+b") as f:
        f.truncate(16)
    m2 = mgr.load_aot()
    assert m2["n_corrupt"] >= 1

    # A state-shape config change moves the fingerprint: the other
    # directory is counted as a mismatch and never read.
    other = CompileCacheManager(
        ColdStartConfig(enabled=True, cache_dir=copy), copy,
        config_json=tiny_config(n_robots=3).to_json())
    m3 = other.load_aot()
    assert m3["n_fingerprint_mismatch"] >= 1
    assert m3["n_loaded"] == 0 and not m3["signatures"]


def test_warm_pool_falls_through_on_signature_miss():
    pool = WarmPool()
    pool.add("jax_mapping.x.f", "sig-a", lambda *a, **k: "warm", "full",
             (), ())
    assert pool.lookup("jax_mapping.x.f", (jnp.ones(3),), {}) is None
    assert pool.stats()["n_fallthrough"] == 1
    assert pool.lookup("jax_mapping.y.g", (), {}) is None


# --------------------------------------------------------- LRU + husk scrub

def test_evict_lru_bounds_disk_and_scrubs_husks(tmp_path):
    root = str(tmp_path / "cache")
    mgr = CompileCacheManager(
        ColdStartConfig(enabled=True, cache_dir=root,
                        max_cache_bytes=3000), root)
    os.makedirs(mgr.xla_dir)
    for i in range(5):
        p = os.path.join(mgr.xla_dir, f"entry{i}")
        with open(p, "wb") as f:
            f.write(b"x" * 1000)
        os.utime(p, (1000 + i, 1000 + i))      # oldest first
    husk = os.path.join(mgr.xla_dir, "husk")
    open(husk, "wb").close()
    assert mgr._scrub_husks(mgr.xla_dir) == 1
    assert not os.path.exists(husk)
    n, freed = mgr.evict_lru()
    assert n == 2 and freed == 2000
    left = sorted(os.listdir(mgr.xla_dir))
    assert left == ["entry2", "entry3", "entry4"]   # oldest evicted
    assert mgr.disk_usage_bytes() <= 3000


# ------------------------------------------------------------- cache_wipe

def test_cache_wipe_faultplan_refcount_composes(tmp_path):
    """Two overlapping cache_wipe windows: files go at first fire, the
    cache stays suppressed until the LAST window clears, then
    re-enables empty — the refcount composition every windowed kind
    honors."""
    from jax_mapping.resilience.faultplan import FaultEvent, FaultPlan
    root = str(tmp_path / "cache")
    mgr = CompileCacheManager(
        ColdStartConfig(enabled=True, cache_dir=root), root)
    os.makedirs(mgr.xla_dir)
    with open(os.path.join(mgr.xla_dir, "e"), "wb") as f:
        f.write(b"x" * 10)
    stack = types.SimpleNamespace(bus=None, compile_cache=mgr)
    plan = FaultPlan([
        FaultEvent(step=1, kind="cache_wipe", duration=4),
        FaultEvent(step=2, kind="cache_wipe", duration=6),
    ], seed=0)
    plan.apply(stack, 1)
    assert not os.listdir(mgr.xla_dir)
    plan.apply(stack, 2)
    assert mgr.status()["wipe_refs"] == 2
    plan.apply(stack, 5)                     # first window clears
    assert mgr.status()["wipe_refs"] == 1 and not mgr.enabled
    # Saves are suppressed while any window holds.
    assert mgr.save_aot({"f": [((), {})]})["n_saved"] == 0
    plan.apply(stack, 8)                     # last window clears
    assert mgr.status()["wipe_refs"] == 0 and mgr.enabled
    assert plan.done()
    mgr.disable()


def test_cache_wipe_skips_without_manager():
    from jax_mapping.resilience.faultplan import FaultEvent, FaultPlan
    stack = types.SimpleNamespace(bus=None)
    plan = FaultPlan([FaultEvent(step=0, kind="cache_wipe")], seed=0)
    plan.apply(stack, 0)
    assert any("cache_wipe skipped" in d for _s, d in plan.log)


def test_cache_wipe_has_a_resource_and_samples():
    from jax_mapping.resilience.faultplan import (_fault_resource,
                                                  random_plan)
    assert _fault_resource("cache_wipe", 0) == ("cache",)
    plan = random_plan(200, n_faults=12, seed=7, allow_cache_wipe=True)
    kinds = {e.kind for e in plan.events}
    # Seeded sampling admits the kind; defaults exclude it (bit-compat
    # with the pre-ISSUE-12 sampler is pinned elsewhere).
    default_plan = random_plan(200, n_faults=12, seed=7)
    assert "cache_wipe" not in {e.kind for e in default_plan.events}
    assert kinds <= set(__import__(
        "jax_mapping.resilience.faultplan", fromlist=["KINDS"]).KINDS)


# ------------------------------------------- devprof warm-process baseline

def test_devprof_rebaseline_excludes_warm_variants():
    """The satellite regression: variants compiled by the warm-up
    (through the RAW function, as StagedWarmup.prewarm does) must not
    count as live recompiles once `rebaseline()` runs — and without it
    they would, which is exactly the warm-process bug being fixed."""
    from jax_mapping.obs.devprof import DispatchProfiler
    mod = types.ModuleType("jax_mapping._coldstart_probe")

    def probe_fn(x):
        return x * 2 + 1

    mod.probe_fn = jax.jit(probe_fn)
    sys.modules["jax_mapping._coldstart_probe"] = mod
    prof = DispatchProfiler(DevProfConfig(enabled=True))
    try:
        prof.install()
        name = [n for n in prof.recompiles()
                if n.endswith("probe_fn")][0]
        raw = prof.raw_fn(name)
        raw(jnp.ones(3))                     # warm-up compile, unprofiled
        assert prof.rebaseline() == 1
        mod.probe_fn(jnp.ones(3))            # first live call, same variant
        assert prof.recompiles()[name] == 0  # NOT a live recompile
        # Control: the same sequence WITHOUT rebaseline counts — the
        # pre-fix behavior this satellite exists to kill.
        raw(jnp.ones(4))                     # second variant via warm-up
        mod.probe_fn(jnp.ones(4))
        assert prof.recompiles()[name] == 1
    finally:
        prof.uninstall()
        del sys.modules["jax_mapping._coldstart_probe"]


def test_warm_pool_uninstall_unwraps_from_wrapper_chains():
    """Shutdown-leak regression: whichever of (profiler, pool)
    installed second wraps the other's wrapper, and the pool's
    uninstall must splice itself out of EITHER nesting — a
    direct-match-only restore would strand a dead wrapper at module
    scope and starve later profilers of those entry points."""
    from jax_mapping.obs.devprof import DispatchProfiler, _ProfiledJit
    from jax_mapping.io.compile_cache import _WarmJit

    for pool_second in (True, False):
        mod = types.ModuleType("jax_mapping._chain_probe")

        def chain_fn(x):
            return x + 3

        raw = jax.jit(chain_fn)
        mod.chain_fn = raw
        sys.modules["jax_mapping._chain_probe"] = mod
        prof = DispatchProfiler(DevProfConfig(enabled=True))
        pool = WarmPool()
        name = "jax_mapping._chain_probe.chain_fn"
        pool.add(name, "never-matches", lambda *a: None, "full", (), ())
        try:
            if pool_second:
                prof.install()
                pool.install()
                assert isinstance(mod.chain_fn, _WarmJit)
            else:
                pool.install()
                prof.install()
                assert isinstance(mod.chain_fn, _ProfiledJit)
            # Shutdown order contract: pool first, then profiler.
            pool.uninstall()
            prof.uninstall()
            assert mod.chain_fn is raw, (pool_second, mod.chain_fn)
        finally:
            pool.uninstall()
            prof.uninstall()
            del sys.modules["jax_mapping._chain_probe"]


# ------------------------------------------------- staged warm-up machine

def test_staged_warmup_walks_stages_and_reports(tmp_path):
    from jax_mapping.obs.recorder import flight_recorder
    mark = flight_recorder.mark()
    wu = StagedWarmup()
    assert wu.state() == "idle"
    wu.begin_restore()
    wu.begin_warming()
    rep = wu.prewarm({})
    wu.mark_ready()
    assert wu.state() == "ready"
    snap = wu.snapshot()
    assert snap["report"]["n_errors"] == 0
    kinds = [e["kind"] for e in flight_recorder.events_since(mark)]
    assert kinds.count("warmup_stage") == 3
    assert "warmup_ready" in kinds
    assert rep["readiness_violations"] == []


def test_staged_warmup_readiness_gate_flags_over_budget(tmp_path):
    """A variant THIS warm-up compiled past its budget ceiling is
    REPORTED (not raised); variants the long-lived process accumulated
    before the warm-up are not the warm-up's doing and stay quiet (the
    baseline-delta semantics — a warm tier-1 process must not cry
    wolf)."""
    from jax_mapping.obs.devprof import abstract_signature
    mod = types.ModuleType("jax_mapping._readiness_probe")

    def readiness_fn(x):
        return x - 1

    mod.readiness_fn = jax.jit(readiness_fn)
    sys.modules["jax_mapping._readiness_probe"] = mod
    try:
        name = "jax_mapping._readiness_probe.readiness_fn"
        budget = tmp_path / "budget.json"
        budget.write_text(json.dumps(
            {"version": 1, "budgets": [{"name": name, "max": 0}]}))
        sig = abstract_signature((jnp.ones(3),), {})
        wu = StagedWarmup(budget_path=str(budget))
        rep = wu.prewarm({name: [sig]})      # warm-up compiles it: 1 > 0
        assert any(name in v for v in rep["readiness_violations"])
        # Pre-existing variants do NOT violate: a second warm-up that
        # compiles nothing new reports clean against the same budget.
        rep2 = StagedWarmup(budget_path=str(budget)).prewarm({})
        assert rep2["readiness_violations"] == []
    finally:
        del sys.modules["jax_mapping._readiness_probe"]


def test_staged_warmup_racewatch_converges_on_declared_lock():
    """Eraser refinement over the warm-up state machine: a reader
    thread hammers state()/snapshot() while the driver walks the
    stages — zero reports, every watched field's candidate lockset
    converges on `_lock` (the analysis/protection.py declaration)."""
    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch
    wu = StagedWarmup()
    watch = RaceWatch()
    try:
        watch.watch_object(wu, groups_by_class()["StagedWarmup"][0],
                           name="warmup")
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                wu.state()
                wu.snapshot()
                stop.wait(0.001)

        t = threading.Thread(target=reader)
        t.start()
        for _ in range(8):
            wu.begin_restore()
            wu.begin_warming()
            wu.prewarm({})
            wu.mark_ready()
        stop.set()
        t.join(timeout=10)
    finally:
        watch.unwatch_all()
    assert watch.reports() == []
    states = watch.field_states()
    moved = [st for st in states.values()
             if st.state == "shared-modified"]
    assert moved, "nothing went shared-modified — the gate saw no race"
    for st in moved:
        assert "StagedWarmup._lock@warmup" in st.candidate, st


def test_staged_warmup_prewarm_skips_in_process_warm(aot_workspace):
    """An in-process restart (jit caches survived the node) pre-warms
    in O(registry): every already-compiled function reports
    `in_process`, no zeros call runs."""
    _root, sigs, _args, _out = aot_workspace
    wu = StagedWarmup()
    rep = wu.prewarm(sigs)
    assert rep["n_in_process"] >= 1
    assert rep["n_prewarmed"] == 0 and rep["n_errors"] == 0


# ------------------------------------------------ decay-aware frontier score

@pytest.fixture()
def decay_gcfg():
    return GridConfig(size_cells=64, resolution_m=0.1, patch_cells=32,
                      max_range_m=2.0, align_rows=8, align_cols=8)


@pytest.fixture()
def decay_fcfg():
    return FrontierConfig(downsample=4, cluster_downsample=1,
                          max_clusters=8, min_cluster_cells=1,
                          label_prop_iters=16, bfs_iters=32,
                          obstacle_aware=False, incremental=False)


def _two_cluster_world(gcfg, fcfg):
    """A log-odds grid with two disjoint free pockets symmetric about
    a centred robot — each pocket's boundary is one frontier cluster
    at equal Euclidean distance; returns (logodds, pose)."""
    n = gcfg.size_cells
    lo = np.zeros((n, n), np.float32)
    lo[28:36, 8:24] = -2.0      # left pocket
    lo[28:36, 40:56] = -2.0     # right pocket (mirror)
    return jnp.asarray(lo), jnp.asarray([[0.0, 0.0, 0.0]], jnp.float32)


def test_decay_aware_off_is_bit_exact(decay_gcfg, decay_fcfg):
    """Knob off (default) and knob on over a grid with NO stale cells
    produce bit-identical assignments/targets/costs: the discount
    multiplies by exactly 1.0 when nothing is stale, and is never
    traced at all when the knob is off."""
    import dataclasses
    lo, pose = _two_cluster_world(decay_gcfg, decay_fcfg)
    off = F_compute(decay_fcfg, decay_gcfg, lo, pose)
    on_cfg = dataclasses.replace(decay_fcfg, decay_aware=True)
    on = F_compute(on_cfg, decay_gcfg, lo, pose)
    np.testing.assert_array_equal(np.asarray(off.costs),
                                  np.asarray(on.costs))
    np.testing.assert_array_equal(np.asarray(off.assignment),
                                  np.asarray(on.assignment))
    np.testing.assert_array_equal(np.asarray(off.targets),
                                  np.asarray(on.targets))


def F_compute(fcfg, gcfg, lo, pose):
    from jax_mapping.ops import frontier as F
    return F.compute_frontiers(fcfg, gcfg, lo, pose)


def test_stale_mask_flags_healed_not_fresh(decay_gcfg, decay_fcfg):
    from jax_mapping.ops import frontier as F
    n = decay_gcfg.size_cells
    lo = np.zeros((n, n), np.float32)
    lo[8:12, 8:12] = 0.2           # decayed evidence: sub-threshold, != 0
    lo[40:44, 40:44] = -2.0        # solidly free: not unknown
    mask = np.asarray(F.stale_mask(decay_fcfg, decay_gcfg,
                                   jnp.asarray(lo)))
    d = decay_fcfg.downsample
    assert mask[8 // d, 8 // d]
    assert not mask[40 // d, 40 // d]
    assert not mask[0, 0]          # fresh unknown never flags


def test_decay_aware_prefers_stale_frontier(decay_gcfg, decay_fcfg):
    """Two equidistant clusters; residual decayed evidence beyond one
    end. decay_aware=True steers the assignment to the stale side for
    re-verification; False keeps the plain distance tie-break."""
    import dataclasses
    lo_np, pose = _two_cluster_world(decay_gcfg, decay_fcfg)
    lo_np = np.array(np.asarray(lo_np))
    # Healed region beyond the RIGHT pocket: touched, sub-threshold.
    lo_np[28:36, 56:62] = 0.1
    lo = jnp.asarray(lo_np)
    off = F_compute(decay_fcfg, decay_gcfg, lo, pose)
    on = F_compute(dataclasses.replace(decay_fcfg, decay_aware=True),
                   decay_gcfg, lo, pose)
    tx_off = float(np.asarray(off.targets)[int(np.asarray(off.assignment)[0])][0])
    tx_on = float(np.asarray(on.targets)[int(np.asarray(on.assignment)[0])][0])
    # The discounted (stale, right-side) cluster wins under the knob.
    assert tx_on > 0.0
    assert tx_on >= tx_off


# --------------------------------------------- checkpoint-load observability

def test_checkpoint_fallback_slot_recorded(tmp_path):
    from jax_mapping.io.checkpoint import (fallback_counts,
                                           load_checkpoint_with_fallback,
                                           save_checkpoint)
    from jax_mapping.obs.recorder import flight_recorder
    path = str(tmp_path / "ck.npz")
    state = {"a": np.arange(6, dtype=np.float32)}
    save_checkpoint(path, state)
    save_checkpoint(path, {"a": np.arange(6, dtype=np.float32) + 1})
    before = fallback_counts()
    mark = flight_recorder.mark()
    _st, _cfg, used = load_checkpoint_with_fallback(path, state)
    assert used == path
    after = fallback_counts()
    assert after["primary"] == before["primary"] + 1
    evs = [e for e in flight_recorder.events_since(mark)
           if e["kind"] == "checkpoint_fallback"]
    assert evs and evs[-1]["slot"] == "primary" \
        and evs[-1]["fell_back"] is False
    # Rot the primary: the .prev rescue is now VISIBLE, not silent.
    with open(path, "r+b") as f:
        f.truncate(20)
    mark = flight_recorder.mark()
    _st, _cfg, used = load_checkpoint_with_fallback(path, state)
    assert used.endswith(".prev.npz")
    assert fallback_counts()["prev"] == before["prev"] + 1
    evs = [e for e in flight_recorder.events_since(mark)
           if e["kind"] == "checkpoint_fallback"]
    assert evs and evs[-1]["slot"] == "prev" \
        and evs[-1]["fell_back"] is True
