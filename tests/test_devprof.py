"""Device-side performance observability (ISSUE 10): the dispatch
profiler (obs/devprof.py), the static XLA cost ledger (obs/ledger.py)
and the bench trajectory harness (bench.py schema / --validate /
--regress).

Unit/component tier — no stack launches (the tier-1 wall budget is
spoken for); the live surfaces (dispatch attribution on a real
mission, steady-state recompile guard, /status.perf, /metrics device
families) piggyback on the shared module-scoped mission stack in
tests/test_scenarios.py.
"""

import functools
import importlib.util
import json
import os
import sys
import types

import numpy as np
import pytest

from jax_mapping.config import DevProfConfig
from jax_mapping.obs import CostLedger, DispatchProfiler

_FIXTURE_PREFIX = "devprof_fixture"


@pytest.fixture()
def fixture_mod():
    """A synthetic module under its own prefix holding jitted entry
    points (plus an alias — the from-import case), so install() can be
    exercised without wrapping the real package."""
    import jax

    mod = types.ModuleType(_FIXTURE_PREFIX)

    @functools.partial(jax.jit, static_argnums=(0,))
    def scaled(k, x):
        return x * k

    @jax.jit
    def double(x):
        return x + x

    mod.scaled = scaled
    mod.double = double
    mod.scaled_alias = scaled                    # from-import binding
    mod.not_jitted = lambda x: x
    sys.modules[_FIXTURE_PREFIX] = mod
    try:
        yield mod
    finally:
        sys.modules.pop(_FIXTURE_PREFIX, None)


def _install(mod, **cfg_kw):
    prof = DispatchProfiler(DevProfConfig(enabled=True, **cfg_kw))
    n = prof.install(prefix=_FIXTURE_PREFIX)
    return prof, n


# ------------------------------------------------------ dispatch profiler

def test_wrapper_wraps_counts_and_times(fixture_mod):
    import jax.numpy as jnp

    prof, n = _install(fixture_mod)
    try:
        assert n == 2                            # scaled(+alias), double
        x = jnp.ones((8, 8))
        fixture_mod.scaled(2, x)
        fixture_mod.scaled_alias(2, x)           # alias -> same profile
        fixture_mod.double(x)
        snap = prof.snapshot()
        sc = snap[f"{_FIXTURE_PREFIX}.scaled"]
        assert sc["count"] == 2
        assert sc["total_ms"] > 0 and sc["max_ms"] >= sc["mean_ms"] / 2
        assert snap[f"{_FIXTURE_PREFIX}.double"]["count"] == 1
        # Histograms ride the shared fixed log-bucket grid.
        from jax_mapping.utils.profiling import HIST_EDGES_S
        h = prof.histograms()[f"{_FIXTURE_PREFIX}.scaled"]
        assert h["edges_s"] == HIST_EDGES_S
        assert sum(h["buckets"]) == h["count"] == 2
        # The un-jitted callable was left alone.
        assert fixture_mod.not_jitted(3) == 3
        assert not hasattr(fixture_mod.not_jitted, "_prof")
    finally:
        prof.uninstall()


def test_wrapper_is_transparent(fixture_mod):
    prof, _ = _install(fixture_mod)
    try:
        w = fixture_mod.scaled
        # Introspection forwards: the compilebudget registry walk and
        # AOT lowering see the wrapped function's own surface.
        assert callable(w._cache_size)
        assert w.__name__ == "scaled"
        assert w.__module__.endswith("test_devprof")
    finally:
        prof.uninstall()


def test_recompile_detection_and_signature_capture(fixture_mod):
    import jax.numpy as jnp

    prof, _ = _install(fixture_mod)
    try:
        fixture_mod.scaled(2, jnp.ones((8, 8)))
        fixture_mod.scaled(2, jnp.ones((8, 8)))   # cache hit: no growth
        fixture_mod.scaled(2, jnp.ones((4, 4)))   # second variant
        fixture_mod.scaled(3, jnp.ones((4, 4)))   # third (static arg)
        recs = prof.recompiles()
        assert recs[f"{_FIXTURE_PREFIX}.scaled"] == 3
        assert recs[f"{_FIXTURE_PREFIX}.double"] == 0
        sigs = prof.signatures()[f"{_FIXTURE_PREFIX}.scaled"]
        assert len(sigs) == 3
    finally:
        prof.uninstall()


def test_signature_capture_is_bounded(fixture_mod):
    import jax.numpy as jnp

    prof, _ = _install(fixture_mod, max_signatures_per_fn=2)
    try:
        for n in range(2, 7):                    # 5 distinct variants
            fixture_mod.scaled(n, jnp.ones((4, 4)))
        assert prof.recompiles()[f"{_FIXTURE_PREFIX}.scaled"] == 5
        assert len(prof.signatures()[f"{_FIXTURE_PREFIX}.scaled"]) == 2
    finally:
        prof.uninstall()


def test_trace_time_calls_bypass_recording(fixture_mod):
    """A wrapped function invoked while ANOTHER jit traces its caller
    is compile cost, not dispatch cost — the recorder must not see
    it."""
    import jax
    import jax.numpy as jnp

    prof, _ = _install(fixture_mod)
    try:
        x = jnp.ones((8, 8))
        fixture_mod.double(x)
        before = prof.snapshot()[f"{_FIXTURE_PREFIX}.double"]["count"]

        @jax.jit
        def outer(x):
            return fixture_mod.double(x) + 1

        jax.block_until_ready(outer(x))          # traces through double
        after = prof.snapshot()[f"{_FIXTURE_PREFIX}.double"]["count"]
        assert after == before
    finally:
        prof.uninstall()


def test_uninstall_restores_every_alias(fixture_mod):
    orig = fixture_mod.scaled
    prof, _ = _install(fixture_mod)
    assert fixture_mod.scaled is not orig        # wrapped
    assert fixture_mod.scaled is fixture_mod.scaled_alias
    prof.uninstall()
    assert fixture_mod.scaled is orig
    assert fixture_mod.scaled_alias is orig
    assert fixture_mod.double.__name__ == "double"
    prof.uninstall()                             # idempotent


def test_second_live_profiler_is_refused(fixture_mod):
    prof, _ = _install(fixture_mod)
    try:
        other = DispatchProfiler(DevProfConfig(enabled=True))
        with pytest.raises(RuntimeError, match="another"):
            other.install(prefix=_FIXTURE_PREFIX)
        # Re-install by the OWNER is incremental, not an error.
        assert prof.install(prefix=_FIXTURE_PREFIX) == 0
    finally:
        prof.uninstall()


def test_memory_stats_graceful_none_on_cpu(fixture_mod):
    prof, _ = _install(fixture_mod)
    try:
        assert prof.memory_stats() is None       # CPU: no memory_stats
        off = DispatchProfiler(DevProfConfig(enabled=True,
                                             memory_stats=False))
        assert off.memory_stats() is None        # knob off: same shape
    finally:
        prof.uninstall()


# ------------------------------------------------------------ cost ledger

def test_cost_ledger_reports_flops_and_bytes(fixture_mod):
    import jax.numpy as jnp

    prof, _ = _install(fixture_mod)
    try:
        fixture_mod.scaled(2, jnp.ones((8, 8)))
        fixture_mod.scaled(2, jnp.ones((4, 4)))
        ledger = CostLedger(prof)
        assert ledger.n_uncollected() == 2
        got = ledger.collect()
        variants = got[f"{_FIXTURE_PREFIX}.scaled"]
        assert len(variants) == 2
        for v in variants:
            assert v["flops"] > 0
            assert v["bytes_accessed"] > 0
            assert "8x8" in v["signature"] or "4x4" in v["signature"]
        assert ledger.n_uncollected() == 0
    finally:
        prof.uninstall()


def test_cost_ledger_collect_is_cached(fixture_mod, monkeypatch):
    import jax.numpy as jnp

    prof, _ = _install(fixture_mod)
    try:
        fixture_mod.double(jnp.ones((8, 8)))
        ledger = CostLedger(prof)
        calls = []
        real = CostLedger._collect_one

        def counting(fn, sig):
            calls.append(1)
            return real(fn, sig)

        monkeypatch.setattr(CostLedger, "_collect_one",
                            staticmethod(counting))
        ledger.collect()
        ledger.collect()                         # second pass: all cached
        assert len(calls) == 1
    finally:
        prof.uninstall()


def test_cost_ledger_cross_check_against_budget(fixture_mod, tmp_path):
    import jax.numpy as jnp

    prof, _ = _install(fixture_mod)
    try:
        fixture_mod.scaled(2, jnp.ones((8, 8)))
        ledger = CostLedger(prof)
        ledger.collect()
        budget = tmp_path / "budget.json"
        budget.write_text(json.dumps({"version": 1, "budgets": [
            {"name": f"{_FIXTURE_PREFIX}.scaled", "max": 1},
        ]}))
        assert ledger.cross_check(str(budget)) == []
        # A budgeted function with no coverage is a violation; so is a
        # variant count above budget.
        budget.write_text(json.dumps({"version": 1, "budgets": [
            {"name": f"{_FIXTURE_PREFIX}.scaled", "max": 1},
            {"name": f"{_FIXTURE_PREFIX}.double", "max": 1},
        ]}))
        (viol,) = ledger.cross_check(str(budget))
        assert "double" in viol and "no cost-ledger coverage" in viol
        fixture_mod.scaled(2, jnp.ones((4, 4)))
        ledger.collect()
        viols = ledger.cross_check(str(budget))
        assert any("exceeds budget" in v for v in viols)
    finally:
        prof.uninstall()


def test_devprof_config_json_roundtrip():
    from jax_mapping.config import ObsConfig, SlamConfig, tiny_config

    cfg = tiny_config().replace(obs=ObsConfig(
        enabled=True,
        devprof=DevProfConfig(enabled=True, max_signatures_per_fn=3)))
    back = SlamConfig.from_json(cfg.to_json())
    assert isinstance(back.obs.devprof, DevProfConfig)
    assert back == cfg
    # devprof defaults OFF — the shipped bit-exact default.
    assert not tiny_config().obs.devprof.enabled


# ------------------------------------------- bench trajectory harness

@pytest.fixture(scope="module")
def bench():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(root, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_validate_committed_trajectory_is_clean(bench):
    """Every committed BENCH_*.json parses and passes the schema
    (legacy records grandfathered) — the `bench.py --validate` gate."""
    n, errors = bench.validate_bench_records()
    assert n >= 11
    assert errors == [], "\n".join(errors)


def test_bench_validate_flags_bad_records(bench, tmp_path):
    (tmp_path / "BENCH_BAD_r01.json").write_text("{not json")
    (tmp_path / "BENCH_EMPTY_r01.json").write_text("{}")
    (tmp_path / "BENCH_V99_r01.json").write_text(json.dumps(
        {"bench_schema": 99, "metric": "m"}))
    (tmp_path / "BENCH_NOMETH_r01.json").write_text(json.dumps(
        {"bench_schema": 1, "suite": "x", "metric": "m"}))
    # A wrapped record whose captured run FAILED is grandfathered (the
    # trajectory recording a dead round is data); a wrapped record
    # claiming success with no JSON line is not.
    (tmp_path / "BENCH_DEAD_r01.json").write_text(json.dumps(
        {"n": 1, "cmd": "python bench.py", "rc": 124, "tail": "boom"}))
    (tmp_path / "BENCH_LIAR_r01.json").write_text(json.dumps(
        {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "no json"}))
    n, errors = bench.validate_bench_records(str(tmp_path))
    assert n == 6
    joined = "\n".join(errors)
    assert "BENCH_BAD_r01.json" in joined
    assert "BENCH_EMPTY_r01.json" in joined
    assert "BENCH_V99_r01.json" in joined
    assert "BENCH_NOMETH_r01.json" in joined
    assert "BENCH_LIAR_r01.json" in joined
    assert "BENCH_DEAD_r01.json" not in joined


def test_bench_record_extraction_unwraps_driver_tail(bench):
    rec, wrapped = bench.extract_bench_record(
        {"n": 3, "cmd": "python bench.py", "rc": 0,
         "tail": 'noise\n{"metric": "m", "value": 1}\n'})
    assert wrapped and rec == {"metric": "m", "value": 1}
    rec, wrapped = bench.extract_bench_record({"metric": "m"})
    assert not wrapped and rec == {"metric": "m"}


def test_bench_stamp_record_preserves_existing_fields(bench):
    r = {"suite": "obs", "methodology": "mine"}
    bench._stamp_record(r, "main", "default", reps=4)
    assert r["suite"] == "obs" and r["methodology"] == "mine"
    assert r["bench_schema"] == bench.BENCH_SCHEMA_VERSION
    assert r["reps"] == 4


def test_regress_detects_seeded_synthetic_slowdown(bench):
    """THE regression-harness acceptance: a clean self-comparison
    passes; a seeded synthetic slowdown injected into the workload
    timing is detected (both the raw and reference-normalized ratios
    clear the gate)."""
    base = bench.run_regress_suite(reps=2)
    ok, report = bench.compare_regress(base, base)
    assert ok, report
    slow_ms = max(4.0 * base["workloads"]["fuse_tiny"]["p50_ms"], 50.0)
    slowed = bench.run_regress_suite(reps=2, synthetic_slow_ms=slow_ms)
    ok, report = bench.compare_regress(slowed, base)
    assert not ok, report
    assert any("REGRESSION" in line for line in report)


def test_regress_passes_clean_against_committed_trajectory(bench):
    """A fresh run of the regress micro-suite on this machine clears
    the committed BENCH_REGRESS_r* trajectory at the default gate —
    the `bench.py --regress` exit-0 path."""
    committed = bench.newest_committed_regress()
    assert committed is not None, "no committed BENCH_REGRESS_r*.json"
    fresh = bench.run_regress_suite(reps=3)
    ok, report = bench.compare_regress(fresh, committed)
    assert ok, "\n".join(report)


def test_regress_refuses_incomparable_records(bench):
    ok, report = bench.compare_regress(
        {"workloads": {"a": {"p50_ms": 1, "ref_p50_ms": 1}}},
        {"workloads": {"b": {"p50_ms": 1, "ref_p50_ms": 1}}})
    assert not ok and "no comparable workloads" in report[0]
