"""Global planner: ops/planner.py + bridge/planner.py + brain waypoint.

The Nav2-shaped capability behind RViz SetGoal (the reference shipped the
tool with no consumer, `server/rviz_config.rviz:193-198`; Nav2 was future
work, report.pdf §VI.2). Ops tests pin the goal-seeded field + greedy
descent against hand-built worlds; the stack test drives the headline
behavior: a goal straight behind a wall — which round 4's straight-line
seek could only shield against (test_bridge.py::
test_goal_behind_wall_shield_wins) — is now navigated AROUND and reached.
"""

import dataclasses
import math

import numpy as np
import jax.numpy as jnp
import pytest

from jax_mapping.config import PlannerConfig, tiny_config
from jax_mapping.ops import frontier as F
from jax_mapping.ops import planner as P


@pytest.fixture(scope="module")
def walled():
    """Tiny grid with a vertical wall and one gap near the top; start on
    the left of the wall, goal on the right, both near the bottom."""
    cfg = tiny_config()
    g, f = cfg.grid, cfg.frontier
    n = g.size_cells
    lo = np.full((n, n), -1.0, np.float32)          # known free
    mid = n // 2
    lo[:, mid - 2:mid + 2] = 3.0                    # wall
    lo[n - 40:n - 20, mid - 2:mid + 2] = -1.0       # gap
    res = g.resolution_m * f.downsample
    ox, oy = g.origin_m
    start = jnp.array([ox + 10 * res, oy + 10 * res])
    goal = jnp.array([ox + (n // f.downsample - 10) * res, oy + 10 * res])
    return cfg, lo, start, goal


def test_plan_detours_through_gap(walled):
    cfg, lo, start, goal = walled
    g, f = cfg.grid, cfg.frontier
    pcfg = PlannerConfig(max_path_len=256, lookahead_cells=4, bfs_iters=256)
    r = P.plan_to_goal(pcfg, f, g, jnp.asarray(lo), goal, start)
    assert bool(r.reachable) and not bool(r.arrived)
    path = np.asarray(r.path_xy)[np.asarray(r.path_valid)]
    assert len(path) == int(r.n_steps) > 0
    # Ends at the goal cell's centre (within one coarse cell).
    res = g.resolution_m * f.downsample
    assert np.hypot(*(path[-1] - np.asarray(goal))) <= res * 1.5
    # The detour passes through the gap's y-band — the straight line does
    # not (start/goal are near the bottom, the gap near the top).
    gap_y_lo = (g.size_cells - 40) * g.resolution_m + g.origin_m[1]
    assert path[:, 1].max() >= gap_y_lo - 2 * res
    # No valid path cell sits inside the wall (coarse-passability check).
    free, _occ, unknown = F.coarsen(f, g, jnp.asarray(lo))
    passable = np.asarray(free | F.frontier_mask(free, unknown) | unknown)
    ox, oy = g.origin_m
    rr = ((path[:, 1] - oy) / res).astype(int)
    cc = ((path[:, 0] - ox) / res).astype(int)
    assert passable[rr, cc].all(), "plan crosses a blocked coarse cell"


def test_plan_sealed_goal_unreachable(walled):
    cfg, lo, start, goal = walled
    g, f = cfg.grid, cfg.frontier
    lo = lo.copy()
    mid = g.size_cells // 2
    lo[:, mid - 2:mid + 2] = 3.0                    # close the gap
    pcfg = PlannerConfig(max_path_len=256, lookahead_cells=4, bfs_iters=256)
    r = P.plan_to_goal(pcfg, f, g, jnp.asarray(lo), goal, start)
    assert not bool(r.reachable)
    assert int(r.n_steps) == 0
    assert not np.asarray(r.path_valid).any()
    # Waypoint degrades to the goal itself (brain keeps round-4 seek).
    assert np.allclose(np.asarray(r.waypoint_xy), np.asarray(goal))


def test_plan_already_at_goal(walled):
    cfg, lo, start, _ = walled
    g, f = cfg.grid, cfg.frontier
    pcfg = PlannerConfig(max_path_len=64, lookahead_cells=4, bfs_iters=64)
    r = P.plan_to_goal(pcfg, f, g, jnp.asarray(lo), start, start)
    assert bool(r.arrived) and bool(r.reachable)
    assert int(r.n_steps) == 0


def test_plan_partial_beyond_horizon(walled):
    """A goal farther than the descent horizon keeps the whole prefix —
    a partial path still steers the robot the right way."""
    cfg, lo, start, goal = walled
    g, f = cfg.grid, cfg.frontier
    pcfg = PlannerConfig(max_path_len=16, lookahead_cells=4, bfs_iters=256)
    r = P.plan_to_goal(pcfg, f, g, jnp.asarray(lo), goal, start)
    assert bool(r.reachable)
    assert int(r.n_steps) == 16
    assert np.asarray(r.path_valid).all()
    # Waypoint is the 4th path cell, one coarse step per cell from start.
    path = np.asarray(r.path_xy)
    assert np.allclose(np.asarray(r.waypoint_xy), path[3])


def test_waypoint_within_lookahead(walled):
    cfg, lo, start, goal = walled
    g, f = cfg.grid, cfg.frontier
    pcfg = PlannerConfig(max_path_len=256, lookahead_cells=4, bfs_iters=256)
    r = P.plan_to_goal(pcfg, f, g, jnp.asarray(lo), goal, start)
    res = g.resolution_m * f.downsample
    d = np.hypot(*(np.asarray(r.waypoint_xy) - np.asarray(start)))
    # 4 coarse steps, diagonal moves allowed, plus the start point's
    # offset from its own cell centre -> at most 4.5*sqrt(2) cells.
    assert d <= 4.5 * math.sqrt(2) * res + 1e-6


# ---------------------------------------------------------------------------
# 3D-aware planning (voxel obstacle overlay)
# ---------------------------------------------------------------------------

def _voxel_band_indices(vox, pcfg):
    oz = vox.origin_m[2]
    zs = (np.arange(vox.size_z_cells) + 0.5) * vox.resolution_m + oz
    return np.nonzero((zs >= pcfg.voxel_z_min_m)
                      & (zs <= pcfg.voxel_z_max_m))[0]


def test_overlay_voxel_obstacles_embeds_band(tiny_cfg):
    """Occupied voxels in the robot's height band stamp the matching 2D
    cells occupied; voxels outside the band (overhead clearance) don't."""
    import jax.numpy as jnp

    from jax_mapping.ops import planner as P

    g, vox, pcfg = tiny_cfg.grid, tiny_cfg.voxel, tiny_cfg.planner
    lo = jnp.full((g.size_cells, g.size_cells), -2.0)   # known free
    vg = np.zeros((vox.size_z_cells, vox.size_y_cells,
                   vox.size_x_cells), np.float32)
    band = _voxel_band_indices(vox, pcfg)
    assert len(band) > 0
    vg[band[0], 20, 30] = 3.0                # in-band obstacle
    above = band[-1] + 1
    vg[above, 40, 50] = 3.0                  # above the robot: ignored
    out = np.asarray(P.overlay_voxel_obstacles(
        pcfg, g, vox, lo, jnp.asarray(vg)))
    res = g.resolution_m
    r0 = round((vox.origin_m[1] - g.origin_m[1]) / res)
    c0 = round((vox.origin_m[0] - g.origin_m[0]) / res)
    assert out[r0 + 20, c0 + 30] >= g.occ_threshold
    assert out[r0 + 40, c0 + 50] == -2.0     # overhead: untouched
    assert out[r0 + 21, c0 + 30] == -2.0     # neighbours untouched
    # Resolution mismatch refuses.
    import dataclasses as _dc
    bad = _dc.replace(vox, resolution_m=vox.resolution_m * 2)
    with pytest.raises(ValueError, match="resolution"):
        P.overlay_voxel_obstacles(pcfg, g, bad, lo, jnp.asarray(vg))


def test_resolution_mismatch_degrades_to_2d(tiny_cfg, tmp_path, capsys):
    """A coarser voxel map than the 2D grid disables the overlay at
    CONSTRUCTION (loudly) instead of raising inside the guarded tick and
    silently killing every plan."""
    import dataclasses as _dc

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    cfg = _dc.replace(
        tiny_cfg, voxel=_dc.replace(tiny_cfg.voxel,
                                    resolution_m=tiny_cfg.grid.resolution_m
                                    * 2))
    world = W.empty_arena(96, cfg.grid.resolution_m)
    st = launch_sim_stack(cfg, world, n_robots=1, http_port=None,
                          seed=9, depth_cam=True)
    try:
        assert st.planner.voxel_mapper is None       # overlay disabled
        assert st.mapper.frontier_grid_provider is None
        assert "DISABLED" in capsys.readouterr().out
        # Planning still works on the bare 2D map.
        n = cfg.grid.size_cells
        st.mapper.seed_map_prior(np.full((n, n), -2.0, np.float32))
        _p, reachable, _w, _a = st.planner._plan((1.0, 1.0),
                                                 np.zeros(2, np.float32))
        assert reachable
    finally:
        st.shutdown()


def test_frontier_assignment_sees_voxel_obstacles(tiny_cfg):
    """The auction and the waypoint descent run on the SAME map: with
    the overlay wired, frontier assignment uses the planning grid, so a
    corridor only the 3D map knows is blocked raises the cluster's cost
    rather than assigning it forever against failing plans."""
    import dataclasses as _dc

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    cfg = _dc.replace(
        tiny_cfg, planner=_dc.replace(tiny_cfg.planner, bfs_iters=64))
    world = W.empty_arena(96, cfg.grid.resolution_m)
    st = launch_sim_stack(cfg, world, n_robots=1, http_port=None,
                          seed=10, depth_cam=True)
    try:
        assert st.mapper.frontier_grid_provider is not None
        # The provider returns the overlaid basis: stamp a 3D obstacle,
        # confirm the grid the mapper's frontier pass reads is blocked
        # there while the published /map basis is not.
        vox, pcfg = cfg.voxel, cfg.planner
        vg = np.zeros((vox.size_z_cells, vox.size_y_cells,
                       vox.size_x_cells), np.float32)
        band = _voxel_band_indices(vox, pcfg)
        vg[band[0], 30, 30] = 3.0
        st.voxel_mapper.restore_grid(vg)
        lo = np.asarray(st.mapper.frontier_grid_provider())
        res = cfg.grid.resolution_m
        r0 = round((vox.origin_m[1] - cfg.grid.origin_m[1]) / res)
        c0 = round((vox.origin_m[0] - cfg.grid.origin_m[0]) / res)
        assert lo[r0 + 30, c0 + 30] >= cfg.grid.occ_threshold
        assert np.asarray(st.mapper.merged_grid())[r0 + 30, c0 + 30] \
            < cfg.grid.occ_threshold
    finally:
        st.shutdown()


def test_plan_blocked_by_3d_obstacle(tiny_cfg, tmp_path):
    """A goal ringed by depth-camera obstacles the 2D map knows nothing
    about: reachable on the bare 2D grid, unreachable once the planner
    sees the voxel overlay — the capability 2D-only planning cannot
    have."""
    import dataclasses as _dc

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    cfg = _dc.replace(
        tiny_cfg, planner=_dc.replace(tiny_cfg.planner, bfs_iters=128))
    world = W.empty_arena(96, cfg.grid.resolution_m)
    st = launch_sim_stack(cfg, world, n_robots=1, http_port=None,
                          seed=8, depth_cam=True)
    try:
        n = cfg.grid.size_cells
        st.mapper.seed_map_prior(np.full((n, n), -2.0, np.float32))
        goal = (1.5, 1.5)
        pose = np.zeros(2, np.float32)
        _p, reachable, _w, _a = st.planner._plan(goal, pose)
        assert reachable, "free 2D map must reach the goal"
        # Ring of in-band voxels around the goal (2D map unchanged).
        vox = cfg.voxel
        vg = np.zeros((vox.size_z_cells, vox.size_y_cells,
                       vox.size_x_cells), np.float32)
        band = _voxel_band_indices(vox, cfg.planner)
        res = vox.resolution_m
        gy = round((goal[1] - vox.origin_m[1]) / res)
        gx = round((goal[0] - vox.origin_m[0]) / res)
        r = 12
        for z in band:
            vg[z, gy - r:gy + r, gx - r:gx - r + 3] = 3.0
            vg[z, gy - r:gy + r, gx + r:gx + r + 3] = 3.0
            vg[z, gy - r:gy - r + 3, gx - r:gx + r] = 3.0
            vg[z, gy + r:gy + r + 3, gx - r:gx + r + 3] = 3.0
        st.voxel_mapper.restore_grid(vg)
        _p, reachable, _w, _a = st.planner._plan(goal, pose)
        assert not reachable, (
            "3D ring did not block the plan — the overlay never reached "
            "the planner")
    finally:
        st.shutdown()


# ---------------------------------------------------------------------------
# Brain waypoint preference (unit)
# ---------------------------------------------------------------------------

def _mk_waypoint(x, y, goal, stamp, reachable=True):
    from jax_mapping.bridge.messages import Header, Waypoint
    return Waypoint(header=Header(stamp=stamp, frame_id="map"), x=x, y=y,
                    reachable=reachable, goal_x=goal[0], goal_y=goal[1])


def test_brain_steer_target_rules(tiny_cfg):
    """The brain steers at the waypoint only while it is fresh (in
    CONTROL TICKS — wall-clock freshness would make faster-than-realtime
    drives host-speed dependent), reachable, and computed for the CURRENT
    goal; otherwise the raw goal (round-4 straight-line seek)."""
    import time as _t

    from jax_mapping.bridge.brain import ThymioBrain
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.driver import SimulatedThymioDriver

    bus = Bus()
    brain = ThymioBrain(tiny_cfg, bus, SimulatedThymioDriver(n_robots=1))
    try:
        goal = (1.0, 2.0)
        now = _t.monotonic()
        ttl_ticks = (tiny_cfg.planner.waypoint_ttl_s
                     * tiny_cfg.robot.control_rate_hz)
        assert brain._steer_target(goal) == goal               # no waypoint
        bus.publisher("/goal_waypoint").publish(
            _mk_waypoint(0.5, 0.6, goal, now))
        assert brain._steer_target(goal) == (0.5, 0.6)         # fresh+match
        brain.n_ticks += int(ttl_ticks) + 1
        assert brain._steer_target(goal) == goal               # stale
        bus.publisher("/goal_waypoint").publish(
            _mk_waypoint(0.5, 0.6, goal, now))
        assert brain._steer_target(goal) == (0.5, 0.6)         # re-fresh
        bus.publisher("/goal_waypoint").publish(
            _mk_waypoint(0.5, 0.6, (9.0, 9.0), now))
        assert brain._steer_target(goal) == goal               # superseded
        bus.publisher("/goal_waypoint").publish(
            _mk_waypoint(0.5, 0.6, goal, now, reachable=False))
        assert brain._steer_target(goal) == goal               # unreachable
    finally:
        brain.destroy()


# ---------------------------------------------------------------------------
# Full stack: the headline behavior
# ---------------------------------------------------------------------------

def _planner_stack(tiny_cfg, world):
    from jax_mapping.bridge.launch import launch_sim_stack
    cfg = dataclasses.replace(
        tiny_cfg,
        robot=dataclasses.replace(tiny_cfg.robot, cruise_speed_units=600),
        planner=dataclasses.replace(tiny_cfg.planner, enabled=True,
                                    lookahead_cells=3, bfs_iters=128))
    return launch_sim_stack(cfg, world, n_robots=1, http_port=0, seed=2)


def test_planner_node_publishes_plan(tiny_cfg):
    """Goal set -> /plan carries a nonempty world-frame path and /status
    exposes the planner's health fields."""
    import json as _json
    import urllib.request

    from jax_mapping.bridge.messages import Pose2D
    from jax_mapping.sim import world as W

    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = _planner_stack(tiny_cfg, world)
    try:
        plans = []
        st.bus.subscribe("/plan", callback=plans.append)
        st.brain.start_exploring()
        st.run_steps(3)
        st.bus.publisher("/goal_pose").publish(Pose2D(0.9, 0.4, 0.0))
        st.run_steps(2 * round(st.cfg.planner.period_s
                               * st.cfg.robot.control_rate_hz))
        assert st.planner.n_plans > 0
        assert plans, "no /plan message published"
        path = plans[-1].poses_xy
        assert path.shape[0] > 0 and path.shape[1] == 2
        with urllib.request.urlopen(
                f"http://127.0.0.1:{st.api.port}/status") as resp:
            body = _json.loads(resp.read())
        assert body["n_plans"] >= 1
        assert body["plan_reachable"] is True
    finally:
        st.shutdown()


def test_planner_reaches_goal_behind_wall(tiny_cfg):
    """THE capability delta vs round 4: the same goal-behind-a-wall
    scenario whose goal the shield test proves merely stays set is now
    navigated around via the live map — the robot reaches the goal, never
    entering a wall cell on the way."""
    from jax_mapping.bridge.messages import Pose2D
    from jax_mapping.sim import world as W

    res = tiny_cfg.grid.resolution_m
    world = np.asarray(W.empty_arena(96, res), bool).copy()
    c = 96 // 2
    # Wall at x = 0.9..1.0 m spanning y = -0.5..0.5; goal beyond it.
    world[c - 10:c + 10, c + 18:c + 20] = True
    st = _planner_stack(tiny_cfg, world)
    try:
        st.brain.start_exploring()
        st.run_steps(3)
        st.bus.publisher("/goal_pose").publish(Pose2D(1.4, 0.0, 0.0))
        reached_at = None
        for step in range(1200):
            st.run_steps(1)
            p = st.sim.truth_poses()[0]
            r = int(round(p[1] / res)) + c
            cc = int(round(p[0] / res)) + c
            assert not world[r, cc], (
                f"robot drove into the wall at ({p[0]:.2f}, {p[1]:.2f})")
            if st.brain.status()["goal"] is None:
                reached_at = step
                break
        assert reached_at is not None, (
            "goal behind the wall never reached with the planner "
            f"(last pose {p[0]:.2f},{p[1]:.2f}; "
            f"plans={st.planner.n_plans}, "
            f"reachable={st.planner.last_reachable})")
        pose = st.sim.truth_poses()[0]
        d = math.hypot(pose[0] - 1.4, pose[1] - 0.0)
        assert d < 3 * st.brain.goal_reached_dist_m
    finally:
        st.shutdown()


def test_fleet_manual_goals_reach_and_clear(tiny_cfg):
    """Fleet goal dispatch: /goal_pose drives robot 0 and {ns}goal_pose
    drives robot 1 SIMULTANEOUSLY; each arrives within
    goal_reached_dist_m and clears its own goal, with planner waypoints
    per robot."""
    from jax_mapping.bridge.messages import Pose2D
    from jax_mapping.sim import world as W

    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    cfg = dataclasses.replace(
        tiny_cfg,
        robot=dataclasses.replace(tiny_cfg.robot, cruise_speed_units=600),
        planner=dataclasses.replace(tiny_cfg.planner, lookahead_cells=3,
                                    bfs_iters=128))
    from jax_mapping.bridge.launch import launch_sim_stack
    st = launch_sim_stack(cfg, world, n_robots=2, http_port=None, seed=20)
    try:
        st.brain.start_exploring()
        st.run_steps(3)
        starts = st.sim.truth_poses().copy()
        g0 = (float(starts[0, 0]) + 0.5, float(starts[0, 1]) + 0.2)
        g1 = (float(starts[1, 0]) - 0.5, float(starts[1, 1]) - 0.2)
        st.bus.publisher("/goal_pose").publish(Pose2D(*g0, 0.0))
        st.bus.publisher("robot1/goal_pose").publish(Pose2D(*g1, 0.0))
        status = st.brain.status()
        assert status["goals"][0] is not None
        assert status["goals"][1] is not None
        done = [None, None]
        for step in range(700):
            st.run_steps(1)
            goals = st.brain.status()["goals"]
            for i in (0, 1):
                if done[i] is None and goals[i] is None:
                    done[i] = step
            if all(d is not None for d in done):
                break
        assert all(d is not None for d in done), (
            f"goals never both cleared: {done}, "
            f"{st.brain.status()['goals']}")
        poses = st.sim.truth_poses()
        assert math.hypot(poses[0, 0] - g0[0], poses[0, 1] - g0[1]) \
            < 3 * st.brain.goal_reached_dist_m
        assert math.hypot(poses[1, 0] - g1[0], poses[1, 1] - g1[1]) \
            < 3 * st.brain.goal_reached_dist_m
    finally:
        st.shutdown()


def test_http_goal_endpoint(tiny_cfg):
    """POST /goal?x&y[&robot] — the HTTP twin of RViz SetGoal, through
    the same bus ingress; GET refused; bad input 400."""
    import json as _json
    import urllib.error
    import urllib.request

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = launch_sim_stack(tiny_cfg, world, n_robots=2, http_port=0,
                          seed=24)
    try:
        base = f"http://127.0.0.1:{st.api.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/goal?x=1&y=2")
        assert ei.value.code == 405
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/goal?x=0.5&y=0.25", method="POST")) as r:
            assert _json.loads(r.read())["robot"] == 0
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/goal?x=-0.5&y=0.1&robot=1", method="POST")) as r:
            assert _json.loads(r.read())["robot"] == 1
        goals = st.brain.status()["goals"]
        assert goals[0] == {"x": 0.5, "y": 0.25}
        assert goals[1] == {"x": -0.5, "y": 0.1}
        for bad in ("/goal?x=abc&y=2", "/goal?y=2", "/goal?x=1&y=2&robot=7",
                    "/goal?x=nan&y=2", "/goal?x=1&y=inf",
                    "/goal?x=99&y=0"):       # outside the map extent
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    base + bad, method="POST"))
            assert ei.value.code == 400
        # Cancel: the escape hatch for a goal the operator regrets.
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/goal/cancel?robot=1", method="POST")) as r:
            assert _json.loads(r.read())["status"] == "goal cancelled"
        assert st.brain.status()["goals"][1] is None
        assert st.brain.status()["goals"][0] is not None   # untouched
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/goal/cancel?robot=1", method="POST")) as r:
            assert _json.loads(r.read())["status"] == "no goal set"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/goal/cancel")   # GET
        assert ei.value.code == 405
    finally:
        st.shutdown()


def test_goal_pipeline_survives_lossy_bus(tiny_cfg):
    """QoS fidelity for the round-5 topics: with 30% bus loss the
    planner/waypoint/frontier pipeline keeps running (drops degrade to
    straight-line seek by design, never crash), the mapper keeps fusing,
    and the goal still clears."""
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.bridge.messages import Pose2D
    from jax_mapping.sim import world as W

    cfg = dataclasses.replace(
        tiny_cfg,
        robot=dataclasses.replace(tiny_cfg.robot, cruise_speed_units=600),
        planner=dataclasses.replace(tiny_cfg.planner, lookahead_cells=3,
                                    bfs_iters=128))
    world = W.empty_arena(96, cfg.grid.resolution_m)
    st = launch_sim_stack(cfg, world, n_robots=2, http_port=None,
                          seed=25, drop_prob=0.3)
    try:
        st.brain.start_exploring()
        st.run_steps(3)
        start = st.sim.truth_poses()[0]
        # Goal via a RELIABLE direct publish (losing the goal itself is
        # not what this test measures).
        goal = (float(start[0]) + 0.5, float(start[1]) + 0.2)
        for _ in range(20):                  # until delivery (lossy bus)
            st.bus.publisher("/goal_pose").publish(Pose2D(*goal, 0.0))
            if st.brain.status()["goals"][0] is not None:
                break
        assert st.brain.status()["goals"][0] is not None, \
            "goal never delivered (vacuous-pass guard)"
        cleared = False
        for _ in range(700):
            st.run_steps(1)
            if st.brain.status()["goals"][0] is None:
                cleared = True
                break
        assert cleared, "goal never cleared under 30% loss"
        assert st.mapper.n_scans_fused > 0
        assert st.brain.n_errors == 0 and st.mapper.n_errors == 0
        assert st.planner.n_errors == 0
    finally:
        st.shutdown()
