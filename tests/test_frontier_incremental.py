"""Incremental revision-keyed exploration pipeline (ISSUE 6).

Parity methodology per PR 5: SEEDED randomized property tests —
deterministic by construction — comparing the incremental pipeline's
published triple (assignment, targets, sizes) against the full
`compute_frontiers` recompute at every step of random dirty-tile
sequences, pose walks and revision interleavings, in all three cost
modes (multigrid with warm starts, exact BFS, euclidean). Plus: crop
bucketing stays a bounded set of compiled shapes over a long mission,
`FrontierConfig.incremental=False` is the bit-exact pre-PR publish, and
the pose/grid snapshot tear in `publish_frontiers` stays fixed.
"""

import dataclasses
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.config import FrontierConfig, GridConfig
from jax_mapping.ops import frontier as F
from jax_mapping.ops.frontier_incremental import IncrementalFrontierPipeline


def _allowed_span(v):
    """Crop spans are 2^k or 3*2^(k-1) (the 1.5x midpoint buckets)."""
    if v & (v - 1) == 0:
        return True
    return v % 3 == 0 and (v // 3) & (v // 3 - 1) == 0


def _gcfg(size=512):
    return GridConfig(size_cells=size, patch_cells=64, max_range_m=2.0,
                      align_rows=8, align_cols=8)


def _fcfg(**kw):
    base = dict(downsample=2, max_clusters=8, min_cluster_cells=2,
                label_prop_iters=64, bfs_iters=256, crop_pad=8)
    base.update(kw)
    return FrontierConfig(**base)


TILE = 64


class WorldSim:
    """Seeded random mission: free-space carves, occasional walls,
    robot pose walks — every mutation marks its tiles' revisions the
    way the mapper's `_mark_dirty_patch` does (conservatively)."""

    def __init__(self, gcfg, seed, n_robots=3, walls=True):
        self.g = gcfg
        self.rng = np.random.default_rng(seed)
        n = gcfg.size_cells
        self.nt = n // TILE
        self.lo = np.zeros((n, n), np.float32)
        self.tile_rev = np.zeros((self.nt, self.nt), np.int64)
        self.rev = 0
        self.walls = walls
        # Seed room + robots inside it.
        self._carve(40, 40, 60, walls=False)
        res = gcfg.resolution_m
        ox, oy = gcfg.origin_m
        self.poses = np.stack([
            np.array([ox + self.rng.uniform(45, 95) * res,
                      oy + self.rng.uniform(45, 95) * res,
                      0.0], np.float32)
            for _ in range(n_robots)])

    def _mark(self, r, c, h, w):
        self.rev += 1
        t0r, t1r = r // TILE, min(self.nt - 1, (r + h) // TILE)
        t0c, t1c = c // TILE, min(self.nt - 1, (c + w) // TILE)
        self.tile_rev[t0r:t1r + 1, t0c:t1c + 1] = self.rev

    def _carve(self, r, c, size, walls):
        n = self.g.size_cells
        r, c = min(r, n - size - 1), min(c, n - size - 1)
        self.lo[r:r + size, c:c + size] = -2.0
        if walls and self.rng.random() < 0.6:
            wr = r + int(self.rng.integers(2, size - 4))
            self.lo[wr:wr + 2, c:c + int(0.7 * size)] = 2.0
        self._mark(r, c, size, size)

    def step(self, grow=True):
        """One mission step: maybe carve near the frontier, walk robots."""
        if grow and self.rng.random() < 0.8:
            free = np.argwhere(self.lo < 0)
            base = free[self.rng.integers(len(free))]
            jitter = self.rng.integers(-20, 30, 2)
            r = int(np.clip(base[0] + jitter[0], 2,
                            self.g.size_cells - 30))
            c = int(np.clip(base[1] + jitter[1], 2,
                            self.g.size_cells - 30))
            self._carve(r, c, int(self.rng.integers(12, 26)),
                        walls=self.walls)
        self.poses[:, :2] += self.rng.normal(
            0, 0.08, self.poses[:, :2].shape).astype(np.float32)


def _assert_parity(pub, full, mode, step):
    for name, a, b in (("sizes", pub.sizes, full.sizes),
                       ("targets", pub.targets, full.targets),
                       ("assignment", pub.assignment, full.assignment)):
        np.testing.assert_array_equal(
            a, np.asarray(b),
            err_msg=f"{name} diverged from full recompute "
                    f"(mode={mode}, step={step})")


@pytest.mark.parametrize("mode,seed", [
    # Two seeds on the product-default multigrid mode (where warm
    # starts and field reuse live); one each on the provably-converging
    # exact mode and the euclidean mode. The slow marker widens the
    # matrix without charging tier-1's wall-clock budget.
    ("mg", 0), ("mg", 1), ("exact", 0), ("euclid", 0),
    pytest.param("exact", 1, marks=pytest.mark.slow),
    pytest.param("euclid", 1, marks=pytest.mark.slow),
])
def test_incremental_matches_full_over_random_missions(mode, seed):
    """The headline property: assignment/targets/sizes identical to the
    full recompute at EVERY step of a random dirty-tile + pose-walk
    mission, including warm-started and skipped steps."""
    g = _gcfg(512)
    fcfg = _fcfg(obstacle_aware=(mode != "euclid"),
                 exact_bfs=(mode == "exact"))
    sim = WorldSim(g, seed=seed, walls=(mode != "mg"))
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)
    for step in range(10):
        if step:
            # Every third step holds the world still (skip/pose-only
            # interleavings); otherwise grow + walk.
            sim.step(grow=(step % 3 != 0))
        pub = pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
        full = F.compute_frontiers(fcfg, g, jnp.asarray(sim.lo),
                                   jnp.asarray(sim.poses))
        _assert_parity(pub, full, mode, step)
    assert pipe.n_recomputes >= 1
    # The mission must have exercised the tile cache (clean tiles kept).
    assert pipe.n_tiles_clean > 0
    if mode == "mg":
        # walls=False keeps every refresh occupancy-growth-free, so the
        # repeated-crop steps must ride the warm start.
        assert pipe.n_warm_starts > 0


def test_warm_start_invalidated_by_new_walls():
    """A wall appearing inside the crop must force a COLD solve (the
    upper-bound contract): min-plus relaxation never raises a value, so
    a warm init through a newly-blocked cell could tunnel forever."""
    g = _gcfg(512)
    fcfg = _fcfg()
    sim = WorldSim(g, seed=3, walls=False)
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)
    pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
    # Pose move with a stable crop: the carried fields ride (warm or
    # exact reuse). A GROWING crop would invalidate the carry — only
    # same-crop publishes may reuse fields.
    sim.poses[0, 0] += 0.2
    pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
    warm_before = pipe.n_warm_starts
    assert warm_before >= 1
    # Drop a wall across the middle of the seed room.
    sim.lo[60:64, 45:90] = 2.0
    sim._mark(60, 45, 4, 45)
    pub = pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
    assert pipe.n_warm_starts == warm_before   # cold solve, not warm
    full = F.compute_frontiers(fcfg, g, jnp.asarray(sim.lo),
                               jnp.asarray(sim.poses))
    _assert_parity(pub, full, "mg-wall", 2)


def test_field_carry_invalidated_by_frontier_consumption():
    """BFS passability keeps frontier-containing clustering blocks
    traversable even when they also pool occupancy — so CONSUMING a
    wall-adjacent frontier cell (unknown→free behind it, ZERO occupancy
    change) grows the blocked mask. The field carry must go cold: a
    reused/warm field would keep finite distances through the
    now-blocked block, and the monotone relaxation could never raise
    them."""
    g = _gcfg(512)
    fcfg = _fcfg(crop_pad=8)
    n = g.size_cells
    lo = np.zeros((n, n), np.float32)
    lo[100:200, 100:200] = -2.0              # room
    lo[100:200, 200:204] = 2.0               # east wall
    lo[148:152, 200:204] = -2.0              # notch through the wall
    lo[100:110, 230:240] = -2.0              # far-east patch: pins the
    #                                          observed bbox so step 2
    #                                          cannot change the crop
    res = g.resolution_m
    ox, oy = g.origin_m
    poses = np.array([[ox + 150 * res, oy + 150 * res, 0.0],
                      [ox + 120 * res, oy + 180 * res, 0.0]], np.float32)
    nt = n // TILE
    tile_rev = np.zeros((nt, nt), np.int64)
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)
    pipe.compute(lo, poses, tile_rev, 0)
    # Establish a live carry: pose-only move, same crop.
    poses[0, 0] += 0.2
    pipe.compute(lo, poses, tile_rev, 1)
    assert pipe.n_warm_starts == 1
    crop_before = pipe.last_crop
    # Consume the notch frontier: the unknown behind it becomes free.
    # occupancy is untouched, but the notch's clustering block (which
    # also pools wall cells) loses its frontier and flips to blocked.
    lo[140:160, 204:230] = -2.0
    tile_rev[140 // TILE:160 // TILE + 1,
             204 // TILE:230 // TILE + 1] = 2
    pub = pipe.compute(lo, poses, tile_rev, 2)
    assert pipe.last_crop == crop_before      # crop stable: the cold
    #                                           solve is forced by the
    #                                           blocked growth, nothing
    #                                           else
    assert pipe.n_warm_starts == 1            # carry went COLD
    full = F.compute_frontiers(fcfg, g, jnp.asarray(lo),
                               jnp.asarray(poses))
    _assert_parity(pub, full, "frontier-consumed", 2)
    # Cold multigrid == the full recompute's costs exactly.
    np.testing.assert_array_equal(pub.costs, np.asarray(full.costs))


def test_publish_skip_and_pose_threshold():
    """No revision advance + sub-threshold pose move = cached republish
    (same stamped revision, recomputed=False); crossing pose_skip_m
    recomputes."""
    g = _gcfg(512)
    fcfg = _fcfg(pose_skip_m=0.05)
    sim = WorldSim(g, seed=4, walls=False)
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)
    # Park robots on coarse-cell CENTRES: the skip demands an unchanged
    # BFS cell, so the sub-threshold jiggle must not straddle a border.
    res_c = g.resolution_m * fcfg.downsample
    ox, oy = g.origin_m
    sim.poses[:, 0] = (np.floor((sim.poses[:, 0] - ox) / res_c) + 0.5) \
        * res_c + ox
    sim.poses[:, 1] = (np.floor((sim.poses[:, 1] - oy) / res_c) + 0.5) \
        * res_c + oy
    p1 = pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
    assert p1.recomputed
    sim.poses[:, :2] += 0.01                  # sub-threshold, same cells
    p2 = pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev + 7)
    assert not p2.recomputed
    assert p2.revision == p1.revision          # computed-at stamp
    np.testing.assert_array_equal(p1.assignment, p2.assignment)
    sim.poses[0, 0] += 0.5                     # past the threshold
    p3 = pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
    assert p3.recomputed
    assert pipe.n_skips == 1


def test_extra_key_change_invalidates_all_tiles():
    """A voxel-overlay key change means the basis changed in ways tile
    revisions cannot see: every tile must re-coarsen."""
    g = _gcfg(256)
    fcfg = _fcfg()
    sim = WorldSim(g, seed=5, walls=False)
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)
    pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev, extra_key="a")
    misses = pipe.n_tiles_refreshed
    pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev, extra_key="a")
    assert pipe.n_tiles_refreshed == misses    # clean reuse (skip)
    sim.poses[0, 0] += 1.0                     # defeat the publish skip
    pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev, extra_key="b")
    assert pipe.n_tiles_refreshed == misses + sim.nt ** 2


def test_crop_bucketing_bounded_shapes_over_long_mission():
    """Compiled-shape churn is BOUNDED: a long growing mission may only
    ever compile power-of-two crop spans and power-of-two refresh
    buckets — log-many shapes, not one per bbox."""
    g = _gcfg(512)
    fcfg = _fcfg(obstacle_aware=False)         # cheap: shape churn test
    sim = WorldSim(g, seed=6, walls=False)
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)
    for step in range(30):
        sim.step()
        pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
    spans = {s[1] for s in pipe.compiled_shapes if s[0] == "crop"}
    buckets = {s[1] for s in pipe.compiled_shapes
               if s[0] == "refresh" and s[1] != "full"}
    n_coarse = g.size_cells // fcfg.downsample
    assert all(_allowed_span(v) for v in spans)
    assert all(v & (v - 1) == 0 for v in buckets)
    assert all(v <= n_coarse for v in spans)
    # ~2*log2 spans (x cold/warm variants) + log2 refresh buckets + the
    # full-refresh path: logarithmic, never one shape per bbox.
    assert len(pipe.compiled_shapes) <= 24
    # The mission actually grew: the crop moved off the minimum bucket.
    assert max(spans) > min(spans) or len(spans) == 1


def test_crop_origin_alignment_and_snapping():
    """Crop origins snap to the clustering x multigrid pooling period so
    cropped pooling blocks align with the full grid's (the parity
    precondition), and spans divide evenly."""
    g = _gcfg(512)
    fcfg = _fcfg()
    sim = WorldSim(g, seed=7, walls=False)
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)
    snap = fcfg.cluster_downsample * (1 << (fcfg.mg_levels - 1))
    for step in range(6):
        sim.step()
        pub = pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
        r0, c0, span = pub.crop_rc
        assert r0 % snap == 0 and c0 % snap == 0
        assert span % snap == 0 and _allowed_span(span)


def test_pipeline_rejects_bad_geometry():
    g = _gcfg(512)
    with pytest.raises(ValueError):
        IncrementalFrontierPipeline(_fcfg(), g, 60)       # tile ∤ grid
    with pytest.raises(ValueError):
        IncrementalFrontierPipeline(_fcfg(cluster_downsample=3), g, TILE)


def test_coarse_mask_cache_matches_full_coarsen():
    """The persistent tile-cached masks equal a from-scratch coarsen of
    the live grid after any dirty pattern — the stage-A exactness the
    downstream parity rests on."""
    g = _gcfg(256)
    fcfg = _fcfg()
    sim = WorldSim(g, seed=8)
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)
    for step in range(6):
        sim.step()
        sim.poses[0, 0] += 0.2                 # defeat skip
        pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
        free, occ, unknown = map(np.asarray, pipe.coarse_masks())
        f2, o2, u2 = map(np.asarray, F.coarsen(fcfg, g,
                                               jnp.asarray(sim.lo)))
        np.testing.assert_array_equal(free, f2)
        np.testing.assert_array_equal(occ, o2)
        np.testing.assert_array_equal(unknown, u2)


# ---------------------------------------------------------------- bridge

def _mk_mapper(tiny_cfg, incremental=True, n_robots=2):
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode
    cfg = dataclasses.replace(
        tiny_cfg, frontier=dataclasses.replace(
            tiny_cfg.frontier, incremental=incremental))
    bus = Bus()
    return MapperNode(cfg, bus, n_robots=n_robots), bus, cfg


def _seed_map(mapper, cfg):
    n = cfg.grid.size_cells
    lo = np.zeros((n, n), np.float32)
    lo[60:180, 60:180] = -2.0
    lo[110:114, 60:150] = 2.0
    mapper.seed_map_prior(lo)
    return lo


def _last_frontiers(bus):
    out = []
    bus.subscribe("/frontiers", callback=out.append)
    return out


def test_mapper_incremental_false_is_pre_pr_publish(tiny_cfg):
    """incremental=False: the publish path never builds a pipeline and
    the published triple is EXACTLY one full-grid compute_frontiers of
    the snapshot (the pre-PR behavior, bit-for-bit)."""
    mapper, bus, cfg = _mk_mapper(tiny_cfg, incremental=False)
    lo = _seed_map(mapper, cfg)
    got = _last_frontiers(bus)
    mapper.publish_frontiers()
    assert mapper._frontier_pipeline is None
    poses = np.stack([np.asarray(st.pose) for st in mapper.states])
    fr = F.compute_frontiers(cfg.frontier, cfg.grid, jnp.asarray(lo),
                             jnp.asarray(poses))
    msg = got[-1]
    np.testing.assert_array_equal(msg.targets_xy, np.asarray(fr.targets))
    np.testing.assert_array_equal(msg.sizes, np.asarray(fr.sizes))
    np.testing.assert_array_equal(msg.assignment,
                                  np.asarray(fr.assignment))


def test_mapper_incremental_publish_matches_full_and_stamps_revision(
        tiny_cfg):
    """The incremental publish equals the full recompute of the same
    snapshot and stamps the map_revision it was computed at; a skipped
    republish re-ships the original stamp even after the revision
    advances out-of-band."""
    mapper, bus, cfg = _mk_mapper(tiny_cfg, incremental=True)
    lo = _seed_map(mapper, cfg)
    got = _last_frontiers(bus)
    mapper.publish_frontiers()
    assert mapper._frontier_pipeline is not None
    rev0 = mapper.map_revision
    poses = np.stack([np.asarray(st.pose) for st in mapper.states])
    fr = F.compute_frontiers(cfg.frontier, cfg.grid, jnp.asarray(lo),
                             jnp.asarray(poses))
    msg = got[-1]
    np.testing.assert_array_equal(msg.targets_xy, np.asarray(fr.targets))
    np.testing.assert_array_equal(msg.sizes, np.asarray(fr.sizes))
    np.testing.assert_array_equal(msg.assignment,
                                  np.asarray(fr.assignment))
    assert msg.map_revision == rev0
    # Skip path: bump the revision WITHOUT touching tiles (no dirty
    # marks) — the republish still carries the computed-at stamp.
    mapper.map_revision += 5
    mapper.publish_frontiers()
    assert got[-1].map_revision == rev0
    assert mapper._frontier_pipeline.n_skips == 1


def test_publish_snapshot_tear_fixed(tiny_cfg):
    """ISSUE 6 satellite: poses and grid must come from ONE lock
    section. The historical code re-read the grid via merged_grid()
    AFTER releasing the pose lock, so a concurrent install could pair a
    new map with old poses — publish_frontiers must not call
    merged_grid() at all, and a revision bump landing mid-publish must
    not leak into the stamped revision."""
    mapper, bus, cfg = _mk_mapper(tiny_cfg, incremental=True)
    _seed_map(mapper, cfg)
    got = _last_frontiers(bus)
    called = []
    orig = mapper.merged_grid
    mapper.merged_grid = lambda: (called.append(1), orig())[1]
    rev0 = mapper.map_revision
    pipe = mapper._frontier_incremental()
    orig_compute = pipe.compute

    def racing_compute(*a, **kw):
        # A concurrent install lands mid-publish: the already-taken
        # snapshot must win.
        mapper.map_revision += 1
        return orig_compute(*a, **kw)

    pipe.compute = racing_compute
    try:
        mapper.publish_frontiers()
    finally:
        pipe.compute = orig_compute
        mapper.merged_grid = orig
    assert not called, "publish_frontiers re-read the grid outside " \
                       "its consistent snapshot section"
    assert got[-1].map_revision == rev0


def test_publish_concurrent_prior_seed_hammer(tiny_cfg):
    """Publishes racing seed_map_prior installs never crash and never
    publish a revision newer than the grid they computed on (smoke for
    the one-lock snapshot)."""
    mapper, bus, cfg = _mk_mapper(tiny_cfg, incremental=True)
    lo = _seed_map(mapper, cfg)
    got = _last_frontiers(bus)
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            mapper.seed_map_prior(lo)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(10):
            mapper.publish_frontiers()
    finally:
        stop.set()
        t.join()
    assert len(got) == 10
    assert all(m.map_revision <= mapper.map_revision for m in got)


def test_planner_overlay_cache_keyed_on_revisions(tiny_cfg):
    """Satellite: the planning basis is keyed on (map_revision, voxel
    fusion key) — repeated calls at unchanged keys reuse the cached
    overlay; either key advancing rebuilds."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.bridge.planner import PlannerNode

    class FakeVoxel:
        def __init__(self, cfg):
            from jax_mapping.ops.voxel import empty_voxel_grid
            self._g = empty_voxel_grid(cfg.voxel)
            self.rev = 0

        def voxel_grid(self):
            return self._g

        def serving_revision(self):
            return self.rev

        def fuse(self):
            # A real fusion: new (immutable) array + revision bump.
            self._g = self._g + 0.0
            self.rev += 1

    cfg = tiny_cfg
    bus = Bus()
    mapper = MapperNode(cfg, bus, n_robots=1)
    voxel = FakeVoxel(cfg)
    planner = PlannerNode(cfg, bus, mapper, voxel_mapper=voxel)
    if planner.voxel_mapper is None:
        pytest.skip("voxel/grid resolution mismatch in tiny config")
    g1 = planner._planning_grid()
    g2 = planner._planning_grid()
    assert g2 is g1
    assert planner.n_overlay_rebuilds == 1
    assert planner.n_overlay_reuses >= 1
    # A voxel fusion (new array + key) -> rebuild.
    voxel.fuse()
    planner._planning_grid()
    assert planner.n_overlay_rebuilds == 2
    # Map revision advances (content mutation) -> rebuild.
    _seed_map(mapper, cfg)
    planner._planning_grid()
    assert planner.n_overlay_rebuilds == 3
    # The mapper-passed-snapshot form shares the same cache.
    lo = mapper.merged_grid()
    out = planner._planning_grid(lo, mapper.serving_revision())
    assert out is planner._lo_cache[3]
    assert planner.overlay_key() == voxel.rev


def test_tile_observed_mask_stays_writable_after_full_refresh():
    """Lint C3 regression (the PR 6 gotcha this checker encodes): the
    dense-refresh path installs the device observed-flags as the host
    mask the SPARSE path later writes into — it must be an np.array
    copy, not a read-only np.asarray view, or the first sparse refresh
    after a dense one raises `assignment destination is read-only`."""
    import numpy as np
    from jax_mapping.ops.frontier_incremental import \
        IncrementalFrontierPipeline

    # The module-default 512 grid: its compiled shapes are shared with
    # the parity tests above, so this regression adds no fresh compiles.
    gcfg = _gcfg()
    fcfg = _fcfg()
    pipe = IncrementalFrontierPipeline(fcfg, gcfg, TILE)
    sim = WorldSim(gcfg, seed=5, n_robots=2)
    # First publish: every tile dirty -> the DENSE full-refresh path.
    pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
    assert pipe.n_full_refreshes >= 1
    assert pipe._tile_observed.flags.writeable
    # A small dirty step now takes the SPARSE path, which writes the
    # mask in place — the line that crashed before the copy fix.
    sim.step(grow=True)
    pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
    assert pipe._tile_observed.flags.writeable


# ------------------------------------------------ decay-aware (ISSUE 14)

def test_decay_aware_incremental_matches_full_over_decaying_mission():
    """ROADMAP item 7c follow-through: the incremental pipeline carries
    the HEALED/STALE mask tile-incrementally, so `decay_aware`
    publishes match the full recompute (which derives the stale mask
    from raw log-odds each publish) at every step — including across a
    decay-style pass (all evidence shrunk toward unknown, every tile
    revision bumped, residual sub-threshold log-odds left behind)."""
    g = _gcfg(512)
    fcfg = _fcfg(decay_aware=True, stale_bonus=0.3)
    sim = WorldSim(g, seed=2, walls=True)
    pipe = IncrementalFrontierPipeline(fcfg, g, TILE)

    def check(step):
        pub = pipe.compute(sim.lo, sim.poses, sim.tile_rev, sim.rev)
        full = F.compute_frontiers(fcfg, g, jnp.asarray(sim.lo),
                                   jnp.asarray(sim.poses))
        _assert_parity(pub, full, "decay", step)
        stale_full = F.stale_mask(fcfg, g, jnp.asarray(sim.lo))
        np.testing.assert_array_equal(
            np.asarray(pipe.stale()), np.asarray(stale_full),
            err_msg=f"carried stale mask diverged (step {step})")

    for step in range(4):
        if step:
            sim.step(grow=True)
        check(step)
    # The stale mask must have actually been EMPTY so far (no decay
    # ran): fresh unknown space never flags.
    assert not np.asarray(pipe.stale()).any()
    # A decay pass: multiplicative shrink leaves previously-saturated
    # cells sub-threshold but nonzero — HEALED regions — and rides an
    # ordinary every-tile revision bump, exactly like
    # mapper._apply_decay.
    sim.lo *= 0.2
    sim._mark(0, 0, g.size_cells, g.size_cells)
    check("post-decay")
    assert np.asarray(pipe.stale()).any(), (
        "decay left residual evidence but nothing flagged stale")
    # Incremental dirty steps after the decay keep the carry exact.
    for step in range(2):
        sim.step(grow=True)
        check(f"post-decay+{step}")


def test_decay_aware_publishes_ride_incremental_pipeline(tiny_cfg):
    """The mapper no longer routes decay-aware publishes around the
    incremental pipeline (the pre-7c behavior this satellite
    retires): with `decay_aware=True` the pipeline is constructed and
    the publish path uses it."""
    mapper, bus, cfg = _mk_mapper(dataclasses.replace(
        tiny_cfg, frontier=dataclasses.replace(
            tiny_cfg.frontier, decay_aware=True)), n_robots=1)
    assert mapper._frontier_incremental() is not None, (
        "decay_aware publish fell back to the full recompute path")
    _seed_map(mapper, cfg)
    mapper.publish_frontiers()
    pipe = mapper._frontier_pipeline
    assert pipe is not None and pipe.n_recomputes >= 1
    assert pipe.stale() is not None
