"""Keyframe thinning: unbounded trajectories in the fixed-capacity ring
(round-3 verdict weak #5 — repair froze forever once a ring saturated) and
the masked-repair regression (unmasked ring re-fusion phantom-carved free
space from never-written zero slots, erasing walls near the origin).
"""

import dataclasses
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.models import slam as S
from jax_mapping.ops import grid as G
from jax_mapping.ops import posegraph as PG
from jax_mapping.ops.odometry import pose_between


def _line_graph(cfg_loop, n, step=0.2):
    """n poses along +x, odometry chain edges, returns (graph, ring)."""
    g = PG.empty_graph(cfg_loop)
    for i in range(n):
        pose = jnp.asarray([i * step, 0.0, 0.0], jnp.float32)
        g = PG.add_pose(g, pose)
        if i:
            g = PG.odometry_edge(g, i - 1, i)
    ring = jnp.arange(cfg_loop.max_poses, dtype=jnp.float32)[:, None] \
        * jnp.ones(8)[None, :]           # row i filled with i: traceable
    return g, ring


def test_thin_structure(tiny_cfg):
    cap = 16
    lc = dataclasses.replace(tiny_cfg.loop, max_poses=cap, max_edges=64)
    g, ring = _line_graph(lc, cap)
    # Two long-range edges: both-even endpoints (2, 10) and both-odd
    # (3, 11), with their true relative poses as measurements.
    for (i, j) in ((2, 10), (3, 11)):
        meas = pose_between(g.poses[i], g.poses[j])
        g = PG.add_edge(g, i, j, meas, jnp.asarray([200.0, 200.0, 400.0]))

    g2, ring2 = PG.thin_keyframes(g, ring)

    assert int(g2.n_poses) == cap // 2
    np.testing.assert_allclose(np.asarray(g2.poses[: cap // 2]),
                               np.asarray(g.poses[::2]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(ring2[: cap // 2]),
                               np.asarray(ring[::2]), atol=1e-6)
    valid = np.asarray(g2.pose_valid)
    assert valid[: cap // 2].all() and not valid[cap // 2:].any()

    # Edges: chain 0..n2-2, then the two surviving loop edges.
    n2 = cap // 2
    assert int(g2.n_edges) == (n2 - 1) + 2
    ij = np.asarray(g2.edge_ij)
    meas = np.asarray(g2.edge_meas)
    for e in range(n2 - 1):
        assert tuple(ij[e]) == (e, e + 1)
        np.testing.assert_allclose(meas[e], [0.4, 0.0, 0.0], atol=1e-5)
    # (2,10) -> (1,5) exactly; (3,11) -> (1,5) adjusted by the odometry
    # hops (all poses collinear, so the adjusted measurement is the true
    # relative pose of the remapped endpoints).
    assert tuple(ij[n2 - 1]) == (1, 5)
    np.testing.assert_allclose(meas[n2 - 1], [1.6, 0.0, 0.0], atol=1e-5)
    assert tuple(ij[n2]) == (1, 5)
    np.testing.assert_allclose(meas[n2], [1.6, 0.0, 0.0], atol=1e-4)
    assert not np.asarray(g2.edge_valid)[int(g2.n_edges):].any()


def test_thin_drops_degenerate_remaps(tiny_cfg):
    """A loop edge whose endpoints collapse to the same kept index (e.g.
    (4, 5) if it were long-range) must be dropped, not become a
    self-edge."""
    cap = 16
    lc = dataclasses.replace(tiny_cfg.loop, max_poses=cap, max_edges=64)
    g, ring = _line_graph(lc, cap)
    meas = pose_between(g.poses[4], g.poses[6])
    g = PG.add_edge(g, 4, 6, meas, jnp.asarray([200.0, 200.0, 400.0]))
    # (4, 6) -> (2, 3): survives. Also add (8, 9)-distance-2? (8, 10) ->
    # (4, 5) survives. A (5, 6)-style j-i==1 edge is chain, rebuilt anyway.
    g2, _ = PG.thin_keyframes(g, ring)
    ij = np.asarray(g2.edge_ij)[np.asarray(g2.edge_valid)]
    assert (ij[:, 1] > ij[:, 0]).all(), "self- or backward edge leaked"


def test_thin_preserves_strong_anchor_edges(tiny_cfg):
    """Gap-1 edges at LOOP weights (the fleet's cross-robot anchors) must
    survive thinning as strong edges where their endpoints stay distinct,
    not be downgraded to re-measured odometry."""
    cap = 16
    lc = dataclasses.replace(tiny_cfg.loop, max_poses=cap, max_edges=64)
    g, ring = _line_graph(lc, cap)
    w_loop = jnp.asarray([200.0, 200.0, 400.0])
    # Anchor at (5, 6): odd->even, remaps to (2, 3) — must survive strong.
    g = PG.add_edge(g, 5, 6, pose_between(g.poses[5], g.poses[6]), w_loop)
    # Anchor at (8, 9): even->odd, collapses to (4, 4) — must drop.
    g = PG.add_edge(g, 8, 9, pose_between(g.poses[8], g.poses[9]), w_loop)

    g2, _ = PG.thin_keyframes(g, ring)
    ij = np.asarray(g2.edge_ij)[np.asarray(g2.edge_valid)]
    w = np.asarray(g2.edge_weight)[np.asarray(g2.edge_valid)]
    strong = w[:, 2] > 100.0
    assert strong.sum() == 1, "exactly one anchor should survive"
    si = int(np.nonzero(strong)[0][0])
    assert tuple(ij[si]) == (2, 3)
    # Adjusted to the kept endpoints: new (2, 3) are old poses (4, 6),
    # 0.4 m apart on the line.
    np.testing.assert_allclose(
        np.asarray(g2.edge_meas)[np.asarray(g2.edge_valid)][si],
        [0.4, 0.0, 0.0], atol=1e-5)


def test_thin_then_optimize_stays_consistent(tiny_cfg):
    """Thinning a consistent graph must leave optimisation a no-op:
    near-zero residuals before and after."""
    cap = 32
    lc = dataclasses.replace(tiny_cfg.loop, max_poses=cap, max_edges=128,
                             gn_iters=4)
    # Poses around a circle; chain + one closing edge, all measurements
    # exact.
    g = PG.empty_graph(lc)
    R_c = 2.0
    for i in range(cap):
        th = 2 * math.pi * i / cap
        g = PG.add_pose(g, jnp.asarray(
            [R_c * math.cos(th), R_c * math.sin(th), th + math.pi / 2]))
        if i:
            g = PG.odometry_edge(g, i - 1, i)
    meas = pose_between(g.poses[0], g.poses[cap - 1])
    g = PG.add_edge(g, 0, cap - 1, meas, jnp.asarray([200.0, 200.0, 400.0]))
    assert float(PG.graph_error(g)) < 1e-6

    ring = jnp.zeros((cap, 8), jnp.float32)
    g2, _ = PG.thin_keyframes(g, ring)
    assert float(PG.graph_error(g2)) < 1e-4
    g3 = PG.optimize(lc, g2)
    a, b = (np.asarray(g3.poses[: cap // 2]),
            np.asarray(g2.poses[: cap // 2]))
    np.testing.assert_allclose(a[:, :2], b[:, :2], atol=1e-2)
    # optimize wraps angles to (-pi, pi]; compare modulo 2*pi.
    dth = np.abs(np.arctan2(np.sin(a[:, 2] - b[:, 2]),
                            np.cos(a[:, 2] - b[:, 2])))
    assert dth.max() < 1e-2


def test_slam_step_extends_past_capacity(tiny_cfg):
    """slam_step keeps accepting key scans beyond max_poses: the ring
    thins instead of freezing (graph stays under capacity, total key
    count keeps counting)."""
    cap = 12
    cfg = dataclasses.replace(
        tiny_cfg,
        loop=dataclasses.replace(tiny_cfg.loop, max_poses=cap,
                                 max_edges=64, enabled=False))
    state = S.init_state(cfg)
    ranges = jnp.zeros(cfg.scan.padded_beams)      # featureless: odometry
    wl = wr = jnp.float32(4000.0)                  # 0.12 m/step > gate
    for _ in range(3 * cap):
        state, diag = S.slam_step(cfg, state, ranges, wl, wr,
                                  jnp.float32(0.1))
    assert int(state.n_keyscans) == 3 * cap
    assert int(state.graph.n_poses) <= cap
    # The surviving keyframes still form a valid, growing chain.
    assert bool(state.graph.pose_valid[: int(state.graph.n_poses)].all())
    # Thinned trajectory still spans the whole drive: the newest pose is
    # ~3*cap*0.12 m out.
    x = float(state.graph.poses[int(state.graph.n_poses) - 1, 0])
    assert x > 0.8 * (3 * cap * 0.12)


@pytest.mark.slow
def test_loop_closure_past_saturation(tiny_cfg):
    """The round-3 verdict's acceptance test: drive MORE key scans than
    max_poses, then close the loop — the map must still de-ghost (repair
    no longer stops at saturation), and the repaired map must keep its
    walls (the masked-repair regression: unmasked zero slots used to
    carve the origin region free and erase every occupied cell)."""
    from tests.test_loop_closure import _drive_loop, loop_cfg
    base = loop_cfg(tiny_cfg)
    # Small enough to saturate mid-drive (the drive produces ~70+ key
    # scans at the 0.3 m gate), big enough to keep loop verification
    # chains meaningful.
    cfg = dataclasses.replace(
        base, loop=dataclasses.replace(base.loop, max_poses=48,
                                       max_edges=256))
    state, hist = _drive_loop(cfg, bias_units=1.0)

    assert int(state.n_keyscans) > cfg.loop.max_poses, \
        "staging failed: drive never saturated the ring"
    loops = np.array([n for _, _, n in hist])
    assert loops[-1] >= 1, "no loop closed after saturation"
    errs = np.array([np.linalg.norm(t[:2] - e[:2]) for t, e, _ in hist])
    assert errs[-1] < 0.3, f"final error {errs[-1]:.2f} m not repaired"

    # Map quality after the post-saturation repair: the start-corner
    # walls must be occupied (masked repair), and known-free space must
    # exist (the map is a real map, not all-unknown).
    occ = np.asarray(G.to_occupancy(cfg.grid, state.grid))
    assert (occ == 100).sum() > 30, "repair erased the walls"
    assert (occ == 0).sum() > 1000, "no free space in the repaired map"
