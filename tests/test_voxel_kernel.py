"""Voxel Pallas kernel (ops/voxel_kernel.py) vs the XLA classify path and
the NumPy loop oracle.

On CPU the kernel runs in interpret mode (same code path the TPU
compiles); semantics must match `ops/voxel.classify_patch` — the two were
measured BIT-identical at build time, but the assertions carry the same
tiny boundary budget as the other kernel suites so a benign float-fusion
change in a jax upgrade doesn't read as a semantics break. On-chip
lowering + parity runs behind JAX_MAPPING_TPU_TESTS (the
test_sensor_kernel.py pattern).
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import tiny_config
from jax_mapping.ops import voxel as V
from jax_mapping.ops import voxel_kernel as VK
from tests.test_voxel import _oracle_classify


@pytest.fixture(scope="module")
def vox():
    return tiny_config().voxel


@pytest.fixture(scope="module")
def cam():
    return tiny_config().depthcam


def _batch(rng, cam, B, spread=0.3):
    depths = rng.uniform(0.0, 1.5, (B, cam.height_px, cam.width_px)) \
        .astype(np.float32)
    depths[rng.random(depths.shape) < 0.1] = 0.0     # no-return speckle
    poses = np.stack([rng.uniform(-spread, spread, B),
                      rng.uniform(-spread, spread, B),
                      rng.uniform(-3.0, 3.0, B)], 1).astype(np.float32)
    return depths, poses


def _origins(vox, cam, poses):
    def one(p):
        pos, _ = V.camera_pose(p[0], p[1], p[2], cam)
        return V.patch_origin(vox, pos[:2])
    return jax.vmap(one)(jnp.asarray(poses))


def test_image_deltas_match_classify_patch(vox, cam, rng):
    depths, poses = _batch(rng, cam, B=3)
    origins = _origins(vox, cam, poses)
    got = np.asarray(VK.image_deltas(vox, cam, jnp.asarray(depths),
                                     jnp.asarray(poses), origins))
    for i in range(len(poses)):
        pos, R = V.camera_pose(poses[i, 0], poses[i, 1], poses[i, 2], cam)
        want = np.asarray(V.classify_patch(vox, cam, jnp.asarray(depths[i]),
                                           pos, R, origins[i]))
        mismatch = np.mean(got[i] != want)
        assert mismatch < 0.002, \
            f"image {i}: {mismatch:.4%} voxels disagree with XLA classify"


def test_image_deltas_match_numpy_oracle(vox, cam, rng):
    depths, poses = _batch(rng, cam, B=2)
    origins = np.asarray(_origins(vox, cam, poses))
    got = np.asarray(VK.image_deltas(vox, cam, jnp.asarray(depths),
                                     jnp.asarray(poses),
                                     jnp.asarray(origins)))
    P = vox.patch_cells
    for i in range(len(poses)):
        pos, R = V.camera_pose(poses[i, 0], poses[i, 1], poses[i, 2], cam)
        want = _oracle_classify(vox, cam, depths[i], np.asarray(pos),
                                np.asarray(R), origins[i][0], origins[i][1],
                                P, P)
        mismatch = np.mean(got[i] != want)
        assert mismatch < 0.005, \
            f"image {i}: {mismatch:.4%} voxels disagree with oracle"


def test_window_delta_matches_image_sum(vox, cam, rng):
    depths, poses = _batch(rng, cam, B=3, spread=0.1)
    origin = V.patch_origin(vox, jnp.asarray(poses[:, :2].mean(0)))
    assert bool(VK.window_fits(vox, jnp.asarray(poses), origin))
    got = np.asarray(VK.window_delta(vox, cam, jnp.asarray(depths),
                                     jnp.asarray(poses), origin))
    origins = jnp.broadcast_to(origin.reshape(1, 2), (len(poses), 2))
    per = np.asarray(VK.image_deltas(vox, cam, jnp.asarray(depths),
                                     jnp.asarray(poses), origins))
    np.testing.assert_allclose(got, per.sum(0), atol=1e-5)


def test_window_fits_rejects_far_pose(vox):
    # Patch of 64 cells at origin (32, 32) spans cells 32..96; world
    # (0, 0) is cell 64 — dead centre, max-range margin (24 cells) fits.
    origin = jnp.asarray([32, 32], jnp.int32)
    inside = jnp.asarray([[0.0, 0.0, 0.0]], jnp.float32)
    assert bool(VK.window_fits(vox, inside, origin))
    # A pose whose max-range disc crosses the patch edge must fail.
    edge = jnp.asarray([[1.55, 0.0, 0.0]], jnp.float32)
    assert not bool(VK.window_fits(vox, edge, origin))
    # One bad pose poisons the whole window (it's an all() contract).
    both = jnp.asarray([[0.0, 0.0, 0.0], [1.55, 0.0, 0.0]], jnp.float32)
    assert not bool(VK.window_fits(vox, both, origin))


def test_fuse_depths_kernel_vs_xla(vox, cam, rng):
    """The full fuse (chunked classify -> fold -> clamp) through the
    kernel engine must match the XLA engine; B=10 > _FUSE_CHUNK covers
    the chunk + remainder paths of both."""
    depths, poses = _batch(rng, cam, B=10)
    grid0 = V.empty_voxel_grid(vox)
    a = np.asarray(VK.fuse_depths(vox, cam, grid0, jnp.asarray(depths),
                                  jnp.asarray(poses)))
    b = np.asarray(V.fuse_depths_xla(vox, cam, grid0, jnp.asarray(depths),
                                     jnp.asarray(poses)))
    np.testing.assert_allclose(a, b, atol=1e-5)
    assert np.abs(a).sum() > 0


def test_batch_split_parity(vox, cam, rng, monkeypatch):
    """B above _MAX_B_PER_CALL splits across pallas calls; per-image
    outputs must concatenate bitwise-identically."""
    depths, poses = _batch(rng, cam, B=5)
    origins = _origins(vox, cam, poses)
    whole = np.asarray(VK.image_deltas(vox, cam, jnp.asarray(depths),
                                       jnp.asarray(poses), origins))
    monkeypatch.setattr(VK, "_MAX_B_PER_CALL", 2)
    VK.image_deltas.clear_cache()
    split = np.asarray(VK.image_deltas(vox, cam, jnp.asarray(depths),
                                       jnp.asarray(poses), origins))
    VK.image_deltas.clear_cache()
    np.testing.assert_array_equal(whole, split)


def test_zero_depth_carves_nothing(vox, cam):
    depths = np.zeros((2, cam.height_px, cam.width_px), np.float32)
    poses = np.zeros((2, 3), np.float32)
    origins = _origins(vox, cam, poses)
    out = np.asarray(VK.image_deltas(vox, cam, jnp.asarray(depths),
                                     jnp.asarray(poses), origins))
    assert (out == 0).all()


def test_unsupported_config_raises(vox, cam):
    import dataclasses
    pitched = dataclasses.replace(cam, mount_pitch_rad=0.2)
    assert not VK.kernel_supported(vox, pitched)
    with pytest.raises(ValueError, match="pitch"):
        VK.image_deltas(vox, pitched,
                        jnp.zeros((1, cam.height_px, cam.width_px)),
                        jnp.zeros((1, 3)), jnp.zeros((1, 2), jnp.int32))
    # The dispatcher must keep pitched configs on the XLA path everywhere.
    assert not V._use_pallas(vox, pitched)


def test_dispatch_off_tpu_stays_xla(vox, cam):
    """On the CPU test backend the public fuse_depths must use the XLA
    engine (interpret-mode pallas in the bridge's hot loop would be a
    silent 100x regression)."""
    assert jax.default_backend() != "tpu"
    assert not V._use_pallas(vox, cam)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="needs the physical TPU")
def test_image_deltas_lower_on_tpu(rng):
    """Production-shape lowering + on-chip parity with the XLA path.

    Full-size config on purpose: P=384, Z=64, 160x120 images are the
    shapes that must pass Mosaic (the tiny interpret tests can't catch a
    VMEM or tiling rejection)."""
    from jax_mapping.config import SlamConfig
    cfg = SlamConfig()
    vox, cam = cfg.voxel, cfg.depthcam
    B = 4
    depths = rng.uniform(0.0, 5.0, (B, cam.height_px, cam.width_px)) \
        .astype(np.float32)
    depths[rng.random(depths.shape) < 0.1] = 0.0
    poses = np.tile(np.array([1.0, -2.0, 0.7], np.float32), (B, 1))
    origins = _origins(vox, cam, poses)
    out = VK.image_deltas(vox, cam, jnp.asarray(depths),
                          jnp.asarray(poses), origins)
    out.block_until_ready()      # raises if Mosaic rejects the kernel
    got = np.asarray(out)
    assert np.isfinite(got).all()
    for i in range(B):
        pos, R = V.camera_pose(poses[i, 0], poses[i, 1], poses[i, 2], cam)
        want = np.asarray(V.classify_patch(vox, cam, jnp.asarray(depths[i]),
                                           pos, R, origins[i]))
        mismatch = np.mean(got[i] != want)
        assert mismatch < 0.002, f"on-chip mismatch {mismatch:.4%}"


def test_region_delta_matches_classify_region_slabs(vox, cam, rng):
    """The sharded Y-slab entry: region_delta over each of two slabs must
    equal the batch-summed XLA classify_region on that slab — the exact
    computation parallel/voxel_sharded.py dispatches per device — and the
    stacked slabs must equal the full-grid region (nothing dropped or
    doubled at the slab seam)."""
    depths, poses = _batch(rng, cam, B=3)
    ny = vox.size_y_cells // 2
    nx = vox.size_x_cells
    assert VK.region_supported(vox, cam, ny, nx)
    slabs = []
    for slab in range(2):
        y0 = slab * ny
        got = np.asarray(VK.region_delta(vox, cam, jnp.asarray(depths),
                                         jnp.asarray(poses),
                                         jnp.int32(y0), ny, nx))
        want = np.zeros_like(got)
        for i in range(len(poses)):
            pos, R = V.camera_pose(poses[i, 0], poses[i, 1], poses[i, 2],
                                   cam)
            want += np.asarray(V.classify_region(
                vox, cam, jnp.asarray(depths[i]), pos, R,
                jnp.int32(y0), jnp.int32(0), ny, nx))
        np.testing.assert_allclose(got, want, atol=1e-5)
        assert np.abs(got).sum() > 0, f"slab {slab} carried no evidence"
        slabs.append(got)
    full = np.asarray(VK.region_delta(vox, cam, jnp.asarray(depths),
                                      jnp.asarray(poses), jnp.int32(0),
                                      vox.size_y_cells, nx))
    np.testing.assert_array_equal(np.concatenate(slabs, axis=1), full)


def test_region_delta_multi_row_tiles(vox, cam, rng):
    """nx < 128 makes each 128-column kernel tile span MULTIPLE patch
    rows (nx=64 -> 2 rows/tile), exercising the generalized row-band
    cull (row_lo != row_hi) no square patch shape reaches."""
    depths, _ = _batch(rng, cam, B=2)
    ny, nx = 16, 64
    # Fixed poses AIMED INTO the region (rows 40..56, cols 0..64 =
    # world y in [-1.2, -0.4], x in [-3.2, 0]): the shared session rng's
    # state depends on test order, and random poses can legitimately see
    # nothing here — the evidence assertion below must not be a lottery.
    poses = np.array([[-1.6, -0.8, math.pi], [-1.2, -0.9, 3.0]],
                     np.float32)
    assert VK.region_supported(vox, cam, ny, nx)
    got = np.asarray(VK.region_delta(vox, cam, jnp.asarray(depths),
                                     jnp.asarray(poses),
                                     jnp.int32(40), ny, nx))
    want = np.zeros_like(got)
    for i in range(len(poses)):
        pos, R = V.camera_pose(poses[i, 0], poses[i, 1], poses[i, 2], cam)
        want += np.asarray(V.classify_region(
            vox, cam, jnp.asarray(depths[i]), pos, R,
            jnp.int32(40), jnp.int32(0), ny, nx))
    np.testing.assert_allclose(got, want, atol=1e-5)
    assert np.abs(got).sum() > 0


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="needs the physical TPU")
def test_region_delta_lowers_on_tpu(rng):
    """Production Y-slab shape (the 8-device slab: 128 rows x 1024 cols
    x 64 z) must pass Mosaic — the shape parallel/voxel_sharded.py
    dispatches per device."""
    from jax_mapping.config import SlamConfig
    cfg = SlamConfig()
    vox, cam = cfg.voxel, cfg.depthcam
    ny, nx = vox.size_y_cells // 8, vox.size_x_cells
    B = 4
    depths = rng.uniform(0.0, 5.0, (B, cam.height_px, cam.width_px)) \
        .astype(np.float32)
    poses = np.tile(np.array([0.5, -1.0, 0.3], np.float32), (B, 1))
    out = VK.region_delta(vox, cam, jnp.asarray(depths),
                          jnp.asarray(poses), jnp.int32(3 * ny), ny, nx)
    out.block_until_ready()
    got = np.asarray(out)
    assert np.isfinite(got).all()
    want = np.zeros_like(got)
    for i in range(B):
        pos, R = V.camera_pose(poses[i, 0], poses[i, 1], poses[i, 2], cam)
        want += np.asarray(V.classify_region(
            vox, cam, jnp.asarray(depths[i]), pos, R,
            jnp.int32(3 * ny), jnp.int32(0), ny, nx))
    np.testing.assert_allclose(got, want, atol=1e-4)
