"""Structural validation of the real-DDS proof kit (docker/dds_proof).

The build image has no Docker daemon and no network, so the kit cannot
EXECUTE here — operators run `docker/dds_proof/run.sh` on a machine with
Docker (it checks the transcript in). What CAN be pinned here: the kit
exists, is executable, parses, and asserts exactly the topic surface the
rclpy adapter actually advertises — so adapter drift breaks this test,
not the operator's proof run.
"""

import os
import re
import stat

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KIT = os.path.join(ROOT, "docker", "dds_proof")


def test_kit_files_present_and_executable():
    for name in ("docker-compose.yml", "probe.sh", "run.sh"):
        p = os.path.join(KIT, name)
        assert os.path.exists(p), f"missing {name}"
    for name in ("probe.sh", "run.sh"):
        mode = os.stat(os.path.join(KIT, name)).st_mode
        assert mode & stat.S_IXUSR, f"{name} not executable"


def test_compose_parses_and_wires_the_stack():
    yaml = pytest.importorskip("yaml")
    with open(os.path.join(KIT, "docker-compose.yml")) as f:
        doc = yaml.safe_load(f)
    svcs = doc["services"]
    assert set(svcs) == {"stack", "probe"}
    cmd = svcs["stack"]["command"]
    assert "jax_mapping.ros_launch" in cmd
    env = "".join(svcs["stack"]["environment"])
    assert "ROS_DOMAIN_ID=42" in env          # reference pi/Dockerfile:3
    assert "probe.sh" in svcs["probe"]["command"]


def test_probe_asserts_the_adapters_topic_surface():
    """Every outbound topic the adapter advertises by default must be
    probed, and the probe must not expect topics the adapter never
    publishes."""
    from jax_mapping.bridge.rclpy_adapter import RclpyAdapter

    with open(os.path.join(KIT, "probe.sh")) as f:
        probe = f.read()
    # The adapter's default outbound surface, as ROS topic names
    # ("frontiers" is published as /frontiers_markers).
    expected = set()
    for t in RclpyAdapter.OUTBOUND_DEFAULT:
        expected.add("/frontiers_markers" if t == "frontiers" else f"/{t}")
    probed = set(re.findall(r"(/[a-z_]+)", probe))
    missing = expected - probed
    assert not missing, f"probe.sh does not check {sorted(missing)}"
    # QoS semantics the contract specifies: latched map, BE scan.
    assert "transient_local" in probe
    assert "best_effort" in probe
    # TF + inbound command path.
    assert "tf2_echo map base_link" in probe
    assert "/cmd_vel" in probe


def test_probe_fails_loudly():
    with open(os.path.join(KIT, "probe.sh")) as f:
        probe = f.read()
    assert "DDS-PROOF-FAIL" in probe and "DDS-PROOF-OK" in probe
    assert probe.count("fail ") >= 5          # every stage gated
