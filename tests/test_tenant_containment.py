"""Tenant blast-radius containment (ISSUE 17): lane-health sentinels,
the durable control plane, and seeded tenant-level chaos.

Three contracts under test:

* **Containment** — a sick tenant (NaN poison, pose teleport) walks the
  healthy -> suspect -> QUARANTINED hysteresis ladder on device-computed
  health words (ZERO extra dispatches — the word rides the megabatch),
  its lane freezes in place via the pad-style ``active=False`` select,
  and every co-tenant stays BIT-IDENTICAL to a no-fault twin (state and
  served tile bytes). Serving keeps the frozen last-good revision with a
  ``state=quarantined`` stamp; bounded seeded probes re-admit with an
  epoch bump.
* **Durability** — the lifecycle journal (CRC-per-record, torn tail
  truncated, compaction snapshots) lets a crashed plane `restore()` the
  SAME tenant set with epochs advanced; all-corrupt checkpoints degrade
  to a `lost` report, never a crash.
* **Chaos determinism** — the tenant FaultPlan kinds compose refcounted,
  reject same-resource overlap in `random_plan`, and two same-seed runs
  produce identical quarantine/restore sequences (the slow drill).

Wall-clock discipline: every ARMED (lane_health=True) in-process test
shares ONE module-scoped config and stays on buckets {1, 2}, so the
armed megabatch variants compile at most twice per test process; the
12-tenant acceptance drill is `slow` and runs in a clean subprocess.
"""

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import TenancyConfig, micro_config
from jax_mapping.models import fleet as FM
from jax_mapping.sim import world as W
from jax_mapping.tenancy import megabatch as MB
from jax_mapping.tenancy.controlplane import (AdmissionRejected,
                                              TenantControlPlane)
from jax_mapping.tenancy.journal import (ControlJournal, read_journal,
                                         read_registry)
from jax_mapping.tenancy.lanehealth import (HEALTHY, QUARANTINED,
                                            SUSPECT, LaneHealthLadder)

#: The ONE armed tenancy shape for this module (buckets {1,2} only):
#: persist=2 and probe cadence 3 give the canonical timeline — poison
#: at tick 4 -> suspect(4) -> quarantined(5) -> probe+readmit(8).
_ARMED = TenancyConfig(
    enabled=True, prewarm_on_admit=False, lane_health=True,
    quarantine_persist_ticks=2, readmit_probe_ticks=3,
    max_readmit_probes=2, journal=True)


@pytest.fixture(scope="module")
def acfg():
    return dataclasses.replace(micro_config(), tenancy=_ARMED)


@pytest.fixture(scope="module")
def world_np(acfg):
    return W.empty_arena(acfg.grid.size_cells, acfg.grid.resolution_m)


def _solo_run(cfg, world, seed, n_steps, state=None):
    s = (FM.init_fleet_state(cfg, jax.random.PRNGKey(seed))
         if state is None else state)
    for _ in range(n_steps):
        s, _ = FM.fleet_step(cfg, s, cfg.grid.resolution_m, world)
    return s


def _assert_states_bitequal(a, b, what: str) -> None:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=what)


def _tile_digest(cp, tid: str) -> str:
    """SHA-256 over the tenant's full served tile manifest — the
    'served bytes' half of the co-tenant bit-identity contract."""
    store = cp.tile_store(tid)
    store.refresh()
    _, entries, _ = store.tiles_since(-1)
    return hashlib.sha256(
        json.dumps(entries, sort_keys=True).encode()).hexdigest()


# ----------------------------------------------------------- knob-off

def test_containment_knobs_default_off():
    """The pre-PR reproduction contract starts at the config layer:
    every ISSUE 17 knob defaults OFF."""
    t = TenancyConfig()
    assert t.lane_health is False
    assert t.journal is False
    assert t.admission_queue_max == 0
    # And micro_config carries the defaults through.
    assert micro_config().tenancy.lane_health is False


def test_knob_off_bit_exact_and_armed_observational(acfg, world_np):
    """Property: arming the sentinel changes NOTHING but the health
    output — the armed and unarmed megabatch evolve bit-identical
    batches from identical inputs (the sentinel is a read-only fold of
    values the step already computes), and the unarmed trace returns a
    constant-zeros word (knob-off = pre-PR behavior bit-exactly)."""
    off_cfg = dataclasses.replace(
        acfg, tenancy=dataclasses.replace(_ARMED, lane_health=False))
    res = acfg.grid.resolution_m
    key = jax.random.PRNGKey(0)
    states = [FM.init_fleet_state(acfg, jax.random.PRNGKey(k))
              for k in range(2)]
    b_off = MB.make_tenant_batch(states, [world_np] * 2, [key] * 2)
    b_arm = b_off
    for _ in range(6):
        b_off, _, h_off = MB.megabatch_tick(off_cfg, b_off, res)
        b_arm, _, h_arm = MB.megabatch_tick(acfg, b_arm, res)
        assert np.asarray(h_off).tolist() == [0, 0], (
            "unarmed health word must be constant zeros")
        assert np.asarray(h_arm).tolist() == [0, 0], (
            "clean run flagged by the armed sentinel")
    for i in range(2):
        _assert_states_bitequal(
            MB.lane_state(b_arm, i), MB.lane_state(b_off, i),
            f"arming the sentinel perturbed lane {i}")


# ------------------------------------------------------------- ladder

def test_lane_health_ladder_units():
    """Hysteresis, probe scheduling, the probe budget, and the
    restore-path re-assertion — pure host logic."""
    cfg = dataclasses.replace(_ARMED, quarantine_persist_ticks=3,
                              readmit_probe_ticks=4,
                              max_readmit_probes=2)
    lad = LaneHealthLadder(cfg)
    assert lad.state("t") == HEALTHY
    # One flagged tick -> suspect; a clean tick returns to healthy.
    assert lad.observe("t", MB.HEALTH_NONFINITE, 1) is None
    assert lad.state("t") == SUSPECT
    assert lad.observe("t", 0, 2) is None
    assert lad.state("t") == HEALTHY
    # persist_ticks CONSECUTIVE flags declare quarantine exactly once.
    assert lad.observe("t", 1, 3) is None
    assert lad.observe("t", 1, 4) is None
    assert lad.observe("t", 1, 5) == QUARANTINED
    assert lad.state("t") == QUARANTINED
    assert lad.n_quarantines == 1
    # No flag-based exit from quarantine; further words are ignored.
    assert lad.observe("t", 0, 6) is None
    assert lad.state("t") == QUARANTINED
    # Probe cadence: every 4 ticks after the declaration (tick 5).
    assert not lad.probe_due("t", 6)
    assert lad.probe_due("t", 9)
    assert not lad.note_probe("t", False, 9)       # burns budget
    assert lad.probe_due("t", 13)
    assert not lad.note_probe("t", False, 13)
    assert not lad.probe_due("t", 17), "probe budget must exhaust"
    # mark_quarantined (restore path) resets the budget and schedule.
    lad2 = LaneHealthLadder(cfg)
    lad2.mark_quarantined("r", 10)
    assert lad2.state("r") == QUARANTINED
    assert lad2.probe_due("r", 14)
    assert lad2.note_probe("r", True, 14)          # readmit
    assert lad2.state("r") == HEALTHY
    assert lad2.n_readmits == 1
    # forget: eviction wipes the ladder entry.
    lad2.mark_quarantined("r", 20)
    lad2.forget("r")
    assert lad2.state("r") == HEALTHY
    assert lad2.quarantined() == []
    snap = lad.snapshot()
    assert snap["n_quarantines"] == 1
    assert snap["lanes"]["t"]["state"] == QUARANTINED


def test_lane_health_host_word_bits(acfg):
    """The host twin flags exactly the three sentinel conditions."""
    cfg = dataclasses.replace(
        acfg, tenancy=dataclasses.replace(_ARMED, match_floor=0.1))
    s0 = FM.init_fleet_state(cfg, jax.random.PRNGKey(0))
    assert MB.lane_health_host(cfg, s0, s0) == 0
    # NaN pose -> NONFINITE (grid delta of identical grids stays 0).
    bad = s0._replace(est_poses=s0.est_poses.at[0, 0].set(jnp.nan))
    assert MB.lane_health_host(cfg, s0, bad) & MB.HEALTH_NONFINITE
    # A finite teleport past the traced threshold -> POSE_JUMP only.
    far = s0._replace(est_poses=s0.est_poses.at[:, :2].add(
        cfg.tenancy.pose_jump_max_m * 3.0))
    word = MB.lane_health_host(cfg, s0, far)
    assert word & MB.HEALTH_POSE_JUMP
    assert not word & MB.HEALTH_NONFINITE
    # Match floor: charged only where a key-step match ran.
    R = cfg.fleet.n_robots
    diag = type("D", (), {})()
    diag.match_response = np.full((R,), 0.01, np.float32)
    diag.is_key = np.ones((R,), bool)
    assert MB.lane_health_host(cfg, s0, s0, diag) \
        & MB.HEALTH_MATCH_FLOOR
    diag.is_key = np.zeros((R,), bool)
    assert MB.lane_health_host(cfg, s0, s0, diag) == 0


# ------------------------------------------- quarantine lifecycle

def test_quarantine_probe_readmit_cycle(acfg, world_np, tmp_path):
    """THE containment tentpole, in-process at bucket 2: a poisoned
    tenant walks suspect -> quarantined on the canonical timeline, its
    revision freezes on the held last-good content, the co-tenant
    stays bit-identical to a no-fault twin (state AND served tile
    bytes), a seeded probe re-admits with an epoch bump — and the
    whole cycle compiles ZERO new megabatch variants post-warmup (the
    live recompile guard: quarantine freezes in place, no restack)."""
    from jax_mapping.obs.recorder import flight_recorder

    world = jnp.asarray(world_np)
    cp = TenantControlPlane(acfg, checkpoint_dir=str(tmp_path / "a"))
    twin = TenantControlPlane(acfg,
                              checkpoint_dir=str(tmp_path / "b"))
    for plane in (cp, twin):
        plane.admit("sick", world_np, seed=0)
        plane.admit("ok", world_np, seed=1)
    cp.step(3)
    twin.step(3)
    variants_warm = int(MB.megabatch_step._cache_size())
    mark = flight_recorder.mark()

    cp.set_tenant_poison("sick", True)
    cp.step(2)                       # tick 4: suspect, tick 5: declare
    twin.step(2)
    assert cp.tenant_lifecycle("sick") == "quarantined"
    assert cp.status()["n_quarantined_now"] == 1
    # Flagged ticks never published: the frozen revision is the
    # last-good tick-3 content, and serving holds exactly that state.
    assert cp.revision("sick") == 3
    assert cp.revision("ok") == 5
    _assert_states_bitequal(cp.tenant_state("sick"),
                            _solo_run(acfg, world, 0, 3),
                            "held last-good != pre-fault content")

    # Probe at tick 8 (cadence 3 after the tick-5 declaration): the
    # held state is finite and survives a solo tick -> readmit.
    cp.set_tenant_poison("sick", False)
    cp.step(3)
    twin.step(3)
    assert cp.tenant_lifecycle("sick") == "active"
    assert cp.epoch("sick") == 1, "re-admission must bump the epoch"
    cp.step(1)
    twin.step(1)
    # Readmitted lane resumed from the held tick-3 state: one tick
    # after re-admission equals the 4-tick solo run.
    _assert_states_bitequal(cp.tenant_state("sick"),
                            _solo_run(acfg, world, 0, 4),
                            "readmitted lane != held-state solo run")

    # Co-tenant blast radius: bit-identical to the no-fault twin in
    # state AND served tile bytes, through poison, quarantine, the
    # probe's solo dispatch and the in-place readmit.
    _assert_states_bitequal(cp.tenant_state("ok"),
                            twin.tenant_state("ok"),
                            "co-tenant state diverged from twin")
    assert _tile_digest(cp, "ok") == _tile_digest(twin, "ok"), (
        "co-tenant served tiles diverged from the no-fault twin")

    # Zero extra dispatches is by construction (the word rides the
    # megabatch); zero extra COMPILES is the gate here. The absolute
    # per-process ceiling is NOT asserted on the shared pytest cache
    # (sibling modules mint their own bucket variants first) — the
    # canonical-scenario ratchet in test_analysis_selfcheck owns it.
    assert int(MB.megabatch_step._cache_size()) == variants_warm, (
        "quarantine/probe/readmit minted a megabatch variant "
        "post-warmup")

    kinds = [e["kind"] for e in flight_recorder.events_since(mark)]
    assert "tenancy_quarantine" in kinds
    assert "tenancy_readmit_probe" in kinds
    assert "tenancy_readmit" in kinds
    # The ladder's transition log is the determinism surface.
    assert [(t, s0_, s1_) for t, tid, s0_, s1_
            in cp._lanehealth.transitions] == [
        (4, HEALTHY, SUSPECT), (5, SUSPECT, QUARANTINED),
        (8, QUARANTINED, HEALTHY)]


def test_state_jump_is_survivable_state_fault(acfg, world_np):
    """`tenant_state_jump` corrupts INPUT state (the within-step delta
    stays small, so the POSE_JUMP sentinel is the wrong detector by
    design) — the host twin confirms the teleported state itself is
    finite and un-flagged, i.e. the fault is survivable and only the
    match-floor sentinel (armed per deployment) would catch the
    degradation."""
    cp = TenantControlPlane(acfg)
    cp.admit("t", world_np, seed=0)
    cp.step(1)
    before = cp.tenant_state("t")
    cp.state_jump_tenant("t", 1.5)
    after = cp.tenant_state("t")
    d = np.asarray(after.est_poses - before.est_poses)[..., :2]
    np.testing.assert_allclose(d, 1.5, rtol=1e-6)
    assert np.isfinite(np.asarray(after.est_poses)).all()
    cp.evict("t", checkpoint=False)


# ------------------------------------------------------------ journal

def test_journal_roundtrip_compaction_and_reopen(tmp_path):
    d = str(tmp_path)
    j = ControlJournal(d)
    j.append("admit", "a", seed=3, epoch=0, revision=1, steps=0,
             world_shape=[64, 64], world_dtype="float32")
    j.append("admit", "b", seed=4, epoch=0, revision=1, steps=0)
    j.append("suspend", "b", epoch=0, revision=5, steps=4)
    j.append("quarantine", "a", epoch=0, revision=7, steps=9, word=1)
    reg = j.registry()
    assert reg["a"]["state"] == "quarantined"
    assert reg["a"]["world_shape"] == [64, 64]
    assert reg["b"]["state"] == "suspended"
    with pytest.raises(ValueError, match="unknown journal record"):
        j.append("frobnicate", "a")
    # Compaction truncates the journal; the snapshot carries the fold.
    j.compact()
    assert os.path.getsize(j.journal_path) == 0
    reg2, seq, meta = read_registry(d)
    assert reg2 == reg and seq == j.seq
    assert meta["snapshot"] and meta["n_replayed"] == 0
    # Post-compaction appends replay on top of the snapshot.
    j.append("evict", "b", epoch=0, revision=5, steps=4)
    reg3, _, meta3 = read_registry(d)
    assert reg3["b"]["state"] == "evicted"
    assert meta3["n_replayed"] == 1
    # Reopening restores seq monotonicity — the ordering extends.
    j2 = ControlJournal(d)
    assert j2.seq == j.seq
    assert j2.registry()["a"]["state"] == "quarantined"
    assert j2.append("resume", "b") == j.seq + 1


def test_journal_torn_tail_truncates(tmp_path):
    """Torn mid-record (the power-loss case): short header, short
    payload, and CRC rot all end the walk at the last intact record
    and truncate the file — corrupt degrades, never crashes."""
    d = str(tmp_path)
    j = ControlJournal(d)
    j.append("admit", "a", seed=0)
    j.append("admit", "b", seed=1)
    good_size = os.path.getsize(j.journal_path)
    # Append a torn record: a length prefix promising more bytes than
    # exist (a crash mid-append).
    with open(j.journal_path, "ab") as f:
        f.write(b"\xff\x00\x00\x00partial")
    recs, truncated = read_journal(j.journal_path)
    assert [r["tid"] for r in recs] == ["a", "b"]
    assert truncated > 0
    assert os.path.getsize(j.journal_path) == good_size, (
        "torn bytes must truncate away, never resurrect")
    # CRC rot inside the LAST record: that record (only) is dropped.
    with open(j.journal_path, "rb+") as f:
        f.seek(good_size - 5)
        f.write(b"\x00")
    recs2, _ = read_journal(j.journal_path)
    assert [r["tid"] for r in recs2] == ["a"]
    # A fresh plane-side open replays only the intact prefix.
    reg, _, meta = read_registry(d)
    assert set(reg) == {"a"}
    assert meta["torn_bytes_truncated"] == 0    # already truncated


def test_snapshot_newer_than_journal_tail(tmp_path):
    """A journal tail OLDER than the snapshot (compaction raced a
    crash that resurrected pre-compaction records) replays to nothing:
    records at or below the snapshot seq are skipped."""
    d = str(tmp_path)
    j = ControlJournal(d)
    j.append("admit", "a", seed=0)
    j.append("suspend", "a")
    j.compact()                                  # snapshot seq = 2
    # Hand-write a stale record (seq 1) into the truncated journal —
    # same bytes an interrupted compaction could leave behind.
    stale = ControlJournal(str(tmp_path / "scratch"))
    stale.append("evict", "a")                   # seq 1 in its file
    with open(stale.journal_path, "rb") as f:
        raw = f.read()
    with open(j.journal_path, "ab") as f:
        f.write(raw)
    reg, seq, meta = read_registry(d)
    assert reg["a"]["state"] == "suspended", (
        "a stale (seq <= snapshot) record replayed over the snapshot")
    assert seq == 2 and meta["n_replayed"] == 0


# ------------------------------------------------------------ restore

def test_restore_crash_roundtrip(acfg, world_np, tmp_path):
    """Plane crash -> rebuild -> restore: the SAME tenant set comes
    back (active tenants re-admitted through the warmup path, a
    quarantined tenant held-state-only with its probe schedule live),
    every epoch advances past its journaled watermark, and the
    restored plane steps and re-admits normally."""
    ckdir = str(tmp_path)
    world = jnp.asarray(world_np)
    cp = TenantControlPlane(acfg, checkpoint_dir=ckdir)
    cp.admit("a", world_np, seed=0)
    cp.admit("q", world_np, seed=1)
    cp.step(3)
    cp.set_tenant_poison("q", True)
    cp.step(2)                                   # q quarantined @5
    assert cp.tenant_lifecycle("q") == "quarantined"
    cp.checkpoint_all()
    a_state = cp.tenant_state("a")
    q_held = cp.tenant_state("q")
    a_epoch, q_epoch = cp.epoch("a"), cp.epoch("q")
    a_rev = cp.revision("a")

    cp2 = TenantControlPlane(acfg, checkpoint_dir=ckdir)
    report = cp2.restore()
    assert sorted(report["restored"]) == ["a", "q"]
    assert report["lost"] == []
    assert cp2.tenant_lifecycle("a") == "active"
    assert cp2.tenant_lifecycle("q") == "quarantined"
    # Epoch protocol: advanced past the journaled watermark, and
    # epoch ⇒ revision so no (epoch, revision) ETag pair recurs.
    assert cp2.epoch("a") == a_epoch + 1
    assert cp2.epoch("q") == q_epoch + 1
    assert cp2.revision("a") == a_rev + 1
    _assert_states_bitequal(cp2.tenant_state("a"), a_state,
                            "restored active state != checkpointed")
    _assert_states_bitequal(cp2.tenant_state("q"), q_held,
                            "restored held state != checkpointed")
    # The restored quarantine probes on the new plane's clock and
    # re-joins through the laneless (resume-style) readmit path.
    cp2.step(3)
    assert cp2.tenant_lifecycle("q") == "active"
    assert cp2.epoch("q") == q_epoch + 2
    # A fault-free tick after readmission advances both tenants.
    cp2.step(1)
    assert cp2.revision("a") > a_rev + 1


def test_restore_all_corrupt_checkpoints_reports_lost(acfg, world_np,
                                                      tmp_path):
    """A tenant whose checkpoint generations are ALL unreadable is
    reported `lost`; the other tenants still restore (degrade, never
    crash)."""
    ckdir = str(tmp_path)
    cp = TenantControlPlane(acfg, checkpoint_dir=ckdir)
    cp.admit("keep", world_np, seed=0)
    cp.admit("gone", world_np, seed=1)
    cp.step(2)
    cp.checkpoint_all()
    for name in os.listdir(ckdir):
        # Every generation: the live slot AND the .prev fallback.
        if name.startswith("tenant_gone.live."):
            p = os.path.join(ckdir, name)
            with open(p, "rb+") as f:
                f.truncate(max(1, os.path.getsize(p) // 3))
    cp2 = TenantControlPlane(acfg, checkpoint_dir=ckdir)
    report = cp2.restore()
    assert report["restored"] == ["keep"]
    assert report["lost"] == ["gone"]
    assert cp2.tenant_lifecycle("keep") == "active"
    cp2.step(1)                                  # still serviceable


def test_restore_with_torn_journal_tail(acfg, world_np, tmp_path):
    """A torn journal tail at plane-construction time truncates and
    restores the intact prefix — never fatal."""
    ckdir = str(tmp_path)
    cp = TenantControlPlane(acfg, checkpoint_dir=ckdir)
    cp.admit("a", world_np, seed=0)
    cp.step(1)
    cp.checkpoint_all()
    jpath = os.path.join(ckdir, "controlplane", "control.journal")
    with open(jpath, "ab") as f:
        f.write(b"\x40\x00\x00\x00torn-mid-record")
    cp2 = TenantControlPlane(acfg, checkpoint_dir=ckdir)
    report = cp2.restore()
    assert report["restored"] == ["a"] and report["lost"] == []


# --------------------------------------------------------- admission

def test_admission_backpressure_rejects(acfg, world_np, tmp_path):
    """Bounded admission: with `admission_queue_max=1`, a second
    admission entering while one is in flight raises AdmissionRejected
    (never queues), bumps the counter, flight-records the rejection,
    and the /status admission block reports it. The in-flight window
    is held open deterministically by gating `_admit`."""
    from jax_mapping.obs.recorder import flight_recorder

    cfg = dataclasses.replace(
        acfg, tenancy=dataclasses.replace(_ARMED, journal=False,
                                          admission_queue_max=1))
    cp = TenantControlPlane(cfg)
    inner = cp._admit
    entered = threading.Event()
    release = threading.Event()

    def gated(tid, world, seed, state, dynamics):
        entered.set()
        assert release.wait(30)
        return inner(tid, world, seed, state, dynamics)

    cp._admit = gated
    mark = flight_recorder.mark()
    t = threading.Thread(target=cp.admit,
                         args=("slow", world_np), kwargs={"seed": 0})
    t.start()
    try:
        assert entered.wait(30)
        with pytest.raises(AdmissionRejected, match="in flight"):
            cp.admit("burst", world_np, seed=1)
    finally:
        release.set()
        t.join(timeout=60)
    assert not t.is_alive()
    cp._admit = inner
    st = cp.status()
    assert st["admission"] == {"in_flight": 0, "queue_max": 1,
                               "n_rejected": 1}
    events = [e for e in flight_recorder.events_since(mark)
              if e["kind"] == "tenancy_admission_rejected"]
    assert len(events) == 1 and events[0]["tenant"] == "burst"
    fams = {f.name for f in cp.metric_families()}
    assert "jax_mapping_tenant_admission_rejected_total" in fams
    # The admitted tenant is intact; the rejected one left no trace.
    assert cp.tenant_lifecycle("slow") == "active"
    assert cp.tenant_lifecycle("burst") == "unknown"


def test_admission_backpressure_concurrent_consistency(acfg, world_np):
    """Concurrent admits against a bounded queue: every thread either
    lands a fully-consistent tenant or gets a clean AdmissionRejected
    — the registry never holds a half-admitted mission and the
    accounting (admitted + rejected) balances."""
    cfg = dataclasses.replace(
        acfg, tenancy=dataclasses.replace(_ARMED, journal=False,
                                          admission_queue_max=1))
    cp = TenantControlPlane(cfg)
    outcomes = []
    gate = threading.Barrier(4)

    def admit_one(i):
        try:
            gate.wait(30)
            cp.admit(f"c{i}", world_np, seed=i)
            outcomes.append(("ok", i))
        except AdmissionRejected:
            outcomes.append(("rejected", i))
        except Exception as e:                   # noqa: BLE001
            outcomes.append(("error", repr(e)))

    threads = [threading.Thread(target=admit_one, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads)
    assert not [o for o in outcomes if o[0] == "error"], outcomes
    ok = [i for k, i in outcomes if k == "ok"]
    st = cp.status()
    assert len(ok) >= 1, "the bounded queue starved every admission"
    assert st["n_admitted"] == len(ok)
    assert st["admission"]["n_rejected"] == 4 - len(ok)
    assert st["admission"]["in_flight"] == 0
    for i in ok:
        assert cp.tenant_lifecycle(f"c{i}") == "active"
        cp.tenant_state(f"c{i}")                 # fully materialized


# ---------------------------------------------------- serving client

def test_client_tenant_gone_and_quarantine_stamp(acfg, world_np):
    """DeltaMapClient on a tenant route: steady polls work, a
    quarantined tenant serves its frozen revision with the
    `state=quarantined` stamp (and a `-quarantined` ETag, so a
    healthy-tagged client re-fetches once), and an evicted tenant's
    404 raises typed TenantGone — mission churn, not breakage."""
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.serving.client import DeltaMapClient, TenantGone

    st = launch_sim_stack(acfg, world_np, n_robots=1, http_port=0,
                          realtime=False, seed=0)
    try:
        plane = st.tenancy
        plane.admit("m0", world_np, seed=0)
        plane.step(3)
        base = f"http://127.0.0.1:{st.api.port}"
        client = DeltaMapClient(base, route="/tiles?tenant=m0")
        body = client.poll()
        assert client.revision == 3 and client.state is None
        assert body["tiles"]

        plane.set_tenant_poison("m0", True)
        plane.step(2)                            # suspect -> quarantined
        assert plane.tenant_lifecycle("m0") == "quarantined"
        body = client.poll()
        assert client.state == "quarantined"
        assert body["revision"] == 3, "frozen revision moved"
        assert "-quarantined" in client._etag
        # Current client + unchanged frozen revision -> 304 now.
        body = client.poll()
        assert body.get("not_modified") is True

        plane.evict("m0", checkpoint=False)
        with pytest.raises(TenantGone) as ei:
            client.poll()
        assert ei.value.route == "/tiles?tenant=m0"
        assert ei.value.detail
        # Unknown tenant ids get the same typed signal.
        ghost = DeltaMapClient(base, route="/tiles?tenant=ghost")
        with pytest.raises(TenantGone):
            ghost.poll()
    finally:
        st.shutdown()


# ----------------------------------------------------------- threads

def test_racewatch_quarantine_vs_status(acfg, world_np):
    """Eraser lockset gate over the containment path: /status and
    /metrics polling from worker threads races the stepping thread
    through poison, quarantine, probes and readmission — zero race
    reports, and the lane-health ladder's candidate lockset converges
    on the declared `_lock`."""
    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch

    cp = TenantControlPlane(acfg)
    cp.admit("sick", world_np, seed=0)
    cp.admit("ok", world_np, seed=1)
    cp.step(3)                                   # warm in-line
    watch = RaceWatch()
    errors = []
    try:
        watch.watch_object(cp,
                           groups_by_class()["TenantControlPlane"][0],
                           name="containment")
        stop = threading.Event()

        def poller():
            while not stop.is_set():
                try:
                    st = cp.status()
                    assert "health" in st
                    cp.metric_families()
                    cp.tenant_lifecycle("sick")
                except Exception as e:           # noqa: BLE001
                    errors.append(f"status: {e}")
                stop.wait(0.002)

        threads = [threading.Thread(target=poller) for _ in range(2)]
        for t in threads:
            t.start()
        cp.set_tenant_poison("sick", True)
        cp.step(2)                               # quarantine
        cp.set_tenant_poison("sick", False)
        cp.step(4)                               # probe + readmit
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
    finally:
        watch.unwatch_all()
    assert not errors, errors
    assert watch.reports() == []
    assert cp.tenant_lifecycle("sick") == "active"
    states = watch.field_states()
    lh = [s for name, s in states.items() if "_lanehealth" in name]
    assert lh, "racewatch never saw the lane-health field"
    for s in lh:
        assert s.candidate is None or any(
            "_lock" in c for c in s.candidate), (
            f"{s.name} lockset did not converge on _lock: "
            f"{s.candidate}")


# --------------------------------------------------------- faultplan

def test_faultplan_tenant_kind_validation_and_sampling():
    from jax_mapping.resilience.faultplan import (TENANT_KINDS,
                                                  FaultEvent,
                                                  random_plan)

    assert TENANT_KINDS == {"tenant_poison", "tenant_state_jump",
                            "controlplane_crash"}
    with pytest.raises(ValueError, match="needs name"):
        FaultEvent(step=1, kind="tenant_poison")
    with pytest.raises(ValueError, match="needs name"):
        FaultEvent(step=1, kind="tenant_state_jump", value=1.0)
    with pytest.raises(ValueError, match="value > 0"):
        FaultEvent(step=1, kind="tenant_state_jump", name="t")
    FaultEvent(step=1, kind="controlplane_crash")        # name-free

    tenants = [f"m{i}" for i in range(4)]
    p1 = random_plan(200, n_faults=12, seed=7, tenant_ids=tenants,
                     allow_controlplane_crash=True)
    p2 = random_plan(200, n_faults=12, seed=7, tenant_ids=tenants,
                     allow_controlplane_crash=True)
    assert p1.events == p2.events, "same-seed plans must be identical"
    tenant_events = [e for e in p1.events
                     if e.kind in ("tenant_poison",
                                   "tenant_state_jump")]
    assert tenant_events, "the tenant kinds never sampled"
    assert all(e.name in tenants for e in tenant_events)
    # Overlap rejection: windows on one tenant never intersect.
    from jax_mapping.resilience.faultplan import _fault_resource
    windows = {}
    for e in p1.events:
        res = _fault_resource(e.kind, e.robot, e.name)
        for s, en in windows.get(res, []):
            assert not (e.step <= en and s <= e.step + e.duration), (
                f"overlapping windows on {res}")
        windows.setdefault(res, []).append(
            (e.step, e.step + e.duration))
    # One plane = one resource: at most ONE crash per plan.
    assert sum(e.kind == "controlplane_crash"
               for e in p1.events) <= 1
    # Without tenant_ids the sampler reproduces the pre-PR pool.
    p3 = random_plan(200, n_faults=6, seed=3)
    assert all(e.kind not in TENANT_KINDS for e in p3.events)


def test_faultplan_tenant_poison_refcount_composes():
    """Two overlapping hand-written poison windows on one tenant: the
    first window's clear must NOT un-poison while the second still
    holds (the partition refcount doctrine); a crash swapping the
    plane mid-window clears against the RESTORED plane."""
    from jax_mapping.resilience.faultplan import FaultEvent, FaultPlan

    class _Plane:
        def __init__(self):
            self.calls = []

        def set_tenant_poison(self, tid, active):
            self.calls.append((tid, active))

    class _Stack:
        def __init__(self):
            self.tenancy = _Plane()
        bus = None
        brain = None

    stack = _Stack()
    plan = FaultPlan([
        FaultEvent(step=1, kind="tenant_poison", name="t", duration=4),
        FaultEvent(step=3, kind="tenant_poison", name="t", duration=4),
    ])
    for step in range(0, 9):
        plan.apply(stack, step)
    # Holds at 1 and 3; window-1 clear at 5 is refcount-held (no
    # un-poison); window-2 clear at 7 releases.
    assert stack.tenancy.calls == [("t", True), ("t", True),
                                   ("t", False)]
    assert plan.done()
    # Plane swapped mid-window (controlplane_crash): the clear re-reads
    # stack.tenancy and lands on the NEW plane.
    stack2 = _Stack()
    plan2 = FaultPlan([
        FaultEvent(step=1, kind="tenant_poison", name="t", duration=3)])
    plan2.apply(stack2, 1)
    old_plane = stack2.tenancy
    stack2.tenancy = _Plane()
    plan2.apply(stack2, 4)
    assert old_plane.calls == [("t", True)]
    assert stack2.tenancy.calls == [("t", False)]


def test_controlplane_crash_overlapping_cache_wipe(acfg, world_np,
                                                   tmp_path):
    """The restore edge the satellites pin: a `controlplane_crash`
    fires INSIDE a `cache_wipe` window — restore re-admits through a
    wiped compile cache (plain recompile, never blocked), the full
    tenant set comes back with epochs advanced, and the wipe window
    clears cleanly afterwards. Runs through the real Stack wiring
    (`Stack.crash_controlplane`) and the real FaultPlan kinds."""
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.io.compile_cache import CompileCacheManager
    from jax_mapping.resilience.faultplan import FaultEvent, FaultPlan

    st = launch_sim_stack(acfg, world_np, n_robots=1, http_port=None,
                          realtime=False, seed=0,
                          checkpoint_dir=str(tmp_path))
    try:
        st.compile_cache = CompileCacheManager(
            acfg.cold_start, str(tmp_path / "cc"))
        plane0 = st.tenancy
        plane0.admit("m0", world_np, seed=0)
        plane0.step(2)
        epoch0 = plane0.epoch("m0")
        plan = FaultPlan([
            FaultEvent(step=1, kind="cache_wipe", duration=4),
            FaultEvent(step=2, kind="controlplane_crash"),
        ])
        for step in range(0, 7):
            plan.apply(st, step)
        assert plan.done()
        assert st.tenancy is not plane0, "the plane did not crash"
        assert st.api is None or st.api.tenancy is st.tenancy
        assert st.tenancy.tenant_lifecycle("m0") == "active"
        assert st.tenancy.epoch("m0") > epoch0
        st.tenancy.step(1)                       # restored plane serves
        logs = [d for _, d in plan.log]
        assert "cache_wipe" in logs
        assert any(d.startswith("controlplane_crash restored=1 lost=0")
                   for d in logs), logs
        assert st.compile_cache._wipe_refs == 0, (
            "the wipe window did not clear after the crash")
    finally:
        st.shutdown()


# ------------------------------------------------- acceptance drill

def _clean_cpu_env() -> dict:
    """CPU-pinned subprocess env WITHOUT the harness's virtual-mesh
    flag (the EXACT_BUCKETS gotcha — see tests/test_tenancy.py)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.slow
def test_tenant_blast_radius_drill(tmp_path):
    """THE ISSUE 17 acceptance drill, from a clean subprocess: a
    12-tenant soak under seeded chaos where (1) the poisoned tenant
    quarantines within the hysteresis budget, (2) all 11 co-tenants
    stay BIT-IDENTICAL to a no-fault twin (state AND served tile
    digests), (3) a control-plane crash restores the full tenant set
    with epochs advanced, (4) the per-tenant SLO ingest-stall burn
    fires ONLY under the poisoned tenant's label, and (5) two
    same-seed runs produce identical quarantine/restore/alert
    sequences."""
    script = r"""
import dataclasses, hashlib, json, os, sys
import numpy as np
import jax, jax.numpy as jnp
from jax_mapping.config import SloObjective, TenancyConfig, micro_config
from jax_mapping.models import fleet as FM
from jax_mapping.obs.pipeline import PipelineLedger
from jax_mapping.obs.slo import SloEngine
from jax_mapping.sim import world as W
from jax_mapping.tenancy import megabatch as MB
from jax_mapping.tenancy.controlplane import TenantControlPlane

ROOT = sys.argv[1]
N = 12
SICK = "t03"
PERSIST = 2
cfg = dataclasses.replace(micro_config(), tenancy=TenancyConfig(
    enabled=True, prewarm_on_admit=False, lane_health=True,
    quarantine_persist_ticks=PERSIST, readmit_probe_ticks=4,
    max_readmit_probes=2, journal=True))
world_np = W.empty_arena(cfg.grid.size_cells, cfg.grid.resolution_m)
OBJ = SloObjective(name="tenant_fresh", metric="scan_to_served_p99_ms",
                   max_silent_ticks=2, fast_window_ticks=4,
                   slow_window_ticks=8, fast_burn=0.5, slow_burn=0.25)

def run(tag, fault):
    ck = os.path.join(ROOT, tag)
    ledger = PipelineLedger()
    cp = TenantControlPlane(cfg, checkpoint_dir=ck, pipeline=ledger)
    for i in range(N):
        cp.admit(f"t{i:02d}", world_np, seed=i)
    slos = {t: SloEngine([OBJ], pipeline=ledger, tenant=t)
            for t in (SICK, "t00")}
    seq = []
    def tick(n):
        for _ in range(n):
            cp.step(1)
            for t, eng in slos.items():
                eng.evaluate(cp.n_ticks)
                for a in eng.alerts()[len([s for s in seq
                                           if s[0] == "slo"
                                           and s[3] == t]):]:
                    seq.append(("slo", a[0], a[1] + ":" + a[2], t))
    tick(3)
    if fault:
        cp.set_tenant_poison(SICK, True)
    tick(PERSIST + 1)
    if fault:
        assert cp.tenant_lifecycle(SICK) == "quarantined", (
            "poisoned tenant not quarantined within the budget")
        seq.append(("quarantine", cp.n_ticks, SICK, ""))
    # Soak 2 more ticks: enough for the SLO burn windows to fire, but
    # INSIDE the quarantine window (the cadence-4 probe at tick 9
    # would re-admit the now-clean lane before the crash).
    tick(2)
    # Crash + restore mid-soak (the durable-registry acceptance).
    if fault:
        cp.checkpoint_all()
        epochs_before = {f"t{i:02d}": cp.epoch(f"t{i:02d}")
                         for i in range(N)}
        cp2 = TenantControlPlane(cfg, checkpoint_dir=ck,
                                 pipeline=ledger)
        report = cp2.restore()
        assert sorted(report["restored"]) == sorted(
            f"t{i:02d}" for i in range(N)), report
        assert report["lost"] == []
        for t, e0 in epochs_before.items():
            assert cp2.epoch(t) == e0 + 1, (t, e0, cp2.epoch(t))
        assert cp2.tenant_lifecycle(SICK) == "quarantined"
        seq.append(("restore", cp2.n_ticks,
                    ",".join(sorted(report["restored"])), ""))
        cp2.step(1)
    digests = {}
    for i in range(N):
        t = f"t{i:02d}"
        if t == SICK and fault:
            continue
        store = cp.tile_store(t)
        store.refresh()
        _, entries, _ = store.tiles_since(-1)
        h = hashlib.sha256(
            json.dumps(entries, sort_keys=True).encode()).hexdigest()
        sh = hashlib.sha256(b"".join(
            np.asarray(x).tobytes() for x in
            jax.tree_util.tree_leaves(cp.tenant_state(t)))).hexdigest()
        digests[t] = (sh, h)
    trans = list(cp._lanehealth.transitions)
    return digests, seq, trans, {t: s.firing()
                                 for t, s in slos.items()}

d_fault, seq1, trans1, firing1 = run("fault_a", True)
d_twin, _, _, _ = run("twin", False)
mismatch = [t for t in d_twin
            if t in d_fault and d_fault[t] != d_twin[t]]
assert not mismatch, f"co-tenants diverged from the twin: {mismatch}"
assert len([t for t in d_fault if t != SICK]) == N - 1
# SLO: the poisoned tenant's label fired; the healthy one's did not.
assert any(k == "slo" and t == SICK and "firing" in v
           for k, _, v, t in seq1), seq1
assert not any(k == "slo" and t == "t00" and "firing" in v
               for k, _, v, t in seq1), seq1
# Determinism: a second same-seed faulted run replays identically.
d2, seq2, trans2, _ = run("fault_b", True)
assert seq2 == seq1, "same-seed chaos sequences diverged"
assert trans2 == trans1
assert d2 == d_fault
print(json.dumps({"ok": True, "n_events": len(seq1),
                  "transitions": trans1[:4]}))
"""
    r = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path)],
        capture_output=True, text=True, timeout=900,
        env=_clean_cpu_env())
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr[-4000:]}"
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert doc["ok"] is True
