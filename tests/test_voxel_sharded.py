"""Sharded voxel fusion on the 8-virtual-device CPU mesh: the Y-slab
layout must produce EXACTLY the patch path's grid (the euclidean trust
horizon makes patch coverage exact — ops/voxel.py classify_region), with
zero collectives along 'space'.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import tiny_config
from jax_mapping.ops import voxel as V
from jax_mapping.parallel import voxel_sharded as VS
from jax_mapping.parallel import mesh as MESH
from jax_mapping.sim import depthcam as DC
from jax_mapping.sim import world as W


@pytest.fixture(scope="module")
def cfg():
    return tiny_config()


def _views(cfg, n=8):
    res = cfg.voxel.resolution_m
    world = jnp.asarray(np.asarray(W.empty_arena(96, res)))
    poses = np.stack([
        np.concatenate([np.linspace(-0.8, 0.8, n // 2)] * 2),
        np.concatenate([np.zeros(n // 2), np.full(n // 2, 0.5)]),
        np.linspace(0, 2 * math.pi, n, endpoint=False),
    ], axis=1).astype(np.float32)
    depths = DC.render_depths(cfg.depthcam, world, res, 96,
                              jnp.asarray(poses))
    return depths, jnp.asarray(poses)


def test_sharded_matches_patch_path(cfg):
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    depths, poses = _views(cfg, 8)

    for n_fleet, n_space in ((1, 8), (2, 4), (8, 1)):
        mesh = MESH.make_mesh(n_fleet=n_fleet, n_space=n_space,
                              devices=devs[:8])
        grid = VS.init_sharded_voxel_grid(cfg.voxel, mesh)
        step = VS.make_voxel_fuse_step(cfg.voxel, cfg.depthcam, mesh)
        out = np.asarray(step(grid, depths, poses))

        ref = np.asarray(V.fuse_depths(cfg.voxel, cfg.depthcam,
                                       V.empty_voxel_grid(cfg.voxel),
                                       depths, poses))
        np.testing.assert_allclose(out, ref, atol=1e-5, err_msg=(
            f"mesh {n_fleet}x{n_space} diverged from the patch path"))


def test_sharded_parity_holds_at_saturation(cfg):
    """Parity must survive clamping: both paths clamp ONCE per call
    (mixed-sign updates on a saturated voxel would diverge if one path
    clamped per image — the code-review failure scenario)."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    depths, poses = _views(cfg, 8)
    # Saturate: repeat the same views until walls pin at logodds_max.
    base = V.empty_voxel_grid(cfg.voxel)
    for _ in range(12):
        base = V.fuse_depths(cfg.voxel, cfg.depthcam, base, depths, poses)
    assert float(jnp.max(base)) == cfg.voxel.logodds_max
    assert float(jnp.min(base)) == cfg.voxel.logodds_min

    mesh = MESH.make_mesh(n_fleet=2, n_space=4, devices=devs[:8])
    step = VS.make_voxel_fuse_step(cfg.voxel, cfg.depthcam, mesh)
    out = np.asarray(step(jax.device_put(
        base, VS.voxel_sharding(mesh)), depths, poses))
    ref = np.asarray(V.fuse_depths(cfg.voxel, cfg.depthcam, base,
                                   depths, poses))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_sharded_grid_layout(cfg):
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    mesh = MESH.make_mesh(n_fleet=1, n_space=8, devices=devs[:8])
    grid = VS.init_sharded_voxel_grid(cfg.voxel, mesh)
    # Each device owns a contiguous Y slab of every Z layer.
    shard_shapes = {tuple(s.data.shape) for s in grid.addressable_shards}
    z, y, x = (cfg.voxel.size_z_cells, cfg.voxel.size_y_cells,
               cfg.voxel.size_x_cells)
    assert shard_shapes == {(z, y // 8, x)}


def test_sharded_rejects_indivisible(cfg):
    import dataclasses
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    mesh = MESH.make_mesh(n_fleet=1, n_space=8, devices=devs[:8])
    bad = dataclasses.replace(cfg.voxel, size_y_cells=100)
    with pytest.raises(ValueError, match="divisible"):
        VS.init_sharded_voxel_grid(bad, mesh)
