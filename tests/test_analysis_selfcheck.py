"""Tier-1 self-check gate: the repo passes its own static analysis.

The contract (ISSUE 1): `jax-mapping-lint jax_mapping/` over the
committed baseline reports ZERO new findings, every suppression in the
baseline still matches something (the ratchet only goes down), and the
static lock graph is consistent with the lock order a live
`launch_sim_stack` run actually exercises.
"""

import numpy as np
import pytest

from jax_mapping.analysis.core import (
    Baseline, all_checkers, analyze_modules, default_baseline_path,
    load_package_modules,
)
from jax_mapping.analysis.lock_discipline import LockGraph, build_lock_graph
from jax_mapping.analysis.lockwatch import LockWatch


@pytest.fixture(scope="module")
def package_modules():
    mods = load_package_modules()
    assert len(mods) > 40, "package discovery looks broken"
    return mods


# ---------------------------------------------------------------- the gate

def test_package_passes_static_analysis(package_modules):
    """THE tier-1 gate: zero non-baselined findings over jax_mapping/."""
    res = analyze_modules(package_modules,
                          Baseline.load(default_baseline_path()))
    assert not res.findings, (
        "new static-analysis findings (fix them, or baseline a "
        "deliberate site WITH a note in analysis/baseline.json):\n"
        + "\n".join(f.format() for f in res.findings))


def test_baseline_has_no_unused_suppressions(package_modules):
    """The baseline ratchets DOWN: a suppression whose site was fixed
    or moved must be deleted, not left to shadow a future regression."""
    res = analyze_modules(package_modules,
                          Baseline.load(default_baseline_path()))
    assert not res.unused_suppressions, (
        "stale baseline suppressions:\n"
        + "\n".join(str(s) for s in res.unused_suppressions))


def test_baseline_entries_carry_justifications():
    """Every accepted finding documents WHY it is acceptable."""
    base = Baseline.load(default_baseline_path())
    missing = [s for s in base.suppressions if not s.get("note")]
    assert not missing, f"baseline entries without a note: {missing}"


def test_cli_runs_clean_with_committed_baseline(capsys):
    from jax_mapping.analysis.cli import main
    assert main([]) == 0                       # package mode, baseline
    out = capsys.readouterr().out
    assert "0 new finding(s)" in out


def test_cli_no_baseline_mode_surfaces_accepted_sites(capsys):
    """--no-baseline must re-expose the baselined findings (proves the
    gate's cleanliness comes from the baseline, not a silent skip)."""
    from jax_mapping.analysis.cli import main
    assert main(["--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "A1-host-sync" in out and "B3-unguarded-write" in out


def test_cli_rejects_unknown_checker_id():
    from jax_mapping.analysis.cli import main
    assert main(["--checker", "Z9-not-a-checker"]) == 2


def test_cli_corrupt_baseline_is_usage_error_not_findings(tmp_path):
    """Exit 2 (usage/parse), never 1 (findings) or a traceback, for a
    broken baseline — CI consumers branch on that distinction. Same for
    --write-baseline, which must refuse to overwrite what it cannot
    merge."""
    from jax_mapping.analysis.cli import main

    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert main(["--baseline", str(bad)]) == 2
    assert main(["--write-baseline", "--baseline", str(bad)]) == 2
    assert bad.read_text() == "{not json"        # untouched
    wrong = tmp_path / "v99.json"
    wrong.write_text('{"version": 99, "suppressions": []}')
    assert main(["--baseline", str(wrong)]) == 2


def test_single_file_keys_match_committed_baseline():
    """Subset invocations must produce the same baseline keys as the
    full run: `jax-mapping-lint <pkg>/bridge/planner.py` anchors at the
    package parent (not the file's own directory), nothing resurfaces
    as a new finding, and — because a lone file lacks the cross-module
    jit context the A checkers need — no staleness claims are made."""
    import os

    import jax_mapping
    from jax_mapping.analysis.core import analyze_paths, load_paths

    pkg = os.path.dirname(os.path.abspath(jax_mapping.__file__))
    target = os.path.join(pkg, "bridge", "planner.py")
    [mod] = load_paths([target])
    assert mod.path == "jax_mapping/bridge/planner.py"
    assert mod.dotted == "jax_mapping.bridge.planner"
    res = analyze_paths([target], baseline_path=default_baseline_path())
    assert not res.findings, "\n".join(f.format() for f in res.findings)
    assert not res.unused_suppressions, \
        "single-file run flagged suppressions as stale without context"


def test_baseline_paths_all_exist(package_modules):
    """Deleted-but-still-baselined files bypass the unused-suppression
    report (their path is never analyzed, so staleness reporting is
    disabled for safety) — catch them here instead."""
    analyzed = {m.path for m in package_modules}
    base = Baseline.load(default_baseline_path())
    missing = {s["path"] for s in base.suppressions} - analyzed
    assert not missing, f"baseline references deleted files: {missing}"


def test_scoped_checker_run_does_not_report_foreign_unused(
        package_modules):
    """`--checker B1-lock-order` runs nothing that could match the
    A-family suppressions — they are out of scope, not stale."""
    from jax_mapping.analysis.lock_discipline import LockOrderChecker

    res = analyze_modules(package_modules,
                          Baseline.load(default_baseline_path()),
                          checkers=[LockOrderChecker()])
    assert res.findings == []
    assert res.unused_suppressions == []


def test_write_baseline_merges_notes_and_out_of_scope_entries(tmp_path):
    """A scoped --write-baseline must not clobber: entries the run
    could not re-observe survive verbatim, and still-live entries keep
    their hand-written notes."""
    import json
    import shutil

    from jax_mapping.analysis.cli import main

    tmp = str(tmp_path / "baseline.json")
    shutil.copy(default_baseline_path(), tmp)
    before = json.load(open(default_baseline_path()))["suppressions"]
    assert main(["--write-baseline", "--baseline", tmp,
                 "--checker", "B1-lock-order"]) == 0
    after = json.load(open(tmp))["suppressions"]
    key = lambda s: (s["checker"], s["path"], s.get("symbol", ""),
                     s.get("code", ""))                          # noqa: E731
    assert {key(s) for s in after} >= {key(s) for s in before}
    notes = {key(s): s.get("note") for s in after}
    assert all(notes[key(s)] == s["note"] for s in before)

    # Unscoped rewrite over the package: same sites, notes intact.
    assert main(["--write-baseline", "--baseline", tmp]) == 0
    rewritten = json.load(open(tmp))["suppressions"]
    assert {key(s) for s in rewritten} == {key(s) for s in before}
    assert all(s.get("note") for s in rewritten)


def test_checker_ids_are_unique_and_complete():
    ids = [c.id for c in all_checkers()]
    assert len(ids) == len(set(ids))
    assert set(ids) == {"A1-host-sync", "A2-jit-hygiene", "A3-dtype-drift",
                        "A4-impure-jit", "B1-lock-order",
                        "B2-callback-lock", "B3-unguarded-write",
                        "C1-revision-order", "C2-snapshot-tear",
                        "C3-device-view", "C4-shape-churn"}


# ---------------------------------------- static graph vs live stack

def test_static_lock_graph_is_acyclic(package_modules):
    """Today every bridge class owns exactly ONE lock, so the static
    intra-class graph is edge-free (cross-object nesting like
    bus._lock -> Subscription._lock is lockwatch's territory). What
    must hold: lock DISCOVERY sees the bridge locks, and whatever
    edges exist never form a cycle."""
    from jax_mapping.analysis import astutil

    found = {f"{cls.name}.{attr}"
             for mod in package_modules
             for cls in astutil.collect_classes(mod)
             for attr in cls.lock_attrs}
    assert {"Bus._lock", "Node._cb_lock", "ThymioBrain._state_lock",
            "MapperNode._state_lock", "Subscription._lock"} <= found, found
    assert build_lock_graph(package_modules).sccs() == []


def test_lockwatch_validates_static_graph_on_live_stack(
        tiny_cfg, package_modules):
    """Drive the real stack with recording locks installed and check the
    runtime acquisition order against the static B1 graph: no runtime
    cycle, and no observed edge may ever be the REVERSE of a static
    edge (that exact pair is a deadlock two threads away).

    Per-node `_cb_lock`s are watched under instance-distinct names —
    they are one `Node._cb_lock` site statically, but distinct runtime
    locks, and folding them together would fake reentrancy."""
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    world = W.empty_arena(64, tiny_cfg.grid.resolution_m)
    st = launch_sim_stack(tiny_cfg, world, n_robots=2, http_port=None,
                          seed=3)
    watch = LockWatch()
    try:
        watch.watch(st.bus, "_lock")                     # "Bus._lock"
        watch.watch(st.brain, "_state_lock")
        watch.watch(st.mapper, "_state_lock")
        for node in (st.sim, st.brain, st.mapper):
            watch.watch(node, "_cb_lock",
                        name=f"Node._cb_lock@{node.name}")
        st.brain.start_exploring()
        st.run_steps(12)
    finally:
        watch.unwatch_all()
        st.shutdown()

    observed = watch.edges()
    assert observed, "no lock nesting observed — the watch is broken"
    assert watch.cycle() is None

    static = build_lock_graph(package_modules).edge_set()
    for a, b in observed:
        assert (b, a) not in static, (
            f"runtime acquires {a} before {b}, but a static site orders "
            f"{b} before {a} — lock-order violation")

    # The union of both views must still be deadlock-free.
    combined = LockGraph(edges={e: None for e in static | observed})
    assert combined.sccs() == []

    # Cross-object edges the static pass cannot see are expected (that
    # is lockwatch's reason to exist) — but they must only ADD order,
    # never contradict it, which the union check above proved.
    watch.check_against_static(static)


def test_lockwatch_poses_match_unwatched_run(tiny_cfg):
    """Watching locks must not perturb the stack's behavior: the same
    seeded run with and without recording proxies lands on identical
    robot poses."""
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    def run(watched: bool):
        world = W.empty_arena(64, tiny_cfg.grid.resolution_m)
        st = launch_sim_stack(tiny_cfg, world, n_robots=1,
                              http_port=None, seed=7)
        watch = LockWatch()
        try:
            if watched:
                watch.watch(st.bus, "_lock")
                watch.watch(st.brain, "_state_lock")
            st.brain.start_exploring()
            st.run_steps(8)
            return np.array(st.brain.poses)
        finally:
            watch.unwatch_all()
            st.shutdown()

    np.testing.assert_array_equal(run(False), run(True))


def test_no_suppressions_in_recovery_or_matcher_modules():
    """ISSUE 5 CI guard: `jax_mapping/recovery/` and the branch-and-
    bound matcher modules (ops/scan_match.py, ops/pyramid.py) carry
    ZERO baseline suppressions — new hazards there must be fixed, not
    baselined."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"].startswith("jax_mapping/recovery/")
              or s["path"] in ("jax_mapping/ops/scan_match.py",
                               "jax_mapping/ops/pyramid.py")]
    assert not banned, (
        "suppressions are not allowed in recovery/ or the matcher "
        f"modules: {banned}")


def test_no_suppressions_in_exploration_modules():
    """ISSUE 6 CI guard, extending the ISSUE 5 pattern: the incremental
    exploration pipeline (`ops/frontier.py`, `ops/costfield.py`,
    `ops/frontier_incremental.py`) carries ZERO baseline suppressions —
    new hazards there must be fixed, not baselined."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"] in ("jax_mapping/ops/frontier.py",
                               "jax_mapping/ops/costfield.py",
                               "jax_mapping/ops/frontier_incremental.py")]
    assert not banned, (
        "suppressions are not allowed in the exploration-pipeline "
        f"modules: {banned}")


# ---------------------------------------- ISSUE 7: hazard-lint v2 gates

#: The grandfathered bridge/ suppression keys at the time the ISSUE 7
#: zero-suppression extension landed — all sanctioned device->host
#: boundary sites (A1) or documented single-writer counters (B3). This
#: set may SHRINK, never grow: a new bridge/ finding is fixed in-tree.
_BRIDGE_GRANDFATHERED = {
    ("A1-host-sync", "jax_mapping/bridge/brain.py"),
    ("B3-unguarded-write", "jax_mapping/bridge/brain.py"),
    ("B3-unguarded-write", "jax_mapping/bridge/mapper.py"),
    ("A1-host-sync", "jax_mapping/bridge/planner.py"),
}


def test_c_family_findings_are_fixed_never_baselined():
    """The ISSUE 7 contract: every C1-C4 finding repo-wide is fixed in
    the tree — the baseline may not carry a single one."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["checker"].startswith("C")]
    assert not banned, f"C-family suppressions are forbidden: {banned}"


def test_no_suppressions_in_serving_or_analysis_modules():
    """Zero-suppression tier extended to serving/ (and analysis/ may
    obviously not suppress itself)."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"].startswith(("jax_mapping/serving/",
                                       "jax_mapping/analysis/"))]
    assert not banned, (
        f"suppressions are not allowed in serving/ or analysis/: "
        f"{banned}")


def test_bridge_suppression_set_is_pinned():
    """bridge/ keeps only its grandfathered (checker, path) pairs; any
    NEW bridge hazard must be fixed, not baselined."""
    base = Baseline.load(default_baseline_path())
    current = {(s["checker"], s["path"]) for s in base.suppressions
               if s["path"].startswith("jax_mapping/bridge/")}
    grew = current - _BRIDGE_GRANDFATHERED
    assert not grew, (
        "bridge/ suppressions grew beyond the grandfathered set — fix "
        f"the new sites in-tree instead: {sorted(grew)}")


def test_no_suppressions_in_scenarios_modules():
    """ISSUE 8 CI guard, extending the zero-suppression tier: the
    scenario engine (`jax_mapping/scenarios/`) and the decay op's home
    (`ops/grid.py` — currently clean) carry ZERO baseline suppressions
    — new hazards in the dynamic-world machinery must be fixed, not
    baselined. (The mapper's decay path rides the separate pinned
    bridge/ grandfathered set, which may shrink but never grow.)"""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"].startswith("jax_mapping/scenarios/")
              or s["path"] == "jax_mapping/ops/grid.py"]
    assert not banned, (
        "suppressions are not allowed in scenarios/ or ops/grid.py: "
        f"{banned}")


def test_no_suppressions_in_fusion_modules():
    """ISSUE 11 CI guard, extending the zero-suppression tier: the
    fused-fusion path (`ops/fuse_kernel.py`) and its home
    (`ops/grid.py`, already pinned by the ISSUE 8 guard) plus the
    sensor kernel it extends carry ZERO baseline suppressions — the
    per-tick floor every robot pays may not baseline its hazards."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"] in ("jax_mapping/ops/fuse_kernel.py",
                               "jax_mapping/ops/grid.py",
                               "jax_mapping/ops/sensor_kernel.py")]
    assert not banned, (
        "suppressions are not allowed in the fusion modules: "
        f"{banned}")


def test_no_suppressions_in_obs_modules():
    """ISSUE 9 CI guard, extending the zero-suppression tier: the
    observability subsystem (`jax_mapping/obs/`) carries ZERO baseline
    suppressions — the layer whose job is surfacing hazards may not
    baseline its own."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"].startswith("jax_mapping/obs/")]
    assert not banned, (
        f"suppressions are not allowed in obs/: {banned}")


def test_no_suppressions_in_tenancy_modules():
    """ISSUE 14 CI guard, extending the zero-suppression tier: the
    mission-multi-tenancy subsystem (`jax_mapping/tenancy/`) carries
    ZERO baseline suppressions — the control plane that multiplexes
    many missions onto one accelerator may not baseline its hazards.
    The prefix deliberately covers the ISSUE 17 containment modules
    too (`tenancy/lanehealth.py`, `tenancy/journal.py`): the code that
    decides quarantine and replays the durable registry is exactly the
    code that runs while a tenant is already sick."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"].startswith("jax_mapping/tenancy/")]
    assert not banned, (
        f"suppressions are not allowed in tenancy/: {banned}")


def test_no_suppressions_in_world_modules():
    """ISSUE 18 CI guard, extending the zero-suppression tier: the
    bounded-memory world subsystem (`jax_mapping/world/`) carries ZERO
    baseline suppressions — the store that evicts, spills and
    rehydrates the live map while serving threads read it may not
    baseline its hazards (the evict-vs-serve pair is exactly where a
    torn read scatters stale walls into a fresh window)."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"].startswith("jax_mapping/world/")]
    assert not banned, (
        f"suppressions are not allowed in world/: {banned}")


def test_no_suppressions_in_coldstart_modules():
    """ISSUE 12 CI guard, extending the zero-suppression tier: the
    warm-restart tier (`io/compile_cache.py`, the staged warm-up
    `resilience/warmup.py`) carries ZERO baseline suppressions — the
    path that runs exactly when the system is recovering from a fault
    may not baseline its hazards."""
    base = Baseline.load(default_baseline_path())
    banned = [s for s in base.suppressions
              if s["path"] in ("jax_mapping/io/compile_cache.py",
                               "jax_mapping/resilience/warmup.py")]
    assert not banned, (
        "suppressions are not allowed in the warm-restart modules: "
        f"{banned}")


def test_protection_map_matches_code(package_modules):
    """Every lock-protection declaration names a real class, its real
    lock attributes, and fields actually assigned in that class — a
    rename cannot silently orphan a row (and with it C2 + racewatch
    coverage)."""
    from jax_mapping.analysis import astutil
    from jax_mapping.analysis.protection import REPO_PROTECTION

    classes = {}
    for mod in package_modules:
        for cls in astutil.collect_classes(mod):
            classes[cls.name] = cls
    for grp in REPO_PROTECTION:
        cls = classes.get(grp.cls)
        assert cls is not None, f"protection map names missing class " \
                                f"{grp.cls}"
        assert grp.lock_attr in cls.lock_attrs, \
            f"{grp.cls} does not own lock {grp.lock_attr}"
        for extra in grp.extra_locks:
            assert extra in cls.lock_attrs, \
                f"{grp.cls} does not own extra lock {extra}"
        assigned = set()
        import ast as _ast
        for meth in cls.methods.values():
            for node in _ast.walk(meth):
                if isinstance(node, _ast.Attribute) \
                        and isinstance(node.ctx, _ast.Store):
                    attr = astutil._self_attr(node)
                    if attr:
                        assigned.add(attr)
        missing = grp.all_fields - assigned
        assert not missing, \
            f"{grp.cls} never assigns declared field(s) {missing}"


def test_cli_github_format_annotations(capsys):
    """`--format github` emits ::error/::warning workflow commands per
    NON-baselined finding and keeps the exit-code contract (clean repo
    with baseline -> no annotations, exit 0; --no-baseline re-exposes
    the accepted sites as annotations, exit 1)."""
    from jax_mapping.analysis.cli import main

    assert main(["--format", "github"]) == 0
    out = capsys.readouterr().out
    assert "::error" not in out and "::warning" not in out

    # Scoped to one checker: the annotation format is checker-agnostic
    # and a single-family pass keeps this test off tier-1's hot path.
    assert main(["--format", "github", "--no-baseline",
                 "--checker", "A1-host-sync"]) == 1
    out = capsys.readouterr().out
    assert "::warning file=jax_mapping/bridge/" in out
    assert ",line=" in out and ",title=A1-host-sync" in out


def test_module_entry_point_runs():
    """`python -m jax_mapping.analysis` mirrors the console script."""
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "jax_mapping.analysis",
         "--list-checkers"],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    assert "C1-revision-order" in r.stdout


# ---------------------------------------- recompile-budget ratchet

def test_compile_budget_entries_above_one_carry_notes():
    """`compile_budget.json` mirrors baseline.json's rules: any entry
    allowing MORE than one compiled variant documents which shapes are
    expected — growth without a justification cannot land."""
    from jax_mapping.analysis.compilebudget import (Budget,
                                                    default_budget_path)

    budget = Budget.load(default_budget_path())
    assert budget.entries, "committed budget is empty"
    noteless = [e["name"] for e in budget.entries
                if e["max"] > 1 and not e.get("note")]
    assert not noteless, (
        f"budget entries above 1 variant without a note: {noteless}")
    assert all(e["max"] >= 1 for e in budget.entries)


def test_bench_trajectory_validates():
    """ISSUE 10 CI wiring: every committed BENCH_*.json parses and
    passes the BenchRecord schema (`bench.py --validate`, run
    in-process — the validator imports no jax). A record the validator
    cannot read is a trajectory hole the --regress gate would silently
    skip."""
    import importlib.util
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_validate", os.path.join(root, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    n, errors = bench.validate_bench_records(root)
    assert n >= 11, f"trajectory shrank? only {n} BENCH_*.json files"
    assert errors == [], "\n".join(errors)
    # The regress gate has a committed trajectory to compare against.
    assert bench.newest_committed_regress(root) is not None


@pytest.mark.slow
def test_cost_ledger_covers_compile_budget():
    """ISSUE 10 acceptance (slow: the ledger AOT-recompiles every
    captured variant, ~seconds per function): a fresh cold-cache
    process runs the canonical scenario under the dispatch profiler
    and the static XLA cost ledger must report FLOPs/bytes for EVERY
    compile-budget-registered function, with variant counts within the
    committed budget — `compilebudget --check --ledger` exits 0."""
    import os
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "jax_mapping.analysis.compilebudget",
         "--check", "--ledger"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, (
        f"cost-ledger/budget violations (exit {r.returncode}):\n"
        f"{r.stdout}\n{r.stderr[-2000:]}")


def test_compile_budget_ratchet_on_canonical_scenario():
    """THE recompile-budget gate: a FRESH process (cold jit caches)
    runs the canonical `AnalysisConfig` scenario and every jitted
    function must compile at most its budgeted variant count — more is
    a recompile regression, a budgeted-but-never-compiled entry is
    stale, an unbudgeted compile needs a conscious entry. The budget
    only ratchets down (see compilebudget.py's module docstring)."""
    import os
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "jax_mapping.analysis.compilebudget",
         "--check"],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, (
        f"recompile-budget violations (exit {r.returncode}):\n"
        f"{r.stdout}\n{r.stderr[-2000:]}")
