"""Fused-fusion parity suite (ISSUE 11).

`GridConfig.fused_fusion` swaps the classify->fold->hash dispatch chain
for the one-pass engines in `ops/fuse_kernel.py`; these tests pin the
bit-parity contract across random seeds, the masked and window paths,
clamp on/off, and the partial-FOV `in_fov` aliasing case — and that
`fused_fusion=False` reproduces the pre-fused chain (sequential
classify+apply) bit-for-bit. Heavy shapes stay out: everything runs the
tiny config (tier-1 wall-clock is the scarce resource)."""

import dataclasses
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import ScanConfig
from jax_mapping.ops import fuse_kernel as FK
from jax_mapping.ops import grid as G
from jax_mapping.ops import sensor_kernel as SK


@pytest.fixture(scope="module")
def pair(tiny_cfg):
    """(classic GridConfig, fused GridConfig, ScanConfig)."""
    g = tiny_cfg.grid
    return (dataclasses.replace(g, fused_fusion=False),
            dataclasses.replace(g, fused_fusion=True),
            tiny_cfg.scan)


def _batch(rng, s, B, spread=0.5):
    ranges = rng.uniform(0.3, 2.8, (B, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    ranges[rng.random((B, s.padded_beams)) < 0.04] = 0.0   # dropouts
    poses = np.stack([rng.uniform(-spread, spread, B),
                      rng.uniform(-spread, spread, B),
                      rng.uniform(-3, 3, B)], axis=1).astype(np.float32)
    return jnp.asarray(ranges), jnp.asarray(poses)


def test_fused_scattered_bit_identical(pair, monkeypatch):
    """fuse_scans / fuse_scans_masked: fused vs classic grids are
    bit-identical across seeds and batch sizes (the per-scan op order is
    unchanged — only the fusion structure moved)."""
    gc, gf, s = pair
    # A small sub-chunk keeps the boundary-crossing case (B=13 -> one
    # full sub-chunk + remainder) at tiny compile cost; B values are
    # unique to this test so the patched constant traces fresh.
    monkeypatch.setattr(FK, "_STREAM_CHUNK", 8)
    for seed, B in ((1, 5), (2, 13)):
        rng = np.random.default_rng(seed)
        rd, pd = _batch(rng, s, B)
        grid0 = G.empty_grid(gc)
        np.testing.assert_array_equal(
            np.asarray(G.fuse_scans(gc, s, grid0, rd, pd)),
            np.asarray(G.fuse_scans(gf, s, grid0, rd, pd)))
        mask = jnp.asarray(rng.random(B) < 0.6)
        np.testing.assert_array_equal(
            np.asarray(G.fuse_scans_masked(gc, s, grid0, rd, pd, mask)),
            np.asarray(G.fuse_scans_masked(gf, s, grid0, rd, pd, mask)))


def test_fused_clamp_off_bit_identical(pair):
    """scan_deltas_full (clamp=False — the fleet psum-merge path)."""
    gc, gf, s = pair
    rd, pd = _batch(np.random.default_rng(3), s, 5)
    np.testing.assert_array_equal(
        np.asarray(G.scan_deltas_full(gc, s, rd, pd)),
        np.asarray(G.scan_deltas_full(gf, s, rd, pd)))


def test_fused_window_bit_identical_within_subchunk(pair):
    """Windows of <= _STREAM_CHUNK scans (every default-batch_scans
    window, every tiny-config window, and the regress-gate fuse_tiny
    workload) are bit-identical fused vs classic — the streaming
    accumulate IS the classic vmap+sum there."""
    gc, gf, s = pair
    assert FK._STREAM_CHUNK >= 16, \
        "default batch_scans windows must stay single-sub-chunk"
    rng = np.random.default_rng(4)
    for B in (2, 4, 16):
        rd, pd = _batch(rng, s, B, spread=0.1)
        grid0 = G.empty_grid(gc)
        np.testing.assert_array_equal(
            np.asarray(G.fuse_scans_window(gc, s, grid0, rd, pd)),
            np.asarray(G.fuse_scans_window(gf, s, grid0, rd, pd)))


def test_fused_window_reassociation_is_last_ulp(pair, monkeypatch):
    """Windows over _STREAM_CHUNK scans reassociate the cross-scan delta
    sum at sub-chunk boundaries (the documented window_delta chunk-split
    caveat) — bounded to last-ulp, never a semantic difference."""
    gc, gf, s = pair
    monkeypatch.setattr(FK, "_STREAM_CHUNK", 8)
    rd, pd = _batch(np.random.default_rng(5), s, 19, spread=0.1)
    grid0 = G.empty_grid(gc)
    a = np.asarray(G.fuse_scans_window(gc, s, grid0, rd, pd))
    b = np.asarray(G.fuse_scans_window(gf, s, grid0, rd, pd))
    # Numeric-only bound: a cell landing EXACTLY on an occupancy
    # threshold (2*occ - 3*|free| = 0.5) can legitimately flip class
    # under any reassociation — the same caveat the classic path's own
    # >_MAX_B_PER_CALL chunk splits carry.
    np.testing.assert_allclose(a, b, atol=2e-6)


def test_fused_partial_fov_aliasing_case(pair):
    """Partial-FOV scanner (n_beams * increment = pi): bearings behind
    the scanner must NOT alias onto real beams — the `in_fov` branch —
    and the fused path must agree with classic bit-for-bit there."""
    gc, gf, s = pair
    half = ScanConfig(n_beams=s.n_beams, padded_beams=s.padded_beams,
                      angle_increment_rad=math.pi / s.n_beams,
                      range_max_m=s.range_max_m)
    rd, pd = _batch(np.random.default_rng(6), s, 6)
    grid0 = G.empty_grid(gc)
    a = np.asarray(G.fuse_scans(gc, half, grid0, rd, pd))
    b = np.asarray(G.fuse_scans(gf, half, grid0, rd, pd))
    np.testing.assert_array_equal(a, b)
    assert (a != 0).any(), "half-FOV batch added no evidence?"


def test_fused_fusion_false_is_pre_fused_chain(pair):
    """The knob's OFF side: `fused_fusion=False` reproduces the pre-PR
    dispatch chain bit-for-bit — pinned against a hand-rolled
    sequential classify->apply oracle of the original semantics."""
    gc, _, s = pair
    rng = np.random.default_rng(7)
    rd, pd = _batch(rng, s, 4)
    grid0 = G.empty_grid(gc)
    oracle = grid0
    for i in range(rd.shape[0]):
        origin = G.patch_origin(gc, pd[i, :2])
        delta = G.classify_patch(gc, s, rd[i], pd[i], origin)
        oracle = G.apply_patch(gc, oracle, delta, origin, clamp=True)
    np.testing.assert_array_equal(
        np.asarray(oracle),
        np.asarray(G.fuse_scans(gc, s, grid0, rd, pd)))


def test_pallas_fused_window_matches_classic_composition(tiny_cfg):
    """The Mosaic fused-apply kernel (interpret mode off-TPU): resident
    accumulate + clamped patch fold is bit-identical to the classic
    `apply_patch(cur, window_delta(...))` composition."""
    g, s = tiny_cfg.grid, tiny_cfg.scan
    rng = np.random.default_rng(8)
    ranges = rng.uniform(0.3, 2.8, (5, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    poses = np.zeros((5, 3), np.float32)
    poses[:, 2] = np.linspace(0, 2, 5)
    rd, pd = jnp.asarray(ranges), jnp.asarray(poses)
    origin = G.patch_origin(g, pd[:, :2].mean(0))
    base = G.fuse_scans(g, s, G.empty_grid(g), rd[:2], pd[:2])
    cur = jax.lax.dynamic_slice(base, (origin[0], origin[1]),
                                (g.patch_cells, g.patch_cells))
    fused = np.asarray(FK._window_apply_pallas(g, s, cur, rd, pd, origin))
    classic = np.asarray(jnp.clip(
        cur + SK.window_delta(g, s, rd, pd, origin),
        g.logodds_min, g.logodds_max))
    np.testing.assert_array_equal(fused, classic)


def test_window_touched_one_pass(pair):
    """fuse_scans_window_touched: same grid as fuse_scans_window, hashes
    equal to tile_hashes over the touched region of the NEW grid, and
    every hash-detected change lies inside the reported tile box."""
    _, gf, s = pair
    t = 64                                # tiny serving tile edge
    rd, pd = _batch(np.random.default_rng(9), s, 4, spread=0.1)
    grid0 = G.empty_grid(gf)
    new, tile_rc, hashes = FK.fuse_scans_window_touched(
        gf, s, t, grid0, rd, pd)
    np.testing.assert_array_equal(
        np.asarray(new), np.asarray(G.fuse_scans_window(gf, s, grid0,
                                                        rd, pd)))
    K = FK.patch_span_tiles(gf, t)
    r0, c0 = int(tile_rc[0]), int(tile_rc[1])
    region = np.asarray(new)[r0 * t:(r0 + K) * t, c0 * t:(c0 + K) * t]
    np.testing.assert_array_equal(
        np.asarray(hashes),
        np.asarray(G.tile_hashes(jnp.asarray(region), t)))
    # Validated-superset: every tile whose full-grid hash changed is
    # inside the touched box (the hash stays the criterion downstream).
    h_old = np.asarray(G.tile_hashes(grid0, t))
    h_new = np.asarray(G.tile_hashes(new, t))
    changed = np.argwhere(np.any(h_old != h_new, axis=-1))
    assert len(changed), "window fuse changed no tiles?"
    for ty, tx in changed:
        assert r0 <= ty < r0 + K and c0 <= tx < c0 + K, (ty, tx)


def test_fuse_scans_touched_mask_is_validated_superset(pair):
    """Scattered fused fold's touched-tile side output: covers every
    hash-detected change; masked-out scans mark nothing."""
    _, gf, s = pair
    t = 64
    rng = np.random.default_rng(10)
    rd, pd = _batch(rng, s, 5, spread=0.4)
    # Scan 4 sits far away AND is masked out: its tiles must stay clean.
    pd = pd.at[4, :2].set(jnp.asarray([4.5, 4.5]))
    mask = jnp.asarray([True, True, True, True, False])
    grid0 = G.empty_grid(gf)
    out, touched = FK.fuse_scans_touched(gf, s, t, grid0, rd, pd, mask)
    np.testing.assert_array_equal(
        np.asarray(out),
        np.asarray(G.fuse_scans_masked(gf, s, grid0, rd, pd, mask)))
    touched = np.asarray(touched)
    h_old = np.asarray(G.tile_hashes(grid0, t))
    h_new = np.asarray(G.tile_hashes(out, t))
    changed = np.any(h_old != h_new, axis=-1)
    assert changed.any()
    assert not (changed & ~touched).any(), "hash change outside the mask"
    # Masked-out scans mark nothing: an all-masked batch reports a
    # clean tile mask (and an unchanged grid).
    none_out, none_touched = FK.fuse_scans_touched(
        gf, s, t, grid0, rd, pd, jnp.zeros(5, jnp.bool_))
    assert not np.asarray(none_touched).any()
    np.testing.assert_array_equal(np.asarray(none_out),
                                  np.asarray(grid0))


def test_touched_tile_box_covers_patch_extents(tiny_cfg):
    g = tiny_cfg.grid
    t = 64
    poses = jnp.asarray([[0.3, -0.4], [0.35, -0.38]], jnp.float32)
    box = np.asarray(FK.touched_tile_box(g, t, poses, jnp.int32(0)))
    tr0, tr1, tc0, tc1 = (int(v) for v in box)
    for xy in np.asarray(poses):
        o = np.asarray(G.patch_origin(g, jnp.asarray(xy)))
        assert tr0 <= o[0] // t and (o[0] + g.patch_cells - 1) // t <= tr1
        assert tc0 <= o[1] // t and (o[1] + g.patch_cells - 1) // t <= tc1
    nt = g.size_cells // t
    assert 0 <= tr0 <= tr1 < nt and 0 <= tc0 <= tc1 < nt
    # Travel slack only widens the box (traced pad: same compiled
    # variant) — on the tiny 4x4 tile grid the align-padded base box
    # may already saturate, so monotonicity is the assertable property.
    wide = np.asarray(FK.touched_tile_box(g, t, poses, jnp.int32(130)))
    assert wide[0] <= tr0 and wide[1] >= tr1
    assert wide[2] <= tc0 and wide[3] >= tc1


def test_touched_tile_box_absorbs_origin_alignment_snap():
    """Production-alignment regression (align_cols=128): `patch_origin`
    ROUNDS to the alignment, so a pose marginally past an endpoint can
    snap its patch a full align step beyond the endpoints' snapped
    origins — the box must absorb the quantum (the host marker's
    align/2 padding, needed in full here because both compared values
    are snapped). Sweep probe poses within the endpoint slack and
    assert every probe patch's tiles stay inside the box."""
    from jax_mapping.config import GridConfig
    g = GridConfig()                       # 4096^2, align_cols=128
    t = 256
    res = g.resolution_m
    base = np.array([3.17, -2.41], np.float32)
    ends = jnp.asarray([base, base + [0.05, 0.02]], jnp.float32)
    box = np.asarray(FK.touched_tile_box(g, t, ends, jnp.int32(0)))
    tr0, tr1, tc0, tc1 = (int(v) for v in box)
    for drow in (-FK._ENDPOINT_SLACK_CELLS, 0, FK._ENDPOINT_SLACK_CELLS):
        for dcol in (-FK._ENDPOINT_SLACK_CELLS, 0,
                     FK._ENDPOINT_SLACK_CELLS):
            probe = jnp.asarray(base + [dcol * res, drow * res])
            o = np.asarray(G.patch_origin(g, probe))
            assert tr0 <= o[0] // t and \
                (o[0] + g.patch_cells - 1) // t <= tr1, (drow, dcol)
            assert tc0 <= o[1] // t and \
                (o[1] + g.patch_cells - 1) // t <= tc1, (drow, dcol)


def test_bucketed_matches_masked_and_bounds_variants(pair):
    """fuse_scans_bucketed == fuse_scans_masked bitwise (padding is
    exact), and batch sizes sharing a bucket ({2^k} ∪ {3·2^(k-1)}, the
    PR 6 crop-span set) share ONE compiled variant — the compile-budget
    contract."""
    _, gf, s = pair
    assert [G._batch_bucket(n) for n in (1, 2, 3, 4, 5, 6, 7, 9, 192)] \
        == [1, 2, 3, 4, 6, 6, 8, 12, 192]
    rng = np.random.default_rng(11)
    for B in (3, 9):
        rd, pd = _batch(rng, s, B)
        mask = jnp.asarray(rng.random(B) < 0.7)
        grid0 = G.empty_grid(gf)
        np.testing.assert_array_equal(
            np.asarray(G.fuse_scans_masked(gf, s, grid0, rd, pd, mask)),
            np.asarray(G.fuse_scans_bucketed(gf, s, grid0, rd, pd,
                                             mask)))
    # Warm bucket 6 (B=5), then B=6 must reuse it: zero new variants.
    rd, pd = _batch(rng, s, 5)
    G.fuse_scans_bucketed(gf, s, G.empty_grid(gf), rd, pd)
    n0 = G.fuse_scans_masked._cache_size()
    rd, pd = _batch(rng, s, 6)
    G.fuse_scans_bucketed(gf, s, G.empty_grid(gf), rd, pd)
    assert G.fuse_scans_masked._cache_size() == n0, \
        "B=6 did not reuse the bucket-6 variant"


def test_remainder_tail_is_bucketed_and_exact(pair, monkeypatch):
    """_classify_fold's remainder tail pads to its bucket with mask=0
    rows: bit-identical to the unbucketed fold, for both the classic
    and fused chunk bodies (B=13 through chunk 8 -> rem 5 -> bucket 6
    -> one padded mask=0 row). The pad rides the mask machinery, so the
    masked path is covered by the same run."""
    gc, gf, s = pair
    rd, pd = _batch(np.random.default_rng(12), s, 13)
    grid0 = G.empty_grid(gc)
    want = {id(gc): None, id(gf): None}
    for g in (gc, gf):
        want[id(g)] = np.asarray(G._classify_fold(g, s, grid0, rd, pd,
                                                  None, clamp=True))
    monkeypatch.setattr(G, "_FUSE_CHUNK", 8)
    for g in (gc, gf):
        got = np.asarray(G._classify_fold(g, s, grid0, rd, pd, None,
                                          clamp=True))
        np.testing.assert_array_equal(want[id(g)], got)
