"""Scenario engine (ISSUE 8): dynamic worlds, map healing, rendezvous
merges, lifelong missions.

Tier-1 keeps ONE module-scoped scenario mission (the PR 7 shared-stack
pattern — every smoke assertion reads its artifacts instead of
launching its own stack) plus pure-unit coverage; the heavyweights
(rendezvous fleet merge, lifelong soak, bit-inertness property sweep)
are `slow`.
"""

import json
import os

import numpy as np
import pytest

from jax_mapping.config import (DecayConfig, DevProfConfig, ObsConfig,
                                SloObjective, tiny_config)
from jax_mapping.resilience.faultplan import (
    FaultEvent, FaultPlan, KINDS, WORLD_KINDS, random_plan,
)
from jax_mapping.scenarios import (
    DoorSpec, WorldDynamics, day_plan, launch_scenario_stack,
    merge_fleets, merged_frontier_assignment, run_lifelong_mission,
    se2_apply, se2_from_pair, transform_state,
)
from jax_mapping.sim import world as W


# ------------------------------------------------------------- unit: dynamics

def test_world_dynamics_compose_and_restore():
    world, doors = W.arena_with_door(96, 0.05)
    dyn = WorldDynamics(world, 0.05, doors=doors, seed=3)
    d = doors[0]
    base = dyn.world_at(0)
    assert np.array_equal(base, world)
    dyn.set_door("door0", True)
    closed = dyn.world_at(1)
    assert closed[d["r0"]:d["r1"], d["c0"]:d["c1"]].all()
    dyn.set_door("door0", False)
    assert np.array_equal(dyn.world_at(2), world)
    # Crowds: deterministic orbit, blob present while active, gone after.
    dyn.set_crowd(0, 0.25)
    c5 = dyn.crowd_center(0, 5)
    assert dyn.crowd_center(0, 5) == c5          # pure in (seed, cid, t)
    assert dyn.crowd_center(0, 6) != c5          # and it MOVES
    assert dyn.world_at(5).sum() > world.sum()
    dyn.set_crowd(0, None)
    assert np.array_equal(dyn.world_at(7), world)
    # The hot-path gate: one recompose after a toggle, quiet afterward.
    assert dyn.world_if_changed(8) is None
    dyn.set_door("door0", True)
    assert dyn.world_if_changed(9) is not None
    assert dyn.world_if_changed(10) is None      # no crowd, no toggle
    dyn.set_crowd(1, 0.2)
    assert dyn.world_if_changed(11) is not None  # crowds move every step
    assert dyn.world_if_changed(12) is not None


def test_world_dynamics_rejects_bad_registrations():
    world, _ = W.arena_with_door(96, 0.05)
    with pytest.raises(ValueError):
        WorldDynamics(world, 0.05, doors=[{"name": "d", "r0": 5, "r1": 5,
                                           "c0": 0, "c1": 2}])
    with pytest.raises(ValueError):
        WorldDynamics(world, 0.05,
                      doors=[{"name": "d", "r0": 0, "r1": 200,
                              "c0": 0, "c1": 2}])
    dyn = WorldDynamics(world, 0.05,
                        doors=[DoorSpec("d", 1, 3, 1, 3)])
    with pytest.raises(ValueError):
        dyn.set_door("nope", True)


def test_rooms_with_doors_reports_real_gaps():
    world, doors = W.rooms_with_doors(96, 0.05, seed=1)
    assert np.array_equal(W.rooms_world(96, 0.05, seed=1), world)
    assert len(doors) == 4
    for d in doors:
        gap = world[d["r0"]:d["r1"], d["c0"]:d["c1"]]
        # Mostly open: a LATER crossing wall may clip a gap's edge (the
        # generator's historical behavior, kept bit-identical), but the
        # reported rectangle must be a real opening.
        assert (~gap).mean() >= 0.5


# ------------------------------------------- unit: FaultPlan world kinds

class _FakeSim:
    """Records the set_door/set_crowd boundary like a SimNode would."""

    def __init__(self, dyn):
        self.dyn = dyn

    def set_door(self, name, closed):
        self.dyn.set_door(name, closed)

    def set_crowd(self, cid, radius):
        self.dyn.set_crowd(cid, radius)


class _FakeStack:
    def __init__(self, dyn):
        self.sim = _FakeSim(dyn)
        self.bus = None


def _dyn():
    world, doors = W.arena_with_door(96, 0.05)
    return WorldDynamics(world, 0.05, doors=doors, seed=0)


def test_world_kinds_registered_and_validated():
    assert WORLD_KINDS <= KINDS
    with pytest.raises(ValueError):
        FaultEvent(step=1, kind="door_close")        # needs a door name
    with pytest.raises(ValueError):
        FaultEvent(step=1, kind="crowd")             # needs a radius


def test_overlapping_door_windows_refcount():
    """Two overlapping door_close windows compose: the first clear must
    NOT re-open a door the second window still holds shut — the
    partition refcount rule applied to the world."""
    dyn = _dyn()
    stack = _FakeStack(dyn)
    plan = FaultPlan([
        FaultEvent(step=2, kind="door_close", name="door0", duration=6),
        FaultEvent(step=4, kind="door_close", name="door0", duration=10),
    ])
    for t in range(20):
        plan.apply(stack, t)
        closed = dyn.snapshot()["doors"].get("door0", False)
        if 2 <= t < 14:
            assert closed, f"door open at t={t} inside a held window"
        elif t >= 14:
            assert not closed, f"door closed at t={t} after last clear"
    assert plan.done()


def test_overlapping_crowd_windows_run_worst_radius():
    dyn = _dyn()
    stack = _FakeStack(dyn)
    plan = FaultPlan([
        FaultEvent(step=1, kind="crowd", robot=0, duration=8, value=0.2),
        FaultEvent(step=3, kind="crowd", robot=0, duration=2, value=0.4),
    ])
    radii = {}
    for t in range(14):
        plan.apply(stack, t)
        radii[t] = dyn.snapshot()["crowds"].get(0)
    assert radii[1] == 0.2
    assert radii[3] == 0.4                       # worst active wins
    assert radii[5] == 0.2                       # big window cleared
    assert radii[9] is None                      # all clear
    assert plan.done()


def test_random_plan_samples_world_kinds_with_sane_magnitudes():
    doors = ["door0", "door1"]
    seen = set()
    for seed in range(12):
        plan = random_plan(200, n_faults=10, seed=seed, n_robots=2,
                           door_names=doors, n_crowds=2)
        occupied = []
        for e in plan.events:
            seen.add(e.kind)
            if e.kind == "door_close":
                assert e.name in doors
            if e.kind == "crowd":
                assert 0.15 <= e.value <= 0.4
                assert e.robot in (0, 1)
        # Same-resource overlap rejection still holds with the new kinds.
        from jax_mapping.resilience.faultplan import _fault_resource
        for e in plan.events:
            res = _fault_resource(e.kind, e.robot, e.name)
            span = (res, e.step, e.step + e.duration)
            for r, s, t in occupied:
                if r == res:
                    assert not (e.step <= t and s <= span[2]), \
                        f"overlap on {res}"
            occupied.append(span)
        # Determinism: the schedule is a pure function of the seed.
        twin = random_plan(200, n_faults=10, seed=seed, n_robots=2,
                           door_names=doors, n_crowds=2)
        assert twin.events == plan.events
    assert "door_close" in seen and "crowd" in seen


def test_random_plan_default_args_exclude_world_kinds():
    """Callers that never registered doors/crowds get the historical
    sampler exactly (no world kind can fire against a stack with no
    WorldDynamics attached)."""
    for seed in range(6):
        plan = random_plan(120, n_faults=8, seed=seed, n_robots=2)
        assert all(e.kind not in WORLD_KINDS for e in plan.events)


# ---------------------------------------------------- unit: decay op

def test_decay_grid_shrinks_and_caps():
    import jax.numpy as jnp
    from jax_mapping.ops import grid as G
    g = jnp.asarray(np.asarray([[4.0, -4.0], [0.5, 0.0]], np.float32))
    out = np.asarray(G.decay_grid(g, 0.9, 2.0))
    np.testing.assert_allclose(out, [[2.0, -2.0], [0.45, 0.0]],
                               rtol=1e-6)
    # factor 1.0 + a loose cap = identity (the knobs are independent).
    out2 = np.asarray(G.decay_grid(g, 1.0, 4.0))
    np.testing.assert_array_equal(out2, np.asarray(g))


# ---------------------------------------------------- unit: rendezvous math

def test_se2_round_trip_and_pair_recovery(rng):
    for _ in range(20):
        T = rng.uniform(-2, 2, 3).astype(np.float32)
        p = rng.uniform(-3, 3, 3).astype(np.float32)
        q = se2_apply(T, p)
        T2 = se2_from_pair(q, p)
        np.testing.assert_allclose(T2[:2], T[:2], atol=1e-5)
        dth = (T2[2] - T[2] + np.pi) % (2 * np.pi) - np.pi
        assert abs(dth) < 1e-5


def test_transform_state_and_merge_fleets():
    """Merge math on synthetic fleets: B's states transformed by T end
    up in A's frame, every merged state aliases ONE grid, and the
    matched robot's graph carries the anchor edge at loop-grade
    weight."""
    import jax.numpy as jnp
    from jax_mapping.models import slam as S
    from jax_mapping.ops import posegraph as PG

    cfg = tiny_config()
    T = np.asarray([0.5, -0.25, 0.4], np.float32)
    sa = [S.init_state(cfg, pose0=jnp.asarray([0.1 * i, 0.0, 0.0]))
          for i in range(2)]
    sb = []
    for i in range(2):
        st = S.init_state(cfg, pose0=jnp.asarray([0.0, 0.1 * i, 0.2]))
        g = st.graph
        for k in range(3):
            g = PG.add_pose(g, jnp.asarray([0.1 * k, 0.1 * i, 0.2],
                                           jnp.float32))
        sb.append(st._replace(graph=g))

    moved = transform_state(sb[0], T)
    np.testing.assert_allclose(
        np.asarray(moved.pose), se2_apply(T, np.asarray(sb[0].pose)),
        atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(moved.graph.poses[:3]),
        se2_apply(T, np.asarray(sb[0].graph.poses[:3])), atol=1e-5)

    verified = se2_apply(T, np.asarray(sb[1].graph.poses[2]))
    grid, merged = merge_fleets(cfg, sa, sb, T, anchor=(1, verified))
    assert len(merged) == 4
    for st in merged:
        assert st.grid is grid                   # one shared world
    # The anchor edge landed on the matched robot's graph tip at a
    # weight that clears the thin_keyframes strong-edge threshold.
    g1 = merged[3].graph
    n_edges = int(g1.n_edges)
    assert n_edges >= 1
    assert float(g1.edge_weight[n_edges - 1, 2]) > 100.0


def test_anchor_tip_noop_on_short_graphs():
    from jax_mapping.models import slam as S
    from jax_mapping.ops import posegraph as PG
    cfg = tiny_config()
    g = S.init_state(cfg).graph
    assert int(PG.anchor_tip(g, np.zeros(3, np.float32)).n_edges) == 0


# ---------------------------------------------------- unit: checkpoint GC

def _save(path, val, retain):
    from jax_mapping.io.checkpoint import save_checkpoint
    save_checkpoint(path, {"v": np.full(4, val)},
                    retain_generations=retain)


def test_checkpoint_generation_retention_bounded(tmp_path):
    """A day of rotation cadence stays bounded: K total generations on
    disk, newest-first fallback order, default K=2 byte-identical to
    the historical current + .prev pair."""
    from jax_mapping.io.checkpoint import (
        generation_paths, load_checkpoint_with_fallback)
    p = str(tmp_path / "auto.npz")
    for i in range(40):                          # "day-long" cadence
        _save(p, i, retain=4)
        assert len(os.listdir(tmp_path)) <= 4
    gens = generation_paths(p)
    assert len(gens) == 2
    st, _, used = load_checkpoint_with_fallback(
        p, {"v": np.zeros(4, np.int64)})
    assert used == p and st["v"][0] == 39
    # Default retain=2: exactly the historical pair, no numbered files.
    q = str(tmp_path / "plain.npz")
    for i in range(6):
        _save(q, i, retain=2)
    names = sorted(n for n in os.listdir(tmp_path) if "plain" in n)
    assert names == ["plain.npz", "plain.prev.npz"]
    with pytest.raises(ValueError):
        _save(q, 0, retain=1)


def test_checkpoint_gc_never_deletes_newest_intact_generation(tmp_path):
    """Corruption-safety: with current AND .prev rotten, the newest
    intact numbered generation survives GC and the fallback chain
    resumes from it."""
    from jax_mapping.io.checkpoint import (
        generation_paths, load_checkpoint_with_fallback,
        previous_checkpoint_path)
    p = str(tmp_path / "auto.npz")
    for i in range(8):
        _save(p, i, retain=4)
    for f in (p, previous_checkpoint_path(p)):
        with open(f, "r+b") as fh:
            fh.truncate(12)                      # power-loss rot
    st, _, used = load_checkpoint_with_fallback(
        p, {"v": np.zeros(4, np.int64)})
    assert ".gen" in used and st["v"][0] == 5
    # Another save GCs — but must spare that only-intact generation.
    _save(p, 99, retain=4)
    assert any(".gen" in g for g in generation_paths(p))
    st, _, _ = load_checkpoint_with_fallback(
        p, {"v": np.zeros(4, np.int64)})
    assert st["v"][0] == 99


# ---------------------------------------------------- unit: client epoch

def test_delta_client_epoch_resync_vs_regression():
    """Within one epoch a revision regression is still a protocol
    error; an epoch advance resets the cache for a full resync
    instead."""
    from jax_mapping.serving.client import (DeltaMapClient,
                                            RevisionRegression)
    c = DeltaMapClient("http://x")
    assert not c._note_epoch({"epoch": 0})       # first sighting adopts
    c.revision = 10
    c.mosaics = {0: np.zeros((4, 4), np.uint8)}
    with pytest.raises(RevisionRegression):
        c.apply({"revision": 3, "since": 10, "tiles": [],
                 "tile_cells": 4, "levels": []})
    assert c._note_epoch({"epoch": 1})           # restart: resync, not raise
    assert c.revision == -1 and not c.mosaics and c.n_epoch_resyncs == 1
    assert not c._note_epoch({"epoch": 1})


# =================================================== the shared mission

#: One module-scoped scenario mission (PR 7 shared-stack budget
#: pattern): a door closes and is mapped, re-opens and heals under
#: decay, a crowd passes through, the mapper is killed and supervisor-
#: resumed mid-mission, and a delta client polls across all of it.
_DOOR_CLOSE_AT, _DOOR_STEPS = 4, 16
_KILL_AT = 48
_MISSION_STEPS = 72


@pytest.fixture(scope="module")
def scenario_mission(tmp_path_factory):
    import jax.numpy as jnp
    from jax_mapping.ops import frontier as F
    from jax_mapping.ops import grid as G
    from jax_mapping.serving.client import DeltaMapClient

    from jax_mapping.obs.recorder import flight_recorder

    cfg = tiny_config().replace(
        decay=DecayConfig(enabled=True, every_n_ticks=8, factor=0.9,
                          evidence_cap=1.5),
        # Causal tracing ON for the shared mission (ISSUE 9 piggyback):
        # the chaos mission doubles as the trace-propagation and
        # recorder-coverage surface — obs is bit-inert, so every
        # pre-obs assertion on this stack holds unchanged. ISSUE 10
        # extends the piggyback: the dispatch profiler rides the same
        # mission (devprof is equally bit-inert), making this stack the
        # live surface for dispatch attribution, /status.perf, the
        # /metrics device families and the steady-state recompile
        # guard — no new tier-1 stack launch.
        # ISSUE 15 piggyback: the freshness tier rides the same
        # mission (pipeline ledger + SLO engine are bit-inert like the
        # rest of obs). The staleness objective is DELIBERATELY tight:
        # the delta client polls once mid-mission and once at the end,
        # so served staleness grows ~2 revisions/step in between and
        # must fire exactly one burn-rate alert at a deterministic
        # step; the post-kill restart serves a fresh epoch's SMALLER
        # revisions (staleness goes negative against the old delivered
        # mark), which is what clears it.
        obs=ObsConfig(enabled=True,
                      devprof=DevProfConfig(enabled=True),
                      slo=(SloObjective(name="staleness",
                                        metric="tile_staleness_revs",
                                        threshold=30.0,
                                        fast_window_ticks=8,
                                        slow_window_ticks=24,
                                        fast_burn=0.5,
                                        slow_burn=0.25),)))
    world, doors = W.arena_with_door(96, cfg.grid.resolution_m)
    td = str(tmp_path_factory.mktemp("scenario_ckpt"))
    rec_mark = flight_recorder.mark()
    st = launch_scenario_stack(cfg, world, doors=doors, n_robots=2,
                               realtime=False, seed=0, http_port=0,
                               checkpoint_dir=td)
    st.brain.start_exploring()
    st.brain.reconnect_period_s = 0.0
    plan = FaultPlan([
        FaultEvent(step=_DOOR_CLOSE_AT, kind="door_close", name="door0",
                   duration=_DOOR_STEPS),
        FaultEvent(step=26, kind="crowd", robot=0, duration=10,
                   value=0.25),
        FaultEvent(step=_KILL_AT, kind="kill_node", name="jax_mapper"),
    ], seed=0)
    st.attach_fault_plan(plan)

    d = doors[0]
    off = (cfg.grid.size_cells - world.shape[0]) // 2
    rect = (d["r0"] + off, d["r1"] + off, d["c0"] + off, d["c1"] + off)

    client = DeltaMapClient(f"http://127.0.0.1:{st.api.port}")

    # Degraded-serving window probe (ISSUE 12 piggyback): the staged
    # restart's warmup_hook fires INSIDE the warming stage — the old
    # node destroyed, the new one not yet bound — which is exactly the
    # window /status and /tiles must answer from the prior epoch with
    # state=warming instead of blocking. handle() direct: no socket
    # round-trip, same handler path.
    warm_probe = {}

    def _probe_warming_window(stack):
        warm_probe["status"] = json.loads(
            stack.api.handle("/status")[2])
        warm_probe["tiles"] = json.loads(
            stack.api.handle("/tiles?since=-1")[2])
        warm_probe["warmup_state"] = stack.warmup.state()
        warm_probe["old_epoch"] = stack.mapper.restart_epoch

    st.warmup_hook = _probe_warming_window

    st.run_steps(_DOOR_CLOSE_AT + _DOOR_STEPS - 2)   # door still closed
    client.poll()
    pre_restart_epoch = client.epoch
    grid_closed = np.array(np.asarray(st.mapper.merged_grid()),
                           copy=True)
    st.run_steps(_MISSION_STEPS - (_DOOR_CLOSE_AT + _DOOR_STEPS - 2))
    grid_end = np.array(np.asarray(st.mapper.merged_grid()), copy=True)
    client.poll()
    revision_at_final_poll = st.mapper.serving_revision()

    # Final served surface + a consistent incremental-pipeline probe.
    gray_end = np.asarray(G.to_gray(cfg.grid, st.mapper.merged_grid()))
    m = st.mapper
    with m._state_lock:
        poses = np.stack([np.asarray(s.pose) for s in m.states])
        lo = m.shared_grid
        rev = m.map_revision
        tile_rev = m._tile_rev.copy()
    pipe = m._frontier_incremental()
    pub = None if pipe is None else pipe.compute(lo, poses, tile_rev,
                                                 rev)
    fr_full = F.compute_frontiers(cfg.frontier, cfg.grid, lo,
                                  jnp.asarray(poses))

    # Observability artifacts (ISSUE 9), captured BEFORE the racewatch
    # toggling below adds nondeterministic traffic: the tracer's span
    # stream, the mission-scoped flight-recorder stream, and the HTTP
    # plane's /metrics + /trace documents (handle() direct — no socket
    # round-trip needed for exposition assertions).
    spans = st.tracer.spans_since(0)
    recorder_events = flight_recorder.events_since(rec_mark)
    metrics_text = st.api.handle("/metrics")[2].decode()
    trace_resp = st.api.handle("/trace?since=0")
    # Freshness tier (ISSUE 15): the SLO picture and a /tiles probe
    # (Server-Timing revision-age header) captured with the other
    # quantitative artifacts.
    slo_status = json.loads(st.api.handle("/status")[2]).get("slo")
    tiles_probe = st.api.handle("/tiles?since=-1")

    # Racewatch over the scenario engine's lock (ISSUE 8 satellite):
    # a side thread hammers the door/snapshot boundary while the step
    # thread composes worlds — Eraser refinement must converge every
    # watched WorldDynamics field on the DECLARED lock with zero
    # reports. Runs AFTER every quantitative artifact is captured, so
    # the nondeterministic toggling cannot perturb the assertions.
    import threading
    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch
    dyn = st.sim._world_dyn
    watch = RaceWatch()
    try:
        watch.watch_object(dyn, groups_by_class()["WorldDynamics"][0],
                           name="dyn")
        stop = threading.Event()

        def toggler():
            flip = True
            while not stop.is_set():
                st.sim.set_door("door0", flip)
                flip = not flip
                dyn.snapshot()
                stop.wait(0.002)

        t = threading.Thread(target=toggler)
        t.start()
        st.run_steps(6)
        stop.set()
        t.join(timeout=10)
    finally:
        watch.unwatch_all()
    st.sim.set_door("door0", False)
    race_reports = watch.reports()
    race_states = watch.field_states()

    art = {
        "cfg": cfg, "stack": st, "plan": plan, "rect": rect,
        "grid_closed": grid_closed, "grid_end": grid_end,
        "client": client, "pre_restart_epoch": pre_restart_epoch,
        "revision_at_final_poll": revision_at_final_poll,
        "gray_end": gray_end, "pub": pub,
        "full_targets": np.asarray(fr_full.targets),
        "full_assignment": np.asarray(fr_full.assignment),
        "ckpt_dir": td,
        "race_reports": race_reports, "race_states": race_states,
        "spans": spans, "recorder_events": recorder_events,
        "metrics_text": metrics_text, "trace_resp": trace_resp,
        "warm_probe": warm_probe,
        "slo_status": slo_status, "tiles_probe": tiles_probe,
    }
    yield art
    st.shutdown()


def test_scenario_door_maps_closed_then_heals(scenario_mission):
    """The healed-wall acceptance: the closed door is MAPPED (occupied
    cells inside the gap rectangle), and after re-opening the interior
    of the gap ends free — stale wall healed by decay +
    re-observation. Edge rows abutting the real wall may keep the hit-
    tolerance blur; the interior may not."""
    a = scenario_mission
    g = a["cfg"].grid
    r0, r1, c0, c1 = a["rect"]
    closed = a["grid_closed"][r0:r1, c0:c1]
    end = a["grid_end"][r0:r1, c0:c1]
    assert (closed > g.occ_threshold).sum() >= 5, \
        "closed door never got mapped"
    interior = end[2:-2]
    assert (interior > g.occ_threshold).sum() == 0, \
        f"unhealed interior cells:\n{interior}"
    assert (interior < g.free_threshold).sum() >= interior.size // 2, \
        "healed door should read FREE, not just unknown"
    assert a["stack"].mapper.n_decay_passes > 0
    assert a["stack"].sim.n_world_updates > 0


def test_scenario_heal_propagates_to_delta_clients(scenario_mission):
    """No cache staleness in the serving path: the polling client's
    reconstructed mosaic equals the served gray of the final healed
    grid bit-for-bit, across a mid-mission mapper restart."""
    a = scenario_mission
    client = a["client"]
    np.testing.assert_array_equal(client.image(0), a["gray_end"])
    r0, r1, c0, c1 = a["rect"]
    assert (client.image(0)[r0 + 2:r1 - 2, c0:c1] != 0).all(), \
        "client still shows the stale closed door as occupied"


def test_scenario_heal_propagates_to_frontier_pipeline(scenario_mission):
    """No cache staleness in the incremental frontier pipeline: its
    revision-keyed recompute over the healed map matches the full
    recompute exactly (targets AND assignment)."""
    a = scenario_mission
    assert a["pub"] is not None, "incremental pipeline never built"
    np.testing.assert_array_equal(a["pub"].targets, a["full_targets"])
    np.testing.assert_array_equal(a["pub"].assignment,
                                  a["full_assignment"])


def test_scenario_client_epoch_resync_across_restart(scenario_mission):
    """The satellite regression: a supervisor mapper-kill + resume
    re-serves an older revision under a bumped epoch; the client
    resyncs full instead of raising RevisionRegression."""
    a = scenario_mission
    st = a["stack"]
    assert st.supervisor.n_restarts("jax_mapper") == 1
    assert st.mapper.restart_epoch == 1
    client = a["client"]
    assert a["pre_restart_epoch"] == 0
    assert client.epoch == 1
    assert client.n_epoch_resyncs == 1
    assert client.revision == a["revision_at_final_poll"]


def test_scenario_degraded_serving_window_reports_warming(
        scenario_mission):
    """ISSUE 12 satellite: DURING the staged restart's warming stage,
    /status and /tiles keep answering — from the prior epoch — and
    stamp `state=warming` instead of blocking. The probe ran inside
    the warmup_hook, i.e. after the old node was destroyed and before
    the new one was bound."""
    probe = scenario_mission["warm_probe"]
    assert probe, "warmup_hook never fired — staged restart regressed"
    assert probe["warmup_state"] == "warming"
    assert probe["status"]["state"] == "warming"
    assert probe["tiles"]["state"] == "warming"
    # Prior-epoch content: the window serves the PRE-restart epoch (0)
    # with real tiles; after the mission the stack serves epoch 1.
    assert probe["old_epoch"] == 0
    assert probe["tiles"]["epoch"] == 0
    assert probe["tiles"]["tiles"], "warming window served no content"
    st = scenario_mission["stack"]
    post = json.loads(st.api.handle("/tiles?since=-1")[2])
    assert post["epoch"] == 1 and "state" not in post
    post_status = json.loads(st.api.handle("/status")[2])
    assert "state" not in post_status
    assert st.warmup is not None and st.warmup.state() == "ready"


def test_scenario_restart_checkpoint_fallback_is_visible(
        scenario_mission):
    """The restart's checkpoint load records WHICH generation it chose
    (flight-recorder event) and the per-slot counter reaches /metrics
    — a silent .prev rescue is no longer indistinguishable from a
    clean load. (This mission's restart loads the intact primary.)"""
    evs = [e for e in scenario_mission["recorder_events"]
           if e["kind"] == "checkpoint_fallback"]
    assert evs, "restart resumed without recording its slot"
    assert evs[-1]["slot"] == "primary" and not evs[-1]["fell_back"]
    st = scenario_mission["stack"]
    metrics = st.api.handle("/metrics")[2].decode()
    assert 'jax_mapping_checkpoint_fallback_total{slot="primary"}' \
        in metrics
    assert 'jax_mapping_checkpoint_fallback_total{slot="prev"}' \
        in metrics


def test_scenario_staged_warmup_recorded_and_clean(scenario_mission):
    """The staged restart walked restore→warming→ready on the flight
    recorder, and the in-process warm-up reported no errors (jit
    caches survived the node — everything skips as in_process)."""
    kinds = [e["kind"] for e in scenario_mission["recorder_events"]]
    assert "warmup_stage" in kinds and "warmup_ready" in kinds
    st = scenario_mission["stack"]
    snap = st.warmup.snapshot()
    assert snap["state"] == "ready"
    assert snap["report"]["n_errors"] == 0


def test_scenario_plan_log_is_the_script(scenario_mission):
    a = scenario_mission
    descs = [d for _, d in a["plan"].log]
    assert descs == [
        "door_close door0",
        "clear: door_close door0",
        "crowd 0 r=0.25m",
        "clear: crowd 0",
        "kill_node jax_mapper",
    ]


def test_scenario_racewatch_clean_on_world_dynamics(scenario_mission):
    """Dynamic-tier lock gate for the scenario engine: cross-thread
    door toggling + world composition end with zero race reports and
    the change flag's candidate lockset converged on the declared
    WorldDynamics._lock."""
    a = scenario_mission
    assert a["race_reports"] == [], \
        "\n".join(r.message for r in a["race_reports"])
    dirty = a["race_states"]["WorldDynamics._dirty@dyn"]
    assert dirty.state == "shared-modified"
    assert "WorldDynamics._lock@dyn" in dirty.candidate


# ------------------------------------------- shared mission: obs tier

def test_obs_trace_propagation_reaches_sim_publish(scenario_mission):
    """ISSUE 9 acceptance: every fused scan's span chain reaches back
    to its sim publish — each `mapper.fuse` span walks parent links to
    a ROOT (parent_span 0) that is the scan topic's publish record."""
    spans = scenario_mission["spans"]
    by_id = {s["span_id"]: s for s in spans}
    fuses = [s for s in spans if s["name"] == "mapper.fuse"]
    assert len(fuses) > 10, "mission fused scans but emitted no spans"
    for f in fuses:
        hops, cur = 0, f
        while cur["parent_span"] != 0:
            assert cur["parent_span"] in by_id, \
                f"span chain broken (evicted?) at {cur['name']}"
            cur = by_id[cur["parent_span"]]
            hops += 1
            assert hops < 16
        assert cur["name"].startswith("publish:"), cur["name"]
        assert cur["name"].endswith("scan"), \
            f"fuse rooted at {cur['name']}, not the sim scan publish"
        assert cur["trace_id"] == f["trace_id"]


def test_obs_spans_cover_the_pipeline_stages(scenario_mission):
    names = {s["name"] for s in scenario_mission["spans"]}
    assert "mapper.tick" in names
    assert "brain.tick" in names
    assert any(n.startswith("publish:/") for n in names)


def test_obs_recorder_covers_mission_transitions(scenario_mission):
    """The flight recorder saw the mission's load-bearing transitions:
    the chaos script, revision advances, decay passes, the supervisor
    kill/restart story, checkpoint saves, and its own postmortem
    dump."""
    events = scenario_mission["recorder_events"]
    kinds = {e["kind"] for e in events}
    assert {"fault", "map_revision", "decay_pass", "supervisor_dead",
            "supervisor_restart", "restart_epoch",
            "checkpoint_save", "postmortem_dump"} <= kinds, kinds
    # The chaos script interleaves in order within the stream.
    faults = [e["desc"] for e in events if e["kind"] == "fault"]
    assert faults[:2] == ["door_close door0", "clear: door_close door0"]
    # The restart epoch bump carries its resume provenance.
    (ep,) = [e for e in events if e["kind"] == "restart_epoch"]
    assert ep["epoch"] == 1 and "resumed_from_checkpoint" in ep
    # map_revision advances are strictly monotone WITHIN an epoch; the
    # checkpoint-resume restart legitimately re-serves an older
    # revision (exactly the regression the epoch stamp exists for —
    # and the recorder stream shows it in causal order).
    segments, cur = [], []
    for e in events:
        if e["kind"] == "restart_epoch":
            segments.append(cur)
            cur = []
        elif e["kind"] == "map_revision":
            cur.append(e["revision"])
    segments.append(cur)
    assert len(segments) == 2                    # one restart
    for seg in segments:
        assert seg and all(a < b for a, b in zip(seg, seg[1:])), seg
    assert segments[1][0] <= segments[0][-1], \
        "resume never re-served an older revision — fixture drifted"


def test_obs_postmortem_dump_artifact(scenario_mission):
    """The supervisor restart auto-dumped to `<ckpt>/postmortem/`; the
    dump is loadable, contains the pre-restart transitions, and feeds
    both the Perfetto exporter and the trace-diff CLI."""
    import glob
    import json as _json
    from jax_mapping.obs import dump_to_chrome
    dumps = sorted(glob.glob(os.path.join(
        scenario_mission["ckpt_dir"], "postmortem", "flight_*.json")))
    assert dumps, "supervisor restart wrote no postmortem dump"
    doc = _json.load(open(dumps[0]))
    assert doc["reason"].startswith("supervisor_restart")
    kinds = {e["kind"] for e in doc["events"]}
    assert "supervisor_dead" in kinds
    assert doc["spans"], "tracing was armed; dump must carry spans"
    chrome = dump_to_chrome(doc)
    assert len(chrome["traceEvents"]) == len(doc["spans"]) \
        + len(doc["events"])


def test_obs_metrics_registry_preserves_historical_document(
        scenario_mission):
    """The registry-refactor acceptance on a LIVE exposition: every
    historical family present, in the historical order, with the
    historical types — and the new obs-tier families strictly after
    the historical tail."""
    text = scenario_mission["metrics_text"]
    types = []
    for line in text.strip().split("\n"):
        if line.startswith("# TYPE "):
            name, mtype = line[len("# TYPE "):].rsplit(" ", 1)
            types.append((name, mtype))
    assert len(types) == len({n for n, _ in types}), "duplicate family"
    # The historical head, in hand-assembled order (brain absent on
    # this stack is impossible: the scenario stack always has one).
    head = [(n, t) for n, t in types
            if not n.startswith(("jax_mapping_stage_",
                                 "jax_mapping_bus_subscription_",
                                 "jax_mapping_obs_"))
            and (n, t) in HISTORICAL_METRIC_FAMILIES]
    expected = [f for f in HISTORICAL_METRIC_FAMILIES if f in head]
    assert head == expected
    assert set(HISTORICAL_METRIC_FAMILIES) <= set(types), \
        sorted(set(HISTORICAL_METRIC_FAMILIES) - set(types))
    # Byte-format spot checks the order test can't see.
    assert "jax_mapping_http_request_seconds_bucket{le=\"0.005\"} " \
        in text
    import re
    assert re.search(
        r"jax_mapping_match_pyramid_cache_hit_rate \d\.\d{4}\n", text)
    assert re.search(r"jax_mapping_stage_mapper_tick_ms_sum \d+\.\d{3}\n",
                     text)
    # New tier: bus per-subscription health labelled by topic, stage
    # histograms on the fixed log grid, obs counters — all AFTER the
    # historical tail.
    first_new = min(i for i, (n, _) in enumerate(types)
                    if n.startswith(("jax_mapping_bus_subscription_",
                                     "jax_mapping_obs_"))
                    or n.endswith("_seconds")
                    and n.startswith("jax_mapping_stage_"))
    last_hist = max(i for i, f in enumerate(types)
                    if f in HISTORICAL_METRIC_FAMILIES)
    assert last_hist < first_new
    assert re.search(
        r'jax_mapping_bus_subscription_dropped_total\{topic="robot0/scan"\} \d+',
        text)
    assert re.search(
        r'jax_mapping_stage_mapper_tick_seconds_bucket\{le="0.00025"\} \d+',
        text)
    assert "jax_mapping_stage_mapper_publish_frontiers_seconds_count" \
        in text
    assert "jax_mapping_stage_serving_snapshot_seconds_count" in text
    assert "jax_mapping_obs_recorder_events_total" in text
    assert "jax_mapping_obs_trace_spans_total" in text


#: The pre-PR hand-assembled `/metrics` families, in the pre-PR
#: emission order (bridge/http_api.py git history) — the byte-compat
#: contract of the MetricsRegistry refactor. Conditional families
#: (planner overlays, frontier recompute_ms, pyramid cache) are listed
#: too: the ORDER test filters to families actually present, the
#: superset test pins presence of everything this stack exports.
HISTORICAL_METRIC_FAMILIES = [
    ("jax_mapping_http_requests_total", "counter"),
    ("jax_mapping_png_cache_hits_total", "counter"),
    ("jax_mapping_brain_ticks_total", "counter"),
    ("jax_mapping_brain_io_errors_total", "counter"),
    ("jax_mapping_brain_connected", "gauge"),
    ("jax_mapping_health_robot_state", "gauge"),
    ("jax_mapping_health_driver_state", "gauge"),
    ("jax_mapping_health_transitions_total", "counter"),
    ("jax_mapping_supervisor_dead_nodes", "gauge"),
    ("jax_mapping_supervisor_restarts_total", "counter"),
    ("jax_mapping_supervisor_checkpoints_total", "counter"),
    ("jax_mapping_match_candidates", "gauge"),
    ("jax_mapping_match_prune_ratio", "gauge"),
    ("jax_mapping_frontier_recompute_total", "counter"),
    ("jax_mapping_frontier_skip_total", "counter"),
    ("jax_mapping_frontier_cache_hits_total", "counter"),
    ("jax_mapping_frontier_cache_misses_total", "counter"),
    ("jax_mapping_frontier_crop_cells", "gauge"),
    # jax_mapping_frontier_recompute_ms (gauge) was RETIRED by ISSUE 10:
    # the recompute latency now reports through the one stage mechanism
    # (jax_mapping_stage_frontier_recompute_ms summary + _seconds
    # histogram) instead of a hand-built gauge.
    ("jax_mapping_planner_overlay_rebuilds_total", "counter"),
    ("jax_mapping_planner_overlay_reuses_total", "counter"),
    ("jax_mapping_recovery_estimator_score", "gauge"),
    ("jax_mapping_recovery_diverge_events_total", "counter"),
    ("jax_mapping_recovery_readmits_total", "counter"),
    ("jax_mapping_recovery_reloc_attempts_total", "counter"),
    ("jax_mapping_recovery_reloc_verified_total", "counter"),
    ("jax_mapping_recovery_stuck_detections_total", "counter"),
    ("jax_mapping_recovery_blacklisted_total", "counter"),
    ("jax_mapping_match_pyramid_cache_hits_total", "counter"),
    ("jax_mapping_match_pyramid_cache_misses_total", "counter"),
    ("jax_mapping_match_pyramid_cache_hit_rate", "gauge"),
    ("jax_mapping_http_requests_by_route_total", "counter"),
    ("jax_mapping_http_request_seconds", "histogram"),
    ("jax_mapping_http_not_modified_total", "counter"),
    ("jax_mapping_serving_grid_revision", "gauge"),
    ("jax_mapping_serving_grid_tiles_encoded_total", "counter"),
    ("jax_mapping_serving_grid_tiles_clean_total", "counter"),
    ("jax_mapping_serving_grid_hint_missed_total", "counter"),
    ("jax_mapping_serving_event_clients", "gauge"),
    ("jax_mapping_serving_events_total", "counter"),
    ("jax_mapping_serving_events_dropped_total", "counter"),
    ("jax_mapping_http_degraded_responses_total", "counter"),
    ("jax_mapping_bus_partition_dropped_total", "counter"),
]


def test_obs_trace_endpoint_serves_the_mission(scenario_mission):
    status, ctype, body = scenario_mission["trace_resp"][:3]
    assert status == 200 and ctype == "application/json"
    import json as _json
    doc = _json.loads(body)
    assert doc["next"] > 0
    names = {e["name"] for e in doc["traceEvents"]}
    assert "mapper.tick" in names
    assert any(n.startswith("publish:/") for n in names)
    for e in doc["traceEvents"][:50]:
        assert e["ph"] == "X"
        int(e["args"]["trace_id"], 16)


# ------------------------------------- shared mission: devprof tier

def test_devprof_live_dispatch_attribution(scenario_mission):
    """ISSUE 10 acceptance on a live mission: the dispatch profiler
    attributed wall time and call counts to the real jitted entry
    points, and `/status.perf` + the `/metrics` device families expose
    them (memory gracefully absent on CPU)."""
    import json as _json
    st = scenario_mission["stack"]
    assert st.devprof is not None and st.devprof.installed
    snap = st.devprof.snapshot()
    assert len(snap) >= 4, sorted(snap)
    for fn in ("jax_mapping.sim.lidar.simulate_scans",
               "jax_mapping.bridge.brain.brain_tick"):
        assert snap[fn]["count"] > 10, (fn, snap.get(fn))
        assert snap[fn]["total_ms"] > 0
    status = _json.loads(st.api.handle("/status")[2])
    perf = status["perf"]
    assert perf["dispatch"] and perf["recompiles"] is not None
    assert perf["memory"] is None                # CPU: graceful None
    assert isinstance(perf["cost_ledger_uncollected"], int)
    text = st.api.handle("/metrics")[2].decode()
    assert "# TYPE jax_mapping_device_dispatch_total counter" in text
    assert ("# TYPE jax_mapping_device_dispatch_seconds histogram"
            in text)
    assert "# TYPE jax_mapping_jit_recompiles_total counter" in text
    import re as _re
    assert _re.search(
        r'jax_mapping_device_dispatch_seconds_bucket\{fn="jax_mapping\.'
        r'[a-z_.]+",le="0.00025"\} \d+', text)
    # The device families are host-side telemetry families, absent
    # when devprof is off — assert they render AFTER the historical
    # tail like every obs-tier family (order pinned by the historical-
    # document test above; presence here).
    assert "jax_mapping_device_memory_bytes" not in text  # CPU


def test_devprof_live_recompile_guard(scenario_mission):
    """ISSUE 10 satellite, the LIVE half of the compile-budget ratchet
    (the cold-cache subprocess gate cannot see runtime churn): after
    the mission's warmup, continued stepping of the live stack
    compiles ZERO new variants in any profiled function — per-call
    retracing (the C4 hazard class at runtime) would show up as
    `jax_mapping_jit_recompiles_total` growth here. The budget-listed
    functions that dispatched live all carry recompile telemetry, so a
    regression is attributable to a function, not just a count."""
    from jax_mapping.analysis.compilebudget import (Budget,
                                                    default_budget_path)
    st = scenario_mission["stack"]
    before = st.devprof.recompiles()
    st.run_steps(4)
    after = st.devprof.recompiles()
    grew = {fn: (before.get(fn, 0), n) for fn, n in after.items()
            if n > before.get(fn, 0)}
    assert not grew, (
        f"steady-state stepping recompiled: {grew} — runtime shape "
        "churn the cold-cache gate cannot see")
    # Every budgeted function this mission dispatched reports through
    # the live recompile counter (the telemetry the satellite adds).
    budget = Budget.load(default_budget_path())
    dispatched = set(st.devprof.snapshot())
    covered = [e["name"] for e in budget.entries
               if e["name"] in dispatched]
    assert covered, "mission dispatched no budget-listed functions?"
    for name in covered:
        assert name in after


def test_devprof_mission_metrics_include_stage_fold(scenario_mission):
    """The folded hot stages report from the LIVE mission: frontier
    recomputes ran, so the `frontier.recompute` stage histogram is in
    the exposition (and the retired hand-built gauge is not)."""
    text = scenario_mission["metrics_text"]
    assert "jax_mapping_stage_frontier_recompute_seconds_count" in text
    assert "jax_mapping_frontier_recompute_ms " not in text


# =========================================================== slow gates

@pytest.mark.slow
def test_scenario_wiring_is_bit_inert_when_disabled(tmp_path):
    """The bit-exactness acceptance, property-style over seeds: decay
    disabled + a WorldDynamics armed but never fired reproduces the
    plain stack EXACTLY — fusion output, frontier targets, serving
    tile hashes."""
    import jax.numpy as jnp
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.ops import frontier as F
    from jax_mapping.ops import grid as G

    cfg = tiny_config()
    assert not cfg.decay.enabled                 # the shipped default
    for seed in (0, 3):
        world, doors = W.rooms_with_doors(96, cfg.grid.resolution_m,
                                          seed=1)

        def drive(scenario):
            if scenario:
                st = launch_scenario_stack(cfg, world, doors=doors,
                                           n_robots=2, realtime=False,
                                           seed=seed)
            else:
                st = launch_sim_stack(cfg, world, n_robots=2,
                                      realtime=False, seed=seed)
            st.brain.start_exploring()
            st.run_steps(40)
            lo = np.array(np.asarray(st.mapper.merged_grid()),
                          copy=True)
            poses = np.stack([np.asarray(s.pose)
                              for s in st.mapper.states])
            fr = F.compute_frontiers(cfg.frontier, cfg.grid,
                                     jnp.asarray(lo),
                                     jnp.asarray(poses))
            hashes = np.asarray(G.tile_hashes(
                G.to_gray(cfg.grid, jnp.asarray(lo)),
                cfg.serving.tile_cells))
            targets = np.asarray(fr.targets)
            st.shutdown()
            return lo, targets, hashes

        lo_a, tg_a, h_a = drive(False)
        lo_b, tg_b, h_b = drive(True)
        np.testing.assert_array_equal(lo_a, lo_b)
        np.testing.assert_array_equal(tg_a, tg_b)
        np.testing.assert_array_equal(h_a, h_b)


@pytest.mark.slow
def test_rendezvous_two_fleets_merge_into_one_world():
    """The rendezvous acceptance: two independently-seeded 2-robot
    fleets with a HIDDEN relative transform detect overlap via the
    cross-fleet sweep, verify the implied transform by streak, and the
    merged world agrees with a jointly-started 4-robot oracle on >= 90%
    of commonly-decided cells — with frontier assignment spanning the
    merged fleet."""
    import jax.numpy as jnp
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.scenarios import RendezvousMerger

    cfg = tiny_config()
    world = W.plank_course(96, cfg.grid.resolution_m, n_planks=5,
                           seed=11)
    sa = launch_sim_stack(cfg, world, n_robots=2, realtime=False, seed=0)
    sa.brain.start_exploring()
    sb = launch_sim_stack(cfg, world, n_robots=2, realtime=False, seed=1)
    # The hidden truth: fleet B physically starts offset+rotated, but
    # its belief frame still says "we started at the spawn poses".
    T_true = np.asarray([0.9, -0.7, 0.6], np.float32)
    truth = se2_apply(T_true, np.asarray(sb.sim.sim_state.poses))
    sb.sim.sim_state = sb.sim.sim_state._replace(
        poses=jnp.asarray(truth))
    sb.brain.start_exploring()

    merger = RendezvousMerger(cfg, sa.mapper, sb.mapper, max_seeds=8)
    merged_at = None
    for seg in range(30):
        sa.run_steps(10)
        sb.run_steps(10)
        if merger.poll():
            merged_at = (seg + 1) * 10
            break
    assert merged_at is not None, \
        f"fleets never merged: {merger.snapshot()}"

    T = merger.transform
    assert np.hypot(*(T[:2] - T_true[:2])) < 0.3
    assert abs((T[2] - T_true[2] + np.pi) % (2 * np.pi) - np.pi) < 0.3

    # Oracle: a jointly-started 4-robot fleet, same mission length.
    so = launch_sim_stack(cfg, world, n_robots=4, realtime=False, seed=2)
    so.brain.start_exploring()
    so.run_steps(merged_at)
    g_o = np.asarray(so.mapper.merged_grid())
    g_m = np.asarray(merger.merged_grid)
    both = (np.abs(g_m) > 0.5) & (np.abs(g_o) > 0.5)
    assert both.sum() > 1000
    agree = float((np.sign(g_m[both]) == np.sign(g_o[both])).mean())
    assert agree >= 0.90, f"post-merge sign agreement {agree:.3f}"

    fr = merged_frontier_assignment(cfg, merger.merged_grid,
                                    merger.merged_states)
    assign = np.asarray(fr.assignment)
    assert len(assign) == 4
    assert (assign[2:] >= 0).any(), \
        "joined fleet's robots got no frontier work"

    # FleetHealth absorbs the joined robots.
    sa.health.absorb(sb.health)
    assert sa.health.n_robots == 4
    assert len(sa.health.robot_states()) == 4

    so.shutdown()
    sa.shutdown()
    sb.shutdown()


@pytest.mark.slow
def test_lifelong_soak_day_mission_under_continuous_chaos(tmp_path):
    """The lifelong acceptance: a sim-accelerated long session under a
    seeded scenario+chaos plan — door cycles, crowd churn, decay churn,
    two supervisor-driven mapper restarts with checkpoint resume and
    bounded generation retention — finishes with coverage >= 55% and
    sign-agreement >= 90% vs the fault-free twin, and two same-seed
    missions are bit-identical including decay state."""
    import dataclasses
    cfg = tiny_config()
    cfg = cfg.replace(
        decay=DecayConfig(enabled=True, every_n_ticks=10, factor=0.93,
                          evidence_cap=2.0),
        resilience=dataclasses.replace(
            cfg.resilience, checkpoint_retain_generations=4))
    world, doors = W.arena_with_door(96, cfg.grid.resolution_m)
    steps = 240
    events = day_plan(steps, [d["name"] for d in doors], n_crowds=1,
                      door_cycle=70, crowd_cycle=90,
                      kill_steps=(100, 180))

    rep = run_lifelong_mission(cfg, world, doors, events, steps, seed=0,
                               checkpoint_dir=str(tmp_path / "a"))
    assert rep.n_mapper_restarts == 2
    assert rep.restart_epoch == 2
    assert rep.n_decay_passes > 0
    assert rep.n_world_updates > 0
    # Bounded retention: the directory holds at most K generations.
    assert 0 < len(rep.checkpoint_files) <= 4, rep.checkpoint_files

    # Fault-free twin (no scenario events, same decay config).
    rep0 = run_lifelong_mission(cfg, world, doors, [], steps, seed=0,
                                checkpoint_dir=str(tmp_path / "b"))
    known, known0 = rep.known_cells(), rep0.known_cells()
    assert known0 > 1000
    assert known / known0 >= 0.55, f"coverage {known / known0:.2f}"
    both = (np.abs(rep.grid) > 0.5) & (np.abs(rep0.grid) > 0.5)
    agree = float((np.sign(rep.grid[both])
                   == np.sign(rep0.grid[both])).mean())
    assert agree >= 0.90, f"sign agreement {agree:.3f}"

    # Determinism: same seed, same schedule -> bit-identical world,
    # decay state included (the grid IS the decay state).
    rep2 = run_lifelong_mission(cfg, world, doors, events, steps, seed=0,
                                checkpoint_dir=str(tmp_path / "c"))
    assert rep2.plan_log == rep.plan_log
    np.testing.assert_array_equal(rep2.grid, rep.grid)


@pytest.mark.slow
def test_obs_tracing_is_bit_inert(tmp_path):
    """ISSUE 9 bit-determinism acceptance, property-style over seeds:
    `ObsConfig(enabled=True)` must not perturb a single array — grids,
    frontier targets and serving tile hashes identical to the
    `enabled=False` twin (which is itself the shipped default, pinned
    bit-exact pre-PR by the rest of the tier-1 suite)."""
    import jax.numpy as jnp
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.ops import frontier as F
    from jax_mapping.ops import grid as G

    base = tiny_config()
    assert not base.obs.enabled                  # the shipped default
    for seed in (0, 3):
        world, _ = W.rooms_with_doors(96, base.grid.resolution_m,
                                      seed=1)

        def drive(obs_on):
            # The enabled side arms the FULL host-side obs stack —
            # tracing + pipeline ledger + SLO engine (ISSUE 15): the
            # freshness tier must be exactly as bit-inert as the
            # tracer it rides with, objectives evaluating and all.
            slo = (SloObjective(name="stale",
                                metric="tile_staleness_revs",
                                threshold=5.0, fast_window_ticks=4,
                                slow_window_ticks=8),) if obs_on else ()
            cfg = base.replace(obs=ObsConfig(enabled=obs_on, slo=slo))
            st = launch_sim_stack(cfg, world, n_robots=2,
                                  realtime=False, seed=seed)
            st.brain.start_exploring()
            st.run_steps(40)
            if obs_on:
                assert st.tracer is not None
                assert st.tracer.last_seq() > 0
                assert st.pipeline is not None and st.slo is not None
                assert st.slo.status()["n_evaluations"] >= 40
            else:
                assert st.tracer is None
                assert st.pipeline is None and st.slo is None
            lo = np.array(np.asarray(st.mapper.merged_grid()),
                          copy=True)
            poses = np.stack([np.asarray(s.pose)
                              for s in st.mapper.states])
            fr = F.compute_frontiers(base.frontier, base.grid,
                                     jnp.asarray(lo),
                                     jnp.asarray(poses))
            hashes = np.asarray(G.tile_hashes(
                G.to_gray(base.grid, jnp.asarray(lo)),
                base.serving.tile_cells))
            targets = np.asarray(fr.targets)
            st.shutdown()
            return lo, targets, hashes

        lo_a, tg_a, h_a = drive(False)
        lo_b, tg_b, h_b = drive(True)
        np.testing.assert_array_equal(lo_a, lo_b)
        np.testing.assert_array_equal(tg_a, tg_b)
        np.testing.assert_array_equal(h_a, h_b)


@pytest.mark.slow
def test_devprof_is_bit_inert(tmp_path):
    """ISSUE 10 bit-determinism acceptance, property-style over seeds:
    `DevProfConfig(enabled=True)` (the full obs stack armed) must not
    perturb a single array vs the shipped `enabled=False` default —
    grids, frontier targets and serving tile hashes identical. The
    disabled default is itself pre-PR behavior by construction (no
    wrapper is ever created), pinned by the rest of tier-1."""
    import jax.numpy as jnp
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.obs import devprof as DP
    from jax_mapping.ops import frontier as F
    from jax_mapping.ops import grid as G

    # Wrappers are process-global (one live profiler): in an unfiltered
    # run the module-scoped mission stack's profiler is still installed
    # while this test launches its own devprof-armed stack, so park the
    # ambient one for the duration and re-arm it after. The mission
    # stack keeps running unprofiled meanwhile — its accumulated stats
    # survive; install() re-baselines cache sizes.
    ambient = DP._installed
    if ambient is not None:
        ambient.uninstall()
    try:
        _drive_devprof_bit_inert(launch_sim_stack, jnp, F, G)
    finally:
        if ambient is not None:
            ambient.install()


def _drive_devprof_bit_inert(launch_sim_stack, jnp, F, G):
    base = tiny_config()
    assert not base.obs.devprof.enabled          # the shipped default
    for seed in (0, 3):
        world, _ = W.rooms_with_doors(96, base.grid.resolution_m,
                                      seed=1)

        def drive(devprof_on):
            cfg = base.replace(obs=ObsConfig(
                enabled=devprof_on,
                devprof=DevProfConfig(enabled=devprof_on)))
            st = launch_sim_stack(cfg, world, n_robots=2,
                                  realtime=False, seed=seed)
            st.brain.start_exploring()
            st.run_steps(40)
            if devprof_on:
                assert st.devprof is not None
                assert sum(v["count"] for v in
                           st.devprof.snapshot().values()) > 0
            else:
                assert st.devprof is None
            lo = np.array(np.asarray(st.mapper.merged_grid()),
                          copy=True)
            poses = np.stack([np.asarray(s.pose)
                              for s in st.mapper.states])
            fr = F.compute_frontiers(base.frontier, base.grid,
                                     jnp.asarray(lo),
                                     jnp.asarray(poses))
            hashes = np.asarray(G.tile_hashes(
                G.to_gray(base.grid, jnp.asarray(lo)),
                base.serving.tile_cells))
            targets = np.asarray(fr.targets)
            st.shutdown()
            return lo, targets, hashes

        lo_a, tg_a, h_a = drive(False)
        lo_b, tg_b, h_b = drive(True)
        np.testing.assert_array_equal(lo_a, lo_b)
        np.testing.assert_array_equal(tg_a, tg_b)
        np.testing.assert_array_equal(h_a, h_b)


@pytest.mark.slow
def test_obs_same_seed_runs_emit_identical_streams(tmp_path):
    """ISSUE 9 stream-identity acceptance: two same-seed chaos runs
    with tracing on produce IDENTICAL span and recorder streams —
    `diff_streams` reports zero divergence — and a seed change moves
    the trace ids (the diff would otherwise pass vacuously)."""
    from jax_mapping.obs import diff_streams
    from jax_mapping.obs.recorder import flight_recorder

    cfg = tiny_config().replace(
        decay=DecayConfig(enabled=True, every_n_ticks=8, factor=0.9,
                          evidence_cap=1.5),
        obs=ObsConfig(enabled=True))
    world, doors = W.arena_with_door(96, cfg.grid.resolution_m)

    def drive(seed):
        mark = flight_recorder.mark()
        st = launch_scenario_stack(cfg, world, doors=doors, n_robots=2,
                                   realtime=False, seed=seed)
        st.brain.start_exploring()
        plan = FaultPlan([
            FaultEvent(step=4, kind="door_close", name="door0",
                       duration=10),
        ], seed=seed)
        st.attach_fault_plan(plan)
        st.run_steps(36)
        spans = st.tracer.spans_since(0)
        events = flight_recorder.events_since(mark)
        st.shutdown()
        return spans, events

    spans_a, events_a = drive(0)
    spans_b, events_b = drive(0)
    div = diff_streams(spans_a, spans_b)
    assert div is None, div.describe()
    div = diff_streams(events_a, events_b)
    assert div is None, div.describe()
    # Sensitivity: a different seed diverges at the very first span.
    spans_c, _ = drive(1)
    div = diff_streams(spans_a, spans_c)
    assert div is not None and div.index == 0


# --------------------------------- shared mission: freshness/SLO tier

def test_slo_mission_fires_exactly_one_deterministic_alert(
        scenario_mission):
    """ISSUE 15 acceptance on the shared mission: the deliberately-
    tight staleness objective fires EXACTLY ONE flight-recorded alert
    (the mid-mission poll→silence stretch), and the post-restart
    epoch's smaller revisions clear it — both transitions recorded
    with deterministic (tick, objective, state) fields. The firing
    STEP's same-seed determinism is pinned at the engine level
    (tests/test_obs.py) and by the slow two-run partition drill; here
    the live mission proves the loop closes once, end to end."""
    evs = [e for e in scenario_mission["recorder_events"]
           if e["kind"] == "slo_alert"]
    fires = [e for e in evs if e["state"] == "firing"]
    clears = [e for e in evs if e["state"] == "clear"]
    assert len(fires) == 1, evs
    assert len(clears) == 1, evs
    assert fires[0]["objective"] == "staleness"
    assert isinstance(fires[0]["tick"], int)
    # Fired while the first epoch was still serving (before the step-48
    # kill), cleared by the restarted epoch's fresh revision numbering.
    assert fires[0]["tick"] < _KILL_AT
    st = scenario_mission["stack"]
    assert st.slo is not None
    assert st.slo.firing() == []
    alerts = st.slo.alerts()
    assert [(a[1], a[2]) for a in alerts] == [("staleness", "firing"),
                                              ("staleness", "clear")]


def test_slo_mission_status_and_metrics_surface(scenario_mission):
    """`/status.slo` carries the objective picture and the
    `jax_mapping_slo_*` + pipeline families render on /metrics —
    after the historical tail (the registry-append contract)."""
    slo = scenario_mission["slo_status"]
    assert slo is not None
    (obj,) = slo["objectives"]
    assert obj["name"] == "staleness"
    assert obj["metric"] == "tile_staleness_revs"
    assert obj["n_fired"] == 1 and obj["n_cleared"] == 1
    assert obj["breach_ticks"] > 0
    assert slo["alerts"], "alert history missing from /status.slo"
    text = scenario_mission["metrics_text"]
    assert 'jax_mapping_slo_firing{objective="staleness"}' in text
    assert 'jax_mapping_slo_alerts_fired_total{objective="staleness"} 1' \
        in text
    assert "jax_mapping_pipeline_hop_seconds_bucket" in text
    assert 'hop="fuse"' in text and 'hop="deliver"' in text
    assert "jax_mapping_scan_to_served_seconds_bucket" in text
    assert "jax_mapping_pipeline_revisions_completed_total" in text


def test_pipeline_mission_ledger_completed_scan_to_served(
        scenario_mission):
    """The ledger closed real scan→served chains on the live mission:
    completed records exist, carry the fuse hop (a scan enqueue
    started them), and /status.pipeline reports the windowed p99."""
    st = scenario_mission["stack"]
    assert st.pipeline is not None
    recs = st.pipeline.records()
    assert recs, "no revision ever completed a client delivery"
    with_scan = [r for r in recs if "fuse" in r["hops_ms"]]
    assert with_scan, "no completed revision carried a scan waypoint"
    for r in with_scan[:5]:
        assert set(r["hops_ms"]) <= {"fuse", "notify", "encode",
                                     "deliver"}
        assert r["critical"] in r["hops_ms"]
    status = json.loads(
        st.api.handle("/status")[2])["pipeline"]
    assert status["completed_revisions"] >= len(recs)
    assert "scan_to_served_p99_ms" in status


def test_pipeline_mission_server_timing_header(scenario_mission):
    """Serving responses stamp the Server-Timing revision-age header —
    server monotonic deltas, the client-observed staleness measure
    that needs no cross-host clock trust."""
    from jax_mapping.serving.client import parse_revision_age_ms
    probe = scenario_mission["tiles_probe"]
    assert probe[0] == 200
    headers = probe[3]
    assert "Server-Timing" in headers, headers
    age = parse_revision_age_ms(headers["Server-Timing"])
    assert age is not None and age >= 0.0
    # The dump artifact carries the ledger's records as its `pipeline`
    # section (the critical-path CLI's input).
    import glob
    dumps = sorted(glob.glob(os.path.join(
        scenario_mission["ckpt_dir"], "postmortem", "flight_*.json")))
    assert dumps
    doc = json.load(open(dumps[-1]))
    assert "pipeline" in doc


def test_obs_disabled_constructs_no_freshness_tier(scenario_mission):
    """The constructs-nothing contract, structurally: SLO objectives
    declared under `obs.enabled=False` build NO ledger and NO engine
    anywhere (launch leaves every handle None) — checked without a
    stack launch (tier-1 budget) by driving the launch-time gate
    directly."""
    from jax_mapping.config import ObsConfig as _Obs
    cfg = tiny_config().replace(obs=_Obs(
        enabled=False,
        slo=(SloObjective(name="x", metric="tick_deadline_ms",
                          threshold=1.0),)))
    # The launch gate in one line: everything hangs off obs.enabled.
    assert not cfg.obs.enabled and cfg.obs.slo
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.bridge.bus import Bus
    mapper = MapperNode(cfg, Bus(), n_robots=1)
    assert mapper._pipeline is None and mapper._slo is None
    mapper.destroy()
    # And the armed mission stack has the full tier (the piggyback's
    # positive control).
    st = scenario_mission["stack"]
    assert st.pipeline is not None and st.slo is not None
    assert st.mapper._pipeline is st.pipeline
    assert st.api.pipeline is st.pipeline


@pytest.mark.slow
def test_slo_partition_drill_fires_and_clears_deterministically(
        tmp_path):
    """THE chaos drill (ISSUE 15 acceptance): under a seeded FaultPlan
    partition window on the scan path (`lidar_dead` takes every
    robot's scan topic down), the scan→served freshness objective
    fires a burn-rate alert DURING the window and clears after heal —
    flight-recorded, visible on /status.slo, and two same-seed runs
    fire and clear at the IDENTICAL step (the chaos-determinism
    contract extended to alerting)."""
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.obs.recorder import flight_recorder

    WINDOW_START, WINDOW_LEN, STEPS = 16, 24, 56
    cfg = tiny_config().replace(obs=ObsConfig(enabled=True, slo=(
        SloObjective(name="scan_to_served",
                     metric="scan_to_served_p99_ms",
                     threshold=1e9,          # wall p99 never breaches:
                     max_silent_ticks=4,     # the drill is the stall
                     fast_window_ticks=6, slow_window_ticks=12,
                     fast_burn=0.5, slow_burn=0.25),)))
    world, _ = W.rooms_with_doors(96, cfg.grid.resolution_m, seed=1)

    def drive(seed):
        mark = flight_recorder.mark()
        st = launch_sim_stack(cfg, world, n_robots=2, realtime=False,
                              seed=seed, http_port=0)
        st.brain.start_exploring()
        plan = FaultPlan(
            [FaultEvent(step=WINDOW_START, kind="lidar_dead", robot=r,
                        duration=WINDOW_LEN) for r in range(2)],
            seed=seed)
        st.attach_fault_plan(plan)
        status_in_window = None
        from jax_mapping.serving.client import DeltaMapClient
        client = DeltaMapClient(f"http://127.0.0.1:{st.api.port}")
        for step in range(STEPS):
            st.run_steps(1)
            client.poll()
            if step == WINDOW_START + WINDOW_LEN - 2:
                status_in_window = json.loads(
                    st.api.handle("/status")[2])["slo"]
        alerts = st.slo.alerts()
        events = [
            (e["tick"], e["objective"], e["state"])
            for e in flight_recorder.events_since(mark)
            if e["kind"] == "slo_alert"]
        st.shutdown()
        return alerts, events, status_in_window, client

    alerts_a, events_a, status_a, client_a = drive(0)
    # The loop closes: fired during the window, cleared after heal.
    assert [(a[1], a[2]) for a in alerts_a] == [
        ("scan_to_served", "firing"), ("scan_to_served", "clear")]
    fire_tick, clear_tick = alerts_a[0][0], alerts_a[1][0]
    assert WINDOW_START < fire_tick <= WINDOW_START + WINDOW_LEN, \
        (fire_tick, alerts_a)
    assert clear_tick > WINDOW_START + WINDOW_LEN, (clear_tick,
                                                    alerts_a)
    # Visible on /status.slo while inside the window.
    (obj,) = status_a["objectives"]
    assert obj["firing"] and obj["silent_ticks"] > 4
    # Flight-recorded with the same deterministic fields.
    assert events_a == [(fire_tick, "scan_to_served", "firing"),
                        (clear_tick, "scan_to_served", "clear")]
    # The client observed the staleness too (Server-Timing ages grow
    # through the window).
    assert client_a.revision_ages_ms
    assert max(client_a.revision_ages_ms) > min(
        client_a.revision_ages_ms)
    # Determinism: the second same-seed run fires and clears at the
    # IDENTICAL steps.
    alerts_b, events_b, _, _ = drive(0)
    assert alerts_b == alerts_a
    assert events_b == events_a
