"""Worker process for the multi-process DCN integration test.

Run as: python _dist_worker.py <process_id> <num_processes> <port>.
Each process owns 2 virtual CPU devices; the hybrid ('fleet', 'space')
mesh places fleet across processes (the DCN axis) and space within one.
The psum checked here is the fleet map-merge collective
(parallel/fleet_sharded.py's per-step log-odds merge).
"""
import functools
import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_MAPPING_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["JAX_MAPPING_NUM_PROCESSES"] = str(nproc)
os.environ["JAX_MAPPING_PROCESS_ID"] = str(pid)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax                                               # noqa: E402
import jax.numpy as jnp                                  # noqa: E402
from jax.experimental.shard_map import shard_map         # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P   # noqa: E402

from jax_mapping.parallel import distributed as D        # noqa: E402

assert D.initialize(D.DistConfig.from_env()), "initialize() returned False"
assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == 2 * nproc, len(jax.devices())

mesh = D.hybrid_fleet_mesh(n_hosts=nproc, space_per_host=2)
assert dict(zip(mesh.axis_names, mesh.devices.shape)) == \
    {"fleet": nproc, "space": 2}


@functools.partial(shard_map, mesh=mesh, in_specs=P("fleet", "space"),
                   out_specs=P(None, "space"))
def merge(x):
    return jax.lax.psum(x, "fleet")


def shard_data(idx):
    fleet_i = idx[0].start // 2          # 2 rows per fleet host
    return jnp.ones((2, 2), jnp.float32) * (fleet_i + 1)


arr = jax.make_array_from_callback(
    (nproc * 2, 4), NamedSharding(mesh, P("fleet", "space")), shard_data)
out = merge(arr)
expect = float(sum(range(1, nproc + 1)))
for sh in out.addressable_shards:
    vals = {float(v) for v in sh.data.ravel()}
    assert vals == {expect}, (vals, expect)
print(f"DIST_OK proc {pid}: fleet psum == {expect}", flush=True)

# ---- phase 2: the FULL sharded fleet step across the process boundary ----
# The same step the driver dry-runs on a single-process virtual mesh
# (__graft_entry__.dryrun_multichip), here with the fleet axis genuinely
# spanning two OS processes: the slab-delta psum map-merge and the coarse
# frontier all_gather both cross Gloo.
from __graft_entry__ import _tiny                        # noqa: E402
from jax_mapping.parallel import fleet_sharded as FS     # noqa: E402
from jax_mapping.sim import world as W                   # noqa: E402

cfg = _tiny(2 * nproc)
world = jnp.asarray(W.empty_arena(96, cfg.grid.resolution_m))
state = FS.init_sharded_state(cfg, mesh)
step = FS.make_fleet_step(cfg, mesh, cfg.grid.resolution_m)
state, metrics = step(state, world)
jax.block_until_ready(state)
err = float(metrics["mean_pose_err_m"])
assert err == err and err < 1.0, err
print(f"DIST_OK proc {pid}: sharded fleet step across processes, "
      f"mean_pose_err={err:.4f} m", flush=True)
