"""Worker process for the multi-process DCN integration test.

Run as: python _dist_worker.py <process_id> <num_processes> <port>.
Each process owns 2 virtual CPU devices; the hybrid ('fleet', 'space')
mesh places fleet across processes (the DCN axis) and space within one.
The psum checked here is the fleet map-merge collective
(parallel/fleet_sharded.py's per-step log-odds merge).
"""
import functools
import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_MAPPING_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["JAX_MAPPING_NUM_PROCESSES"] = str(nproc)
os.environ["JAX_MAPPING_PROCESS_ID"] = str(pid)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax                                               # noqa: E402
import jax.numpy as jnp                                  # noqa: E402
from jax.experimental.shard_map import shard_map         # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P   # noqa: E402

from jax_mapping.parallel import distributed as D        # noqa: E402

assert D.initialize(D.DistConfig.from_env()), "initialize() returned False"
assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == 2 * nproc, len(jax.devices())

mesh = D.hybrid_fleet_mesh(n_hosts=nproc, space_per_host=2)
assert dict(zip(mesh.axis_names, mesh.devices.shape)) == \
    {"fleet": nproc, "space": 2}


@functools.partial(shard_map, mesh=mesh, in_specs=P("fleet", "space"),
                   out_specs=P(None, "space"))
def merge(x):
    return jax.lax.psum(x, "fleet")


def shard_data(idx):
    fleet_i = idx[0].start // 2          # 2 rows per fleet host
    return jnp.ones((2, 2), jnp.float32) * (fleet_i + 1)


arr = jax.make_array_from_callback(
    (nproc * 2, 4), NamedSharding(mesh, P("fleet", "space")), shard_data)
out = merge(arr)
expect = float(sum(range(1, nproc + 1)))
for sh in out.addressable_shards:
    vals = {float(v) for v in sh.data.ravel()}
    assert vals == {expect}, (vals, expect)
print(f"DIST_OK proc {pid}: fleet psum == {expect}", flush=True)

# ---- phase 2: the FULL sharded fleet step across the process boundary ----
# The same step the driver dry-runs on a single-process virtual mesh
# (__graft_entry__.dryrun_multichip), here with the fleet axis genuinely
# spanning two OS processes: the slab-delta psum map-merge and the coarse
# frontier all_gather both cross Gloo.
from __graft_entry__ import _tiny                        # noqa: E402
from jax_mapping.parallel import fleet_sharded as FS     # noqa: E402
from jax_mapping.sim import world as W                   # noqa: E402

cfg = _tiny(2 * nproc)
world = jnp.asarray(W.empty_arena(96, cfg.grid.resolution_m))
state = FS.init_sharded_state(cfg, mesh)
step = FS.make_fleet_step(cfg, mesh, cfg.grid.resolution_m)
state, metrics = step(state, world)
jax.block_until_ready(state)
err = float(metrics["mean_pose_err_m"])
assert err == err and err < 1.0, err
print(f"DIST_OK proc {pid}: sharded fleet step across processes, "
      f"mean_pose_err={err:.4f} m", flush=True)

# ---- phase 3: sharded 3D voxel fusion across the process boundary -------
# 'fleet' (the depth-image batch + its merge psum) spans Gloo; 'space'
# (the Y-slab grid layout) stays host-local — and the result must equal
# the single-device patch path bit-for-bit (the exact-parity contract of
# parallel/voxel_sharded.py).
import numpy as np                                       # noqa: E402

from jax_mapping.ops import voxel as V                   # noqa: E402
from jax_mapping.parallel import voxel_sharded as VS     # noqa: E402
from jax_mapping.sim import depthcam as DC               # noqa: E402

vox, cam = cfg.voxel, cfg.depthcam
B = 2 * nproc
poses_np = np.stack([
    np.linspace(-0.5, 0.5, B),
    np.zeros(B),
    np.linspace(0.0, 6.0, B),
], axis=1).astype(np.float32)
depths_np = np.asarray(DC.render_depths(
    cam, world, cfg.grid.resolution_m, 48, jnp.asarray(poses_np)))

vshard = VS.voxel_sharding(mesh)
Z, Y, X = vox.size_z_cells, vox.size_y_cells, vox.size_x_cells
vgrid = jax.make_array_from_callback(
    (Z, Y, X), vshard, lambda idx: np.zeros(
        (len(range(*idx[0].indices(Z))), len(range(*idx[1].indices(Y))),
         len(range(*idx[2].indices(X)))), np.float32))
depths_g = jax.make_array_from_callback(
    (B, cam.height_px, cam.width_px),
    NamedSharding(mesh, P("fleet", None, None)),
    lambda idx: depths_np[idx])
poses_g = jax.make_array_from_callback(
    (B, 3), NamedSharding(mesh, P("fleet", None)),
    lambda idx: poses_np[idx])

fuse = VS.make_voxel_fuse_step(vox, cam, mesh)
out = fuse(vgrid, depths_g, poses_g)
jax.block_until_ready(out)

ref = np.asarray(V.fuse_depths(vox, cam, V.empty_voxel_grid(vox),
                               jnp.asarray(depths_np),
                               jnp.asarray(poses_np)))
n_evidence = 0
for sh in out.addressable_shards:
    got = np.asarray(sh.data)
    want = ref[tuple(sh.index)]
    np.testing.assert_allclose(got, want, atol=1e-5)
    n_evidence += int((np.abs(got) > 0).sum())
assert n_evidence > 0, "voxel fuse produced no evidence on this host"
print(f"DIST_OK proc {pid}: sharded voxel fuse across processes matches "
      f"the patch path ({n_evidence} voxels updated locally)", flush=True)
