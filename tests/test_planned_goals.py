"""On-device planned frontier steering (FrontierConfig.planned_goals).

`frontier.assigned_waypoints`: target-seeded multigrid cost fields
descended greedily from each robot's cell — the fleet model steers along
the min-plus shortest path toward its assignment instead of straight at
it. Off by default (a second cost_fields pass ~doubles the
obstacle-aware frontier cost); these tests pin the geometry, the fleet
integration, and sharded/unsharded agreement.
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax_mapping.config import tiny_config
from jax_mapping.ops import frontier as F


@pytest.fixture(scope="module")
def cfg():
    c = tiny_config()
    return dataclasses.replace(
        c, frontier=dataclasses.replace(c.frontier, planned_goals=True))


def test_waypoint_routes_around_wall(cfg):
    """Robot west of a wall, assigned target directly east of it, gap to
    the north: the waypoint must lead NORTH (around), not east (into the
    wall)."""
    g, f = cfg.grid, cfg.frontier
    n = g.size_cells
    lo = np.full((n, n), -1.0, np.float32)   # all known free
    mid = n // 2
    lo[:, mid - 2:mid + 2] = 3.0             # wall
    lo[n - 48:n - 16, mid - 2:mid + 2] = -1.0   # gap near the top
    res = g.resolution_m
    ox, oy = g.origin_m
    robot_y = oy + 40 * res
    poses = jnp.asarray([[ox + 40 * res, robot_y, 0.0]], jnp.float32)
    # Hand-built target east of the wall at the robot's latitude.
    targets = jnp.asarray([[ox + (n - 40) * res, robot_y]], jnp.float32)
    assignment = jnp.asarray([0], jnp.int32)
    wps, valid = F.assigned_waypoints(f, g, jnp.asarray(lo), poses,
                                      targets, assignment)
    wps, valid = np.asarray(wps), np.asarray(valid)
    assert valid[0]
    assert wps[0, 1] > robot_y + res, (
        f"waypoint {wps[0]} does not detour toward the gap")
    # And it must not have crossed the wall.
    assert wps[0, 0] < ox + mid * res


def test_waypoint_invalid_cases(cfg):
    g, f = cfg.grid, cfg.frontier
    n = g.size_cells
    lo = np.full((n, n), -1.0, np.float32)
    res, (ox, oy) = g.resolution_m, g.origin_m
    poses = jnp.asarray([[ox + 40 * res, oy + 40 * res, 0.0]], jnp.float32)
    targets = jnp.asarray([[ox + 200 * res, oy + 40 * res]], jnp.float32)
    # Unassigned robot: invalid.
    _wps, valid = F.assigned_waypoints(f, g, jnp.asarray(lo), poses,
                                       targets, jnp.asarray([-1]))
    assert not bool(np.asarray(valid)[0])
    # Robot already at the target cell: invalid (caller keeps raw target).
    _wps, valid = F.assigned_waypoints(
        f, g, jnp.asarray(lo), poses,
        jnp.asarray([[ox + 40 * res, oy + 40 * res]], jnp.float32),
        jnp.asarray([0]))
    assert not bool(np.asarray(valid)[0])


def test_fleet_step_with_planned_goals(cfg):
    """fleet_step compiles and runs with planned steering on; the policy
    stays finite and the map still fuses."""
    from jax_mapping.models import fleet as FM
    from jax_mapping.ops import grid as G
    from jax_mapping.sim import world as W

    c = dataclasses.replace(
        cfg, fleet=dataclasses.replace(cfg.fleet, n_robots=4))
    world = jnp.asarray(W.empty_arena(96, c.grid.resolution_m))
    state = FM.init_fleet_state(c, jax.random.PRNGKey(0))
    for _ in range(3):
        state, diag = FM.fleet_step(c, state, c.grid.resolution_m, world)
    assert np.isfinite(np.asarray(diag.policy.targets)).all()
    occ = np.asarray(G.to_occupancy(c.grid, state.grid))
    assert (occ == 100).sum() > 30


def test_sharded_planned_goals_matches_unsharded_waypoints(cfg):
    """The sharded step's waypoint inputs are the gathered coarse masks;
    the waypoints it computes for its local robots must equal the
    unsharded computation over the same state."""
    g, f = cfg.grid, cfg.frontier
    n = g.size_cells
    rng = np.random.default_rng(3)
    lo = np.zeros((n, n), np.float32)
    lo[40:220, 40:220] = -2.0
    lo[40:220, 128:132] = 2.0
    lo[180:220, 128:132] = -2.0
    poses = np.stack([rng.uniform(-2, 2, 8), rng.uniform(-2, 2, 8),
                      rng.uniform(-3, 3, 8)], 1).astype(np.float32)
    lo_j = jnp.asarray(lo)
    fr = F.compute_frontiers(f, g, lo_j, jnp.asarray(poses))
    wps_a, val_a = F.assigned_waypoints(f, g, lo_j, jnp.asarray(poses),
                                        fr.targets, fr.assignment)
    free, _occ, unk = F.coarsen(f, g, lo_j)
    wps_b, val_b = F.assigned_waypoints_from_masks(
        f, g, free, unk, jnp.asarray(poses), fr.targets, fr.assignment)
    assert (np.asarray(val_a) == np.asarray(val_b)).all()
    assert np.allclose(np.asarray(wps_a), np.asarray(wps_b))


def test_sharded_fleet_step_runs_with_planned_goals(cfg):
    """The full sharded step lowers and runs on the virtual 8-device mesh
    with planned steering on (no extra collectives: the masks are already
    gathered for the assignment)."""
    from jax_mapping.ops import grid as G
    from jax_mapping.parallel import fleet_sharded as FS
    from jax_mapping.parallel import mesh as MESH
    from jax_mapping.sim import world as W

    c = dataclasses.replace(
        cfg, fleet=dataclasses.replace(cfg.fleet, n_robots=8))
    assert len(jax.devices()) == 8
    mesh = MESH.make_mesh(n_fleet=4, n_space=2)
    world = jnp.asarray(W.empty_arena(96, c.grid.resolution_m))
    state = FS.init_sharded_state(c, mesh)
    step = FS.make_fleet_step(c, mesh, c.grid.resolution_m)
    for _ in range(3):
        state, metrics = step(state, world)
    assert int(state.t) == 3
    assert np.isfinite(float(metrics["mean_pose_err_m"]))
    occ = np.asarray(G.to_occupancy(c.grid, state.grid))
    assert (occ == 100).sum() > 30
