"""Mission multi-tenancy (ISSUE 14): megabatched mission step + the
tenant control plane.

The load-bearing contract is BIT-IDENTITY: a tenant's trajectory
inside a megabatch equals its solo `fleet_step` trajectory bit-for-bit
— same seed, any bucket size, any co-tenants (admissions, evictions,
suspensions, pad slots). Everything else (bucket math, control-plane
lifecycle, pre-warm ladder, per-tenant serving namespaces, the live
recompile guard, the cross-thread racewatch gate) hangs off that.

Wall-clock discipline: every megabatch test in this module shares ONE
module-scoped `micro_config`, so each tenant BUCKET compiles at most
once per test process; the cold-cache full admission-ladder gate
(buckets 1..8 from a fresh subprocess, checked against the committed
compile-budget ceiling) is `slow`.
"""

import dataclasses
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import TenancyConfig, micro_config
from jax_mapping.models import fleet as FM
from jax_mapping.sim import world as W
from jax_mapping.tenancy import megabatch as MB
from jax_mapping.tenancy.controlplane import (MEGABATCH_ENTRY,
                                              TenantControlPlane)


@pytest.fixture(scope="module")
def mcfg():
    """ONE mission shape for the whole module: every test's megabatch
    variants land in the same jit cache (buckets compile once)."""
    return dataclasses.replace(
        micro_config(), tenancy=TenancyConfig(enabled=True))


@pytest.fixture(scope="module")
def world_np(mcfg):
    return W.empty_arena(mcfg.grid.size_cells, mcfg.grid.resolution_m)


def _solo_run(cfg, world, seed, n_steps, state=None):
    """The solo-run oracle: `fleet_step` ticked from `seed` (or a
    given state) for n_steps."""
    s = (FM.init_fleet_state(cfg, jax.random.PRNGKey(seed))
         if state is None else state)
    for _ in range(n_steps):
        s, _ = FM.fleet_step(cfg, s, cfg.grid.resolution_m, world)
    return s


def _assert_states_bitequal(a: FM.FleetState, b: FM.FleetState,
                            what: str) -> None:
    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=what)


# ---------------------------------------------------------------- buckets

def test_bucket_capacity_set():
    """Throughput mode serves the full {2^k} ∪ {3·2^(k-1)} set; the
    default bit-exact mode serves only the verified-exact ladder
    (megabatch.EXACT_BUCKETS) and refuses past its top instead of
    silently degrading the contract."""
    got = [MB.bucket_capacity(n, exact=False) for n in range(1, 17)]
    assert got == [1, 2, 3, 4, 6, 6, 8, 8, 12, 12, 12, 12, 16, 16,
                   16, 16]
    assert MB.bucket_capacity(17, exact=False) == 24
    assert MB.bucket_capacity(25, exact=False) == 32
    exact = [MB.bucket_capacity(n) for n in range(1, 13)]
    assert exact == [1, 2, 3, 6, 6, 6, 12, 12, 12, 12, 12, 12]
    assert all(b in MB.EXACT_BUCKETS for b in exact)
    with pytest.raises(ValueError, match="bit-exact bucket ladder"):
        MB.bucket_capacity(MB.EXACT_BUCKETS[-1] + 1)
    with pytest.raises(ValueError):
        MB.bucket_capacity(9, cap=8, exact=False)
    with pytest.raises(ValueError):
        MB.bucket_capacity(0)


def test_make_tenant_batch_pads_inactive(mcfg, world_np):
    s = FM.init_fleet_state(mcfg, jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(0)
    b = MB.make_tenant_batch([s, s], [world_np, world_np], [key, key])
    assert b.active.shape == (2,)
    assert bool(b.active.all())
    b5 = MB.make_tenant_batch([s] * 5, [world_np] * 5, [key] * 5)
    assert b5.worlds.shape[0] == 6          # bucket(5) == 6
    assert np.asarray(b5.active).tolist() == [True] * 5 + [False]
    # Pad lanes duplicate lane 0 — identical shapes, no special path.
    _assert_states_bitequal(MB.lane_state(b5, 5), MB.lane_state(b5, 0),
                            "pad lane != lane 0 copy")


# ----------------------------------------------------- megabatch identity

def test_megabatch_bit_identity_and_exact_noop_pads(mcfg, world_np):
    """Three seeded missions megabatched for 12 ticks are bit-equal to
    their solo runs; a 2-active/1-pad batch at the SAME bucket keeps
    the pad slot frozen bit-for-bit (the exact-no-op pad contract)."""
    res = mcfg.grid.resolution_m
    world = jnp.asarray(world_np)
    key = jax.random.PRNGKey(0)
    states = [FM.init_fleet_state(mcfg, jax.random.PRNGKey(k))
              for k in range(3)]
    b = MB.make_tenant_batch(states, [world_np] * 3, [key] * 3)
    for _ in range(12):
        b, diag, _ = MB.megabatch_tick(mcfg, b, res)
    assert diag.is_key.shape[0] == 3
    for i in range(3):
        _assert_states_bitequal(
            MB.lane_state(b, i), _solo_run(mcfg, world, i, 12),
            f"tenant {i} diverged from its solo run")

    # Same bucket, 2 active + 1 pad: actives bit-equal their solo
    # runs, the pad lane never advances.
    b2 = MB.make_tenant_batch(states[:2], [world_np] * 2, [key] * 2,
                              capacity=3)
    pad_before = MB.lane_state(b2, 2)
    for _ in range(8):
        b2, _, _ = MB.megabatch_tick(mcfg, b2, res)
    for i in range(2):
        _assert_states_bitequal(
            MB.lane_state(b2, i), _solo_run(mcfg, world, i, 8),
            f"tenant {i} perturbed by the pad slot")
    _assert_states_bitequal(MB.lane_state(b2, 2), pad_before,
                            "pad slot advanced")


def test_bucket_churn_bit_identity(mcfg, world_np, tmp_path):
    """Admission/eviction churn across a bucket boundary (2 -> 3 -> 2)
    keeps every surviving tenant bit-identical to a solo run of the
    same total tick count, and the compiled megabatch variants stay
    within the committed budget ceiling."""
    world = jnp.asarray(world_np)
    cp = TenantControlPlane(
        dataclasses.replace(mcfg, tenancy=TenancyConfig(
            enabled=True, prewarm_on_admit=False)),
        checkpoint_dir=str(tmp_path))
    cp.admit("a", world_np, seed=0)
    cp.admit("b", world_np, seed=1)
    cp.step(3)                                    # bucket 2
    cp.admit("c", world_np, seed=2)
    cp.step(4)                                    # bucket 3 (grow)
    cp.evict("b")                                 # compact back to 2
    cp.step(5)
    _assert_states_bitequal(cp.tenant_state("a"),
                            _solo_run(mcfg, world, 0, 12),
                            "tenant a diverged across churn")
    _assert_states_bitequal(cp.tenant_state("c"),
                            _solo_run(mcfg, world, 2, 9),
                            "tenant c diverged across churn")
    st = cp.status()
    assert st["n_active"] == 2 and st["n_evicted"] == 1
    assert st["bucket_capacity"] == 2             # shrank, not padded

    # Variant ceiling: everything this module compiled must fit the
    # committed compile-budget entry (the cold-cache ladder gate is
    # the slow subprocess test below).
    from jax_mapping.analysis.compilebudget import (Budget,
                                                    default_budget_path)
    entry = Budget.load(default_budget_path()).by_name[MEGABATCH_ENTRY]
    n_variants = int(MB.megabatch_step._cache_size())
    assert 0 < n_variants <= entry["max"], (
        f"{n_variants} megabatch variants vs budget {entry['max']}")


def _closure_poised_state(cfg) -> FM.FleetState:
    """A FleetState whose next key tick finds an own-graph loop
    candidate: a fabricated chain that left the search radius
    mid-chain (loop_candidate's departure rule) and returned near the
    current estimate."""
    from jax_mapping.ops import posegraph as PG

    R = cfg.fleet.n_robots
    cap = cfg.loop.max_poses
    s = FM.init_fleet_state(cfg, jax.random.PRNGKey(0))
    n = cfg.loop.min_chain_size + 5
    poses = np.zeros((R, cap, 3), np.float32)
    # Out past the radius and back: candidates 0..n-1-min_chain sit
    # near the estimate, the excursion satisfies "departed".
    for j in range(n):
        frac = j / max(1, n - 1)
        out = (cfg.loop.search_radius_m + 2.0) * np.sin(np.pi * frac)
        poses[:, j, 0] = 0.02 * j + out
        poses[:, j, 2] = 0.1 * j
    valid = np.zeros((R, cap), bool)
    valid[:, :n] = True
    g = jax.vmap(lambda _: PG.empty_graph(cfg.loop))(jnp.arange(R))
    g = g._replace(poses=jnp.asarray(poses),
                   pose_valid=jnp.asarray(valid),
                   n_poses=jnp.full((R,), n, jnp.int32))
    rng = np.random.default_rng(3)
    rings = jnp.asarray(rng.uniform(
        0.05, cfg.scan.range_max_m,
        (R, cap, cfg.scan.padded_beams)).astype(np.float32))
    return s._replace(graphs=g, scan_rings=rings)


def test_closure_pending_resolves_via_solo_executable(mcfg, world_np):
    """A closure-poised tenant raises its pending flag in the jitted
    no-closure step, and `megabatch_tick` resolves that lane through
    the solo `fleet_step` executable bit-exactly (state AND diag row)
    while the co-tenant rides the batch undisturbed — the host-hop
    design that keeps closure ticks bit-identical (no cross-executable
    bit-stability on XLA:CPU; see megabatch.py's module docstring)."""
    res = mcfg.grid.resolution_m
    world = jnp.asarray(world_np)
    key = jax.random.PRNGKey(0)
    normal = FM.init_fleet_state(mcfg, jax.random.PRNGKey(1))
    poised = _closure_poised_state(mcfg)
    b = MB.make_tenant_batch([normal, poised], [world_np] * 2,
                             [key] * 2)
    _, _, pending, _ = MB.megabatch_step(mcfg, b, res)
    assert np.asarray(pending).tolist() == [False, True], (
        "the poised lane did not raise its closure-pending flag")
    b2, diag, _ = MB.megabatch_tick(mcfg, b, res)
    want_s, want_d = FM.fleet_step(mcfg, poised, res, world)
    _assert_states_bitequal(MB.lane_state(b2, 1), want_s,
                            "pending lane != solo fleet_step")
    for bx, sx in zip(
            jax.tree_util.tree_leaves(
                jax.tree.map(lambda x: x[1], diag)),
            jax.tree_util.tree_leaves(want_d)):
        np.testing.assert_array_equal(np.asarray(bx), np.asarray(sx),
                                      err_msg="pending lane diag row")
    solo_normal, _ = FM.fleet_step(mcfg, normal, res, world)
    _assert_states_bitequal(MB.lane_state(b2, 0), solo_normal,
                            "co-tenant perturbed by closure resolve")


# --------------------------------------------------- control plane

def test_controlplane_lifecycle(mcfg, world_np, tmp_path):
    """admit -> suspend (compaction) -> resume (epoch bump) -> evict
    (generation-retained checkpoint); per-tenant revision clocks; the
    /status + /metrics surfaces; flight-recorded transitions."""
    from jax_mapping.obs.recorder import flight_recorder

    mark = flight_recorder.mark()
    cp = TenantControlPlane(mcfg, checkpoint_dir=str(tmp_path))
    cp.admit("t0", world_np, seed=0)
    assert cp.n_prewarms == 1                  # bucket-1 pre-warm ran
    assert cp.warmup.state() == "ready"
    cp.admit("t1", world_np, seed=1)
    cp.step(2)
    assert cp.revision("t0") == 2 and cp.revision("t1") == 2
    assert cp.epoch("t0") == 0

    held_rev = cp.revision("t0")
    cp.suspend("t0")
    st = cp.status()
    assert st["n_active"] == 1 and st["n_suspended"] == 1
    assert st["bucket_capacity"] == 1          # compacted, not padded
    cp.step(1)
    assert cp.revision("t0") == held_rev       # suspended clock frozen
    assert cp.revision("t1") == 3

    cp.resume("t0")
    assert cp.epoch("t0") == 1                 # per-tenant restart epoch
    # Re-admission bumps the revision too (the epoch⇒revision ETag
    # contract), then the tick advances it again.
    cp.step(1)
    assert cp.revision("t0") == held_rev + 2

    path = cp.evict("t1")
    assert path is not None and os.path.exists(path)
    from jax_mapping.io.checkpoint import load_checkpoint
    like = FM.init_fleet_state(mcfg, jax.random.PRNGKey(1))
    restored, meta = load_checkpoint(path, like)
    assert int(np.asarray(restored.t)) == 4    # t1 ticked 4 times
    # An evicted mission re-admits from its checkpoint like a resume.
    cp.admit("t1", world_np, seed=1, state=restored)
    assert cp.epoch("t1") == 1

    # Pad-waste / occupancy telemetry and the metric families render.
    st = cp.status()
    assert 0.0 <= st["pad_waste_frac"] < 1.0
    fams = {f.name for f in cp.metric_families()}
    assert {"jax_mapping_tenant_active",
            "jax_mapping_tenant_bucket_occupancy",
            "jax_mapping_tenant_pad_waste_frac"} <= fams
    kinds = {e["kind"] for e in flight_recorder.events_since(mark)}
    assert {"tenancy_admit", "tenancy_suspend", "tenancy_resume",
            "tenancy_evict", "warmup_stage"} <= kinds


def test_tenant_tile_store_namespaces(mcfg, world_np):
    """`/tiles?tenant=` correctness core: each tenant's store lives in
    its OWN (epoch, revision) namespace — revisions advance with the
    tenant's ticks, a suspend/resume cycle bumps the epoch (the
    per-mission restart-epoch contract), and a suspended tenant still
    serves its held state."""
    cp = TenantControlPlane(mcfg)
    cp.admit("a", world_np, seed=0)
    cp.step(2)
    store = cp.tile_store("a")
    rev = store.refresh()
    assert rev == cp.revision("a") == 2
    r, entries, meta = store.tiles_since(-1)
    assert r == 2 and len(entries) > 0
    r2, entries2, _ = store.tiles_since(r)
    assert r2 == 2 and entries2 == []          # delta session current
    cp.suspend("a")
    assert cp.tile_store("a").refresh() == 2   # held state still served
    cp.resume("a")
    assert cp.epoch("a") == 1                  # ETag namespace advances
    assert cp.revision("a") == 3               # epoch⇒revision bump
    cp.step(1)
    assert cp.tile_store("a").refresh() == 4


def test_live_recompile_guard_with_tenancy_armed(mcfg, world_np):
    """The ISSUE 10 live recompile guard, tenancy armed: after the
    admission pre-warm (which re-baselines the profiler), continued
    stepping and churn WITHIN warmed buckets must compile zero new
    megabatch variants."""
    from jax_mapping.obs.devprof import DispatchProfiler

    prof = DispatchProfiler()
    prof.install()
    try:
        cp = TenantControlPlane(mcfg, devprof=prof)
        cp.admit("a", world_np, seed=0)
        cp.admit("b", world_np, seed=1)        # buckets 1, 2 pre-warmed
        cp.step(4)
        cp.suspend("b")
        cp.step(2)
        cp.resume("b")
        cp.step(2)
        recs = prof.recompiles()
        assert recs.get(MEGABATCH_ENTRY, 0) == 0, (
            "megabatch recompiled post-warm-up: "
            f"{recs.get(MEGABATCH_ENTRY)}")
    finally:
        prof.uninstall()


def test_racewatch_admit_evict_cross_thread(mcfg, world_np):
    """Eraser lockset gate over the control plane: concurrent
    admit/evict churn, stepping and status polling from separate
    threads produce zero race reports, and the batch field's candidate
    lockset converges on the declared `_lock`."""
    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch

    cp = TenantControlPlane(
        dataclasses.replace(mcfg, tenancy=TenancyConfig(
            enabled=True, prewarm_on_admit=False)))
    cp.admit("base", world_np, seed=0)
    cp.step(1)                                 # warm bucket 1 inline
    watch = RaceWatch()
    errors = []
    try:
        watch.watch_object(cp, groups_by_class()["TenantControlPlane"][0],
                           name="tenancy")
        stop = threading.Event()

        def churner():
            i = 0
            while not stop.is_set():
                try:
                    tid = f"x{i}"
                    cp.admit(tid, world_np, seed=i + 1)
                    cp.evict(tid, checkpoint=False)
                except Exception as e:         # noqa: BLE001
                    errors.append(f"churn: {e}")
                i += 1
                stop.wait(0.01)

        def poller():
            while not stop.is_set():
                try:
                    cp.status()
                    cp.metric_families()
                except Exception as e:         # noqa: BLE001
                    errors.append(f"status: {e}")
                stop.wait(0.005)

        threads = [threading.Thread(target=churner),
                   threading.Thread(target=poller)]
        for t in threads:
            t.start()
        for _ in range(6):
            cp.step(1)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
    finally:
        watch.unwatch_all()
    assert not errors, errors
    assert watch.reports() == []
    states = watch.field_states()
    batch_states = [s for name, s in states.items()
                    if "._batch@" in name or name.endswith("._batch")]
    assert batch_states, "racewatch never saw the batch field"
    for s in batch_states:
        assert s.candidate is None or any(
            "_lock" in c for c in s.candidate), (
            f"{s.name} lockset did not converge on _lock: "
            f"{s.candidate}")


# ------------------------------------------------------- stack wiring

def test_tenancy_disabled_constructs_nothing(world_np):
    """TenancyConfig.enabled=False: no control plane on the stack, no
    megabatch entry point ever traced — bit-exact pre-tenancy."""
    from jax_mapping.bridge.launch import launch_sim_stack

    cfg = micro_config()
    assert not cfg.tenancy.enabled
    st = launch_sim_stack(cfg, world_np, n_robots=1, http_port=None,
                          realtime=False, seed=0)
    try:
        assert st.tenancy is None
    finally:
        st.shutdown()


def test_stack_tenancy_http_surfaces(mcfg, world_np):
    """Launch wiring + HTTP: /status.tenancy, jax_mapping_tenant_*
    metrics, and per-tenant /tiles delta sessions with (epoch,
    revision)-keyed ETags."""
    import urllib.request

    from jax_mapping.bridge.launch import launch_sim_stack

    st = launch_sim_stack(mcfg, world_np, n_robots=1, http_port=0,
                          realtime=False, seed=0)
    try:
        assert st.tenancy is not None
        st.tenancy.admit("m0", world_np, seed=0)
        st.tenancy.step(2)
        base = f"http://127.0.0.1:{st.api.port}"
        body = json.loads(urllib.request.urlopen(
            f"{base}/status", timeout=10).read())
        assert body["tenancy"]["n_active"] == 1
        assert body["tenancy"]["tenants"]["m0"]["revision"] == 2
        metrics = urllib.request.urlopen(
            f"{base}/metrics", timeout=10).read().decode()
        assert "jax_mapping_tenant_active 1" in metrics
        # Per-tenant delta session: full snapshot, then a 304 on the
        # same (epoch, revision) ETag.
        req = urllib.request.urlopen(
            f"{base}/tiles?tenant=m0&since=-1", timeout=10)
        etag = req.headers["ETag"]
        doc = json.loads(req.read())
        assert doc["revision"] == 2 and doc["epoch"] == 0
        assert len(doc["tiles"]) > 0
        r2 = urllib.request.Request(f"{base}/tiles?tenant=m0&since=2",
                                    headers={"If-None-Match": etag})
        try:
            resp = urllib.request.urlopen(r2, timeout=10)
            assert resp.status == 304
        except urllib.error.HTTPError as e:    # urllib treats 304 as err
            assert e.code == 304
        # Unknown tenant: 404, not a 500.
        try:
            urllib.request.urlopen(f"{base}/tiles?tenant=nope&since=-1",
                                   timeout=10)
            assert False, "unknown tenant should 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        st.shutdown()


def test_cotenant_independence_beyond_exact_ladder(mcfg, world_np):
    """At capacities past the bit-exact ladder (throughput mode) the
    per-lane guarantee that REMAINS exact is co-tenant independence:
    a lane's trajectory is bit-identical whatever data the other
    lanes carry — one executable, lanewise-independent arithmetic.
    (Solo parity past the ladder is ulp-faithful only; EXACT_BUCKETS
    documents the backend boundary.)"""
    res = mcfg.grid.resolution_m
    key = jax.random.PRNGKey(0)

    def run(co_seeds):
        states = [FM.init_fleet_state(mcfg, jax.random.PRNGKey(0))] + [
            FM.init_fleet_state(mcfg, jax.random.PRNGKey(s))
            for s in co_seeds]
        b = MB.make_tenant_batch(states, [world_np] * 4, [key] * 4,
                                 capacity=4)
        for _ in range(8):
            b, _, _ = MB.megabatch_tick(mcfg, b, res)
        return MB.lane_state(b, 0)

    _assert_states_bitequal(run([1, 2, 3]), run([7, 8, 9]),
                            "lane 0 perturbed by co-tenant data")


def _clean_cpu_env() -> dict:
    """Subprocess env for the solo-parity gates: CPU-pinned and WITHOUT
    the test harness's `--xla_force_host_platform_device_count=8`
    virtual mesh — that flag shifts LLVM's vectorization thresholds
    enough to perturb ulps even at ladder buckets (the EXACT_BUCKETS
    gotcha), and production megabatches do not run under it."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    return env


# ------------------------------------------------- cold-cache ladder gate

@pytest.mark.slow
def test_bucket_edge_ladder_cold_subprocess(tmp_path):
    """THE bucket-edge gate, from cold caches: a fresh process admits
    tenants one at a time up to 8 (walking the bit-exact ladder
    capacities 1,2,3,6,12), then shrinks 8 -> 5 (capacity 6, already
    compiled); every surviving tenant stays bit-identical to its solo
    run across every boundary crossing and the compiled variant count
    never exceeds the committed budget ceiling."""
    script = r"""
import dataclasses, json, sys
import numpy as np
import jax
from jax_mapping.config import TenancyConfig, micro_config
from jax_mapping.models import fleet as FM
from jax_mapping.sim import world as W
from jax_mapping.tenancy import megabatch as MB
from jax_mapping.tenancy.controlplane import (MEGABATCH_ENTRY,
                                              TenantControlPlane)

cfg = dataclasses.replace(micro_config(), tenancy=TenancyConfig(
    enabled=True, prewarm_on_admit=False))
world_np = W.empty_arena(cfg.grid.size_cells, cfg.grid.resolution_m)
world = jax.numpy.asarray(world_np)
cp = TenantControlPlane(cfg)
ticks = {}
for m in range(8):
    cp.admit(f"m{m}", world_np, seed=m)
    ticks[f"m{m}"] = 0
    cp.step(1)
    for t in ticks:
        ticks[t] += 1
for m in range(5, 8):
    cp.evict(f"m{m}", checkpoint=False)      # 8 -> 5: bucket 6
cp.step(2)
for t in list(ticks):
    if t in (f"m{m}" for m in range(5, 8)):
        del ticks[t]
    else:
        ticks[t] += 2
for tid, n in ticks.items():
    seed = int(tid[1:])
    s = FM.init_fleet_state(cfg, jax.random.PRNGKey(seed))
    for _ in range(n):
        s, _ = FM.fleet_step(cfg, s, cfg.grid.resolution_m, world)
    got = jax.tree_util.tree_leaves(cp.tenant_state(tid))
    want = jax.tree_util.tree_leaves(s)
    for a, b in zip(got, want):
        assert np.array_equal(np.asarray(a), np.asarray(b)), tid
print(json.dumps({"variants": int(MB.megabatch_step._cache_size()),
                  "entry": MEGABATCH_ENTRY}))
"""
    r = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=900, env=_clean_cpu_env())
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr[-3000:]}"
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    from jax_mapping.analysis.compilebudget import (Budget,
                                                    default_budget_path)
    entry = Budget.load(default_budget_path()).by_name[doc["entry"]]
    # Exact-ladder capacities visited: 1,2,3,6,12 — one compiled
    # variant each; the 8->5 shrink re-uses the 6-capacity (no 6th
    # variant).
    assert doc["variants"] == 5
    assert doc["variants"] <= entry["max"]


@pytest.mark.slow
def test_megabatch_closure_mission_bit_identity():
    """A closure-heavy mission (rooms world, tight key gate, SMALL
    search radius — loop_candidate's departure rule needs the robot to
    LEAVE the disc and come back — permissive verification): loop
    closures actually FIRE, and the megabatched trajectories — with
    every closure tick resolved through the solo `fleet_step`
    executable (the pending-hop) — stay bit-identical to the solo
    runs. Runs in a CLEAN subprocess: the harness's virtual-mesh flag
    perturbs the backend's lowering (see _clean_cpu_env)."""
    script = r"""
import dataclasses, numpy as np, jax, jax.numpy as jnp
from jax_mapping.config import micro_config
from jax_mapping.models import fleet as FM
from jax_mapping.tenancy import megabatch as MB
from jax_mapping.sim import world as W

cfg = micro_config()
cfg = dataclasses.replace(
    cfg,
    matcher=dataclasses.replace(cfg.matcher, min_travel_m=0.004,
                                min_heading_rad=0.03),
    loop=dataclasses.replace(cfg.loop, min_chain_size=3,
                             search_radius_m=0.12,
                             response_coarse=0.02,
                             response_fine=0.02, loop_window_m=0.4))
res = cfg.grid.resolution_m
out = W.rooms_world(64, res)
world_np = out[0] if isinstance(out, tuple) else out
world = jnp.asarray(world_np)
key = jax.random.PRNGKey(0)
states = [FM.init_fleet_state(cfg, jax.random.PRNGKey(k))
          for k in range(2)]
b = MB.make_tenant_batch(states, [world_np] * 2, [key] * 2)
closed = 0
n_steps = 150
for _ in range(n_steps):
    b, diag, _ = MB.megabatch_tick(cfg, b, res)
    closed += int(np.asarray(diag.loop_closed).sum())
assert closed > 0, "closure branch never fired"
for i in range(2):
    s = FM.init_fleet_state(cfg, jax.random.PRNGKey(i))
    for _ in range(n_steps):
        s, _ = FM.fleet_step(cfg, s, res, world)
    got = jax.tree_util.tree_leaves(MB.lane_state(b, i))
    want = jax.tree_util.tree_leaves(s)
    for a, w in zip(got, want):
        assert np.array_equal(np.asarray(a), np.asarray(w)), (
            f"tenant {i} diverged through closure ticks")
print("OK", closed)
"""
    r = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=900, env=_clean_cpu_env())
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr[-3000:]}"
    assert r.stdout.strip().startswith("OK")


# ----------------------------------------------- bounded-memory tenancy

def test_windowed_mission_config_window_sized_lanes(mcfg):
    """ISSUE 18 satellite: under `world.windowed` every tenant lane
    runs at the WINDOW-sized grid (the single window_slam_config
    derivation), identity object when not windowed, and the control
    plane applies the transform once at construction."""
    from jax_mapping.config import WorldConfig
    from jax_mapping.tenancy.controlplane import TenantControlPlane
    from jax_mapping.world.store import window_slam_config

    # Knob off: the SAME object, not an equal copy — bit-exact pre-PR.
    assert MB.windowed_mission_config(mcfg) is mcfg

    wcfg_in = dataclasses.replace(
        mcfg,
        serving=dataclasses.replace(mcfg.serving, tile_cells=8),
        world=WorldConfig(windowed=True, window_tiles=4,
                          margin_tiles=1))
    out = MB.windowed_mission_config(wcfg_in)
    # ONE derivation: bit-equal to the store's own.
    assert out == window_slam_config(wcfg_in)
    assert out.grid.size_cells == 4 * 8            # the window
    # Everything that shapes kernels EXCEPT the lattice is untouched.
    assert out.grid.patch_cells == wcfg_in.grid.patch_cells
    assert out.scan == wcfg_in.scan
    assert out.matcher == wcfg_in.matcher
    assert out.loop == wcfg_in.loop

    # Lane state actually lands on the window shape (N tenants cost
    # N x window^2 device cells, not N x logical^2).
    s = FM.init_fleet_state(out, jax.random.PRNGKey(0))
    assert s.grid.shape == (32, 32)

    # The control plane transforms ONCE at construction, so lane
    # init / checkpoints / serving all agree on shapes.
    plane = TenantControlPlane(wcfg_in)
    assert plane.cfg.grid.size_cells == 32

    # The derivation refuses ill-posed windows rather than mis-shaping
    # lanes: a window smaller than the fuse patch cannot host a scan.
    bad = dataclasses.replace(
        wcfg_in, world=WorldConfig(windowed=True, window_tiles=2,
                                   margin_tiles=0))
    with pytest.raises(ValueError, match="exceeds the window"):
        MB.windowed_mission_config(bad)
