"""Assigned-frontier exploration through the bridge brain.

The mapper has always PUBLISHED /frontiers (targets + per-robot
assignment); until round 5 nothing drove the robots with it — the bridge
stack explored reactively (blind cruise + shield) while the assignments
only fed RViz markers. FrontierConfig.seek_assigned wires them into the
brain's goal-seek: the map-based explorer the reference's report defers
to future work (report.pdf §VI.2), actually steering the fleet.
"""

import dataclasses
import math

import numpy as np

from jax_mapping.bridge.messages import FrontierArray, Header


def _bare_brain(tiny_cfg, seek=True, n_robots=1):
    from jax_mapping.bridge.brain import ThymioBrain
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.driver import SimulatedThymioDriver

    cfg = dataclasses.replace(
        tiny_cfg,
        robot=dataclasses.replace(tiny_cfg.robot, cruise_speed_units=300),
        frontier=dataclasses.replace(tiny_cfg.frontier,
                                     seek_assigned=seek))
    bus = Bus()
    brain = ThymioBrain(cfg, bus, SimulatedThymioDriver(n_robots=n_robots),
                        n_robots=n_robots)
    return bus, brain


def _publish_frontiers(bus, targets, assignment):
    bus.publisher("/frontiers").publish(FrontierArray(
        header=Header.now("map"),
        targets_xy=np.asarray(targets, np.float32),
        sizes=np.full(len(targets), 10, np.int32),
        assignment=np.asarray(assignment, np.int32)))


def test_brain_steers_to_assigned_frontier(tiny_cfg):
    """A frontier BEHIND the robot: with seek the robot turns around and
    closes distance; without it the blind cruise drives away. The bare
    brain + simulated driver is a pure kinematic rig (no LiDAR walls, no
    mapper interference)."""
    results = {}
    for seek in (True, False):
        bus, brain = _bare_brain(tiny_cfg, seek=seek)
        try:
            brain.start_exploring()
            target = (-1.0, 0.0)             # robot starts at 0,0 facing +x
            d0 = math.hypot(*target)
            for _ in range(120):
                _publish_frontiers(bus, [target], [0])
                brain.update_loop()
                # Perfect-response physics: written targets become the
                # measured speeds the next tick reads (the sim node's
                # ingest_state role, minus the lag model).
                brain.driver.ingest_state(brain.driver.targets(),
                                          np.zeros((1, 7), np.int32))
            p = brain.robot_pose(0)
            results[seek] = math.hypot(p[0] - target[0], p[1] - target[1])
        finally:
            brain.destroy()
    assert results[True] < d0 * 0.6, (
        f"seek never closed on the frontier (d={results[True]:.2f})")
    assert results[False] > d0, (
        "blind cruise unexpectedly approached the rear frontier — the "
        "control rig no longer distinguishes the modes")


def test_manual_goal_outranks_frontier(tiny_cfg):
    """Robot 0's RViz nav goal wins over its frontier assignment; other
    robots still take theirs."""
    bus, brain = _bare_brain(tiny_cfg, n_robots=2)
    try:
        brain.start_exploring()
        goals = np.zeros((2, 2), np.float32)
        valid = np.zeros(2, bool)
        goals[0] = (2.0, 2.0)                # manual goal, robot 0
        valid[0] = True
        _publish_frontiers(bus, [(-1.0, 0.0), (0.0, -1.0)], [0, 1])
        brain._apply_frontier_goals(goals, valid)
        assert valid.all()
        assert tuple(goals[0]) == (2.0, 2.0)           # manual goal kept
        assert tuple(goals[1]) == (0.0, -1.0)          # assignment applied
    finally:
        brain.destroy()


def test_unassigned_and_stale_frontiers_ignored(tiny_cfg):
    bus, brain = _bare_brain(tiny_cfg)
    try:
        goals = np.zeros((1, 2), np.float32)
        valid = np.zeros(1, bool)
        _publish_frontiers(bus, [(1.0, 1.0)], [-1])    # no reachable one
        brain._apply_frontier_goals(goals, valid)
        assert not valid.any()
        _publish_frontiers(bus, [(1.0, 1.0)], [0])
        brain.n_ticks += int(brain.cfg.frontier.seek_ttl_s
                             * brain.cfg.robot.control_rate_hz) + 1
        brain._apply_frontier_goals(goals, valid)      # stale: mapper dead
        assert not valid.any()
    finally:
        brain.destroy()


def test_frontier_waypoint_preferred_when_matching(tiny_cfg):
    """The brain steers at the planner's per-robot frontier waypoint when
    it is fresh, reachable, and planned for (about) the robot's CURRENT
    assignment — raw target otherwise."""
    from jax_mapping.bridge.messages import Waypoint

    bus, brain = _bare_brain(tiny_cfg, n_robots=2)
    try:
        target = (2.0, 0.0)
        tol = (tiny_cfg.grid.resolution_m * tiny_cfg.frontier.downsample
               * 2.0)

        def wp(robot, goal, reachable=True):
            return Waypoint(header=Header.now("map"), x=0.5, y=0.5,
                            reachable=reachable, goal_x=goal[0],
                            goal_y=goal[1], robot=robot)

        goals = np.zeros((2, 2), np.float32)
        valid = np.zeros(2, bool)
        _publish_frontiers(bus, [target], [0, 0])
        bus.publisher("/frontier_waypoints").publish(wp(0, target))
        brain._apply_frontier_goals(goals, valid)
        assert tuple(goals[0]) == (0.5, 0.5)           # planned waypoint
        assert tuple(goals[1]) == target               # no waypoint: raw

        # Waypoint for a DIFFERENT target (cluster moved): raw target.
        bus.publisher("/frontier_waypoints").publish(
            wp(0, (target[0] + 3 * tol, target[1])))
        goals[:] = 0
        valid[:] = False
        brain._apply_frontier_goals(goals, valid)
        assert tuple(goals[0]) == target

        # Unreachable plan: raw target (blind seek under the shield).
        bus.publisher("/frontier_waypoints").publish(
            wp(0, target, reachable=False))
        goals[:] = 0
        valid[:] = False
        brain._apply_frontier_goals(goals, valid)
        assert tuple(goals[0]) == target
    finally:
        brain.destroy()


def test_planner_publishes_frontier_waypoints(tiny_cfg):
    """Full stack: with no manual goal, the planner plans toward the live
    mapper's assignments and publishes per-robot /frontier_waypoints."""
    import dataclasses as _dc

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    cfg = _dc.replace(
        tiny_cfg,
        # A fast sim platform: cruise at 600 with the saturation range
        # raised to match — frontier_policy now clamps wheel targets to
        # motor_limit_units (the real Thymio's ±600), and this rig's
        # seek steer has always commanded beyond that.
        robot=_dc.replace(tiny_cfg.robot, cruise_speed_units=600,
                          motor_limit_units=1200),
        planner=_dc.replace(tiny_cfg.planner, lookahead_cells=3,
                            bfs_iters=128))
    world = W.empty_arena(96, cfg.grid.resolution_m)
    st = launch_sim_stack(cfg, world, n_robots=2, http_port=None, seed=7)
    try:
        wps = []
        st.bus.subscribe("/frontier_waypoints", callback=wps.append)
        st.brain.start_exploring()
        # Frontier clusters need some explored area before assignments
        # become valid; step until the planner has planned one (bounded).
        for _ in range(30):
            st.run_steps(round(cfg.planner.period_s
                               * cfg.robot.control_rate_hz))
            if st.planner.n_frontier_plans > 0:
                break
        assert st.planner.n_frontier_plans > 0
        assert wps, "no frontier waypoint ever published"
        robots = {w.robot for w in wps}
        assert robots <= {0, 1} and len(robots) >= 1
        for w in wps:
            assert np.isfinite([w.x, w.y]).all()
        # Field dedup: the goal-seeded field is computed once per UNIQUE
        # assigned target, never more than once per plan (robots sharing
        # a cluster share the field).
        assert 0 < st.planner.n_goal_fields <= st.planner.n_frontier_plans
    finally:
        st.shutdown()


def test_stack_explores_toward_frontiers(tiny_cfg):
    """Full stack: with seek the robot leaves its corner of a rooms world
    through the live mapper's assignments and fuses more of the map than
    the blind cruiser over the same budget."""
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    coverage = {}
    for seek in (True, False):
        cfg = dataclasses.replace(
            tiny_cfg,
            robot=dataclasses.replace(tiny_cfg.robot,
                                      cruise_speed_units=600),
            planner=dataclasses.replace(tiny_cfg.planner, enabled=False),
            frontier=dataclasses.replace(tiny_cfg.frontier,
                                         seek_assigned=seek))
        world = W.rooms_world(128, cfg.grid.resolution_m, seed=5)
        st = launch_sim_stack(cfg, world, n_robots=1, http_port=None,
                              seed=6)
        try:
            st.brain.start_exploring()
            st.run_steps(250)
            lo = np.asarray(st.mapper.merged_grid())
            coverage[seek] = int((np.abs(lo) > 0.3).sum())
        finally:
            st.shutdown()
    # Frontier seek must not map LESS than blind wander (it usually maps
    # substantially more; equality-ish can happen in tiny worlds, so the
    # bound is conservative).
    assert coverage[True] >= coverage[False] * 0.8, coverage
