"""Grid kernel tests: transforms, inverse sensor model vs NumPy oracle,
fusion semantics, occupancy export, PNG contract."""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.ops import grid as G
from tests.oracle import classify_patch_np


def test_world_cell_roundtrip(tiny_cfg):
    g = tiny_cfg.grid
    pts = np.array([[0.0, 0.0], [1.0, -2.0], [-3.0, 0.5]], np.float32)
    cells = np.asarray(G.world_to_cell(g, jnp.asarray(pts)))
    back = np.asarray(G.cell_to_world(g, jnp.asarray(cells)))
    np.testing.assert_allclose(back, pts, atol=1e-5)
    # World (0,0) lands at the grid centre.
    c = np.asarray(G.world_to_cell(g, jnp.zeros(2)))
    assert np.allclose(c, g.size_cells / 2)


def test_sanitize_ranges_reference_semantics(tiny_cfg):
    s = tiny_cfg.scan
    ranges = np.zeros(s.padded_beams, np.float32)
    ranges[:s.n_beams] = 1.5
    ranges[3] = 0.0          # outlier -> invalid_range, not a hit
    ranges[7] = 100.0        # beyond max -> carves but no hit
    r, hit = G.sanitize_ranges(s, jnp.asarray(ranges))
    r, hit = np.asarray(r), np.asarray(hit)
    assert r[3] == pytest.approx(s.invalid_range_m)  # main.py:152 rule
    assert not hit[3]
    assert not hit[7]
    assert hit[0] and r[0] == pytest.approx(1.5)
    assert not hit[s.n_beams:].any()
    assert (r[s.n_beams:] == 0).all()


def test_patch_origin_alignment_and_coverage(tiny_cfg):
    g = tiny_cfg.grid
    o = np.asarray(G.patch_origin(g, jnp.array([0.3, -0.7])))
    assert o[0] % g.align_rows == 0 and o[1] % g.align_cols == 0
    # Robot must sit well inside the patch.
    cr = np.asarray(G.world_to_cell(g, jnp.array([0.3, -0.7])))
    max_c = g.max_range_m / g.resolution_m
    assert o[1] <= cr[0] - max_c + g.align_cols and \
        cr[0] + max_c - g.align_cols <= o[1] + g.patch_cells
    # Clipped at grid edges.
    o_edge = np.asarray(G.patch_origin(g, jnp.array([-100.0, 100.0])))
    assert 0 <= o_edge[0] <= g.size_cells - g.patch_cells
    assert 0 <= o_edge[1] <= g.size_cells - g.patch_cells


def test_classify_patch_matches_oracle(tiny_cfg, rng):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    ranges = rng.uniform(0.3, 2.5, s.padded_beams).astype(np.float32)
    ranges[5] = 0.0
    ranges[40] = 50.0
    pose = np.array([0.42, -0.31, 0.7], np.float32)
    origin = np.asarray(G.patch_origin(g, jnp.asarray(pose[:2])))
    got = np.asarray(G.classify_patch(g, s, jnp.asarray(ranges),
                                      jnp.asarray(pose), jnp.asarray(origin)))
    want = classify_patch_np(g, s, ranges, pose, origin)
    # Beam-index rounding at cell-bearing boundaries can differ by one ulp;
    # demand exact agreement on >99.8% of cells and zero large deviations.
    agree = np.mean(got == want)
    assert agree > 0.998, f"only {agree:.4f} of cells agree with oracle"
    assert np.abs(got - want).max() <= g.logodds_occ - g.logodds_free + 1e-6


def test_classify_patch_geometry(tiny_cfg):
    """Property test: the cell at each beam endpoint is occupied, cells along
    the beam are free, cells beyond are untouched."""
    g, s = tiny_cfg.grid, tiny_cfg.scan
    ranges = np.zeros(s.padded_beams, np.float32)
    ranges[:s.n_beams] = 2.0
    pose = np.array([0.0, 0.0, 0.0], np.float32)
    origin = np.asarray(G.patch_origin(g, jnp.zeros(2)))
    delta = np.asarray(G.classify_patch(g, s, jnp.asarray(ranges),
                                        jnp.asarray(pose), jnp.asarray(origin)))
    res = g.resolution_m

    def cell_of(x, y):
        col = int((x - g.origin_m[0]) / res) - origin[1]
        row = int((y - g.origin_m[1]) / res) - origin[0]
        return row, col

    for ang_deg in (0, 45, 90, 200, 315):
        a = math.radians(ang_deg)
        # Endpoint occupied.
        r, c = cell_of(2.0 * math.cos(a), 2.0 * math.sin(a))
        assert delta[r, c] == pytest.approx(g.logodds_occ), ang_deg
        # Midpoint free.
        r, c = cell_of(1.0 * math.cos(a), 1.0 * math.sin(a))
        assert delta[r, c] == pytest.approx(g.logodds_free), ang_deg
        # Beyond endpoint untouched.
        r, c = cell_of(2.6 * math.cos(a), 2.6 * math.sin(a))
        assert delta[r, c] == pytest.approx(0.0), ang_deg


def test_fuse_batch_equals_sequential(tiny_cfg, rng):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    B = 5
    ranges = rng.uniform(0.3, 2.8, (B, s.padded_beams)).astype(np.float32)
    poses = np.stack([rng.uniform(-0.5, 0.5, B), rng.uniform(-0.5, 0.5, B),
                      rng.uniform(-3, 3, B)], axis=1).astype(np.float32)
    grid0 = G.empty_grid(g)
    seq = grid0
    for i in range(B):
        seq = G.fuse_scan(g, s, seq, jnp.asarray(ranges[i]), jnp.asarray(poses[i]))
    bat = G.fuse_scans(g, s, grid0, jnp.asarray(ranges), jnp.asarray(poses))
    np.testing.assert_allclose(np.asarray(seq), np.asarray(bat), atol=1e-6)


def test_fuse_clamps_logodds(tiny_cfg):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    ranges = np.full((40, s.padded_beams), 1.0, np.float32)
    poses = np.zeros((40, 3), np.float32)
    out = np.asarray(G.fuse_scans(g, s, G.empty_grid(g),
                                  jnp.asarray(ranges), jnp.asarray(poses)))
    assert out.max() <= g.logodds_max + 1e-6
    assert out.min() >= g.logodds_min - 1e-6
    assert out.max() == pytest.approx(g.logodds_max)   # saturated hits
    assert out.min() == pytest.approx(g.logodds_min)   # saturated free space


def test_scan_deltas_full_matches_fuse(tiny_cfg, rng):
    g, s = tiny_cfg.grid, tiny_cfg.scan
    B = 3
    ranges = rng.uniform(0.5, 2.5, (B, s.padded_beams)).astype(np.float32)
    poses = np.zeros((B, 3), np.float32)
    delta = G.scan_deltas_full(g, s, jnp.asarray(ranges), jnp.asarray(poses))
    merged = G.merge_delta(g, G.empty_grid(g), delta)
    direct = G.fuse_scans(g, s, G.empty_grid(g), jnp.asarray(ranges),
                          jnp.asarray(poses))
    np.testing.assert_allclose(np.asarray(merged), np.asarray(direct), atol=1e-5)


def test_occupancy_export_and_png(tiny_cfg):
    g = tiny_cfg.grid
    arr = np.zeros((g.size_cells, g.size_cells), np.float32)
    arr[10, 10] = 2.0    # occupied
    arr[20, 20] = -2.0   # free
    occ = np.asarray(G.to_occupancy(g, jnp.asarray(arr)))
    assert occ[10, 10] == 100 and occ[20, 20] == 0 and occ[0, 0] == -1
    img = G.occupancy_to_png_array(occ)
    H = g.size_cells
    # Reference PNG contract (main.py:259-266): 0->255, 100->0, else 127, flipud.
    assert img[H - 1 - 10, 10] == 0
    assert img[H - 1 - 20, 20] == 255
    assert img[H - 1, 0] == 127


def test_fuse_chunked_fold_parity(tiny_cfg, rng, monkeypatch):
    """The chunked classify->fold (incl. a remainder chunk) is exact: B=5
    through chunk size 2 must match the unchunked result bitwise."""
    g, s = tiny_cfg.grid, tiny_cfg.scan
    B = 5
    ranges = rng.uniform(0.3, 2.8, (B, s.padded_beams)).astype(np.float32)
    poses = np.stack([rng.uniform(-0.5, 0.5, B), rng.uniform(-0.5, 0.5, B),
                      rng.uniform(-3, 3, B)], axis=1).astype(np.float32)
    grid0 = G.empty_grid(g)
    whole = G._classify_fold(g, s, grid0, jnp.asarray(ranges),
                             jnp.asarray(poses), None, clamp=True)
    monkeypatch.setattr(G, "_FUSE_CHUNK", 2)
    chunked = G._classify_fold(g, s, grid0, jnp.asarray(ranges),
                               jnp.asarray(poses), None, clamp=True)
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(chunked))
    # masked variant: only scans 0 and 3 contribute, across chunk bounds
    mask = np.zeros(B, bool); mask[0] = mask[3] = True
    masked = G._classify_fold(g, s, grid0, jnp.asarray(ranges),
                              jnp.asarray(poses), jnp.asarray(mask),
                              clamp=True)
    two = G.fuse_scans(g, s, grid0, jnp.asarray(ranges[[0, 3]]),
                       jnp.asarray(poses[[0, 3]]))
    np.testing.assert_allclose(np.asarray(masked), np.asarray(two),
                               atol=1e-6)
