"""Production-shape proofs on the physical TPU (round-2 VERDICT weak #8:
"full-size shapes run nowhere but the bench" — matcher and loop closure
had never executed at the 4096^2/640-patch/1024-pose config on the chip).

Run with: JAX_MAPPING_TPU_TESTS=1 pytest tests/test_tpu_fullsize.py
(skipped wholesale off-TPU; conftest pins CPU otherwise).

Each test asserts finiteness/shape sanity AND a wall-time bound generous
enough to never flake on a healthy chip (compile time excluded by a
warm-up call) but tight enough to catch a silent fallback onto a
scalarised path.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="needs the physical TPU (JAX_MAPPING_TPU_TESTS=1)")


@pytest.fixture(scope="module")
def cfg():
    from jax_mapping.config import SlamConfig
    return SlamConfig()      # the full 4096^2 / 640-patch / 1024-pose config


def _walled_ranges(cfg, rng, n):
    s = cfg.scan
    r = rng.uniform(1.0, 10.0, (n, s.padded_beams)).astype(np.float32)
    r[:, s.n_beams:] = 0.0
    return r


def test_match_full_size_on_chip(cfg):
    from jax_mapping.ops import grid as G
    from jax_mapping.ops import scan_match as M
    g, s = cfg.grid, cfg.scan
    rng = np.random.default_rng(0)
    ranges = jnp.asarray(_walled_ranges(cfg, rng, 2))
    poses = jnp.asarray(np.array([[0.0, 0.0, 0.0], [0.05, -0.03, 0.02]],
                                 np.float32))
    grid_arr = G.fuse_scans(g, s, G.empty_grid(g), ranges[:1], poses[:1])

    res = M.match(g, s, cfg.matcher, grid_arr, ranges[1], poses[1])
    jax.block_until_ready(res)          # warm compile
    t0 = time.perf_counter()
    res = M.match(g, s, cfg.matcher, grid_arr, ranges[1],
                  poses[1] + jnp.float32(1e-4))
    pose = np.asarray(res.pose)         # force materialisation (axon:
    resp = float(res.response)          # block_until_ready is a no-op)
    dt = time.perf_counter() - t0
    assert np.isfinite(pose).all() and np.isfinite(resp)
    # Self-match of the scan that built the map must score well and land
    # near the guess.
    assert resp > 0.3
    assert np.linalg.norm(pose[:2] - np.asarray(poses[1])[:2]) < 0.3
    assert dt < 30.0, f"full-size match took {dt:.1f}s — fallback path?"


def test_loop_verify_full_size_on_chip(cfg):
    from jax_mapping.models import slam as S
    from jax_mapping.ops import posegraph as PG
    g, s = cfg.grid, cfg.scan
    rng = np.random.default_rng(1)
    n_chain = cfg.loop.min_chain_size * 2 + 2

    graph = PG.empty_graph(cfg.loop)
    ring = jnp.zeros((cfg.loop.max_poses, s.padded_beams), jnp.float32)
    scan0 = jnp.asarray(_walled_ranges(cfg, rng, 1)[0])
    for i in range(n_chain):
        pose = jnp.asarray(np.array([0.3 * i, 0.0, 0.0], np.float32))
        graph = PG.add_pose_if(graph, pose, jnp.bool_(True))
        ring = ring.at[i].set(scan0)

    cand = jnp.int32(1)
    k = jnp.int32(n_chain - 1)
    query_pose = jnp.asarray(np.array([0.3, 0.1, 0.0], np.float32))

    res = S._verify_loop(cfg, graph, ring, cand, k, scan0, query_pose)
    jax.block_until_ready(res)          # warm compile (two-stage, heavy)
    t0 = time.perf_counter()
    res = S._verify_loop(cfg, graph, ring, cand, k, scan0,
                         query_pose + jnp.float32(1e-4))
    pose = np.asarray(res.pose)
    resp = float(res.response)
    dt = time.perf_counter() - t0
    assert np.isfinite(pose).all() and np.isfinite(resp)
    assert dt < 60.0, f"full-size loop verify took {dt:.1f}s"


def test_frontier_full_size_on_chip(cfg):
    from jax_mapping.ops import frontier as F
    g = cfg.grid
    rng = np.random.default_rng(2)
    lo = np.zeros((g.size_cells, g.size_cells), np.float32)
    lo[1800:2400, 1800:2400] = -2.0
    lo[1800:2400, 2100:2104] = 2.0
    lo[2000:2080, 2100:2104] = -2.0
    poses = jnp.asarray(np.stack(
        [rng.uniform(-5, 5, 64), rng.uniform(-5, 5, 64),
         rng.uniform(-3, 3, 64)], 1).astype(np.float32))
    lo_j = jnp.asarray(lo)

    r = F.compute_frontiers(cfg.frontier, g, lo_j, poses)
    jax.block_until_ready(r)            # warm compile
    t0 = time.perf_counter()
    r = F.compute_frontiers(cfg.frontier, g, lo_j + jnp.float32(0.0), poses)
    n_assigned = int((np.asarray(r.assignment) >= 0).sum())
    dt = time.perf_counter() - t0
    assert n_assigned == 64
    assert np.isfinite(np.asarray(r.costs)).all()
    # Generous wall bound incl. one tunnel round-trip; the real latency
    # target lives in bench.py (frontier_p50_ms_64robots < 5).
    assert dt < 10.0, f"full-size frontier took {dt:.1f}s"


def test_costfield_pallas_full_size_on_chip(cfg):
    """The multigrid cost-field kernel lowers and runs at the production
    clustering shape (n=256, 64 robots) — the VMEM chunk budget must hold
    on real Mosaic, not just in interpret mode."""
    from jax_mapping.ops import costfield as CF
    rng = np.random.default_rng(0)
    n = (cfg.grid.size_cells // cfg.frontier.downsample
         // cfg.frontier.cluster_downsample)
    blocked = jnp.asarray(rng.random((n, n)) < 0.2)
    rc = jnp.asarray(rng.integers(0, n, (64, 2)), dtype=jnp.int32)
    f = CF.cost_fields(blocked, rc, cfg.frontier.mg_levels,
                       cfg.frontier.mg_refine_iters)
    jax.block_until_ready(f)
    t0 = time.perf_counter()
    f = CF.cost_fields(blocked, rc, cfg.frontier.mg_levels,
                       cfg.frontier.mg_refine_iters)
    jax.block_until_ready(f)
    assert time.perf_counter() - t0 < 2.0
    fn = np.asarray(f)
    assert fn.shape == (64, n, n)
    assert np.isfinite(fn[fn < 1e8]).all()
    # every robot reaches its own open cell at zero cost
    rcn = np.asarray(rc)
    assert (fn[np.arange(64), rcn[:, 0], rcn[:, 1]] == 0.0).all()


def test_label_prop_pallas_full_size_on_chip(cfg):
    """The label-propagation kernel lowers and runs at the production
    clustering shape; components separated by gaps stay distinct."""
    from jax_mapping.ops import frontier as F
    n = (cfg.grid.size_cells // cfg.frontier.downsample
         // cfg.frontier.cluster_downsample)
    assert F._use_pallas_labels(n), "size gate should admit the kernel"
    mask = np.zeros((n, n), bool)
    mask[10, 10:40] = True           # component A
    mask[100, 120:180] = True        # component B
    import dataclasses
    cfg_c = dataclasses.replace(
        cfg.frontier, label_prop_iters=max(
            1, -(-cfg.frontier.label_prop_iters
                 // cfg.frontier.cluster_downsample)))
    labels = F.label_components(cfg_c, jnp.asarray(mask))
    jax.block_until_ready(labels)
    ln = np.asarray(labels)
    a = set(np.unique(ln[10, 10:40]).tolist())
    b = set(np.unique(ln[100, 120:180]).tolist())
    assert len(a) == 1 and len(b) == 1 and a != b
    assert (ln[~mask] == -1).all()


def test_plan_to_goal_full_size_on_chip(cfg):
    """The global planner lowers and runs at the production shape
    (4096^2 map -> coarse 1024^2 goal-seeded BFS + 256-step descent).
    Staged for hardware validation like the kernels above; the latency
    target lives in bench.py (plan_p50_ms under PlannerConfig.period_s)."""
    from jax_mapping.ops import planner as P
    g = cfg.grid
    lo = np.full((g.size_cells, g.size_cells), -1.0, np.float32)
    lo[:, 2048:2052] = 3.0                    # wall splitting the map
    lo[3600:3800, 2048:2052] = -1.0           # gap
    lo_j = jnp.asarray(lo)
    ox, oy = g.origin_m
    span = g.size_cells * g.resolution_m
    start = jnp.asarray([ox + 0.3 * span, oy + 0.3 * span], jnp.float32)
    goal = jnp.asarray([ox + 0.7 * span, oy + 0.3 * span], jnp.float32)

    r = P.plan_to_goal(cfg.planner, cfg.frontier, g, lo_j, goal, start)
    jax.block_until_ready(r)                  # warm compile
    t0 = time.perf_counter()
    r = P.plan_to_goal(cfg.planner, cfg.frontier, g,
                       lo_j + jnp.float32(0.0), goal, start)
    reachable = bool(r.reachable)
    dt = time.perf_counter() - t0
    assert reachable, "goal through the gap must be reachable"
    path = np.asarray(r.path_xy)[np.asarray(r.path_valid)]
    assert len(path) > 0 and np.isfinite(path).all()
    assert dt < 10.0, f"full-size plan took {dt:.1f}s"
