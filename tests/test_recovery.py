"""Estimator-guardrail tests: the divergence watchdog's hysteresis, the
quarantine + wide-window relocalization path, the anti-stuck recovery
ladder, the adversarial sensor-fault kinds — and the headline missions
(ISSUE 3 acceptance): a tier-1 ghost_returns smoke where the watchdog
fires, the robot's evidence quarantines, and relocalization re-admits it
after the fault clears; plus a `slow` wheel_slip + lidar_miscal soak
asserting bounded-budget detection, fleet-map protection, re-admission,
and bit-determinism.
"""

import dataclasses

import numpy as np
import pytest

from jax_mapping.bridge.launch import launch_sim_stack
from jax_mapping.config import RecoveryConfig, tiny_config
from jax_mapping.recovery import (
    DIVERGED, HEALTHY, MONITOR, AntiStuckLadder, EstimatorWatchdog,
    FrontierBlacklist, RecoveryManager,
)
from jax_mapping.resilience import (
    ESTIMATOR_DIVERGED, OK, SENSOR_KINDS, FaultEvent, FaultPlan,
    FleetHealth, random_plan,
)
from jax_mapping.resilience.faultplan import _fault_resource
from jax_mapping.sim import world as W


# -------------------------------------------------------------- watchdog

def _wd(**kw):
    kw.setdefault("min_keyscans", 2)
    kw.setdefault("score_decay", 0.5)
    kw.setdefault("diverge_threshold", 0.4)
    kw.setdefault("diverge_persist_steps", 2)
    return EstimatorWatchdog(RecoveryConfig(**kw), 2)


def test_watchdog_declares_after_persistent_badness_only():
    """Hysteresis: one bad observation is weather; a streak past the
    persist count declares — exactly once."""
    wd = _wd()
    for _ in range(10):
        assert not wd.observe(0, key=True, matched=True, agreement=1.0)
    assert wd.states() == [HEALTHY, HEALTHY]
    # One isolated garbage scan: not a declaration.
    assert not wd.observe(0, key=True, matched=False, agreement=0.0)
    for _ in range(5):
        assert not wd.observe(0, key=True, matched=True, agreement=1.0)
    assert wd.states()[0] == HEALTHY
    # A persistent streak declares once; further badness cannot re-fire.
    fired = [wd.observe(0, key=True, matched=False, agreement=0.0)
             for _ in range(6)]
    assert fired.count(True) == 1
    assert wd.is_diverged(0) and not wd.is_diverged(1)
    assert wd.n_diverge_events == 1


def test_watchdog_no_score_based_exit_readmit_resets():
    """Only a verified re-anchor re-admits: good observations while
    DIVERGED never clear the state (a quarantined robot produces no
    fresh evidence to judge)."""
    wd = _wd()
    for _ in range(8):
        wd.observe(0, key=True, matched=False, agreement=0.0)
    assert wd.is_diverged(0)
    for _ in range(20):
        wd.observe(0, key=True, matched=True, agreement=1.0)
    assert wd.is_diverged(0)            # still: no score-based exit
    wd.readmit(0)
    assert not wd.is_diverged(0)
    assert wd.scores()[0] == 0.0
    assert wd.n_readmits == 1
    assert wd.transitions[-1][2:] == (DIVERGED, HEALTHY)


def test_watchdog_bootstrap_grace_ignores_match_failures():
    """With an empty map the matcher legitimately rejects: match
    failures inside the first min_keyscans key observations must not
    charge the match term (agreement stays neutral at bootstrap)."""
    wd = _wd(min_keyscans=5, diverge_persist_steps=1)
    for _ in range(5):
        assert not wd.observe(0, key=True, matched=False, agreement=1.0)
    assert wd.states()[0] == HEALTHY
    # Past the grace, the same stream declares.
    declared = False
    for _ in range(6):
        declared = declared or wd.observe(0, key=True, matched=False,
                                          agreement=1.0)
    assert declared


def test_fleet_health_estimator_rung():
    """ESTIMATOR_DIVERGED folds into the ladder: set while scans flow ->
    the rung; staleness outranks it; clear -> OK. The assignable mask
    strips diverged robots, the alive mask keeps them."""
    from jax_mapping.config import ResilienceConfig
    h = FleetHealth(ResilienceConfig(lidar_silent_ticks=3,
                                     dead_after_ticks=8), 2)
    for t in range(1, 4):
        h.note_scan(0, t)
        h.note_scan(1, t)
        h.note_tick(t)
    h.note_estimator(0, True)
    h.note_scan(0, 4)
    h.note_scan(1, 4)
    h.note_tick(4)
    assert h.robot_states() == [ESTIMATOR_DIVERGED, OK]
    assert h.alive_mask().tolist() == [True, True]
    assert h.assignable_mask().tolist() == [False, True]
    assert h.lidar_ok_mask().tolist() == [False, True]
    assert h.diverged_mask().tolist() == [True, False]
    assert h.snapshot()["estimator_diverged"] == [True, False]
    # Lidar silence outranks the estimator rung.
    for t in range(5, 10):
        h.note_scan(1, t)
        h.note_tick(t)
    assert h.robot_states()[0] == "no_lidar"
    # Scans resume + estimator cleared -> OK.
    h.note_estimator(0, False)
    h.note_scan(0, 10)
    h.note_tick(10)
    assert h.robot_states() == [OK, OK]
    ladder = [(a, b) for _, a, b in h.transitions_for("robot0")]
    assert (OK, ESTIMATOR_DIVERGED) in ladder


# ------------------------------------------------------------- anti-stuck

def _ladder(n_robots=1, **kw):
    kw.setdefault("stuck_window_ticks", 6)
    kw.setdefault("stuck_displacement_frac", 0.25)
    kw.setdefault("rotate_recovery_ticks", 3)
    kw.setdefault("backup_recovery_ticks", 3)
    kw.setdefault("escalation_memory_ticks", 30)
    kw.setdefault("blacklist_ttl_ticks", 20)
    return AntiStuckLadder(RecoveryConfig(**kw), n_robots,
                           rotation_units=50, cruise_units=100)


def _drive(ladder, ticks, t0, pose, cmd=(100, 100), active=True):
    """Run `ticks` stationary ticks; returns (events seen, overrides,
    blacklist requests, final tick)."""
    ov_log, bl_log = [], []
    poses = np.asarray([list(pose) + [0.0]], np.float32)
    for t in range(t0, t0 + ticks):
        ov, bl = ladder.step(t, poses, np.asarray([cmd], np.int32),
                             np.asarray([active]))
        ov_log.append(ov.get(0))
        bl_log += bl
    return ov_log, bl_log, t0 + ticks


def test_antistuck_ladder_escalates_rotate_backup_blacklist():
    lad = _ladder()
    # Commanded motion, zero displacement: rung 0 (rotate) after the
    # window fills; maneuver overrides for rotate_recovery_ticks.
    ov, bl, t = _drive(lad, 10, 0, (1.0, 2.0))
    assert (50, -50) in ov and not bl
    assert lad.n_recoveries["rotate"] == 1
    # Still stuck within escalation memory: rung 1 (backup).
    ov, bl, t = _drive(lad, 10, t, (1.0, 2.0))
    assert (-100, -100) in ov and not bl
    assert lad.n_recoveries["backup"] == 1
    # Still stuck: rung 2 requests a blacklist (no maneuver; it may
    # re-request if the goal somehow stays assigned — the blacklist's
    # dedup absorbs that).
    ov, bl, t = _drive(lad, 10, t, (1.0, 2.0))
    assert bl and set(bl) == {0}
    assert lad.n_recoveries["blacklist"] >= 1
    kinds = [e for _, _, e in lad.events if e.startswith("stuck")]
    assert kinds[:3] == ["stuck:rung=rotate", "stuck:rung=backup",
                        "stuck:rung=blacklist"]


def test_antistuck_resets_after_clean_stretch_and_skips_inactive():
    lad = _ladder()
    ov, _, t = _drive(lad, 10, 0, (0.0, 0.0))
    assert (50, -50) in ov                   # first detection: rotate
    # A long clean (moving) stretch: escalation memory expires.
    poses = np.asarray([[0.0, 0.0, 0.0]], np.float32)
    for k in range(40):
        poses[0, 0] += 0.05                  # plenty of displacement
        lad.step(t + k, poses, np.asarray([[100, 100]], np.int32),
                 np.asarray([True]))
    t += 40
    # Stuck again: the ladder restarts at rung 0 (rotate, not backup).
    ov, _, t = _drive(lad, 10, t, (5.0, 5.0))
    assert (50, -50) in ov
    assert lad.n_recoveries["rotate"] == 2
    assert lad.n_recoveries["backup"] == 0
    # Inactive robots (coasting / manual / idle) are never detected.
    lad2 = _ladder()
    ov, bl, _ = _drive(lad2, 30, 0, (0.0, 0.0), active=False)
    assert not any(ov) and not bl
    assert lad2.n_stuck_detections == 0


def test_antistuck_ignores_slow_but_healthy_cruise():
    """Regression: a Thymio cruising at 100 units covers only ~3 mm per
    tick — an absolute displacement floor would call that stuck. The
    commanded-relative detector must not."""
    lad = _ladder()
    poses = np.asarray([[0.0, 0.0, 0.0]], np.float32)
    for t in range(40):
        # Exactly the commanded distance: 100 units * 3.027e-5 m/unit/tick.
        poses[0, 0] += 100 * 3.027e-5
        ov, bl = lad.step(t, poses, np.asarray([[100, 100]], np.int32),
                          np.asarray([True]))
        assert not ov and not bl
    assert lad.n_stuck_detections == 0
    # Even at HALF the commanded distance (motor lag, soft ground) the
    # 25% floor keeps a moving robot out of recovery.
    lad2 = _ladder()
    poses = np.asarray([[0.0, 0.0, 0.0]], np.float32)
    for t in range(40):
        poses[0, 0] += 50 * 3.027e-5
        lad2.step(t, poses, np.asarray([[100, 100]], np.int32),
                  np.asarray([True]))
    assert lad2.n_stuck_detections == 0


def test_frontier_blacklist_ttl_and_dedup():
    bl = FrontierBlacklist(RecoveryConfig(blacklist_ttl_ticks=10))
    bl.note_tick(5)
    bl.add(0, (1.0, 2.0))
    bl.add(0, (1.0, 2.0))                   # dedup: refresh, not stack
    bl.add(1, (1.0, 2.0))                   # per-robot entries
    assert bl.n_blacklisted == 2
    assert bl.is_blacklisted(0, (1.05, 2.0), tol_m=0.1)
    assert not bl.is_blacklisted(0, (3.0, 2.0), tol_m=0.1)
    assert bl.is_blacklisted(1, (1.0, 2.0), tol_m=0.1)
    bl.note_tick(16)                        # past the TTL: expired
    assert not bl.is_blacklisted(0, (1.0, 2.0), tol_m=0.1)
    assert bl.entries() == []


# ------------------------------------------- adversarial fault kinds

def test_sensor_fault_kind_validation_and_resources():
    for kind in SENSOR_KINDS:
        FaultEvent(step=0, kind=kind, value=0.2)    # constructs fine
    # The value default (0.0) is refused for value-carrying kinds: for
    # wheel_slip it is the worst possible fault (0x = odometry
    # blackout, not slip), for miscal/ghosts a silent no-op.
    with pytest.raises(ValueError, match="wheel_slip needs value > 0"):
        FaultEvent(step=0, kind="wheel_slip")
    with pytest.raises(ValueError, match="nonzero value"):
        FaultEvent(step=0, kind="lidar_miscal")
    with pytest.raises(ValueError, match="nonzero value"):
        FaultEvent(step=0, kind="ghost_returns")
    FaultEvent(step=0, kind="scan_jam")             # value-less kind
    assert _fault_resource("ghost_returns", 1) == ("scan", 1)
    assert _fault_resource("scan_jam", 1) == ("scan", 1)
    assert _fault_resource("wheel_slip", 0) == ("odom", 0)
    assert _fault_resource("bus_drop", 0) == ("bus", "bus_drop")


def test_sensor_fault_windows_compose_worst_active():
    """Overlapping windows on one robot's sensor run the WORST active
    value and revert to the identity baseline when the last clears."""
    class _Sim:
        def __init__(self):
            self.slip, self.miscal, self.ghost, self.jam = 1.0, 0.0, 0.0, False

        def set_wheel_slip(self, r, v):
            self.slip = v

        def set_lidar_miscal(self, r, v):
            self.miscal = v

        def set_ghost_returns(self, r, v):
            self.ghost = v

        def set_scan_jam(self, r, v):
            self.jam = v

    class _Stack:
        def __init__(self):
            self.sim = _Sim()
            self.bus = None

    plan = FaultPlan([
        FaultEvent(step=0, kind="ghost_returns", value=0.3, duration=10),
        FaultEvent(step=5, kind="ghost_returns", value=0.2, duration=10),
        FaultEvent(step=0, kind="wheel_slip", value=1.3, duration=8),
        FaultEvent(step=2, kind="wheel_slip", value=0.8, duration=10),
        FaultEvent(step=0, kind="scan_jam", duration=6),
    ], seed=0)
    st = _Stack()
    plan.apply(st, 0)
    assert st.sim.ghost == 0.3 and st.sim.slip == 1.3 and st.sim.jam
    plan.apply(st, 2)
    assert st.sim.slip == 1.3               # |1.3-1| > |0.8-1|: worst wins
    plan.apply(st, 6)
    assert not st.sim.jam                   # jam window cleared
    plan.apply(st, 8)
    assert st.sim.slip == 0.8               # first slip window out
    plan.apply(st, 10)
    assert st.sim.ghost == 0.2              # second ghost window holds
    # The second window FIRED at apply-step 6 (first apply at or after
    # its scheduled step), so its clear lands at 6 + 10.
    plan.apply(st, 16)
    assert st.sim.ghost == 0.0 and st.sim.slip == 1.0
    assert plan.done()


def test_sensor_fault_helpers_deterministic():
    from jax_mapping.sim.lidar import apply_ghost_returns, apply_lidar_miscal
    from jax_mapping.sim.thymio import apply_wheel_slip
    cfg = tiny_config()
    ranges = np.linspace(0.5, 2.5, cfg.scan.padded_beams).astype(np.float32)
    a = apply_ghost_returns(cfg.scan, ranges, 0.4,
                            np.random.default_rng((7, 3, 0)))
    b = apply_ghost_returns(cfg.scan, ranges, 0.4,
                            np.random.default_rng((7, 3, 0)))
    np.testing.assert_array_equal(a, b)     # seeded: bit-identical
    changed = (a[:cfg.scan.n_beams] != ranges[:cfg.scan.n_beams])
    assert 0.2 < changed.mean() < 0.6       # ~the requested fraction
    assert (a[changed.nonzero()[0]] <= 0.5 + 1e-6).all()   # SHORT ghosts
    np.testing.assert_array_equal(a[cfg.scan.n_beams:],
                                  ranges[cfg.scan.n_beams:])  # padded tail
    m = apply_wheel_slip(np.ones((2, 2), np.float32), np.asarray([1.5, 1.0]))
    np.testing.assert_allclose(m, [[1.5, 1.5], [1.0, 1.0]])
    p = apply_lidar_miscal(np.zeros((2, 3), np.float32),
                           np.asarray([0.25, 0.0]))
    np.testing.assert_allclose(p[:, 2], [0.25, 0.0])


def test_random_plan_samples_adversarial_and_rejects_overlap():
    """The fuzz generator samples the new kinds and never schedules two
    windows on one resource that overlap in time (satellite: reject at
    generation time)."""
    seen = set()
    for seed in range(12):
        plan = random_plan(200, n_faults=8, seed=seed, n_robots=2)
        assert len(plan.events) > 0
        windows = []
        for ev in plan.events:
            seen.add(ev.kind)
            res = _fault_resource(ev.kind, ev.robot)
            for r, s, e in windows:
                if r == res:
                    assert not (ev.step <= e and s <= ev.step + ev.duration), \
                        f"seed {seed}: overlapping windows on {res}"
            windows.append((res, ev.step, ev.step + ev.duration))
        # Kind-appropriate magnitudes.
        for ev in plan.events:
            if ev.kind == "wheel_slip":
                assert 1.1 <= ev.value <= 1.5
            elif ev.kind == "lidar_miscal":
                assert 0.05 <= abs(ev.value) <= 0.3
            elif ev.kind == "ghost_returns":
                assert 0.1 <= ev.value <= 0.4
    assert seen & SENSOR_KINDS              # the new kinds are sampled
    a = random_plan(150, n_faults=6, seed=9, n_robots=2)
    b = random_plan(150, n_faults=6, seed=9, n_robots=2)
    assert a.events == b.events             # seed-deterministic
    # Saturation is VISIBLE, never silent: a short mission cannot place
    # many disjoint windows, and the dropped count is reported.
    tight = random_plan(20, n_faults=30, seed=1, n_robots=1)
    assert len(tight.events) + tight.generation_shortfall == 30
    assert tight.generation_shortfall > 0


# ------------------------------------------------- reactive shield (sat 4)

def test_reactive_shield_overrides_seek_at_every_state():
    """Regression (satellite): `subsumption_policy` outranks the seek
    branch whenever IR or LiDAR demand it — seek engages ONLY in the
    cruise state (reactive.state == 1), checked at every state value
    the policy can produce (0 idle, 1 cruise, 2 ir, 3 warn)."""
    import jax.numpy as jnp
    from jax_mapping.models.explorer import (frontier_policy,
                                             subsumption_policy)
    cfg = tiny_config()
    robot, scan = cfg.robot, cfg.scan
    B = scan.padded_beams
    goal = jnp.asarray([[-2.0, 0.0]])       # behind: strong seek steer
    pose = jnp.zeros((1, 3))
    valid = jnp.asarray([True])

    def both(ranges, prox, exploring=True):
        r = jnp.asarray(ranges, jnp.float32)[None]
        p = jnp.asarray(prox, jnp.float32)[None]
        e = jnp.asarray([exploring])
        re = subsumption_policy(robot, scan, r, p, e)
        fr = frontier_policy(robot, scan, pose, goal, valid, r, p, e)
        return re, fr

    clear = np.full(B, 5.0, np.float32)
    no_ir = np.zeros(5, np.float32)

    # state 0 (idle): not exploring -> zero targets, seek irrelevant.
    re, fr = both(clear, no_ir, exploring=False)
    assert int(re.state[0]) == 0
    np.testing.assert_array_equal(np.asarray(fr.targets), [[0, 0]])

    # state 1 (cruise): seek ENGAGES — differs from the blind cruise.
    re, fr = both(clear, no_ir)
    assert int(re.state[0]) == 1 and int(fr.state[0]) == 1
    assert not np.array_equal(np.asarray(fr.targets),
                              np.asarray(re.targets))

    # state 2 (IR emergency): the pivot overrides seek EXACTLY — at the
    # threshold boundary too (prox must EXCEED ir_threshold).
    ir_at = np.asarray([robot.ir_threshold] * 5, np.float32)
    re, fr = both(clear, ir_at)
    assert int(re.state[0]) == 1            # boundary: == is not over
    ir_over = ir_at + 1
    re, fr = both(clear, ir_over)
    assert int(re.state[0]) == 2 and int(fr.state[0]) == 2
    np.testing.assert_array_equal(np.asarray(fr.targets),
                                  np.asarray(re.targets))

    # state 3 (LiDAR warn): the swerve overrides seek EXACTLY — at the
    # distance boundary too (dist must be UNDER lidar_warn_dist_m).
    warn = clear.copy()
    warn[:30] = robot.lidar_warn_dist_m     # boundary: == is not under
    re, fr = both(warn, no_ir)
    assert int(re.state[0]) == 1
    warn[:30] = robot.lidar_warn_dist_m - 0.01
    re, fr = both(warn, no_ir)
    assert int(re.state[0]) == 3 and int(fr.state[0]) == 3
    np.testing.assert_array_equal(np.asarray(fr.targets),
                                  np.asarray(re.targets))


def test_frontier_policy_clamps_to_motor_range():
    """Satellite: the seek branch's base ± steer*cruise*0.5 must
    saturate at the Thymio motor command range before the int32 cast."""
    import jax.numpy as jnp
    from jax_mapping.models.explorer import frontier_policy
    cfg = tiny_config()
    robot = dataclasses.replace(cfg.robot, cruise_speed_units=500)
    B = cfg.scan.padded_beams
    # A goal ~45 deg off-axis: |steer| large while base stays high —
    # the un-clamped right wheel would command 500 + 1.5*250 = 875.
    out = frontier_policy(
        robot, cfg.scan, jnp.zeros((1, 3)),
        jnp.asarray([[2.0, 2.0]]), jnp.asarray([True]),
        jnp.full((1, B), 5.0), jnp.zeros((1, 5)), jnp.asarray([True]))
    t = np.asarray(out.targets)
    assert int(out.state[0]) == 1           # seek really engaged
    assert np.abs(t).max() == robot.motor_limit_units
    assert (np.abs(t) <= robot.motor_limit_units).all()


# ---------------------------------------- goal staleness (satellite)

def test_brain_goal_state_watermark_and_ttl_prune(tiny_cfg):
    """A reordered STALE /frontiers message must not clobber a fresher
    one, and expired goal state is structurally deleted."""
    from jax_mapping.bridge.brain import ThymioBrain
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.driver import SimulatedThymioDriver
    from jax_mapping.bridge.messages import FrontierArray, Header

    bus = Bus()
    brain = ThymioBrain(tiny_cfg, bus, SimulatedThymioDriver(n_robots=1),
                        n_robots=1)

    def fr_msg(stamp, assignment):
        return FrontierArray(
            header=Header(stamp=stamp, frame_id="map"),
            targets_xy=np.asarray([[1.0, 0.0]], np.float32),
            sizes=np.asarray([4], np.int32),
            assignment=np.asarray([assignment], np.int32))

    pub = bus.publisher("/frontiers")
    pub.publish(fr_msg(100.0, 0))
    pub.publish(fr_msg(50.0, -1))           # stale reorder: rejected
    assert brain._frontiers is not None
    assert brain._frontiers[0].header.stamp == 100.0
    assert int(np.asarray(brain._frontiers[0].assignment)[0]) == 0
    # TTL prune: after seek_ttl_s of control ticks with no fresh
    # message, the entry is DELETED (not just gated).
    ttl_ticks = int(tiny_cfg.frontier.seek_ttl_s
                    * tiny_cfg.robot.control_rate_hz)
    brain.n_ticks = ttl_ticks + 2
    brain._prune_stale_goal_state()
    assert brain._frontiers is None
    # The watermark SURVIVES the prune: a stale message flushed after a
    # TTL-length gap (healed reorder window, dead mapper) must not be
    # resurrected as fresh...
    pub.publish(fr_msg(60.0, 0))
    assert brain._frontiers is None
    # ...while a genuinely fresh one is accepted.
    pub.publish(fr_msg(120.0, 1))
    assert brain._frontiers is not None
    assert brain._frontiers[0].header.stamp == 120.0


# ---------------------------------------------- tier-1 adversarial smoke

def _known_cells(grid, thresh=0.5):
    return int((np.abs(np.asarray(grid)) > thresh).sum())


def test_adversarial_smoke_ghost_watchdog_relocalize(tmp_path):
    """Tier-1 (satellite): ONE ghost_returns window mid-mission — the
    watchdog declares divergence, the robot's evidence quarantines
    (never fuses), relocalization re-admits it after the heal, and
    coverage keeps growing afterward."""
    import json
    import urllib.request
    cfg = tiny_config()
    world = W.plank_course(96, cfg.grid.resolution_m, n_planks=4, seed=3)
    st = launch_sim_stack(cfg, world, n_robots=1, realtime=False, seed=0,
                          http_port=0)
    st.brain.start_exploring()
    plan = FaultPlan([FaultEvent(step=25, kind="ghost_returns", robot=0,
                                 duration=15, value=0.5)], seed=0)
    st.attach_fault_plan(plan)
    st.run_steps(50)                        # fault window: steps 25-40
    known_mid = _known_cells(st.mapper.merged_grid())
    assert st.recovery.watchdog.n_diverge_events >= 1
    assert st.mapper.n_scans_quarantined > 0
    st.run_steps(30)                        # post-heal: relocalize + map
    # The whole guardrail picture is exported on /status and /metrics.
    base = f"http://127.0.0.1:{st.api.port}"
    status = json.load(urllib.request.urlopen(f"{base}/status",
                                              timeout=10))
    rec = status["recovery"]
    assert rec["watchdog"]["n_diverge_events"] >= 1
    assert rec["n_scans_quarantined"] > 0
    assert rec["n_relocalizations"] >= 1
    assert "antistuck" in rec and "blacklist" in rec
    metrics = urllib.request.urlopen(f"{base}/metrics",
                                     timeout=10).read().decode()
    assert "jax_mapping_recovery_diverge_events_total 1" in metrics
    assert "jax_mapping_recovery_reloc_verified_total" in metrics
    st.shutdown()
    assert plan.done()
    # The full ladder: diverged mid-fault, re-admitted after the heal.
    ladder = [(a, b) for _, a, b in st.health.transitions_for("robot0")]
    assert (OK, ESTIMATOR_DIVERGED) in ladder
    assert ladder[-1][1] == OK
    assert st.mapper.n_relocalizations >= 1
    assert st.recovery.watchdog.n_readmits >= 1
    assert st.recovery.watchdog.states() == [HEALTHY]
    # Coverage recovered: mapping resumed after re-admission.
    known_end = _known_cells(st.mapper.merged_grid())
    assert known_end > known_mid
    assert known_end > 200


def test_recovery_disabled_restores_pre_guardrail_behavior(tmp_path):
    """RecoveryConfig.enabled=False: no manager is built, nothing
    quarantines, no health rung fires — and two same-seed disabled runs
    under the same fault plan stay bit-identical."""
    cfg = tiny_config()
    cfg = cfg.replace(recovery=dataclasses.replace(cfg.recovery,
                                                   enabled=False))
    world = W.plank_course(96, cfg.grid.resolution_m, n_planks=4, seed=3)
    grids = []
    for _ in range(2):
        st = launch_sim_stack(cfg, world, n_robots=1, realtime=False,
                              seed=0)
        st.brain.start_exploring()
        plan = FaultPlan([FaultEvent(step=20, kind="ghost_returns",
                                     robot=0, duration=10, value=0.5)],
                         seed=0)
        st.attach_fault_plan(plan)
        st.run_steps(45)
        grids.append(np.asarray(st.mapper.merged_grid()).copy())
        assert st.recovery is None
        assert st.mapper._recovery is None
        assert st.mapper.n_scans_quarantined == 0
        states = [s for _, _, s in
                  [(t, a, b) for t, a, b in
                   st.health.transitions_for("robot0")]]
        assert ESTIMATOR_DIVERGED not in states
        st.shutdown()
    np.testing.assert_array_equal(grids[0], grids[1])


def test_no_lint_suppressions_in_recovery():
    """Satellite: the analysis baseline must not grow — recovery/ ships
    with ZERO suppressions (the ratchet cannot hide new hazards there)."""
    from jax_mapping.analysis.core import Baseline, default_baseline_path
    base = Baseline.load(default_baseline_path())
    offenders = [s for s in base.suppressions
                 if "recovery" in s.get("path", "")]
    assert not offenders, offenders


# ------------------------------------------------- adversarial soak (slow)

#: The acceptance mission: seeded wheel_slip + lidar_miscal on robot 0
#: mid-mission, two robots mapping one world.
SOAK_STEPS = 200
SOAK_EVENTS = [
    dict(step=40, kind="wheel_slip", robot=0, duration=40, value=1.5),
    dict(step=50, kind="lidar_miscal", robot=0, duration=40, value=0.5),
]
#: Steps after the first fault's onset within which the watchdog must
#: have declared divergence.
DETECT_BUDGET_STEPS = 60


def _soak_mission(seed, events, steps, enabled=True):
    cfg = tiny_config()
    if not enabled:
        cfg = cfg.replace(recovery=dataclasses.replace(cfg.recovery,
                                                       enabled=False))
    world = W.plank_course(96, cfg.grid.resolution_m, n_planks=4, seed=3)
    st = launch_sim_stack(cfg, world, n_robots=2, realtime=False,
                          seed=seed)
    st.brain.start_exploring()
    plan = FaultPlan([FaultEvent(**e) for e in events], seed=seed)
    st.attach_fault_plan(plan)
    st.run_steps(steps)
    grid = np.asarray(st.mapper.merged_grid()).copy()
    st.shutdown()
    return st, plan, grid


@pytest.mark.slow
def test_adversarial_soak_slip_miscal_detect_quarantine_readmit():
    st, plan, grid_f = _soak_mission(0, SOAK_EVENTS, SOAK_STEPS)
    assert plan.done()

    # Detection within the bounded step budget of the first fault.
    div = [(t, a, b) for t, a, b in st.health.transitions_for("robot0")
           if b == ESTIMATOR_DIVERGED]
    assert div, "watchdog never declared divergence"
    assert div[0][0] <= SOAK_EVENTS[0]["step"] + DETECT_BUDGET_STEPS
    assert st.mapper.n_scans_quarantined > 0

    # Relocalization re-admitted the robot (healthy at mission end).
    assert st.mapper.n_relocalizations >= 1
    ladder = [(a, b) for _, a, b in st.health.transitions_for("robot0")]
    assert ladder[-1][1] == OK
    assert st.recovery.watchdog.states() == [HEALTHY, HEALTHY]

    # The healthy robot never walked any ladder.
    assert st.health.transitions_for("robot1") == []

    # Map protection: vs the fault-free run, the faulted mission's map
    # agrees on >= 90% of the cells both runs claim to know.
    st0, _, grid_0 = _soak_mission(0, [], SOAK_STEPS)
    known_f, known_0 = _known_cells(grid_f), _known_cells(grid_0)
    assert known_0 > 1000                   # the baseline actually mapped
    both = (np.abs(grid_f) > 0.5) & (np.abs(grid_0) > 0.5)
    agree = float((np.sign(grid_f[both]) == np.sign(grid_0[both])).mean())
    assert agree >= 0.90, f"sign agreement {agree:.3f}"
    assert known_f / known_0 >= 0.5, f"coverage {known_f / known_0:.2f}"

    # Bit-determinism: same seed, same plan -> identical map, identical
    # guardrail history.
    st_g, plan_g, grid_g = _soak_mission(0, SOAK_EVENTS, SOAK_STEPS)
    np.testing.assert_array_equal(grid_f, grid_g)
    assert plan_g.log == plan.log
    assert st_g.recovery.watchdog.transitions == \
        st.recovery.watchdog.transitions
    assert st_g.health.transitions == st.health.transitions


@pytest.mark.slow
def test_adversarial_soak_disabled_is_bit_deterministic():
    """enabled=False under the SAME fault plan: deterministic, no
    guardrail activity (the pre-PR baseline the flag restores)."""
    st_a, _, grid_a = _soak_mission(0, SOAK_EVENTS, SOAK_STEPS,
                                    enabled=False)
    st_b, _, grid_b = _soak_mission(0, SOAK_EVENTS, SOAK_STEPS,
                                    enabled=False)
    np.testing.assert_array_equal(grid_a, grid_b)
    assert st_a.recovery is None and st_b.recovery is None
    for st in (st_a, st_b):
        states = [b for _, _, b in st.health.transitions_for("robot0")]
        assert ESTIMATOR_DIVERGED not in states
