"""Resilience subsystem tests: FleetHealth's degraded-mode ladder, the
Supervisor's restart-with-backoff policy, FaultPlan's deterministic chaos
injection, the HTTP plane's bounded-wait 503 contract — and the headline
chaos missions: a scripted multi-fault run (ISSUE 2 acceptance: lidar
transport dead >= 5 s mid-mission, one robot killed and rejoined, the
mapper node killed and supervisor-resumed from checkpoint) that still
produces a map within quality thresholds of the fault-free run,
bit-deterministically across same-seed runs.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from jax_mapping.bridge.launch import launch_sim_stack
from jax_mapping.config import ResilienceConfig, tiny_config
from jax_mapping.resilience import (
    DEAD, NO_LIDAR, OK, FaultEvent, FaultPlan, FleetHealth, LockTimeout,
    Supervisor, acquire_bounded, random_plan,
)
from jax_mapping.sim import world as W


# ------------------------------------------------------------ FleetHealth

def _health(n_robots=2, **kw):
    kw.setdefault("lidar_silent_ticks", 3)
    kw.setdefault("dead_after_ticks", 8)
    return FleetHealth(ResilienceConfig(**kw), n_robots)


def test_health_ladder_ok_no_lidar_dead_rejoin():
    """The per-robot ladder: OK -> NO_LIDAR -> DEAD on silence, straight
    back to OK on a scan (rejoin), with every transition logged."""
    h = _health()
    for t in range(1, 20):
        h.note_scan(1, t)                   # robot 1 stays chatty
        h.note_tick(t)
    assert h.robot_states() == [DEAD, OK]
    assert h.transitions_for("robot0") == [(4, OK, NO_LIDAR),
                                           (9, NO_LIDAR, DEAD)]
    assert h.transitions_for("robot1") == []

    h.note_scan(0, 20)                      # the rejoin scan
    h.note_tick(20)
    assert h.robot_states() == [OK, OK]
    assert h.transitions_for("robot0")[-1] == (20, DEAD, OK)


def test_health_masks_and_boot_grace():
    h = _health()
    # Boot counts as activity: no robot boots degraded.
    h.note_tick(1)
    assert h.robot_states() == [OK, OK]
    for t in range(2, 12):
        h.note_scan(0, t)
        h.note_tick(t)
    assert h.alive_mask().tolist() == [True, False]
    assert h.lidar_ok_mask().tolist() == [True, False]
    snap = h.snapshot()
    assert snap["robots"] == [OK, DEAD] and snap["driver"] == "ok"


def test_acquire_bounded_times_out():
    lock = threading.Lock()
    acquire_bounded(lock, 0.05, "t")        # uncontended: acquires
    with pytest.raises(LockTimeout, match="wedged"):
        acquire_bounded(lock, 0.05, "t")    # held (by us): times out
    lock.release()


# ------------------------------------------------------------- Supervisor

def _supervisor(**kw):
    from jax_mapping.bridge.bus import Bus
    kw.setdefault("supervisor_missed_beats", 2)
    kw.setdefault("restart_backoff_base_steps", 2)
    kw.setdefault("restart_backoff_max_steps", 16)
    bus = Bus()
    sup = Supervisor(ResilienceConfig(**kw), bus, seed=7)
    return sup, bus


def test_supervisor_declares_dead_and_restarts():
    restarts = []
    sup, bus = _supervisor()
    sup.register("worker", lambda: restarts.append(sup.n_ticks))
    hb = bus.publisher("/heartbeat")
    from jax_mapping.resilience.supervisor import beat
    for i in range(5):
        beat(hb, "worker", i)
        sup.tick()
    assert sup.is_alive("worker") and not restarts
    # Beats stop: dead after missed_beats ticks, restart after backoff.
    for _ in range(12):
        sup.tick()
        if restarts:
            break
    assert restarts and sup.n_restarts("worker") == 1
    kinds = [k for _, n, k, _ in sup.events if n == "worker"]
    assert kinds == ["dead", "restart"]
    # The restarted node resumes beating: stays alive, no more restarts.
    assert sup.is_alive("worker")
    for i in range(5, 10):
        beat(hb, "worker", i)
        sup.tick()
    assert sup.is_alive("worker") and sup.n_restarts("worker") == 1


def test_supervisor_cancels_pending_restart_when_beats_resume():
    """A node that recovers from a transient stall BEFORE its backoff
    expires must NOT be restarted — destroying a live node would throw
    away everything since the last checkpoint to cure a healed hiccup."""
    restarts = []
    sup, bus = _supervisor(restart_backoff_base_steps=6)
    sup.register("worker", lambda: restarts.append(True))
    hb = bus.publisher("/heartbeat")
    from jax_mapping.resilience.supervisor import beat
    for i in range(3):
        beat(hb, "worker", i)
        sup.tick()
    for _ in range(3):
        sup.tick()                          # stall: declared dead
    assert not sup.is_alive("worker")
    beat(hb, "worker", 99)                  # ...but it comes back
    for _ in range(10):
        sup.tick()
        beat(hb, "worker", 100 + sup.n_ticks)
    assert sup.is_alive("worker")
    assert not restarts                     # never destroyed
    kinds = [k for _, n, k, _ in sup.events if n == "worker"]
    assert kinds == ["dead", "recovered"]


def test_supervisor_backoff_grows_exponentially_with_jitter():
    sup, _ = _supervisor(restart_backoff_jitter=0.25)
    raw = [sup.backoff_ticks(a) for a in range(6)]
    # Jitter never exceeds +25%, growth doubles, cap at max: each delay
    # sits in [base*2^a, 1.25*base*2^a] until the cap.
    for a, d in enumerate(raw):
        lo = min(2 * 2 ** a, 16)
        assert lo <= d <= int(round(lo * 1.25)) + 1
    # Seeded: a same-seed supervisor reproduces the exact sequence.
    sup2, _ = _supervisor(restart_backoff_jitter=0.25)
    assert [sup2.backoff_ticks(a) for a in range(6)] == raw


def test_supervisor_restart_failure_reschedules_with_longer_backoff():
    boom = {"n": 0}

    def flaky():
        boom["n"] += 1
        if boom["n"] < 3:
            raise RuntimeError("still broken")

    sup, _ = _supervisor()
    sup.register("worker", flaky)
    for _ in range(60):
        sup.tick()
        if boom["n"] >= 3 and sup.is_alive("worker"):
            break
    assert boom["n"] == 3                   # two failures, then success
    kinds = [k for _, n, k, _ in sup.events if n == "worker"]
    assert kinds == ["dead", "restart_failed", "restart_failed", "restart"]
    # Backoff_log records growing delays across the failed attempts.
    delays = [d for name, _, d in sup.backoff_log if name == "worker"]
    assert len(delays) == 3 and delays[0] <= delays[1] <= delays[2]


def test_supervisor_checkpoint_cadence_and_error_tolerance():
    saves = []

    def saver():
        saves.append(sup.n_ticks)
        if len(saves) == 2:
            raise OSError("disk full")

    sup, _ = _supervisor(checkpoint_every_steps=5)
    sup.attach_checkpointer(saver)
    for _ in range(20):
        sup.tick()
    assert saves == [5, 10, 15, 20]
    assert sup.n_checkpoints == 3 and sup.n_checkpoint_errors == 1
    # The failing save was contained: supervision kept ticking.
    assert sup.n_ticks == 20


# --------------------------------------------------------------- FaultPlan

def test_fault_event_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultEvent(step=0, kind="meteor_strike")
    with pytest.raises(ValueError, match=">= 0"):
        FaultEvent(step=-1, kind="lidar_dead")


def test_fault_plan_overlapping_windows_compose():
    """Two overlapping windows on the same resource: the first window's
    auto-clear must not end the second one early (refcounted holds; the
    weather knob runs the worst active window, then the baseline)."""
    class _Bus:
        def __init__(self):
            self.drop_prob = 0.05            # pre-chaos baseline
            self.parts = set()

        def partition(self, *t):
            self.parts.update(t)

        def heal(self, *t):
            self.parts.difference_update(t)

        def set_fault_injection(self, drop_prob=None, reorder_prob=None):
            if drop_prob is not None:
                self.drop_prob = drop_prob

    class _Stack:
        def __init__(self):
            self.bus = _Bus()

    plan = FaultPlan([
        FaultEvent(step=0, kind="bus_drop", value=0.4, duration=10),
        FaultEvent(step=5, kind="bus_drop", value=0.2, duration=10),
    ], seed=0)
    st = _Stack()
    plan.apply(st, 0)
    assert st.bus.drop_prob == 0.4
    plan.apply(st, 5)
    assert st.bus.drop_prob == 0.4           # worst active window wins
    plan.apply(st, 10)                       # first window clears
    assert st.bus.drop_prob == 0.2           # second still active
    plan.apply(st, 15)                       # second clears
    assert st.bus.drop_prob == 0.05          # baseline restored
    assert plan.done()

    # Same for partitions: overlapping lidar_dead windows, one robot.
    plan2 = FaultPlan([
        FaultEvent(step=0, kind="lidar_dead", robot=0, duration=10),
        FaultEvent(step=5, kind="lidar_dead", robot=0, duration=10),
    ], seed=0)
    st2 = _Stack()
    st2.brain = type("B", (), {"n_robots": 1})()
    plan2.apply(st2, 0)
    plan2.apply(st2, 5)
    plan2.apply(st2, 10)                     # first clear: still held
    assert "scan" in st2.bus.parts
    plan2.apply(st2, 15)                     # last window out heals
    assert "scan" not in st2.bus.parts

    # A stray rejoin_robot with NO kill held must not heal a partition
    # another window owns.
    plan3 = FaultPlan([
        FaultEvent(step=0, kind="lidar_dead", robot=0, duration=20),
        FaultEvent(step=5, kind="rejoin_robot", robot=0),
    ], seed=0)
    st3 = _Stack()
    st3.brain = type("B", (), {"n_robots": 1})()
    plan3.apply(st3, 0)
    plan3.apply(st3, 5)
    assert "scan" in st3.bus.parts           # lidar_dead still owns it
    plan3.apply(st3, 20)
    assert "scan" not in st3.bus.parts


def test_random_plan_is_seed_deterministic():
    a = random_plan(100, n_faults=5, seed=3, n_robots=2)
    b = random_plan(100, n_faults=5, seed=3, n_robots=2)
    assert a.events == b.events
    c = random_plan(100, n_faults=5, seed=4, n_robots=2)
    assert a.events != c.events
    for ev in a.events:
        assert 1 <= ev.step < 90 and 0 <= ev.robot < 2


# ----------------------------------------------- HTTP degraded responses

def test_http_status_503_when_brain_lock_wedged(tiny_cfg):
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=3,
                           seed=3)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0)
    try:
        st.run_steps(3)
        st.api.lock_timeout_s = 0.1
        url = f"http://127.0.0.1:{st.api.port}/status"
        assert json.load(urllib.request.urlopen(url))["connected"]
        # Wedge the brain's state lock from another thread: the bounded
        # wait must answer 503 degraded, not hang the worker.
        st.brain._state_lock.acquire()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url, timeout=10)
            assert ei.value.code == 503
            body = json.load(ei.value)
            assert body["state"] == "degraded"
        finally:
            st.brain._state_lock.release()
        # Healthy again once the lock frees.
        assert json.load(urllib.request.urlopen(url))["connected"]
        assert st.api.n_degraded_responses == 1
    finally:
        st.shutdown()


def test_http_mutations_503_while_mapper_dead(tiny_cfg, tmp_path):
    """Between the supervisor's dead declaration and the restart, /save
    answers 503 degraded; after the restart it works again."""
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=3,
                           seed=3)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0,
                          checkpoint_dir=str(tmp_path))
    try:
        st.api.checkpoint_dir = str(tmp_path)
        st.run_steps(5)
        st.kill_node("jax_mapper")
        missed = st.cfg.resilience.supervisor_missed_beats
        st.run_steps(missed + 1)            # dead declared, restart pending
        assert not st.supervisor.is_alive("jax_mapper")
        url = f"http://127.0.0.1:{st.api.port}/save"
        req = urllib.request.Request(url, method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
        assert json.load(ei.value)["state"] == "degraded"
        # /status keeps answering (read-only) and exports the death.
        status = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{st.api.port}/status"))
        assert status["supervisor"]["dead"] == ["jax_mapper"]
        st.run_steps(30)                    # backoff elapses, restart runs
        assert st.supervisor.is_alive("jax_mapper")
        req = urllib.request.Request(url, method="POST")
        assert json.load(urllib.request.urlopen(req, timeout=10))[
            "status"] == "saved"
    finally:
        st.shutdown()


# -------------------------------------------------- chaos smoke (tier-1)

def _known_cells(grid, thresh=0.5):
    return int((np.abs(np.asarray(grid)) > thresh).sum())


def _chaos_mission(seed, plan_events, steps, tmp_dir, n_robots=2):
    cfg = tiny_config()
    world = W.plank_course(96, cfg.grid.resolution_m, n_planks=4, seed=3)
    st = launch_sim_stack(cfg, world, n_robots=n_robots, realtime=False,
                          checkpoint_dir=tmp_dir, seed=seed)
    st.brain.start_exploring()
    st.brain.reconnect_period_s = 0.0       # probe every tick (stepped time)
    plan = FaultPlan([FaultEvent(**e) for e in plan_events], seed=seed)
    st.attach_fault_plan(plan)
    st.run_steps(steps)
    grid = np.asarray(st.mapper.merged_grid()).copy()
    st.shutdown()
    return st, plan, grid


def test_chaos_smoke_single_fault(tmp_path):
    """Tier-1 chaos: ONE scripted lidar outage mid-mission. The robot
    walks the NO_LIDAR ladder and back, mapping continues after the
    heal, and the fault log is exactly the scripted schedule."""
    events = [dict(step=8, kind="lidar_dead", robot=0, duration=15)]
    st, plan, grid = _chaos_mission(0, events, 45, str(tmp_path),
                                    n_robots=1)
    assert plan.done()
    assert [d for _, d in plan.log] == ["lidar_dead robot0",
                                        "clear: lidar_dead robot0"]
    ladder = [(a, b) for _, a, b in st.health.transitions_for("robot0")]
    assert (OK, NO_LIDAR) in ladder          # degraded during the outage
    assert ladder[-1][1] == OK               # healed by mission end
    assert st.mapper.n_scans_fused > 0
    assert _known_cells(grid) > 200
    assert st.bus.n_partition_dropped > 0   # the outage really dropped scans


# ---------------------------------------------------- chaos soak (slow)

#: The acceptance plan: lidar transport dead 5 s (50 control ticks at
#: 10 Hz) mid-mission, one robot killed and later rejoined, the mapper
#: node killed and supervisor-resumed from checkpoint.
SOAK_STEPS = 240
SOAK_EVENTS = [
    dict(step=40, kind="lidar_dead", robot=0, duration=50),
    dict(step=70, kind="kill_robot", robot=1, duration=80),
    dict(step=130, kind="kill_node", name="jax_mapper"),
]


@pytest.mark.slow
def test_chaos_soak_multi_fault_map_quality_and_determinism(tmp_path):
    st_f, plan, grid_f = _chaos_mission(0, SOAK_EVENTS, SOAK_STEPS,
                                        str(tmp_path / "a"))
    assert plan.done()

    # The mapper died and the supervisor resumed it from checkpoint.
    assert st_f.supervisor.n_restarts("jax_mapper") == 1
    kinds = [k for _, n, k, _ in st_f.supervisor.events
             if n == "jax_mapper"]
    assert "dead" in kinds and "restart" in kinds

    # Robot 1 was declared DEAD mid-mission and rejoined.
    ladder1 = [(a, b) for _, a, b in st_f.health.transitions_for("robot1")]
    assert (NO_LIDAR, DEAD) in ladder1
    assert ladder1[-1][1] == OK             # rejoined by mission end

    # Robot 0's 5 s lidar outage walked the degrade ladder and healed.
    ladder0 = [(a, b) for _, a, b in st_f.health.transitions_for("robot0")]
    assert (OK, NO_LIDAR) in ladder0
    assert ladder0[-1][1] == OK

    # Map quality vs the fault-free run: the faulted mission must still
    # deliver >= 55% of the fault-free coverage, and agree on >= 90% of
    # the cells both runs claim to know (sign of the log-odds evidence).
    cfg = tiny_config()
    world = W.plank_course(96, cfg.grid.resolution_m, n_planks=4, seed=3)
    st0 = launch_sim_stack(cfg, world, n_robots=2, realtime=False, seed=0)
    st0.brain.start_exploring()
    st0.run_steps(SOAK_STEPS)
    grid_0 = np.asarray(st0.mapper.merged_grid()).copy()
    st0.shutdown()

    known_f, known_0 = _known_cells(grid_f), _known_cells(grid_0)
    assert known_0 > 1000                   # the baseline actually mapped
    coverage = known_f / known_0
    assert coverage >= 0.55, f"coverage ratio {coverage:.2f}"

    both = (np.abs(grid_f) > 0.5) & (np.abs(grid_0) > 0.5)
    agree = float((np.sign(grid_f[both]) == np.sign(grid_0[both])).mean())
    assert agree >= 0.90, f"sign agreement {agree:.3f}"

    # Determinism: the SAME seed and plan reproduce the chaos run
    # bit-for-bit — fault log included (CI-replayable chaos).
    st_g, plan_g, grid_g = _chaos_mission(0, SOAK_EVENTS, SOAK_STEPS,
                                          str(tmp_path / "b"))
    assert plan_g.log == plan.log
    np.testing.assert_array_equal(grid_f, grid_g)
    assert st_g.supervisor.backoff_log == st_f.supervisor.backoff_log


@pytest.mark.slow
def test_chaos_soak_corrupt_checkpoint_falls_back(tmp_path):
    """corrupt_checkpoint + kill_node: the newest auto-checkpoint is
    truncated before the mapper dies, so the supervisor's resume must
    fall back to the rotated last-good generation — and still produce a
    live, growing map."""
    every = tiny_config().resilience.checkpoint_every_steps   # 25
    events = [
        # Two checkpoint generations exist after step 2*every; corrupt
        # the newest right before killing the mapper.
        dict(step=2 * every + 5, kind="corrupt_checkpoint"),
        dict(step=2 * every + 6, kind="kill_node", name="jax_mapper"),
    ]
    st, plan, grid = _chaos_mission(1, events, 2 * every + 60,
                                    str(tmp_path))
    assert plan.done()
    assert any("corrupt_checkpoint" in d and "skipped" not in d
               for _, d in plan.log)
    assert st.supervisor.n_restarts("jax_mapper") == 1
    # The resumed mapper kept fusing (map alive after the fallback).
    assert st.mapper.n_scans_fused > 0
    assert _known_cells(grid) > 500


# ------------------------------------------- world-fault kinds (ISSUE 18)

def test_world_fault_event_validation():
    with pytest.raises(ValueError, match="memory_pressure needs"):
        FaultEvent(step=0, kind="memory_pressure", value=0.0)
    with pytest.raises(ValueError, match="memory_pressure needs"):
        FaultEvent(step=0, kind="memory_pressure", value=1.5)
    with pytest.raises(ValueError, match="spill_corrupt needs"):
        FaultEvent(step=0, kind="spill_corrupt", value=0.0)
    # The valid shapes construct.
    FaultEvent(step=0, kind="memory_pressure", value=0.6, duration=10)
    FaultEvent(step=0, kind="spill_corrupt", value=2.0)


class _StubWorldStore:
    """Records the governor seam calls FaultPlan makes."""

    def __init__(self, spilled=2):
        self.holds = []                      # live hold names
        self.trace = []                      # (op, arg) sequence
        self._spilled = spilled

    def hold_pressure(self, name, squeeze):
        self.holds.append(name)
        self.trace.append(("hold", name, float(squeeze)))

    def release_pressure(self, name):
        self.holds.remove(name)
        self.trace.append(("release", name))

    def corrupt_spill(self, n):
        k = min(int(n), self._spilled)
        self._spilled -= k
        hit = [(0, i) for i in range(k)]
        self.trace.append(("corrupt", k))
        return hit


def test_memory_pressure_windows_compose_per_event_holds():
    """Two overlapping memory_pressure windows hold under DISTINCT
    per-event names (worst-of composes inside the governor), and each
    window's clear releases only its own hold — the bus_drop/partition
    refcount doctrine applied to the memory resource."""
    store = _StubWorldStore()
    stack = type("S", (), {"world": store, "bus": None})()
    plan = FaultPlan([
        FaultEvent(step=0, kind="memory_pressure", value=0.7,
                   duration=10),
        FaultEvent(step=5, kind="memory_pressure", value=0.4,
                   duration=10),
    ], seed=0)
    plan.apply(stack, 0)
    assert store.holds == ["chaos@0"]
    plan.apply(stack, 5)
    assert store.holds == ["chaos@0", "chaos@5"]   # both live
    plan.apply(stack, 10)                    # first window clears
    assert store.holds == ["chaos@5"]        # second survives
    plan.apply(stack, 15)
    assert store.holds == []
    assert plan.done()
    assert ("hold", "chaos@0", 0.7) in store.trace
    assert ("hold", "chaos@5", 0.4) in store.trace


def test_world_faults_skip_note_on_storeless_stack():
    """Degrade, never die: both kinds no-op with a log note against a
    stack with no windowed world store (windowed=False missions run
    the same chaos scripts)."""
    stack = type("S", (), {"world": None, "mapper": None,
                        "bus": None})()
    plan = FaultPlan([
        FaultEvent(step=0, kind="memory_pressure", value=0.5,
                   duration=5),
        FaultEvent(step=1, kind="spill_corrupt", value=1.0),
    ], seed=0)
    plan.apply(stack, 0)
    plan.apply(stack, 1)
    plan.apply(stack, 6)
    assert plan.done()
    assert sum(1 for _, d in plan.log if "skipped" in d) == 2

    # The mapper.world fallback path reaches the store too.
    store = _StubWorldStore(spilled=3)
    mapper = type("M", (), {"world": store})()
    stack2 = type("S", (), {"mapper": mapper, "bus": None})()
    plan2 = FaultPlan([
        FaultEvent(step=0, kind="spill_corrupt", value=2.0),
    ], seed=0)
    plan2.apply(stack2, 0)
    assert ("corrupt", 2) in store.trace
    assert any("spill_corrupt 2 tile(s)" in d for _, d in plan2.log)

    # An empty spill notes the skip instead of inventing a hit list.
    store3 = _StubWorldStore(spilled=0)
    stack3 = type("S", (), {"world": store3, "bus": None})()
    plan3 = FaultPlan([
        FaultEvent(step=0, kind="spill_corrupt", value=1.0),
    ], seed=0)
    plan3.apply(stack3, 0)
    assert any("no spilled tiles" in d for _, d in plan3.log)


def test_random_plan_world_faults_magnitudes_and_shared_resource():
    """`allow_world_faults=True` admits both memory kinds with
    kind-appropriate magnitudes, and `spill_corrupt` shares the
    durable-storage resource with `corrupt_checkpoint` so generated
    plans never overlap the two."""
    from jax_mapping.resilience.faultplan import (MEMORY_KINDS,
                                                  _fault_resource)
    # One resource, by declaration: generated plans can therefore
    # never stack a spill rot inside a checkpoint-truncation window.
    assert _fault_resource("spill_corrupt", 0) \
        == _fault_resource("corrupt_checkpoint", 0) == ("checkpoint",)
    assert _fault_resource("memory_pressure", 0) == ("memory",)

    seen = set()
    for seed in range(30):
        plan = random_plan(200, n_faults=8, seed=seed, n_robots=2,
                           allow_world_faults=True)
        occupied = []
        for ev in plan.events:
            if ev.kind == "memory_pressure":
                assert 0.4 <= ev.value <= 0.9
                assert ev.duration > 0
            elif ev.kind == "spill_corrupt":
                assert ev.value in (1.0, 2.0, 3.0)
            if ev.kind in MEMORY_KINDS or ev.kind == "corrupt_checkpoint":
                res = _fault_resource(ev.kind, ev.robot, ev.name)
                window = (res, ev.step, ev.step + ev.duration)
                for r, s, e in occupied:
                    assert not (r == res and s <= window[2]
                                and window[1] <= e), \
                        f"seed {seed}: overlapping {res} windows"
                occupied.append(window)
            seen.add(ev.kind)
    assert "memory_pressure" in seen and "spill_corrupt" in seen


def test_random_plan_defaults_reproduce_pre_world_sampler():
    """Default arguments are bit-compatible with the pre-world-fault
    sampler: same seed, same events, no memory kinds."""
    from jax_mapping.resilience.faultplan import MEMORY_KINDS
    for seed in (0, 3, 7):
        a = random_plan(150, n_faults=6, seed=seed, n_robots=2)
        b = random_plan(150, n_faults=6, seed=seed, n_robots=2,
                        allow_world_faults=False)
        assert a.events == b.events
        assert not any(ev.kind in MEMORY_KINDS for ev in a.events)
