"""Observability subsystem: counters, stage timers, trace guard, /metrics."""

import time

import numpy as np

from jax_mapping.utils import Counters, StageTimer, device_trace, global_metrics
from jax_mapping.utils.profiling import Metrics


def test_counters_threadsafe_increment():
    import threading
    c = Counters()
    def work():
        for _ in range(500):
            c.inc("x")
    ts = [threading.Thread(target=work) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert c.get("x") == 2000
    assert c.snapshot() == {"x": 2000}
    assert c.get("missing") == 0


def test_stage_timer_stats():
    t = StageTimer()
    for _ in range(3):
        with t.stage("s"):
            time.sleep(0.01)
    snap = t.snapshot()["s"]
    assert snap["count"] == 3
    assert 5 < snap["mean_ms"] < 100
    assert snap["max_ms"] >= snap["mean_ms"] * 0.5
    assert snap["ewma_ms"] > 0


def test_stage_timer_counts_exceptions():
    t = StageTimer()
    try:
        with t.stage("boom"):
            raise ValueError
    except ValueError:
        pass
    assert t.snapshot()["boom"]["count"] == 1


def test_device_trace_never_raises(tmp_path):
    # CPU backend: trace may or may not start; the guard must not raise
    # either way and the block must run.
    ran = False
    with device_trace(str(tmp_path / "trace")):
        ran = True
    assert ran


def test_metrics_flow_into_http_endpoint(tiny_cfg):
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.http_api import MapApiServer

    global_metrics.counters.inc("test.flow")
    with global_metrics.stages.stage("test.stage"):
        pass
    api = MapApiServer(Bus(), brain=None, port=0)
    api.serve_thread()
    try:
        code, ctype, body = api.handle("/metrics")
        assert code == 200 and ctype == "text/plain"
        text = body if isinstance(body, str) else body.decode()
        assert "jax_mapping_test_flow_total" in text
        assert "jax_mapping_stage_test_stage_ms_count" in text
    finally:
        api.shutdown()


def test_mapper_feeds_global_metrics(tiny_cfg):
    before = global_metrics.counters.get("mapper.scans_fused")
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W
    stack = launch_sim_stack(
        tiny_cfg, W.empty_arena(96, tiny_cfg.grid.resolution_m))
    try:
        stack.run_steps(12)
    finally:
        stack.shutdown()
    assert global_metrics.counters.get("mapper.scans_fused") > before
    assert "mapper.slam_step" in global_metrics.stages.snapshot()
