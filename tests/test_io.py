"""Checkpoint/resume + trace record/replay tests (SURVEY.md §4-5: the
capabilities the reference lacked, validated the way it never could)."""

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.bridge.launch import launch_sim_stack
from jax_mapping.io import (
    TraceRecorder, TraceReplayer, load_checkpoint, save_checkpoint,
)
from jax_mapping.models import slam as S
from jax_mapping.sim import world as W


def _run_slam(cfg, world, n, state=None):
    from jax_mapping.sim import lidar
    res = cfg.grid.resolution_m
    n_samples = int(cfg.scan.range_max_m / (res * 0.5))
    st = S.init_state(cfg) if state is None else state
    for _ in range(n):
        scan = lidar.simulate_scans(cfg.scan, jnp.asarray(world), res,
                                    n_samples, st.pose[None])[0]
        st, _ = S.slam_step(cfg, st, scan, jnp.float32(60.0),
                            jnp.float32(100.0), jnp.float32(0.1))
    return st


def test_checkpoint_roundtrip_exact(tiny_cfg, tmp_path):
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, seed=5)
    st = _run_slam(tiny_cfg, world, 8)
    path = str(tmp_path / "slam.ckpt.npz")
    save_checkpoint(path, st, config_json=tiny_cfg.to_json())

    restored, cfg_json = load_checkpoint(path, S.init_state(tiny_cfg))
    assert cfg_json == tiny_cfg.to_json()
    for a, b in zip(__import__("jax").tree_util.tree_leaves(st),
                    __import__("jax").tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_resume_equals_continuous(tiny_cfg, tmp_path):
    """restart-from-checkpoint == never-restarted, bit-for-bit."""
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, seed=5)
    st10 = _run_slam(tiny_cfg, world, 10)
    st15_direct = _run_slam(tiny_cfg, world, 5, state=st10)

    path = str(tmp_path / "mid.ckpt.npz")
    save_checkpoint(path, st10)
    restored, _ = load_checkpoint(path, S.init_state(tiny_cfg))
    st15_resumed = _run_slam(tiny_cfg, world, 5, state=restored)

    np.testing.assert_array_equal(np.asarray(st15_direct.grid),
                                  np.asarray(st15_resumed.grid))
    np.testing.assert_array_equal(np.asarray(st15_direct.pose),
                                  np.asarray(st15_resumed.pose))


def test_checkpoint_shape_drift_detected(tiny_cfg, tmp_path):
    import dataclasses
    st = S.init_state(tiny_cfg)
    path = str(tmp_path / "drift.ckpt.npz")
    save_checkpoint(path, st)
    bigger = dataclasses.replace(
        tiny_cfg, grid=dataclasses.replace(tiny_cfg.grid, size_cells=512))
    with pytest.raises(ValueError, match="shape"):
        load_checkpoint(path, S.init_state(bigger))


# --------------------------------------------- corruption (resilience PR)

def test_checkpoint_truncation_raises_checkpoint_corrupt(tiny_cfg, tmp_path):
    """The power-loss case: a truncated .npz must raise CheckpointCorrupt
    (a ValueError), never a raw zipfile/KeyError escape."""
    from jax_mapping.io import CheckpointCorrupt
    st = S.init_state(tiny_cfg)
    path = str(tmp_path / "trunc.ckpt.npz")
    save_checkpoint(path, st)
    import os
    with open(path, "rb+") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CheckpointCorrupt):
        load_checkpoint(path, S.init_state(tiny_cfg))
    assert issubclass(CheckpointCorrupt, ValueError)  # old handlers catch


def test_checkpoint_crc_detects_bit_rot(tiny_cfg, tmp_path):
    """A checkpoint that is a VALID zip but whose leaf bytes changed
    (bit rot, partial sidecar copy) fails the per-leaf CRC32: exactly
    the corruption zipfile-level checks cannot see when the whole
    member was rewritten."""
    from jax_mapping.io import CheckpointCorrupt
    from jax_mapping.io.checkpoint import _META_KEY
    st = S.init_state(tiny_cfg)
    path = str(tmp_path / "rot.ckpt.npz")
    save_checkpoint(path, st)
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    key = next(k for k in arrays if k != _META_KEY
               and arrays[k].size > 0 and arrays[k].dtype == np.float32)
    arrays[key] = arrays[key].copy()
    arrays[key].flat[0] += 1.0              # one flipped value
    with open(path, "wb") as f:
        np.savez_compressed(f, **arrays)    # re-zipped: zip CRCs now FINE
    with pytest.raises(CheckpointCorrupt, match="CRC32"):
        load_checkpoint(path, S.init_state(tiny_cfg))


def test_checkpoint_fallback_rotates_to_last_good(tiny_cfg, tmp_path):
    """save_checkpoint keeps the previous generation; the fallback loader
    degrades to it when the newest file rots — the supervisor's
    auto-resume contract."""
    from jax_mapping.io import (CheckpointCorrupt,
                                load_checkpoint_with_fallback,
                                previous_checkpoint_path)
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, seed=5)
    st_a = _run_slam(tiny_cfg, world, 4)
    st_b = _run_slam(tiny_cfg, world, 4, state=st_a)
    path = str(tmp_path / "gen.ckpt.npz")
    save_checkpoint(path, st_a)
    save_checkpoint(path, st_b)             # rotates gen A to .prev
    prev = previous_checkpoint_path(path)
    import os
    assert os.path.exists(prev)

    # Intact newest: fallback loads it and reports the primary path.
    got, _, used = load_checkpoint_with_fallback(
        path, S.init_state(tiny_cfg))
    assert used == path
    np.testing.assert_array_equal(np.asarray(got.grid),
                                  np.asarray(st_b.grid))

    # Corrupt newest: fallback degrades to the rotated last-good gen.
    with open(path, "rb+") as f:
        f.truncate(os.path.getsize(path) // 3)
    got, _, used = load_checkpoint_with_fallback(
        path, S.init_state(tiny_cfg))
    assert used == prev
    np.testing.assert_array_equal(np.asarray(got.grid),
                                  np.asarray(st_a.grid))

    # BOTH generations gone: the corruption propagates.
    with open(prev, "rb+") as f:
        f.truncate(8)
    with pytest.raises(CheckpointCorrupt):
        load_checkpoint_with_fallback(path, S.init_state(tiny_cfg))


def test_save_does_not_rotate_corrupt_primary_over_last_good(tiny_cfg,
                                                             tmp_path):
    """A corrupted primary must NOT be rotated into the .prev slot on
    the next save — that would evict the genuine last-good generation
    (the corrupt-then-save-then-crash chaos sequence)."""
    import os

    from jax_mapping.io import (load_checkpoint_with_fallback,
                                previous_checkpoint_path)
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, seed=5)
    st_a = _run_slam(tiny_cfg, world, 4)
    st_b = _run_slam(tiny_cfg, world, 4, state=st_a)
    st_c = _run_slam(tiny_cfg, world, 4, state=st_b)
    path = str(tmp_path / "rot.ckpt.npz")
    save_checkpoint(path, st_a)
    save_checkpoint(path, st_b)              # .prev = A (intact)
    with open(path, "rb+") as f:             # primary (B) rots on disk
        f.truncate(os.path.getsize(path) // 3)
    save_checkpoint(path, st_c)              # must NOT move B over A
    got, _ = load_checkpoint(
        previous_checkpoint_path(path), S.init_state(tiny_cfg))
    np.testing.assert_array_equal(np.asarray(got.grid),
                                  np.asarray(st_a.grid))
    # And the new primary is C, loadable.
    got, _, used = load_checkpoint_with_fallback(
        path, S.init_state(tiny_cfg))
    assert used == path
    np.testing.assert_array_equal(np.asarray(got.grid),
                                  np.asarray(st_c.grid))


def test_trace_record_replay_golden(tiny_cfg, tmp_path):
    """Record a live run's /scan+/odom, replay into a FRESH mapper, and the
    rebuilt map must equal the live mapper's map (golden-trace path)."""
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=3, seed=9)
    stack = launch_sim_stack(tiny_cfg, world, n_robots=1, realtime=False)
    try:
        rec = TraceRecorder(stack.bus, ["scan", "odom"])
        stack.brain.start_exploring()
        stack.run_steps(20)
        live_grid = np.asarray(stack.mapper.merged_grid())
        path = str(tmp_path / "run.trace.npz")
        n = rec.save(path)
        assert n > 20                      # scans + odoms
    finally:
        stack.shutdown()

    # Replay through a fresh bus + mapper only (no sim, no brain).
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode
    bus = Bus()
    mapper = MapperNode(tiny_cfg, bus, n_robots=1)
    player = TraceReplayer(path)
    assert len(player) == n
    sent = player.replay(bus, speed=None)
    assert sent == n
    mapper.tick()
    replayed_grid = np.asarray(mapper.merged_grid())

    # Identical inputs -> identical device math -> identical map, except the
    # initial pose calibration the stack applies; compare occupancy content.
    live_occ = (live_grid > 0.5).sum()
    rep_occ = (replayed_grid > 0.5).sum()
    assert rep_occ > 0
    assert abs(int(live_occ) - int(rep_occ)) < max(60, 0.35 * live_occ)


def test_trace_replay_realtime_timing(tiny_cfg, tmp_path):
    """speed=K respects relative stamps."""
    import time
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.messages import Header, LaserScan
    bus = Bus()
    rec = TraceRecorder(bus, ["scan"])
    pub = bus.publisher("scan")
    for i in range(4):
        pub.publish(LaserScan(header=Header(stamp=i * 0.1),
                              ranges=np.arange(5, dtype=np.float32)))
    path = str(tmp_path / "t.trace.npz")
    rec.save(path)

    out = Bus()
    sub = out.subscribe("scan", callback=lambda m: None)
    t0 = time.monotonic()
    TraceReplayer(path).replay(out, speed=2.0)     # 0.3 s span at 2x
    assert 0.10 < time.monotonic() - t0 < 1.0
    assert sub.n_received == 4


def test_trace_message_fidelity(tmp_path):
    """Every allowlisted type survives the npz round trip field-for-field."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.messages import (
        FrontierArray, Header, LaserScan, MapMetaData, OccupancyGrid,
        Odometry, Pose2D, Twist,
    )
    bus = Bus()
    rec = TraceRecorder(bus, ["a", "b", "c"])
    scan = LaserScan(header=Header(stamp=1.5, frame_id="laser"),
                     angle_increment=0.2,
                     ranges=np.array([1.0, 2.0, 0.0], np.float32))
    odom = Odometry(header=Header(stamp=1.6, frame_id="odom"),
                    pose=Pose2D(1.0, -2.0, 0.3),
                    twist=Twist(linear_x=0.1, angular_z=-0.5))
    grid = OccupancyGrid(header=Header(stamp=1.7, frame_id="map"),
                         info=MapMetaData(resolution=0.05, width=2, height=1,
                                          origin=Pose2D(-1, -1, 0)),
                         data=np.array([0, 100], np.int8))
    bus.publisher("a").publish(scan)
    bus.publisher("b").publish(odom)
    bus.publisher("c").publish(grid)
    path = str(tmp_path / "f.trace.npz")
    rec.save(path)

    msgs = {t: m for _, t, m in TraceReplayer(path).messages()}
    assert msgs["a"].header.frame_id == "laser"
    np.testing.assert_array_equal(msgs["a"].ranges, scan.ranges)
    assert msgs["a"].angle_increment == pytest.approx(0.2)
    assert msgs["b"].pose.theta == pytest.approx(0.3)
    assert msgs["b"].twist.angular_z == pytest.approx(-0.5)
    assert msgs["c"].info.origin.x == pytest.approx(-1)
    np.testing.assert_array_equal(msgs["c"].data, grid.data)


def test_keyframe_sidecar_guards(tmp_path):
    """Review r5: the .voxelkf saver refuses to clobber a non-sidecar
    file at the colliding name, and the loader turns structural damage
    (missing arrays, mismatched lengths) into ValueError — the type the
    HTTP /load handler maps to 409."""
    import numpy as np
    import pytest

    from jax_mapping.io.checkpoint import (keyframe_sidecar_path,
                                           load_keyframe_sidecar,
                                           save_checkpoint,
                                           save_keyframe_sidecar)

    base = str(tmp_path / "ck.npz")
    kf = {"depths": np.zeros((2, 4, 5), np.float32),
          "rels": np.zeros((2, 3), np.float32),
          "node_idx": np.zeros(2, np.int32),
          "thins": np.zeros(2, np.int32),
          "robot": np.zeros(2, np.int32)}

    # A REAL checkpoint parked at the sidecar's path must not be
    # silently overwritten.
    save_checkpoint(keyframe_sidecar_path(base), {"grid": np.ones(3)})
    with pytest.raises(ValueError, match="refusing to overwrite"):
        save_keyframe_sidecar(base, kf)
    import os
    os.remove(keyframe_sidecar_path(base))

    save_keyframe_sidecar(base, kf)
    got = load_keyframe_sidecar(base)
    np.testing.assert_array_equal(got["depths"], kf["depths"])

    # Wrong-kind file at the sidecar path -> ValueError, not KeyError.
    save_checkpoint(keyframe_sidecar_path(base), {"grid": np.ones(3)})
    with pytest.raises(ValueError, match="not a voxel keyframe"):
        load_keyframe_sidecar(base)

    # Length disagreement -> ValueError.
    bad = dict(kf, robot=np.zeros(3, np.int32))
    os.remove(keyframe_sidecar_path(base))
    save_keyframe_sidecar(base, bad)
    with pytest.raises(ValueError, match="disagree on length"):
        load_keyframe_sidecar(base)


def test_world_sidecar_roundtrip_and_guards(tiny_cfg, tmp_path):
    """ISSUE 18 satellite: the .world sidecar (window re-anchor
    manifest) follows the full sidecar doctrine — exact roundtrip,
    refuse-to-clobber, wrong-kind refusal, CRC-loud corruption,
    config-drift refusal, None on absence, sentinel-checked clear."""
    import dataclasses
    import os

    from jax_mapping.io.checkpoint import (CheckpointCorrupt,
                                           clear_world_sidecar,
                                           load_world_sidecar,
                                           save_checkpoint,
                                           save_world_sidecar,
                                           world_sidecar_path)

    base = str(tmp_path / "ck.npz")
    payload = {
        "origin_tile": np.asarray([2, 5], np.int64),
        "epochs": np.asarray([3, 17, 4], np.int64),
        "away": np.asarray([[0, 1], [7, 9]], np.int64),
    }

    # No sidecar yet -> None (pre-windowed checkpoints load fine).
    assert load_world_sidecar(base) is None

    # A REAL checkpoint parked at the sidecar's path must not be
    # silently overwritten…
    save_checkpoint(world_sidecar_path(base), {"grid": np.ones(3)})
    with pytest.raises(ValueError, match="refusing to overwrite"):
        save_world_sidecar(base, payload)
    # …and must not load as one either.
    with pytest.raises(ValueError, match="not a world sidecar"):
        load_world_sidecar(base)
    # clear() is sentinel-checked: it refuses to delete the impostor.
    assert clear_world_sidecar(base) is False
    assert os.path.exists(world_sidecar_path(base))
    os.remove(world_sidecar_path(base))

    # Incomplete payloads refuse at SAVE time.
    with pytest.raises(ValueError, match="missing keys"):
        save_world_sidecar(base, {"origin_tile": payload["origin_tile"]})

    wp = save_world_sidecar(base, payload,
                            config_json=tiny_cfg.to_json())
    got = load_world_sidecar(base,
                             running_config_json=tiny_cfg.to_json())
    for k in payload:
        np.testing.assert_array_equal(got[k], payload[k])

    # Config drift (a different lattice) refuses with ValueError.
    drifted = tiny_cfg.replace(grid=dataclasses.replace(
        tiny_cfg.grid, size_cells=tiny_cfg.grid.size_cells * 2))
    with pytest.raises(ValueError, match="differs from the running"):
        load_world_sidecar(base, running_config_json=drifted.to_json())

    # Truncation -> CheckpointCorrupt, never a silent re-anchor.
    raw = open(wp, "rb").read()
    with open(wp, "wb") as f:
        f.write(raw[:len(raw) // 2])
    with pytest.raises(CheckpointCorrupt):
        load_world_sidecar(base)

    # The damaged file fails the sentinel, so even the saver refuses
    # to touch it (it COULD be a user checkpoint) — explicit removal
    # is the operator's escape hatch.
    with pytest.raises(ValueError, match="refusing to overwrite"):
        save_world_sidecar(base, payload)
    os.remove(wp)

    # A fresh save wins, and clear() removes the genuine article.
    save_world_sidecar(base, payload)
    assert clear_world_sidecar(base) is True
    assert load_world_sidecar(base) is None
