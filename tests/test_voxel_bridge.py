"""The 3D pipeline as a runtime citizen: sim depth cam -> VoxelMapperNode
-> shared voxel grid -> HTTP /voxel-image (BASELINE configs[4] in the
node graph, not just ops).
"""

import urllib.error
import urllib.request

import numpy as np
import pytest

from jax_mapping.bridge.launch import launch_sim_stack
from jax_mapping.bridge.png import decode_gray
from jax_mapping.ops import voxel as V
from jax_mapping.sim import world as W


@pytest.fixture(scope="module")
def stack(tiny_cfg):
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=3)
    st = launch_sim_stack(tiny_cfg, world, n_robots=2, http_port=0,
                          seed=3, depth_cam=True)
    st.brain.start_exploring()
    st.run_steps(30)
    yield st
    st.shutdown()


def test_depth_images_flow_and_fuse(stack):
    vm = stack.voxel_mapper
    assert vm is not None
    # 2 robots x 30 ticks, modulo any unpaired startup images.
    assert vm.n_images_fused >= 40
    grid = np.asarray(vm.voxel_grid())
    assert np.abs(grid).sum() > 0, "no 3D evidence fused"
    occ3 = np.asarray(V.to_occupancy(stack.cfg.voxel, grid))
    assert (occ3 == 0).sum() > 100, "no free space carved in 3D"


def test_height_map_and_slice_exports(stack):
    vm = stack.voxel_mapper
    hm = vm.height_map()
    z, y, x = (stack.cfg.voxel.size_z_cells, stack.cfg.voxel.size_y_cells,
               stack.cfg.voxel.size_x_cells)
    assert hm.shape == (y, x)
    blocked = vm.obstacle_slice(0.05, 0.45)
    assert blocked.shape == (y, x)
    img = vm.height_map_image()
    assert img.dtype == np.uint8 and img.shape == (y, x)


def test_http_voxel_image(stack):
    url = f"http://127.0.0.1:{stack.api.port}/voxel-image"
    body = urllib.request.urlopen(url).read()
    assert body[:8] == b"\x89PNG\r\n\x1a\n"
    img = decode_gray(body)
    assert img.shape == (stack.cfg.voxel.size_y_cells,
                         stack.cfg.voxel.size_x_cells)


def test_http_voxel_image_404_without_depth_cam(tiny_cfg):
    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{st.api.port}/voxel-image")
        assert ei.value.code == 404
    finally:
        st.shutdown()


def test_voxel_mapper_rejects_shape_drift(tiny_cfg):
    """A depth image whose shape disagrees with DepthCamConfig must be
    counted out, not mis-projected."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.messages import DepthImage, Header, Odometry, \
        Pose2D
    from jax_mapping.bridge.voxel_mapper import VoxelMapperNode
    from jax_mapping.utils import global_metrics as M

    bus = Bus()
    vm = VoxelMapperNode(tiny_cfg, bus, n_robots=1)
    od = bus.publisher("odom")
    dp = bus.publisher("depth")
    od.publish(Odometry(header=Header(stamp=1.0), pose=Pose2D(0, 0, 0)))
    before = M.counters.get("voxel_mapper.images_bad_shape")
    dp.publish(DepthImage(header=Header(stamp=1.1),
                          depth=np.ones((7, 9), np.float32)))
    vm.tick()
    assert vm.n_images_fused == 0
    assert M.counters.get("voxel_mapper.images_bad_shape") == before + 1


def test_demo_record_replay_with_depth(tiny_cfg, tmp_path, capsys):
    """The rosbag workflow covers the 3D pipeline: a bag recorded with
    --depth-cam replays into both maps, and --voxel-out works off the
    bag alone."""
    import json

    from jax_mapping import demo

    bag = str(tmp_path / "depth.bag.npz")
    rc = demo.main(["--steps", "16", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--depth-cam",
                    "--record", bag])
    assert rc == 0
    capsys.readouterr()

    png = str(tmp_path / "replayed_hm.png")
    rc = demo.main(["--robots", "1", "--replay", bag, "--voxel-out", png])
    assert rc == 0
    raw = capsys.readouterr().out
    out = json.loads(raw[raw.index("{\n"):])
    assert out["depth_images_fused"] > 0
    assert out["voxels_free"] > 0
    import os
    assert os.path.exists(png)


def test_demo_voxel_checkpoint_sidecar(tiny_cfg, tmp_path, capsys):
    """--save-final writes the 3D sidecar; --resume restores it."""
    import json

    from jax_mapping import demo
    from jax_mapping.io.checkpoint import voxel_sidecar_path

    ckpt = str(tmp_path / "run.npz")
    rc = demo.main(["--steps", "16", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--depth-cam",
                    "--save-final", ckpt])
    assert rc == 0
    raw = capsys.readouterr().out
    first = json.loads(raw[raw.index("{\n"):])
    assert first["voxels_free"] > 0
    import os
    assert os.path.exists(voxel_sidecar_path(ckpt))

    rc = demo.main(["--steps", "2", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--depth-cam",
                    "--resume", ckpt])
    assert rc == 0
    raw = capsys.readouterr().out
    second = json.loads(raw[raw.index("{\n"):])
    # The resumed 3D map keeps (and extends) the first run's evidence.
    assert second["voxels_free"] >= first["voxels_free"] * 0.9


def test_http_save_load_voxel_sidecar(tiny_cfg, tmp_path):
    import json as _json
    import urllib.request

    import jax.numpy as jnp

    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=3)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0,
                          seed=3, depth_cam=True)
    try:
        st.api.checkpoint_dir = str(tmp_path)
        st.brain.start_exploring()
        st.run_steps(25)
        url = f"http://127.0.0.1:{st.api.port}"
        body = _json.loads(urllib.request.urlopen(
            urllib.request.Request(url + "/save", method="POST")).read())
        assert "voxel_path" in body
        g_before = np.asarray(st.voxel_mapper.voxel_grid()).copy()
        assert np.abs(g_before).sum() > 0

        st.voxel_mapper.restore_grid(
            jnp.zeros_like(st.voxel_mapper.voxel_grid()))
        body = _json.loads(urllib.request.urlopen(
            urllib.request.Request(url + "/load", method="POST")).read())
        assert "voxel_path" in body
        np.testing.assert_array_equal(
            np.asarray(st.voxel_mapper.voxel_grid()), g_before)
    finally:
        st.shutdown()


def test_sidecar_guards(tiny_cfg, tmp_path):
    """The name-collision and drift guards: a sidecar never clobbers or
    masquerades as a 2D checkpoint, and config drift refuses loudly."""
    import dataclasses

    import jax.numpy as jnp
    import pytest as _pytest

    from jax_mapping.io.checkpoint import (
        load_voxel_sidecar, save_checkpoint, save_voxel_sidecar,
        voxel_sidecar_path,
    )

    grid = jnp.zeros((4, 8, 8), jnp.float32)
    ck = str(tmp_path / "x.npz")

    # A (user's) checkpoint occupying the sidecar filename: save refuses.
    save_checkpoint(voxel_sidecar_path(ck), {"other": np.ones(3)})
    with _pytest.raises(ValueError, match="not a voxel sidecar"):
        save_voxel_sidecar(ck, grid)
    with _pytest.raises(ValueError, match="not a voxel sidecar"):
        load_voxel_sidecar(ck, grid)

    # Clean path: roundtrip + drift refusal.
    ck2 = str(tmp_path / "y.npz")
    save_voxel_sidecar(ck2, grid, config_json=tiny_cfg.to_json())
    out = load_voxel_sidecar(ck2, grid,
                             running_config_json=tiny_cfg.to_json())
    np.testing.assert_array_equal(np.asarray(out), np.asarray(grid))
    other = dataclasses.replace(
        tiny_cfg, voxel=dataclasses.replace(tiny_cfg.voxel,
                                            logodds_occ=0.123))
    with _pytest.raises(ValueError, match="config differs"):
        load_voxel_sidecar(ck2, grid,
                           running_config_json=other.to_json())
    # No sidecar at all: None, not an error.
    assert load_voxel_sidecar(str(tmp_path / "none.npz"), grid) is None


def test_http_rejects_reserved_voxel_name(tiny_cfg, tmp_path):
    import urllib.error
    import urllib.request

    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0)
    try:
        st.api.checkpoint_dir = str(tmp_path)
        url = f"http://127.0.0.1:{st.api.port}/save?name=x.voxel"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(url, method="POST"))
        assert ei.value.code == 400
    finally:
        st.shutdown()


def test_replay_voxel_out_without_depth_bag_errors(tiny_cfg, tmp_path,
                                                   capsys):
    from jax_mapping import demo

    bag = str(tmp_path / "no_depth.bag.npz")
    rc = demo.main(["--steps", "8", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--record", bag])
    assert rc == 0
    capsys.readouterr()
    rc = demo.main(["--robots", "1", "--replay", bag,
                    "--voxel-out", str(tmp_path / "hm.png")])
    assert rc == 2
    assert "no depth topics" in capsys.readouterr().err


def test_voxel_restore_survives_inflight_fuse(tiny_cfg):
    """ADVICE r4 (medium): a restore_grid landing while tick() fuses
    outside the lock must not be overwritten by a grid fused from the
    pre-restore state. The post-fuse revision check drops the fused
    result instead."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.messages import DepthImage, Header, Odometry, \
        Pose2D
    from jax_mapping.bridge.voxel_mapper import VoxelMapperNode
    from jax_mapping.utils import global_metrics as M

    bus = Bus()
    vm = VoxelMapperNode(tiny_cfg, bus, n_robots=1)
    cam = tiny_cfg.depthcam
    od = bus.publisher("odom")
    dp = bus.publisher("depth")
    od.publish(Odometry(header=Header(stamp=1.0), pose=Pose2D(0, 0, 0)))
    dp.publish(DepthImage(header=Header(stamp=1.1),
                          depth=np.full((cam.height_px, cam.width_px),
                                        1.0, np.float32)))

    restored = np.full((tiny_cfg.voxel.size_z_cells,
                        tiny_cfg.voxel.size_y_cells,
                        tiny_cfg.voxel.size_x_cells), 0.625, np.float32)
    real_V = vm._V

    class RacingV:
        """voxel-ops proxy landing an HTTP /load mid-fuse."""

        def __getattr__(self, name):
            return getattr(real_V, name)

        def fuse_depths(self, *args):
            out = real_V.fuse_depths(*args)
            vm.restore_grid(restored)
            return out

    vm._V = RacingV()
    before = M.counters.get("voxel_mapper.fuse_dropped_stale")
    try:
        vm.tick()
    finally:
        vm._V = real_V
    assert M.counters.get("voxel_mapper.fuse_dropped_stale") == before + 1
    np.testing.assert_array_equal(
        np.asarray(vm.voxel_grid()), restored,
        err_msg="fuse from pre-restore state overwrote the restored map")


def test_height_map_and_slice_exports_are_writable_copies(stack):
    """Lint C3 regression: the public 2.5D exports must be WRITABLE
    host copies, never read-only np.asarray views of the live device
    grid — a consumer masking them in place would otherwise crash (or
    alias the device buffer)."""
    vm = stack.voxel_mapper
    hm = vm.height_map()
    blocked = vm.obstacle_slice(0.05, 0.45)
    assert hm.flags.writeable
    assert blocked.flags.writeable
    # In-place consumer edits must not leak into the next export (the
    # copies are genuinely per-call).
    before = hm.copy()
    hm[:] = -1.0
    np.testing.assert_array_equal(vm.height_map(), before)
