"""The 3D pipeline as a runtime citizen: sim depth cam -> VoxelMapperNode
-> shared voxel grid -> HTTP /voxel-image (BASELINE configs[4] in the
node graph, not just ops).
"""

import urllib.error
import urllib.request

import numpy as np
import pytest

from jax_mapping.bridge.launch import launch_sim_stack
from jax_mapping.bridge.png import decode_gray
from jax_mapping.ops import voxel as V
from jax_mapping.sim import world as W


@pytest.fixture(scope="module")
def stack(tiny_cfg):
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=3)
    st = launch_sim_stack(tiny_cfg, world, n_robots=2, http_port=0,
                          seed=3, depth_cam=True)
    st.brain.start_exploring()
    st.run_steps(30)
    yield st
    st.shutdown()


def test_depth_images_flow_and_fuse(stack):
    vm = stack.voxel_mapper
    assert vm is not None
    # 2 robots x 30 ticks, modulo any unpaired startup images.
    assert vm.n_images_fused >= 40
    grid = np.asarray(vm.voxel_grid())
    assert np.abs(grid).sum() > 0, "no 3D evidence fused"
    occ3 = np.asarray(V.to_occupancy(stack.cfg.voxel, grid))
    assert (occ3 == 0).sum() > 100, "no free space carved in 3D"


def test_height_map_and_slice_exports(stack):
    vm = stack.voxel_mapper
    hm = vm.height_map()
    z, y, x = (stack.cfg.voxel.size_z_cells, stack.cfg.voxel.size_y_cells,
               stack.cfg.voxel.size_x_cells)
    assert hm.shape == (y, x)
    blocked = vm.obstacle_slice(0.05, 0.45)
    assert blocked.shape == (y, x)
    img = vm.height_map_image()
    assert img.dtype == np.uint8 and img.shape == (y, x)


def test_http_voxel_image(stack):
    url = f"http://127.0.0.1:{stack.api.port}/voxel-image"
    body = urllib.request.urlopen(url).read()
    assert body[:8] == b"\x89PNG\r\n\x1a\n"
    img = decode_gray(body)
    assert img.shape == (stack.cfg.voxel.size_y_cells,
                         stack.cfg.voxel.size_x_cells)


def test_http_voxel_image_404_without_depth_cam(tiny_cfg):
    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{st.api.port}/voxel-image")
        assert ei.value.code == 404
    finally:
        st.shutdown()


def test_voxel_mapper_rejects_shape_drift(tiny_cfg):
    """A depth image whose shape disagrees with DepthCamConfig must be
    counted out, not mis-projected."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.messages import DepthImage, Header, Odometry, \
        Pose2D
    from jax_mapping.bridge.voxel_mapper import VoxelMapperNode
    from jax_mapping.utils import global_metrics as M

    bus = Bus()
    vm = VoxelMapperNode(tiny_cfg, bus, n_robots=1)
    od = bus.publisher("odom")
    dp = bus.publisher("depth")
    od.publish(Odometry(header=Header(stamp=1.0), pose=Pose2D(0, 0, 0)))
    before = M.counters.get("voxel_mapper.images_bad_shape")
    dp.publish(DepthImage(header=Header(stamp=1.1),
                          depth=np.ones((7, 9), np.float32)))
    vm.tick()
    assert vm.n_images_fused == 0
    assert M.counters.get("voxel_mapper.images_bad_shape") == before + 1
