"""Correlative scan-matcher tests: pose recovery, response gating."""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.ops import grid as G
from jax_mapping.ops import scan_match as M


def room_scan(scan_cfg, pose, half=2.0):
    """Analytic scan of a square room centred at the origin."""
    out = np.zeros(scan_cfg.padded_beams, np.float32)
    for b in range(scan_cfg.n_beams):
        a = pose[2] + b * scan_cfg.angle_increment_rad
        ca, sa = math.cos(a), math.sin(a)
        rx = ((half if ca > 0 else -half) - pose[0]) / ca if abs(ca) > 1e-9 else 1e9
        ry = ((half if sa > 0 else -half) - pose[1]) / sa if abs(sa) > 1e-9 else 1e9
        out[b] = min(rx, ry)
    return out


@pytest.fixture(scope="module")
def room_map(tiny_cfg):
    """Map built from several scans around the room (so walls are crisp)."""
    g, s = tiny_cfg.grid, tiny_cfg.scan
    poses, scans = [], []
    for i in range(8):
        p = np.array([0.3 * math.cos(i), 0.3 * math.sin(i), 0.7 * i], np.float32)
        poses.append(p)
        scans.append(room_scan(s, p))
    grid = G.fuse_scans(g, s, G.empty_grid(g),
                        jnp.asarray(np.stack(scans)), jnp.asarray(np.stack(poses)))
    return grid


def test_scan_points_geometry(tiny_cfg):
    s = tiny_cfg.scan
    ranges = np.zeros(s.padded_beams, np.float32)
    ranges[:s.n_beams] = 1.0
    pts, valid = M.scan_points(s, jnp.asarray(ranges))
    pts, valid = np.asarray(pts), np.asarray(valid)
    assert valid[:s.n_beams].all() and not valid[s.n_beams:].any()
    np.testing.assert_allclose(pts[0], [1.0, 0.0], atol=1e-6)
    half = s.n_beams // 2   # exactly 180 degrees for an even beam count
    np.testing.assert_allclose(pts[half], [-1.0, 0.0], atol=1e-5)


def test_likelihood_field_peaks_on_walls(tiny_cfg, room_map):
    g, m = tiny_cfg.grid, tiny_cfg.matcher
    origin = np.asarray(G.patch_origin(g, jnp.zeros(2)))
    patch = np.asarray(room_map)[origin[0]:origin[0] + g.patch_cells,
                                 origin[1]:origin[1] + g.patch_cells]
    field = np.asarray(M.likelihood_field(g, m, jnp.asarray(patch)))
    occ = patch > g.occ_threshold
    assert field[occ].min() > 0.9          # walls are high
    centre = g.patch_cells // 2
    assert field[centre, centre] < 0.05    # open interior is low
    assert field.max() <= 1.0 + 1e-6




def test_match_recovers_known_offset(tiny_cfg, room_map):
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    true_pose = np.array([0.12, -0.08, 0.25], np.float32)
    scan = room_scan(s, true_pose)
    # Guess is off by a realistic odometry drift.
    guess = true_pose + np.array([0.08, -0.06, 0.12], np.float32)
    res = M.match(g, s, m, room_map, jnp.asarray(scan), jnp.asarray(guess))
    got = np.asarray(res.pose)
    assert bool(res.accepted)
    np.testing.assert_allclose(got[:2], true_pose[:2], atol=0.03)
    assert abs(got[2] - true_pose[2]) < 0.02
    assert float(res.response) > float(res.coarse_response) - 0.05


def test_match_identity_when_guess_correct(tiny_cfg, room_map):
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    true_pose = np.array([0.0, 0.0, 0.0], np.float32)
    scan = room_scan(s, true_pose)
    res = M.match(g, s, m, room_map, jnp.asarray(scan), jnp.asarray(true_pose))
    got = np.asarray(res.pose)
    np.testing.assert_allclose(got, true_pose, atol=0.02)
    assert float(res.response) > 0.5


def test_match_rejects_empty_map(tiny_cfg):
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    scan = room_scan(s, np.zeros(3, np.float32))
    res = M.match(g, s, m, G.empty_grid(g), jnp.asarray(scan), jnp.zeros(3))
    assert not bool(res.accepted)          # nothing to match against
    assert float(res.response) < m.min_response


def test_match_batch_matches_single(tiny_cfg, room_map):
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    poses = np.array([[0.0, 0.0, 0.0], [0.1, 0.05, 0.3]], np.float32)
    scans = np.stack([room_scan(s, p) for p in poses])
    batch = M.match_batch(g, s, m, room_map, jnp.asarray(scans),
                          jnp.asarray(poses))
    for i in range(2):
        single = M.match(g, s, m, room_map, jnp.asarray(scans[i]),
                         jnp.asarray(poses[i]))
        np.testing.assert_allclose(np.asarray(batch.pose[i]),
                                   np.asarray(single.pose), atol=1e-6)


def test_conv_scores_bf16_parity(tiny_cfg, rng):
    """The bf16 coarse-scoring path (MatcherConfig.coarse_bf16, TPU
    default) must track the f32 scores within bf16 rounding and keep the
    same winner on a peaked response surface."""
    field = jnp.asarray(rng.random((64, 64)).astype(np.float32))
    rasters = jnp.asarray(
        (rng.random((5, 64, 64)) < 0.05).astype(np.float32))
    mass = jnp.float32(1.0)
    f32 = M._conv_scores(field, rasters, mass, 3, 1)
    bf16 = M._conv_scores(field, rasters, mass, 3, 1,
                          compute_dtype=jnp.bfloat16)
    assert bf16.dtype == jnp.float32          # fp32 accumulate/output
    np.testing.assert_allclose(np.asarray(bf16), np.asarray(f32),
                               rtol=2e-2, atol=1e-2)
    assert int(jnp.argmax(bf16)) == int(jnp.argmax(f32))


def test_match_covariance_sharp_room_vs_corridor(tiny_cfg, room_map):
    """Correlation-surface covariance (MatchResult.cov): a structured
    room pins all three axes tightly; an infinite corridor (two parallel
    walls along x) leaves x unconstrained — its variance must blow up
    relative to the constrained y while the room's stays tight."""
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    true_pose = np.array([0.0, 0.0, 0.0], np.float32)
    scan = room_scan(s, true_pose)
    res_room = M.match(g, s, m, room_map, jnp.asarray(scan),
                       jnp.asarray(true_pose))
    cov_room = np.asarray(res_room.cov)
    assert (cov_room >= 0).all() and np.isfinite(cov_room).all()
    # Tight: stddev within a few map cells / the fine angle step's scale.
    assert cov_room[0] < (4 * g.resolution_m) ** 2
    assert cov_room[1] < (4 * g.resolution_m) ** 2

    # Corridor along x: walls at y = +-0.8 m spanning the whole grid.
    n = g.size_cells
    corridor = np.zeros((n, n), np.float32)
    half = n // 2
    wall = int(round(0.8 / g.resolution_m))
    corridor[half - wall - 1:half - wall + 1, :] = 3.0
    corridor[half + wall - 1:half + wall + 1, :] = 3.0
    # A corridor scan: beams hit the walls, nothing bounds x.
    rr = np.zeros(s.padded_beams, np.float32)
    angles = np.linspace(0, 2 * math.pi, s.n_beams, endpoint=False)
    sin = np.sin(angles)
    with np.errstate(divide="ignore"):
        d = np.where(np.abs(sin) > 1e-6, 0.8 / np.abs(sin), 0.0)
    rr[:s.n_beams] = np.where((d > 0) & (d <= s.range_max_m), d, 0.0)
    res_cor = M.match(g, s, m, jnp.asarray(corridor), jnp.asarray(rr),
                      jnp.asarray(true_pose))
    cov_cor = np.asarray(res_cor.cov)
    assert cov_cor[0] > cov_cor[1] * 4, (
        f"corridor did not widen x variance: {cov_cor}")
    assert cov_cor[1] < (4 * g.resolution_m) ** 2
