"""JoyDeviceReader: raw evdev byte streams drive the teleop chain.

No /dev/input or uinput exists in this image, so the reader is driven
with spec-conformant synthetic input_event bytes through a pipe — the
emulated-device pattern tests/test_native.py uses for the LD06 parser.
The end-to-end test runs pad bytes -> reader -> TeleopNode -> /cmd_vel
-> ThymioBrain manual override -> motor targets.
"""

import os
import struct
import time

import pytest

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.driver import (
    MOTOR_LEFT_TARGET, MOTOR_RIGHT_TARGET, SimulatedThymioDriver,
)
from jax_mapping.bridge.joydev import (
    EV_ABS, EV_KEY, EV_SYN, EVENT, JoyDeviceReader, pack_event,
)
from jax_mapping.bridge.teleop import JoystickConfig, TeleopNode


BTN_SOUTH = 0x130      # PS4 "X", joystick.yaml enable_button 0


def collect(bus, topic="/cmd_vel"):
    out = []
    bus.subscribe(topic, callback=out.append)
    return out


def _feed(events: bytes):
    r, w = os.pipe()
    os.write(w, events)
    os.close(w)                      # EOF ends pump()
    return r


def test_event_struct_layout():
    """24-byte native input_event framing: round-trips type/code/value."""
    b = pack_event(EV_ABS, 0x05, 255, t=1.5)
    assert len(b) == EVENT.size
    sec, usec, etype, code, value = EVENT.unpack(b)
    assert (sec, usec) == (1, 500000)
    assert (etype, code, value) == (EV_ABS, 0x05, 255)


def test_sample_assembled_only_on_syn(tiny_cfg):
    bus = Bus()
    teleop = TeleopNode(bus)
    seen = []
    teleop.update = lambda axes, buttons: seen.append((axes, buttons))
    ev = (pack_event(EV_KEY, BTN_SOUTH, 1)
          + pack_event(EV_ABS, 0x02, 255)     # right stick hard right
          + pack_event(EV_ABS, 0x05, 0))      # right stick full forward
    rd = JoyDeviceReader(_feed(ev), teleop)
    rd.pump()
    assert seen == []                          # no SYN yet -> no sample

    ev += pack_event(EV_SYN, 0, 0)
    rd2 = JoyDeviceReader(_feed(ev), teleop)
    rd2.pump()
    assert len(seen) == 1
    axes, buttons = seen[0]
    assert buttons[0] == 1
    # 0..255 normalization: 255 -> +1; axis 5 is vertical -> inverted,
    # raw 0 (stick pushed forward) -> +1.
    assert axes[2] == pytest.approx(1.0)
    assert axes[5] == pytest.approx(1.0)
    assert rd2.n_samples == 1


def test_normalization_center_and_clamp(tiny_cfg):
    bus = Bus()
    teleop = TeleopNode(bus)
    seen = []
    teleop.update = lambda a, b: seen.append(a)
    ev = (pack_event(EV_ABS, 0x00, 128) + pack_event(EV_SYN, 0, 0)
          + pack_event(EV_ABS, 0x00, 300) + pack_event(EV_SYN, 0, 0))
    rd = JoyDeviceReader(_feed(ev), teleop)
    rd.pump()
    assert seen[0][0] == pytest.approx(0.0, abs=0.01)   # centred stick
    assert seen[1][0] == 1.0                            # out-of-range clamps


def test_hat_range_and_custom_override(tiny_cfg):
    bus = Bus()
    teleop = TeleopNode(bus)
    seen = []
    teleop.update = lambda a, b: seen.append(a)
    ev = (pack_event(EV_ABS, 0x10, -1)      # hat left
          + pack_event(EV_ABS, 0x03, 512)   # custom-range axis
          + pack_event(EV_SYN, 0, 0))
    rd = JoyDeviceReader(_feed(ev), teleop,
                         abs_ranges={3: (0.0, 1024.0)})
    rd.pump()
    assert seen[0][6] == pytest.approx(-1.0)
    assert seen[0][3] == pytest.approx(0.0, abs=0.01)


def test_pad_drives_brain_override(tiny_cfg):
    """The verdict's acceptance chain: emulated pad events drive
    /cmd_vel through the brain's manual override to motor targets."""
    bus = Bus()
    out = collect(bus)
    driver = SimulatedThymioDriver(n_robots=1)
    from jax_mapping.bridge.brain import ThymioBrain
    brain = ThymioBrain(tiny_cfg, bus, driver)
    assert brain.link_up and not brain.is_exploring

    cfg = JoystickConfig()
    teleop = TeleopNode(bus, cfg)
    # Full forward on the linear axis (vertical -> raw 0 is forward),
    # centred angular, deadman held.
    ev = (pack_event(EV_KEY, BTN_SOUTH, 1)
          + pack_event(EV_ABS, 0x03, 0)          # axis 3 = linear
          + pack_event(EV_ABS, 0x02, 128)        # axis 2 = angular ~ 0
          + pack_event(EV_SYN, 0, 0))
    rd = JoyDeviceReader(_feed(ev), teleop,
                         invert_axes=frozenset({1, 3, 5, 7}))
    rd.pump()
    teleop._tick()

    assert len(out) == 1
    assert out[0].linear_x == pytest.approx(cfg.scale_linear, rel=0.02)
    assert abs(out[0].angular_z) < 0.02

    brain.update_loop()
    node = driver.first_node()
    k = tiny_cfg.robot.speed_coeff_m_per_unit_s
    # 0.20 m/s maps to ~660 wheel units, clamped to the Thymio target
    # range (+-600, brain.py).
    expect = min(cfg.scale_linear / k, 600.0)
    assert driver[node][MOTOR_LEFT_TARGET] == pytest.approx(expect, rel=0.05)
    assert driver[node][MOTOR_RIGHT_TARGET] == pytest.approx(expect,
                                                            rel=0.05)

    # Deadman release stops the robot.
    ev2 = pack_event(EV_KEY, BTN_SOUTH, 0) + pack_event(EV_SYN, 0, 0)
    rd2 = JoyDeviceReader(_feed(ev2), teleop,
                          invert_axes=frozenset({1, 3, 5, 7}))
    rd2.pump()
    teleop._tick()
    assert out[-1].linear_x == 0.0
    brain.update_loop()
    assert driver[node][MOTOR_LEFT_TARGET] == 0


def test_spin_thread_and_close(tiny_cfg):
    bus = Bus()
    teleop = TeleopNode(bus)
    r, w = os.pipe()
    rd = JoyDeviceReader(r, teleop).spin_thread()
    os.write(w, pack_event(EV_KEY, BTN_SOUTH, 1) + pack_event(EV_SYN, 0, 0))
    deadline = time.monotonic() + 2.0
    while rd.n_samples == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert rd.n_samples == 1
    os.close(w)
    rd.close()


def test_close_interrupts_quiet_pad(tiny_cfg):
    """close() must return promptly even when no events ever arrive (a
    bare blocking read would hang the 2 s join and race fd reuse)."""
    bus = Bus()
    teleop = TeleopNode(bus)
    r, w = os.pipe()
    rd = JoyDeviceReader(r, teleop).spin_thread()
    time.sleep(0.05)                       # thread parked in select()
    t0 = time.monotonic()
    rd.close()
    assert time.monotonic() - t0 < 1.0
    assert not rd._thread.is_alive()
    os.close(w)
    os.close(r)


def test_attach_joystick_publishes_without_manual_ticks(tiny_cfg):
    """attach_joystick must own a running executor: pad bytes alone must
    reach /cmd_vel through the autorepeat timer (the code-review finding:
    a TeleopNode without an executor never publishes)."""
    from jax_mapping.bridge.joydev import attach_joystick

    bus = Bus()
    out = collect(bus)
    r, w = os.pipe()
    session = attach_joystick(bus, r)
    try:
        os.write(w, pack_event(EV_KEY, BTN_SOUTH, 1)
                 + pack_event(EV_ABS, 0x03, 0)
                 + pack_event(EV_SYN, 0, 0))
        deadline = time.monotonic() + 3.0
        while not out and time.monotonic() < deadline:
            time.sleep(0.02)
        assert out, "autorepeat timer never published /cmd_vel"
        assert out[0].linear_x != 0.0
    finally:
        session.close()
        os.close(w)


def test_attach_joystick_bad_device_leaks_nothing(tiny_cfg):
    """ADVICE r4: a bad --joy-device path must raise WITHOUT leaving a
    spinning executor thread or a live TeleopNode subscription behind."""
    import threading

    from jax_mapping.bridge.joydev import attach_joystick

    bus = Bus()
    before = threading.active_count()
    with pytest.raises(OSError):
        attach_joystick(bus, "/nonexistent/input/event99")
    time.sleep(0.1)
    assert threading.active_count() == before, \
        "executor thread leaked after device-open failure"
