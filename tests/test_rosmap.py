"""ROS map_server map interchange (io/rosmap.py + /save-map + prior seed).

The reference ecosystem's portable map artifact: map_saver_cli writes
`map.pgm` + `map.yaml`, map_server/Nav2/localization consume it. The
reference itself never saved a map (restart lost it, SURVEY.md §5); the
framework's npz checkpoints are lossless but private. These tests pin the
format (trinary pixel values, row flip, YAML sidecar), the HTTP export,
and the localization-bootstrapping import path.
"""

import dataclasses
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from jax_mapping.config import tiny_config
from jax_mapping.io import rosmap


def _trinary(shape, seed=0):
    rng = np.random.default_rng(seed)
    return rng.choice(np.array([-1, 0, 100], np.int8), size=shape)


def test_roundtrip_bitwise(tmp_path):
    occ = _trinary((48, 64))
    pgm, yaml = rosmap.save_map(str(tmp_path / "m"), occ, 0.05,
                                (-1.6, -1.2))
    occ2, res, origin = rosmap.load_map(yaml)
    assert res == 0.05 and origin == (-1.6, -1.2)
    assert occ2.dtype == np.int8 and (occ2 == occ).all()


def test_pgm_format_pinned(tmp_path):
    """The bytes a foreign map_server reads: P5 header, 255 maxval, and
    the trinary pixel values with grid row 0 (min-y) at the image
    BOTTOM."""
    occ = np.full((4, 3), -1, np.int8)
    occ[0, 0] = 100                          # min-y corner occupied
    occ[3, 2] = 0                            # max-y corner free
    pgm, _ = rosmap.save_map(str(tmp_path / "m"), occ, 0.05, (0.0, 0.0))
    raw = open(pgm, "rb").read()
    assert raw.startswith(b"P5\n3 4\n255\n")
    px = np.frombuffer(raw[len(b"P5\n3 4\n255\n"):], np.uint8).reshape(4, 3)
    assert px[3, 0] == 0                     # occupied, image bottom-left
    assert px[0, 2] == 254                   # free, image top-right
    assert px[1, 1] == 205                   # unknown elsewhere


def test_load_foreign_negate_and_thresholds(tmp_path):
    """Imports honour the sidecar's negate/threshold fields, not just the
    values this module writes."""
    px = np.array([[0, 128, 255]], np.uint8)
    with open(tmp_path / "f.pgm", "wb") as f:
        f.write(b"P5\n3 1\n255\n" + px.tobytes())
    (tmp_path / "f.yaml").write_text(
        "image: f.pgm\nresolution: 0.1\norigin: [0.0, 0.0, 0.0]\n"
        "negate: 1\noccupied_thresh: 0.9\nfree_thresh: 0.1\n")
    occ, res, origin = rosmap.load_map(str(tmp_path / "f.yaml"))
    # negate=1: p_occ = px/255 -> 0.0, 0.502, 1.0
    assert occ[0, 0] == 0 and occ[0, 1] == -1 and occ[0, 2] == 100


def test_embed_offsets_and_clip():
    cfg = tiny_config()
    g = cfg.grid
    occ = np.full((10, 10), 0, np.int8)
    occ[5, 5] = 100
    # Origin one metre inside the grid's min corner.
    ox, oy = g.origin_m
    out = rosmap.embed_in_grid(occ, g.resolution_m, (ox + 1.0, oy + 1.0), g)
    k = round(1.0 / g.resolution_m)
    assert out[k + 5, k + 5] == 100
    assert out[k, k] == 0
    assert out[0, 0] == -1                   # outside the import: unknown
    with pytest.raises(ValueError):
        rosmap.embed_in_grid(occ, g.resolution_m * 2, (0, 0), g)


def test_load_rejects_rotated_origin(tmp_path):
    """origin yaw != 0 is legal ROS but the axis-aligned embed would put
    every wall in the wrong place — must refuse loudly."""
    px = np.full((2, 2), 254, np.uint8)
    with open(tmp_path / "r.pgm", "wb") as f:
        f.write(b"P5\n2 2\n255\n" + px.tobytes())
    (tmp_path / "r.yaml").write_text(
        "image: r.pgm\nresolution: 0.05\norigin: [0.0, 0.0, 1.57]\n"
        "negate: 0\n")
    with pytest.raises(ValueError, match="yaw"):
        rosmap.load_map(str(tmp_path / "r.yaml"))


def test_logodds_prior_values():
    occ = np.array([[-1, 0, 100]], np.int8)
    lo = rosmap.logodds_prior(occ)
    assert lo[0, 0] == 0.0 and lo[0, 1] == -2.0 and lo[0, 2] == 2.0


# ---------------------------------------------------------------------------
# HTTP export + localization-bootstrap import, end to end
# ---------------------------------------------------------------------------

def _stack(tiny_cfg, tmp_path, seed=0):
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    cfg = dataclasses.replace(
        tiny_cfg, planner=dataclasses.replace(tiny_cfg.planner,
                                              enabled=False))
    world = W.empty_arena(96, cfg.grid.resolution_m)
    st = launch_sim_stack(cfg, world, n_robots=1, http_port=0, seed=seed)
    st.api.checkpoint_dir = str(tmp_path)
    return st


def test_http_save_map_and_reimport(tiny_cfg, tmp_path):
    """Drive: explore a bit -> POST /save-map -> artifact loads back to
    exactly the occupancy the live /map exports; GET is rejected; a
    FRESH mapper seeded with the import serves the imported walls."""
    st = _stack(tiny_cfg, tmp_path)
    try:
        st.brain.start_exploring()
        st.run_steps(30)
        url = f"http://127.0.0.1:{st.api.port}/save-map?name=arena"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url)     # GET must not write
        assert ei.value.code == 405
        with urllib.request.urlopen(
                urllib.request.Request(url, method="POST")) as r:
            body = json.loads(r.read())
        assert body["status"] == "saved"
        occ, res, origin = rosmap.load_map(body["yaml"])
        g = st.cfg.grid
        assert res == g.resolution_m and origin == g.origin_m
        from jax_mapping.bridge.messages import occupancy_from_logodds
        live = occupancy_from_logodds(
            np.asarray(st.mapper.merged_grid()), g.occ_threshold,
            g.free_threshold, g.resolution_m, g.origin_m)
        live_occ = np.asarray(live.data, np.int8).reshape(
            live.info.height, live.info.width)
        assert (occ == live_occ).all()
        assert (occ == 100).sum() > 0, "nothing mapped in 30 steps?"
    finally:
        st.shutdown()

    # Fresh stack, seeded from the artifact: the walls are served on
    # /map-image terms without a single scan fused.
    st2 = _stack(tiny_cfg, tmp_path, seed=1)
    try:
        occ2 = rosmap.embed_in_grid(occ, res, origin, st2.cfg.grid)
        st2.mapper.seed_map_prior(rosmap.logodds_prior(occ2))
        g = st2.cfg.grid
        from jax_mapping.bridge.messages import occupancy_from_logodds
        seeded = occupancy_from_logodds(
            np.asarray(st2.mapper.merged_grid()), g.occ_threshold,
            g.free_threshold, g.resolution_m, g.origin_m)
        s_occ = np.asarray(seeded.data, np.int8).reshape(
            seeded.info.height, seeded.info.width)
        assert ((s_occ == 100) == (occ == 100)).all()
        assert ((s_occ == 0) == (occ == 0)).all()
    finally:
        st2.shutdown()


def test_prior_survives_closure_refusion(tiny_cfg, tmp_path):
    """Loop-closure ring re-fusions rebuild the shared grid from EMPTY +
    key scans, which would silently erase an imported prior at the first
    closure. _finish_step must backfill: live evidence wins wherever any
    exists, the prior keeps the unobserved map."""
    import jax.numpy as jnp

    from jax_mapping.bridge.messages import Header, Odometry, Pose2D

    st = _stack(tiny_cfg, tmp_path)
    try:
        m = st.mapper
        n = st.cfg.grid.size_cells
        prior = np.zeros((n, n), np.float32)
        prior[10:20, 10:20] = 2.0            # imported wall A
        prior[30:40, 30:40] = -2.0           # imported free space
        m.seed_map_prior(prior)
        # A closure's in-step repair output: empty except live evidence —
        # wall B, plus fresh FREE evidence overlapping imported wall A's
        # corner (live must win there).
        refused = np.zeros((n, n), np.float32)
        refused[60:70, 60:70] = 3.0          # live wall B
        refused[10:12, 10:12] = -0.4         # live free over prior wall A
        base_grid = m.merged_grid()
        state = m.states[0]._replace(grid=jnp.asarray(refused))
        od = Odometry(header=Header(stamp=1.0), pose=Pose2D(0, 0, 0))
        assert m._finish_step(0, state, od, 1, matched=True, closed=True,
                              base_grid=base_grid, base_gen=m._state_gen[0])
        out = np.asarray(m.merged_grid())
        assert (out[60:70, 60:70] == 3.0).all()      # live wall kept
        assert (out[10:12, 10:12] == -0.4).all()     # live free wins
        assert (out[12:20, 12:20] == 2.0).all()      # prior wall backfilled
        assert (out[30:40, 30:40] == -2.0).all()     # prior free backfilled
        assert (out[0, 0] == 0.0)                    # unknown stays unknown
    finally:
        st.shutdown()


def test_prior_lifecycle_across_save_load(tiny_cfg, tmp_path):
    """The prior persists through /save + /load (a .prior sidecar) — a
    resumed session's first closure must still backfill the imported map
    — and a /load of a PRIOR-LESS checkpoint CLEARS a live prior, so a
    stale prior can't paint another environment's walls."""
    import json as _json
    import urllib.request

    st = _stack(tiny_cfg, tmp_path)
    try:
        n = st.cfg.grid.size_cells
        prior = np.zeros((n, n), np.float32)
        prior[10:20, 10:20] = 2.0
        st.mapper.seed_map_prior(prior)
        base = f"http://127.0.0.1:{st.api.port}"
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/save?name=withprior", method="POST")) as r:
            body = _json.loads(r.read())
        assert body["prior_path"].endswith(".prior.npz")

        # Clear the live prior, then /load: it must come back.
        st.mapper.restore_states(st.mapper.snapshot_states())
        assert st.mapper.map_prior() is None
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/load?name=withprior", method="POST")) as r:
            body = _json.loads(r.read())
        assert "prior_path" in body
        restored = np.asarray(st.mapper.map_prior())
        assert (restored[10:20, 10:20] == 2.0).all()

        # Save WITHOUT a prior, re-seed one live, /load the prior-less
        # checkpoint: the stale prior must clear.
        st.mapper.restore_states(st.mapper.snapshot_states())
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/save?name=noprior", method="POST")) as r:
            assert "prior_path" not in _json.loads(r.read())
        st.mapper.seed_map_prior(prior)
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/load?name=noprior", method="POST")) as r:
            _json.loads(r.read())
        assert st.mapper.map_prior() is None

        # Overwrite the SAME name without a live prior: the earlier
        # save's .prior sidecar must be deleted, or the old environment's
        # prior resurrects on the next /load of that name.
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/save?name=withprior", method="POST")) as r:
            assert "prior_path" not in _json.loads(r.read())
        import os as _os
        assert not _os.path.exists(
            str(tmp_path / "withprior.prior.npz"))
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/load?name=withprior", method="POST")) as r:
            _json.loads(r.read())
        assert st.mapper.map_prior() is None
    finally:
        st.shutdown()


def test_clear_prior_sidecar_is_sentinel_checked(tmp_path):
    """The stale-sidecar cleanup must never delete a NON-sidecar file at
    the sidecar path (a user checkpoint literally named '.prior' — the
    collision the save/load guards refuse); only real sidecars go."""
    from jax_mapping.io.checkpoint import (clear_prior_sidecar,
                                           prior_sidecar_path,
                                           save_checkpoint,
                                           save_prior_sidecar)

    ckpt = str(tmp_path / "x.npz")
    # A real sidecar: removed.
    save_prior_sidecar(ckpt, np.zeros((4, 4), np.float32))
    assert clear_prior_sidecar(ckpt)
    import os as _os
    assert not _os.path.exists(prior_sidecar_path(ckpt))
    # A user checkpoint at the sidecar path: left alone.
    save_checkpoint(prior_sidecar_path(ckpt),
                    {"grid": np.zeros((4, 4), np.float32)})
    assert not clear_prior_sidecar(ckpt)
    assert _os.path.exists(prior_sidecar_path(ckpt))


def test_demo_map_prior_bad_input_polite(tmp_path, capsys):
    """--map-prior input failures follow the --resume contract: polite
    message + rc=2, not a traceback."""
    from jax_mapping import demo

    rc = demo.main(["--steps", "1", "--world", "arena", "--world-cells",
                    "96", "--map-prior", str(tmp_path / "nope.yaml")])
    assert rc == 2
    assert "cannot seed --map-prior" in capsys.readouterr().out


def test_seed_prior_shape_guard(tiny_cfg, tmp_path):
    st = _stack(tiny_cfg, tmp_path)
    try:
        with pytest.raises(ValueError):
            st.mapper.seed_map_prior(np.zeros((8, 8), np.float32))
    finally:
        st.shutdown()


def test_demo_map_prior_cli(tmp_path, capsys):
    """The operator surface: a map_server artifact boots a demo run via
    --map-prior and the seed is reported."""
    from jax_mapping import demo

    occ = np.full((32, 32), 0, np.int8)
    occ[0, :] = 100
    _pgm, yaml = rosmap.save_map(str(tmp_path / "prior"), occ, 0.05,
                                 (-0.8, -0.8))
    rc = demo.main(["--steps", "2", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--map-prior", yaml])
    assert rc == 0
    out = capsys.readouterr().out
    assert "seeded map prior" in out
    # --map-prior + --resume would let restore_states silently overwrite
    # the prior; the demo refuses the combination instead.
    rc = demo.main(["--steps", "1", "--world", "arena", "--world-cells",
                    "96", "--map-prior", yaml, "--resume", "nope.npz"])
    assert rc == 2
    assert "pick one" in capsys.readouterr().out
