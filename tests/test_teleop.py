"""Teleop node semantics (joystick.yaml capability) + brain manual override."""

import numpy as np
import pytest

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.driver import (
    MOTOR_LEFT_TARGET, MOTOR_RIGHT_TARGET, SimulatedThymioDriver,
)
from jax_mapping.bridge.messages import Twist
from jax_mapping.bridge.teleop import JoystickConfig, TeleopNode


def collect(bus, topic="/cmd_vel"):
    out = []
    bus.subscribe(topic, callback=out.append)
    return out


def test_teleop_requires_deadman():
    bus = Bus()
    out = collect(bus)
    node = TeleopNode(bus)
    node.update(axes=[0, 0, 0.5, 1.0], buttons=[0])   # deadman NOT held
    node._tick()
    assert out == []                                   # no motion commands


def test_teleop_scales_axes():
    bus = Bus()
    out = collect(bus)
    cfg = JoystickConfig()
    node = TeleopNode(bus, cfg)
    node.update(axes=[0, 0, -0.5, 1.0], buttons=[1])   # deadman held
    node._tick()
    node._tick()                                       # autorepeat
    assert len(out) == 2
    assert out[0].linear_x == pytest.approx(1.0 * cfg.scale_linear)
    assert out[0].angular_z == pytest.approx(-0.5 * cfg.scale_angular)


def test_teleop_stop_on_release():
    bus = Bus()
    out = collect(bus)
    node = TeleopNode(bus)
    node.update(axes=[0, 0, 0, 1.0], buttons=[1])
    node._tick()
    node.update(axes=[0, 0, 0, 1.0], buttons=[0])      # release deadman
    node._tick()
    node._tick()                                       # idle: nothing more
    assert len(out) == 2
    assert out[-1].linear_x == 0.0 and out[-1].angular_z == 0.0


def test_brain_manual_override(tiny_cfg):
    from jax_mapping.bridge.brain import ThymioBrain
    bus = Bus()
    driver = SimulatedThymioDriver(n_robots=1)
    brain = ThymioBrain(tiny_cfg, bus, driver)
    assert brain.link_up

    # Exploring off + fresh cmd_vel -> wheel targets from the twist.
    pub = bus.publisher("/cmd_vel")
    k = tiny_cfg.robot.speed_coeff_m_per_unit_s
    pub.publish(Twist(linear_x=100 * k, angular_z=0.0))
    brain.update_loop()
    assert driver[driver.first_node()][MOTOR_LEFT_TARGET] == 100
    assert driver[driver.first_node()][MOTOR_RIGHT_TARGET] == 100

    # While exploring, the autonomous policy owns the motors again.
    brain.start_exploring()
    pub.publish(Twist(linear_x=-100 * k, angular_z=0.0))
    brain.update_loop()
    assert driver[driver.first_node()][MOTOR_LEFT_TARGET] >= 0

    # Stale command (timeout) -> no override.
    brain.stop_exploring()
    brain._last_cmd_vel_t = -1e9
    brain.update_loop()
    assert driver[driver.first_node()][MOTOR_LEFT_TARGET] == 0


def test_teleop_input_watchdog_stops_robot():
    bus = Bus()
    out = collect(bus)
    node = TeleopNode(bus, input_timeout_s=0.05)
    node.update(axes=[0, 0, 0, 1.0], buttons=[1])
    node._tick()
    assert len(out) == 1 and out[0].linear_x > 0
    import time as _t
    _t.sleep(0.08)            # input source dies; autorepeat must not outlive it
    node._tick()
    node._tick()
    assert len(out) == 2
    assert out[-1].linear_x == 0.0 and out[-1].angular_z == 0.0
