"""3D voxel pipeline: NumPy-oracle golden tests + depth-cam sim + fusion
integration (BASELINE.json configs[4]; VERDICT r3 item 3).

Strategy mirrors tests/test_grid.py: an independent, loop-based NumPy
implementation of the inverse sensor model pins the vectorised device
code; geometry facts (flat wall, floor, frustum) pin the conventions.
"""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.config import DepthCamConfig, VoxelConfig, tiny_config
from jax_mapping.ops import voxel as V
from jax_mapping.sim import depthcam as DC
from jax_mapping.sim import world as W


@pytest.fixture(scope="module")
def vox():
    return tiny_config().voxel


@pytest.fixture(scope="module")
def cam():
    return tiny_config().depthcam


# ---------------------------------------------------------------------------
# Camera pose geometry
# ---------------------------------------------------------------------------

def test_camera_pose_axes(cam):
    pos, R = V.camera_pose(1.0, 2.0, 0.0, cam)
    pos, R = np.asarray(pos), np.asarray(R)
    np.testing.assert_allclose(pos, [1.0, 2.0, cam.mount_height_m],
                               atol=1e-6)
    # yaw 0: optical axis +x, camera right -> world -y, camera down -> -z.
    np.testing.assert_allclose(R[:, 2], [1, 0, 0], atol=1e-6)   # forward
    np.testing.assert_allclose(R[:, 0], [0, -1, 0], atol=1e-6)  # right
    np.testing.assert_allclose(R[:, 1], [0, 0, -1], atol=1e-6)  # down
    # Proper rotation.
    np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-6)
    assert np.linalg.det(R) == pytest.approx(1.0, abs=1e-6)


def test_camera_pose_yaw_quarter_turn(cam):
    _, R = V.camera_pose(0.0, 0.0, math.pi / 2, cam)
    np.testing.assert_allclose(np.asarray(R)[:, 2], [0, 1, 0], atol=1e-6)


# ---------------------------------------------------------------------------
# Inverse sensor model vs a NumPy loop oracle
# ---------------------------------------------------------------------------

def _oracle_classify(vox, cam, depth, cam_pos, R_wc, y0, x0, ny, nx):
    """Independent loop-based inverse sensor model."""
    res = vox.resolution_m
    ox, oy, oz = vox.origin_m
    Z = vox.size_z_cells
    out = np.zeros((Z, ny, nx), np.float32)
    tol = vox.hit_tolerance_cells * res
    for zi in range(Z):
        for yi in range(ny):
            for xi in range(nx):
                w = np.array([(x0 + xi + 0.5) * res + ox,
                              (y0 + yi + 0.5) * res + oy,
                              (zi + 0.5) * res + oz])
                c = R_wc.T @ (w - cam_pos)
                if c[2] <= cam.range_min_m:
                    continue
                u = int(round(cam.fx * c[0] / c[2] + cam.cx))
                v = int(round(cam.fy * c[1] / c[2] + cam.cy))
                if not (0 <= u < cam.width_px and 0 <= v < cam.height_px):
                    continue
                if c @ c > vox.max_range_m ** 2:    # euclidean trust horizon
                    continue
                z_img = depth[v, u]
                if z_img <= 0.0 or z_img < cam.range_min_m:
                    continue
                carve = min(z_img, vox.max_range_m)
                if abs(c[2] - z_img) <= tol and z_img <= vox.max_range_m:
                    out[zi, yi, xi] = vox.logodds_occ
                elif c[2] < carve - tol:
                    out[zi, yi, xi] = vox.logodds_free
    return out


def test_classify_region_matches_oracle(vox, cam, rng):
    depth = rng.uniform(0.0, 1.5, (cam.height_px, cam.width_px)) \
        .astype(np.float32)
    depth[rng.random(depth.shape) < 0.1] = 0.0       # no-return speckle
    pos, R = V.camera_pose(0.3, -0.2, 0.7, cam)
    pos_n, R_n = np.asarray(pos), np.asarray(R)
    y0, x0, ny, nx = 40, 48, 24, 24
    got = np.asarray(V.classify_region(vox, cam, jnp.asarray(depth),
                                       pos, R, y0, x0, ny, nx))
    want = _oracle_classify(vox, cam, depth, pos_n, R_n, y0, x0, ny, nx)
    # Round-to-nearest pixel boundaries can flip on f32 vs f64 — allow a
    # tiny disagreement budget on boundary voxels, like the 2D grid tests.
    mismatch = np.mean(got != want)
    assert mismatch < 0.005, f"{mismatch:.4%} voxels disagree with oracle"


def test_zero_depth_carves_nothing(vox, cam):
    """An all-no-return image must leave the grid untouched (the depth-cam
    convention differs from the LD06 zero-as-outlier rule on purpose)."""
    depth = jnp.zeros((cam.height_px, cam.width_px), jnp.float32)
    g0 = V.empty_voxel_grid(vox)
    g1 = V.fuse_depth(vox, cam, g0, depth, jnp.asarray([0.0, 0.0, 0.0]))
    assert np.asarray(g1).sum() == 0.0


def test_behind_camera_untouched(vox, cam):
    """Voxels behind the image plane never classify."""
    depth = jnp.full((cam.height_px, cam.width_px), 1.0, jnp.float32)
    pos, R = V.camera_pose(0.0, 0.0, 0.0, cam)     # facing +x
    # Region strictly at negative x (behind the camera).
    ctr_y = vox.size_y_cells // 2
    delta = np.asarray(V.classify_region(vox, cam, depth, pos, R,
                                         ctr_y - 8, 8, 16, 16))
    x_hi_m = (8 + 16 + 0.5) * vox.resolution_m + vox.origin_m[0]
    assert x_hi_m < 0                               # sanity: region behind
    assert np.abs(delta).sum() == 0.0


# ---------------------------------------------------------------------------
# Flat-wall fusion: occupied shell at the wall, free space before it
# ---------------------------------------------------------------------------

def test_flat_wall_fusion(vox, cam):
    """Synthetic depth of a wall at 0.8 m: fusing twice must mark the wall
    voxels occupied and the corridor free, nothing beyond the wall."""
    d_wall = 0.8
    depth = jnp.full((cam.height_px, cam.width_px), d_wall, jnp.float32)
    g = V.empty_voxel_grid(vox)
    pose = jnp.asarray([0.0, 0.0, 0.0])
    for _ in range(2):                              # cross the thresholds
        g = V.fuse_depth(vox, cam, g, depth, pose)
    occ = np.asarray(V.to_occupancy(vox, g))        # (Z, Y, X)

    res = vox.resolution_m
    ox, oy, oz = vox.origin_m
    # The camera-height z-layer, the camera's y row.
    zi = int((cam.mount_height_m - oz) / res)
    yi = int((0.0 - oy) / res)
    # NOTE: depth is optical-axis z, so for yaw 0 the wall plane sits at
    # world x = d_wall regardless of pixel.
    xi_wall = int((d_wall - ox) / res)
    row = occ[zi, yi, :]
    assert (row[xi_wall - 1:xi_wall + 2] == 100).any(), \
        "wall band not occupied at the expected x"
    # Corridor strictly inside the carve region is free.
    xi_cam = int((0.0 - ox) / res)
    corridor = row[xi_cam + 8:xi_wall - 3]
    assert (corridor == 0).all(), "corridor not carved free"
    # Nothing beyond the wall got evidence.
    assert (occ[:, :, xi_wall + 3:] == -1).all(), "evidence beyond the wall"


# ---------------------------------------------------------------------------
# Batch fusion == sequential fusion
# ---------------------------------------------------------------------------

def test_fuse_depths_matches_sequential(vox, cam, rng):
    B = 5
    depths = rng.uniform(0.3, 1.1, (B, cam.height_px, cam.width_px)) \
        .astype(np.float32)
    poses = np.stack([rng.uniform(-0.5, 0.5, B),
                      rng.uniform(-0.5, 0.5, B),
                      rng.uniform(-3, 3, B)], axis=1).astype(np.float32)
    g_batch = V.fuse_depths(vox, cam, V.empty_voxel_grid(vox),
                            jnp.asarray(depths), jnp.asarray(poses))
    g_seq = V.empty_voxel_grid(vox)
    for b in range(B):
        g_seq = V.fuse_depth(vox, cam, g_seq, jnp.asarray(depths[b]),
                             jnp.asarray(poses[b]))
    np.testing.assert_allclose(np.asarray(g_batch), np.asarray(g_seq),
                               atol=1e-5)


def test_patch_coverage_guard(vox, cam):
    import dataclasses
    bad = dataclasses.replace(vox, patch_cells=32)   # 16-4=12 cells < range
    with pytest.raises(ValueError, match="coverage"):
        V.fuse_depth(bad, cam, V.empty_voxel_grid(bad),
                     jnp.zeros((cam.height_px, cam.width_px)),
                     jnp.zeros(3))


# ---------------------------------------------------------------------------
# Simulated depth camera geometry
# ---------------------------------------------------------------------------

def test_render_depth_flat_wall(cam):
    """World with one wall 0.9 m ahead: the centre pixel's depth is the
    wall distance; the wall plane depth is constant across the row
    (projective depth, not euclidean range)."""
    cells = 96
    res = 0.05
    world = np.zeros((cells, cells), bool)
    xi = int(0.9 / res + cells / 2)
    world[:, xi] = True                              # wall plane x ~ 0.9
    depth = np.asarray(DC.render_depth(cam, jnp.asarray(world), res, 96,
                                       jnp.asarray([0.0, 0.0, 0.0])))
    ctr = depth[cam.height_px // 2, cam.width_px // 2]
    assert ctr == pytest.approx(0.9, abs=3 * res)
    # Same row, off-centre pixel: projective depth equals the centre's.
    off = depth[cam.height_px // 2, cam.width_px // 4]
    if off > 0:                                      # still on the wall
        assert off == pytest.approx(ctr, abs=3 * res)


def test_render_depth_sees_floor(cam):
    """Empty world: lower pixels return the floor, upper pixels nothing."""
    world = np.zeros((64, 64), bool)
    depth = np.asarray(DC.render_depth(cam, jnp.asarray(world), 0.05, 128,
                                       jnp.asarray([0.0, 0.0, 0.0])))
    H = cam.height_px
    # A pixel well below centre: expected floor depth from similar
    # triangles z = h * fy / (v - cy).
    v = int(H * 0.9)
    expect = cam.mount_height_m * cam.fy / (v - cam.cy)
    if cam.range_min_m <= expect <= cam.range_max_m:
        assert depth[v, cam.width_px // 2] == pytest.approx(expect,
                                                            rel=0.15)
    # Above the horizon nothing returns.
    assert (depth[: H // 4, :] == 0.0).all()


# ---------------------------------------------------------------------------
# End-to-end: render from the sim world, fuse, compare against the world
# ---------------------------------------------------------------------------

def test_sim_to_voxel_integration(vox, cam):
    """Render depth views inside an arena and fuse: wall columns become
    occupied in the height band, interior becomes free, and the 2D
    obstacle_slice projection agrees with the world bitmap."""
    res = vox.resolution_m
    cells = 96
    world = np.asarray(W.empty_arena(cells, res))
    world_j = jnp.asarray(world)

    # Stations 0.8 m from each wall (walls sit at +-2.4 m; the euclidean
    # trust horizon is 1.2 m, so only close stations can map them) plus
    # the centre station for floor carving, each rotating in place.
    poses = []
    for xy in ((0.0, 0.0), (1.6, 0.0), (-1.6, 0.0), (0.0, 1.6),
               (0.0, -1.6)):
        for k in range(8):
            poses.append([xy[0], xy[1], k * math.pi / 4])
    poses = jnp.asarray(np.asarray(poses, np.float32))
    depths = DC.render_depths(cam, world_j, res, 96, poses,
                              wall_height_m=0.5)
    g = V.fuse_depths(vox, cam, V.empty_voxel_grid(vox), depths, poses)
    g = V.fuse_depths(vox, cam, g, depths, poses)    # cross thresholds

    occ2d = np.asarray(V.obstacle_slice(vox, g, 0.05, 0.45))
    # Where the 3D map claims an obstacle, the world must have one nearby
    # (dilate the world by 1 cell for rounding).
    wd = world.copy()
    wd[1:, :] |= world[:-1, :]
    wd[:-1, :] |= world[1:, :]
    wd[:, 1:] |= world[:, :-1]
    wd[:, :-1] |= world[:, 1:]
    ys, xs = np.nonzero(occ2d)
    # Map voxel indices to world bitmap indices (both centred, same res).
    oy = (vox.size_y_cells - cells) // 2
    ox = (vox.size_x_cells - cells) // 2
    inside = (ys >= oy) & (ys < oy + cells) & (xs >= ox) & (xs < ox + cells)
    assert inside.all(), "occupied voxels outside the world extent"
    false_pos = ~wd[ys - oy, xs - ox]
    assert false_pos.mean() < 0.05, \
        f"{false_pos.mean():.1%} of occupied columns have no world wall"
    assert len(ys) > 10, "no walls mapped at all"

    # Free space around the camera stations — asserted BELOW camera
    # height (z ~ 0.125 m), where floor-return rays carve. At exactly
    # camera height nothing carves here: the walls are beyond the
    # on-axis projective range, and no-return pixels carve nothing by
    # design (DepthCamConfig docstring) — that band stays unknown.
    # ... and the carved region is an annulus: the steepest in-range ray
    # (bottom image edge, axial depth ~0.37 m) crosses z = 0.125 m at
    # ~0.19 m out, so check the 0.25-0.45 m ring around the centre
    # station (8 yaws x 86 deg hfov covers all bearings).
    ctr_y, ctr_x = vox.size_y_cells // 2, vox.size_x_cells // 2
    zi = int(0.125 / res)
    occ3d = np.asarray(V.to_occupancy(vox, g))
    yy, xx = np.mgrid[-10:11, -10:11]
    rr = np.sqrt(yy ** 2 + xx ** 2) * res
    ring = (rr >= 0.25) & (rr <= 0.45)
    vals = occ3d[zi, ctr_y - 10:ctr_y + 11, ctr_x - 10:ctr_x + 11][ring]
    assert (vals == 0).mean() > 0.5, "floor-band ring near camera not free"

    # Height map: tops at mapped wall columns never exceed the true wall
    # height (+ the tolerance shell), and a decent share reach it
    # (oblique-only visibility maps some walls partially).
    hm = np.asarray(V.height_map(vox, g))
    wall_heights = hm[ys, xs]
    assert wall_heights.max() <= 0.5 + 3 * res
    assert (wall_heights > 0.35).mean() > 0.25


def test_occupied_voxel_centers_roundtrip(vox, cam):
    depth = jnp.full((cam.height_px, cam.width_px), 0.7, jnp.float32)
    g = V.empty_voxel_grid(vox)
    pose = jnp.asarray([0.0, 0.0, 0.0])
    for _ in range(2):
        g = V.fuse_depth(vox, cam, g, depth, pose)
    pts = V.occupied_voxel_centers(vox, g)
    assert pts.shape[1] == 3 and len(pts) > 0
    # All occupied voxels sit near the x = 0.7 wall plane.
    assert np.abs(pts[:, 0] - 0.7).max() < 3 * vox.resolution_m
