"""LD06 transports: serial (pty), TCP (reconnect), UDP — carrying the
same spec-conformant wire bytes the native parser tests use, end to end
into published LaserScans.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.ld06_node import Ld06IngestNode
from jax_mapping.bridge.ld06_transport import (
    SerialTransport, TcpTransport, UdpTransport,
)
from jax_mapping.native import ld06 as N


def _rotation_bytes(n_beams=360, r0=2.0):
    ranges = np.full(n_beams, r0, np.float32)
    return N.encode_packets(ranges)


def _collect_scans(bus, topic="scan"):
    out = []
    bus.subscribe(topic, callback=out.append)
    return out


def _drain(node, transport, deadline_s=3.0, want=1):
    t0 = time.monotonic()
    while node.n_scans_published < want and \
            time.monotonic() - t0 < deadline_s:
        node.poll()
        time.sleep(0.005)


def test_serial_transport_pty_roundtrip(tiny_cfg):
    """A pty stands in for /dev/ttyUSB0: the reference's UART path."""
    if not N.native_available():
        pytest.skip("libld06 not buildable")
    master, slave = os.openpty()
    tr = SerialTransport(os.ttyname(slave))
    bus = Bus()
    scans = _collect_scans(bus)
    node = Ld06IngestNode(tiny_cfg.scan, bus, tr, realtime=False)

    # Two rotations: the parser needs the next rotation's start to close
    # out the previous one.
    os.write(master, _rotation_bytes(tiny_cfg.scan.n_beams))
    os.write(master, _rotation_bytes(tiny_cfg.scan.n_beams))
    _drain(node, tr)
    assert node.n_scans_published >= 1
    assert scans and scans[0].ranges.shape == (tiny_cfg.scan.n_beams,)
    assert scans[0].ranges.max() == pytest.approx(2.0, abs=0.01)
    tr.close()
    os.close(master)


def test_udp_transport_datagrams(tiny_cfg):
    if not N.native_available():
        pytest.skip("libld06 not buildable")
    tr = UdpTransport(bind_host="127.0.0.1", bind_port=0)
    bus = Bus()
    scans = _collect_scans(bus)
    node = Ld06IngestNode(tiny_cfg.scan, bus, tr, realtime=False)

    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    data = _rotation_bytes(tiny_cfg.scan.n_beams) \
        + _rotation_bytes(tiny_cfg.scan.n_beams)
    # One datagram per packet, like a serial-to-ethernet bridge.
    for i in range(0, len(data), N.PACKET_BYTES):
        tx.sendto(data[i:i + N.PACKET_BYTES], ("127.0.0.1", tr.port))
    _drain(node, tr)
    assert node.n_scans_published >= 1
    assert scans[0].ranges.max() == pytest.approx(2.0, abs=0.01)
    tr.close()
    tx.close()


def test_tcp_transport_reconnects(tiny_cfg):
    """The lidar bridge boots late and reboots mid-stream: the client
    transport must dial, deliver, survive the drop, and re-deliver."""
    if not N.native_available():
        pytest.skip("libld06 not buildable")
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    tr = TcpTransport("127.0.0.1", port, reconnect_backoff_s=0.05)
    assert tr() == b""                      # server not listening yet
    srv.listen(1)

    bus = Bus()
    scans = _collect_scans(bus)
    node = Ld06IngestNode(tiny_cfg.scan, bus, tr, realtime=False)

    def serve_once():
        conn, _ = srv.accept()
        conn.sendall(_rotation_bytes(tiny_cfg.scan.n_beams))
        conn.sendall(_rotation_bytes(tiny_cfg.scan.n_beams))
        time.sleep(0.1)
        conn.close()                        # mid-stream reboot

    t = threading.Thread(target=serve_once, daemon=True)
    t.start()
    _drain(node, tr)
    assert node.n_scans_published >= 1
    n_before = node.n_scans_published

    # Second incarnation of the server: the transport re-dials.
    def serve_again():
        conn, _ = srv.accept()
        conn.sendall(_rotation_bytes(tiny_cfg.scan.n_beams, r0=3.0))
        conn.sendall(_rotation_bytes(tiny_cfg.scan.n_beams, r0=3.0))
        time.sleep(0.1)
        conn.close()

    t2 = threading.Thread(target=serve_again, daemon=True)
    t2.start()
    # Leftover round-1 bytes can complete an extra rotation BEFORE the
    # reconnect, so a bare scan count races; wait for the second
    # incarnation's distinctive 3.0 m rotation instead.
    t0 = time.monotonic()
    def got_new():
        return any(abs(float(s.ranges.max()) - 3.0) < 0.01 for s in scans)
    while not got_new() and time.monotonic() - t0 < 5.0:
        node.poll()
        time.sleep(0.005)
    assert got_new(), "no scan from the reconnected server"
    assert node.n_scans_published > n_before
    # First dial is a connect, not a REconnect (review finding): one
    # clean session + one recovery == n_connects 2, n_reconnects 1+.
    assert tr.n_connects >= 2
    assert tr.n_reconnects >= 1
    tr.close()
    srv.close()


def test_tcp_backoff_jitter_is_seeded_and_desynchronizes():
    """A fleet of clients that lost the same lidar bridge must not redial
    in lockstep: each scheduled retry is jittered in
    [backoff, backoff*(1+jitter)), seeded so chaos runs replay exactly."""
    def waits(seed, n=6):
        # Port 1 on localhost refuses instantly: every attempt fails.
        tr = TcpTransport("127.0.0.1", 1, reconnect_backoff_s=0.5,
                          max_backoff_s=4.0, jitter=0.25, seed=seed)
        out = []
        for _ in range(n):
            tr._fail_attempt()
            out.append(tr.last_backoff_s)
        tr.close()
        return out

    a, b, c = waits(0), waits(0), waits(1)
    assert a == b                            # seeded: same-seed replay
    assert a != c                            # different clients differ
    # Every wait respects the jittered-exponential envelope.
    base = 0.5
    for i, w in enumerate(a):
        lo = min(base * 2 ** i, 4.0)
        assert lo <= w < lo * 1.25 + 1e-9
    # Heartbeat-payload export carries the reconnect posture (and,
    # since the trace-frame tier, the wire protocol's framing posture).
    tr = TcpTransport("127.0.0.1", 1, seed=3)
    st = tr.stats()
    assert st == {"connected": False, "n_connects": 0,
                  "n_reconnects": 0, "backoff_s": 0.0,
                  "framing": {"mode": "unknown", "n_frames": 0,
                              "n_traced_frames": 0,
                              "n_frame_errors": 0}}
    tr._fail_attempt()
    assert tr.stats()["backoff_s"] > 0
    tr.close()


def test_ld06_node_heartbeat_carries_transport_stats(tiny_cfg):
    """The ingest node beats on /heartbeat with the transport's reconnect
    counters in the payload — the supervisor (and /status) see a
    flapping lidar bridge without shelling into the pi."""
    if not N.native_available():
        pytest.skip("libld06 not buildable")
    bus = Bus()
    beats = []
    bus.subscribe("/heartbeat", callback=beats.append)
    tr = TcpTransport("127.0.0.1", 1, reconnect_backoff_s=0.01, seed=0)
    node = Ld06IngestNode(tiny_cfg.scan, bus, tr, realtime=False)
    node.poll()
    node.poll()
    tr.close()
    assert [b.seq for b in beats] == [1, 2]
    assert beats[-1].node == "ld06_ingest"
    payload = beats[-1].payload
    assert payload["scans_published"] == 0
    assert payload["transport"]["n_reconnects"] == 0
    assert "backoff_s" in payload["transport"]


def test_transports_nonblocking_when_idle(tiny_cfg):
    """Empty reads return immediately — the poll timer must never stall."""
    tr = UdpTransport(bind_host="127.0.0.1", bind_port=0)
    t0 = time.monotonic()
    for _ in range(100):
        assert tr() == b""
    assert time.monotonic() - t0 < 0.5
    tr.close()

    master, slave = os.openpty()
    st = SerialTransport(os.ttyname(slave))
    t0 = time.monotonic()
    for _ in range(100):
        assert st() == b""
    assert time.monotonic() - t0 < 0.5
    st.close()
    os.close(master)


# --------------------------- cross-process trace frames (ISSUE 15)

def test_frame_codec_roundtrip_and_context():
    """Unit tier for the wire format: framed payloads reassemble across
    arbitrary read boundaries, contexts decode exactly, context-less
    frames clear the freshest context."""
    from jax_mapping.bridge.ld06_transport import (FrameDecoder,
                                                   encode_frame)
    from jax_mapping.obs.trace import TraceContext
    ctx = TraceContext(0x1122334455667788, 0x99AABBCCDDEEFF00, 7)
    wire = encode_frame(b"abc", ctx) + encode_frame(b"defg")
    d = FrameDecoder()
    out = b""
    for k in range(len(wire)):            # byte-at-a-time worst case
        out += d.feed(wire[k:k + 1])
    assert out == b"abcdefg"
    assert d.mode == "framed"
    assert d.n_frames == 2 and d.n_traced_frames == 1
    assert d.n_frame_errors == 0
    assert d.last_ctx is None             # frame 2 carried no context
    d2 = FrameDecoder()
    d2.feed(encode_frame(b"x", ctx))
    assert d2.last_ctx == ctx


def test_frame_decoder_garbage_header_degrades_untraced():
    """The robustness contract: a truncated/garbage frame header
    degrades to untraced raw delivery with a counter — the byte stream
    keeps flowing (the LD06 parser's own resync copes), never a
    protocol abort, and subsequent good frames parse traced again."""
    from jax_mapping.bridge.ld06_transport import (FRAME_MAGIC,
                                                   FrameDecoder,
                                                   encode_frame)
    from jax_mapping.obs.trace import TraceContext
    ctx = TraceContext(1, 2, 0)
    d = FrameDecoder()
    # Open framed, then a corrupted header (bad version), then garbage
    # bytes, then a good traced frame.
    wire = encode_frame(b"good1", ctx)
    wire += FRAME_MAGIC + bytes((99, 0)) + (5).to_bytes(4, "little")
    wire += b"JUNKJUNK"
    wire += encode_frame(b"good2", ctx)
    out = d.feed(wire)
    assert b"good1" in out and b"good2" in out
    assert d.n_frame_errors >= 1
    assert d.last_ctx == ctx              # the good tail re-traced
    assert d.mode == "framed"


def test_tcp_framed_sender_traces_ingest_publish(tiny_cfg):
    """End-to-end cross-process propagation: a framing server (the
    Pi-side acquisition process) sends rotations wrapped in trace
    frames; the receiving ingest node — on a TRACED bus — publishes
    each completed rotation under the wire context, so the publish
    span chains as a child of the REMOTE acquisition span."""
    if not N.native_available():
        pytest.skip("libld06 not buildable")
    from jax_mapping.bridge.ld06_transport import FrameEncoder
    from jax_mapping.obs import Tracer

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    sender_tracer = Tracer(seed=99)       # the remote process's seed
    enc = FrameEncoder(tracer=sender_tracer)
    tr = TcpTransport("127.0.0.1", port, reconnect_backoff_s=0.05)
    receiver_tracer = Tracer(seed=0)
    bus = Bus(tracer=receiver_tracer)
    scans = _collect_scans(bus)
    node = Ld06IngestNode(tiny_cfg.scan, bus, tr, realtime=False)

    def serve():
        conn, _ = srv.accept()
        data = _rotation_bytes(tiny_cfg.scan.n_beams) \
            + _rotation_bytes(tiny_cfg.scan.n_beams)
        # One frame per LD06 packet, like a per-packet bridge.
        for i in range(0, len(data), N.PACKET_BYTES):
            conn.sendall(enc.encode(data[i:i + N.PACKET_BYTES]))
        time.sleep(0.3)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    _drain(node, tr)
    assert node.n_scans_published >= 1
    assert node.n_traced_publishes >= 1
    assert tr.stats()["framing"]["mode"] == "framed"
    assert tr.stats()["framing"]["n_traced_frames"] > 0
    assert tr.stats()["framing"]["n_frame_errors"] == 0
    # The publish span's parent is a WIRE span id — one the sender's
    # tracer minted (it exists in the sender's ring, not ours).
    pubs = [s for s in receiver_tracer.spans_since(0)
            if s["name"] == "publish:scan"]
    assert pubs, "traced bus recorded no scan publish"
    sender_span_ids = {s["span_id"]
                       for s in sender_tracer.spans_since(0)}
    assert any(p["parent_span"] in sender_span_ids for p in pubs), \
        "no publish chained to a remote acquisition span"
    tr.close()
    srv.close()


def test_tcp_framed_sender_against_legacy_receiver(tiny_cfg):
    """Interop, PC-side-lags direction: a framing sender against a
    receiver that predates frames (`framed=False` = the old byte
    passthrough exactly). Frame headers are small inter-packet garbage
    the LD06 parser's checksum resync skips — rotations still parse,
    just untraced."""
    if not N.native_available():
        pytest.skip("libld06 not buildable")
    from jax_mapping.bridge.ld06_transport import FrameEncoder

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    enc = FrameEncoder()                  # context-less frames
    tr = TcpTransport("127.0.0.1", port, reconnect_backoff_s=0.05,
                      framed=False)
    bus = Bus()
    node = Ld06IngestNode(tiny_cfg.scan, bus, tr, realtime=False)

    def serve():
        conn, _ = srv.accept()
        data = _rotation_bytes(tiny_cfg.scan.n_beams) * 3
        for i in range(0, len(data), N.PACKET_BYTES):
            conn.sendall(enc.encode(data[i:i + N.PACKET_BYTES]))
        time.sleep(0.3)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    _drain(node, tr)
    assert node.n_scans_published >= 1
    assert node.n_traced_publishes == 0
    assert "framing" not in tr.stats()    # the pre-frames export shape
    tr.close()
    srv.close()


def test_tcp_legacy_sender_against_framed_receiver(tiny_cfg):
    """Interop, Pi-side-lags direction: a legacy raw-byte sender
    against the auto-detecting receiver — the connection negotiates to
    legacy passthrough (absent frames = legacy peer), scans parse,
    nothing counts as a frame error."""
    if not N.native_available():
        pytest.skip("libld06 not buildable")
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    tr = TcpTransport("127.0.0.1", port, reconnect_backoff_s=0.05)
    bus = Bus()
    node = Ld06IngestNode(tiny_cfg.scan, bus, tr, realtime=False)

    def serve():
        conn, _ = srv.accept()
        conn.sendall(_rotation_bytes(tiny_cfg.scan.n_beams))
        conn.sendall(_rotation_bytes(tiny_cfg.scan.n_beams))
        time.sleep(0.3)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    _drain(node, tr)
    assert node.n_scans_published >= 1
    st = tr.stats()["framing"]
    assert st["mode"] == "legacy"
    assert st["n_frames"] == 0 and st["n_frame_errors"] == 0
    assert tr.trace_context() is None
    tr.close()
    srv.close()


def test_tcp_garbage_frame_midstream_never_disconnects(tiny_cfg):
    """The degraded-delivery contract end to end: a framing session
    with a corrupted header mid-stream counts the error, keeps the
    connection, and later rotations still arrive."""
    if not N.native_available():
        pytest.skip("libld06 not buildable")
    from jax_mapping.bridge.ld06_transport import (FRAME_MAGIC,
                                                   FrameEncoder)

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    enc = FrameEncoder()
    tr = TcpTransport("127.0.0.1", port, reconnect_backoff_s=0.05)
    bus = Bus()
    node = Ld06IngestNode(tiny_cfg.scan, bus, tr, realtime=False)

    def serve():
        conn, _ = srv.accept()
        data = _rotation_bytes(tiny_cfg.scan.n_beams)
        for i in range(0, len(data), N.PACKET_BYTES):
            conn.sendall(enc.encode(data[i:i + N.PACKET_BYTES]))
        # Corrupted header: right magic, bogus version, then garbage.
        conn.sendall(FRAME_MAGIC + bytes((200, 7))
                     + (9).to_bytes(4, "little") + b"\x00" * 9)
        data = _rotation_bytes(tiny_cfg.scan.n_beams, r0=3.0) \
            + _rotation_bytes(tiny_cfg.scan.n_beams, r0=3.0)
        for i in range(0, len(data), N.PACKET_BYTES):
            conn.sendall(enc.encode(data[i:i + N.PACKET_BYTES]))
        time.sleep(0.3)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    t0 = time.monotonic()
    scans = _collect_scans(bus)

    def got_new():
        return any(abs(float(s.ranges.max()) - 3.0) < 0.01
                   for s in scans)
    while not got_new() and time.monotonic() - t0 < 5.0:
        node.poll()
        time.sleep(0.005)
    assert got_new(), "post-garbage rotations never arrived"
    st = tr.stats()["framing"]
    assert st["n_frame_errors"] >= 1
    assert tr.n_reconnects == 0           # degraded, never disconnected
    tr.close()
    srv.close()
