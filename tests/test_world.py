"""Bounded-memory world store tests (ISSUE 18 tentpole).

The load-bearing assertions:

* ORACLE BIT-IDENTITY — a windowed mission's live window content is
  bit-identical (float-for-float) to an oracle big-grid run of the
  same scans, through shifts, host/disk eviction, re-entry and decay
  catch-up (the store-level direct-drive gate).
* DEGRADE, NEVER DIE — a corrupt spill degrades its tile to unknown
  with a flight event; refused admissions re-enter as unknown; no
  world-store path ever raises into the mapper tick.
* DETERMINISM — two same-seed drives produce bit-identical
  eviction/spill/rehydrate schedules (the FaultPlan doctrine extended
  to memory traffic).
* EVICT-VS-SERVE RACE GATE — the tick thread shifting/evicting under
  RaceWatch against serving composition and /status reads converges
  with zero reports on the declared locks.
* KNOB-OFF — `WorldConfig.windowed=False` builds no store and is
  bit-exact regardless of the window knobs.
"""

import dataclasses
import functools
import threading

import numpy as np
import pytest

from jax_mapping.config import WorldConfig, tiny_config
from jax_mapping.world.store import WorldStore, window_slam_config


# ------------------------------------------------------------------ helpers

def _wcfg(base=None, **world_kw):
    """Windowed config on the verified tiny geometry: 768-cell logical
    lattice (12 serving tiles), a 4-tile (256-cell — the tiny device
    shape, so jits reuse the suite's compile cache) window, 1-tile
    margin band (recentre triggers at |x| > 3.2 m)."""
    cfg = base if base is not None else tiny_config()
    kw = dict(windowed=True, window_tiles=4, margin_tiles=1,
              host_tile_budget=64)
    kw.update(world_kw)
    return cfg.replace(
        grid=dataclasses.replace(cfg.grid, size_cells=768),
        world=WorldConfig(**kw))


def _ranges(cfg, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(0.5, 2.5,
                       cfg.scan.padded_beams).astype(np.float32)


@functools.lru_cache(maxsize=None)
def _oracle_jit():
    """The oracle big-grid fusion: the exact clip-add formula the
    store's `fuse_patch_global` applies, evaluated on the full logical
    grid — what a windowed run's live region must match bit-for-bit."""
    import jax
    import jax.numpy as jnp
    from jax_mapping.ops import grid as G

    @functools.partial(jax.jit, static_argnums=(0, 1))
    def fuse(grid_cfg, scan_cfg, big, ranges, pose, origin):
        delta = G.classify_patch(grid_cfg, scan_cfg, ranges, pose,
                                 origin)
        p = grid_cfg.patch_cells
        cur = jax.lax.dynamic_slice(big, (origin[0], origin[1]), (p, p))
        new = jnp.clip(cur + delta, grid_cfg.logodds_min,
                       grid_cfg.logodds_max)
        return jax.lax.dynamic_update_slice(big, new,
                                            (origin[0], origin[1]))

    return fuse


def _oracle_fuse(cfg, big, ranges, pose_world):
    import jax.numpy as jnp
    from jax_mapping.ops import grid as G
    pose = jnp.asarray(pose_world, jnp.float32)
    origin = G.patch_origin(cfg.grid, pose[:2])
    return _oracle_jit()(cfg.grid, cfg.scan, big, jnp.asarray(ranges),
                         pose,
                         jnp.asarray(np.asarray(origin), jnp.int32))


def _window_region(store, big):
    """The oracle's cells under the store's current window."""
    t = store.tile_cells
    r0, c0 = store.origin_tile
    w = store.window_cells
    return np.asarray(big)[r0 * t:r0 * t + w, c0 * t:c0 * t + w]


def _drive(cfg, xs, spill_dir=None, decay_at=(), pressure_at=(),
           check_each=True):
    """Walk one robot along y=0 fusing a scan per pose, shifting the
    window exactly as the mapper does (poll, recentre, fuse), with the
    oracle big grid running alongside. Returns (store, window, big)."""
    from jax_mapping.ops import grid as G
    store = WorldStore(cfg, spill_dir=spill_dir)
    win = G.empty_grid(store.cfg.grid)
    big = G.empty_grid(cfg.grid)
    ranges = _ranges(cfg)
    for i, x in enumerate(xs):
        pose_w = np.array([x, 0.0, 0.0], np.float32)
        win, _ = store.poll_prefetch(win)
        off = store.offset_xy()
        dr, dc = store.desired_shift(
            [pose_w - np.array([off[0], off[1], 0.0], np.float32)])
        if (dr, dc) != (0, 0):
            win = store.shift(win, dr, dc)
            # Join disk rehydrations NOW (determinism over latency) so
            # the next fuse never writes into a tile a pending scatter
            # would overwrite — the mapper pays the one-tick degrade
            # instead; that path has its own test below.
            win, _ = store.poll_prefetch(win)
        win = store.fuse_scan_global(win, ranges, pose_w)
        big = _oracle_fuse(cfg, big, ranges, pose_w)
        if i in decay_at:
            d = cfg.decay
            win = G.decay_grid(win, d.factor, d.evidence_cap)
            store.note_decay_pass()
            big = G.decay_grid(big, d.factor, d.evidence_cap)
        if i in pressure_at:
            store.hold_pressure(f"drive@{i}", 0.5)
        if check_each:
            np.testing.assert_array_equal(
                np.asarray(win), _window_region(store, big),
                err_msg=f"window diverged from oracle at step {i}")
    return store, win, big


#: The east-and-back corridor walk: two eastward recentres (evicting
#: the content the robot mapped near the origin), then the return leg
#: rehydrates it.  Margin trigger is |window x| > 3.2 m.
_WALK = [0.0, 1.6, 3.3, 6.6, 9.9, 6.6, 3.3, 0.0]


# --------------------------------------------------- config derivation

def test_window_slam_config_geometry_validation(tiny_cfg):
    cfg = _wcfg(tiny_cfg)
    out = window_slam_config(cfg)
    # ONLY grid.size_cells shrinks, to the window edge.
    assert out.grid.size_cells == 4 * 64
    assert out.grid.patch_cells == cfg.grid.patch_cells
    assert out.scan == cfg.scan
    assert out.serving == cfg.serving

    bad = cfg.replace(grid=dataclasses.replace(cfg.grid,
                                               size_cells=800))
    with pytest.raises(ValueError, match="not divisible"):
        window_slam_config(bad)
    with pytest.raises(ValueError, match="exceeds the logical"):
        window_slam_config(_wcfg(tiny_cfg, window_tiles=16))
    with pytest.raises(ValueError, match="must be even"):
        window_slam_config(_wcfg(tiny_cfg, window_tiles=3))
    with pytest.raises(ValueError, match="no interior"):
        window_slam_config(_wcfg(tiny_cfg, margin_tiles=2))


def test_offset_starts_at_exact_zero_and_advances_by_tiles(tiny_cfg):
    store = WorldStore(_wcfg(tiny_cfg))
    assert store.origin_tile == (4, 4)
    np.testing.assert_array_equal(store.offset_xy(),
                                  np.zeros(2, np.float32))
    from jax_mapping.ops import grid as G
    win = G.empty_grid(store.cfg.grid)
    win = store.shift(win, 1, 2)
    assert store.origin_tile == (5, 6)
    # offset = (dc, dr) tiles * 64 cells * 0.05 m; x is columns.
    np.testing.assert_allclose(store.offset_xy(), [6.4, 3.2])
    np.testing.assert_array_equal(store.shift_delta_m(1, 2),
                                  store.offset_xy())
    assert store.n_shifts == 1


# ------------------------------------------- oracle bit-identity gates

def test_host_eviction_roundtrip_bit_identical_to_oracle(tiny_cfg):
    """East-and-back with a roomy host budget: every fuse along the
    way — through two evicting shifts, a mid-mission decay pass and
    the host rehydration on the return leg — leaves the live window
    bit-identical to the oracle big grid (decay catch-up included:
    evicted tiles missed the device pass and replay it lazily)."""
    cfg = _wcfg(tiny_cfg)
    store, win, big = _drive(cfg, _WALK, decay_at=(4,))
    assert store.n_shifts >= 3
    assert store.n_evictions > 0
    assert store.n_rehydrated_host > 0
    assert store.n_lost == 0 and store.n_corrupt_spills == 0
    assert store.decay_epoch == 1
    # Return to the anchor: the offset is EXACTLY zero again.
    np.testing.assert_array_equal(store.offset_xy(),
                                  np.zeros(2, np.float32))


def test_disk_spill_roundtrip_bit_identical_to_oracle(tiny_cfg,
                                                      tmp_path):
    """A one-tile host budget pushes evicted content to disk
    (retention_coarsen=1 keeps the spill lossless at every rung); the
    return leg rehydrates through the prefetch path and still matches
    the oracle float-for-float."""
    cfg = _wcfg(tiny_cfg, host_tile_budget=1, retention_coarsen=1)
    store, win, big = _drive(cfg, _WALK, spill_dir=str(tmp_path))
    assert store.n_rehydrated_disk > 0
    assert store.governor.n_spills > 0
    assert store.n_corrupt_spills == 0
    assert store.spill.n_corrupt_reads == 0


def test_disk_rehydration_is_one_tick_unknown_degrade(tiny_cfg,
                                                      tmp_path):
    """Disk hits do NOT scatter at shift time: the tile reads unknown
    until the next poll joins the prefetch (deterministic one-tick
    degrade regardless of IO timing)."""
    from jax_mapping.ops import grid as G
    cfg = _wcfg(tiny_cfg, host_tile_budget=1, retention_coarsen=1)
    store = WorldStore(cfg, spill_dir=str(tmp_path))
    win = G.empty_grid(store.cfg.grid)
    big = G.empty_grid(cfg.grid)
    ranges = _ranges(cfg)
    pose = np.zeros(3, np.float32)
    win = store.fuse_scan_global(win, ranges, pose)
    big = _oracle_fuse(cfg, big, ranges, pose)
    win = store.shift(win, 0, 4)           # whole window leaves
    assert store.host_tiles() == 1         # budget: newest stays warm
    spilled = store.spill.tiles()
    assert len(spilled) == 3
    win = store.shift(win, 0, -4)          # ...and comes back
    st = store.status()
    assert st["pending_prefetch"] > 0
    # Host tile scattered NOW; disk tiles still unknown this tick.
    t = store.tile_cells
    w = np.asarray(win)
    oracle = _window_region(store, big)
    for (r, c) in spilled:
        sr, sc = r - store.origin_tile[0], c - store.origin_tile[1]
        assert not w[sr * t:(sr + 1) * t, sc * t:(sc + 1) * t].any()
    win, n = store.poll_prefetch(win)
    assert n == len(spilled)
    np.testing.assert_array_equal(np.asarray(win), oracle)
    assert store.status()["pending_prefetch"] == 0
    assert store.n_rehydrated_disk == n


# ----------------------------------------------- integrity + degrade

def test_corrupt_spill_degrades_to_unknown_with_flight_event(
        tiny_cfg, tmp_path):
    """The `spill_corrupt` contract: a rotted spilled tile re-enters
    as unknown with a `world_spill_corrupt` flight event — counters
    move, the away marker clears, and nothing raises."""
    from jax_mapping.obs.recorder import flight_recorder
    from jax_mapping.ops import grid as G
    cfg = _wcfg(tiny_cfg, host_tile_budget=1, retention_coarsen=1)
    store = WorldStore(cfg, spill_dir=str(tmp_path))
    win = G.empty_grid(store.cfg.grid)
    big = G.empty_grid(cfg.grid)
    ranges = _ranges(cfg)
    pose = np.zeros(3, np.float32)
    win = store.fuse_scan_global(win, ranges, pose)
    big = _oracle_fuse(cfg, big, ranges, pose)
    win = store.shift(win, 0, 4)
    hit = store.corrupt_spill(1)
    assert len(hit) == 1
    mark = flight_recorder.mark()
    win = store.shift(win, 0, -4)
    win, n_ok = store.poll_prefetch(win)   # never raises
    assert store.n_corrupt_spills == 1
    assert store.n_lost >= 1
    evs = [e for e in flight_recorder.events_since(mark)
           if e["kind"] == "world_spill_corrupt"]
    assert len(evs) == 1 and tuple(evs[0]["tile"]) == hit[0]
    # The rotted tile is resident-as-unknown: away marker cleared,
    # content zero; every OTHER tile matches the oracle.
    st = store.status()
    assert st["away_tiles"] == 0
    t = store.tile_cells
    w = np.asarray(win)
    oracle = _window_region(store, big).copy()
    r, c = hit[0]
    sr, sc = r - store.origin_tile[0], c - store.origin_tile[1]
    assert not w[sr * t:(sr + 1) * t, sc * t:(sc + 1) * t].any()
    oracle[sr * t:(sr + 1) * t, sc * t:(sc + 1) * t] = 0.0
    np.testing.assert_array_equal(w, oracle)


def test_spillstore_torn_tail_truncates_newest_gen_wins(tmp_path):
    from jax_mapping.world.spill import SpillStore
    s = SpillStore(str(tmp_path))
    a1 = np.full((8, 8), 1.0, np.float32)
    a2 = np.full((8, 8), 2.0, np.float32)
    b = np.full((8, 8), 3.0, np.float32)
    s.put((1, 2), 1, a1, 0)
    s.put((1, 2), 2, a2, 0)                # newest generation wins
    s.put((3, 4), 1, b, 0)
    np.testing.assert_array_equal(s.get((1, 2)).data, a2)
    assert s.get((9, 9)) is None           # miss, not an exception
    size_before = s.nbytes()
    s.close()

    # A torn append (length prefix promising more bytes than exist)
    # must truncate to the last good record on reopen, never fail.
    with open(s.path, "ab") as f:
        f.write(b"\x40\x00\x00\x00partial")
    s2 = SpillStore(str(tmp_path))
    assert s2.n_truncated_bytes > 0
    np.testing.assert_array_equal(s2.get((1, 2)).data, a2)
    np.testing.assert_array_equal(s2.get((3, 4)).data, b)

    # Compaction drops the superseded (1,2) gen-1 record.
    s2.compact()
    assert s2.nbytes() < size_before
    np.testing.assert_array_equal(s2.get((1, 2)).data, a2)

    # corrupt_tiles flips INSIDE the tile bytes and re-stamps the
    # frame CRC: only the inner CRC catches it, at read time.
    assert s2.corrupt_tiles(1) == [(1, 2)]
    assert s2.get((1, 2)) is None
    assert s2.n_corrupt_reads == 1
    np.testing.assert_array_equal(s2.get((3, 4)).data, b)
    s2.close()


# -------------------------------------------------- governor ladder

def test_governor_watermark_ladder_and_worst_of_holds():
    from jax_mapping.world.governor import MemoryGovernor
    gov = MemoryGovernor(WorldConfig(host_tile_budget=100))
    assert gov.observe(50) == 0
    assert gov.observe(80) == 1            # >= 0.75 high watermark
    assert gov.observe(93) == 2            # >= 0.92 critical
    assert gov.observe(100) == 3           # at budget: refuse
    assert gov.observe(10) == 0
    assert gov.n_rung_changes == 4

    gov.hold_pressure("a", 0.5)
    assert gov.effective_budget() == 50
    gov.hold_pressure("b", 0.75)           # worst-of composes
    assert gov.effective_budget() == 25
    assert gov.pressure() == 0.75
    gov.release_pressure("b")
    assert gov.effective_budget() == 50    # a's window still holds
    gov.release_pressure("a")
    assert gov.effective_budget() == 100
    st = gov.status()
    assert st["rung_name"] == "normal" and st["pressure_holds"] == 0
    assert st["effective_budget_tiles"] == 100


def test_refused_admission_reenters_as_unknown(tiny_cfg):
    """Rung 3 with no disk tier: eviction drops the tile (flight
    event, counters), and re-entry clears the away marker — the tile
    is resident again AS UNKNOWN, never as stale walls."""
    from jax_mapping.obs.recorder import flight_recorder
    from jax_mapping.ops import grid as G
    cfg = _wcfg(tiny_cfg, host_tile_budget=1)
    store = WorldStore(cfg)
    win = G.empty_grid(store.cfg.grid)
    win = store.fuse_scan_global(win, _ranges(cfg),
                                 np.zeros(3, np.float32))
    mark = flight_recorder.mark()
    win = store.shift(win, 0, 4)
    assert store.governor.n_refused > 0
    assert store.n_lost == store.governor.n_refused
    assert store.host_tiles() == 0
    evs = [e for e in flight_recorder.events_since(mark)
           if e["kind"] == "world_admission_refused"]
    assert len(evs) == store.governor.n_refused
    st = store.status()
    assert st["away_tiles"] > 0
    epoch = store.eviction_epoch

    win = store.shift(win, 0, -4)
    assert store.status()["away_tiles"] == 0   # reenter_unknown
    assert store.eviction_epoch > epoch
    assert not np.asarray(win).any()
    assert any(ev[0] == "reenter_unknown" for ev in store.schedule)


def test_pressure_hold_sheds_immediately_drop_without_spill(tiny_cfg):
    from jax_mapping.ops import grid as G
    cfg = _wcfg(tiny_cfg, host_tile_budget=4)
    store = WorldStore(cfg)
    win = G.empty_grid(store.cfg.grid)
    win = store.fuse_scan_global(win, _ranges(cfg),
                                 np.zeros(3, np.float32))
    win = store.shift(win, 0, 4)
    n_host = store.host_tiles()
    assert n_host >= 2                     # content survived eviction
    lost_before = store.n_lost
    store.hold_pressure("chaos@1", 0.7)    # effective budget -> 1
    assert store.host_tiles() == 1
    assert store.governor.n_drops == n_host - 1
    assert store.n_lost - lost_before == n_host - 1
    store.release_pressure("chaos@1")
    assert store.governor.effective_budget() == 4
    assert any(ev[0] == "pressure" for ev in store.schedule)
    assert any(ev[0] == "pressure_clear" for ev in store.schedule)


def test_rung2_coarsens_spilled_retention(tiny_cfg, tmp_path):
    """Above the critical watermark the spill coarsens by
    `retention_coarsen` (lossy, bounded); rehydrate upsamples back to
    the tile lattice — content survives approximately, shape exactly."""
    from jax_mapping.ops import grid as G
    cfg = _wcfg(tiny_cfg, host_tile_budget=1)   # default coarsen=2
    store = WorldStore(cfg, spill_dir=str(tmp_path))
    win = G.empty_grid(store.cfg.grid)
    win = store.fuse_scan_global(win, _ranges(cfg),
                                 np.zeros(3, np.float32))
    win = store.shift(win, 0, 4)
    assert store.governor.n_coarsened > 0
    win = store.shift(win, 0, -4)
    win, n = store.poll_prefetch(win)
    assert n > 0 and store.n_rehydrated_disk == n
    assert np.asarray(win).any()           # coarse content came back
    assert np.asarray(win).shape == (256, 256)


# --------------------------------------------------- determinism gate

def test_same_seed_drives_produce_bit_identical_schedules(tiny_cfg,
                                                          tmp_path):
    cfg = _wcfg(tiny_cfg, host_tile_budget=1, retention_coarsen=1)
    a, win_a, _ = _drive(cfg, _WALK, spill_dir=str(tmp_path / "a"),
                         decay_at=(4,), pressure_at=(3,),
                         check_each=False)
    b, win_b, _ = _drive(cfg, _WALK, spill_dir=str(tmp_path / "b"),
                         decay_at=(4,), pressure_at=(3,),
                         check_each=False)
    assert a.schedule == b.schedule
    assert a.n_schedule_events == b.n_schedule_events
    assert a.origin_tile == b.origin_tile
    assert a.status()["evictions"] == b.status()["evictions"]
    np.testing.assert_array_equal(np.asarray(win_a), np.asarray(win_b))
    # The schedule saw every transition class this drive exercises.
    kinds = {ev[0] for ev in a.schedule}
    assert {"shift", "evict", "spill", "prefetch", "rehydrate",
            "pressure"} <= kinds


# ----------------------------------------------- serving composition

def test_compose_serving_masks_away_tiles(tiny_cfg):
    from jax_mapping.ops import grid as G
    cfg = _wcfg(tiny_cfg, host_tile_budget=64)
    store = WorldStore(cfg)
    win = G.empty_grid(store.cfg.grid)
    win = store.fuse_scan_global(win, _ranges(cfg),
                                 np.zeros(3, np.float32))
    win = store.shift(win, 0, 4)
    gray = np.full((store.window_cells, store.window_cells), 200,
                   np.uint8)
    mosaic, mask = store.compose_serving(gray)
    assert mosaic.shape == (768, 768) and mask.shape == (12, 12)
    r0, c0 = store.origin_tile
    t = store.tile_cells
    w = store.window_cells
    assert (mosaic[r0 * t:r0 * t + w, c0 * t:c0 * t + w] == 200).all()
    outside = mosaic.copy()
    outside[r0 * t:r0 * t + w, c0 * t:c0 * t + w] = 127
    assert (outside == 127).all()
    away = {tuple(t_) for t_ in np.argwhere(mask)}
    assert away and away == store._away


# ------------------------------------------------ checkpoint payloads

def test_checkpoint_payload_roundtrip_embedded_host(tiny_cfg):
    from jax_mapping.ops import grid as G
    cfg = _wcfg(tiny_cfg)
    store, win, big = _drive(cfg, [0.0, 1.6, 3.3], check_each=False)
    payload = store.checkpoint_payload()
    assert "host_meta" in payload and "host_tiles" in payload

    fresh = WorldStore(cfg)
    fresh.restore_payload(payload)
    assert fresh.origin_tile == store.origin_tile
    assert fresh._away == store._away
    assert fresh.decay_epoch == store.decay_epoch
    assert fresh.eviction_epoch == store.eviction_epoch
    # Walking back onto the evicted region restores the content the
    # payload carried, bit-exact vs the oracle.
    win2 = G.empty_grid(fresh.cfg.grid)
    win2 = fresh.shift(win2, 0, 4 - fresh.origin_tile[1])
    evicted_cols = np.asarray(win2)[:, :2 * 64]
    np.testing.assert_array_equal(
        evicted_cols, _window_region(fresh, big)[:, :2 * 64])
    assert fresh.n_rehydrated_host > 0


def test_checkpoint_payload_spill_backed_flushes_host(tiny_cfg,
                                                      tmp_path):
    from jax_mapping.ops import grid as G
    cfg = _wcfg(tiny_cfg, host_tile_budget=1, retention_coarsen=1)
    store, win, big = _drive(cfg, [0.0, 1.6, 3.3],
                             spill_dir=str(tmp_path),
                             check_each=False)
    payload = store.checkpoint_payload()
    # With a disk tier the host flushes: the spill file IS the
    # manifest, the sidecar carries only the re-anchor arrays.
    assert "host_meta" not in payload
    assert store.host_tiles() == 0
    store.close()

    fresh = WorldStore(cfg, spill_dir=str(tmp_path))
    fresh.restore_payload(payload)
    assert fresh.origin_tile == store.origin_tile
    win2 = G.empty_grid(fresh.cfg.grid)
    win2 = fresh.shift(win2, 0, 4 - fresh.origin_tile[1])
    win2, n = fresh.poll_prefetch(win2)
    assert n > 0
    evicted_cols = np.asarray(win2)[:, :2 * 64]
    np.testing.assert_array_equal(
        evicted_cols, _window_region(fresh, big)[:, :2 * 64])
    fresh.close()


# ----------------------------------------------- racewatch gate (CI)

def test_racewatch_gate_evict_vs_serve(tiny_cfg):
    """ISSUE 18 CI satellite: one tick-thread shifting/evicting/
    rehydrating (+ pressure holds) against serving composition,
    /status reads and checkpoint snapshots from concurrent threads —
    RaceWatch must converge every declared field on the declared lock
    with ZERO reports."""
    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch
    from jax_mapping.ops import grid as G

    cfg = _wcfg(tiny_cfg, host_tile_budget=64)
    store = WorldStore(cfg)
    win = G.empty_grid(store.cfg.grid)
    win = store.fuse_scan_global(win, _ranges(cfg),
                                 np.zeros(3, np.float32))
    errs = []
    watch = RaceWatch()
    try:
        watch.watch_object(store, groups_by_class()["WorldStore"][0],
                           name="world")
        watch.watch_object(store.governor,
                           groups_by_class()["MemoryGovernor"][0],
                           name="gov")

        def tick(g=win):
            try:
                for _ in range(25):
                    g = store.shift(g, 0, 2)
                    store.note_decay_pass()
                    store.hold_pressure("gate", 0.3)
                    g = store.shift(g, 0, -2)
                    g, _ = store.poll_prefetch(g)
                    store.release_pressure("gate")
            except Exception as e:            # noqa: BLE001
                errs.append(e)

        def serve():
            gray = np.full((store.window_cells, store.window_cells),
                           127, np.uint8)
            try:
                for _ in range(120):
                    store.compose_serving(gray)
                    store.status()
                    store.host_tiles()
                    store.checkpoint_payload()
            except Exception as e:            # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=tick)] + \
            [threading.Thread(target=serve) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        watch.unwatch_all()
    assert errs == []
    assert watch.reports() == [], \
        "\n".join(r.message for r in watch.reports())
    # `_gen` is the cross-thread written field (evictions stamp it on
    # the tick thread; checkpoint snapshots read it from the serve
    # threads) — its candidate lockset must converge on the store lock.
    gen = watch.field_states()["WorldStore._gen@world"]
    assert gen.state == "shared-modified"
    assert "WorldStore._lock@world" in gen.candidate


# ------------------------------------------------ mapper integration

def _scan(stamp, cfg, ranges=None):
    from jax_mapping.bridge.messages import Header, LaserScan
    n = cfg.scan.n_beams
    r = np.zeros(n, np.float32) if ranges is None else ranges
    return LaserScan(header=Header(stamp=stamp, frame_id="base_laser"),
                     angle_increment=cfg.scan.angle_increment_rad,
                     ranges=r)


def _odom(stamp, x, y, theta):
    from jax_mapping.bridge.messages import (Header, Odometry, Pose2D,
                                             Twist)
    return Odometry(header=Header(stamp=stamp, frame_id="odom"),
                    pose=Pose2D(x, y, theta),
                    twist=Twist(linear_x=0.0, angular_z=0.0))


def test_windowed_mapper_shift_translates_pose_leaves(tiny_cfg):
    """Bridge integration: the mapper runs window-frame machinery, and
    a margin-band crossing shifts the window + translates every
    pose-like leaf so `window pose + offset == world pose` holds
    through the shift (zero-range scans = pure odometric propagation,
    so the odometry IS the world-frame truth)."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.obs.recorder import flight_recorder

    cfg = _wcfg(tiny_cfg)
    bus = Bus()
    mapper = MapperNode(cfg, bus, n_robots=1)
    try:
        assert mapper.world is not None
        assert mapper.cfg.grid.size_cells == 256     # window config
        assert mapper.full_cfg.grid.size_cells == 768
        scan_pub = bus.publisher("scan")
        odom_pub = bus.publisher("odom")
        mark = flight_recorder.mark()
        t = 0.0
        for x in [0.0, 0.8, 1.6, 2.4, 3.2, 4.0, 4.8]:
            t += 0.5
            odom_pub.publish(_odom(t, x, 0.0, 0.0))
            scan_pub.publish(_scan(t, cfg))
            mapper.tick()
        assert mapper.world.n_shifts >= 1
        ws = mapper.world_status()
        assert ws["windowed"] and ws["origin_tile"] != [4, 4]
        off = mapper.world.offset_xy()
        assert float(off[0]) > 0.0 and float(off[1]) == 0.0
        pose = np.asarray(mapper.states[0].pose)
        assert pose[0] + off[0] == pytest.approx(4.8, abs=1e-3)
        assert abs(pose[0]) < 6.4            # pose stays in-window
        evs = [e for e in flight_recorder.events_since(mark)
               if e["kind"] == "window_shift"]
        assert evs and evs[0]["dr"] == 0 and evs[0]["dc"] > 0
        assert ws["offset_m"] == [float(off[0]), float(off[1])]
    finally:
        mapper.destroy()


def test_windowed_off_builds_no_store_and_is_knob_inert(tiny_cfg):
    """The knob-off doctrine: `windowed=False` builds no store, and
    the OTHER world knobs are bit-inert — two mappers with different
    window parameters produce identical grids for identical input."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode

    grids = []
    for knobs in (WorldConfig(),
                  WorldConfig(windowed=False, window_tiles=6,
                              margin_tiles=2, host_tile_budget=7)):
        cfg = tiny_cfg.replace(world=knobs)
        bus = Bus()
        mapper = MapperNode(cfg, bus, n_robots=1)
        assert mapper.world is None
        assert mapper.world_status() is None
        assert mapper.cfg.grid.size_cells == tiny_cfg.grid.size_cells
        scan_pub = bus.publisher("scan")
        odom_pub = bus.publisher("odom")
        ranges = _ranges(cfg)[:cfg.scan.n_beams]
        for i, x in enumerate([0.0, 0.3, 0.6]):
            st = 0.5 * (i + 1)
            odom_pub.publish(_odom(st, x, 0.0, 0.0))
            scan_pub.publish(_scan(st, cfg, ranges=ranges))
            mapper.tick()
        grids.append(np.asarray(mapper.shared_grid))
        mapper.destroy()
    np.testing.assert_array_equal(grids[0], grids[1])


def test_windowed_serving_and_http_surface(tiny_cfg):
    """End-to-end on a real windowed mapper: `/tiles` serves typed
    evicted markers the DeltaMapClient prunes on, the ETag grows a
    `-w{epoch}` suffix across an eviction flip, `/status` carries the
    world section, and `/metrics` exports the jax_mapping_world_*
    families."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.http_api import MapApiServer
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.serving.client import DeltaMapClient
    import json

    cfg = _wcfg(tiny_cfg)
    bus = Bus()
    mapper = MapperNode(cfg, bus, n_robots=1)
    api = MapApiServer(bus, mapper=mapper, port=0)
    try:
        store = api.serving.map_store
        scan_pub = bus.publisher("scan")
        odom_pub = bus.publisher("odom")
        ranges = _ranges(cfg)[:cfg.scan.n_beams]

        # Map some content around the origin, serve the snapshot.
        for i, x in enumerate([0.0, 0.2]):
            st = 0.5 * (i + 1)
            odom_pub.publish(_odom(st, x, 0.0, 0.0))
            scan_pub.publish(_scan(st, cfg, ranges=ranges))
            mapper.tick()
        store.refresh()
        rev0, entries0, meta0 = store.tiles_since(-1)
        assert meta0["size_cells"] == 768    # LOGICAL manifest
        assert not any(e.get("evicted") for e in entries0)
        client = DeltaMapClient("http://unused")
        client.apply({"revision": rev0, "since": -1, "tiles": entries0,
                      "tile_cells": 64, "levels": meta0["levels"]})
        assert client.image().shape == (768, 768)
        known0 = int((client.image() != 127).sum())
        assert known0 > 0
        res = api.handle("/tiles?since=-1")
        assert res[0] == 200
        etag0 = res[3]["ETag"]
        assert "-w" not in etag0             # nothing evicted yet

        # Walk east past the margin: the shift evicts mapped tiles.
        t = 1.0
        for x in [1.6, 2.4, 3.2, 4.0, 4.8]:
            t += 0.5
            odom_pub.publish(_odom(t, x, 0.0, 0.0))
            scan_pub.publish(_scan(t, cfg))
            mapper.tick()
        assert mapper.world.n_shifts >= 1
        assert mapper.world.status()["away_tiles"] > 0
        store.refresh()
        rev1, entries1, meta1 = store.tiles_since(rev0)
        markers = [e for e in entries1 if e.get("evicted")]
        assert markers and meta1["evicted_tiles"] > 0
        assert all("png" not in e for e in markers)
        before = client.n_tiles_pruned
        client.apply({"revision": rev1, "since": rev0,
                      "tiles": entries1, "tile_cells": 64,
                      "levels": meta1["levels"]})
        assert client.n_tiles_pruned == before + len(markers)
        for e in markers:
            ty, tx = e["ty"], e["tx"]
            region = client.image()[ty * 64:(ty + 1) * 64,
                                    tx * 64:(tx + 1) * 64]
            assert (region == 127).all()
        assert store.stats()["n_tiles_evicted"] > 0
        assert store.stats()["evicted_epoch"] > 0
        res1 = api.handle("/tiles?since=-1")
        etag1 = res1[3]["ETag"]
        assert f"-w{store.evicted_epoch}" in etag1
        assert etag1 != etag0

        # /status.world + /metrics world families.
        body = json.loads(api.handle("/status")[2])
        assert body["world"]["windowed"] is True
        assert body["world"]["shifts"] >= 1
        text = api.handle("/metrics")[2].decode()
        for fam in ("jax_mapping_world_shifts_total",
                    "jax_mapping_world_evictions_total",
                    "jax_mapping_world_device_window_bytes",
                    "jax_mapping_world_governor_rung",
                    "jax_mapping_world_away_tiles"):
            assert f"# TYPE {fam} " in text
    finally:
        api.shutdown()
        mapper.destroy()


# ------------------------------------------------- the lifelong gate

@pytest.mark.slow
def test_bounded_memory_corridor_soak(tmp_path):
    """ISSUE 18 acceptance: a robot walks a corridor far beyond the
    window — peak device grid bytes stay constant while traveled
    distance grows, the window recentres in BOTH directions (out and
    back: eviction, disk spill, re-entry), the memory chaos kinds
    fire mid-mission (`spill_corrupt` rotting REAL spilled tiles),
    occupancy sign-agreement vs sim ground truth holds in the final
    live window, and two same-seed missions are bit-identical
    INCLUDING the eviction/spill series.

    Oracle note: bit-identity of the live window vs a big-grid oracle
    is asserted at the STORE level by the fast tests above (a
    windowed=False twin MISSION is not a trajectory oracle — the
    planner sees a different map extent and drives a different path).

    The trajectory is a SCRIPTED goal patrol (out +x, back past the
    spawn to −x, out +x again), not free frontier exploration: on
    this symmetric corridor the frontier auction's two directions
    score within float noise of each other, so the pick — frozen
    per process by XLA CPU codegen — is the one mission input
    same-seed determinism cannot pin ACROSS processes. Manual goals
    override frontier assignment in the brain, pinning the path to
    the step clock while still exercising the full sim/SLAM/window
    path. Chaos is timed to the patrol: pressure squeezes the host
    tier while the return leg's shifts evict the outbound columns,
    the rot fires while the spill holds those tiles, and the third
    leg drives BACK INTO them — the rehydrate hits the bad CRC,
    degrades to unknown with a `world_spill_corrupt` flight event,
    and the mission keeps driving."""
    from jax_mapping.obs.recorder import flight_recorder
    from jax_mapping.resilience.faultplan import FaultEvent
    from jax_mapping.scenarios.lifelong import run_lifelong_mission
    from jax_mapping.sim import world as W

    base = tiny_config()
    cfg = base.replace(
        grid=dataclasses.replace(base.grid, size_cells=768),
        # 32-cell serving tiles: same 256-cell window (8 tiles — the
        # suite's compile cache reuses the jits) but a 3-tile margin
        # band, so recentring triggers after only 1.6 m of travel.
        serving=dataclasses.replace(base.serving, tile_cells=32),
        # Odometry-driven tracking: the corridor's aperture problem
        # makes scan matching slide along the axis, so gate it off.
        matcher=dataclasses.replace(base.matcher, min_travel_m=1e9),
        # A 10x-calibration robot (0.3 m/s cruise): sim AND odometry
        # share the coefficient, so SLAM stays consistent — the stock
        # 3 cm/s Thymio would need thousands of steps to leave the
        # window. The lidar shield scales with the speed.
        robot=dataclasses.replace(base.robot,
                                  speed_coeff_m_per_unit_s=0.003027,
                                  speed_noise_frac=0.0,
                                  lidar_warn_dist_m=0.5,
                                  lidar_stop_dist_m=0.8),
        # Estimator-watchdog guardrails off: with the matcher gated
        # (no relocalization evidence) a single diverge verdict would
        # quarantine the robot into a permanent coast. The guardrails
        # have their own suite (test_recovery.py); this gate is about
        # the memory tier under a DRIVING robot.
        recovery=dataclasses.replace(base.recovery, enabled=False),
        world=WorldConfig(windowed=True, window_tiles=8,
                          margin_tiles=3, host_tile_budget=6,
                          retention_coarsen=1))
    # 3.2 m corridor: narrower widths keep the fast robot inside its
    # own lidar warn band, where the swerve reflex fights the goal
    # seek and the patrol crawls.
    world, doors = W.corridor_course(768, cfg.grid.resolution_m,
                                     corridor_w_m=3.2)
    steps = 800
    # Out-and-back-and-out patrol: +x to ~+4.0 m (turn at step 130),
    # back west across the spawn (turn at 520), then +x again to
    # ~+7 m. The return leg shifts the window back, evicting the
    # columns the robot mapped outbound — and leg 3 drives back INTO
    # those very columns. Goals sit at ±15 m (in-corridor, in-map) so
    # they are never "reached": the patrol never falls back to
    # frontier exploration. The +0.9 bias on goal 2 points the return
    # bearing away from the south wall.
    goal_script = [(0, 15.0, 0.0), (130, -15.0, 0.9),
                   (520, 15.0, 0.0)]
    # Pressure squeezes the host tier across leg 2's shift-back
    # (~step 265): leg 1's content columns evict past the squeezed
    # budget into the spill. TWO rots (x=1.6 sits on the recentre
    # trigger, so leg 2 may re-cross it and rehydrate early — which
    # empties the spill): one inside the pressure window right after
    # the shift-back, one during the second back-swing; each fires
    # while the spill holds real tiles in at least one of the two
    # wiggle patterns leg 2 exhibits, and every rotted tile is
    # re-read by a later eastbound re-entry.
    events = [
        FaultEvent(step=240, kind="memory_pressure", value=0.7,
                   duration=150),
        FaultEvent(step=330, kind="spill_corrupt", value=2.0),
        FaultEvent(step=500, kind="spill_corrupt", value=2.0),
    ]

    mark = flight_recorder.mark()
    rep = run_lifelong_mission(cfg, world, doors, events, steps,
                               seed=0, n_robots=1,
                               checkpoint_dir=str(tmp_path / "a"),
                               goal_script=goal_script)
    degrades = [e for e in flight_recorder.events_since(mark)
                if e["kind"] == "world_spill_corrupt"]
    # Constant-memory gate: the device window never grows, whatever
    # the traveled distance did (~17 m on a 12.8 m window).
    window_bytes = (8 * 32) ** 2 * 4
    assert rep.peak_device_window_bytes() == window_bytes
    assert all(s["device_window_bytes"] == window_bytes
               for s in rep.world_series)
    assert rep.distance_traveled_m > 8.0
    dists = [s["distance_m"] for s in rep.world_series]
    assert dists == sorted(dists) and dists[-1] > dists[0]
    # The window machinery actually ran: recentres (plural origins),
    # eviction to host/disk on the way.
    origins = {tuple(s["origin_tile"]) for s in rep.world_series}
    assert len(origins) >= 2
    assert max(s["away_tiles"] for s in rep.world_series) > 0
    assert max(s["spill_tiles"] for s in rep.world_series) > 0
    # Chaos fired for real: the rot note names actual tiles (not the
    # "no spilled tiles" skip), and the pressure window cleared.
    assert any("memory_pressure" in d for _, d in rep.plan_log)
    assert any("clear: memory_pressure" in d for _, d in rep.plan_log)
    assert any("spill_corrupt" in d and "tile(s)" in d
               for _, d in rep.plan_log), rep.plan_log
    # …and the rotted tiles were READ BACK: re-entry hit the bad
    # inner CRC, degraded to unknown with a flight event, and the
    # mission drove on (degrade-never-die at mission scale).
    assert degrades, "corrupt spill records were never re-read"
    assert rep.grid.shape == (256, 256)     # the WINDOW, not 768²

    # Map quality through eviction/re-entry/chaos: occupancy sign vs
    # sim ground truth in the final window slice. (Odometry drift
    # compresses the estimated frame along the corridor, so this is a
    # structural gate — the walls sit at fixed y — not exact-pose.)
    t = cfg.serving.tile_cells
    r0, c0 = rep.world_series[-1]["origin_tile"]
    truth = world[r0 * t:r0 * t + 256, c0 * t:c0 * t + 256]
    known = np.abs(rep.grid) > 0.5
    assert int(known.sum()) > 3000
    agree = float(((rep.grid > 0.5) == (truth > 0.5))[known].mean())
    assert agree >= 0.85, f"sign agreement {agree:.3f}"

    # Same-seed chaos determinism, memory traffic included: the
    # world_series carries origin/host/spill/away per chunk — the
    # eviction/spill schedule the gate demands bit-identical.
    rep2 = run_lifelong_mission(cfg, world, doors, events, steps,
                                seed=0, n_robots=1,
                                checkpoint_dir=str(tmp_path / "c"),
                                goal_script=goal_script)
    assert rep2.plan_log == rep.plan_log
    assert rep2.world_series == rep.world_series
    np.testing.assert_array_equal(rep2.grid, rep.grid)
