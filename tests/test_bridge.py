"""Bridge layer tests: messages, QoS bus, TF tree, node/executor.

Covers the transport semantics the reference depends on but never tested
(SURVEY.md §4): Best-Effort drops, transient-local latching, loss/reorder
injection (report.pdf §V.A), TF chain lookups, honest-stamp interpolation.
"""

import math
import threading
import time

import numpy as np
import pytest

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.messages import (
    Header, LaserScan, OccupancyGrid, MapMetaData, Pose2D, TransformStamped,
    occupancy_from_logodds,
)
from jax_mapping.bridge.node import Executor, Node
from jax_mapping.bridge.qos import (
    QoSProfile, Reliability, qos_map, qos_sensor_data,
)
from jax_mapping.bridge.tf import TfTree


# ---------------------------------------------------------------- messages

def test_pose2d_quaternion_roundtrip():
    for th in [-3.0, -1.0, 0.0, 0.5, 2.9]:
        p = Pose2D(1.0, 2.0, th)
        q = p.to_quaternion()
        back = Pose2D.from_quaternion(*q, x=p.x, y=p.y)
        assert back.theta == pytest.approx(th, abs=1e-6)


def test_occupancy_image_semantics():
    """Exact thresholds of the reference endpoint (server main.py:256-266):
    127 unknown, 255 free, 0 occupied, flipud to image coords."""
    data = np.array([[-1, 0], [100, 50]], np.int8)
    g = OccupancyGrid(info=MapMetaData(width=2, height=2),
                      data=data.reshape(-1))
    img = g.as_image_array()
    # flipud: grid row 1 becomes image row 0.
    assert img[0, 0] == 0            # occupied
    assert img[0, 1] == 127          # mid value stays unknown-gray
    assert img[1, 0] == 127          # unknown
    assert img[1, 1] == 255          # free


def test_occupancy_from_logodds_trichotomy():
    lo = np.array([[2.0, 0.0], [-2.0, 0.4]], np.float32)
    g = occupancy_from_logodds(lo, 0.5, -0.5, 0.05, (-1.0, -1.0))
    d = g.data.reshape(2, 2)
    assert d[0, 0] == 100 and d[1, 0] == 0
    assert d[0, 1] == -1 and d[1, 1] == -1
    assert g.info.resolution == 0.05


def test_transform_compose_inverse():
    a = TransformStamped(header=Header(frame_id="map"),
                         child_frame_id="odom", x=1.0, y=0.0,
                         theta=math.pi / 2)
    b = TransformStamped(header=Header(frame_id="odom"),
                         child_frame_id="base", x=1.0, y=0.0, theta=0.0)
    ab = a.compose(b)
    # Rotating (1,0) by 90 deg lands at (0,1), plus the (1,0) offset.
    assert ab.x == pytest.approx(1.0, abs=1e-9)
    assert ab.y == pytest.approx(1.0, abs=1e-9)
    ident = a.compose(a.inverse())
    assert ident.x == pytest.approx(0.0, abs=1e-9)
    assert ident.theta == pytest.approx(0.0, abs=1e-9)


# ---------------------------------------------------------------- bus QoS

def test_best_effort_drops_oldest_on_overflow():
    bus = Bus()
    sub = bus.subscribe("/scan", qos_sensor_data)     # depth 5
    pub = bus.publisher("/scan", qos_sensor_data)
    for i in range(8):
        pub.publish(i)
    got = sub.take_all()
    assert got == [3, 4, 5, 6, 7]
    assert sub.n_dropped == 3


def test_reliable_no_loss_with_consumer():
    bus = Bus()
    qos = QoSProfile(depth=4, reliability=Reliability.RELIABLE)
    sub = bus.subscribe("/odom", qos)
    pub = bus.publisher("/odom", qos)
    got = []

    def consumer():
        while len(got) < 20:
            m = sub.take(timeout=1.0)
            if m is not None:
                got.append(m)

    t = threading.Thread(target=consumer)
    t.start()
    for i in range(20):
        pub.publish(i)
    t.join(timeout=5.0)
    assert got == list(range(20))
    assert sub.n_dropped == 0


def test_transient_local_latches_for_late_joiner():
    """The /map pattern: RViz started after the mapper still sees a map."""
    bus = Bus()
    pub = bus.publisher("/map", qos_map)
    pub.publish("the-map")
    sub = bus.subscribe("/map", qos_map)
    assert sub.take(timeout=0.1) == "the-map"


def test_latest_keeps_only_newest():
    """The reference's latest_scan cache pattern (main.py:77-78)."""
    bus = Bus()
    sub = bus.subscribe("/scan", qos_sensor_data)
    pub = bus.publisher("/scan", qos_sensor_data)
    for i in range(4):
        pub.publish(i)
    assert sub.latest() == 3
    assert sub.latest() is None


def test_loss_injection_only_hits_best_effort():
    bus = Bus(drop_prob=0.5, seed=7)
    be = bus.subscribe("/scan", QoSProfile(
        depth=1000, reliability=Reliability.BEST_EFFORT))
    rel = bus.subscribe("/scan", QoSProfile(depth=1000))
    pub = bus.publisher("/scan", qos_sensor_data)
    for i in range(200):
        pub.publish(i)
    n_be = len(be.take_all())
    n_rel = len(rel.take_all())
    assert n_rel == 200
    assert 40 < n_be < 160          # ~50% loss


def test_reorder_injection_preserves_content():
    bus = Bus(reorder_prob=0.3, seed=3)
    sub = bus.subscribe("/scan", QoSProfile(
        depth=1000, reliability=Reliability.BEST_EFFORT))
    pub = bus.publisher("/scan", qos_sensor_data)
    for i in range(100):
        pub.publish(i)
    got = sub.take_all()
    # At most one in-flight held sample is lost; no duplicates; order differs.
    assert len(set(got)) == len(got)
    assert len(got) >= 99
    assert sorted(got) != got or len(got) < 100


def test_callback_delivery():
    bus = Bus()
    seen = []
    bus.subscribe("/x", callback=seen.append)
    pub = bus.publisher("/x")
    pub.publish("a")
    pub.publish("b")
    assert seen == ["a", "b"]


# ---------------------------------------------------------------- tf tree

def test_tf_static_chain_lookup():
    """map->odom->base_link->base_laser, the reference's full chain
    (SURVEY.md §1 L1) with the z=0.12 laser mount."""
    tf = TfTree()
    tf.set_transform(TransformStamped(
        header=Header(stamp=1.0, frame_id="map"), child_frame_id="odom",
        x=0.5, y=0.0, theta=0.0))
    tf.set_transform(TransformStamped(
        header=Header(stamp=1.0, frame_id="odom"), child_frame_id="base_link",
        x=1.0, y=2.0, theta=math.pi / 2))
    tf.set_static_transform(TransformStamped(
        header=Header(frame_id="base_link"), child_frame_id="base_laser",
        z=0.12))
    out = tf.lookup("map", "base_laser", stamp=1.0)
    assert out.x == pytest.approx(1.5)
    assert out.y == pytest.approx(2.0)
    assert out.z == pytest.approx(0.12)
    assert out.theta == pytest.approx(math.pi / 2)
    # Reverse direction = inverse.
    inv = tf.lookup("base_laser", "map", stamp=1.0)
    assert inv.compose(out).x == pytest.approx(0.0, abs=1e-9)


def test_tf_interpolation_and_clamp():
    tf = TfTree()
    for stamp, x in [(0.0, 0.0), (1.0, 2.0)]:
        tf.set_transform(TransformStamped(
            header=Header(stamp=stamp, frame_id="odom"),
            child_frame_id="base_link", x=x))
    mid = tf.lookup("odom", "base_link", stamp=0.25)
    assert mid.x == pytest.approx(0.5)
    # Clamp instead of future extrapolation (honest-stamp policy,
    # SURVEY.md Appendix B).
    fut = tf.lookup("odom", "base_link", stamp=5.0)
    assert fut.x == pytest.approx(2.0)


def test_tf_unknown_frame_raises():
    tf = TfTree()
    with pytest.raises(LookupError):
        tf.lookup("map", "nowhere")
    assert not tf.can_transform("map", "nowhere")


# ---------------------------------------------------------------- executor

def test_executor_timers_fire_and_shutdown():
    bus = Bus()
    node = Node("n", bus)
    ticks = []
    node.create_timer(0.02, lambda: ticks.append(time.monotonic()))
    ex = Executor([node])
    ex.spin_thread()
    time.sleep(0.25)
    ex.shutdown()
    assert len(ticks) >= 5
    n_after = len(ticks)
    time.sleep(0.1)
    assert len(ticks) == n_after          # really stopped


def test_callback_publish_chain_no_deadlock():
    """A guarded callback that publishes back into the same node must not
    self-deadlock (inline delivery re-enters the node's callback guard)."""
    bus = Bus()
    node = Node("n", bus)
    seen = []
    pub_b = bus.publisher("/b")
    node.create_subscription("/a", lambda m: pub_b.publish(m + 1))
    node.create_subscription("/b", seen.append)
    done = []

    def publish():
        bus.publisher("/a").publish(1)
        done.append(True)

    t = threading.Thread(target=publish, daemon=True)
    t.start()
    t.join(timeout=2.0)
    assert done, "publish chain deadlocked"
    assert seen == [2]


def test_node_callback_exception_contained():
    """The reference survives loop exceptions by design (main.py:198-200);
    the node guard must contain them and count them."""
    bus = Bus()
    node = Node("n", bus)

    def bad(_msg):
        raise RuntimeError("boom")

    node.create_subscription("/x", bad)
    pub = bus.publisher("/x")
    pub.publish(1)        # must not raise into the publisher
    assert node.n_errors == 1


def test_http_save_load_roundtrip(tiny_cfg, tmp_path):
    """/save then /load on a fresh stack restores the live SLAM state —
    the serialization capability slam_toolbox exposes but the reference
    never invokes (slam_config.yaml:32)."""
    import json as _json
    import urllib.request

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=3)
    stack = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0,
                             seed=3)
    try:
        stack.api.checkpoint_dir = str(tmp_path)
        stack.brain.start_exploring()
        stack.run_steps(25)
        grid_before = np.asarray(stack.mapper.states[0].grid).copy()
        assert np.abs(grid_before).sum() > 0    # fused something
        url = f"http://127.0.0.1:{stack.api.port}"
        # GET must NOT mutate (ADVICE r3: prefetcher-safe); POST does.
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + "/save")
        assert ei.value.code == 405
        body = _json.loads(urllib.request.urlopen(
            urllib.request.Request(url + "/save", method="POST")).read())
        assert body["status"] == "saved"

        # wipe the live state, then restore
        from jax_mapping.models import slam as S
        stack.mapper.states[0] = S.init_state(tiny_cfg)
        assert np.abs(np.asarray(stack.mapper.states[0].grid)).sum() == 0
        body = _json.loads(urllib.request.urlopen(
            urllib.request.Request(url + "/load", method="POST")).read())
        assert body["status"] == "loaded"
        np.testing.assert_array_equal(
            np.asarray(stack.mapper.states[0].grid), grid_before)
    finally:
        stack.shutdown()


def test_http_load_refuses_config_drift(tiny_cfg, tmp_path):
    """A checkpoint written under a different config must 409, not load."""
    import dataclasses
    import json as _json
    import urllib.error
    import urllib.request

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.io.checkpoint import save_checkpoint
    from jax_mapping.models import slam as S
    from jax_mapping.sim import world as W

    other = dataclasses.replace(
        tiny_cfg, matcher=dataclasses.replace(tiny_cfg.matcher,
                                              min_response=0.42))
    save_checkpoint(str(tmp_path / "drift.npz"), [S.init_state(tiny_cfg)],
                    config_json=other.to_json())

    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=2,
                           seed=1)
    stack = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0)
    try:
        stack.api.checkpoint_dir = str(tmp_path)
        url = f"http://127.0.0.1:{stack.api.port}/load?name=drift"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(url,
                                                          method="POST"))
        assert ei.value.code == 409
        body = _json.loads(ei.value.read())
        assert "config" in body["error"]
    finally:
        stack.shutdown()


def _goal_stack(tiny_cfg, world, planner: bool = False):
    """Sim stack tuned for goal-seek drives: faster cruise so a metre of
    travel fits a CPU test budget. planner=False pins the round-4
    straight-line-seek behavior these tests target (the map-aware planner
    has its own suite, tests/test_planner.py)."""
    import dataclasses

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.config import PlannerConfig
    cfg = dataclasses.replace(
        tiny_cfg, robot=dataclasses.replace(tiny_cfg.robot,
                                            cruise_speed_units=300),
        planner=dataclasses.replace(tiny_cfg.planner, enabled=planner))
    return launch_sim_stack(cfg, world, n_robots=1, http_port=0, seed=2)


def test_goal_seek_reaches_and_clears(tiny_cfg):
    """VERDICT r4 weak #4: the full /goal_pose flow through ThymioBrain —
    goal set -> exploring robot steers to it -> arrives within
    goal_reached_dist_m -> goal clears. (The policy math and adapter
    routing are unit-tested; this drives the stack end to end.)"""
    from jax_mapping.sim import world as W

    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = _goal_stack(tiny_cfg, world)
    try:
        st.brain.start_exploring()
        st.run_steps(5)
        start = st.sim.truth_poses()[0]
        goal = (float(start[0]) + 0.55, float(start[1]) + 0.30)
        st.bus.publisher("/goal_pose").publish(Pose2D(goal[0], goal[1], 0.0))
        assert st.brain.status()["goal"] is not None
        reached_at = None
        for step in range(400):
            st.run_steps(1)
            if st.brain.status()["goal"] is None:
                reached_at = step
                break
        assert reached_at is not None, \
            "goal never cleared after 400 steps of goal-seek"
        pose = st.sim.truth_poses()[0]
        # The goal clears on the BRAIN's pose estimate; the true position
        # must still be in the neighbourhood (estimate drift is small in
        # an empty arena over a short drive).
        d = math.hypot(pose[0] - goal[0], pose[1] - goal[1])
        assert d < 3 * st.brain.goal_reached_dist_m, (
            f"goal cleared {d:.2f} m from the target")
    finally:
        st.shutdown()


def test_goal_behind_wall_shield_wins(tiny_cfg):
    """Goal-seek must not defeat the reactive shield: with the goal
    straight behind a wall and NO planner (round-4 behavior, pinned via
    _goal_stack(planner=False)), the robot keeps avoiding (IR pivot /
    LiDAR swerve outrank goal steering in the subsumption stack) and never
    drives into the wall; the straight-line-unreachable goal stays set.
    With the planner the same scenario is navigated around —
    tests/test_planner.py::test_planner_reaches_goal_behind_wall."""
    import numpy as np

    from jax_mapping.sim import world as W

    res = tiny_cfg.grid.resolution_m
    world = np.asarray(W.empty_arena(96, res), bool).copy()
    # Wall at x = 0.9 m spanning y = -0.8..0.8 (robot starts near
    # (0.3, 0) facing +x; the goal sits beyond the wall).
    c = 96 // 2
    world[c - 16:c + 16, c + 18:c + 20] = True
    st = _goal_stack(tiny_cfg, world)
    try:
        st.brain.start_exploring()
        st.run_steps(3)
        st.bus.publisher("/goal_pose").publish(Pose2D(1.4, 0.0, 0.0))
        for _ in range(150):
            st.run_steps(1)
            p = st.sim.truth_poses()[0]
            r = int(round(p[1] / res)) + c
            cc = int(round(p[0] / res)) + c
            assert not world[r, cc], (
                f"robot drove into the wall at ({p[0]:.2f}, {p[1]:.2f}) — "
                "goal-seek defeated the reactive shield")
        assert st.brain.status()["goal"] is not None, \
            "unreachable goal reported reached"
    finally:
        st.shutdown()


def test_status_exposes_mapping_health(tiny_cfg):
    """/status carries the mapping pipeline's counters (scans fused,
    loops closed, 3D images/keyframes/refuses) alongside the brain's
    motion fields — the operator's one-glance health check."""
    import json as _json
    import urllib.request

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=4)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0,
                          seed=4, depth_cam=True)
    try:
        st.brain.start_exploring()
        st.run_steps(8)
        body = _json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{st.api.port}/status").read())
        assert body["n_scans_fused"] == st.mapper.n_scans_fused > 0
        assert body["n_loops_closed"] == st.mapper.n_loops_closed
        assert body["n_images_fused"] == st.voxel_mapper.n_images_fused > 0
        assert "n_depth_keyframes" in body and "n_voxel_refuses" in body
    finally:
        st.shutdown()
