"""ROS 2 adapter tests against a stub rclpy (this image has no ROS).

The stub mirrors the attribute surface the adapter touches on rclpy,
the message packages, and tf2_ros, so every conversion and wiring path
runs in CI; on a real ROS 2 install the same code hits real DDS.
"""

import math
import sys
import types

import numpy as np
import pytest


# ---------------------------------------------------------------- stub ROS

class Obj:
    """Recursive attribute bag: msg.pose.pose.position.x just works."""

    def __getattr__(self, k):
        if k.startswith("_"):
            raise AttributeError(k)
        v = Obj()
        setattr(self, k, v)
        return v


def _msg(name):
    return type(name, (Obj,), {})


class StubTime:
    def __init__(self, sec=0, nanosec=0):
        self.sec, self.nanosec = sec, nanosec


class StubPublisher:
    def __init__(self, topic):
        self.topic = topic
        self.published = []

    def publish(self, m):
        self.published.append(m)


class StubNode:
    def __init__(self, name):
        self.name = name
        self.pubs = {}
        self.subs = {}
        self.timers = []

    def create_publisher(self, type_, topic, qos):
        p = StubPublisher(topic)
        self.pubs[topic] = p
        return p

    def create_subscription(self, type_, topic, cb, qos):
        self.subs[topic] = cb

    def create_timer(self, period, cb):
        self.timers.append((period, cb))

    def destroy_node(self):
        pass


class StubBroadcaster:
    def __init__(self, node):
        self.sent = []

    def sendTransform(self, tfs):
        self.sent.append(list(tfs))


@pytest.fixture
def stub_ros(monkeypatch):
    rclpy = types.ModuleType("rclpy")
    rclpy.ok = lambda: True
    rclpy.init = lambda: None
    rclpy.spin_once = lambda node, timeout_sec=0.1: None
    node_mod = types.ModuleType("rclpy.node")
    node_mod.Node = StubNode
    qos_mod = types.ModuleType("rclpy.qos")

    class _QoS:
        def __init__(self, depth=10, reliability=None, durability=None):
            self.depth, self.reliability = depth, reliability
            self.durability = durability

    class _R:
        BEST_EFFORT, RELIABLE = "be", "rel"

    class _D:
        TRANSIENT_LOCAL, VOLATILE = "tl", "vol"

    qos_mod.QoSProfile, qos_mod.ReliabilityPolicy = _QoS, _R
    qos_mod.DurabilityPolicy = _D
    rclpy.node, rclpy.qos = node_mod, qos_mod

    sen = types.ModuleType("sensor_msgs.msg")
    sen.LaserScan = _msg("LaserScan")
    sen.PointCloud2 = _msg("PointCloud2")
    sen.PointField = _msg("PointField")
    nav = types.ModuleType("nav_msgs.msg")
    nav.OccupancyGrid = _msg("OccupancyGrid")
    nav.Odometry = _msg("Odometry")
    nav.Path = _msg("Path")
    geo = types.ModuleType("geometry_msgs.msg")
    geo.Twist = _msg("Twist")
    geo.PoseWithCovarianceStamped = _msg("PoseWithCovarianceStamped")
    geo.PoseArray = _msg("PoseArray")
    geo.PoseStamped = _msg("PoseStamped")
    geo.Pose = _msg("Pose")
    geo.Point = _msg("Point")
    geo.TransformStamped = _msg("TransformStamped")
    bi = types.ModuleType("builtin_interfaces.msg")
    bi.Time = StubTime
    vis = types.ModuleType("visualization_msgs.msg")
    vis.Marker = _msg("Marker")
    vis.MarkerArray = _msg("MarkerArray")
    mapm = types.ModuleType("map_msgs.msg")
    mapm.OccupancyGridUpdate = _msg("OccupancyGridUpdate")
    tf2 = types.ModuleType("tf2_ros")
    tf2.TransformBroadcaster = StubBroadcaster

    mods = {
        "rclpy": rclpy, "rclpy.node": node_mod, "rclpy.qos": qos_mod,
        "sensor_msgs": types.ModuleType("sensor_msgs"),
        "sensor_msgs.msg": sen,
        "nav_msgs": types.ModuleType("nav_msgs"), "nav_msgs.msg": nav,
        "geometry_msgs": types.ModuleType("geometry_msgs"),
        "geometry_msgs.msg": geo,
        "builtin_interfaces": types.ModuleType("builtin_interfaces"),
        "builtin_interfaces.msg": bi,
        "visualization_msgs": types.ModuleType("visualization_msgs"),
        "visualization_msgs.msg": vis,
        "map_msgs": types.ModuleType("map_msgs"),
        "map_msgs.msg": mapm,
        "tf2_ros": tf2,
    }
    for k, v in mods.items():
        monkeypatch.setitem(sys.modules, k, v)
    return mods


# ---------------------------------------------------------------- tests

def _adapter(tiny_cfg, stub_ros, **kw):
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.rclpy_adapter import RclpyAdapter
    from jax_mapping.bridge.tf import TfTree
    bus = Bus()
    tf = TfTree()
    return bus, tf, RclpyAdapter(bus, tiny_cfg, tf=tf, **kw)


def test_unavailable_without_ros(tiny_cfg):
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.rclpy_adapter import RclpyAdapter, rclpy_available
    assert not rclpy_available()          # this image has no ROS
    with pytest.raises(RuntimeError, match="rclpy"):
        RclpyAdapter(Bus(), tiny_cfg)


def test_outbound_map_reaches_ros(tiny_cfg, stub_ros):
    from jax_mapping.bridge.messages import occupancy_from_logodds
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    lo = np.zeros((4, 5), np.float32)
    lo[1, 2] = 2.0     # occupied
    lo[3, :] = -2.0    # free row
    bus.publisher("/map").publish(occupancy_from_logodds(
        lo, 0.5, -0.5, 0.05, (-1.0, -1.0)))
    ros_map = ad.node.pubs["/map"].published[-1]
    assert ros_map.info.width == 5 and ros_map.info.height == 4
    data = np.array(ros_map.data).reshape(4, 5)
    assert data[1, 2] == 100
    assert (data[3] == 0).all()
    assert data[0, 0] == -1
    assert ros_map.info.origin.position.x == -1.0


def test_inbound_cmd_vel_reaches_bus(tiny_cfg, stub_ros):
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    got = []
    bus.subscribe("/cmd_vel", callback=got.append)
    ros_twist = Obj()
    ros_twist.linear.x = 0.2
    ros_twist.angular.z = -1.5
    ad.node.subs["/cmd_vel"](ros_twist)
    assert len(got) == 1
    assert got[0].linear_x == pytest.approx(0.2)
    assert got[0].angular_z == pytest.approx(-1.5)


def test_scan_roundtrip(tiny_cfg, stub_ros):
    from jax_mapping.bridge.messages import Header, LaserScan
    _bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    scan = LaserScan(header=Header(stamp=12.25, frame_id="base_laser"),
                     angle_increment=0.0175,
                     ranges=np.array([0.5, 2.0, 0.0], np.float32))
    back = ad.scan_from_ros(ad.scan_to_ros(scan))
    assert back.header.stamp == pytest.approx(12.25, abs=1e-6)
    assert back.header.frame_id == "base_laser"
    assert back.angle_increment == pytest.approx(0.0175)
    np.testing.assert_allclose(back.ranges, scan.ranges)


def test_odom_roundtrip(tiny_cfg, stub_ros):
    from jax_mapping.bridge.messages import Header, Odometry, Pose2D, Twist
    _bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    od = Odometry(header=Header(stamp=3.5, frame_id="odom"),
                  pose=Pose2D(1.0, -0.5, 0.7),
                  twist=Twist(linear_x=0.03, angular_z=0.2))
    back = ad.odom_from_ros(ad.odom_to_ros(od))
    assert back.pose.x == pytest.approx(1.0)
    assert back.pose.y == pytest.approx(-0.5)
    assert back.pose.theta == pytest.approx(0.7, abs=1e-6)
    assert back.twist.linear_x == pytest.approx(0.03)
    assert back.twist.angular_z == pytest.approx(0.2)


def test_tf_broadcast(tiny_cfg, stub_ros):
    from jax_mapping.bridge.messages import Header, TransformStamped
    _bus, tf, ad = _adapter(tiny_cfg, stub_ros)
    tf.set_static_transform(TransformStamped(
        header=Header(stamp=0.0, frame_id="base_link"),
        child_frame_id="base_laser", z=0.12))
    tf.set_transform(TransformStamped(
        header=Header(stamp=1.0, frame_id="odom"),
        child_frame_id="base_link", x=0.4, theta=math.pi / 2))
    ad.publish_tf_once()
    sent = ad._tf_bcast.sent[-1]
    by_child = {m.child_frame_id: m for m in sent}
    assert by_child["base_laser"].transform.translation.z == \
        pytest.approx(0.12)
    laser_parent = by_child["base_laser"].header.frame_id
    assert laser_parent == "base_link"
    m = by_child["base_link"]
    assert m.transform.translation.x == pytest.approx(0.4)
    assert m.transform.rotation.z == pytest.approx(math.sin(math.pi / 4))
    # TF timer registered at the configured period (slam_config.yaml:24).
    assert any(abs(p - tiny_cfg.tf_publish_period_s) < 1e-9
               for p, _ in ad.node.timers)


def test_inbound_hardware_mode_scan(tiny_cfg, stub_ros):
    """Live-hardware wiring: a real ROS LD06 driver's /scan feeds the Bus."""
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros,
                            inbound=("cmd_vel", "scan", "odom"))
    got = []
    bus.subscribe("scan", callback=got.append)
    ros_scan = Obj()
    ros_scan.header.stamp = StubTime(sec=2, nanosec=500_000_000)
    ros_scan.header.frame_id = "base_laser"
    for f in ("angle_min", "time_increment", "scan_time", "range_min"):
        setattr(ros_scan, f, 0.0)
    ros_scan.angle_max = 6.283
    ros_scan.angle_increment = 0.0175
    ros_scan.range_max = 12.0
    ros_scan.ranges = [1.0, 2.0]
    ros_scan.intensities = []
    ad.node.subs["/scan"](ros_scan)
    assert len(got) == 1
    assert got[0].header.stamp == pytest.approx(2.5)
    np.testing.assert_allclose(got[0].ranges, [1.0, 2.0])


def test_pose_outbound_all_robots_and_stamp(tiny_cfg, stub_ros):
    """/pose carries robot 0 WITH a stamp; /poses carries the whole fleet
    (round-2 VERDICT: the adapter dropped the stamp and robots 1..N)."""
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    payload = [{"x": 1.0, "y": 2.0, "theta": 0.5, "stamp": 3.25},
               {"x": -1.0, "y": 0.5, "theta": -0.25, "stamp": 3.25}]
    bus.publisher("/pose").publish(payload)
    one = ad.node.pubs["/pose"].published[-1]
    assert one.header.stamp.sec == 3
    assert one.header.stamp.nanosec == pytest.approx(250_000_000, abs=2)
    assert one.pose.pose.position.x == 1.0
    arr = ad.node.pubs["/poses"].published[-1]
    assert len(arr.poses) == 2
    assert arr.poses[1].position.x == -1.0
    assert arr.header.stamp.sec == 3


def test_ros_launch_artifact(tiny_cfg, stub_ros, capsys):
    """jax-mapping-ros wires stack + adapter + prints the RViz command
    (the pc_server.launch.py equivalent, stub-ROS only in this image)."""
    import os
    from jax_mapping import ros_launch
    # --print-rviz-config path exists and is printed.
    assert ros_launch.main(["--print-rviz-config"]) == 0
    path = capsys.readouterr().out.strip()
    assert os.path.exists(path), path
    # Full bring-up against the stub: runs briefly and shuts down cleanly.
    rc = ros_launch.main(["--world", "arena", "--world-cells", "96",
                          "--duration-s", "0.4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "/map" in out and "rviz2 -d" in out


def test_integrated_stack_bridges_topics(tiny_cfg, stub_ros):
    """Boot the REAL sim stack + adapter (not hand-published payloads) and
    assert data actually crosses the Bus->ROS boundary — pins the bus
    topic strings end-to-end (a 'pose' vs '/pose' mismatch silently
    bridges nothing; round-3 review catch)."""
    import numpy as np
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.bridge.rclpy_adapter import RclpyAdapter
    from jax_mapping.sim import world as W

    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    stack = launch_sim_stack(tiny_cfg, world, n_robots=1)
    try:
        ad = RclpyAdapter(stack.bus, tiny_cfg, tf=stack.tf)
        stack.brain.start_exploring()
        stack.run_steps(5)
        # /map rides a wall-clock timer (5 s, idle in stepped mode);
        # invoke the same callback the timer runs.
        stack.mapper.publish_map()
        assert ad.node.pubs["/scan"].published, "scan never bridged"
        assert ad.node.pubs["/odom"].published, "odom never bridged"
        assert ad.node.pubs["/pose"].published, "pose never bridged"
        assert ad.node.pubs["/poses"].published, "poses never bridged"
        assert ad.node.pubs["/map"].published, "map never bridged"
        arr = ad.node.pubs["/poses"].published[-1]
        assert len(arr.poses) == 1
        # Inbound: ROS /cmd_vel reaches the brain's bus subscription.
        tw = Obj()
        tw.linear.x = 0.1
        tw.angular.z = 0.0
        ad.node.subs["/cmd_vel"](tw)
        assert stack.brain._last_cmd_vel is not None
    finally:
        stack.shutdown()


def test_live_hardware_mode_no_sim_no_echo(tiny_cfg, stub_ros, capsys):
    """--live-hardware boots mapper-only (no simulator feeding 'scan') and
    must NOT republish /scan //odom (echo loop through its own inbound
    subscriptions)."""
    from jax_mapping import ros_launch
    rc = ros_launch.main(["--live-hardware", "--duration-s", "0.3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "live stack up" in out


def test_ros_launch_map_prior_and_localization(tiny_cfg, stub_ros,
                                               tmp_path, capsys):
    """The ROS entry point mirrors the demo's operator surface: a
    map_server artifact seeds the mapper, --localization freezes it, and
    bad input follows the polite rc=2 contract."""
    import numpy as np

    from jax_mapping import ros_launch
    from jax_mapping.io import rosmap

    occ = np.full((32, 32), 0, np.int8)
    occ[0, :] = 100
    _pgm, yaml = rosmap.save_map(str(tmp_path / "prior"), occ, 0.05,
                                 (-0.8, -0.8))
    rc = ros_launch.main(["--world", "arena", "--world-cells", "96",
                          "--duration-s", "0.3", "--localization",
                          "--map-prior", yaml])
    assert rc == 0
    assert "seeded map prior" in capsys.readouterr().out
    rc = ros_launch.main(["--world", "arena", "--world-cells", "96",
                          "--duration-s", "0.2",
                          "--map-prior", str(tmp_path / "nope.yaml")])
    assert rc == 2
    assert "cannot seed --map-prior" in capsys.readouterr().err


def test_inbound_initialpose_relocalizes_mapper(tiny_cfg, stub_ros):
    """RViz SetInitialPose -> adapter -> bus -> mapper pose reset."""
    import math as _m
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.bridge.rclpy_adapter import RclpyAdapter

    bus = Bus()
    mapper = MapperNode(tiny_cfg, bus, n_robots=1)
    ad = RclpyAdapter(bus, tiny_cfg)
    m = Obj()
    m.pose.pose.position.x = 1.5
    m.pose.pose.position.y = -0.5
    m.pose.pose.orientation.z = _m.sin(0.4 / 2)
    m.pose.pose.orientation.w = _m.cos(0.4 / 2)
    grid_before = mapper.states[0].grid
    ad.node.subs["/initialpose"](m)
    st = mapper.states[0]
    pose = np.asarray(st.pose)
    assert pose[0] == pytest.approx(1.5)
    assert pose[1] == pytest.approx(-0.5)
    assert pose[2] == pytest.approx(0.4, abs=1e-6)
    # Fresh chain, kept map: the graph restarts (no odometry edge will
    # span the teleport) while the grid carries on.
    assert int(st.graph.n_poses) == 0 and int(st.n_keyscans) == 0
    assert st.grid is grid_before


def test_inbound_goal_pose_reaches_bus(tiny_cfg, stub_ros):
    import math as _m
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    got = []
    bus.subscribe("/goal_pose", callback=got.append)
    m = Obj()
    m.pose.position.x = 2.0
    m.pose.position.y = 3.0
    m.pose.orientation.z = _m.sin(-0.3 / 2)
    m.pose.orientation.w = _m.cos(-0.3 / 2)
    ad.node.subs["/goal_pose"](m)
    assert len(got) == 1
    assert got[0].x == pytest.approx(2.0)
    assert got[0].theta == pytest.approx(-0.3, abs=1e-6)


def test_inbound_namespaced_goal_pose_for_fleets(tiny_cfg, stub_ros):
    """Fleets bridge /robotN/goal_pose to the bus's namespaced goal
    topics (the brain's per-robot manual goals); single-robot stacks
    keep only /goal_pose."""
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros, n_robots=2)
    got = []
    bus.subscribe("robot1/goal_pose", callback=got.append)
    m = Obj()
    m.pose.position.x = -1.5
    m.pose.position.y = 0.5
    m.pose.orientation.z = 0.0
    m.pose.orientation.w = 1.0
    ad.node.subs["/robot1/goal_pose"](m)
    assert len(got) == 1 and got[0].x == pytest.approx(-1.5)
    # The other half of the contract: plain /goal_pose (RViz SetGoal ->
    # robot 0) and /robot0/goal_pose both survive in fleet mode.
    assert "/goal_pose" in ad.node.subs
    assert "/robot0/goal_pose" in ad.node.subs

    _bus2, _tf2, ad2 = _adapter(tiny_cfg, stub_ros)   # n_robots = 1
    assert "/robot1/goal_pose" not in ad2.node.subs
    assert "/goal_pose" in ad2.node.subs


def test_fleet_namespaced_scan_odom_bridging(tiny_cfg, stub_ros):
    """n_robots>1 bridges every robot's namespaced scan/odom topics both
    ways (robot_ns convention: 'robot<i>/scan'), not just robot 0."""
    from jax_mapping.bridge.messages import Header, LaserScan
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros, n_robots=2,
                            inbound=("cmd_vel", "scan", "odom"))
    assert "/robot0/scan" in ad.node.pubs and "/robot1/scan" in ad.node.pubs
    assert "/robot0/odom" in ad.node.pubs and "/robot1/odom" in ad.node.pubs
    assert "/robot1/scan" in ad.node.subs and "/robot1/odom" in ad.node.subs

    # outbound: a bus scan on robot1's namespace reaches only its ROS pub
    scan = LaserScan(header=Header(stamp=1.0, frame_id="robot1/base_laser"),
                     angle_min=0.0, angle_max=6.283, angle_increment=0.0175,
                     time_increment=0.0, scan_time=0.1, range_min=0.02,
                     range_max=12.0, ranges=np.array([1.5, 2.5], np.float32))
    bus.publisher("robot1/scan").publish(scan)
    assert len(ad.node.pubs["/robot1/scan"].published) == 1
    assert len(ad.node.pubs["/robot0/scan"].published) == 0


def test_frontiers_markers_outbound(tiny_cfg, stub_ros):
    """/frontiers becomes the /frontiers_markers MarkerArray the bundled
    RViz config displays: DELETEALL lead, one sphere per live cluster,
    claimed clusters green."""
    from jax_mapping.bridge.messages import FrontierArray, Header
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    assert "/frontiers_markers" in ad.node.pubs
    fa = FrontierArray(
        header=Header(stamp=4.5),
        targets_xy=np.array([[1.0, 2.0], [3.0, -1.0], [0.0, 0.0]],
                            np.float32),
        sizes=np.array([10, 5, 0], np.int32),     # third slot empty
        assignment=np.array([1, -1], np.int32))   # robot 0 claims slot 1
    bus.publisher("/frontiers").publish(fa)
    sent = ad.node.pubs["/frontiers_markers"].published
    assert len(sent) == 1
    ms = sent[0].markers
    assert ms[0].action == 3                      # DELETEALL lead
    live = ms[1:]
    assert len(live) == 2                         # empty slot skipped
    assert live[0].pose.position.x == pytest.approx(1.0)
    assert live[1].color.g == pytest.approx(1.0)  # claimed slot 1: green
    assert live[0].color.r == pytest.approx(1.0)  # unclaimed: orange


def test_map_updates_outbound_is_grid_update_type(tiny_cfg, stub_ros):
    """/map_updates carries map_msgs/OccupancyGridUpdate (full extent) —
    the type RViz's Map display reads on its update topic — not a second
    OccupancyGrid."""
    from jax_mapping.bridge.messages import occupancy_from_logodds
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    lo = np.zeros((3, 4), np.float32)
    lo[1, 1] = 2.0
    bus.publisher("/map_updates").publish(occupancy_from_logodds(
        lo, 0.5, -0.5, 0.05, (0.0, 0.0)))
    sent = ad.node.pubs["/map_updates"].published
    assert len(sent) == 1
    u = sent[0]
    assert type(u).__name__ == "OccupancyGridUpdate"
    assert (u.x, u.y, u.width, u.height) == (0, 0, 4, 3)
    assert len(u.data) == 12 and max(u.data) == 100


def test_integrated_fleet_stack_bridges_namespaced_topics(tiny_cfg,
                                                          stub_ros):
    """The REAL 2-robot sim stack bridges every robot's namespaced
    scan/odom into ROS plus the fleet PoseArray and frontier markers —
    end-to-end over the actual bus topic strings."""
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.bridge.rclpy_adapter import RclpyAdapter
    from jax_mapping.sim import world as W

    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    stack = launch_sim_stack(tiny_cfg, world, n_robots=2)
    try:
        ad = RclpyAdapter(stack.bus, tiny_cfg, tf=stack.tf, n_robots=2)
        stack.brain.start_exploring()
        stack.run_steps(6)
        stack.mapper.publish_map()
        stack.mapper.publish_frontiers()
        for ns in ("robot0/", "robot1/"):
            assert ad.node.pubs[f"/{ns}scan"].published, f"{ns}scan dropped"
            assert ad.node.pubs[f"/{ns}odom"].published, f"{ns}odom dropped"
        arr = ad.node.pubs["/poses"].published[-1]
        assert len(arr.poses) == 2
        assert ad.node.pubs["/frontiers_markers"].published
    finally:
        stack.shutdown()


def test_outbound_voxel_points_reach_ros(tiny_cfg, stub_ros):
    """VoxelPoints on the bus -> sensor_msgs/PointCloud2 on /voxel_points
    (packed float32 x/y/z, the RViz PointCloud2 display contract)."""
    import struct

    from jax_mapping.bridge.messages import Header, VoxelPoints

    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    pts = np.asarray([[1.0, 2.0, 0.25], [-0.5, 0.0, 0.1]], np.float32)
    bus.publisher("/voxel_points").publish(
        VoxelPoints(header=Header(stamp=3.5, frame_id="map"), points=pts))

    pub = ad.node.pubs["/voxel_points"]
    assert len(pub.published) == 1
    m = pub.published[0]
    assert m.width == 2 and m.height == 1
    assert m.point_step == 12 and m.row_step == 24
    assert [f.name for f in m.fields] == ["x", "y", "z"]
    assert all(f.datatype == 7 for f in m.fields)       # FLOAT32
    vals = struct.unpack("<6f", m.data)
    assert vals == pytest.approx((1.0, 2.0, 0.25, -0.5, 0.0, 0.1))
    assert m.header.frame_id == "map"


def test_pose_covariance_reaches_ros(tiny_cfg, stub_ros):
    """/pose carries the correlative matcher's surface covariance on the
    x/x, y/y, yaw/yaw diagonals of the 6x6 (slam_toolbox's
    PoseWithCovariance contract); poses without a match yet omit it."""
    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    bus.publisher("/pose").publish([
        {"x": 1.0, "y": 2.0, "theta": 0.5, "stamp": 1.0,
         "cov": [0.01, 0.04, 0.002]}])
    m = ad.node.pubs["/pose"].published[-1]
    c = m.pose.covariance
    assert c[0] == pytest.approx(0.01)
    assert c[7] == pytest.approx(0.04)
    assert c[35] == pytest.approx(0.002)
    assert sum(abs(v) for v in c) == pytest.approx(0.052)
    bus.publisher("/pose").publish([
        {"x": 1.0, "y": 2.0, "theta": 0.5, "stamp": 1.0, "cov": None}])
    m2 = ad.node.pubs["/pose"].published[-1]
    # Stub Obj auto-creates attributes; covariance must simply not have
    # been assigned a list.
    assert not isinstance(getattr(m2.pose, "covariance", None), list)


def test_outbound_plan_reaches_ros(tiny_cfg, stub_ros):
    """Path on the bus -> nav_msgs/Path on /plan (PoseStamped per
    waypoint, identity orientation — the RViz Path display contract)."""
    from jax_mapping.bridge.messages import Header, Path

    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    pts = np.asarray([[0.5, 0.0], [0.6, 0.1], [0.7, 0.2]], np.float32)
    bus.publisher("/plan").publish(
        Path(header=Header(stamp=2.5, frame_id="map"), poses_xy=pts))

    pub = ad.node.pubs["/plan"]
    assert len(pub.published) == 1
    m = pub.published[0]
    assert m.header.frame_id == "map"
    assert len(m.poses) == 3
    got = np.asarray([(p.pose.position.x, p.pose.position.y)
                      for p in m.poses])
    assert np.allclose(got, pts, atol=1e-6)
    assert all(p.pose.orientation.w == 1.0 for p in m.poses)


def test_voxel_mapper_publishes_points(tiny_cfg):
    """The voxel mapper's periodic export feeds the bus topic the
    adapter bridges."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.messages import DepthImage, Header, Odometry, \
        Pose2D
    from jax_mapping.bridge.voxel_mapper import VoxelMapperNode

    bus = Bus()
    got = []
    bus.subscribe("/voxel_points", callback=got.append)
    vm = VoxelMapperNode(tiny_cfg, bus, n_robots=1)
    cam = tiny_cfg.depthcam
    od = bus.publisher("odom")
    dp = bus.publisher("depth")
    od.publish(Odometry(header=Header(stamp=1.0), pose=Pose2D(0, 0, 0)))
    wall = np.full((cam.height_px, cam.width_px), 0.7, np.float32)
    for k in range(2):                       # cross the occ threshold
        dp.publish(DepthImage(header=Header(stamp=1.1 + 0.1 * k),
                              depth=wall))
        vm.tick()
    vm.publish_points()
    assert got and got[-1].points.shape[1] == 3
    assert len(got[-1].points) > 0
    # All points on the synthetic wall plane.
    assert np.abs(got[-1].points[:, 0] - 0.7).max() < 0.2


def test_graph_markers_outbound(tiny_cfg, stub_ros):
    """GraphMarkers on the bus -> MarkerArray on /graph: DELETEALL lead,
    per-robot SPHERE_LIST node layers, gray odometry LINE_LIST and red
    loop LINE_LIST (the slam_toolbox interactive-mode graph view)."""
    from jax_mapping.bridge.messages import GraphMarkers, Header

    bus, _tf, ad = _adapter(tiny_cfg, stub_ros)
    nodes = np.asarray([[0.0, 0.0], [0.5, 0.0], [0.5, 0.5], [1.0, 1.0]],
                       np.float32)
    nrob = np.asarray([0, 0, 0, 1], np.int32)
    edges = np.asarray([[[0.0, 0.0], [0.5, 0.0]],      # odometry
                        [[0.5, 0.0], [0.5, 0.5]],      # odometry
                        [[0.5, 0.5], [0.0, 0.0]]],     # loop (non-consec)
                       np.float32)
    isloop = np.asarray([False, False, True])
    bus.publisher("/graph").publish(GraphMarkers(
        header=Header(stamp=4.0, frame_id="map"), nodes_xy=nodes,
        node_robot=nrob, edges_xy=edges, edge_is_loop=isloop))
    out = ad.node.pubs["/graph"].published[-1]
    ms = out.markers
    assert ms[0].action == 3                 # DELETEALL
    node_layers = [m for m in ms if m.ns == "graph_nodes"]
    assert {m.id for m in node_layers} == {0, 1}
    assert len(node_layers[0].points) == 3   # robot 0's nodes
    assert len(node_layers[1].points) == 1
    odo = [m for m in ms if m.ns == "graph_edges"][0]
    loops = [m for m in ms if m.ns == "graph_loops"][0]
    assert len(odo.points) == 4              # 2 edges x 2 endpoints
    assert len(loops.points) == 2
    assert loops.color.r == pytest.approx(1.0)


def test_mapper_publishes_graph(tiny_cfg):
    """The mapper's periodic /graph export carries the live graphs: after
    real key scans there are nodes and consecutive odometry edges."""
    import jax.numpy as jnp

    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.bridge.messages import Header, LaserScan, Odometry, \
        Pose2D
    from jax_mapping.sim import lidar
    from jax_mapping.sim import world as W

    bus = Bus()
    mapper = MapperNode(tiny_cfg, bus, n_robots=1)
    got = []
    bus.subscribe("/graph", callback=got.append)
    world = jnp.asarray(W.empty_arena(96, tiny_cfg.grid.resolution_m))
    n_samples = int(tiny_cfg.scan.range_max_m
                    / (tiny_cfg.grid.resolution_m * 0.5))
    for k in range(5):
        t, x = 0.5 * k, 0.15 * k
        r = np.asarray(lidar.simulate_scans(
            tiny_cfg.scan, world, tiny_cfg.grid.resolution_m, n_samples,
            jnp.asarray([[x, 0.0, 0.0]]))[0])[:tiny_cfg.scan.n_beams]
        bus.publisher("odom").publish(Odometry(
            header=Header(stamp=t, frame_id="odom"),
            pose=Pose2D(x, 0.0, 0.0)))
        bus.publisher("scan").publish(LaserScan(
            header=Header(stamp=t, frame_id="base_laser"),
            angle_increment=tiny_cfg.scan.angle_increment_rad, ranges=r))
        mapper.tick()
    mapper.publish_graph()
    assert got, "no /graph message"
    g = got[-1]
    assert len(g.nodes_xy) >= 3
    assert (g.node_robot == 0).all()
    assert len(g.edges_xy) >= 2
    assert not g.edge_is_loop.any()          # straight drive: no loops
