"""The shared wedged-tunnel guard (utils/backend_guard.py).

Round 3's verdict: the operator entry points (`demo.py`, `ros_launch.py`)
hung >= 300 s under the ambient wedged-TPU-tunnel env because the bounded
probe + scrubbed re-exec lived only in bench/conftest/__graft_entry__
copies. These tests pin the shared helper's contract without spawning a
real probe against a wedged backend (the e2e proof is running the demo
under the ambient env, which the driver and operator do for real).
"""

import os
import sys
from unittest import mock

from jax_mapping.utils import backend_guard as BG


def test_scrubbed_env_drops_axon_hooks():
    env_in = {
        "PALLAS_AXON_POOL_IPS": "127.0.0.1",
        "AXON_LOOPBACK_RELAY": "1",
        "TPU_SKIP_MDS_QUERY": "1",
        "JAX_PLATFORMS": "axon",
        "PYTHONPATH": "/root/.axon_site:/somewhere/else",
        "HOME": "/root",
    }
    with mock.patch.dict(os.environ, env_in, clear=True):
        env = BG.scrubbed_cpu_env()
    assert not any(k.startswith(("AXON", "PALLAS_AXON", "TPU_"))
                   for k in env)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env[BG.FALLBACK_FLAG] == "1"
    assert ".axon_site" not in env["PYTHONPATH"]
    # The child must still find the package and the untouched entries.
    assert BG._PKG_PARENT in env["PYTHONPATH"].split(os.pathsep)
    assert "/somewhere/else" in env["PYTHONPATH"].split(os.pathsep)
    assert env["HOME"] == "/root"


def test_scrubbed_env_extra_keys_win():
    with mock.patch.dict(os.environ, {}, clear=True):
        env = BG.scrubbed_cpu_env(extra_env={"X_DEADLINE": "42"})
    assert env["X_DEADLINE"] == "42"


def test_suspect_only_when_wedge_possible():
    with mock.patch.dict(os.environ, {}, clear=True):
        assert not BG.backend_env_suspect()          # plain CPU image
    with mock.patch.dict(os.environ,
                         {"PALLAS_AXON_POOL_IPS": "127.0.0.1"}, clear=True):
        assert BG.backend_env_suspect()              # plugin registered
    with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "axon"}, clear=True):
        assert BG.backend_env_suspect()              # platform pinned
    with mock.patch.dict(os.environ,
                         {"PALLAS_AXON_POOL_IPS": "127.0.0.1",
                          BG.FALLBACK_FLAG: "1"}, clear=True):
        assert not BG.backend_env_suspect()          # already fell back


def test_ensure_noop_when_env_clean():
    """No probe subprocess, no re-exec on a clean env (common case must
    stay free)."""
    with mock.patch.dict(os.environ, {}, clear=True), \
            mock.patch.object(BG, "backend_probe_ok") as probe, \
            mock.patch.object(os, "execvpe") as ex:
        BG.ensure_responsive_backend("t")
    probe.assert_not_called()
    ex.assert_not_called()


def test_ensure_reexecs_on_wedged_probe():
    """Wedged probe -> re-exec with the CALLER-BUILT argv, scrubbed env."""
    with mock.patch.dict(os.environ,
                         {"PALLAS_AXON_POOL_IPS": "127.0.0.1"}, clear=True), \
            mock.patch.object(BG, "backend_probe_ok", return_value=False), \
            mock.patch.object(os, "execvpe") as ex:
        BG.ensure_responsive_backend(
            "t", argv=["-m", "jax_mapping.demo", "--steps", "2"])
    (prog, argv, env), _ = ex.call_args
    assert prog == sys.executable
    assert argv == [sys.executable, "-m", "jax_mapping.demo", "--steps", "2"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env[BG.FALLBACK_FLAG] == "1"


def test_ensure_proceeds_on_healthy_probe():
    with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "axon"}, clear=True), \
            mock.patch.object(BG, "backend_probe_ok", return_value=True), \
            mock.patch.object(os, "execvpe") as ex:
        BG.ensure_responsive_backend("t")
    ex.assert_not_called()


def test_probe_ok_real_subprocess():
    """The probe really runs jax.devices() + a jit compile in a child; on
    this test env (scrubbed CPU) it must succeed well inside the timeout."""
    assert BG.backend_probe_ok(timeout_s=120)


def test_probe_compiles_not_just_enumerates():
    """Round-5 regression pin: a half-wedged tunnel answers jax.devices()
    in ~1 s but blocks every compile RPC >5 min, so an enumeration-only
    probe waves the entry point through to a hang at its first jit. The
    probe's child code must therefore jit-compile and block on a result,
    not merely enumerate. Pinned on the actual child source
    (BG._PROBE_CODE, what subprocess.run executes — not prose around it)
    alongside the behavioral CPU run above."""
    assert "jax.jit" in BG._PROBE_CODE
    assert "block_until_ready" in BG._PROBE_CODE
    assert "jax.devices()" in BG._PROBE_CODE
