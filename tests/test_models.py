"""Model-level tests: sim lidar, explorer policies, slam_step, fleet_step."""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import tiny_config
from jax_mapping.models import explorer as E
from jax_mapping.models import fleet as FM
from jax_mapping.models import slam as SM
from jax_mapping.ops import grid as G
from jax_mapping.sim import lidar, thymio, world as W


@pytest.fixture(scope="module")
def cfg():
    import dataclasses
    c = tiny_config()
    # Frontier at the same resolution the fleet model uses.
    return c


@pytest.fixture(scope="module")
def arena(cfg):
    # 6.4 m arena at map resolution (walls at +-3.2 m).
    return jnp.asarray(W.empty_arena(128, cfg.grid.resolution_m))


@pytest.fixture(scope="module")
def small_arena(cfg):
    # 4.8 m arena: walls at +-2.4 m, inside the tiny config's 3 m range.
    return jnp.asarray(W.empty_arena(96, cfg.grid.resolution_m))


def test_simulated_scan_matches_oracle(cfg, arena):
    from tests.oracle import raycast_scan_np
    s = cfg.scan
    pose = np.array([0.3, -0.2, 0.5], np.float32)
    got = np.asarray(lidar.simulate_scan(s, arena, cfg.grid.resolution_m,
                                         256, jnp.asarray(pose)))
    want = raycast_scan_np(np.asarray(arena), pose, s.n_beams,
                           s.angle_increment_rad, s.range_max_m,
                           cfg.grid.resolution_m)
    live = want[:s.n_beams] > 0
    err = np.abs(got[:s.n_beams][live] - want[:s.n_beams][live])
    assert np.median(err) < 0.06          # within a cell-ish
    assert (got[s.n_beams:] == 0).all()   # padded tail silent


def test_ir_proximity_scales(cfg, arena):
    res = cfg.grid.resolution_m
    # Robot facing the east wall from ~5 cm away: strong IR response.
    wall_x = (64 - 2) * res
    near = jnp.asarray(np.array([[wall_x - 0.05, 0.0, 0.0]], np.float32))
    far = jnp.asarray(np.array([[0.0, 0.0, 0.0]], np.float32))
    p_near = np.asarray(lidar.ir_proximity(arena, res, near))
    p_far = np.asarray(lidar.ir_proximity(arena, res, far))
    assert p_near.max() > 2000            # above IR_THRESHOLD territory
    assert p_far.max() == 0.0


def test_subsumption_policy_layers(cfg):
    s, r = cfg.scan, cfg.robot
    R = 4
    ranges = np.full((R, s.padded_beams), 5.0, np.float32)
    prox = np.zeros((R, 5), np.float32)
    exploring = np.array([True, True, True, False])
    # Robot 1: IR emergency on the left side -> pivot right.
    prox[1, 0] = 3000
    # Robot 2: obstacle in the left LiDAR cone -> swerve right.
    ranges[2, 5] = 0.1
    out = E.subsumption_policy(r, s, jnp.asarray(ranges), jnp.asarray(prox),
                               jnp.asarray(exploring))
    t = np.asarray(out.targets)
    st = np.asarray(out.state)
    assert st.tolist() == [1, 2, 3, 0]
    np.testing.assert_array_equal(t[0], [r.cruise_speed_units] * 2)  # cruise
    assert t[1, 0] == r.rotation_speed_units and t[1, 1] == -r.rotation_speed_units
    assert t[2, 0] == r.cruise_speed_units and t[2, 1] == r.swerve_inner_units
    np.testing.assert_array_equal(t[3], [0, 0])                      # stopped
    # LED protocol (reference colors).
    np.testing.assert_array_equal(np.asarray(out.led[3]), [0, 32, 0])
    np.testing.assert_array_equal(np.asarray(out.led[1]), [32, 0, 0])


def test_frontier_policy_steers_toward_goal(cfg):
    s, r = cfg.scan, cfg.robot
    ranges = np.full((2, s.padded_beams), 5.0, np.float32)
    prox = np.zeros((2, 5), np.float32)
    poses = jnp.asarray(np.array([[0, 0, 0], [0, 0, 0]], np.float32))
    goals = jnp.asarray(np.array([[1.0, 1.0], [1.0, -1.0]], np.float32))
    out = E.frontier_policy(r, s, poses, goals, jnp.array([True, True]),
                            jnp.asarray(ranges), jnp.asarray(prox),
                            jnp.ones(2, bool))
    t = np.asarray(out.targets)
    assert t[0, 1] > t[0, 0]   # goal up-left -> right wheel faster (turn left)
    assert t[1, 0] > t[1, 1]   # goal down-right -> turn right


def test_slam_step_runs_and_maps(cfg, small_arena):
    arena = small_arena
    state = SM.init_state(cfg)
    res_m = cfg.grid.resolution_m
    key_count = 0
    for t in range(12):
        pose_t = state.pose
        scan = lidar.simulate_scan(cfg.scan, arena, res_m, 256, pose_t)
        state, diag = SM.slam_step(cfg, state, scan,
                                   jnp.float32(120.0), jnp.float32(150.0),
                                   jnp.float32(0.3))
        key_count += int(diag.key_added)
    assert key_count >= 2
    assert int(state.n_keyscans) == key_count
    occ = np.asarray(G.to_occupancy(cfg.grid, state.grid))
    assert (occ == 100).sum() > 50        # walls appeared
    assert (occ == 0).sum() > 200         # free space carved
    assert np.isfinite(np.asarray(state.pose)).all()


def test_fleet_step_explores(cfg, small_arena):
    arena = small_arena
    import dataclasses
    c = dataclasses.replace(cfg, fleet=dataclasses.replace(
        cfg.fleet, n_robots=4))
    state = FM.init_fleet_state(c, jax.random.PRNGKey(0))
    res_m = c.grid.resolution_m
    for t in range(8):
        state, diag = FM.fleet_step(c, state, res_m, arena)
    assert int(state.t) == 8
    # Map has content; robots stayed in the arena; estimates track truth.
    occ = np.asarray(G.to_occupancy(c.grid, state.grid))
    assert (occ == 100).sum() > 30
    tp = np.asarray(state.sim.poses)
    assert (np.abs(tp[:, :2]) < 3.2).all()
    assert np.asarray(diag.pose_err).max() < 0.3
