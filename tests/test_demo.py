"""Demo CLI end-to-end: the operator workflow in one command."""


from jax_mapping import demo


def test_demo_save_and_resume_cli(tmp_path, capsys):
    """--save-final writes a checkpoint a later --resume run continues
    from (the reference loses its map on restart; SURVEY.md §5)."""
    ck = str(tmp_path / "ck.npz")
    rc = demo.main(["--steps", "16", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--save-final", ck])
    assert rc == 0
    first = capsys.readouterr()
    occ1 = _cells_occupied(first.out)
    assert occ1 > 0

    rc = demo.main(["--steps", "2", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--resume", ck])
    assert rc == 0
    second = capsys.readouterr()
    assert "resumed 1 robot state(s)" in second.err
    # A 2-step run starting from the checkpoint keeps the inherited map:
    # at least as many cells known as the 16-step run that produced it.
    assert _cells_occupied(second.out) >= occ1


def _cells_occupied(out: str) -> int:
    import json
    start = out.index("{\n")
    return json.loads(out[start:])["cells_occupied"]


def test_demo_resume_friendly_errors(tmp_path, capsys):
    """Missing or mismatched checkpoints exit 2 with a message, not a
    traceback."""
    rc = demo.main(["--steps", "1", "--world", "arena", "--world-cells",
                    "96", "--resume", str(tmp_path / "nope.npz")])
    assert rc == 2
    assert "no checkpoint" in capsys.readouterr().err

    ck = str(tmp_path / "one.npz")
    rc = demo.main(["--steps", "1", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--save-final", ck])
    assert rc == 0
    capsys.readouterr()
    rc = demo.main(["--steps", "1", "--robots", "2", "--world", "arena",
                    "--world-cells", "96", "--resume", ck])
    assert rc == 2
    assert "cannot resume" in capsys.readouterr().err


def test_demo_record_then_replay(tmp_path, capsys):
    """--record writes a bag that --replay maps from WITHOUT the sim —
    the rosbag workflow of SURVEY.md §7 item 7."""
    import json
    bag = str(tmp_path / "run.npz")
    rc = demo.main(["--steps", "20", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--record", bag])
    assert rc == 0
    first = capsys.readouterr()
    assert "recorded" in first.err
    occ1 = _cells_occupied(first.out)
    assert occ1 > 0

    rc = demo.main(["--robots", "1", "--replay", bag])
    assert rc == 0
    out = capsys.readouterr().out
    body = json.loads(out[out.index("{\n"):])
    assert body["replayed"] > 0
    assert body["scans_fused"] > 0
    # Mapping from the bag reproduces the walls the live run saw.
    assert body["cells_occupied"] > 0.5 * occ1


def test_demo_replay_flag_and_topic_guards(tmp_path, capsys):
    """--replay rejects conflicting flags and robot-count-mismatched bags
    with exit 2, not silent empty maps."""
    bag = str(tmp_path / "two.npz")
    rc = demo.main(["--steps", "8", "--robots", "2", "--world", "arena",
                    "--world-cells", "96", "--record", bag])
    assert rc == 0
    capsys.readouterr()

    rc = demo.main(["--replay", bag, "--serve"])
    assert rc == 2
    assert "--serve" in capsys.readouterr().err

    rc = demo.main(["--robots", "1", "--replay", bag])
    assert rc == 2
    assert "different --robots" in capsys.readouterr().err


def test_demo_replay_rejects_config_drift(tmp_path, capsys):
    """A bag recorded under one config replayed under another exits 2
    (the bag stores the recording config; v2 trace format)."""
    bag = str(tmp_path / "drift.npz")
    rc = demo.main(["--steps", "8", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--record", bag])
    assert rc == 0
    capsys.readouterr()

    import json

    from jax_mapping.config import tiny_config
    other = tiny_config(n_robots=1)
    import dataclasses
    other = dataclasses.replace(
        other, matcher=dataclasses.replace(other.matcher, min_response=0.42))
    cfgfile = tmp_path / "other.json"
    cfgfile.write_text(other.to_json())
    rc = demo.main(["--robots", "1", "--replay", bag,
                    "--config", str(cfgfile)])
    assert rc == 2
    assert "different config" in capsys.readouterr().err


def test_demo_replay_is_deterministic(tmp_path, capsys):
    """Replaying the same bag twice produces bitwise-identical maps —
    the jit'd pipeline plus the interleaved replay schedule is fully
    deterministic (no wall-clock or thread-order dependence)."""
    import json
    bag = str(tmp_path / "det.npz")
    rc = demo.main(["--steps", "14", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--record", bag])
    assert rc == 0
    capsys.readouterr()

    outs = []
    for i in range(2):
        png = str(tmp_path / f"replay{i}.png")
        rc = demo.main(["--robots", "1", "--replay", bag, "--out", png])
        assert rc == 0
        out = capsys.readouterr().out
        outs.append(json.loads(out[out.index("{\n"):]))
    assert outs[0] == {**outs[1], "bag": outs[0]["bag"]}
    a = (tmp_path / "replay0.png").read_bytes()
    b = (tmp_path / "replay1.png").read_bytes()
    assert a == b
