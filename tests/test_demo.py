"""Demo CLI end-to-end: the operator workflow in one command."""


from jax_mapping import demo


def test_demo_save_and_resume_cli(tmp_path, capsys):
    """--save-final writes a checkpoint a later --resume run continues
    from (the reference loses its map on restart; SURVEY.md §5)."""
    ck = str(tmp_path / "ck.npz")
    rc = demo.main(["--steps", "16", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--save-final", ck])
    assert rc == 0
    first = capsys.readouterr()
    occ1 = _cells_occupied(first.out)
    assert occ1 > 0

    rc = demo.main(["--steps", "2", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--resume", ck])
    assert rc == 0
    second = capsys.readouterr()
    assert "resumed 1 robot state(s)" in second.err
    # A 2-step run starting from the checkpoint keeps the inherited map:
    # at least as many cells known as the 16-step run that produced it.
    assert _cells_occupied(second.out) >= occ1


def _cells_occupied(out: str) -> int:
    import json
    start = out.index("{\n")
    return json.loads(out[start:])["cells_occupied"]


def test_demo_resume_friendly_errors(tmp_path, capsys):
    """Missing or mismatched checkpoints exit 2 with a message, not a
    traceback."""
    rc = demo.main(["--steps", "1", "--world", "arena", "--world-cells",
                    "96", "--resume", str(tmp_path / "nope.npz")])
    assert rc == 2
    assert "no checkpoint" in capsys.readouterr().err

    ck = str(tmp_path / "one.npz")
    rc = demo.main(["--steps", "1", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--save-final", ck])
    assert rc == 0
    capsys.readouterr()
    rc = demo.main(["--steps", "1", "--robots", "2", "--world", "arena",
                    "--world-cells", "96", "--resume", ck])
    assert rc == 2
    assert "cannot resume" in capsys.readouterr().err
