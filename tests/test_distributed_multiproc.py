"""True multi-process DCN integration: two OS processes, jax.distributed
over localhost, running (1) the fleet map-merge psum, (2) the FULL
sharded fleet step — slab-delta psum merge, coarse-mask all_gather,
matching, fusion, graphs — and (3) the sharded 3D voxel fusion, each
with the fleet mesh axis genuinely spanning the process boundary (Gloo
CPU backend). Phase 3 additionally pins exact parity against the
single-device patch path on every locally-addressable slab.

The reference's distributed operation is two hosts over DDS
(`/root/reference/README.md:78-86`); this is the XLA-collective
equivalent actually exercised across processes, not just a
single-process virtual mesh (which `__graft_entry__.dryrun_multichip`
already covers).
"""

import os
import socket
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_two_process_fleet_psum():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    worker = os.path.join(os.path.dirname(__file__), "_dist_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "TPU_", "AXON"))}
    env["PYTHONPATH"] = repo
    # A fresh env also drops the re-exec marker so workers stand alone.
    env.pop("_JAX_MAPPING_REEXEC", None)

    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2000:]}"
        assert f"DIST_OK proc {i}" in out
