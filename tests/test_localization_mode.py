"""SlamConfig.mode = "localization": the frozen-map operating mode.

slam_toolbox's config file selects mapping vs localization
(`slam_config.yaml:20` ships "mapping"); the reference only ever mapped.
This framework's localization mode freezes the map — key scans MATCH for
pose tracking, nothing fuses, the graph never grows, closures never fire
— pairing with an imported prior (--map-prior) for
localize-on-a-known-map.
"""

import dataclasses
import math

import numpy as np
import jax.numpy as jnp
import pytest

from jax_mapping.config import configs_equivalent, tiny_config
from jax_mapping.models import slam as S
from jax_mapping.sim import lidar


def _loc_cfg(tiny_cfg):
    return dataclasses.replace(tiny_cfg, mode="localization")


def test_unknown_mode_refused(tiny_cfg):
    bad = dataclasses.replace(tiny_cfg, mode="slam")
    st = S.init_state(bad)
    with pytest.raises(ValueError, match="mode"):
        S.slam_step(bad, st, jnp.zeros(bad.scan.padded_beams),
                    jnp.float32(0), jnp.float32(0), jnp.float32(0.1))


def test_mode_not_config_drift(tiny_cfg):
    """A checkpoint mapped in mapping mode must load under localization:
    map a site, then localize on it is the feature's core flow."""
    a = tiny_cfg.to_json()
    b = _loc_cfg(tiny_cfg).to_json()
    assert configs_equivalent(a, b)
    # Real drift still refuses.
    c = dataclasses.replace(
        tiny_cfg, grid=dataclasses.replace(tiny_cfg.grid,
                                           size_cells=128)).to_json()
    assert not configs_equivalent(a, c)


def test_localization_freezes_map_and_tracks(tiny_cfg):
    """Drive a robot with biased odometry over a PRIOR map: the grid
    stays bitwise frozen (no fusion, no graph growth, no closures) while
    the matcher keeps the pose estimate near truth — the mapping-mode
    estimate without corrections would drift away."""
    from jax_mapping.sim import world as W

    cfg = _loc_cfg(tiny_cfg)
    res = cfg.grid.resolution_m
    world = np.asarray(W.rooms_world(128, res, seed=4), bool)
    world_j = jnp.asarray(world)
    n = cfg.grid.size_cells

    # The prior: the true world rasterized as log-odds (what --map-prior
    # seeding produces after a good mapping session).
    prior = np.zeros((n, n), np.float32)
    c0 = (n - 128) // 2
    prior[c0:c0 + 128, c0:c0 + 128] = np.where(world, 2.0, -2.0)
    st = S.init_state(cfg)._replace(grid=jnp.asarray(prior))
    grid0 = st.grid

    n_samples = int(cfg.scan.range_max_m / (res * 0.5))
    v, dt = 0.25, 0.1
    from jax_mapping.ops.odometry import twist_to_wheel_units
    wl, wr = twist_to_wheel_units(cfg.robot, v, 0.0)
    true_pose = np.array([0.0, 0.0, 0.0])
    bias = 6.0                                 # wheel-units bias
    k = cfg.robot.speed_coeff_m_per_unit_s
    for _ in range(60):
        vl, vr = wl * k, wr * k
        v_lin = (vl + vr) / 2
        v_ang = (vr - vl) / cfg.robot.wheel_base_m
        mid = true_pose[2] + v_ang * dt / 2
        true_pose = true_pose + np.array(
            [v_lin * math.cos(mid) * dt, v_lin * math.sin(mid) * dt,
             v_ang * dt])
        scan = lidar.simulate_scans(cfg.scan, world_j, res, n_samples,
                                    jnp.asarray(true_pose)[None])[0]
        st, diag = S.slam_step(cfg, st, scan, jnp.float32(wl + bias),
                               jnp.float32(wr), jnp.float32(dt))

    assert st.grid is grid0 or bool((st.grid == grid0).all()), \
        "localization mode mutated the frozen map"
    assert int(st.graph.n_poses) == 0, "graph grew in localization mode"
    assert int(st.n_loops) == 0
    err = np.linalg.norm(np.asarray(st.pose)[:2] - true_pose[:2])
    assert err < 0.15, f"localized pose drifted {err:.2f} m from truth"
    # The same biased drive with matching disabled drifts further —
    # proof the matcher (not luck) kept the estimate close.
    odo = np.array([0.0, 0.0, 0.0])
    tp = np.array([0.0, 0.0, 0.0])
    for _ in range(60):
        for pose, (l, r) in ((odo, (wl + bias, wr)), (tp, (wl, wr))):
            vl, vr = l * k, r * k
            v_lin = (vl + vr) / 2
            v_ang = (vr - vl) / cfg.robot.wheel_base_m
            mid = pose[2] + v_ang * dt / 2
            pose += np.array([v_lin * math.cos(mid) * dt,
                              v_lin * math.sin(mid) * dt, v_ang * dt])
    odo_err = np.linalg.norm(odo[:2] - tp[:2])
    assert err < odo_err * 0.7, (
        f"matcher did not beat raw odometry ({err:.3f} vs {odo_err:.3f})")


def test_localization_depth_anchor_still_corrects(tiny_cfg):
    """Localization + depth cam: the graph never grows, but
    depth_anchor must still hand the 3D mapper the live map->odom
    correction (node_idx -1, keyframes skipped) — or the voxel map
    would shear off the frozen 2D map at raw odometry."""
    import dataclasses as _dc

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    cfg = _dc.replace(tiny_cfg, mode="localization")
    world = W.empty_arena(96, cfg.grid.resolution_m)
    st = launch_sim_stack(cfg, world, n_robots=1, http_port=None,
                          seed=14, depth_cam=True)
    try:
        n = cfg.grid.size_cells
        st.mapper.seed_map_prior(np.full((n, n), -2.0, np.float32))
        st.brain.start_exploring()
        st.run_steps(25)
        anchor = st.mapper.depth_anchor(0)
        assert anchor is not None, \
            "no correction anchor in localization mode"
        assert anchor[3] == -1                   # no node to anchor to
        assert st.voxel_mapper.n_images_fused > 0
        assert st.voxel_mapper.n_keyframes_stored == 0, \
            "keyframes stored with no graph to anchor them"
    finally:
        st.shutdown()


def test_demo_localization_cli(tmp_path, capsys):
    """Operator flow: --localization + --map-prior boots, runs, and the
    saved checkpoint still carries the (frozen) map."""
    from jax_mapping import demo
    from jax_mapping.io import rosmap

    occ = np.full((32, 32), 0, np.int8)
    occ[0, :] = 100
    _pgm, yaml = rosmap.save_map(str(tmp_path / "prior"), occ, 0.05,
                                 (-0.8, -0.8))
    rc = demo.main(["--steps", "4", "--robots", "1", "--world", "arena",
                    "--world-cells", "96", "--localization",
                    "--map-prior", yaml])
    assert rc == 0
    out = capsys.readouterr().out
    assert "seeded map prior" in out


def test_stack_publishes_pose_covariance(tiny_cfg):
    """After real matches, the mapper's /pose dicts carry the last
    accepted match's covariance diag (finite, positive)."""
    import dataclasses as _dc

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    cfg = _dc.replace(
        tiny_cfg, planner=_dc.replace(tiny_cfg.planner, enabled=False))
    world = W.empty_arena(96, cfg.grid.resolution_m)
    st = launch_sim_stack(cfg, world, n_robots=1, http_port=None, seed=15)
    try:
        poses_msgs = []
        st.bus.subscribe("/pose", callback=poses_msgs.append)
        st.brain.start_exploring()
        st.run_steps(40)
        with_cov = [m for m in poses_msgs if m and m[0].get("cov")]
        assert with_cov, "no /pose ever carried a covariance"
        cov = with_cov[-1][0]["cov"]
        assert len(cov) == 3
        assert all(np.isfinite(c) and c > 0 for c in cov)
    finally:
        st.shutdown()


def test_fleet_step_localization_freezes_map(tiny_cfg):
    """The batch fleet model honours the mode too: matched corrections
    stand, the shared grid stays bitwise frozen, graphs never grow."""
    import jax

    from jax_mapping.models import fleet as FM
    from jax_mapping.sim import world as W

    cfg = dataclasses.replace(
        _loc_cfg(tiny_cfg),
        fleet=dataclasses.replace(tiny_cfg.fleet, n_robots=4))
    world = jnp.asarray(W.empty_arena(96, cfg.grid.resolution_m))
    state = FM.init_fleet_state(cfg, jax.random.PRNGKey(2))
    prior = jnp.where(world, 2.0, -2.0)
    n = cfg.grid.size_cells
    c0 = (n - 96) // 2
    full = jnp.zeros((n, n)).at[c0:c0 + 96, c0:c0 + 96].set(prior)
    state = state._replace(grid=full)
    grid0 = state.grid
    for _ in range(5):
        state, diag = FM.fleet_step(cfg, state, cfg.grid.resolution_m,
                                    world)
    assert bool((state.grid == grid0).all()), "fleet grid mutated"
    assert int(np.asarray(state.graphs.n_poses).sum()) == 0
    assert int(np.asarray(state.n_loops).sum()) == 0
    assert np.isfinite(np.asarray(diag.pose_err)).all()


def test_sharded_fleet_step_localization(tiny_cfg):
    """The sharded twin compiles and runs frozen across the virtual
    8-device mesh (the skipped fuse/closure psums vanish uniformly)."""
    import jax

    from jax_mapping.parallel import fleet_sharded as FS
    from jax_mapping.parallel import mesh as MESH
    from jax_mapping.sim import world as W

    cfg = dataclasses.replace(
        _loc_cfg(tiny_cfg),
        fleet=dataclasses.replace(tiny_cfg.fleet, n_robots=8))
    assert len(jax.devices()) == 8
    mesh = MESH.make_mesh(n_fleet=4, n_space=2)
    world = jnp.asarray(W.empty_arena(96, cfg.grid.resolution_m))
    state = FS.init_sharded_state(cfg, mesh)
    grid0 = np.asarray(jax.device_get(state.grid)).copy()
    step = FS.make_fleet_step(cfg, mesh, cfg.grid.resolution_m)
    for _ in range(3):
        state, metrics = step(state, world)
    assert int(state.t) == 3
    assert (np.asarray(jax.device_get(state.grid)) == grid0).all(), \
        "sharded grid mutated in localization mode"
    assert np.isfinite(float(metrics["mean_pose_err_m"]))
