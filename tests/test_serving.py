"""Serving subsystem tests: tiled delta distribution, revision-keyed
caching, fan-out push, and the serving load generator.

The load-bearing assertions:

* DELTA CORRECTNESS — a client that applies an initial snapshot plus
  every tile delta reconstructs the mapper's LIVE grid bit-for-bit.
* NO STALE TILE EVER — under 8+ concurrent threads mixing /map-image,
  /tiles?since= and /map-events while the stack runs, every client's
  revision is monotonic and no returned tile is stamped at or before
  the client's `since` (DeltaMapClient raises on either violation).
* BOUNDED BACKPRESSURE — a slow /map-events client's queue stays at
  its configured depth, dropping oldest (drop-to-latest).
* `ServingConfig(enabled=False)` runs are bit-identical to serving-on
  runs (the subsystem observes the mapper; it never perturbs it).
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from jax_mapping.bridge.launch import launch_sim_stack
from jax_mapping.config import ServingConfig, tiny_config
from jax_mapping.serving.client import DeltaMapClient
from jax_mapping.serving.events import EventChannel
from jax_mapping.serving.tiles import TileStore
from jax_mapping.sim import world as W


# ------------------------------------------------------------------ units

def test_tile_hashes_change_iff_content_changes():
    import jax.numpy as jnp
    from jax_mapping.ops import grid as G
    img = np.full((256, 256), 127, np.uint8)
    h0 = np.asarray(G.tile_hashes(jnp.asarray(img), 64))
    assert h0.shape == (4, 4, 2)
    img2 = img.copy()
    img2[70, 130] = 0                     # tile (1, 2)
    h1 = np.asarray(G.tile_hashes(jnp.asarray(img2), 64))
    changed = np.argwhere(np.any(h0 != h1, axis=-1))
    assert changed.tolist() == [[1, 2]]


def test_tile_hashes_float_is_bit_exact():
    """Float grids hash their BIT PATTERNS: a sub-epsilon log-odds
    change still changes the hash (no stale tile can hide behind a
    rounding threshold)."""
    import jax.numpy as jnp
    from jax_mapping.ops import grid as G
    lo = np.zeros((128, 128), np.float32)
    h0 = np.asarray(G.tile_hashes(jnp.asarray(lo), 64))
    lo2 = lo.copy()
    lo2[3, 3] = 1e-7
    h1 = np.asarray(G.tile_hashes(jnp.asarray(lo2), 64))
    assert np.any(h0 != h1)


def test_downsample_gray_priority():
    """Occupied (0) beats free (255) beats unknown (127) per block."""
    from jax_mapping.ops import grid as G
    img = np.full((4, 4), 127, np.uint8)
    img[0, 0] = 0                          # block (0,0): occ + unknown
    img[0, 2] = 255                        # block (0,1): free + unknown
    out = np.asarray(G.downsample_gray(img))
    assert out.tolist() == [[0, 255], [127, 127]]
    img[0, 1] = 255                        # occ + free in one block
    out = np.asarray(G.downsample_gray(img))
    assert out[0, 0] == 0                  # occupied still wins


def test_tile_store_delta_and_pyramid():
    cfg = ServingConfig(tile_cells=64, pyramid_levels=3)
    state = {"rev": 0, "img": np.full((256, 256), 127, np.uint8)}
    store = TileStore(cfg, "grid", lambda: state["rev"],
                      lambda: (state["rev"], state["img"], None))
    store.refresh()
    rev, entries, meta = store.tiles_since(-1)
    # 4x4 level 0 + 2x2 level 1 + 1 level 2.
    assert rev == 0 and len(entries) == 16 + 4 + 1
    assert [lv["size_cells"] for lv in meta["levels"]] == [256, 128, 64]

    # One touched region -> one tile per level, nothing else re-sent.
    state["img"] = state["img"].copy()
    state["img"][10:20, 70:80] = 0         # level-0 tile (0, 1)
    state["rev"] = 7
    store.refresh()
    rev, entries, _ = store.tiles_since(0)
    assert rev == 7
    assert [(e["level"], e["ty"], e["tx"]) for e in entries] == \
        [(0, 0, 1), (1, 0, 0), (2, 0, 0)]
    assert all(e["revision"] == 7 for e in entries)

    # Revision bump with identical content: hash dedupe, no new tiles.
    state["rev"] = 9
    store.refresh()
    _, entries, _ = store.tiles_since(7)
    assert entries == []
    assert store.stats()["n_tiles_clean_skipped"] > 0


def test_tile_hashes_rectangular():
    import jax.numpy as jnp
    from jax_mapping.ops import grid as G
    img = np.zeros((128, 256), np.uint8)
    h = np.asarray(G.tile_hashes(jnp.asarray(img), 64))
    assert h.shape == (2, 4, 2)


def test_voxel_store_gated_on_square_geometry():
    """A rectangular (or tile-indivisible) voxel grid must leave
    /voxel-tiles dark (no store -> 404), never 500 per request."""
    from jax_mapping.config import VoxelConfig
    from jax_mapping.serving.tiles import MapServing
    cfg = ServingConfig(tile_cells=64)
    assert MapServing._voxel_servable(
        cfg, VoxelConfig(size_x_cells=128, size_y_cells=128))
    assert not MapServing._voxel_servable(
        cfg, VoxelConfig(size_x_cells=256, size_y_cells=128))
    assert not MapServing._voxel_servable(
        cfg, VoxelConfig(size_x_cells=96, size_y_cells=96))


def test_event_channel_drop_counter_survives_disconnect():
    """The exported drop counter is Prometheus-monotonic: a slow
    client's drops fold into the channel total when it disconnects
    instead of vanishing with its queue."""
    ch = EventChannel(depth=1)
    sub = ch.subscribe()
    for rev in range(4):
        ch.emit({"revision": rev})
    assert ch.n_dropped_total() == 3
    ch.unsubscribe(sub)
    assert ch.n_dropped_total() == 3


def test_event_channel_drop_to_latest():
    ch = EventChannel(depth=2)
    sub = ch.subscribe()
    for rev in range(5):
        ch.emit({"revision": rev})
    assert sub.pending() == 2
    assert sub.n_dropped == 3
    # Oldest dropped: the two NEWEST events survive.
    assert sub.next(0.1)["revision"] == 3
    assert sub.next(0.1)["revision"] == 4
    assert sub.next(0.05) is None          # bounded wait, no event
    ch.unsubscribe(sub)
    assert ch.n_clients() == 0


# ------------------------------------------------------------------ stack

@pytest.fixture(scope="module")
def stack(tiny_cfg):
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=3)
    st = launch_sim_stack(tiny_cfg, world, n_robots=2, http_port=0,
                          realtime=False)
    st.brain.start_exploring()
    st.run_steps(20)
    st.mapper.publish_map()
    yield st
    st.shutdown()


def _expected_gray(st):
    from jax_mapping.ops import grid as G
    return np.asarray(G.to_gray(st.cfg.grid, st.mapper.merged_grid()))


def test_delta_reconstruction_bit_equality(stack):
    """THE delta-correctness proof: initial snapshot + applied tile
    deltas == the mapper's live grid, bit for bit."""
    base = f"http://127.0.0.1:{stack.api.port}"
    client = DeltaMapClient(base)
    client.poll()                          # full snapshot
    assert client.revision >= 0 and client.n_tiles_applied > 0
    for _ in range(4):                     # steady exploration + deltas
        stack.run_steps(10)
        client.poll()
    stack.run_steps(5)
    client.poll()                          # final sync, stack quiescent
    expect = _expected_gray(stack)
    assert np.array_equal(client.image(0), expect)
    # The mapper's patch-extent dirty marks were a true superset of
    # every hash-detected change (the hint never missed).
    assert stack.api.serving.map_store.stats()["n_hint_missed"] == 0


def test_pyramid_levels_consistent(stack):
    """Overview tiles must be the deterministic downsample of level 0
    (a zoomed-out client sees the same world, coarser)."""
    from jax_mapping.ops import grid as G
    base = f"http://127.0.0.1:{stack.api.port}"
    client = DeltaMapClient(base)
    client.poll()
    lvl1 = np.asarray(G.downsample_gray(client.image(0)))
    assert np.array_equal(client.image(1), lvl1)
    lvl2 = np.asarray(G.downsample_gray(lvl1))
    assert np.array_equal(client.image(2), lvl2)


def test_tiles_etag_304_and_client_dedupe(stack):
    base = f"http://127.0.0.1:{stack.api.port}"
    client = DeltaMapClient(base)
    client.poll()
    # No steps in between: the replayed ETag answers 304, zero body.
    before = client.bytes_received
    body = client.poll()
    assert body.get("not_modified") is True
    assert client.n_not_modified == 1
    assert client.bytes_received == before


def test_map_image_etag_304(stack):
    stack.mapper.publish_map()
    base = f"http://127.0.0.1:{stack.api.port}"
    with urllib.request.urlopen(f"{base}/map-image", timeout=5) as r:
        etag = r.headers["ETag"]
        assert len(r.read()) > 0
    req = urllib.request.Request(f"{base}/map-image",
                                 headers={"If-None-Match": etag})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 304
    assert ei.value.read() == b""
    # A stale tag still gets the full body.
    req = urllib.request.Request(f"{base}/map-image",
                                 headers={"If-None-Match": 'W/"map-0"'})
    with urllib.request.urlopen(req, timeout=5) as r:
        assert len(r.read()) > 0


def test_map_events_long_poll(stack):
    base = f"http://127.0.0.1:{stack.api.port}"
    rev = stack.mapper.serving_revision()
    # Already-advanced revision answers immediately.
    with urllib.request.urlopen(
            f"{base}/map-events?mode=poll&since=-1", timeout=5) as r:
        body = json.loads(r.read())
    assert body["revision"] == rev and not body["timed_out"]
    # Waiting poll released by a revision advance.
    out = {}

    def waiter():
        with urllib.request.urlopen(
                f"{base}/map-events?mode=poll&since={rev}&wait_s=5",
                timeout=10) as r:
            out.update(json.loads(r.read()))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    stack.run_steps(5)
    t.join(timeout=10)
    assert not t.is_alive()
    assert out["revision"] > rev and not out["timed_out"]


def test_map_events_sse_stream(stack):
    base = f"http://127.0.0.1:{stack.api.port}"
    since = stack.mapper.serving_revision()
    revisions = []

    def reader():
        req = urllib.request.Request(
            f"{base}/map-events?since={since}&timeout_s=4")
        with urllib.request.urlopen(req, timeout=10) as r:
            for line in r:
                if line.startswith(b"data:"):
                    revisions.append(
                        json.loads(line[5:].decode())["revision"])
                if len(revisions) >= 2:
                    break

    t = threading.Thread(target=reader)
    t.start()
    for _ in range(4):
        time.sleep(0.1)
        stack.run_steps(5)
    t.join(timeout=10)
    assert not t.is_alive()
    assert len(revisions) >= 2
    assert revisions == sorted(revisions)          # monotonic stream
    assert all(r > since for r in revisions)


def test_metrics_routes_and_latency_histogram(stack):
    base = f"http://127.0.0.1:{stack.api.port}"
    urllib.request.urlopen(f"{base}/status", timeout=5).read()
    with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
        text = r.read().decode()
    assert 'jax_mapping_http_requests_by_route_total{route="/status"}' \
        in text
    assert 'jax_mapping_http_request_seconds_bucket{le="+Inf"}' in text
    assert "jax_mapping_http_request_seconds_count" in text
    assert "jax_mapping_serving_grid_revision" in text
    assert "jax_mapping_serving_events_total" in text
    # Histogram consistency: +Inf cumulative count == _count.
    inf = count = None
    for line in text.splitlines():
        if line.startswith('jax_mapping_http_request_seconds_bucket'
                           '{le="+Inf"}'):
            inf = int(line.split()[-1])
        if line.startswith("jax_mapping_http_request_seconds_count"):
            count = int(line.split()[-1])
    assert inf == count and count > 0


def test_request_counters_thread_safe(stack):
    """500 requests across 10 threads count exactly 500 (the
    unsynchronized `n_requests += 1` of the pre-serving handler lost
    increments under this exact load)."""
    base = f"http://127.0.0.1:{stack.api.port}"
    before = stack.api.route_requests.get("/frontiers", 0)

    def worker():
        for _ in range(50):
            urllib.request.urlopen(f"{base}/frontiers", timeout=10).read()

    threads = [threading.Thread(target=worker) for _ in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert stack.api.route_requests.get("/frontiers", 0) == before + 500


def test_concurrent_hammer_no_stale_tiles(stack):
    """8+ threads mixing /map-image, /tiles?since= and /map-events
    while the stack explores: every delta client's revision stays
    monotonic with no stale tile (DeltaMapClient raises otherwise),
    event queues stay bounded, and polling keeps a cache hit-rate > 0."""
    base = f"http://127.0.0.1:{stack.api.port}"
    stop = threading.Event()
    errors = []

    def delta_worker():
        try:
            client = DeltaMapClient(base)
            while not stop.is_set():
                client.poll()
                stop.wait(0.03)
        except Exception as e:             # noqa: BLE001
            errors.append(f"delta: {type(e).__name__}: {e}")

    def png_worker():
        try:
            while not stop.is_set():
                with urllib.request.urlopen(f"{base}/map-image",
                                            timeout=10) as r:
                    r.read()
                stop.wait(0.03)
        except Exception as e:             # noqa: BLE001
            errors.append(f"png: {type(e).__name__}: {e}")

    def events_worker():
        try:
            while not stop.is_set():
                with urllib.request.urlopen(
                        f"{base}/map-events?mode=poll&since=-1&wait_s=1",
                        timeout=10) as r:
                    json.loads(r.read())
        except Exception as e:             # noqa: BLE001
            errors.append(f"events: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=delta_worker) for _ in range(3)] \
        + [threading.Thread(target=png_worker) for _ in range(3)] \
        + [threading.Thread(target=events_worker) for _ in range(2)]
    for t in threads:
        t.start()
    for _ in range(10):
        stack.run_steps(5)
        stack.mapper.publish_map()
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not [t for t in threads if t.is_alive()]
    assert errors == []
    # Every /map-events queue stayed within its configured bound.
    depth = stack.cfg.serving.event_queue_depth
    ch = stack.api.serving.events
    assert all(s.pending() <= depth for s in list(ch._subs))
    # Polling kept the PNG cache warm.
    assert stack.api.png_cache_hits.get("map", 0) > 0
    # Dirty hints stayed a superset of hash-detected changes throughout.
    assert stack.api.serving.map_store.stats()["n_hint_missed"] == 0


# ------------------------------------------------------ disabled / voxel

def test_serving_disabled_is_bit_identical_and_dark(tiny_cfg):
    """ServingConfig(enabled=False): /tiles and /map-events answer 404,
    no revision tracking runs, and the resulting MAP is bit-identical
    to a serving-enabled run of the same seed — serving observes the
    mapper, it never perturbs it."""
    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    cfg_off = dataclasses.replace(
        tiny_cfg,
        serving=dataclasses.replace(tiny_cfg.serving, enabled=False))
    grids = {}
    for key, cfg in (("on", tiny_cfg), ("off", cfg_off)):
        st = launch_sim_stack(cfg, world, n_robots=1, http_port=0,
                              realtime=False, seed=11)
        try:
            st.brain.start_exploring()
            st.run_steps(25)
            grids[key] = np.asarray(st.mapper.merged_grid())
            if key == "off":
                assert st.api.serving is None
                assert st.mapper.map_revision == 0
                base = f"http://127.0.0.1:{st.api.port}"
                for route in ("/tiles", "/map-events?mode=poll",
                              "/voxel-tiles"):
                    with pytest.raises(urllib.error.HTTPError) as ei:
                        urllib.request.urlopen(base + route, timeout=5)
                    assert ei.value.code == 404
            else:
                assert st.mapper.map_revision > 0
        finally:
            st.shutdown()
    assert np.array_equal(grids["on"], grids["off"])


def test_voxel_height_tiles_ride_the_same_store(tiny_cfg):
    """The 3D pipeline's height map serves through the identical
    TileStore + delta protocol on /voxel-tiles."""
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=5)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0,
                          realtime=False, depth_cam=True)
    try:
        st.brain.start_exploring()
        st.run_steps(15)
        base = f"http://127.0.0.1:{st.api.port}"
        client = DeltaMapClient(base, route="/voxel-tiles")
        client.poll()
        st.run_steps(10)
        client.poll()
        client.poll()                      # quiescent final sync
        _rev, expect = st.voxel_mapper.serving_snapshot()
        assert np.array_equal(client.image(0), expect)
        assert client.meta["map"] == "voxel-height"
    finally:
        st.shutdown()


# ------------------------------------------------------------- benchmark

def test_loadgen_smoke(tiny_cfg):
    """Tier-1-safe smoke of the serving benchmark: tiny grid, a few
    seconds, asserts the harness runs clean end-to-end and that the
    delta path is strictly cheaper than whole-PNG polling even at toy
    scale (the committed BENCH_SERVING artifact records the >= 10x
    production-shape figure; test_serving_benchmark_reduction below is
    the slow gate on it)."""
    from jax_mapping.serving.loadgen import run_serving_benchmark
    r = run_serving_benchmark(cfg=tiny_cfg, n_clients=4, duration_s=2.5,
                              warmup_steps=20, world_cells=80,
                              n_planks=4, n_robots=1)
    assert r["whole_png_polling"]["errors"] == []
    assert r["tiled_delta"]["errors"] == []
    assert r["whole_png_polling"]["polls"] > 0
    assert r["tiled_delta"]["polls"] > 0
    assert r["bytes_reduction_factor"] is not None
    assert r["bytes_reduction_factor"] > 1.0
    assert r["png_cache_hit_rate"] > 0


@pytest.mark.slow
def test_serving_benchmark_reduction_10x():
    """The acceptance gate at benchmark shape: >= 10x fewer bytes per
    client than whole-PNG polling during steady exploration."""
    from jax_mapping.serving.loadgen import run_serving_benchmark
    r = run_serving_benchmark(duration_s=12.0)
    assert r["whole_png_polling"]["errors"] == []
    assert r["tiled_delta"]["errors"] == []
    assert r["bytes_reduction_factor"] >= 10.0


def test_racewatch_clean_on_live_stack_with_serving(stack):
    """ISSUE 7 dynamic-tier gate: Eraser lockset refinement over a REAL
    serving stack — including the fan-out and SSE/long-poll threads
    lockwatch does not cover. The protection-map fields must end with
    NON-empty candidate locksets (zero race reports), and the serving
    state must actually have been exercised cross-thread (no vacuous
    pass). Lives here (not test_analysis_selfcheck.py) to reuse this
    module's already-launched stack — tier-1 wall-clock is budgeted
    against the 870 s timeout.

    The seeded-race counterpart (a guarded field written under the
    WRONG lock that racewatch MUST flag) is
    tests/test_analysis.py::test_racewatch_flags_write_under_wrong_lock.
    """
    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch

    by = groups_by_class()
    base = f"http://127.0.0.1:{stack.api.port}"
    watch = RaceWatch()
    try:
        watch.watch_object(stack.mapper, by["MapperNode"][0],
                           name="mapper")
        watch.watch_object(stack.api.serving.map_store,
                           by["TileStore"][0], name="grid-store")
        watch.watch_object(stack.api.serving.events,
                           by["EventChannel"][0], name="events")
        stop = threading.Event()
        errors = []

        def tile_poller():
            client = DeltaMapClient(base)
            while not stop.is_set():
                try:
                    client.poll()
                except Exception as e:           # noqa: BLE001
                    errors.append(f"poll: {e}")
                stop.wait(0.03)      # poll cadence; don't starve the GIL

        def sse_reader():
            try:
                req = urllib.request.Request(
                    f"{base}/map-events?since=-1&timeout_s=3")
                with urllib.request.urlopen(req, timeout=10) as r:
                    for line in r:
                        if stop.is_set():
                            break
                        if line.startswith(b"data:"):
                            json.loads(line[5:].decode())
            except Exception as e:               # noqa: BLE001
                errors.append(f"sse: {e}")

        def long_poller():
            while not stop.is_set():
                try:
                    urllib.request.urlopen(
                        f"{base}/map-events?mode=poll&since=-1&wait_s=0.2",
                        timeout=5).read()
                except Exception:                # noqa: BLE001
                    pass                         # shutdown races are fine
                stop.wait(0.03)

        threads = [threading.Thread(target=tile_poller),
                   threading.Thread(target=sse_reader),
                   threading.Thread(target=long_poller)]
        for t in threads:
            t.start()
        stack.run_steps(12)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        assert not any(t.is_alive() for t in threads)
    finally:
        watch.unwatch_all()

    assert not errors, errors
    reports = watch.reports()
    assert reports == [], "\n".join(r.message for r in reports)
    states = watch.field_states()
    # Not vacuous: serving state crossed threads (HTTP workers install
    # AND read the tile cache, the tick thread fans out events) and
    # refinement converged on the DECLARED locks.
    tiles = states["TileStore._tiles@grid-store"]
    assert tiles.state in ("shared", "shared-modified")
    assert tiles.candidate and \
        "TileStore._lock@grid-store" in tiles.candidate
    grid = states["MapperNode.shared_grid@mapper"]
    assert grid.state == "shared-modified"
    assert grid.candidate == \
        frozenset({"MapperNode._state_lock@mapper"})


def test_tile_store_typed_evicted_markers_and_client_prune():
    """ISSUE 18 satellite: the tile protocol's typed `evicted` markers
    — a windowed provider's 4th snapshot element turns level-0 tiles
    into byteless markers (cached bytes dropped, so no resync can
    serve a tile the window no longer backs), the client prunes them
    to unknown instead of raising, and re-entry re-encodes normally."""
    cfg = ServingConfig(tile_cells=64, pyramid_levels=1)
    img = np.full((256, 256), 127, np.uint8)
    img[:64, :64] = 0                        # content in tile (0, 0)
    state = {"rev": 0, "img": img, "ev": np.zeros((4, 4), bool)}
    store = TileStore(cfg, "grid", lambda: state["rev"],
                      lambda: (state["rev"], state["img"], None,
                               state["ev"]))
    store.refresh()
    rev0, entries0, meta0 = store.tiles_since(-1)
    assert not any(e.get("evicted") for e in entries0)
    assert meta0.get("evicted_tiles", 0) == 0
    client = DeltaMapClient("http://unused")
    client.apply({"revision": rev0, "since": -1, "tiles": entries0,
                  "tile_cells": 64, "levels": meta0["levels"]})
    assert (client.image()[:64, :64] == 0).all()

    # The window drops (0, 0): the provider paints it unknown and
    # flags it in the mask.
    img2 = img.copy()
    img2[:64, :64] = 127
    ev2 = np.zeros((4, 4), bool)
    ev2[0, 0] = True
    state.update(rev=5, img=img2, ev=ev2)
    store.refresh()
    rev1, entries1, meta1 = store.tiles_since(rev0)
    markers = [e for e in entries1 if e.get("evicted")]
    assert markers == [{"level": 0, "ty": 0, "tx": 0, "revision": 5,
                        "evicted": True}]
    assert meta1["evicted_tiles"] == 1
    assert store.evicted_epoch == 1
    assert store.stats()["n_tiles_evicted"] == 1
    # The cached bytes are GONE: a since=-1 resync serves the marker,
    # never stale content for the evicted slot.
    _, full, _ = store.tiles_since(-1)
    slot = [e for e in full
            if e["level"] == 0 and (e["ty"], e["tx"]) == (0, 0)]
    assert all(e.get("evicted") for e in slot) and slot

    before = client.n_tiles_pruned
    client.apply({"revision": rev1, "since": rev0, "tiles": entries1,
                  "tile_cells": 64, "levels": meta1["levels"]})
    assert client.n_tiles_pruned == before + 1
    assert (client.image()[:64, :64] == 127).all()

    # Re-entry: content returns, the marker clears, bytes flow again.
    img3 = img2.copy()
    img3[:64, :64] = 0
    state.update(rev=9, img=img3, ev=np.zeros((4, 4), bool))
    store.refresh()
    assert store.evicted_epoch == 2          # the flip BACK also bumps
    assert store.stats()["n_tiles_evicted"] == 0
    rev2, entries2, meta2 = store.tiles_since(rev1)
    assert not any(e.get("evicted") for e in entries2)
    assert any((e["ty"], e["tx"]) == (0, 0) and "png" in e
               for e in entries2 if e["level"] == 0)
    client.apply({"revision": rev2, "since": rev1, "tiles": entries2,
                  "tile_cells": 64, "levels": meta2["levels"]})
    assert (client.image()[:64, :64] == 0).all()
