"""SLAM-coupled 3D map: depth fuses at corrected poses and the voxel grid
re-fuses from the depth-keyframe ring after a loop closure, de-ghosting 3D
walls the way the 2D ring re-fusion de-ghosts 2D walls.

Bridge-level version of tests/test_loop_closure.py's acceptance drive: a
robot with a constant wheel-calibration bias drives a square loop through
featureless open space (pure dead-reckoning drift), returning to a plank
it depth-mapped at the start. Pre-closure the plank is ghosted in 3D
(fused once nearly drift-free, once displaced); the 2D wide loop search
closes, and the voxel re-fuse at optimized graph poses must collapse the
ghost.
"""

import dataclasses
import math

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.mapper import MapperNode
from jax_mapping.bridge.messages import (DepthImage, Header, LaserScan,
                                         Odometry, Pose2D, Twist)
from jax_mapping.bridge.voxel_mapper import VoxelMapperNode
from jax_mapping.ops import voxel as V
from jax_mapping.ops.odometry import twist_to_wheel_units
from jax_mapping.sim import depthcam as DC
from jax_mapping.sim import lidar
from tests.test_loop_closure import loop_cfg


def coupled_cfg(tiny_cfg):
    """The loop-drive config with a voxel grid big enough to hold the
    12.8 m course (the tiny 6.4 m grid ends before the walls), and a
    depth camera whose range (2.6 m) meets the plank BEFORE the 2D lidar
    (3.0 m) can close the loop — otherwise the corrected-pose fusion
    never ghosts and the re-fuse has nothing to prove. Patch grows to
    cover the wider trust horizon (coverage contract)."""
    cfg = loop_cfg(tiny_cfg)
    return dataclasses.replace(
        cfg,
        voxel=dataclasses.replace(cfg.voxel, size_x_cells=256,
                                  size_y_cells=256, max_range_m=2.6,
                                  patch_cells=128),
        depthcam=dataclasses.replace(cfg.depthcam, range_max_m=2.6))


def _build_world():
    """The test_loop_closure world: L-corner + north plank + stub."""
    world = np.zeros((256, 256), bool)

    def put(r0, r1, c0, c1):
        world[r0:r1, c0:c1] = True
    put(30, 32, 30, 70)
    put(30, 70, 30, 32)
    put(58, 60, 30, 52)     # the north plank the depth cam ghosts
    put(86, 89, 30, 37)
    return world


def _plank_band_rows(vox):
    """Voxel rows of the true plank (world rows 58..60 at 0.05 m,
    world centred like the voxel grid)."""
    _, oy, _ = vox.origin_m
    # world row r -> y = (r - 128) * 0.05; voxel row = (y - oy) / res
    y0 = (58 - 128) * 0.05
    y1 = (60 - 128) * 0.05
    r0 = int((y0 - oy) / vox.resolution_m)
    r1 = int(math.ceil((y1 - oy) / vox.resolution_m))
    return r0, r1


def _ghost_error(vox, grid, x_lo=-4.9, x_hi=-2.4):
    """Mean |row offset| (cells) of occupied voxel columns from the true
    plank rows, within the plank's x extent and a 24-cell neighbourhood —
    the 3D ghosting metric (0 = every wall voxel on the true plank)."""
    r0, r1 = _plank_band_rows(vox)
    occ = np.asarray(V.obstacle_slice(vox, grid, 0.06, 0.45))
    ox, _, _ = vox.origin_m
    c0 = int((x_lo - ox) / vox.resolution_m)
    c1 = int((x_hi - ox) / vox.resolution_m)
    band = occ[max(r0 - 24, 0):r1 + 24, c0:c1]
    rows, _ = np.nonzero(band)
    if len(rows) == 0:
        return None
    centre = (r0 + r1) / 2 - max(r0 - 24, 0)
    return float(np.abs(rows + 0.5 - centre).mean())


@pytest.mark.slow
def test_voxel_map_deghosts_on_loop_closure(tiny_cfg):
    cfg = coupled_cfg(tiny_cfg)
    world = _build_world()
    world_j = jnp.asarray(world)
    res = cfg.grid.resolution_m
    n_samples = int(cfg.scan.range_max_m / (res * 0.5))

    bus = Bus()
    mapper = MapperNode(cfg, bus, n_robots=1)
    voxel = VoxelMapperNode(cfg, bus, n_robots=1, mapper=mapper)
    scan_pub = bus.publisher("scan")
    odom_pub = bus.publisher("odom")
    depth_pub = bus.publisher("depth")

    start = np.array([-3.8, -3.8, 0.0])
    mapper.states[0] = mapper.states[0]._replace(
        pose=jnp.asarray(start, dtype=jnp.float32))

    v, w_turn, dt = 0.35, math.pi / 2, 0.1
    legs = [("fwd", 5.5), ("turn", 1.0), ("fwd", 5.5), ("turn", 1.0),
            ("fwd", 5.5), ("turn", 1.0), ("fwd", 4.9)]
    bias = 1.0
    k = cfg.robot.speed_coeff_m_per_unit_s

    true_pose = start.copy()
    odom_pose = start.copy()
    t = 0.0
    step = 0
    err_preclose = None
    for kind, amount in legs:
        n = int(round((amount / v if kind == "fwd" else amount) / dt))
        tv, tw = (v, 0.0) if kind == "fwd" else (0.0, w_turn)
        wl_t, wr_t = twist_to_wheel_units(cfg.robot, tv, tw)
        for _ in range(n):
            def integrate(pose, wl, wr):
                vl, vr = wl * k, wr * k
                v_lin = (vl + vr) / 2
                v_ang = (vr - vl) / cfg.robot.wheel_base_m
                mid = pose[2] + v_ang * dt / 2
                return pose + np.array([v_lin * math.cos(mid) * dt,
                                        v_lin * math.sin(mid) * dt,
                                        v_ang * dt])
            true_pose = integrate(true_pose, wl_t, wr_t)
            # The bridge sees BIASED odometry (left-wheel offset).
            odom_pose = integrate(odom_pose, wl_t + bias, wr_t)
            t += dt
            step += 1
            scan = np.asarray(lidar.simulate_scans(
                cfg.scan, world_j, res, n_samples,
                jnp.asarray(true_pose)[None])[0])
            odom_pub.publish(Odometry(
                header=Header(stamp=t, frame_id="odom"),
                pose=Pose2D(*odom_pose), twist=Twist()))
            scan_pub.publish(LaserScan(
                header=Header(stamp=t, frame_id="base_laser"),
                angle_increment=cfg.scan.angle_increment_rad,
                ranges=scan[:cfg.scan.n_beams]))
            if step % 3 == 0:       # depth at a third of the scan rate
                depth = np.asarray(DC.render_depth(
                    cfg.depthcam, world_j, res, n_samples,
                    jnp.asarray(true_pose)))
                depth_pub.publish(DepthImage(
                    header=Header(stamp=t, frame_id="base_camera"),
                    depth=depth))
            mapper.tick()
            # Between the 2D closure and the 3D re-fuse (voxel.tick sees
            # the closure next): the ghosted pre-repair 3D map.
            if err_preclose is None and mapper.n_loops_closed > 0:
                err_preclose = _ghost_error(cfg.voxel, voxel.voxel_grid())
            voxel.tick()

    assert mapper.n_loops_closed >= 1, "staging failed: no loop closed"
    assert voxel.n_keyframes_stored > 10, "keyframe ring never populated"
    assert voxel.n_refuses >= 1, "closure never triggered a 3D re-fuse"

    # Pre-closure the plank must actually have ghosted (else the test
    # proves nothing): the drift at loop end exceeds several cells.
    assert err_preclose is not None and err_preclose > 3.0, (
        f"staging failed: pre-closure ghost error {err_preclose} cells "
        "— drift never displaced the 3D plank")
    err_post = _ghost_error(cfg.voxel, voxel.voxel_grid())
    assert err_post is not None, "post-closure 3D map lost the plank"
    assert err_post < err_preclose / 2, (
        f"3D wall did not de-ghost: {err_preclose:.1f} -> "
        f"{err_post:.1f} cells")
    assert err_post < 3.0, f"post-closure ghost error {err_post:.1f} cells"


def test_corrected_pose_math(tiny_cfg):
    """The map->odom correction applied to a later odom sample equals
    composing the estimate with the odom-frame motion since the basis."""
    from jax_mapping.bridge.voxel_mapper import (_se2_between, _se2_compose)
    est = np.array([2.0, 1.0, 0.7], np.float32)
    odom_then = np.array([1.5, 0.5, 0.2], np.float32)
    # Robot moves 0.3 m forward in its own frame after the basis.
    fwd = np.array([0.3, 0.0, 0.0], np.float32)
    odom_now = _se2_compose(odom_then, fwd)
    got = _se2_compose(est, _se2_between(odom_then, odom_now))
    want = _se2_compose(est, fwd)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_standalone_voxel_mapper_unchanged(tiny_cfg):
    """mapper=None keeps the round-4 odom-frame behavior: images fuse at
    raw odometry, no keyframes, no refuses."""
    bus = Bus()
    vm = VoxelMapperNode(tiny_cfg, bus, n_robots=1)
    cam = tiny_cfg.depthcam
    od = bus.publisher("odom")
    dp = bus.publisher("depth")
    od.publish(Odometry(header=Header(stamp=1.0), pose=Pose2D(0, 0, 0)))
    dp.publish(DepthImage(header=Header(stamp=1.1),
                          depth=np.full((cam.height_px, cam.width_px), 0.8,
                                        np.float32)))
    vm.tick()
    assert vm.n_images_fused == 1
    assert vm.n_keyframes_stored == 0 and vm.n_refuses == 0


def test_keyframe_ring_survives_http_save_load(tiny_cfg, tmp_path):
    """/save writes the depth-keyframe ring as a .voxelkf sidecar and
    /load restores it (tagged with the live state generation), so the 3D
    closure repair capability survives a server restart — the 2D scan
    ring's checkpoint persistence, in 3D."""
    import json as _json
    import urllib.request

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=6)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0,
                          seed=6, depth_cam=True)
    try:
        st.api.checkpoint_dir = str(tmp_path)
        st.brain.start_exploring()
        st.run_steps(40)
        vm = st.voxel_mapper
        assert vm.n_keyframes_stored > 0, "staging: no keyframes captured"
        snap = vm.snapshot_keyframes()
        n_kf = len(snap["robot"])
        assert n_kf > 0

        url = f"http://127.0.0.1:{st.api.port}"
        body = _json.loads(urllib.request.urlopen(
            urllib.request.Request(url + "/save?name=kf", method="POST")
        ).read())
        assert body["keyframe_path"].endswith(".voxelkf.npz")

        # Wipe the live ring, then restore.
        vm.restore_grid(vm.snapshot_grid())     # clears keyframes
        assert sum(len(r) for r in vm._keyframes) == 0
        body = _json.loads(urllib.request.urlopen(
            urllib.request.Request(url + "/load?name=kf", method="POST")
        ).read())
        assert body["keyframes_restored"] == n_kf
        restored = vm.snapshot_keyframes()
        np.testing.assert_array_equal(restored["depths"], snap["depths"])
        np.testing.assert_array_equal(restored["node_idx"],
                                      snap["node_idx"])
        # Restored keyframes carry the LIVE generation (post-restore), so
        # the next closure re-fuse accepts them.
        gen = st.mapper.graph_snapshot(0)[0]
        assert all(kf.gen == gen for kf in vm._keyframes[0])
        # And the ring is actually usable: force a re-fuse and check the
        # rebuilt grid carries evidence.
        vm._refuse_from_keyframes()
        assert vm.n_refuses == 1
        assert float(np.abs(np.asarray(vm.voxel_grid())).sum()) > 0
    finally:
        st.shutdown()


def test_old_checkpoints_without_keyframe_sidecar_load(tiny_cfg, tmp_path):
    """Pre-round-5 checkpoints have no .voxelkf file: /load must succeed
    with an empty ring (the pre-persistence behavior), not fail."""
    import json as _json
    import os
    import urllib.request

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.io.checkpoint import keyframe_sidecar_path
    from jax_mapping.sim import world as W

    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4,
                           seed=6)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, http_port=0,
                          seed=6, depth_cam=True)
    try:
        st.api.checkpoint_dir = str(tmp_path)
        st.brain.start_exploring()
        st.run_steps(15)
        url = f"http://127.0.0.1:{st.api.port}"
        urllib.request.urlopen(
            urllib.request.Request(url + "/save?name=old", method="POST")
        ).read()
        os.remove(keyframe_sidecar_path(str(tmp_path / "old.npz")))
        body = _json.loads(urllib.request.urlopen(
            urllib.request.Request(url + "/load?name=old", method="POST")
        ).read())
        assert body["status"] == "loaded"
        assert "keyframes_restored" not in body
        assert sum(len(r) for r in st.voxel_mapper._keyframes) == 0
    finally:
        st.shutdown()


def _tiny_ring_cfg(tiny_cfg, cap=8):
    """Tiny 8-slot ring + key-every-step gating: thinning fires within a
    short straight drive (shared by the thin-replica and remap tests so
    both exercise the SAME schedule)."""
    return dataclasses.replace(
        tiny_cfg,
        loop=dataclasses.replace(tiny_cfg.loop, max_poses=cap,
                                 max_edges=64),
        matcher=dataclasses.replace(tiny_cfg.matcher, min_travel_m=0.01,
                                    min_heading_rad=3.0))


def _drive_straight_step(cfg, pubs, step):
    """Publish one tick of the straight drive (odom + scan, optionally a
    flat-wall depth image at 0.6 m)."""
    t = 0.1 * step
    odom_pub, scan_pub, depth_pub = pubs
    odom_pub.publish(Odometry(header=Header(stamp=t, frame_id="odom"),
                              pose=Pose2D(0.02 * step, 0.0, 0.0),
                              twist=Twist()))
    scan_pub.publish(LaserScan(
        header=Header(stamp=t, frame_id="base_laser"),
        angle_increment=cfg.scan.angle_increment_rad,
        ranges=np.full(cfg.scan.n_beams, 1.0, np.float32)))
    if depth_pub is not None:
        cam = cfg.depthcam
        depth_pub.publish(DepthImage(
            header=Header(stamp=t, frame_id="base_camera"),
            depth=np.full((cam.height_px, cam.width_px), 0.6,
                          np.float32)))


def test_thin_replica_tracks_real_graph(tiny_cfg):
    """_ThinSim must reproduce the REAL graph's node count after every
    key add — the invariant the keyframe remap (idx >> dthins) rests on.
    Drive enough keys through a tiny 8-slot ring that thinning fires
    repeatedly and check the replica never diverges."""
    from jax_mapping.bridge.voxel_mapper import _ThinSim

    cap = 8
    cfg = _tiny_ring_cfg(tiny_cfg, cap)
    bus = Bus()
    mapper = MapperNode(cfg, bus, n_robots=1)
    pubs = (bus.publisher("odom"), bus.publisher("scan"), None)
    sim = _ThinSim(cap)
    for step in range(1, 25):
        _drive_straight_step(cfg, pubs, step)
        mapper.tick()
        st = mapper.states[0]
        k = int(st.n_keyscans)
        sim.thins_at(k)      # advance the replica to the real counter
        assert sim.n == int(st.graph.n_poses), (
            f"replica diverged at step {step}: sim n={sim.n} vs graph "
            f"n_poses={int(st.graph.n_poses)} (k={k})")
    assert int(mapper.states[0].n_keyscans) > cap, \
        "staging: ring never saturated"
    assert sim.t >= 1, "staging: no thin ever fired"


def test_keyframes_survive_graph_thinning(tiny_cfg):
    """Keyframes captured BEFORE a graph thin must re-anchor to the
    surviving even node (idx >> dthins) and still rebuild the 3D map on
    re-fuse — not dangle or vanish. Same drive schedule as the replica
    test (shared helpers)."""
    cap = 8
    cfg = _tiny_ring_cfg(tiny_cfg, cap)
    bus = Bus()
    mapper = MapperNode(cfg, bus, n_robots=1)
    voxel = VoxelMapperNode(cfg, bus, n_robots=1, mapper=mapper)
    pubs = (bus.publisher("odom"), bus.publisher("scan"),
            bus.publisher("depth"))
    kfs_before_thin = 0
    for step in range(1, 25):
        _drive_straight_step(cfg, pubs, step)
        mapper.tick()
        voxel.tick()
        if int(mapper.states[0].n_keyscans) == cap:
            kfs_before_thin = sum(len(x) for x in voxel._keyframes)
    assert int(mapper.states[0].n_keyscans) > cap, "ring never saturated"
    assert kfs_before_thin > 0, "no keyframes captured before the thin"
    n_kf = sum(len(x) for x in voxel._keyframes)
    voxel._refuse_from_keyframes()
    assert voxel.n_refuses == 1
    # Every keyframe remapped onto a live node: none dropped for a
    # dangling index, and the rebuilt map carries wall evidence.
    assert sum(len(x) for x in voxel._keyframes) == n_kf
    g = np.asarray(voxel.voxel_grid())
    assert (g > 0).sum() > 0, "re-fuse after thinning lost the wall"
