"""End-to-end loop closure: the one capability slam_toolbox is most famous
for, driven the way the reference's report describes it (report.pdf §V.B-C:
odometry drift ghosts the map; loop closure repairs it).

A robot with a systematic wheel-calibration bias drives a square loop whose
middle legs see NOTHING (open space beyond lidar range -> the online
matcher rejects -> pure biased dead-reckoning drift), then returns to the
plank cluster it mapped at the start. The drift exceeds the online
matcher's +-0.25 m window, so only the two-stage wide loop search (8 m
window on the coarse grid, slam_config.yaml:56-58) can recover it.
"""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.models import slam as S
from jax_mapping.ops.odometry import twist_to_wheel_units
from jax_mapping.sim import lidar
from jax_mapping.sim import world as W


def loop_cfg(tiny_cfg):
    """tiny config resized for a 22 m drive: enough pose slots for the
    loop's key scans, reference-true 0.1 m/0.1 rad gating relaxed to
    0.3 m/0.35 rad to keep the CPU test fast."""
    import dataclasses
    return dataclasses.replace(
        tiny_cfg,
        loop=dataclasses.replace(tiny_cfg.loop, max_poses=128,
                                 max_edges=512, gn_iters=4,
                                 coarse_downsample=2),
        matcher=dataclasses.replace(tiny_cfg.matcher, min_travel_m=0.3,
                                    min_heading_rad=0.35),
    )


def _drive_loop(cfg, bias_units: float):
    """Square loop through featureless open space; returns (state, history).

    history rows: (true_pose, est_pose_after_step, n_loops).
    """
    res = cfg.grid.resolution_m
    # 12.8 m world: NO border walls — only an L-shaped plank corner around
    # the start, so the loop's middle legs see nothing and drift freely.
    world = np.zeros((256, 256), bool)
    def put(r0, r1, c0, c1):
        world[r0:r1, c0:c1] = True
    # world indexing: row = y/res + 128, col = x/res + 128
    put(30, 32, 30, 70)     # wall south of start (y=-4.9..-4.8)
    put(30, 70, 30, 32)     # wall west of start (x=-4.9..-4.8)
    put(58, 60, 30, 52)     # plank north of start (y=-3.5..-3.4, x<-2.4)
    # Symmetry breaker: a stub off the west wall near the return corridor.
    # Without it the corner is ambiguous under y-translation (plank can
    # snap onto the south wall — parallel walls 1.4 m apart) and a wide
    # match can verify a WRONG loop.
    put(86, 89, 30, 37)     # stub y=-2.1..-1.95, x=-4.9..-4.55
    world_j = jnp.asarray(world)

    n_samples = int(cfg.scan.range_max_m / (res * 0.5))
    v = 0.35                      # m/s (sim-fast; irrelevant to the math)
    w_turn = math.pi / 2 / 1.0    # 90 deg in 1 s
    dt = 0.1

    # Square loop from the start corner through the open middle; the last
    # leg stops just short of the north plank (no wall crossing).
    legs = [("fwd", 5.5), ("turn", 1.0), ("fwd", 5.5), ("turn", 1.0),
            ("fwd", 5.5), ("turn", 1.0), ("fwd", 4.9)]

    state = S.init_state(cfg, pose0=jnp.array([-3.8, -3.8, 0.0]))
    true_pose = np.array([-3.8, -3.8, 0.0])
    hist = []
    for kind, amount in legs:
        n = int(round((amount / v if kind == "fwd" else amount) / dt))
        tv, tw = (v, 0.0) if kind == "fwd" else (0.0, w_turn)
        wl_t, wr_t = twist_to_wheel_units(cfg.robot, tv, tw)
        for _ in range(n):
            # Truth integrates the true wheels (RK2, same model).
            k = cfg.robot.speed_coeff_m_per_unit_s
            vl, vr = wl_t * k, wr_t * k
            v_lin, v_ang = (vl + vr) / 2, (vr - vl) / cfg.robot.wheel_base_m
            mid = true_pose[2] + v_ang * dt / 2
            true_pose = true_pose + np.array([
                v_lin * math.cos(mid) * dt, v_lin * math.sin(mid) * dt,
                v_ang * dt])
            scan = lidar.simulate_scans(cfg.scan, world_j, res, n_samples,
                                        jnp.asarray(true_pose)[None])[0]
            # SLAM sees BIASED wheel readings (constant left-wheel offset —
            # the calibration error class report.pdf §III.D measures).
            state, diag = S.slam_step(
                cfg, state, scan,
                jnp.float32(wl_t + bias_units), jnp.float32(wr_t),
                jnp.float32(dt))
            hist.append((true_pose.copy(), np.asarray(state.pose),
                         int(state.n_loops)))
    return state, hist


@pytest.mark.slow
def test_loop_closure_recovers_biased_odometry(tiny_cfg):
    cfg = loop_cfg(tiny_cfg)
    state, hist = _drive_loop(cfg, bias_units=1.0)

    errs = np.array([np.linalg.norm(t[:2] - e[:2]) for t, e, _ in hist])
    loops = np.array([n for _, _, n in hist])
    assert loops[-1] >= 1, "no loop ever closed"

    # The drive must actually have drifted far beyond the online matcher's
    # window (else this test proves nothing about loop closure)...
    assert errs.max() > 2 * cfg.matcher.search_half_extent_m, (
        f"staging failed: max drift {errs.max():.2f} m never exceeded the "
        "online window")
    # ...the first closure must immediately reduce the error...
    first_close = int(np.argmax(loops >= 1))
    assert errs[first_close] < errs[max(0, first_close - 1)], (
        f"closure made things worse: {errs[max(0, first_close - 1)]:.2f} "
        f"-> {errs[first_close]:.2f} m")
    # ...and by the end the trajectory is repaired (report.pdf §V.B-C).
    assert errs[-1] < 0.15, f"final error {errs[-1]:.2f} m not repaired"


def test_wide_loop_cfg_covers_window(tiny_cfg):
    """The wide stage's search half-extent must beat the online window by
    a wide margin (the whole point of the two-stage search)."""
    from jax_mapping.models.slam import _loop_wide_cfgs
    g_c, m_c = _loop_wide_cfgs(tiny_cfg)
    assert m_c.search_half_extent_m >= 4 * tiny_cfg.matcher.search_half_extent_m
    assert g_c.resolution_m == tiny_cfg.grid.resolution_m * \
        tiny_cfg.loop.coarse_downsample

    from jax_mapping.config import SlamConfig
    full = SlamConfig()
    g_cf, m_cf = _loop_wide_cfgs(full)
    # Full-size config sweeps the whole 8 m slam_toolbox window (half = 4).
    assert m_cf.search_half_extent_m == pytest.approx(4.0)


def test_downsample_max_keeps_walls(tiny_cfg):
    from jax_mapping.ops import grid as G
    g = np.zeros((16, 16), np.float32)
    g[3, 5] = 3.0          # one occupied cell
    g[10:12, :] = -2.0     # free band
    c = np.asarray(G.downsample_max(jnp.asarray(g), 2))
    assert c.shape == (8, 8)
    assert c[1, 2] == 3.0                  # wall survives
    assert (c >= 0).all() or (c[5] <= 0).any()  # free band may survive
