"""Pose-graph tests: construction, loop gating, Gauss-Newton convergence."""

import numpy as np
import pytest

import jax.numpy as jnp

from jax_mapping.config import LoopClosureConfig
from jax_mapping.ops import posegraph as PG
from jax_mapping.ops.odometry import pose_between


@pytest.fixture()
def cfg():
    return LoopClosureConfig(max_poses=64, max_edges=256, gn_iters=6)


def test_add_pose_and_edge(cfg):
    g = PG.empty_graph(cfg)
    g = PG.add_pose(g, jnp.array([1.0, 2.0, 0.3]))
    g = PG.add_pose(g, jnp.array([2.0, 2.0, 0.3]))
    assert int(g.n_poses) == 2
    assert bool(g.pose_valid[0]) and bool(g.pose_valid[1])
    assert not bool(g.pose_valid[2])
    g = PG.odometry_edge(g, jnp.int32(0), jnp.int32(1))
    assert int(g.n_edges) == 1
    np.testing.assert_allclose(
        np.asarray(g.edge_meas[0]),
        np.asarray(pose_between(g.poses[0], g.poses[1])), atol=1e-6)


def test_capacity_overflow_is_noop():
    cfg = LoopClosureConfig(max_poses=2, max_edges=1, gn_iters=2)
    g = PG.empty_graph(cfg)
    for i in range(4):
        g = PG.add_pose(g, jnp.array([float(i), 0.0, 0.0]))
    assert int(g.n_poses) == 2
    np.testing.assert_allclose(np.asarray(g.poses[1]), [1, 0, 0])
    g = PG.add_edge(g, 0, 1, jnp.zeros(3), jnp.ones(3))
    g = PG.add_edge(g, 0, 1, jnp.ones(3), jnp.ones(3))
    assert int(g.n_edges) == 1
    np.testing.assert_allclose(np.asarray(g.edge_meas[0]), np.zeros(3))


def test_loop_candidate_gating(cfg):
    g = PG.empty_graph(cfg)
    # A loop trajectory: 20 poses around a circle of radius 2.5 (diameter
    # 5 m > the 3 m search radius, so the chain genuinely DEPARTS) ->
    # pose 19 is close to pose 0 but far in index.
    for i in range(20):
        a = 2 * np.pi * i / 20
        g = PG.add_pose(g, jnp.array([2.5 * np.cos(a), 2.5 * np.sin(a), a],
                                     jnp.float32))
    idx, found = PG.loop_candidate(cfg, g, jnp.int32(19))
    assert bool(found)
    assert int(idx) == 0           # nearest old-enough pose
    # Pose 5 has no old-enough pose within 3 m ... pose 0 is within 3 m but
    # the chain gate (>=10 behind) excludes everything.
    idx, found = PG.loop_candidate(cfg, g, jnp.int32(5))
    assert not bool(found)


def test_loop_candidate_excludes_near_linked_tail(cfg):
    """Karto's near-linked exclusion: a robot creeping along a line keeps
    its whole tail within the search radius — those are NOT loops."""
    g = PG.empty_graph(cfg)
    for i in range(20):
        g = PG.add_pose(g, jnp.array([0.1 * i, 0.0, 0.0], jnp.float32))
    # Pose 19 is 1.9 m from pose 0: inside the 3 m radius, >= 10 behind,
    # but the chain never left the disc -> no candidate.
    _idx, found = PG.loop_candidate(cfg, g, jnp.int32(19))
    assert not bool(found)

    # Extend the line beyond the radius and drive back near the start:
    # now the chain departed and returning DOES yield pose 0.
    for i in range(20, 45):
        g = PG.add_pose(g, jnp.array([0.2 * (i - 20) + 2.0, 0.0, 0.0],
                                     jnp.float32))
    g = PG.add_pose(g, jnp.array([0.05, 0.0, 0.0], jnp.float32))  # back home
    idx, found = PG.loop_candidate(cfg, g, g.n_poses - 1)
    assert bool(found)
    assert int(idx) <= 10


def test_gn_recovers_noisy_loop(cfg, rng):
    """Classic pose-graph test: odometry edges with drift + one loop edge;
    optimisation must pull the chain back together."""
    T = 30
    # Ground truth: square loop.
    truth = []
    pose = np.zeros(3)
    for t in range(T):
        truth.append(pose.copy())
        pose = pose + np.array([0.2 * np.cos(pose[2]), 0.2 * np.sin(pose[2]), 0.0])
        if (t + 1) % 8 == 0:
            pose[2] += np.pi / 2
    truth = np.array(truth, np.float32)

    # Noisy odometry estimate: accumulate perturbed relative poses.
    est = [truth[0]]
    rels = []
    for t in range(1, T):
        rel = np.asarray(pose_between(jnp.asarray(truth[t - 1]),
                                      jnp.asarray(truth[t])))
        rels.append(rel)
        noisy = rel + rng.normal(0, [0.01, 0.01, 0.02])
        prev = est[-1]
        c, s = np.cos(prev[2]), np.sin(prev[2])
        est.append(np.array([prev[0] + c * noisy[0] - s * noisy[1],
                             prev[1] + s * noisy[0] + c * noisy[1],
                             prev[2] + noisy[2]], np.float32))
    est = np.array(est, np.float32)

    g = PG.empty_graph(cfg)
    for t in range(T):
        g = PG.add_pose(g, jnp.asarray(est[t]))
    for t in range(1, T):
        # Edge measurement = the noisy relative pose actually observed.
        rel = np.asarray(pose_between(jnp.asarray(est[t - 1]), jnp.asarray(est[t])))
        g = PG.add_edge(g, t - 1, t, jnp.asarray(rel),
                        jnp.array([50.0, 50.0, 100.0]))
    # Loop edge: perfect observation pose 0 -> pose T-1.
    loop_rel = pose_between(jnp.asarray(truth[0]), jnp.asarray(truth[-1]))
    g = PG.add_edge(g, 0, T - 1, loop_rel, jnp.array([500.0, 500.0, 500.0]))

    err_before = np.linalg.norm(est[-1][:2] - truth[-1][:2])
    g_opt = PG.optimize(cfg, g)
    opt = np.asarray(g_opt.poses[:T])
    err_after = np.linalg.norm(opt[-1][:2] - truth[-1][:2])
    # End pose snaps to the loop constraint.
    assert err_after < err_before * 0.5
    assert err_after < 0.05
    # Gauge: pose 0 stays pinned.
    np.testing.assert_allclose(opt[0], truth[0], atol=1e-3)
    # Graph error decreases.
    assert float(PG.graph_error(g_opt)) < float(PG.graph_error(g))


def test_optimize_noop_on_consistent_graph(cfg):
    g = PG.empty_graph(cfg)
    poses = [np.array([0.1 * t, 0.05 * t, 0.01 * t], np.float32) for t in range(5)]
    for p in poses:
        g = PG.add_pose(g, jnp.asarray(p))
    for t in range(1, 5):
        g = PG.odometry_edge(g, jnp.int32(t - 1), jnp.int32(t))
    g_opt = PG.optimize(cfg, g)
    np.testing.assert_allclose(np.asarray(g_opt.poses[:5]),
                               np.stack(poses), atol=1e-3)
