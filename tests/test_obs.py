"""Observability subsystem (ISSUE 9): causal tracing, flight recorder,
metrics registry, trace-diff, Perfetto export, postmortem CLI.

Pure unit/component tier — NO stack launches (the tier-1 wall budget
is spoken for); the end-to-end surfaces (trace propagation through a
live mission, /metrics byte-order, recorder coverage of real
transitions) piggyback on the shared module-scoped mission stack in
tests/test_scenarios.py.
"""

import json
import threading

import numpy as np
import pytest

from jax_mapping.obs import (
    Divergence, FlightRecorder, MetricsRegistry, Family, TraceContext,
    Tracer, chrome_events, diff_dumps, diff_streams, dump_to_chrome,
    h64, histogram_samples, normalize_events, summary_samples,
)
from jax_mapping.obs.__main__ import main as obs_main


# ----------------------------------------------------------- trace ids

def test_h64_deterministic_and_never_zero():
    assert h64("trace", 0, "/scan", 1) == h64("trace", 0, "/scan", 1)
    assert h64("trace", 0, "/scan", 1) != h64("trace", 0, "/scan", 2)
    assert h64("trace", 0, "/scan", 1) != h64("trace", 1, "/scan", 1)
    # 0 is the no-parent sentinel; an id may never collide with it.
    assert h64() != 0


def _drive(tracer):
    """One scripted emission sequence: publish roots, a traced tick
    whose inner publish chains, an explicit-parent fuse."""
    tracer.on_publish("/robot0/scan")
    tracer.on_publish("/robot0/scan")
    with tracer.span("mapper.tick", key=1):
        ctx = tracer.on_publish("/frontiers")
        tracer.emit("mapper.fuse", parent=ctx, key=(0, 1.25))


def test_tracer_streams_identical_across_same_seed_instances():
    """The deterministic-id contract at the unit tier: two Tracers fed
    the same sequence emit IDENTICAL streams (ids and all) once the
    wall-clock fields are normalized away — what makes obs/diff.py able
    to name a divergence point between two same-seed runs."""
    a, b = Tracer(seed=7), Tracer(seed=7)
    _drive(a)
    _drive(b)
    assert normalize_events(a.spans_since(0)) \
        == normalize_events(b.spans_since(0))
    # A different seed moves every root-derived id.
    c = Tracer(seed=8)
    _drive(c)
    ids = {s["trace_id"] for s in a.spans_since(0)}
    assert ids.isdisjoint({s["trace_id"] for s in c.spans_since(0)})


def test_tracer_root_child_and_ambient_chaining():
    tr = Tracer(seed=0)
    root = tr.on_publish("/robot0/scan")
    assert root.parent_span == 0
    assert root.trace_id == h64("trace", 0, "/robot0/scan", 1)
    # Delivery context made current -> a publish inside chains under it.
    with tr.use(root):
        child = tr.on_publish("/pose")
        assert child.trace_id == root.trace_id
        assert child.parent_span == root.span_id
        with tr.span("mapper.tick") as tick:
            assert tick.parent_span == root.span_id
            inner = tr.emit("mapper.fuse")
            assert inner.parent_span == tick.span_id
    assert tr.current() is None                  # restored after the block
    # Explicit parent beats the ambient context.
    other = TraceContext(h64("t"), h64("s"), 0)
    with tr.use(root):
        got = tr.emit("x", parent=other)
        assert got.trace_id == other.trace_id


def test_tracer_use_restores_context_on_exception():
    tr = Tracer(seed=0)
    ctx = tr.on_publish("/a")
    with pytest.raises(RuntimeError):
        with tr.use(ctx):
            raise RuntimeError("boom")
    assert tr.current() is None


def test_tracer_ring_bounded_and_since_filter():
    tr = Tracer(seed=0, capacity=8)
    for k in range(20):
        tr.emit("e", key=k)
    spans = tr.spans_since(0)
    assert [s["seq"] for s in spans] == list(range(13, 21))
    assert [s["seq"] for s in tr.spans_since(17)] == [18, 19, 20]
    assert tr.last_seq() == 20
    assert tr.stats() == {"n_spans": 20, "ring_len": 8}


# ------------------------------------------------------ flight recorder

def test_recorder_ring_mark_and_capacity():
    rec = FlightRecorder(capacity=4)
    for k in range(6):
        rec.record("ev", k=k)
    assert [e["k"] for e in rec.events_since(0)] == [2, 3, 4, 5]
    m = rec.mark()
    rec.record("late", k=6)
    assert [e["kind"] for e in rec.events_since(m)] == ["late"]
    # A capacity change rebuilds the ring keeping the newest events.
    rec.configure(capacity=2)
    assert [e["k"] for e in rec.events_since(0)] == [5, 6]


def test_recorder_dump_roundtrip(tmp_path):
    rec = FlightRecorder()
    rec.record("map_revision", revision=3)
    assert rec.dump("no_dir_configured") is None  # events-only mode
    tr = Tracer(seed=0)
    tr.emit("mapper.fuse")
    rec.configure(dump_dir=str(tmp_path), tracer=tr)
    path = rec.dump("watchdog divergence robot/0")
    assert path is not None and path.startswith(str(tmp_path))
    doc = json.load(open(path))
    assert doc["reason"] == "watchdog divergence robot/0"
    assert [e["kind"] for e in doc["events"]] == ["map_revision"]
    assert [s["name"] for s in doc["spans"]] == ["mapper.fuse"]
    # The dump itself lands in the ring as a transition (basename only
    # — absolute tmp paths would break same-seed stream identity).
    kinds = [e["kind"] for e in rec.events_since(0)]
    assert kinds == ["map_revision", "postmortem_dump"]
    ev = rec.events_since(0)[-1]
    assert "/" not in ev["path"]
    assert rec.stats()["n_dumps"] == 1 and rec.dumps == [path]


def test_recorder_concurrent_triggers_one_dump_each(tmp_path):
    """ISSUE 10 satellite: two trigger threads dumping at once — a
    supervisor restart racing a watchdog divergence — produce ONE dump
    per trigger (distinct reserved flight_NNNN slots, never an
    overwrite), every file parses as whole JSON (no torn writes), and
    the on-disk dump population stays GC-bounded under a dump storm."""
    import glob
    import threading as _t

    from jax_mapping.obs import recorder as R

    rec = FlightRecorder(capacity=64)
    rec.configure(dump_dir=str(tmp_path))
    for k in range(8):
        rec.record("map_revision", revision=k)

    n_per_thread = 6
    barrier = _t.Barrier(2)
    paths = {"sup": [], "wd": []}

    def trigger(name, reason, use_async):
        barrier.wait()
        for k in range(n_per_thread):
            p = (rec.dump_async if use_async else rec.dump)(
                f"{reason}_{k}")
            paths[name].append(p)

    ts = [_t.Thread(target=trigger,
                    args=("sup", "supervisor_restart", False)),
          _t.Thread(target=trigger,
                    args=("wd", "watchdog_divergence", True))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    # Async writers may still be in flight: wait for all dumps to land.
    deadline = 10.0
    import time as _time
    while rec.stats()["n_dumps"] < 2 * n_per_thread and deadline > 0:
        _time.sleep(0.05)
        deadline -= 0.05
    all_paths = paths["sup"] + paths["wd"]
    assert None not in all_paths
    assert len(set(all_paths)) == 2 * n_per_thread, \
        "two triggers shared a flight_NNNN slot"
    assert rec.stats()["n_dumps"] == 2 * n_per_thread
    for p in all_paths:
        doc = json.load(open(p))                 # whole, untorn JSON
        assert doc["reason"].startswith(("supervisor_restart",
                                         "watchdog_divergence"))
        assert doc["events"]
    # Disk GC bound: storm past _MAX_DUMP_FILES, the population stays
    # capped at the newest N.
    for k in range(R._MAX_DUMP_FILES + 5):
        rec.dump(f"storm_{k}")
    on_disk = glob.glob(str(tmp_path / "flight_*.json"))
    assert len(on_disk) <= R._MAX_DUMP_FILES


def test_recorder_dump_never_raises(tmp_path):
    """A failing postmortem write must not take down the recovery path
    that triggered it — an unwritable dump dir degrades to None."""
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file, not dir")
    rec = FlightRecorder()
    rec.configure(dump_dir=str(blocker / "sub"))
    rec.record("ev")
    assert rec.dump("doomed") is None
    assert rec.stats()["n_dumps"] == 0


# ----------------------------------------------------------- trace-diff

def _stream(n, wall=0.0):
    return [{"seq": k + 1, "kind": "ev", "step": k, "wall_ts": wall + k}
            for k in range(n)]


def test_diff_streams_identical_modulo_volatile():
    a, b = _stream(5), _stream(5, wall=100.0)    # wall clocks differ
    b[2]["seq"] = 99                             # absolute seqs differ
    assert diff_streams(a, b) is None


def test_diff_streams_names_first_divergence():
    a, b = _stream(5), _stream(5)
    b[3]["step"] = 42
    div = diff_streams(a, b)
    assert isinstance(div, Divergence) and div.index == 3
    assert div.a["step"] == 3 and div.b["step"] == 42
    assert "step=42" in div.describe()
    # Length mismatch: the shorter stream "ended".
    div = diff_streams(_stream(3), _stream(5))
    assert div.index == 3 and div.a is None and div.b["step"] == 3
    assert "<stream ended>" in div.describe()


def test_diff_dumps_one_call_answer():
    da = {"events": _stream(3), "spans": _stream(2)}
    db = {"events": _stream(3), "spans": _stream(2)}
    assert diff_dumps(da, db)["identical"]
    db["spans"][1]["step"] = 9
    res = diff_dumps(da, db)
    assert not res["identical"]
    assert res["events"] is None and res["spans"].index == 1


# --------------------------------------------------------------- export

def test_chrome_events_shape():
    tr = Tracer(seed=0)
    tr.emit("mapper.fuse")
    (ev,) = chrome_events(tr.spans_since(0))
    assert ev["ph"] == "X" and ev["name"] == "mapper.fuse"
    assert ev["dur"] >= 1.0                      # instant-span floor
    assert len(ev["args"]["trace_id"]) == 16     # 64-bit hex
    doc = dump_to_chrome({"spans": tr.spans_since(0),
                          "events": [{"kind": "fault", "step": 3}],
                          "reason": "r"})
    names = [e["name"] for e in doc["traceEvents"]]
    assert names == ["mapper.fuse", "fault"]
    assert doc["traceEvents"][1]["ph"] == "i"


def test_obs_cli_diff_and_export(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps({"events": _stream(4), "spans": []}))
    b.write_text(json.dumps({"events": _stream(4), "spans": []}))
    assert obs_main(["diff", str(a), str(b)]) == 0
    assert "events: identical" in capsys.readouterr().out
    ev = _stream(4)
    ev[1]["step"] = 77
    b.write_text(json.dumps({"events": ev, "spans": []}))
    assert obs_main(["diff", str(a), str(b)]) == 1
    assert "first divergence at event #1" in capsys.readouterr().out
    assert obs_main(["export", str(a)]) == 0
    out = json.load(open(str(a) + ".trace.json"))
    assert len(out["traceEvents"]) == 4
    assert obs_main(["diff", str(a)]) == 2       # usage error
    assert obs_main(["diff", str(a), str(tmp_path / "nope.json")]) == 2


# ------------------------------------------------------ metrics registry

def test_registry_renders_exact_document():
    """The renderer's byte contract, pinned on a fully-known registry:
    registration order is exposition order, values pass through as
    pre-formatted strings, histogram/summary helpers produce the
    repo's exposition shapes exactly."""
    reg = MetricsRegistry()
    reg.family("jm_requests_total", "counter", lambda: [("", "7")])
    reg.family("jm_absent", "gauge", lambda: None)   # omitted family
    reg.family("jm_state", "gauge",
               lambda: [('{robot="0"}', "1"), ('{robot="1"}', "2")])
    reg.add_source(lambda: (
        Family("jm_lat_seconds", "histogram",
               tuple(histogram_samples((0.1, 0.2), [1, 2, 3], 0.75, 6))),
        Family("jm_stage_ms", "summary",
               tuple(summary_samples(4, 12.3456))),
    ))
    assert reg.render() == (
        "# TYPE jm_requests_total counter\n"
        "jm_requests_total 7\n"
        "# TYPE jm_state gauge\n"
        'jm_state{robot="0"} 1\n'
        'jm_state{robot="1"} 2\n'
        "# TYPE jm_lat_seconds histogram\n"
        'jm_lat_seconds_bucket{le="0.1"} 1\n'
        'jm_lat_seconds_bucket{le="0.2"} 3\n'
        'jm_lat_seconds_bucket{le="+Inf"} 6\n'
        "jm_lat_seconds_sum 0.750000\n"
        "jm_lat_seconds_count 6\n"
        "# TYPE jm_stage_ms summary\n"
        "jm_stage_ms_count 4\n"
        "jm_stage_ms_sum 12.346\n"
    )


def test_histogram_samples_cumulative_bucket_math():
    samples = histogram_samples((0.005, 0.01), [2, 0, 5], 0.123456, 7)
    assert samples == [
        ('_bucket{le="0.005"}', "2"),
        ('_bucket{le="0.01"}', "2"),
        ('_bucket{le="+Inf"}', "7"),
        ("_sum", "0.123456"),
        ("_count", "7"),
    ]


# ------------------------------------------------- stage histograms

def test_stage_timer_histograms_fixed_buckets(monkeypatch):
    from jax_mapping.utils import profiling as P
    t = P.StageTimer()
    # Deterministic durations: 1 ms (== edge, le semantics -> that
    # bucket), 3 ms, and one past the last edge -> overflow.
    ticks = iter([0.0, 0.001, 10.0, 10.003, 20.0, 20.0 + 16.0])
    monkeypatch.setattr(P.time, "perf_counter", lambda: next(ticks))
    for _ in range(3):
        with t.stage("mapper.tick"):
            pass
    h = t.histograms()["mapper.tick"]
    assert h["edges_s"] == P.HIST_EDGES_S
    assert h["count"] == 3 and sum(h["buckets"]) == 3
    assert h["buckets"][P.HIST_EDGES_S.index(0.001)] == 1
    import bisect
    assert h["buckets"][bisect.bisect_left(P.HIST_EDGES_S, 0.003)] == 1
    assert h["buckets"][-1] == 1                 # 16 s -> overflow
    np.testing.assert_allclose(h["sum_s"], 0.001 + 0.003 + 16.0)


# ------------------------------------------------- device_trace satellite

def test_device_trace_start_failure_yields_none(monkeypatch, tmp_path):
    """The start-failure path (previously untested): a profiler that
    refuses to start yields None and must NOT call stop_trace — the
    control loop proceeds untraced instead of dying."""
    import jax

    def boom(*a, **k):
        raise RuntimeError("profiler unavailable")

    stopped = []
    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stopped.append(True))
    from jax_mapping.utils.profiling import device_trace
    with device_trace(str(tmp_path)) as d:
        assert d is None
    assert stopped == []


def test_device_trace_perfetto_flag_passthrough(monkeypatch, tmp_path):
    import jax
    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda log_dir, create_perfetto_trace: calls.append(
            (log_dir, create_perfetto_trace)))
    stopped = []
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stopped.append(True))
    from jax_mapping.utils.profiling import device_trace
    with device_trace(str(tmp_path)) as d:
        assert d == str(tmp_path)
    with device_trace(str(tmp_path), create_perfetto_trace=True) as d:
        assert d == str(tmp_path)
    assert [c[1] for c in calls] == [False, True]   # default stays off
    assert stopped == [True, True]


def test_device_trace_stop_failure_swallowed(monkeypatch, tmp_path):
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace", lambda *a, **k: None)

    def boom():
        raise RuntimeError("serialization exploded")

    monkeypatch.setattr(jax.profiler, "stop_trace", boom)
    from jax_mapping.utils.profiling import device_trace
    with device_trace(str(tmp_path)) as d:       # must not raise
        assert d == str(tmp_path)


# ------------------------------------------------- racewatch gate (CI)

def test_racewatch_gate_cross_thread_span_emission():
    """ISSUE 9 CI satellite: hammer one Tracer and one FlightRecorder
    from concurrent threads (bus delivery / mapper tick / HTTP handler
    emission in miniature) under RaceWatch — Eraser refinement must
    converge every declared field on the declared lock with ZERO
    reports."""
    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch

    tr = Tracer(seed=0, capacity=256)
    rec = FlightRecorder(capacity=256)
    watch = RaceWatch()
    try:
        watch.watch_object(tr, groups_by_class()["Tracer"][0],
                           name="tracer")
        watch.watch_object(rec, groups_by_class()["FlightRecorder"][0],
                           name="rec")

        def worker(tid):
            for k in range(200):
                ctx = tr.on_publish(f"/robot{tid}/scan")
                with tr.use(ctx):
                    with tr.span("mapper.tick", key=(tid, k)):
                        tr.emit("mapper.fuse", key=k)
                rec.record("map_revision", revision=k)
                if k % 50 == 0:
                    tr.spans_since(0)
                    rec.events_since(0)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        watch.unwatch_all()
    assert watch.reports() == [], \
        "\n".join(r.message for r in watch.reports())
    # `n_spans` is the cross-thread WRITTEN field (the deque attribute
    # itself is only read; its mutation is the append under `_lock`) —
    # its candidate lockset must converge on the declared Tracer lock.
    counter = watch.field_states()["Tracer.n_spans@tracer"]
    assert counter.state == "shared-modified"
    assert "Tracer._lock@tracer" in counter.candidate


def test_racewatch_gate_cross_thread_devprof_emission():
    """ISSUE 10 satellite: hammer one DispatchProfiler's recording
    surface from concurrent threads (mapper tick / HTTP tile-hash /
    test-driver dispatches in miniature) under RaceWatch — the
    declared `_lock` must converge as every watched field's lockset
    with ZERO reports."""
    import functools
    import sys
    import types

    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch
    from jax_mapping.config import DevProfConfig
    from jax_mapping.obs import DispatchProfiler

    import jax
    import jax.numpy as jnp

    mod = types.ModuleType("devprof_race_fixture")

    @functools.partial(jax.jit, static_argnums=(0,))
    def scaled(k, x):
        return x * k

    mod.scaled = scaled
    sys.modules["devprof_race_fixture"] = mod
    prof = DispatchProfiler(DevProfConfig(enabled=True))
    prof.install(prefix="devprof_race_fixture")
    xs = [jnp.ones((4, 4)), jnp.ones((8, 8))]
    for x in xs:
        mod.scaled(2, x)                         # compile outside the race
    watch = RaceWatch()
    try:
        watch.watch_object(prof,
                           groups_by_class()["DispatchProfiler"][0],
                           name="prof")

        def worker(tid):
            for k in range(120):
                mod.scaled(2, xs[k % 2])
                if k % 40 == 0:
                    prof.snapshot()
                    prof.recompiles()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        watch.unwatch_all()
        prof.uninstall()
        sys.modules.pop("devprof_race_fixture", None)
    assert watch.reports() == [], \
        "\n".join(r.message for r in watch.reports())
    st = watch.field_states()["DispatchProfiler._profiles@prof"]
    assert "DispatchProfiler._lock@prof" in st.candidate


# ------------------------------------------- stage-fold (ISSUE 10 sat.)

def test_hot_stages_report_through_one_histogram_mechanism():
    """The PR 5 match stages and the PR 6 frontier recompute report
    through the ONE stage mechanism: a StageTimer.observe / stage()
    entry renders as both the `_ms` summary and the fixed log-bucket
    `_seconds` histogram family — no hand-built gauge needed."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.http_api import MapApiServer
    from jax_mapping.utils import global_metrics

    # The exact names the hot paths record (relocalize.py stage_match
    # spans; frontier_incremental.observe fold).
    global_metrics.stages.observe("frontier.recompute", 0.004)
    with global_metrics.stages.stage("match.pyramid_build"):
        pass
    with global_metrics.stages.stage("match.coarse_score"):
        pass
    with global_metrics.stages.stage("match.refine"):
        pass
    api = MapApiServer(Bus(domain_id=1), mapper=None, port=0)
    text = api.handle("/metrics")[2].decode()
    for stage in ("frontier_recompute", "match_pyramid_build",
                  "match_coarse_score", "match_refine"):
        assert f"# TYPE jax_mapping_stage_{stage}_ms summary" in text
        assert (f"# TYPE jax_mapping_stage_{stage}_seconds histogram"
                in text)
        assert f'jax_mapping_stage_{stage}_seconds_bucket{{le="' in text
    # The hand-built gauge is GONE — the histogram family is the only
    # `frontier_recompute` latency surface on /metrics.
    assert "jax_mapping_frontier_recompute_ms " not in text


def test_incremental_pipeline_records_recompute_stage():
    """The frontier pipeline's recompute folds into the stage
    mechanism at the source: a compute() that recomputes bumps the
    `frontier.recompute` stage count."""
    import jax.numpy as jnp

    from jax_mapping.config import tiny_config
    from jax_mapping.ops.frontier_incremental import (
        IncrementalFrontierPipeline,
    )
    from jax_mapping.utils import global_metrics

    cfg = tiny_config()
    tile = cfg.serving.tile_cells
    nt = cfg.grid.size_cells // tile
    pipe = IncrementalFrontierPipeline(cfg.frontier, cfg.grid, tile)
    lo = jnp.zeros((cfg.grid.size_cells,) * 2, jnp.float32)
    poses = np.zeros((1, 3), np.float32)
    tile_rev = np.zeros((nt, nt), np.int64)
    before = global_metrics.stages.snapshot().get(
        "frontier.recompute", {"count": 0})["count"]
    out = pipe.compute(lo, poses, tile_rev, 0)
    assert out.recomputed
    after = global_metrics.stages.snapshot()["frontier.recompute"]
    assert after["count"] == before + 1
    assert pipe.last_recompute_ms is not None    # /status one-glance


# --------------------------------------------------- bus context plumbing

def test_bus_carries_context_through_mailboxes():
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.qos import qos_sensor_data

    tr = Tracer(seed=3)
    bus = Bus(domain_id=1, seed=3, tracer=tr)
    seen = []
    bus.subscribe("/robot0/scan",
                  callback=lambda m: seen.append(tr.current()))
    pub = bus.publisher("/robot0/scan")
    pub.publish({"beam": 1})
    pub.publish({"beam": 2})
    assert len(seen) == 2 and None not in seen
    # The delivered context IS the publish root: deterministic id from
    # (seed, topic, per-topic seq), parent 0.
    assert seen[0].trace_id == h64("trace", 3, "/robot0/scan", 1)
    assert seen[1].trace_id == h64("trace", 3, "/robot0/scan", 2)
    assert seen[0].parent_span == 0
    # Queue-then-take path (no callback): taken_ctx follows each take,
    # and overflow drops keep the shadow queue in lockstep.
    sub = bus.subscribe("/lossy", qos_sensor_data)    # depth 5
    lossy = bus.publisher("/lossy", qos_sensor_data)
    for k in range(8):
        lossy.publish(k)
    assert sub.n_dropped == 3
    msg = sub.take(timeout=0)
    assert msg == 3                                    # oldest surviving
    assert sub.taken_ctx.trace_id == h64("trace", 3, "/lossy", 4)
    assert len(sub._queue) == len(sub._ctxq)


def test_bus_subscription_stats_aggregate_and_survive_churn():
    from jax_mapping.bridge.bus import Bus

    bus = Bus(domain_id=1)
    s1 = bus.subscribe("/scan")
    s2 = bus.subscribe("/scan")
    bus.subscribe("/pose", callback=lambda m: None)
    scan_pub = bus.publisher("/scan")
    for k in range(3):
        scan_pub.publish(k)
    bus.publisher("/pose").publish(0)
    stats = bus.subscription_stats()
    assert stats["/scan"] == {"subscriptions": 2, "queue_depth": 6,
                              "n_received": 6, "n_dropped": 0}
    assert stats["/pose"]["n_received"] == 1
    assert stats["/pose"]["queue_depth"] == 0          # drained by callback
    # Prometheus monotonicity across churn: a closed subscription's
    # totals fold into the topic's retired carry instead of vanishing.
    s1.close()
    s2.close()
    stats = bus.subscription_stats()
    assert stats["/scan"] == {"subscriptions": 0, "queue_depth": 0,
                              "n_received": 6, "n_dropped": 0}


# ----------------------------------------------------- /trace endpoint

class _Headers(dict):
    """Minimal If-None-Match header carrier (http.server's .get API)."""


def test_trace_endpoint_gating_and_incremental_poll():
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.http_api import MapApiServer

    # Tracing off: /trace answers 404 (the /tiles-when-disabled rule).
    api = MapApiServer(Bus(domain_id=1), mapper=None, port=0)
    status, ctype, body = api.handle("/trace")[:3]
    assert status == 404 and b"tracing disabled" in body

    tr = Tracer(seed=0)
    bus = Bus(domain_id=1, tracer=tr)
    api = MapApiServer(bus, mapper=None, port=0)
    status, _, body = api.handle("/trace?since=0")[:3]
    assert status == 200
    doc = json.loads(body)
    # /trace does NOT trace itself (ISSUE 10: a span per poll would
    # advance the ring every request and the ETag could never match) —
    # an idle tracer polls empty forever.
    assert doc["traceEvents"] == [] and doc["next"] == 0
    assert json.loads(api.handle("/trace?since=0")[2])["traceEvents"] \
        == []
    # Other routes still span; the poll then serves them.
    api.handle("/status")
    doc2 = json.loads(api.handle("/trace?since=0")[2])
    assert any(e["name"] == "http:/status" for e in doc2["traceEvents"])
    nxt = doc2["next"]
    assert nxt == tr.last_seq()
    # Incremental tail: only spans after `since` come back.
    api.handle("/status")
    doc3 = json.loads(api.handle(f"/trace?since={nxt}")[2])
    assert doc3["traceEvents"] and \
        all(e["args"]["seq"] > nxt for e in doc3["traceEvents"])
    assert api.handle("/trace?since=bogus")[0] == 400
    # /metrics renders through the registry with no stack attached, and
    # the obs tail families are present.
    text = api.handle("/metrics")[2].decode()
    assert "# TYPE jax_mapping_obs_recorder_events_total counter" in text
    assert "# TYPE jax_mapping_obs_trace_spans_total counter" in text


def test_trace_endpoint_etag_304_and_empty_window():
    """ISSUE 10 satellite: /trace gets the /tiles conditional-GET
    treatment — ETag keyed on the span-ring head seq READ BEFORE the
    span content (lint C1), If-None-Match hit answers a body-less 304,
    and an empty-window poll (since == head) returns an empty event
    list echoing `since` as `next`."""
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.http_api import MapApiServer

    tr = Tracer(seed=0)
    bus = Bus(domain_id=1, tracer=tr)
    api = MapApiServer(bus, mapper=None, port=0)
    api.handle("/status")                        # one real span
    res = api.handle("/trace?since=0")
    assert res[0] == 200
    etag = res[3]["ETag"]
    assert etag.startswith('W/"trace-')
    # Same window, unchanged ring -> 304 with an empty body.
    res2 = api.handle("/trace?since=0",
                      headers=_Headers({"If-None-Match": etag}))
    assert res2[0] == 304 and res2[2] == b""
    assert res2[3]["ETag"] == etag
    # Ring advanced -> the stale ETag misses and fresh spans arrive.
    api.handle("/status")
    res3 = api.handle("/trace?since=0",
                      headers=_Headers({"If-None-Match": etag}))
    assert res3[0] == 200 and res3[3]["ETag"] != etag
    # Empty-window regression: a poller already at the head gets an
    # empty list and its own `since` back — never a stale `next`.
    head = tr.last_seq()
    doc = json.loads(api.handle(f"/trace?since={head}")[2])
    assert doc["traceEvents"] == [] and doc["next"] == head


# ------------------------------------- pipeline latency ledger (ISSUE 15)

def _ledger():
    from jax_mapping.obs.pipeline import PipelineLedger
    return PipelineLedger()


def test_pipeline_ledger_waypoints_fold_into_hops():
    """One revision's full waypoint chain produces all four hop
    observations plus the end-to-end sample and a completed record
    whose critical hop is the dominant one."""
    import time
    led = _ledger()
    led.note_tick(7)
    t0 = time.perf_counter()
    led.installed(3, enq_t=t0, tick=7)
    led.notified(3)
    led.encoded(3)
    led.delivered(3)
    hists = led.histograms()
    for hop in ("fuse", "notify", "encode", "deliver",
                "scan_to_served"):
        assert (hop, "") in hists, hop
        assert hists[(hop, "")]["count"] == 1
    (rec,) = led.records()
    assert rec["revision"] == 3 and rec["tick"] == 7
    assert set(rec["hops_ms"]) == {"fuse", "notify", "encode",
                                   "deliver"}
    assert rec["critical"] in rec["hops_ms"]
    assert rec["total_ms"] >= max(rec["hops_ms"].values()) - 1e-6
    assert led.p99_ms() is not None
    assert led.last_delivered() == (7, 3)


def test_pipeline_ledger_delivery_completes_superseded_revisions():
    """Serving revision N completes every pending revision <= N (a
    client holding N is at least as fresh as N-1 — freshness is
    cumulative), and later duplicate deliveries are no-ops."""
    led = _ledger()
    for rev in (1, 2, 3):
        led.installed(rev, tick=rev)
    led.delivered(3)
    recs = led.records()
    assert [r["revision"] for r in recs] == [1, 2, 3]
    assert led.status()["pending_revisions"] == 0
    led.delivered(3)                      # idempotent
    assert len(led.records()) == 3
    # A revision the ledger never saw installed still moves the
    # delivered mark (restore-resumed epochs serve unknown revisions).
    led.delivered(5)
    assert led.last_delivered()[1] == 5


def test_pipeline_ledger_bounded_and_tenant_sliced():
    """The pending table is bounded (an unserved mission cannot grow
    host memory), and tenant stamps land under their own label."""
    from jax_mapping.obs.pipeline import PipelineLedger
    led = PipelineLedger(pending_cap=8)
    for rev in range(20):
        led.installed(rev, tick=rev)
    assert led.status()["pending_revisions"] <= 8
    assert led.n_evicted >= 12
    led.installed(1, tick=1, tenant="t0")
    led.encoded(1, tenant="t0")
    led.delivered(1, tenant="t0")
    hists = led.histograms()
    assert ("deliver", "t0") in hists
    assert led.last_delivered("t0") == (0, 1)
    (rec,) = [r for r in led.records() if r["tenant"] == "t0"]
    assert rec["revision"] == 1


def test_pipeline_ledger_revision_age_is_monotonic_and_scoped():
    led = _ledger()
    assert led.revision_age_ms(1) is None         # pre-ledger revision
    led.installed(4, tick=1)
    age4 = led.revision_age_ms(4)
    assert age4 is not None and age4 >= 0
    # Serving revision 9 (never installed) falls back to the newest
    # known install at or below it; revision 3 predates the ledger.
    assert led.revision_age_ms(9) is not None
    assert led.revision_age_ms(3) is None
    assert led.revision_age_ms(None) is not None


def test_fixed_histogram_percentiles_bucket_resolved():
    from jax_mapping.obs.pipeline import FixedHistogram
    from jax_mapping.utils.profiling import HIST_EDGES_S
    h = FixedHistogram()
    assert h.percentile_ms(99) is None
    for _ in range(99):
        h.observe(0.0002)                 # below the first edge
    h.observe(1.0)                        # one outlier
    assert h.percentile_ms(50) == HIST_EDGES_S[0] * 1e3
    p99 = h.percentile_ms(99)
    assert p99 is not None and p99 <= HIST_EDGES_S[0] * 1e3
    assert h.percentile_ms(100) >= 1000.0 * 0.9


def test_server_timing_header_parse():
    from jax_mapping.serving.client import parse_revision_age_ms
    assert parse_revision_age_ms('rev;desc="42", age;dur=12.5') == 12.5
    assert parse_revision_age_ms("age;dur=0.0") == 0.0
    assert parse_revision_age_ms('rev;desc="42"') is None
    assert parse_revision_age_ms(None) is None
    assert parse_revision_age_ms("age;dur=bogus") is None


# ------------------------------------------------- SLO engine (ISSUE 15)

def _slo_cfg(**kw):
    from jax_mapping.config import SloObjective
    base = dict(name="obj", metric="tile_staleness_revs", threshold=5,
                fast_window_ticks=4, slow_window_ticks=8,
                fast_burn=0.5, slow_burn=0.25)
    base.update(kw)
    return SloObjective(**base)


def test_slo_engine_fires_and_clears_on_burn_windows():
    """Multi-window burn gating: breaches must fill BOTH windows'
    budgets to fire, and the alert clears when the fast window
    recovers — transitions flight-recorded with deterministic
    fields."""
    from jax_mapping.obs.recorder import flight_recorder
    from jax_mapping.obs.slo import SloEngine
    mark = flight_recorder.mark()
    eng = SloEngine((_slo_cfg(),))
    # Staleness grows with no deliveries (pipeline=None -> served
    # revision 0): breach from map_revision > 5.
    for t in range(1, 9):
        eng.evaluate(t, map_revision=t)
    st = eng.status()["objectives"][0]
    assert st["firing"], st
    assert st["last_fire_tick"] is not None
    fire_tick = st["last_fire_tick"]
    # Healing: staleness back under threshold -> the fast window
    # drains below its burn budget -> clear.
    for t in range(9, 16):
        eng.evaluate(t, map_revision=1)
    st = eng.status()["objectives"][0]
    assert not st["firing"]
    assert st["n_fired"] == 1 and st["n_cleared"] == 1
    evs = [e for e in flight_recorder.events_since(mark)
           if e["kind"] == "slo_alert"]
    assert [(e["state"], e["tick"]) for e in evs] == [
        ("firing", fire_tick),
        ("clear", st["last_clear_tick"])]


def test_slo_engine_same_inputs_fire_at_identical_steps():
    """The determinism contract at the engine level: two engines fed
    the identical evaluation sequence fire and clear at the identical
    ticks (burn denominators are the FIXED window sizes; everything is
    clocked in ticks)."""
    from jax_mapping.obs.slo import SloEngine

    def drive():
        eng = SloEngine((_slo_cfg(),))
        for t in range(1, 40):
            rev = t if t < 25 else 1
            eng.evaluate(t, map_revision=rev)
        return eng.alerts()

    a, b = drive(), drive()
    assert a == b and a, a


def test_slo_engine_silent_ticks_guard_breaches_without_samples():
    """The ingest-stall guard: a partition delivers NO scan→served
    samples, so the p99 predicate alone can never see the outage —
    silence past `max_silent_ticks` breaches instead."""
    from jax_mapping.obs.pipeline import PipelineLedger
    from jax_mapping.obs.slo import SloEngine
    led = PipelineLedger()
    cfg = _slo_cfg(metric="scan_to_served_p99_ms", threshold=1e9,
                   max_silent_ticks=3)
    eng = SloEngine((cfg,), pipeline=led)
    led.installed(1, tick=2)
    fired_at = None
    for t in range(1, 20):
        eng.evaluate(t, map_revision=1)
        st = eng.status()["objectives"][0]
        if st["firing"] and fired_at is None:
            fired_at = t
    assert fired_at is not None
    st = eng.status()["objectives"][0]
    # silent_ticks surfaced for the operator on /status.slo.
    assert st["silent_ticks"] == 19 - 2
    # Breaches begin at tick 6 (silence > 3 past install tick 2):
    # fast window (4, burn 0.5) fills at 7, slow (8, burn 0.25) at 7.
    assert fired_at == 7, fired_at


def test_slo_engine_tick_deadline_metric():
    from jax_mapping.obs.slo import SloEngine
    eng = SloEngine((_slo_cfg(metric="tick_deadline_ms",
                              threshold=100.0),))
    for t in range(1, 10):
        eng.evaluate(t, tick_ms=500.0)
    st = eng.status()["objectives"][0]
    assert st["firing"] and st["value"] == 500.0
    fams = {f.name for f in eng.metric_families()}
    assert "jax_mapping_slo_firing" in fams
    assert "jax_mapping_slo_burn_rate_fast" in fams


def test_racewatch_gate_cross_thread_pipeline_stamps():
    """ISSUE 15 CI satellite: hammer one PipelineLedger's stamp surface
    from concurrent threads (mapper tick installs / HTTP delivery /
    tenancy stepping in miniature) under RaceWatch — zero reports, and
    the stamp counter's candidate lockset converges on the declared
    ledger lock."""
    from jax_mapping.analysis.protection import groups_by_class
    from jax_mapping.analysis.racewatch import RaceWatch
    from jax_mapping.obs.pipeline import PipelineLedger

    led = PipelineLedger()
    watch = RaceWatch()
    try:
        watch.watch_object(led, groups_by_class()["PipelineLedger"][0],
                           name="led")

        def worker(tid):
            tenant = "" if tid % 2 == 0 else f"t{tid}"
            for k in range(150):
                led.note_tick(k)
                led.installed(k, tick=k, tenant=tenant)
                led.notified(k, tenant=tenant)
                led.encoded(k, tenant=tenant)
                led.delivered(k, tenant=tenant)
                if k % 25 == 0:
                    led.status()
                    led.histograms()
                    led.records()
                    led.revision_age_ms(k, tenant=tenant)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        watch.unwatch_all()
    assert watch.reports() == [], \
        "\n".join(r.message for r in watch.reports())
    counter = watch.field_states()["PipelineLedger.n_stamps@led"]
    assert counter.state == "shared-modified"
    assert "PipelineLedger._lock@led" in counter.candidate


# -------------------------------------------- critical-path CLI (ISSUE 15)

def _pipeline_dump(tmp_path, name, hops):
    doc = {"reason": "test", "events": [], "spans": [],
           "pipeline": [
               {"revision": r, "tenant": "", "tick": r,
                "hops_ms": dict(h), "total_ms": sum(h.values()),
                "critical": max(h, key=h.get)}
               for r, h in hops]}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_obs_cli_critical_path_report_and_diff(tmp_path, capsys):
    hops_a = [(1, {"fuse": 1.0, "deliver": 9.0}),
              (2, {"fuse": 7.0, "deliver": 2.0})]
    a = _pipeline_dump(tmp_path, "a.json", hops_a)
    assert obs_main(["critical-path", a]) == 0
    out = capsys.readouterr().out
    assert "2 completed revision(s)" in out
    assert "dominant in 1 revision(s)" in out
    # Same structure, different timings: identical after normalization
    # (hop durations and dominance are volatile by design).
    hops_b = [(1, {"fuse": 8.0, "deliver": 1.0}),
              (2, {"fuse": 1.0, "deliver": 8.0})]
    b = _pipeline_dump(tmp_path, "b.json", hops_b)
    assert obs_main(["critical-path", a, b]) == 0
    assert "structurally identical" in capsys.readouterr().out
    # Structural divergence (an extra revision) is exit 1.
    hops_c = hops_a + [(3, {"fuse": 1.0})]
    c = _pipeline_dump(tmp_path, "c.json", hops_c)
    assert obs_main(["critical-path", a, c]) == 1
    # No pipeline section: usage error, not a crash.
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"events": [], "spans": []}))
    assert obs_main(["critical-path", str(empty)]) == 2


def test_recorder_dump_carries_pipeline_section(tmp_path):
    """A configured ledger's completed records ride every dump as its
    `pipeline` section — and the dump stays same-seed diffable to zero
    (diff compares only events+spans)."""
    from jax_mapping.obs.pipeline import PipelineLedger
    rec = FlightRecorder(capacity=64)
    led = PipelineLedger()
    rec.configure(dump_dir=str(tmp_path), pipeline=led)
    led.installed(1, tick=1)
    led.delivered(1)
    rec.record("map_revision", revision=1)
    path = rec.dump("test")
    doc = json.load(open(path))
    assert [r["revision"] for r in doc["pipeline"]] == [1]
    res = diff_dumps(doc, {"events": doc["events"], "spans": [],
                           "pipeline": []})
    assert res["identical"]


def test_pipeline_non_ingest_install_does_not_feed_silence_guard():
    """Regression (caught by a live drive): a decay pass stamps its
    revision for age bookkeeping but is NOT scan ingest — it must not
    advance the ingest-stall clock, or a healing cadence running
    through a scan-path outage re-arms the silence guard every pass
    and the outage alert flaps instead of holding."""
    led = _ledger()
    led.installed(1, tick=5)                      # real scan ingest
    assert led.last_install_tick() == 5
    led.installed(2, tick=12, ingest=False)       # decay pass
    assert led.last_install_tick() == 5           # clock unmoved
    assert led.revision_age_ms(2) is not None     # age still honest
    led.installed(3, tick=14)                     # ingest resumes
    assert led.last_install_tick() == 14


def test_pipeline_epoch_restart_resets_ages_and_delivered_mark():
    """Review regressions: a restarted epoch replays SMALLER revision
    numbers. (1) `revision_age_ms(None)` must track the NEWEST install
    — re-inserting an old revision key must reorder it to the tail, or
    the newest-age read reports the dead epoch's stamp forever. (2) a
    delivery stamped with a NEW epoch resets the delivered mark, so
    the staleness objective follows the new numbering instead of
    reading negative until it outgrows the old epoch's mark."""
    import time as _time
    led = _ledger()
    for rev in (1, 2, 3):
        led.installed(rev, tick=rev)
    led.delivered(3, epoch=0)
    assert led.last_delivered()[1] == 3
    _time.sleep(0.01)
    # Epoch restart: revision numbering starts over.
    led.installed(1, tick=10)                    # re-inserts key 1
    age = led.revision_age_ms(None)
    assert age is not None and age < 8.0, \
        f"newest-install age reports the dead epoch: {age} ms"
    # Old-epoch mark would make staleness negative; the epoch stamp
    # resets it to the new epoch's delivery.
    led.delivered(1, epoch=1)
    assert led.last_delivered()[1] == 1
    # Same-epoch idle repeat (the steady 304 poll): fast-pathed, mark
    # unchanged, nothing completed twice.
    n = led.n_completed
    led.delivered(1, epoch=1)
    assert led.n_completed == n and led.last_delivered()[1] == 1
