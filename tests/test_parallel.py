"""Distributed tests on the virtual 8-device CPU mesh."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import tiny_config
from jax_mapping.ops import grid as G
from jax_mapping.parallel import fleet_sharded as FS
from jax_mapping.parallel import mesh as MESH
from jax_mapping.sim import world as W


@pytest.fixture(scope="module")
def cfg():
    c = tiny_config()
    return dataclasses.replace(
        c, fleet=dataclasses.replace(c.fleet, n_robots=8))


def test_factor_devices():
    assert MESH.factor_devices(8) == (4, 2)
    assert MESH.factor_devices(7) == (7, 1)
    assert MESH.factor_devices(16) == (4, 4)
    assert MESH.factor_devices(1) == (1, 1)


def test_make_mesh_shapes():
    m = MESH.make_mesh()
    assert m.shape["fleet"] * m.shape["space"] == len(jax.devices())
    m2 = MESH.make_mesh(n_fleet=2, n_space=4)
    assert m2.shape == {"fleet": 2, "space": 4}
    with pytest.raises(ValueError):
        MESH.make_mesh(n_fleet=3, n_space=3)


def test_sharded_fleet_step_runs(cfg):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    mesh = MESH.make_mesh(n_fleet=4, n_space=2)
    # 4.8 m arena: walls inside the tiny config's 3 m scan range.
    world = jnp.asarray(W.empty_arena(96, cfg.grid.resolution_m))
    state = FS.init_sharded_state(cfg, mesh)
    step = FS.make_fleet_step(cfg, mesh, cfg.grid.resolution_m)
    for _ in range(3):
        state, metrics = step(state, world)
    assert int(state.t) == 3
    assert np.isfinite(float(metrics["mean_pose_err_m"]))
    occ = np.asarray(G.to_occupancy(cfg.grid, state.grid))
    assert (occ == 100).sum() > 30       # walls fused into the sharded grid
    assert (occ == 0).sum() > 100


def test_sharded_matches_single_device_fusion(cfg):
    """The sharded psum-merge fusion must equal the single-device batched
    fusion for the same scans/poses (same robots, same order)."""
    from jax_mapping.sim import lidar
    mesh = MESH.make_mesh(n_fleet=4, n_space=2)
    g, s = cfg.grid, cfg.scan
    R = cfg.fleet.n_robots
    rng = np.random.default_rng(3)
    poses = np.stack([rng.uniform(-0.8, 0.8, R), rng.uniform(-0.8, 0.8, R),
                      rng.uniform(-3, 3, R)], 1).astype(np.float32)
    world = jnp.asarray(W.empty_arena(96, g.resolution_m))
    scans = lidar.simulate_scans(s, world, g.resolution_m, 128,
                                 jnp.asarray(poses))

    # Single-device reference: unclamped delta accumulation then one clamp.
    delta_full = G.scan_deltas_full(g, s, scans, jnp.asarray(poses))
    want = G.merge_delta(g, G.empty_grid(g), delta_full)

    # Sharded: slab deltas + psum along fleet via shard_map.
    from jax.sharding import PartitionSpec as P
    slab_rows = g.size_cells // 2

    def fuse_only(grid, scans_l, poses_l):
        row0 = jax.lax.axis_index("space") * slab_rows
        d = FS._slab_delta(cfg, scans_l, poses_l, row0, slab_rows)
        d = jax.lax.psum(d, "fleet")
        return jnp.clip(grid + d, g.logodds_min, g.logodds_max)

    fn = jax.jit(jax.shard_map(
        fuse_only, mesh=mesh,
        in_specs=(P("space", None), P("fleet", None), P("fleet", None)),
        out_specs=P("space", None), check_vma=False))
    got = fn(G.empty_grid(g), scans, jnp.asarray(poses))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


# ---------------------------------------------------------------------------
# Distributed (DCN) backend
# ---------------------------------------------------------------------------

def test_dist_config_from_env():
    from jax_mapping.parallel.distributed import DistConfig
    cfg = DistConfig.from_env(env={})
    assert cfg.num_processes == 1 and cfg.coordinator_address is None
    cfg = DistConfig.from_env(env={
        "JAX_MAPPING_COORDINATOR": "10.0.0.1:1234",
        "JAX_MAPPING_NUM_PROCESSES": "4",
        "JAX_MAPPING_PROCESS_ID": "2"})
    assert cfg.coordinator_address == "10.0.0.1:1234"
    assert cfg.num_processes == 4 and cfg.process_id == 2
    # Standard JAX names as fallback.
    cfg = DistConfig.from_env(env={"JAX_COORDINATOR_ADDRESS": "h:1",
                                   "JAX_NUM_PROCESSES": "2"})
    assert cfg.coordinator_address == "h:1" and cfg.num_processes == 2


def test_initialize_single_process_noop():
    from jax_mapping.parallel.distributed import DistConfig, initialize
    assert initialize(DistConfig()) is False          # no-op, no crash


def test_hybrid_mesh_single_host_degrades_to_local():
    from jax_mapping.parallel.distributed import hybrid_fleet_mesh
    mesh = hybrid_fleet_mesh()
    assert mesh.axis_names == ("fleet", "space")
    assert mesh.devices.size == 8                     # virtual CPU mesh


def test_hybrid_mesh_simulated_two_hosts(monkeypatch):
    """Treat the 8 virtual CPU devices as 2 hosts x 4: fleet axis must be
    host-major so the space axis stays intra-host (ICI)."""
    import jax
    from jax_mapping.parallel import distributed as D
    monkeypatch.setattr(jax, "local_device_count", lambda: 4)
    mesh = D.hybrid_fleet_mesh(n_hosts=2, space_per_host=2)
    assert mesh.devices.shape == (4, 2)
    # Each space row must use consecutive device ids (same "host" block).
    ids = [[d.id for d in row] for row in mesh.devices]
    for row in ids:
        assert abs(row[0] - row[1]) == 1


def test_initialize_half_configured_raises():
    import pytest
    from jax_mapping.parallel.distributed import DistConfig, initialize
    with pytest.raises(ValueError):
        initialize(DistConfig(num_processes=4, coordinator_address=None))


def test_sharded_repair_matches_local_refusion(cfg):
    """The sharded closure's map repair (psum of per-shard slab re-fusions
    from rings) must equal the local fleet's full re-fusion
    (fuse_scans_masked) — the round-2 VERDICT flagged the rings-only
    repair as untested at any scale (weak #5)."""
    from jax_mapping.sim import lidar
    mesh = MESH.make_mesh(n_fleet=4, n_space=2)
    g, s = cfg.grid, cfg.scan
    R = cfg.fleet.n_robots
    cap = 8
    rng = np.random.default_rng(11)
    world = jnp.asarray(W.empty_arena(96, g.resolution_m))

    # Synthetic rings: each robot has `cap` key scans along a short arc,
    # a few slots invalid (unfilled ring tail). Poses stay near the arena
    # centre: the local path crops each scan to its aligned patch while
    # the slab path keeps the whole slab, so hits at the extreme range
    # margin (patch half-width minus alignment slack) are the one place
    # the two legitimately differ — keep all hits inside it.
    poses = rng.uniform(-0.1, 0.1, (R, cap, 3)).astype(np.float32)
    poses[:, :, 2] = rng.uniform(-3, 3, (R, cap))
    valid = rng.random((R, cap)) < 0.7
    rings = lidar.simulate_scans(
        s, world, g.resolution_m, 128,
        jnp.asarray(poses.reshape(R * cap, 3))).reshape(R, cap, -1)

    # Local reference: the repair grid _close_loops builds.
    want = G.fuse_scans_masked(
        g, s, G.empty_grid(g),
        rings.reshape(R * cap, -1),
        jnp.asarray(poses.reshape(R * cap, 3)),
        jnp.asarray(valid.reshape(R * cap)))

    # Sharded: per-shard slab deltas from local rings, psum over fleet —
    # exactly the close() branch's repair computation in fleet_sharded.
    from jax.sharding import PartitionSpec as P
    slab_rows = g.size_cells // 2

    def repair_only(rings_l, poses_l, valid_l):
        Rl = rings_l.shape[0]
        row0 = jax.lax.axis_index("space") * slab_rows
        d = FS._slab_delta(cfg, rings_l.reshape(Rl * cap, -1),
                           poses_l.reshape(Rl * cap, 3), row0, slab_rows,
                           mask=valid_l.reshape(Rl * cap))
        d = jax.lax.psum(d, "fleet")
        return jnp.clip(d, g.logodds_min, g.logodds_max)

    fn = jax.jit(jax.shard_map(
        repair_only, mesh=mesh,
        in_specs=(P("fleet"), P("fleet"), P("fleet")),
        out_specs=P("space", None), check_vma=False))
    got = fn(rings, jnp.asarray(poses), jnp.asarray(valid))
    got_n, want_n = np.asarray(got), np.asarray(want)
    # The two repairs differ ONLY in clamp order (local: sequential
    # clamped fold; sharded: accumulate once, clamp once — the same
    # documented trade as fuse_scans_window). Occupancy classification
    # must agree exactly, and raw log-odds wherever no clamp bound was
    # hit on either side.
    occ_got = np.asarray(G.to_occupancy(g, got))
    occ_want = np.asarray(G.to_occupancy(g, want))
    np.testing.assert_array_equal(occ_got, occ_want)
    # Raw log-odds agree everywhere the sequential fold never hit a clamp
    # bound mid-fold; with 64 overlapping scans that is still the vast
    # majority of the grid.
    frac_diff = float((np.abs(got_n - want_n) > 1e-5).mean())
    assert frac_diff < 0.01, f"{frac_diff:.4f} of cells differ"
