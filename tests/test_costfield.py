"""Multigrid batched cost fields (ops/costfield.py) vs the exact dilation.

Properties pinned:
  * open map: multigrid == exact geodesic (chamfer 8-neighbour) distance;
  * walled map: multigrid never UNDERestimates the exact distance (the
    upper-bound contract the frontier auction relies on), and reaches
    cells the exact field reaches whenever corridors are >= 2 coarse cells;
  * blocked cells hold _BIG; robot seed cell is 0 even inside a
    conservatively-blocked cell;
  * the XLA twin and the Pallas (interpret) kernel agree exactly;
  * the frontier pipeline produces the same assignments as exact_bfs on a
    toy map.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.config import FrontierConfig, GridConfig
from jax_mapping.ops import costfield as CF
from jax_mapping.ops import frontier as F

BIG = float(CF._BIG)


def exact_field(blocked, rc, iters=None):
    """Reference: full-convergence single-field dilation in NumPy."""
    n = blocked.shape[0]
    d = np.full((n, n), BIG, np.float32)
    blk = blocked.copy()
    blk[rc[0], rc[1]] = False
    d[rc[0], rc[1]] = 0.0
    sq2 = np.float32(1.41421356)
    for _ in range(iters or 2 * n):
        best = d.copy()
        for dr, dc, w in ((1, 0, 1), (-1, 0, 1), (0, 1, 1), (0, -1, 1),
                          (1, 1, sq2), (1, -1, sq2), (-1, 1, sq2),
                          (-1, -1, sq2)):
            sh = np.full_like(d, BIG)
            if dr >= 0 and dc >= 0:
                sh[dr:, dc:] = d[:n - dr, :n - dc]
            elif dr >= 0 > dc:
                sh[dr:, :dc] = d[:n - dr, -dc:]
            elif dr < 0 <= dc:
                sh[:dr, dc:] = d[-dr:, :n - dc]
            else:
                sh[:dr, :dc] = d[-dr:, -dc:]
            best = np.minimum(best, sh + w)
        new = np.where(blk, BIG, best)
        if np.array_equal(new, d):
            break
        d = new
    return d


def test_open_map_bounded_upper_bound():
    n = 64
    blocked = np.zeros((n, n), bool)
    rc = np.array([[10, 12], [50, 40]], np.int32)
    levels, refine = 3, 8
    got = np.asarray(CF.cost_fields(jnp.asarray(blocked), jnp.asarray(rc),
                                    levels=levels, refine_iters=refine))
    for i in range(2):
        want = exact_field(blocked, rc[i])
        diff = got[i] - want
        # Contract: strict upper bound, overestimate bounded by the
        # accumulated per-level slack (+2 cells per upsample plus the
        # corner-cut), and EXACT near the seed where the finest level's
        # refinement fully converges (a doubled sweep moves 2 cells).
        assert diff.min() >= -1e-3, "multigrid underestimated a distance"
        assert diff.max() <= 3.0 * levels
        rr, cc = np.mgrid[0:n, 0:n]
        near = np.maximum(np.abs(rr - rc[i, 0]),
                          np.abs(cc - rc[i, 1])) <= refine
        np.testing.assert_allclose(got[i][near], want[near], atol=1e-3)


def test_walled_map_upper_bound_and_reaches():
    n = 64
    blocked = np.zeros((n, n), bool)
    blocked[20, :40] = True            # wall with an opening on the right
    rc = np.array([[10, 10]], np.int32)
    got = np.asarray(CF.cost_fields(jnp.asarray(blocked), jnp.asarray(rc),
                                    levels=3, refine_iters=16))[0]
    want = exact_field(blocked, rc[0])
    reach = want < BIG
    # Upper bound everywhere (small epsilon for float sweep ordering).
    assert (got[reach] >= want[reach] - 1e-3).all()
    # The far side of the wall is reached through the opening.
    assert got[40, 10] < BIG
    assert got[40, 10] >= want[40, 10] - 1e-3
    # Blocked cells stay BIG.
    assert (got[blocked] >= BIG).all()


def test_wall_hugger_does_not_leak_for_fleet():
    """Regression: a robot standing in a conservatively-blocked cell must
    not open that cell in OTHER robots' fields — a shared opening punches
    a hole through the wall for the whole fleet and produces finite costs
    to unreachable cells."""
    n = 64
    blocked = np.zeros((n, n), bool)
    blocked[:, 33] = True              # solid wall, no openings
    rc = np.array([[16, 32],           # robot B hugging the wall
                   [16, 4]], np.int32)  # robot A far west
    got = np.asarray(CF.cost_fields(jnp.asarray(blocked), jnp.asarray(rc),
                                    levels=3, refine_iters=8))
    # Robot A must see the east side as unreachable.
    assert got[1, 48, 50] >= BIG, \
        "robot A crossed a solid wall through robot B's seed cell"
    # Robot B itself also cannot cross (its cell is west of the wall —
    # even though its POOLED coarse cell straddles it).
    assert got[0, 48, 50] >= BIG
    # A (open space) reaches the far west; B (wall-hugger, fine cell open
    # here but coarse cells blocked) keeps a bounded local field — near
    # cells reachable, and that's the documented conservatism.
    assert got[1, 30, 10] < BIG
    assert got[0, 20, 28] < BIG


def test_seed_cell_zero_even_when_blocked():
    n = 32
    blocked = np.ones((n, n), bool)    # everything blocked
    rc = np.array([[5, 5]], np.int32)
    got = np.asarray(CF.cost_fields(jnp.asarray(blocked), jnp.asarray(rc),
                                    levels=2, refine_iters=2))[0]
    assert got[5, 5] == 0.0


def test_xla_twin_matches_pallas_interpret():
    n = 64
    rng = np.random.default_rng(0)
    blocked = rng.random((n, n)) < 0.2
    rc = np.array([[3, 3], [60, 50], [32, 32], [8, 55]], np.int32)
    init = np.full((len(rc), n, n), BIG, np.float32)
    for i in range(len(rc)):
        blocked[rc[i, 0], rc[i, 1]] = False
        init[i, rc[i, 0], rc[i, 1]] = 0.0
    blk = jnp.asarray(blocked)
    a = np.asarray(CF._relax_level_pallas(blk, jnp.asarray(init), iters=12))
    b = np.asarray(CF._relax_level_xla(blk, jnp.asarray(init), iters=12))
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_frontier_pipeline_multigrid_vs_exact_assignment():
    gcfg = GridConfig(size_cells=128, patch_cells=64, max_range_m=2.0,
                      align_rows=8, align_cols=8)
    fcfg = FrontierConfig(downsample=2, cluster_downsample=1, max_clusters=8,
                          min_cluster_cells=2, label_prop_iters=64,
                          bfs_iters=256, obstacle_aware=True)
    n = gcfg.size_cells
    lo = np.zeros((n, n), np.float32)
    lo[30:100, 30:100] = -2.0
    lo[30:100, 64:66] = 2.0            # wall splitting the room
    lo[60:70, 64:66] = -2.0            # door
    import dataclasses
    poses = jnp.asarray(np.array([[1.8, 1.8, 0.0], [4.2, 1.8, 0.0]],
                                 np.float32))
    res_mg = F.compute_frontiers(fcfg, gcfg, jnp.asarray(lo), poses)
    res_ex = F.compute_frontiers(
        dataclasses.replace(fcfg, exact_bfs=True), gcfg,
        jnp.asarray(lo), poses)
    # Same clusters detected; costs may differ (upper bound) but the
    # greedy auction must land on the same assignment on this map.
    assert (np.asarray(res_mg.sizes) == np.asarray(res_ex.sizes)).all()
    assert (np.asarray(res_mg.assignment) == np.asarray(res_ex.assignment)).all()
    # Multigrid costs never undercut exact costs where both are finite.
    cm, ce = np.asarray(res_mg.costs), np.asarray(res_ex.costs)
    both = (cm < BIG) & (ce < BIG)
    assert (cm[both] >= ce[both] - 1e-2).all()
