"""Branch-and-bound pruned matcher: argmax/score parity vs the exhaustive
oracle, pyramid admissibility, revision-keyed cache invalidation, and the
exhaustive path's knob-independence (the `MatcherConfig.pruned=False`
bit-identity contract)."""

import dataclasses
import math
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_mapping.ops import grid as G
from jax_mapping.ops import pyramid as PYR
from jax_mapping.ops import scan_match as M


def room_scan(scan_cfg, pose, half=2.0):
    """Analytic scan of a square room centred at the origin."""
    out = np.zeros(scan_cfg.padded_beams, np.float32)
    for b in range(scan_cfg.n_beams):
        a = pose[2] + b * scan_cfg.angle_increment_rad
        ca, sa = math.cos(a), math.sin(a)
        rx = ((half if ca > 0 else -half) - pose[0]) / ca \
            if abs(ca) > 1e-9 else 1e9
        ry = ((half if sa > 0 else -half) - pose[1]) / sa \
            if abs(sa) > 1e-9 else 1e9
        out[b] = min(rx, ry)
    return out


def build_room_map(cfg, half=2.0, n_scans=8, seed=0):
    g, s = cfg.grid, cfg.scan
    rng = np.random.default_rng(seed)
    poses, scans = [], []
    for _ in range(n_scans):
        p = np.array([rng.uniform(-0.3, 0.3), rng.uniform(-0.3, 0.3),
                      rng.uniform(-math.pi, math.pi)], np.float32)
        poses.append(p)
        scans.append(room_scan(s, p, half))
    return G.fuse_scans(g, s, G.empty_grid(g),
                        jnp.asarray(np.stack(scans)),
                        jnp.asarray(np.stack(poses)))


def assert_match_parity(g, s, m, grid, scan, guess):
    """Pruned and exhaustive must pick the same coarse winner — and a
    matching winner implies a BIT-identical refined pose (the fine
    stages are shared code on identical inputs)."""
    r_ex = M.match(g, s, dataclasses.replace(m, pruned=False), grid,
                   jnp.asarray(scan), jnp.asarray(guess))
    r_pr = M.match(g, s, dataclasses.replace(m, pruned=True), grid,
                   jnp.asarray(scan), jnp.asarray(guess))
    np.testing.assert_array_equal(np.asarray(r_ex.pose),
                                  np.asarray(r_pr.pose))
    assert float(r_ex.response) == float(r_pr.response)
    # The winner-angle surface re-scores through the same conv but at
    # batch size 1 vs A — XLA vectorises the reduction differently, so
    # the value may differ by an ulp (pose/argmax stay exact).
    np.testing.assert_allclose(float(r_ex.coarse_response),
                               float(r_pr.coarse_response), rtol=1e-5)
    assert bool(r_ex.accepted) == bool(r_pr.accepted)
    # The pruned covariance reads the level-1 block surface (wider
    # quantisation floor, admissibly-smoothed moments): finite, positive,
    # and never tighter than the exhaustive floor.
    cov_pr = np.asarray(r_pr.cov)
    assert np.isfinite(cov_pr).all() and (cov_pr > 0).all()
    assert (cov_pr[:2] >= np.asarray(r_ex.cov)[:2] * 0.5).all()
    assert int(r_pr.n_candidates) < int(r_ex.n_candidates)
    assert 0.0 < float(r_pr.prune_ratio) < 1.0
    assert float(r_ex.prune_ratio) == 0.0
    return r_ex, r_pr


def test_pruned_argmax_parity_random_worlds(tiny_cfg):
    """Property: across random rooms, true poses, and odometry drifts the
    pruned matcher returns the exhaustive sweep's pose exactly."""
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    rng = np.random.default_rng(7)
    for trial in range(5):
        half = float(rng.uniform(1.2, 2.3))
        grid = build_room_map(tiny_cfg, half=half, seed=trial)
        true_pose = np.array([rng.uniform(-0.25, 0.25),
                              rng.uniform(-0.25, 0.25),
                              rng.uniform(-0.5, 0.5)], np.float32)
        scan = room_scan(s, true_pose, half)
        guess = true_pose + np.array(
            [rng.uniform(-0.1, 0.1), rng.uniform(-0.1, 0.1),
             rng.uniform(-0.15, 0.15)], np.float32)
        assert_match_parity(g, s, m, grid, scan, guess)


def test_pruned_parity_across_window_sizes(tiny_cfg):
    """Parity must hold as the search window (and thus pyramid depth)
    changes — including a strided coarse step and a forced depth."""
    g, s = tiny_cfg.grid, tiny_cfg.scan
    grid = build_room_map(tiny_cfg)
    true_pose = np.array([0.1, -0.05, 0.2], np.float32)
    scan = room_scan(s, true_pose)
    guess = true_pose + np.array([0.05, 0.04, 0.1], np.float32)
    variants = [
        dataclasses.replace(tiny_cfg.matcher, search_half_extent_m=0.15),
        dataclasses.replace(tiny_cfg.matcher, search_half_extent_m=0.4),
        dataclasses.replace(tiny_cfg.matcher, coarse_step_m=0.1),
        dataclasses.replace(tiny_cfg.matcher, bnb_levels=1),
        dataclasses.replace(tiny_cfg.matcher, bnb_topk=32),
    ]
    for m in variants:
        assert_match_parity(g, s, m, grid, scan, guess)


def test_pruned_parity_across_map_revisions(tiny_cfg):
    """The map evolves (new scans fuse, walls sharpen) — parity must hold
    at every revision, not just on a converged map."""
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    rng = np.random.default_rng(3)
    grid = G.empty_grid(g)
    true_pose = np.array([0.08, -0.1, 0.15], np.float32)
    scan = room_scan(s, true_pose)
    guess = true_pose + np.array([0.06, 0.05, 0.08], np.float32)
    for rev in range(4):
        p = np.array([rng.uniform(-0.3, 0.3), rng.uniform(-0.3, 0.3),
                      rng.uniform(-3, 3)], np.float32)
        grid = G.fuse_scans(g, s, grid,
                            jnp.asarray(room_scan(s, p))[None],
                            jnp.asarray(p)[None])
        r_ex = M.match(g, s, dataclasses.replace(m, pruned=False), grid,
                       jnp.asarray(scan), jnp.asarray(guess))
        r_pr = M.match(g, s, dataclasses.replace(m, pruned=True), grid,
                       jnp.asarray(scan), jnp.asarray(guess))
        np.testing.assert_array_equal(np.asarray(r_ex.pose),
                                      np.asarray(r_pr.pose))


def test_pyramid_levels_are_exact_block_maxima(tiny_cfg, rng):
    """Dual-pyramid oracle: levels[l][Y, X] == max over the 2^l x 2^l
    cell block at (2^l Y, 2^l X) of the SLIDING shift-window maxima
    F_l[x] = max_{d < 2^l} f0[x + stride*d] (numpy oracle per the
    build_levels docstring) — the admissible field side of the
    sum-pooled-raster x max-pooled-field bound."""
    stride, n_steps = 2, 4
    field = jnp.asarray(rng.random((48, 48)).astype(np.float32))
    n_levels = 3
    levels = M.build_levels(field, n_steps, stride, n_levels)
    pad = n_steps * stride
    f0 = np.asarray(levels[0])
    np.testing.assert_array_equal(f0, np.pad(np.asarray(field), pad))
    H, W = f0.shape

    def sliding(lv):
        out = np.zeros_like(f0)
        for y in range(H):
            for x in range(W):
                vals = [f0[y + stride * dy, x + stride * dx]
                        for dy in range(2 ** lv) for dx in range(2 ** lv)
                        if y + stride * dy < H and x + stride * dx < W]
                out[y, x] = max(vals)
        return out

    for lv in range(1, n_levels + 1):
        q = 2 ** lv
        fl = np.asarray(levels[lv])
        sl = sliding(lv)
        for Y in range(fl.shape[0]):
            for X in range(fl.shape[1]):
                blk = sl[q * Y:q * (Y + 1), q * X:q * (X + 1)]
                assert fl[Y, X] == (blk.max() if blk.size else 0.0)


def test_top_level_scores_are_admissible_bounds(tiny_cfg):
    """Every top-level node score must be >= the exact score of every
    leaf candidate in its block (the branch-and-bound soundness
    property), up to conv-vs-einsum rounding."""
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    grid = build_room_map(tiny_cfg)
    guess = jnp.asarray(np.array([0.05, 0.02, 0.1], np.float32))
    scan = jnp.asarray(room_scan(s, np.array([0.0, 0.0, 0.0])))
    stride, n_steps = M.window_params(g, m)
    lv = M.bnb_num_levels(m, n_steps)
    origin = G.patch_origin(g, guess[:2])
    patch = jax.lax.dynamic_slice(grid, (origin[0], origin[1]),
                                  (g.patch_cells, g.patch_cells))
    field = M.likelihood_field(g, m, patch)
    levels = M.build_levels(field, n_steps, stride, lv)
    resp_top, rasters_c, mass_ref = M.pyramid_coarse_scores(
        g, s, m, lv, levels, origin, scan, guess)
    resp_top = np.asarray(resp_top)
    # The exhaustive full-resolution surface (all angles x all leaves).
    dth_c, rasters, mass = M._bnb_setup(g, s, m, origin, scan, guess)
    resp_full = np.asarray(M._conv_scores(field, rasters, mass, n_steps,
                                          stride))
    A, nw = resp_full.shape[0], 2 * n_steps + 1
    blk = 2 ** lv
    Mn = resp_top.shape[1]
    for a in range(A):
        for my in range(Mn):
            for mx in range(Mn):
                leaves = resp_full[a,
                                   my * blk:min((my + 1) * blk, nw),
                                   mx * blk:min((mx + 1) * blk, nw)]
                if leaves.size:
                    assert resp_top[a, my, mx] >= leaves.max() - 1e-5


def test_match_with_pyramid_and_split_parity(tiny_cfg):
    """The host-driven cached entries (single-dispatch and the
    coarse/refine split with donated score buffer) must reproduce the
    in-graph pruned match bit-for-bit."""
    g, s, m = tiny_cfg.grid, tiny_cfg.scan, tiny_cfg.matcher
    grid = build_room_map(tiny_cfg)
    true_pose = np.array([0.1, -0.08, 0.2], np.float32)
    scan = jnp.asarray(room_scan(s, true_pose))
    guess = jnp.asarray(true_pose + np.array([0.05, 0.03, 0.08],
                                             np.float32))
    stride, n_steps = M.window_params(g, m)
    lv = M.bnb_num_levels(m, n_steps)
    origin = G.patch_origin(g, guess[:2])
    levels = PYR.build_match_pyramid(g, m, lv, grid, origin)
    r0 = M.match(g, s, m, grid, scan, guess)
    r1 = M.match_with_pyramid(g, s, m, lv, levels, origin, scan, guess)
    resp_top, rasters_c, mass_ref = M.pyramid_coarse_scores(
        g, s, m, lv, levels, origin, scan, guess)
    r2 = M.pyramid_refine(g, s, m, lv, resp_top, levels, origin, scan,
                          rasters_c, mass_ref, guess)
    for r in (r1, r2):
        np.testing.assert_array_equal(np.asarray(r0.pose),
                                      np.asarray(r.pose))
        assert float(r0.response) == float(r.response)


def test_exhaustive_path_ignores_bnb_knobs(tiny_cfg):
    """pruned=False must be byte-identical regardless of the new knobs —
    the pre-PR pipeline does not read them."""
    g, s = tiny_cfg.grid, tiny_cfg.scan
    grid = build_room_map(tiny_cfg)
    true_pose = np.array([0.1, -0.08, 0.2], np.float32)
    scan = jnp.asarray(room_scan(s, true_pose))
    guess = jnp.asarray(true_pose + np.array([0.05, 0.03, 0.08],
                                             np.float32))
    base = M.match(g, s, dataclasses.replace(tiny_cfg.matcher,
                                             pruned=False),
                   grid, scan, guess)
    for m in (dataclasses.replace(tiny_cfg.matcher, pruned=False,
                                  bnb_topk=1, bnb_levels=5),
              dataclasses.replace(tiny_cfg.matcher, pruned=False,
                                  bnb_topk=999)):
        r = M.match(g, s, m, grid, scan, guess)
        for fa, fb in zip(base, r):
            np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_pyramid_cache_revision_keying():
    """dirty region (new revision) -> rebuilt; clean region (same
    revision) -> reused; None revision -> never cached."""
    cache = PYR.PyramidCache(max_entries=2)
    builds = []

    def build(tag):
        def f():
            builds.append(tag)
            return (jnp.zeros((4, 4)),)
        return f

    k = ("fine", 0, 0)
    cache.get(k, 1, build("a"))
    cache.get(k, 1, build("b"))          # clean: reused
    assert builds == ["a"]
    cache.get(k, 2, build("c"))          # dirty tile: re-pooled
    assert builds == ["a", "c"]
    cache.get(k, 2, build("d"))
    assert builds == ["a", "c"]
    snap = cache.snapshot()
    assert snap["n_hits"] == 2 and snap["n_misses"] == 2
    assert snap["n_invalidations"] == 1
    assert snap["hit_rate"] == pytest.approx(0.5)
    # No revision source: always rebuilt, never stored.
    cache.get(("x",), None, build("e"))
    cache.get(("x",), None, build("f"))
    assert builds == ["a", "c", "e", "f"]
    # LRU bound holds.
    cache.get(("k2",), 1, build("g"))
    cache.get(("k3",), 1, build("h"))
    assert cache.snapshot()["n_entries"] == 2


def test_slam_diag_carries_match_accounting(tiny_cfg):
    """Key steps surface the matcher's candidate count and prune ratio
    through SlamDiag (the /metrics gauges' source)."""
    from jax_mapping.models import slam as S
    st = S.init_state(tiny_cfg)
    scan = room_scan(tiny_cfg.scan, np.zeros(3, np.float32))
    _st2, diag = S.slam_step(tiny_cfg, st, jnp.asarray(scan),
                             jnp.float32(0), jnp.float32(0),
                             jnp.float32(0.1))
    assert bool(diag.key_added)
    assert int(diag.match_candidates) > 0
    assert 0.0 < float(diag.match_prune_ratio) < 1.0


@pytest.mark.slow
def test_pruned_match_5x_faster_on_bench_world():
    """CPU regression gate (satellite): on the bench world at the
    production config, the pruned matcher must be >= 5x faster than the
    exhaustive sweep under the BENCH methodology — a data-dependent
    `fori_loop` chain of matches, per-iteration time from the marginal
    t(3) - t(1) (bench.py's `match_p50_ms`). The chain is the sustained
    regime the acceptance gate (BENCH_MATCH_r01) records; one-shot
    dispatch timings hide the exhaustive conv's in-loop cost and would
    let a regression through at the wrong magnitude."""
    from jax_mapping.config import SlamConfig
    cfg = SlamConfig()
    g, s = cfg.grid, cfg.scan
    rng = np.random.default_rng(0)
    B = 64
    t = np.linspace(0, 2 * math.pi, B, endpoint=False)
    poses = np.stack([0.4 * np.cos(t), 0.4 * np.sin(t),
                      t + math.pi / 2], axis=1).astype(np.float32)
    ranges = rng.uniform(1.0, 10.0, (B, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    grid = G.fuse_scans_window(g, s, G.empty_grid(g), jnp.asarray(ranges),
                               jnp.asarray(poses))
    jax.block_until_ready(grid)
    scan = jnp.asarray(ranges[0])

    def chain_ms(m):
        def run_g(gr0, k):
            def body(_, p):
                return M.match(g, s, m, gr0, scan, p).pose
            p = jax.lax.fori_loop(0, k, body,
                                  jnp.zeros(3, jnp.float32) + 0.01)
            return p.sum()
        jitted = jax.jit(run_g)

        def f(k):
            return float(jitted(grid, jnp.int32(k)))
        f(1)                                   # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            f(1)
            t1 = time.perf_counter() - t0
            t0 = time.perf_counter()
            f(3)
            t3 = time.perf_counter() - t0
            best = min(best, max(t3 - t1, 1e-9) / 2)
        return best * 1e3

    t_ex = chain_ms(dataclasses.replace(cfg.matcher, pruned=False))
    t_pr = chain_ms(dataclasses.replace(cfg.matcher, pruned=True))
    assert t_pr * 5.0 <= t_ex, (
        f"pruned match {t_pr:.0f} ms not >= 5x faster than "
        f"exhaustive {t_ex:.0f} ms")


def test_relocalizer_reuses_pyramids_across_attempts(tiny_cfg):
    """Steady-state relocalization (the quarantined-robot tick loop):
    the second attempt against an unchanged map region must HIT the
    pyramid cache for both stages; a region revision bump must rebuild
    (dirty tile -> re-pooled, clean tile -> reused)."""
    from jax_mapping.recovery.relocalize import Relocalizer

    reloc = Relocalizer(tiny_cfg.recovery, n_robots=1)
    grid = build_room_map(tiny_cfg)
    true_pose = np.array([0.1, -0.05, 0.15], np.float32)
    ranges = room_scan(tiny_cfg.scan, true_pose)
    guess = true_pose + np.array([0.05, 0.03, 0.05], np.float32)
    rev = {"v": 3}

    def region_rev_fn(_row0, _col0, _span):
        return rev["v"]

    reloc.attempt_for(0, tiny_cfg, grid, ranges, guess,
                      region_rev_fn=region_rev_fn)
    s1 = reloc.pyramid_cache.snapshot()
    assert s1["n_misses"] == 2 and s1["n_hits"] == 0   # wide + fine built
    reloc.attempt_for(0, tiny_cfg, grid, ranges, guess,
                      region_rev_fn=region_rev_fn)
    s2 = reloc.pyramid_cache.snapshot()
    assert s2["n_misses"] == 2 and s2["n_hits"] == 2   # clean: reused
    rev["v"] = 4                                       # region went dirty
    reloc.attempt_for(0, tiny_cfg, grid, ranges, guess,
                      region_rev_fn=region_rev_fn)
    s3 = reloc.pyramid_cache.snapshot()
    assert s3["n_misses"] == 4                         # re-pooled
    assert s3["n_invalidations"] == 2
    assert reloc.snapshot()["pyramid_cache"]["hit_rate"] == \
        pytest.approx(2 / 6)
    # Race guard: a region revision NEWER than the caller's grid
    # snapshot means a mutation landed between snapshot and probe — the
    # snapshot-built pyramid must NOT be cached at that revision (it
    # would serve stale data as current), and must not hit either.
    rev["v"] = 9
    for _ in range(2):
        reloc.attempt_for(0, tiny_cfg, grid, ranges, guess,
                          region_rev_fn=region_rev_fn, grid_revision=8)
    s4 = reloc.pyramid_cache.snapshot()
    assert s4["n_hits"] == s3["n_hits"]                # never served
    assert s4["n_misses"] == s3["n_misses"] + 4        # rebuilt each time
