"""Integration tests for the full node-graph stack.

The coverage the reference never had (SURVEY.md §4): driver failure paths,
brain reconnect semantics, the HTTP management plane, and the whole
sim → brain → mapper → map/frontiers loop running deterministically.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from jax_mapping.bridge import png as png_codec
from jax_mapping.bridge.driver import (
    DriverError, MOTOR_LEFT_SPEED, MOTOR_LEFT_TARGET, PROX_HORIZONTAL,
    SimulatedThymioDriver, connect_with_retries,
)
from jax_mapping.bridge.launch import launch_sim_stack
from jax_mapping.sim import world as W


# ---------------------------------------------------------------- driver

def test_driver_connect_retry_then_success():
    d = SimulatedThymioDriver(fail_connect_times=2)
    assert connect_with_retries(d, max_retries=3, timeout_s=1.0)
    assert d.connected and d.n_connect_calls == 3


def test_driver_connect_exhausts_retries():
    d = SimulatedThymioDriver(fail_connect_times=10)
    assert not connect_with_retries(d, max_retries=3, timeout_s=1.0)
    assert not d.connected


def test_driver_connect_timeout_on_hang():
    """The pi variant's thread+join timeout (`pi/src/.../main.py:111-148`):
    a hanging connect must be abandoned, then the next attempt succeeds."""
    d = SimulatedThymioDriver(hang_connect_times=1)
    t0 = time.monotonic()
    assert connect_with_retries(d, max_retries=2, timeout_s=0.3)
    assert time.monotonic() - t0 < 2.0


def test_driver_wire_encoding_roundtrip():
    """Negative wheel speeds wrap to unsigned 16-bit on the wire; the brain
    undoes it with sign_extend_16bit (`server/.../main.py:101-102`)."""
    from jax_mapping.config import sign_extend_16bit
    d = SimulatedThymioDriver()
    d.connect()
    d.ingest_state(np.array([[-50.0, 120.0]]), np.zeros((1, 7)))
    raw = d[0][MOTOR_LEFT_SPEED]
    assert raw == 65486                      # wrapped
    assert sign_extend_16bit(raw) == -50


def test_driver_io_error_after_failure_injection():
    d = SimulatedThymioDriver(fail_reads_after=2)
    d.connect()
    d[0][MOTOR_LEFT_SPEED]
    d[0][MOTOR_LEFT_SPEED]
    with pytest.raises(DriverError):
        d[0][PROX_HORIZONTAL]
    assert not d.connected


# ---------------------------------------------------------------- stack

@pytest.fixture(scope="module")
def stack(tiny_cfg):
    world = W.plank_course(96, tiny_cfg.grid.resolution_m, n_planks=4, seed=3)
    st = launch_sim_stack(tiny_cfg, world, n_robots=2, http_port=0,
                          realtime=False)
    st.brain.start_exploring()
    yield st
    st.shutdown()


def test_stack_end_to_end_mapping(stack):
    stack.run_steps(30)
    assert stack.brain.n_ticks >= 29
    assert stack.mapper.n_scans_fused > 0
    # The merged grid saw both walls and free space.
    lo = np.asarray(stack.mapper.merged_grid())
    g = stack.cfg.grid
    assert (lo >= g.occ_threshold).sum() > 20
    assert (lo <= g.free_threshold).sum() > 200


def test_stack_robots_actually_move(stack):
    p0 = stack.sim.truth_poses().copy()
    stack.run_steps(20)
    p1 = stack.sim.truth_poses()
    assert np.linalg.norm(p1[:, :2] - p0[:, :2], axis=1).max() > 0.02


def test_stack_odometry_tracks_truth(stack):
    truth = stack.sim.truth_poses()
    est = stack.brain.poses
    # Dead-reckoning with 5% wheel noise over a few seconds: loose bound.
    assert np.linalg.norm(est[:, :2] - truth[:, :2], axis=1).max() < 0.5


def test_stack_tf_chain_complete(stack):
    """map->odom->base_link->base_laser resolvable for every robot
    (the chain slam_toolbox needs, SURVEY.md §3.3)."""
    for i in range(2):
        tfm = stack.tf.lookup("map", f"robot{i}/base_laser")
        assert abs(tfm.z - 0.12) < 1e-9


def test_stack_http_endpoints(stack):
    stack.mapper.publish_map()
    base = f"http://127.0.0.1:{stack.api.port}"

    with urllib.request.urlopen(f"{base}/status", timeout=5) as r:
        st = json.loads(r.read())
    assert st["connected"] and st["n_robots"] == 2

    with urllib.request.urlopen(f"{base}/map-image", timeout=5) as r:
        body = r.read()
        assert r.headers["Content-Type"] == "image/png"
    img = png_codec.decode_gray(body)
    assert img.shape == (stack.cfg.grid.size_cells,) * 2
    assert set(np.unique(img)) <= {0, 127, 255}

    # PNG cache: second hit within 1 s returns the cached bytes.
    hits0 = stack.api.n_png_cache_hits
    with urllib.request.urlopen(f"{base}/map-image", timeout=5) as r:
        assert r.read() == body
    assert stack.api.n_png_cache_hits == hits0 + 1

    with urllib.request.urlopen(f"{base}/frontiers", timeout=5) as r:
        fr = json.loads(r.read())
    assert len(fr["assignment"]) == 2

    with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
        text = r.read().decode()
    assert "jax_mapping_brain_ticks_total" in text

    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(f"{base}/nope", timeout=5)


def test_stack_start_stop_contract(stack):
    """`/start` `/stop` flip is_exploring (`server/.../main.py:227-239`);
    stop forces motors off (pi variant)."""
    base = f"http://127.0.0.1:{stack.api.port}"
    with urllib.request.urlopen(f"{base}/stop", timeout=5) as r:
        assert json.loads(r.read())["status"] == "exploration stopped"
    assert not stack.brain.is_exploring
    assert np.all(stack.driver.targets() == 0)
    stack.run_steps(3)
    assert np.all(stack.driver.targets() == 0)   # stays stopped
    with urllib.request.urlopen(f"{base}/start", timeout=5) as r:
        assert json.loads(r.read())["status"] == "exploration started"
    assert stack.brain.is_exploring


def test_brain_reconnect_after_io_failure(tiny_cfg):
    """Runtime I/O error ⇒ drop link ⇒ throttled re-probe recovers
    (`server/.../main.py:84-88,198-200`)."""
    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, realtime=False)
    try:
        st.brain.start_exploring()
        st.run_steps(3)
        assert st.brain.link_up
        # Kill the link mid-flight.
        st.driver.fail_reads_after = st.driver._n_reads
        st.brain.reconnect_period_s = 0.0            # probe immediately
        st.run_steps(1)
        assert not st.brain.link_up
        assert st.brain.n_io_errors == 1
        st.driver.fail_reads_after = None
        st.run_steps(2)
        assert st.brain.link_up                      # recovered
    finally:
        st.shutdown()


def test_brain_driver_flapping_safe_stop_and_health_ladder(tiny_cfg):
    """Driver FLAPPING — offline ⇒ reconnect ⇒ offline again within one
    mission (the reconnect probe's multi-transition case the single-
    transition test above can't see). Each reconnect must run exactly
    one safe-stop tick (motors zeroed, LED red) BEFORE any policy
    output reaches the wheels — stale pre-fault targets never replay —
    and the shared health registry must walk the full driver ladder
    twice: ok → offline → recovering → ok, both times."""
    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, realtime=False)
    try:
        st.brain.start_exploring()
        st.brain.reconnect_period_s = 0.0
        st.run_steps(8)
        assert np.any(st.driver.targets() != 0)      # policy is driving

        def flap():
            st.driver.fail_reads_after = st.driver._n_reads
            st.run_steps(1)                          # I/O error: offline
            assert not st.brain.link_up
            pre_fault = st.driver.targets().copy()
            st.driver.fail_reads_after = None
            st.run_steps(1)          # probe reconnects + safe-stop tick
            assert st.brain.link_up
            # No duplicate motor commands: the reconnect tick's only
            # writes are the zeroing ones — pre-fault targets (still
            # nonzero in the driver registers) never replay — and the
            # LED shows the red degraded posture.
            assert np.any(pre_fault != 0)
            assert np.all(st.driver.targets() == 0)
            assert st.driver.leds()[0].tolist() == [32, 0, 0]
            return pre_fault

        flap()
        st.run_steps(4)                              # policy resumes
        assert np.any(st.driver.targets() != 0)
        flap()                                       # ...and flaps AGAIN
        st.run_steps(1)                              # recovering -> ok

        ladder = [(a, b) for _, a, b in
                  st.health.transitions_for("driver")]
        assert ladder == [("ok", "offline"), ("offline", "recovering"),
                          ("recovering", "ok"),
                          ("ok", "offline"), ("offline", "recovering"),
                          ("recovering", "ok")]
        # Each outage counted exactly one I/O error: the probe path
        # reconnected without spurious extra drops.
        assert st.brain.n_io_errors == 2
    finally:
        st.shutdown()


def test_stack_survives_scan_loss(tiny_cfg):
    """Best-Effort drops must not wedge the mapper (report.pdf §V.A)."""
    world = W.empty_arena(96, tiny_cfg.grid.resolution_m)
    st = launch_sim_stack(tiny_cfg, world, n_robots=1, realtime=False,
                          drop_prob=0.5, seed=11)
    try:
        st.brain.start_exploring()
        st.run_steps(30)
        assert 0 < st.mapper.n_scans_fused < 30 * 1.01
        lo = np.asarray(st.mapper.merged_grid())
        assert (np.abs(lo) > 0.3).sum() > 100        # still mapped
    finally:
        st.shutdown()


@pytest.mark.slow
def test_bridge_stack_at_baseline_64_robots(tiny_cfg):
    """BASELINE configs-4's robot count through the ACTUAL node graph —
    bus fan-in, brain batch, shared-grid mapper, planner — not just the
    fleet model: 64 robots boot, every robot's scans fuse, no node
    errors. (The model-level 64-robot tick is bench.py's job; this pins
    that the BRIDGE composes at that scale.)"""
    import dataclasses as _dc

    cfg = _dc.replace(tiny_cfg,
                      fleet=_dc.replace(tiny_cfg.fleet, n_robots=64))
    world = W.rooms_world(128, cfg.grid.resolution_m, seed=6)
    st = launch_sim_stack(cfg, world, n_robots=64, http_port=None,
                          seed=28)
    try:
        st.brain.start_exploring()
        st.run_steps(4)
        s = st.brain.status()
        assert s["n_robots"] == 64
        assert st.mapper.n_scans_fused == 64 * 4, \
            "some robot's scans never fused"
        assert st.brain.n_errors == 0 and st.mapper.n_errors == 0
        assert st.planner.n_errors == 0
        lo = np.asarray(st.mapper.merged_grid())
        assert int((np.abs(lo) > 0.3).sum()) > 1000
    finally:
        st.shutdown()
