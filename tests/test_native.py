"""Native LD06 ingest tests: wire round trip, resync, CRC rejection,
filtering — the C++ pipeline the reference vendored pre-built
(SURVEY.md §2.3), here exercised byte-for-byte."""

import numpy as np
import pytest

from jax_mapping.native import Ld06Parser, encode_packets, native_available
from jax_mapping.native.ld06 import PACKET_BYTES, crc8

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++/libld06 unavailable")


def _rotation(n=360, base=2.0):
    """A plausible rotation: smooth wall at ~2 m with a bump."""
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    return (base + 0.5 * np.cos(ang)).astype(np.float64)


def test_wire_roundtrip_full_rotation():
    ranges = _rotation()
    # Two rotations: the assembler needs to cross 360 deg to emit the first.
    data = encode_packets(ranges) + encode_packets(ranges)
    p = Ld06Parser(n_beams=360)
    n_pts = p.feed(data)
    assert n_pts == 720
    out = p.take_scan()
    assert out is not None
    got, intens = out
    valid = got > 0
    assert valid.sum() > 340
    np.testing.assert_allclose(got[valid], ranges[valid], atol=0.01)
    assert intens[valid].min() > 0
    assert p.take_scan() is None            # only one complete rotation
    assert p.stats()["scans"] == 1
    assert abs(p.speed_deg_s - 3600) < 1e-6


def test_parser_resyncs_over_garbage():
    ranges = _rotation()
    clean = encode_packets(ranges)
    noise = bytes(range(1, 200))            # no 0x54,0x2C pairs that pass CRC
    p = Ld06Parser()
    p.feed(noise + clean + noise + clean)
    assert p.stats()["packets"] == 60       # 2 rotations x 30 packets
    assert p.stats()["resyncs"] > 0
    assert p.take_scan() is not None


def test_crc_corruption_rejected():
    ranges = _rotation()
    data = bytearray(encode_packets(ranges))
    data[10] ^= 0xFF                        # corrupt first packet payload
    p = Ld06Parser()
    p.feed(bytes(data))
    st = p.stats()
    assert st["crc_errors"] >= 1
    assert st["packets"] == 29              # one packet lost


def test_chunked_feed_equals_bulk():
    """UART delivers arbitrary chunk sizes; framing must not care."""
    ranges = _rotation()
    data = encode_packets(ranges) + encode_packets(ranges)
    bulk = Ld06Parser()
    bulk.feed(data)
    chunked = Ld06Parser()
    for i in range(0, len(data), 13):       # awkward chunk size
        chunked.feed(data[i:i + 13])
    sb = bulk.take_scan()
    sc = chunked.take_scan()
    assert sb is not None and sc is not None
    np.testing.assert_array_equal(sb[0], sc[0])


def test_tof_filter_kills_low_confidence_and_spikes():
    ranges = _rotation()
    conf = np.full(360, 200)
    conf[50] = 3                            # below min_confidence
    spiked = ranges.copy()
    spiked[100] = 9.0                       # isolated spike between ~2 m walls
    data = encode_packets(spiked, conf) + encode_packets(spiked, conf)
    p = Ld06Parser(min_confidence=15, band_m=0.15)
    p.feed(data)
    got, _ = p.take_scan()
    assert got[50] == 0.0                   # confidence-rejected
    assert got[100] == 0.0                  # spike-rejected
    assert p.stats()["points_filtered"] >= 4
    # Neighbours survive.
    assert got[49] > 0 and got[51] > 0 and got[99] > 0


def test_crc8_self_consistency():
    pkt = bytes(range(PACKET_BYTES - 1))
    c = crc8(pkt)
    assert 0 <= c <= 255
    assert crc8(pkt) == c
    assert crc8(pkt + bytes([1])) != crc8(pkt + bytes([2]))


def test_beam_binning_partial_rotation_pending():
    """Half a rotation parsed -> no scan yet."""
    ranges = _rotation()
    data = encode_packets(ranges)
    p = Ld06Parser()
    p.feed(data[:len(data) // 2])
    assert p.take_scan() is None


def test_ingest_node_wire_path(tiny_cfg):
    """Full wire path: sim raycast -> LD06 byte encoding -> C++ parser ->
    LaserScan on the bus -> mapper-ready ranges."""
    import jax.numpy as jnp

    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.ld06_node import Ld06IngestNode
    from jax_mapping.bridge.qos import qos_sensor_data
    from jax_mapping.sim import lidar, world as W

    cfg = tiny_cfg
    res = cfg.grid.resolution_m
    world = W.empty_arena(96, res)
    n_samples = int(cfg.scan.range_max_m / (res * 0.5))
    scan = np.asarray(lidar.simulate_scans(
        cfg.scan, jnp.asarray(world), res, n_samples,
        jnp.zeros((1, 3))))[0, :cfg.scan.n_beams]

    chunks = [encode_packets(scan.astype(np.float64)) for _ in range(2)]
    pending = [b"".join(chunks)]

    def transport():
        data, pending[0] = pending[0], b""
        return data

    bus = Bus()
    sub = bus.subscribe("scan", qos_sensor_data)
    node = Ld06IngestNode(cfg.scan, bus, transport, realtime=False)
    node.poll()
    assert node.n_scans_published == 1
    msg = sub.take(timeout=0.5)
    assert msg is not None
    valid = msg.ranges > 0
    # Encoder quantizes to mm; raycast walls must survive the wire.
    np.testing.assert_allclose(msg.ranges[valid], scan[valid], atol=0.01)
    assert valid.sum() > 0.9 * (scan > 0).sum()
