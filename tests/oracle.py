"""Pure-NumPy reference implementations (oracles) for the JAX kernels.

Deliberately written in the naive per-ray / per-cell style so they are easy
to audit against the textbook inverse sensor model and the reference's
behavioral contracts, and slow enough that nobody mistakes them for the
product."""

import math

import numpy as np


def sanitize_ranges_np(scan_cfg, ranges):
    r = np.asarray(ranges, np.float64).copy()
    n = scan_cfg.padded_beams
    idx = np.arange(n)
    in_beam = idx < scan_cfg.n_beams
    is_zero = r <= 0.0
    r[is_zero] = scan_cfg.invalid_range_m
    hit = (~is_zero) & (r >= scan_cfg.range_min_m) & \
        (r <= scan_cfg.range_max_m) & in_beam
    r[~in_beam] = 0.0
    return r, hit


def classify_patch_np(grid, scan_cfg, ranges, pose, origin_rc):
    """Cell-by-cell inverse sensor model, mirroring ops.grid.classify_patch."""
    P = grid.patch_cells
    res = grid.resolution_m
    r_m, hit = sanitize_ranges_np(scan_cfg, ranges)
    ox, oy = grid.origin_m
    out = np.zeros((P, P), np.float32)
    tol = grid.hit_tolerance_cells * res
    for i in range(P):
        for j in range(P):
            y = (origin_rc[0] + i + 0.5) * res + oy
            x = (origin_rc[1] + j + 0.5) * res + ox
            dx, dy = x - pose[0], y - pose[1]
            r_cell = math.hypot(dx, dy)
            theta = math.atan2(dy, dx) - pose[2]
            if not scan_cfg.counterclockwise:
                theta = -theta
            theta = (theta - scan_cfg.angle_min_rad) % (2 * math.pi)
            beam = int(round(theta / scan_cfg.angle_increment_rad)) % scan_cfg.n_beams
            z = r_m[beam]
            carve = min(z if z > 0 else 0.0, grid.max_range_m)
            if hit[beam] and abs(r_cell - z) <= tol and r_cell <= grid.max_range_m:
                out[i, j] = grid.logodds_occ
            elif scan_cfg.range_min_m < r_cell < carve - tol:
                out[i, j] = grid.logodds_free
    return out


def raycast_scan_np(world_occ, pose, n_beams, angle_increment, max_range, res):
    """Ground-truth LiDAR: march each beam through a boolean occupancy image
    (row-major, row=y/res, col=x/res, origin centred) until it hits."""
    H, W = world_occ.shape
    out = np.zeros(n_beams, np.float64)
    step = res * 0.25
    for b in range(n_beams):
        a = pose[2] + b * angle_increment
        ca, sa = math.cos(a), math.sin(a)
        r = 0.0
        hit = 0.0
        while r < max_range:
            x = pose[0] + r * ca
            y = pose[1] + r * sa
            col = int(x / res + W / 2)
            row = int(y / res + H / 2)
            if not (0 <= row < H and 0 <= col < W):
                break
            if world_occ[row, col]:
                hit = r
                break
            r += step
        out[b] = hit
    return out


def rk2_odometry_np(robot_cfg, x, y, yaw, left_units, right_units, dt):
    """Reference odometry math (`server/.../main.py:104-115`): differential
    drive with 2nd-order Runge-Kutta midpoint integration."""
    vl = left_units * robot_cfg.speed_coeff_m_per_unit_s
    vr = right_units * robot_cfg.speed_coeff_m_per_unit_s
    v_lin = (vr + vl) / 2.0
    v_ang = (vr - vl) / robot_cfg.wheel_base_m
    delta_th = v_ang * dt
    mid = yaw + delta_th / 2.0
    return (x + v_lin * math.cos(mid) * dt,
            y + v_lin * math.sin(mid) * dt,
            yaw + delta_th)
