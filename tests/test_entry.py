"""Driver-entry contract tests: the two artifacts the round is judged on.

Round 1's MULTICHIP artifact timed out (VERDICT.md: rc 124, >420 s on tiny
shapes) because the ambient axon TPU plugin stalls backend init even under
JAX_PLATFORMS=cpu. These tests pin the fix: the dry run must complete well
inside the driver budget, from BOTH a clean in-process CPU mesh (the happy
path) and a poisoned-looking environment (the subprocess hop).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as E  # noqa: E402


def test_entry_compiles_and_runs():
    import jax
    fn, args = E.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    state, pose_err = out
    assert pose_err.shape[0] == 4


def test_dryrun_multichip_under_budget(monkeypatch):
    """The 8-device dry run (compile + short trajectory + voxel fusion)
    in <= 180 s CPU, exercising the IN-PROCESS branch (conftest pins cpu
    + 8 host devices and scrubs the axon env, so _cpu_env_ready must hold
    here). These plumbing tests run the SHORT trajectory
    (JAX_MAPPING_DRYRUN_STEPS) — the full 16-step gate-crossing run is
    the driver artifact's job at ~12 s/step on a 1-core virtual mesh."""
    monkeypatch.setenv("JAX_MAPPING_DRYRUN_STEPS", "4")
    assert E._cpu_env_ready(8), "conftest env contract changed"
    t0 = time.monotonic()
    E.dryrun_multichip(8)
    elapsed = time.monotonic() - t0
    assert elapsed < 180.0, f"dryrun_multichip(8) took {elapsed:.0f}s"


def test_dryrun_subprocess_hop_from_poisoned_env(monkeypatch):
    """With the axon marker set, the dry run must detect the poisoned
    process and still succeed via the scrubbed subprocess."""
    monkeypatch.setenv("JAX_MAPPING_DRYRUN_STEPS", "4")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    assert not E._cpu_env_ready(8)
    t0 = time.monotonic()
    E.dryrun_multichip(8)
    assert time.monotonic() - t0 < 240.0


def test_scrubbed_env_contents():
    env = E._scrubbed_cpu_env(8)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert not any(k.startswith(("AXON", "PALLAS_AXON")) for k in env)
    assert ".axon_site" not in env.get("PYTHONPATH", "")
    repo = os.path.dirname(os.path.abspath(E.__file__))
    assert env["PYTHONPATH"].split(os.pathsep)[0] == repo
