"""Test harness: force an 8-device virtual CPU mesh BEFORE jax imports.

The reference has no automated tests beyond lint scaffolding (SURVEY.md §4);
this suite is the created test strategy: NumPy-oracle golden tests for the
kernels, property tests for raycast/matcher, and multi-"chip" distributed
tests on virtual CPU devices so they run anywhere.
"""

import os
import sys

# Tests are CPU-only, but the axon TPU plugin (registered at interpreter
# startup via sitecustomize when PALLAS_AXON_POOL_IPS is set) can hang every
# jax backend init when its tunnel is wedged — even under JAX_PLATFORMS=cpu.
# Registration already happened by the time conftest runs, so re-exec the
# whole pytest process once with the axon env removed.
if os.environ.get("PALLAS_AXON_POOL_IPS") and \
        not os.environ.get("_JAX_MAPPING_REEXEC") and \
        not os.environ.get("JAX_MAPPING_TPU_TESTS") and \
        "pytest" in (sys.argv[0] or ""):
    # Only when launched as a pytest CLI (python -m pytest / pytest binary);
    # programmatic pytest.main() callers have a foreign sys.argv we must not
    # replay. They get the env cleanup below instead.
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["_JAX_MAPPING_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"]
               + sys.argv[1:], env)

# Force CPU: the ambient environment may pin JAX_PLATFORMS=axon (TPU).
# JAX_MAPPING_TPU_TESTS=1 opts out so the @skipif(tpu) lowering tests can
# meet the real chip: `JAX_MAPPING_TPU_TESTS=1 pytest tests/ -k tpu`.
if not os.environ.get("JAX_MAPPING_TPU_TESTS"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


# -- tier-1 failure-set guard (ISSUE 7) --------------------------------------
#
# `tests/known_failures.json` pins the PRE-EXISTING tier-1 failure set
# (jax.shard_map AttributeError on this jax version + flaky/threshold —
# verified identical since seed). Every run compares its failures
# against the pin and prints an explicit diff section, so the set
# cannot grow *silently*: a new failure is named as NEW (not lost in
# the expected red count), and a pinned failure that now passes is
# named as ratchetable. Subset runs only compare among tests that
# actually ran.

_KNOWN_FAILURES_PATH = os.path.join(os.path.dirname(__file__),
                                    "known_failures.json")
_guard_state = {"ran": set(), "failed": set()}


def _known_failures():
    import json
    try:
        with open(_KNOWN_FAILURES_PATH) as f:
            return set(json.load(f)["failures"])
    except (OSError, ValueError, KeyError):
        return None


def pytest_runtest_logreport(report):
    # "ran" = the test actually executed (call phase) or its setup
    # FAILED. Setup SKIPS are neither: counting them would report a
    # skipped pinned failure as FIXED and invite ratcheting out a
    # still-valid pin.
    if report.when == "call" or (report.when == "setup" and report.failed):
        _guard_state["ran"].add(report.nodeid)
    if report.failed:
        _guard_state["failed"].add(report.nodeid)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    known = _known_failures()
    if known is None:
        return
    ran, failed = _guard_state["ran"], _guard_state["failed"]
    new = sorted(failed - known)
    fixed = sorted((known & ran) - failed)
    tr = terminalreporter
    if new or fixed:
        tr.section("tier-1 failure-set guard (tests/known_failures.json)")
    if new:
        tr.write_line(f"{len(new)} NEW failure(s) beyond the pinned "
                      "pre-existing set — these are regressions, not "
                      "the known jax.shard_map/threshold set:")
        for nodeid in new:
            tr.write_line(f"  NEW  {nodeid}")
    if fixed:
        tr.write_line(f"{len(fixed)} pinned failure(s) now pass — "
                      "ratchet tests/known_failures.json down:")
        for nodeid in fixed:
            tr.write_line(f"  FIXED {nodeid}")
    if _budget_state["overrun"] is not None:
        elapsed, wall_s = _budget_state["overrun"]
        tr.section("tier-1 wall-clock budget (tests/tier1_budget.json)")
        tr.write_line(
            f"non-slow suite took {elapsed:.0f}s > committed budget "
            f"{wall_s:.0f}s — mark new soaks `slow` or piggyback on a "
            "shared module-scoped stack (see ISSUE 8 satellite); "
            "JAX_MAPPING_NO_TIME_BUDGET=1 to bypass locally")


@pytest.fixture(scope="session")
def tiny_cfg():
    from jax_mapping.config import tiny_config
    return tiny_config()


# -- tier-1 wall-clock budget guard (ISSUE 8) --------------------------------
#
# The tier-1 harness kills the suite at a hard timeout; a suite that
# creeps up to it dies as an opaque SIGKILL with no named culprit.
# `tests/tier1_budget.json` commits a wall-clock budget UNDER that
# timeout; a full non-slow run (>= min_tests executed — subset runs and
# `-m slow` runs never trip it) that exceeds the budget fails loudly at
# session end, naming the overrun while the logs still exist. New
# long-running tests must either fit the budget (piggyback on a shared
# module-scoped stack, the PR 7 pattern) or be marked `slow`.
# JAX_MAPPING_NO_TIME_BUDGET=1 is the local-dev escape hatch.

_BUDGET_PATH = os.path.join(os.path.dirname(__file__),
                            "tier1_budget.json")
_budget_state = {"t0": None, "overrun": None}


def _load_budget():
    import json
    try:
        with open(_BUDGET_PATH) as f:
            b = json.load(f)
        return float(b["wall_s"]), int(b["min_tests"])
    except (OSError, ValueError, KeyError):
        return None


def pytest_sessionstart(session):
    import time
    _budget_state["t0"] = time.monotonic()


def pytest_sessionfinish(session, exitstatus):
    import time
    if os.environ.get("JAX_MAPPING_NO_TIME_BUDGET") \
            or _budget_state["t0"] is None:
        return
    budget = _load_budget()
    if budget is None:
        return
    wall_s, min_tests = budget
    elapsed = time.monotonic() - _budget_state["t0"]
    if len(_guard_state["ran"]) >= min_tests and elapsed > wall_s:
        _budget_state["overrun"] = (elapsed, wall_s)
        session.exitstatus = 1
