"""Test harness: force an 8-device virtual CPU mesh BEFORE jax imports.

The reference has no automated tests beyond lint scaffolding (SURVEY.md §4);
this suite is the created test strategy: NumPy-oracle golden tests for the
kernels, property tests for raycast/matcher, and multi-"chip" distributed
tests on virtual CPU devices so they run anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def tiny_cfg():
    from jax_mapping.config import tiny_config
    return tiny_config()
