"""Test harness: force an 8-device virtual CPU mesh BEFORE jax imports.

The reference has no automated tests beyond lint scaffolding (SURVEY.md §4);
this suite is the created test strategy: NumPy-oracle golden tests for the
kernels, property tests for raycast/matcher, and multi-"chip" distributed
tests on virtual CPU devices so they run anywhere.
"""

import os
import sys

# Tests are CPU-only, but the axon TPU plugin (registered at interpreter
# startup via sitecustomize when PALLAS_AXON_POOL_IPS is set) can hang every
# jax backend init when its tunnel is wedged — even under JAX_PLATFORMS=cpu.
# Registration already happened by the time conftest runs, so re-exec the
# whole pytest process once with the axon env removed.
if os.environ.get("PALLAS_AXON_POOL_IPS") and \
        not os.environ.get("_JAX_MAPPING_REEXEC") and \
        not os.environ.get("JAX_MAPPING_TPU_TESTS") and \
        "pytest" in (sys.argv[0] or ""):
    # Only when launched as a pytest CLI (python -m pytest / pytest binary);
    # programmatic pytest.main() callers have a foreign sys.argv we must not
    # replay. They get the env cleanup below instead.
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["_JAX_MAPPING_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"]
               + sys.argv[1:], env)

# Force CPU: the ambient environment may pin JAX_PLATFORMS=axon (TPU).
# JAX_MAPPING_TPU_TESTS=1 opts out so the @skipif(tpu) lowering tests can
# meet the real chip: `JAX_MAPPING_TPU_TESTS=1 pytest tests/ -k tpu`.
if not os.environ.get("JAX_MAPPING_TPU_TESTS"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def tiny_cfg():
    from jax_mapping.config import tiny_config
    return tiny_config()
