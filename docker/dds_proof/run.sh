#!/usr/bin/env bash
# One-command real-DDS proof (operator machine with Docker + network):
# boots the stack + probe containers, captures the transcript, exits
# with the probe's status.
set -euo pipefail
cd "$(dirname "$0")"
docker compose up --abort-on-container-exit --exit-code-from probe \
    2>&1 | tee transcript.txt
