#!/usr/bin/env bash
# Assert the jax-mapping ROS 2 bridge's contract surfaces over REAL DDS.
# Runs inside a ros:jazzy container next to the stack container
# (docker-compose.yml); exits non-zero on any missing surface.
set -u
. /opt/ros/jazzy/setup.sh

fail() { echo "DDS-PROOF-FAIL: $*" >&2; exit 1; }

echo "== waiting for /map to be advertised (the stack installs jax on"
echo "   first boot; allow a few minutes) =="
deadline=$((SECONDS + 240))
until ros2 topic list 2>/dev/null | grep -qx /map; do
  [ $SECONDS -ge $deadline ] && fail "/map never advertised"
  sleep 3
done

echo "== topic list =="
ros2 topic list

for t in /map /map_updates /scan /odom /pose /tf /frontiers_markers \
         /voxel_points /plan /graph; do
  ros2 topic list | grep -qx "$t" || fail "topic $t not advertised"
done

echo "== /map arrives (latched: transient-local reliable) =="
timeout 60 ros2 topic echo --once \
    --qos-durability transient_local --qos-reliability reliable \
    /map > /tmp/map.msg || fail "/map message never arrived"
grep -q "resolution: 0.05" /tmp/map.msg || fail "/map resolution wrong"

echo "== /scan flows (Best-Effort sensor QoS) =="
timeout 30 ros2 topic echo --once --qos-reliability best_effort \
    /scan > /tmp/scan.msg || fail "/scan message never arrived"
grep -q "frame_id: base_laser" /tmp/scan.msg || fail "/scan frame wrong"

echo "== /scan rate =="
timeout 15 ros2 topic hz /scan --window 20 2>&1 | tail -2 || true

echo "== TF chain map -> base_link resolves =="
timeout 30 ros2 run tf2_ros tf2_echo map base_link 2>&1 | head -6 \
    > /tmp/tf.txt
grep -q "Translation" /tmp/tf.txt || fail "tf map->base_link unresolved"
cat /tmp/tf.txt

echo "== inbound /cmd_vel is subscribed by the stack =="
info=$(ros2 topic info /cmd_vel 2>/dev/null)
echo "$info"
echo "$info" | grep -q "Subscription count: [1-9]" \
    || fail "stack does not subscribe /cmd_vel"

echo "DDS-PROOF-OK"
