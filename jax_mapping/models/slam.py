"""Single-robot SLAM model: the full slam_toolbox capability as one jitted
step function.

Replaces the reference's external SLAM process (slam_toolbox online_async,
`/root/reference/server/thymio_project/launch/pc_server.launch.py:14-19`,
behavior fixed by `config/slam_config.yaml` — see SURVEY.md §3.4):

  gate (min travel 0.1 m / 0.1 rad) -> correlative scan match -> pose-graph
  insert -> loop-closure search/verify -> optimise -> occupancy update.

TPU-first: state is a pytree of fixed-shape device arrays (grid, pose ring,
scan ring, pose graph); every branch is a `lax.cond` with identical shapes;
loop-closure *map repair* is a full re-fusion of the stored scan ring from
the optimised trajectory (cheap on TPU, exact) instead of Karto's
incremental patching.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import SlamConfig, ensure_valid_mode
from jax_mapping.ops import grid as G
from jax_mapping.ops import posegraph as PG
from jax_mapping.ops import scan_match as M
from jax_mapping.ops.odometry import pose_between, rk2_step, wrap_angle

Array = jax.Array


class SlamState(NamedTuple):
    grid: Array          # (N, N) log-odds
    pose: Array          # (3,) current estimate (map frame)
    last_key_pose: Array  # (3,) pose at the last accepted key-scan
    graph: PG.PoseGraph
    scan_ring: Array     # (max_poses, padded_beams) key-scans
    n_loops: Array       # () int32 closed loops (telemetry)
    n_keyscans: Array    # () int32


class SlamDiag(NamedTuple):
    matched: Array       # () bool: scan-matcher accepted
    response: Array      # () float
    key_added: Array     # () bool
    loop_closed: Array   # () bool
    # Windowed path only (slam_step_window): mean map-agreement of the
    # W-1 leading scans that fuse WITHOUT match/acceptance telemetry — a
    # window of garbage scans must not be invisible in the diag. 1.0 for
    # the single-scan path (no leading scans to disagree).
    window_agreement: Array  # () float in [0, 1]
    # Correlative-match covariance diag (MatchResult.cov) from this
    # step's match; zeros when no match ran (non-key step). The bridge
    # publishes it with /pose (slam_toolbox's PoseWithCovariance).
    cov: Array           # (3,) [var_x m^2, var_y m^2, var_th rad^2]
    # Matcher work accounting (MatchResult.n_candidates/prune_ratio):
    # coarse-stage candidate evaluations this step's match scored and the
    # fraction the branch-and-bound stage pruned off the exhaustive
    # sweep; zeros on non-key steps. The mapper exports them as
    # jax_mapping_match_* gauges.
    match_candidates: Array   # () int32
    match_prune_ratio: Array  # () float32


def init_state(cfg: SlamConfig, pose0=None) -> SlamState:
    g = cfg.grid
    pose = jnp.zeros(3) if pose0 is None else jnp.asarray(pose0)
    return SlamState(
        grid=G.empty_grid(g),
        pose=pose.astype(jnp.float32),
        last_key_pose=jnp.full(3, 1e9, jnp.float32),   # force first key-scan
        graph=PG.empty_graph(cfg.loop),
        scan_ring=jnp.zeros((cfg.loop.max_poses, cfg.scan.padded_beams),
                            jnp.float32),
        n_loops=jnp.int32(0),
        n_keyscans=jnp.int32(0),
    )


def _loop_matcher_cfg(cfg: SlamConfig):
    """Fine-stage search window for loop verification: the regular online
    window widened to the patch margin, around the wide-stage estimate."""
    m = cfg.matcher
    half = min(cfg.loop.search_radius_m,
               (cfg.grid.patch_cells / 2 - cfg.grid.align_cols / 2)
               * cfg.grid.resolution_m - cfg.grid.max_range_m)
    half = max(half, m.search_half_extent_m)
    return dataclasses.replace(m, search_half_extent_m=half,
                               coarse_step_m=m.coarse_step_m * 2)


def _chain_grid(cfg: SlamConfig, graph: PG.PoseGraph, ring: Array,
                cand: Array, k: Array) -> Array:
    """Ghost-free loop-verification map: re-fuse the CANDIDATE's local
    chain of stored key-scans at their graph poses.

    Matching the current scan against the live map cannot verify a loop —
    the live map already contains the drift ghosts the loop exists to fix
    (report.pdf §V.B-C), so a ghost wall is a legitimate-looking basin.
    Karto instead matches against the candidate chain (slam_config.yaml:45
    `loop_match_minimum_chain_size`); the chain's poses are locally
    consistent, so the resulting relative pose is exactly the loop-edge
    measurement. Fixed chain length 2*min_chain_size+1 keeps shapes static.
    """
    CH = min(2 * cfg.loop.min_chain_size + 1, cfg.loop.max_poses)
    start = jnp.clip(cand - CH // 2, 0, cfg.loop.max_poses - CH)
    scans = jax.lax.dynamic_slice_in_dim(ring, start, CH, axis=0)
    poses = jax.lax.dynamic_slice_in_dim(graph.poses, start, CH, axis=0)
    valid = jax.lax.dynamic_slice_in_dim(graph.pose_valid, start, CH, axis=0)
    # The query's own recent tail must not leak into the verification map
    # (it would re-introduce the current drift frame).
    sl_idx = start + jnp.arange(CH)
    valid = valid & (sl_idx <= k - cfg.loop.min_chain_size)
    return G.fuse_scans_masked(cfg.grid, cfg.scan, G.empty_grid(cfg.grid),
                               scans, poses, valid)


def _verify_loop(cfg: SlamConfig, graph: PG.PoseGraph, ring: Array,
                 cand: Array, k: Array, ranges: Array, pose: Array):
    """Two-stage loop verification against the candidate chain's map.

    Stage 1 sweeps the full loop window (8 m, slam_config.yaml:56) on a
    coarse view; stage 2 refines at full resolution. Returns the fine
    MatchResult (gate on `.accepted` and `.response`).
    """
    grid_v = _chain_grid(cfg, graph, ring, cand, k)
    g_c, m_c = _loop_wide_cfgs(cfg)
    wide = M.match(g_c, cfg.scan, m_c,
                   G.downsample_max(grid_v, cfg.loop.coarse_downsample),
                   ranges, pose)
    seed = jnp.where(wide.accepted, wide.pose, pose)
    return M.match(cfg.grid, cfg.scan, _loop_matcher_cfg(cfg), grid_v,
                   ranges, seed)


def _loop_wide_cfgs(cfg: SlamConfig):
    """(coarse GridConfig, wide MatcherConfig) for the 8 m loop sweep.

    slam_toolbox searches loops in an 8 m window at 0.05 m
    (`slam_config.yaml:56-58`); a full-res correlative sweep that wide is
    pointless work, so stage one runs the SAME dense-conv matcher on a
    `loop.coarse_downsample`x coarser view of the grid, whose patch covers
    the whole window (grid.coarse_grid_config). Stage two refines on the
    full-res patch (`_loop_matcher_cfg`). The wide half-extent is the
    8 m window's half, clamped by the coarse patch's own margin.
    """
    g_c = G.coarse_grid_config(cfg.grid, cfg.loop.coarse_downsample)
    half = min(cfg.loop.loop_window_m / 2.0,
               (g_c.patch_cells / 2 - g_c.align_cols / 2)
               * g_c.resolution_m - g_c.max_range_m)
    half = max(half, g_c.resolution_m)
    m_c = dataclasses.replace(
        cfg.matcher,
        search_half_extent_m=half,
        coarse_step_m=g_c.resolution_m,       # one coarse cell per step
        min_response=cfg.loop.response_coarse,  # yaml:47 coarse gate
    )
    return g_c, m_c


@functools.partial(jax.jit, static_argnums=(0,))
def slam_step(cfg: SlamConfig, state: SlamState, ranges: Array,
              wheel_left: Array, wheel_right: Array,
              dt: Array) -> tuple[SlamState, SlamDiag]:
    """One control-period update: odometry, gated match+fuse, loop closure."""
    ensure_valid_mode(cfg)
    m = cfg.matcher
    pose_odo = rk2_step(cfg.robot, state.pose, wheel_left, wheel_right, dt)

    # Key-scan gate (slam_config.yaml:37-38).
    d = jnp.linalg.norm(pose_odo[:2] - state.last_key_pose[:2])
    dth = jnp.abs(wrap_angle(pose_odo[2] - state.last_key_pose[2]))
    is_key = (d > m.min_travel_m) | (dth > m.min_heading_rad)

    def key_branch(st: SlamState):
        # Bootstrap: with an empty map the matcher has nothing to align to;
        # response gating rejects and we fall back to odometry (reference
        # degraded-mode semantics, SURVEY.md §5 failure detection).
        res = M.match(cfg.grid, cfg.scan, m, st.grid, ranges, pose_odo)
        pose = jnp.where(res.accepted, res.pose, pose_odo)

        if cfg.mode == "localization":
            # slam_toolbox's other mode (slam_config.yaml:20 selects
            # mapping vs localization): track the pose against a FROZEN
            # map — no fusion, no graph growth, no loop closures. Pairs
            # with an imported map (mapper.seed_map_prior / --map-prior):
            # the robot localizes on the known environment without
            # redrawing it. Static config -> this branch is compiled
            # out entirely in mapping mode.
            st2 = st._replace(pose=pose, last_key_pose=pose)
            diag = SlamDiag(matched=res.accepted, response=res.response,
                            key_added=jnp.bool_(False),
                            loop_closed=jnp.bool_(False),
                            window_agreement=jnp.float32(1.0),
                            cov=res.cov,
                            match_candidates=res.n_candidates,
                            match_prune_ratio=res.prune_ratio)
            return st2, diag

        # Pre-fusion map agreement at the chosen pose — the same health
        # signal the window path computes for its leading scans, so the
        # mapper's do-no-harm floor (ResilienceConfig
        # .window_agreement_reject) covers the single-scan cadence too,
        # not just queued bursts. One (beams,)-point gather, free next
        # to the fusion below.
        agreement = _window_agreement(cfg, st.grid, ranges[None],
                                      pose[None])

        grid = G.fuse_scan(cfg.grid, cfg.scan, st.grid, ranges, pose)

        # Ring full? Halve keyframe density first (PG.thin_keyframes) so
        # the trajectory keeps extending and loop repair keeps working —
        # slam_toolbox's unbounded graph, fixed-shape style.
        graph0, ring0 = jax.lax.cond(
            st.graph.n_poses >= cfg.loop.max_poses,
            lambda a: PG.thin_keyframes(*a),
            lambda a: a, (st.graph, st.scan_ring))

        k = graph0.n_poses
        graph = PG.add_pose(graph0, pose)
        graph = jax.lax.cond(
            k > 0,
            lambda gr: PG.odometry_edge(gr, jnp.maximum(k - 1, 0), k),
            lambda gr: gr, graph)
        ring = ring0.at[jnp.minimum(k, cfg.loop.max_poses - 1)].set(ranges)

        # ---- loop closure ------------------------------------------------
        cand, found = PG.loop_candidate(cfg.loop, graph, k)

        def close_loop(args):
            graph, grid, ring = args
            # Two-stage verification (wide 8 m sweep -> fine) against the
            # CANDIDATE CHAIN's ghost-free map (_verify_loop). Recovers
            # drift far beyond the online matcher's reach (the report's
            # §V.B-C wall-ghosting case); acceptance on the fine response
            # gate (yaml:48).
            lres = _verify_loop(cfg, graph, ring, cand, k, ranges, pose)
            good = lres.accepted & (lres.response >= cfg.loop.response_fine)

            def apply(args):
                graph, grid, ring = args
                # Loop edge: candidate -> current, measured by the verified
                # match; strong information.
                rel = pose_between(graph.poses[cand], lres.pose)
                g2 = PG.add_edge(graph, cand, k, rel,
                                 jnp.array([200.0, 200.0, 400.0]))
                g2 = PG.optimize(cfg.loop, g2)
                # Map repair: re-fuse every key-scan from optimised poses,
                # MASKED on pose validity — unmasked, the ring's never-
                # written all-zero slots would each carve a phantom free
                # disc at the origin (a zero range means "outlier, carve
                # to 10 m", server/.../main.py:152) and erase real walls
                # there; measured: a 3-scan ring repaired unmasked lost
                # all 272 occupied cells of its wall.
                grid2 = G.fuse_scans_masked(
                    cfg.grid, cfg.scan, G.empty_grid(cfg.grid), ring,
                    g2.poses[:cfg.loop.max_poses],
                    g2.pose_valid[:cfg.loop.max_poses])
                return g2, grid2, jnp.bool_(True)

            return jax.lax.cond(good, apply,
                                lambda a: (a[0], a[1], jnp.bool_(False)),
                                (graph, grid, ring))

        graph, grid, closed = jax.lax.cond(
            found & (cfg.loop.enabled),
            close_loop,
            lambda a: (a[0], a[1], jnp.bool_(False)),
            (graph, grid, ring))

        # After optimisation the current pose may have moved.
        pose = jnp.where(closed, graph.poses[k], pose)

        st2 = SlamState(grid=grid, pose=pose, last_key_pose=pose,
                        graph=graph, scan_ring=ring,
                        n_loops=st.n_loops + closed.astype(jnp.int32),
                        n_keyscans=st.n_keyscans + 1)
        diag = SlamDiag(matched=res.accepted, response=res.response,
                        key_added=jnp.bool_(True), loop_closed=closed,
                        window_agreement=agreement, cov=res.cov,
                        match_candidates=res.n_candidates,
                        match_prune_ratio=res.prune_ratio)
        return st2, diag

    def skip_branch(st: SlamState):
        st2 = st._replace(pose=pose_odo)
        diag = SlamDiag(matched=jnp.bool_(False), response=jnp.float32(0),
                        key_added=jnp.bool_(False),
                        loop_closed=jnp.bool_(False),
                        window_agreement=jnp.float32(1.0),
                        cov=jnp.zeros(3, jnp.float32),
                        match_candidates=jnp.int32(0),
                        match_prune_ratio=jnp.float32(0.0))
        return st2, diag

    return jax.lax.cond(is_key, key_branch, skip_branch, state)


@functools.partial(jax.jit, static_argnums=(0,))
def slam_step_window(cfg: SlamConfig, state: SlamState, ranges_w: Array,
                     wheels_w: Array, dts_w: Array
                     ) -> tuple[SlamState, SlamDiag]:
    """Windowed update: a burst of W consecutive scans in one device step.

    The throughput path for scan rates far above the key-scan rate (the
    BASELINE 50k scans/sec regime): odometry integrates through the window
    with `lax.scan`, the leading W-1 scans fuse through the shared-patch
    Pallas window kernel (one read-modify-write of the grid — these scans
    add map evidence without pose-graph entries, like slam_toolbox's
    sub-gate scans except their information is kept rather than dropped),
    and the LAST scan runs the full `slam_step` pipeline (gate, match,
    pose graph, loop closure).

    The shared-patch contract is enforced on device: a window whose poses
    spread beyond the patch falls back to the exact per-scan fold
    (`grid.fuse_scans_window_checked`) instead of silently dropping map
    evidence.

    Args:
      ranges_w: (W, padded_beams); wheels_w: (W, 2) raw wheel speeds;
      dts_w: per-scan intervals — scalar or (W,) (irregular scan stamps
      under Best-Effort delivery are first-class). W >= 2 and static.
    """
    W = ranges_w.shape[0]
    if W < 2:
        raise ValueError(
            f"slam_step_window needs a window of >= 2 scans, got W={W}; "
            "feed single scans through slam_step")
    dts_w = jnp.broadcast_to(jnp.asarray(dts_w, jnp.float32), (W,))

    def integrate(p, wd):
        w, dt = wd
        p2 = rk2_step(cfg.robot, p, w[0], w[1], dt)
        return p2, p2

    # Scan i is taken at the pose AFTER integrating wheels_w[i] (slam_step's
    # convention): poses_w[i] = pose at scan i.
    _, poses_w = jax.lax.scan(integrate, state.pose,
                              (wheels_w, dts_w))   # (W, 3)

    agreement = _window_agreement(cfg, state.grid, ranges_w[:-1],
                                  poses_w[:-1])
    if cfg.mode == "localization":
        # Frozen map: the window's leading scans contribute telemetry
        # (agreement) but no evidence; only the last scan's match runs.
        grid = state.grid
    else:
        grid = G.fuse_scans_window_checked(cfg.grid, cfg.scan, state.grid,
                                           ranges_w[:-1], poses_w[:-1])
    # The last scan runs the full pipeline; starting it from the W-2th pose
    # makes its internal odometry land exactly on poses_w[-1].
    st = state._replace(grid=grid, pose=poses_w[-2])
    st2, diag = slam_step(cfg, st, ranges_w[-1],
                          wheels_w[-1, 0], wheels_w[-1, 1], dts_w[-1])
    return st2, diag._replace(window_agreement=agreement)


@functools.partial(jax.jit, static_argnums=(0,))
def scan_agreement(cfg: SlamConfig, grid: Array, ranges: Array,
                   pose: Array) -> Array:
    """Pre-fusion map agreement of ONE scan at `pose` — the per-scan
    estimator-health signal the recovery watchdog samples at full scan
    cadence (key steps get it from SlamDiag for free; sub-gate steps
    carry no diag agreement, and the watchdog must not go blind between
    key scans — a ghosting sensor fires every scan, not every 0.1 m of
    travel). One (beams,)-point gather."""
    return _window_agreement(cfg, grid, ranges[None], pose[None])


def _window_agreement(cfg: SlamConfig, grid: Array, ranges_w: Array,
                      poses_w: Array) -> Array:
    """Mean map-agreement of a window's leading scans, BEFORE they fuse.

    These scans add evidence with no match/acceptance telemetry
    (throughput path); this is their health signal: the fraction of hit
    endpoints landing on cells the map does NOT call confidently free.
    Misaligned scans put walls inside known-free space -> low agreement;
    hits in unknown territory are fine (that is what exploring looks
    like). A (W * beams)-point gather — microscopic next to the window
    fusion itself.
    """
    g, s = cfg.grid, cfg.scan
    pts, hit = jax.vmap(lambda r: M.scan_points(s, r))(ranges_w)
    cs = jnp.cos(poses_w[:, 2])[:, None]
    sn = jnp.sin(poses_w[:, 2])[:, None]
    x = poses_w[:, 0:1] + pts[:, :, 0] * cs - pts[:, :, 1] * sn
    y = poses_w[:, 1:2] + pts[:, :, 0] * sn + pts[:, :, 1] * cs
    cr = G.world_to_cell(g, jnp.stack([x, y], axis=-1))
    cols = jnp.floor(cr[..., 0]).astype(jnp.int32)
    rows = jnp.floor(cr[..., 1]).astype(jnp.int32)
    inb = ((rows >= 0) & (rows < g.size_cells)
           & (cols >= 0) & (cols < g.size_cells))
    vals = grid[jnp.clip(rows, 0, g.size_cells - 1),
                jnp.clip(cols, 0, g.size_cells - 1)]
    ok = hit & inb
    agree = (vals > g.free_threshold) & ok
    n_ok = ok.sum()
    # No valid in-bounds hits (open space beyond range_max, dropouts):
    # neutral 1.0, not maximum-alarm 0.0 — "no evidence" != "disagrees".
    return jnp.where(
        n_ok == 0, jnp.float32(1.0),
        agree.sum().astype(jnp.float32)
        / jnp.maximum(n_ok, 1).astype(jnp.float32))
