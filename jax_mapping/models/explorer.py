"""Exploration policies as pure, vmappable functions.

Two policies, matching the reference's present and future:

* `subsumption_policy` — the reference's 3-layer reactive navigator
  (`/root/reference/server/thymio_project/thymio_project/main.py:119-196`):
  (1) IR emergency pivot when any front prox > 1800, turn away from the
  heavier side (prox[0]*2+prox[1] vs prox[4]*2+prox[3]); (2) LiDAR
  anticipation over the two 30-beam front cones with the asymmetric swerve
  (inner wheel -10); (3) cruise. Zero-range outliers read as 10 m
  (main.py:152). LED state machine included (green idle / red IR / orange
  LiDAR warn / blue cruise — main.py:131,161,181,192).

* `frontier_policy` — map-based goal seeking toward an assigned frontier
  centroid (the report's §VI.2 future work): proportional heading control
  with the same reactive layers as a safety shield.

Both return integer wheel targets in Thymio units, batched over robots.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from jax_mapping.config import RobotConfig, ScanConfig
from jax_mapping.ops.odometry import wrap_angle

Array = jax.Array

# LED colors, reference state machine (main.py:69,131,161,181).
# numpy on purpose: module import may happen inside a jit trace (a lazy
# importer), and jnp.array here would bake leaked tracers into the module.
LED_IDLE = np.array([0, 32, 0])
LED_IR = np.array([32, 0, 0])
LED_WARN = np.array([32, 16, 0])
LED_CRUISE = np.array([0, 0, 32])


class PolicyOut(NamedTuple):
    targets: Array     # (R, 2) wheel targets [left, right], thymio units
    led: Array         # (R, 3) LED color (physical status display)
    state: Array       # (R,) int32: 0 idle, 1 cruise, 2 ir, 3 warn


def _front_cones(scan_cfg: ScanConfig, ranges: Array) -> tuple[Array, Array]:
    """Min range over the two front 30-beam cones.

    The reference indexes ranges[0:30] and ranges[-30:] and notes the
    left/right decision is "inverted because of the LIDAR angle convention"
    (main.py:154-177). Here the convention is explicit: beam 0 points along
    +x (robot forward), beams increase counterclockwise, so beams [0:30)
    sweep the robot's LEFT-front and the last 30 live beams sweep the
    RIGHT-front.
    """
    r = jnp.where(ranges <= 0.0, 10.0, ranges)        # outlier rule
    left = jnp.min(r[..., 0:30], axis=-1)
    n = scan_cfg.n_beams
    right = jnp.min(r[..., n - 30:n], axis=-1)
    return left, right


def subsumption_policy(robot: RobotConfig, scan_cfg: ScanConfig,
                       ranges: Array, prox: Array,
                       exploring: Array) -> PolicyOut:
    """Batched reactive navigator. ranges (R, B), prox (R, 5),
    exploring (R,) bool."""
    R = ranges.shape[0]
    cruise = jnp.float32(robot.cruise_speed_units)
    rot = jnp.float32(robot.rotation_speed_units)
    inner = jnp.float32(robot.swerve_inner_units)

    max_ir = jnp.max(prox[:, 0:5], axis=-1)
    ir_stop = max_ir > robot.ir_threshold
    weight_left = prox[:, 0] * 2 + prox[:, 1]
    weight_right = prox[:, 4] * 2 + prox[:, 3]
    # Obstacle on the left -> pivot right (left wheel fwd, right wheel back).
    pivot = jnp.where((weight_left > weight_right)[:, None],
                      jnp.stack([jnp.full(R, rot), jnp.full(R, -rot)], -1),
                      jnp.stack([jnp.full(R, -rot), jnp.full(R, rot)], -1))

    left_cone, right_cone = _front_cones(scan_cfg, ranges)
    min_dist = jnp.minimum(left_cone, right_cone)
    lidar_warn = min_dist < robot.lidar_warn_dist_m
    # Obstacle in the left cone -> swerve right, else swerve left.
    swerve = jnp.where((left_cone < right_cone)[:, None],
                       jnp.stack([jnp.full(R, cruise), jnp.full(R, inner)], -1),
                       jnp.stack([jnp.full(R, inner), jnp.full(R, cruise)], -1))

    go = jnp.stack([jnp.full(R, cruise), jnp.full(R, cruise)], -1)

    targets = jnp.where(ir_stop[:, None], pivot,
                        jnp.where(lidar_warn[:, None], swerve, go))
    targets = jnp.where(exploring[:, None], targets, 0.0)

    state = jnp.where(~exploring, 0,
                      jnp.where(ir_stop, 2, jnp.where(lidar_warn, 3, 1)))
    led = jnp.stack([LED_IDLE, LED_CRUISE, LED_IR, LED_WARN])[state]
    return PolicyOut(targets=targets.astype(jnp.int32), led=led,
                     state=state.astype(jnp.int32))


def frontier_policy(robot: RobotConfig, scan_cfg: ScanConfig,
                    poses: Array, goals_xy: Array, goal_valid: Array,
                    ranges: Array, prox: Array,
                    exploring: Array) -> PolicyOut:
    """Goal-seeking with the reactive shield.

    Steers toward the assigned frontier centroid; the subsumption layers
    override whenever IR/LiDAR demand it; robots without a valid goal cruise
    (the reference's LiDAR-less fallback, main.py:185-188).
    """
    reactive = subsumption_policy(robot, scan_cfg, ranges, prox, exploring)

    bearing = jnp.arctan2(goals_xy[:, 1] - poses[:, 1],
                          goals_xy[:, 0] - poses[:, 0])
    err = wrap_angle(bearing - poses[:, 2])                  # (R,)
    cruise = jnp.float32(robot.cruise_speed_units)
    # Proportional differential steer, saturating at a pivot.
    steer = jnp.clip(err * 2.0, -1.5, 1.5)
    base = cruise * jnp.clip(1.0 - jnp.abs(err) / jnp.pi * 1.5, 0.2, 1.0)
    left = base - steer * cruise * 0.5
    right = base + steer * cruise * 0.5
    seek = jnp.stack([left, right], axis=-1)

    use_seek = goal_valid & (reactive.state == 1)            # only in cruise
    targets = jnp.where(use_seek[:, None], seek, reactive.targets)
    targets = jnp.where(exploring[:, None], targets, 0.0)
    # Saturate to the Thymio motor command range BEFORE the int32 cast:
    # the seek branch's base ± steer*cruise*0.5 can exceed ±motor_limit
    # for large cruise speeds, and an un-clamped target would be clipped
    # by the firmware differently than the odometry model assumes.
    lim = jnp.float32(robot.motor_limit_units)
    targets = jnp.clip(targets, -lim, lim)
    return PolicyOut(targets=targets.astype(jnp.int32), led=reactive.led,
                     state=reactive.state)
