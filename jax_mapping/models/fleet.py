"""Fleet model: N simulated Thymios exploring and mapping one shared world —
the framework's flagship pipeline (BASELINE.json configs 4-5).

One jitted step closes the whole loop the reference spreads across two
machines and three processes (SURVEY.md §3.2-3.4):

  simulate LD06 scans (device raycast)           [was: LD06 driver on the Pi]
  -> odometry from measured wheel speeds         [was: ThymioBrain update_loop]
  -> batched correlative matching                [was: slam_toolbox matcher]
  -> batched log-odds fusion into a shared grid  [was: slam_toolbox rasterizer]
  -> frontier detect/cluster/assign              [was: future work, §VI.2]
  -> explorer policy -> wheel targets            [was: subsumption navigator]
  -> fleet kinematics step                       [was: physical robots]

Everything is (R, ...)-batched with vmap; `parallel.fleet_sharded` runs the
same step under shard_map over a ('fleet', 'space') mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import SlamConfig
from jax_mapping.models.explorer import PolicyOut, frontier_policy
from jax_mapping.ops import frontier as F
from jax_mapping.ops import grid as G
from jax_mapping.ops import scan_match as M
from jax_mapping.ops.odometry import rk2_step
from jax_mapping.sim import lidar, thymio

Array = jax.Array


class FleetState(NamedTuple):
    sim: thymio.FleetSimState   # ground truth
    est_poses: Array            # (R, 3) SLAM estimates
    grid: Array                 # (N, N) shared log-odds map
    exploring: Array            # (R,) bool (the /start /stop flag)
    t: Array                    # () int32 step counter


class FleetDiag(NamedTuple):
    policy: PolicyOut
    frontiers: F.FrontierResult
    match_response: Array       # (R,)
    pose_err: Array             # (R,) |est - truth| (sim-only luxury)


def init_fleet_state(cfg: SlamConfig, key: Array) -> FleetState:
    R = cfg.fleet.n_robots
    sim = thymio.init_fleet(cfg.robot, key, R)
    return FleetState(
        sim=sim,
        est_poses=sim.poses,               # start calibrated
        grid=G.empty_grid(cfg.grid),
        exploring=jnp.ones((R,), bool),
        t=jnp.int32(0),
    )


@functools.partial(jax.jit, static_argnums=(0, 2))
def fleet_step(cfg: SlamConfig, state: FleetState, world_res_m: float,
               world: Array) -> tuple[FleetState, FleetDiag]:
    """One synchronous fleet tick (the reference's 10 Hz loop, batched)."""
    dt = 1.0 / cfg.robot.control_rate_hz
    n_samples = int(cfg.scan.range_max_m / (world_res_m * 0.5))

    # 1. Sense: scans + IR from ground truth.
    scans = lidar.simulate_scans(cfg.scan, world, world_res_m, n_samples,
                                 state.sim.poses)
    prox = lidar.ir_proximity(world, world_res_m, state.sim.poses)

    # 2. Act: frontier assignment on the current map drives the policy.
    fr = F.compute_frontiers(cfg.frontier, cfg.grid, state.grid,
                             state.est_poses)
    goals = fr.targets[jnp.clip(fr.assignment, 0)]
    goal_valid = fr.assignment >= 0
    pol = frontier_policy(cfg.robot, cfg.scan, state.est_poses, goals,
                          goal_valid, scans, prox, state.exploring)

    # 3. Move the simulated fleet; read measured wheel speeds.
    sim2, measured = thymio.step_fleet(cfg.robot, state.sim,
                                       pol.targets.astype(jnp.float32), dt)

    # 4. Odometry propagate estimates from measured speeds.
    est = jax.vmap(lambda p, w: rk2_step(cfg.robot, p, w[0], w[1], dt))(
        state.est_poses, measured)

    # 5. Correlative correction against the shared map.
    res = M.match_batch(cfg.grid, cfg.scan, cfg.matcher, state.grid,
                        scans, est)
    est = jnp.where(res.accepted[:, None], res.pose, est)

    # 6. Fuse this tick's scans (batched fold, exact under overlap).
    grid = G.fuse_scans(cfg.grid, cfg.scan, state.grid, scans, est)

    state2 = FleetState(sim=sim2, est_poses=est, grid=grid,
                        exploring=state.exploring, t=state.t + 1)
    diag = FleetDiag(policy=pol, frontiers=fr, match_response=res.response,
                     pose_err=jnp.linalg.norm(
                         est[:, :2] - sim2.poses[:, :2], axis=-1))
    return state2, diag
