"""Fleet model: N simulated Thymios exploring and mapping one shared world —
the framework's flagship pipeline (BASELINE.json configs 4-5).

One jitted step closes the whole loop the reference spreads across two
machines and three processes (SURVEY.md §3.2-3.4):

  simulate LD06 scans (device raycast)           [was: LD06 driver on the Pi]
  -> odometry from measured wheel speeds         [was: ThymioBrain update_loop]
  -> key-scan gate 0.1 m / 0.1 rad               [was: slam_toolbox gate,
                                                  slam_config.yaml:37-38]
  -> batched correlative matching                [was: slam_toolbox matcher]
  -> masked log-odds fusion into a shared grid   [was: slam_toolbox rasterizer]
  -> per-robot pose graphs + loop closure        [was: slam_toolbox graph,
     with shared-map re-fusion on closure         slam_config.yaml:43-48]
  -> frontier detect/cluster/assign              [was: future work, §VI.2]
  -> explorer policy -> wheel targets            [was: subsumption navigator]
  -> fleet kinematics step                       [was: physical robots]

Everything is (R, ...)-batched with vmap; gating is by masking (all robots
compute every tick — the batched-SIMD trade — but sub-gate robots add no
map evidence and no graph nodes). Loop-closure verification and map repair
run under one batch-level `lax.cond`, so their cost is paid only on ticks
where some robot actually has a candidate. `parallel.fleet_sharded` runs
the same step under shard_map over a ('fleet', 'space') mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import SlamConfig, ensure_valid_mode
from jax_mapping.models.explorer import PolicyOut, frontier_policy
from jax_mapping.models.slam import _verify_loop
from jax_mapping.ops import frontier as F
from jax_mapping.ops import grid as G
from jax_mapping.ops import posegraph as PG
from jax_mapping.ops import scan_match as M
from jax_mapping.ops.odometry import pose_between, rk2_step, wrap_angle
from jax_mapping.sim import lidar, thymio

Array = jax.Array

_ODO_W = (50.0, 100.0)          # odometry edge information (t, theta)
_LOOP_W = (200.0, 400.0)        # verified loop edge information


class FleetState(NamedTuple):
    sim: thymio.FleetSimState   # ground truth
    est_poses: Array            # (R, 3) SLAM estimates
    grid: Array                 # (N, N) shared log-odds map
    exploring: Array            # (R,) bool (the /start /stop flag)
    last_key_poses: Array       # (R, 3) pose at each robot's last key-scan
    graphs: PG.PoseGraph        # per-robot graphs, leading (R,) axis
    scan_rings: Array           # (R, max_poses, padded_beams) key-scans
    n_loops: Array              # (R,) int32 closed loops per robot
    t: Array                    # () int32 step counter


class FleetDiag(NamedTuple):
    policy: PolicyOut
    frontiers: F.FrontierResult
    match_response: Array       # (R,)
    pose_err: Array             # (R,) |est - truth| (sim-only luxury)
    is_key: Array               # (R,) bool: passed the key-scan gate
    loop_closed: Array          # (R,) bool: closed a loop this tick


def init_fleet_state(cfg: SlamConfig, key: Array) -> FleetState:
    R = cfg.fleet.n_robots
    sim = thymio.init_fleet(cfg.robot, key, R)
    return FleetState(
        sim=sim,
        est_poses=sim.poses,               # start calibrated
        grid=G.empty_grid(cfg.grid),
        exploring=jnp.ones((R,), bool),
        last_key_poses=jnp.full((R, 3), 1e9, jnp.float32),  # force first key
        graphs=jax.vmap(lambda _: PG.empty_graph(cfg.loop))(jnp.arange(R)),
        scan_rings=jnp.zeros((R, cfg.loop.max_poses, cfg.scan.padded_beams),
                             jnp.float32),
        n_loops=jnp.zeros((R,), jnp.int32),
        t=jnp.int32(0),
    )


def _update_graphs(cfg: SlamConfig, graphs: PG.PoseGraph, est: Array,
                   is_key: Array, scans: Array, rings: Array):
    """Key robots append a pose + odometry edge + ring scan. Returns
    (graphs, rings, k_idx) with k_idx the slot each robot's new pose used
    (== pre-add n_poses; garbage for non-key robots, masked downstream).

    A full ring thins FIRST (PG.thin_keyframes — keyframe spacing doubles,
    half the ring frees), so graphs never saturate and map repair never
    stops (round-3 verdict weak #5). Thinning is not gated on is_key: a
    robot that parks with a full ring must not hold the fleet's
    ring-completeness invariant hostage."""
    cap = cfg.loop.max_poses

    need_thin = graphs.n_poses >= cap                          # (R,)

    def maybe_thin(g, ring, flag):
        g2, ring2 = PG.thin_keyframes(g, ring, _ODO_W[0], _ODO_W[1])
        g3 = jax.tree.map(lambda a, b: jnp.where(flag, a, b), g2, g)
        return g3, jnp.where(flag, ring2, ring)

    graphs, rings = jax.vmap(maybe_thin)(graphs, rings, need_thin)
    k_idx = graphs.n_poses                                     # (R,)

    def upd(g, pose, flag):
        k = g.n_poses
        prev = g.poses[jnp.maximum(k - 1, 0)]
        g2 = PG.add_pose_if(g, pose, flag)
        meas = pose_between(prev, pose)
        w = jnp.array([_ODO_W[0], _ODO_W[0], _ODO_W[1]], jnp.float32)
        # k < cap: a full ring must not grow edges onto the never-written
        # slot k == cap (clamped gathers would turn it into a corrupting
        # self-edge in every later optimise).
        return PG.add_edge_if(g2, jnp.maximum(k - 1, 0), k, meas, w,
                              flag & (k > 0) & (k < cap))

    graphs = jax.vmap(upd)(graphs, est, is_key)

    def ring_upd(ring, k, ranges, flag):
        slot = jnp.minimum(k, cap - 1)
        ok = flag & (k < cap)
        return jnp.where(ok, ring.at[slot].set(ranges), ring)

    rings = jax.vmap(ring_upd)(rings, k_idx, scans, is_key)
    return graphs, rings, k_idx


def _cross_candidates(cfg: SlamConfig, graphs: PG.PoseGraph,
                      est: Array) -> tuple[Array, Array, Array]:
    """Nearest OTHER robot's established chain pose within the loop radius.

    Inter-robot consistency: the reference's single SLAM node fuses every
    robot's scan into one graph (`pc_server.launch.py:14-19`), so two
    robots mapping the same wall share constraints for free. Here graphs
    are per-robot (they shard over the fleet axis without collectives), so
    the equivalent coupling is explicit: a robot may close a loop against
    a fleet-mate's chain. Returns (robot (R,), pose_idx (R,), found (R,)).
    """
    R = est.shape[0]
    cap = cfg.loop.max_poses
    pos = graphs.poses[:, :, :2]                             # (R, cap, 2)
    d = jnp.linalg.norm(pos[None, :, :, :] - est[:, None, None, :2],
                        axis=-1)                             # (R, R, cap)
    established = graphs.n_poses >= cfg.loop.min_chain_size  # (R,)
    ok = (graphs.pose_valid & established[:, None])[None, :, :]
    ok = ok & ~jnp.eye(R, dtype=bool)[:, :, None]
    d = jnp.where(ok, d, jnp.inf)
    flat = d.reshape(R, R * cap)
    best = jnp.argmin(flat, axis=1)
    found = jnp.take_along_axis(flat, best[:, None], 1)[:, 0] \
        <= cfg.loop.search_radius_m
    return ((best // cap).astype(jnp.int32),
            (best % cap).astype(jnp.int32), found)


def _verify_and_optimize(cfg: SlamConfig, graphs: PG.PoseGraph,
                         rings: Array, est: Array, scans: Array,
                         k_idx: Array, cand: Array, attempt: Array,
                         xrobot: Array, xcand: Array, xattempt: Array):
    """Shared closure body for the local AND sharded fleet steps:
    two-stage verification of every attempting robot against a ghost-free
    chain map (models/slam._verify_loop), loop edges, per-robot
    optimisation, pose update. Returns (graphs, est, closed).

    Own-graph loops verify against the robot's own candidate chain and add
    the edge cand -> k. Cross-robot loops (xattempt, own candidates take
    precedence) verify against robot `xrobot`'s chain — the full chain is
    admitted (vk past the ring) because the query's drift frame cannot
    leak into ANOTHER robot's map — and anchor the robot's OWN graph with
    a strong (k-1) -> k edge re-measured from the verified pose. The
    anchor approximates a joint-graph inter-robot edge in exchange for
    graphs that stay per-robot (shardable without collectives); it encodes
    "my pose in my neighbour's frame at verification time".

    Verification runs under `lax.map` over robots — each iteration
    materialises one chain grid, so peak memory is one extra full-size
    grid regardless of fleet size."""
    cap = cfg.loop.max_poses
    R = est.shape[0]
    use_x = xattempt & ~attempt
    vrobot = jnp.where(use_x, xrobot, jnp.arange(R))
    vcand = jnp.where(use_x, xcand, cand)
    # Own: exclude the query's recent tail from the chain map. Cross: the
    # whole chain is admissible.
    vk = jnp.where(use_x, jnp.int32(cap + cfg.loop.min_chain_size), k_idx)

    def one(r):
        g_v = jax.tree.map(lambda x: x[vrobot[r]], graphs)
        res = _verify_loop(cfg, g_v, rings[vrobot[r]], vcand[r], vk[r],
                           scans[r], est[r])
        return res.pose, res.accepted, res.response

    fine_pose, fine_acc, fine_resp = jax.lax.map(one, jnp.arange(R))
    closed = (attempt | use_x) & fine_acc & \
        (fine_resp >= cfg.loop.response_fine)

    def add_loop(g, c, q, meas_pose, flag, isx):
        # Own loop: edge c -> q. Cross relocalization: the verified pose
        # overwrites the robot's newest node directly (its drifted value
        # was pure dead reckoning), and when a previous node exists an
        # anchor edge (q-1) -> q re-measured from the verified pose pulls
        # the chain (the weak odometry edge between the same nodes stays;
        # the optimiser blends them by information weight).
        # q < cap gate matches the edge add below: a saturated graph's
        # k_idx == cap would alias onto slot cap-1, corrupting an
        # established keyframe other robots may be matching against.
        qc = jnp.minimum(q, cap - 1)
        g = g._replace(poses=g.poses.at[qc].set(
            jnp.where(flag & isx & (q < cap), meas_pose, g.poses[qc])))
        src = jnp.where(isx, jnp.maximum(q - 1, 0), c)
        rel = pose_between(g.poses[src], meas_pose)
        w = jnp.array([_LOOP_W[0], _LOOP_W[0], _LOOP_W[1]], jnp.float32)
        ok = flag & (q < cap) & (~isx | (q > 0))
        return PG.add_edge_if(g, src, q, rel, w, ok)

    graphs2 = jax.vmap(add_loop)(graphs, cand, k_idx, fine_pose, closed,
                                 use_x)
    opt = jax.vmap(lambda g: PG.optimize(cfg.loop, g))(graphs2)
    graphs3 = jax.tree.map(
        lambda a, b: jnp.where(
            closed.reshape((-1,) + (1,) * (a.ndim - 1)), a, b), opt, graphs2)

    est2 = jnp.where(closed[:, None],
                     jax.vmap(lambda g, q: g.poses[jnp.minimum(q, cap - 1)])(
                         graphs3, k_idx), est)
    return graphs3, est2, closed


def _close_loops(cfg: SlamConfig, graphs: PG.PoseGraph, grid: Array,
                 rings: Array, est: Array, scans: Array, k_idx: Array,
                 cand: Array, attempt: Array,
                 xrobot: Array, xcand: Array, xattempt: Array):
    """Fleet closure: shared verify/optimise body + shared-map re-fusion.
    Returns (graphs, grid, est, closed)."""
    graphs3, est2, closed = _verify_and_optimize(
        cfg, graphs, rings, est, scans, k_idx, cand, attempt,
        xrobot, xcand, xattempt)

    # Shared-map repair: re-fuse EVERY robot's key-scan ring from the
    # (possibly re-optimised) trajectories. The shared grid mixes all
    # robots' evidence, so per-robot incremental patching is impossible —
    # full re-fusion is the exact, TPU-cheap answer (ops/posegraph.py
    # module docstring). Rings are complete by construction: a full ring
    # thins before any append (_update_graphs), so every key-scan that
    # shaped the map is either in a ring or was superseded by thinning —
    # repair never has to stop (the round-3 saturation freeze is gone).
    R, cap, beams = rings.shape
    poses_flat = graphs3.poses[:, :cap].reshape(R * cap, 3)
    valid_flat = graphs3.pose_valid[:, :cap].reshape(R * cap)
    refused = G.fuse_scans_masked(cfg.grid, cfg.scan, G.empty_grid(cfg.grid),
                                  rings.reshape(R * cap, beams), poses_flat,
                                  valid_flat)
    grid2 = jnp.where(closed.any(), refused, grid)
    return graphs3, grid2, est2, closed


class _TickPre(NamedTuple):
    """Everything one fleet tick computes BEFORE the batch-level loop-
    closure cond: sense/act/move/match/fuse/graph-growth plus the
    closure candidates. Split out of `fleet_step` so the tenant
    megabatch (`tenancy/megabatch.py`) can vmap this part over a
    tenant axis and hoist the closure `lax.cond` ABOVE the vmap — a
    cond with a vmapped predicate lowers to `select` (BOTH branches
    execute every tick for every tenant), which turns the rare-tick
    closure repair into an every-tick tax. Hoisted, the predicate is
    the any() over the whole batch and the common no-candidate tick
    skips closure work exactly like a solo run."""

    sim2: thymio.FleetSimState  # moved ground truth
    pol: PolicyOut
    fr: F.FrontierResult
    match_response: Array       # (R,)
    est: Array                  # (R, 3) post-match estimates
    is_key: Array               # (R,) bool
    grid: Array                 # fused (mapping) or untouched grid
    graphs: PG.PoseGraph
    rings: Array
    k_idx: Array                # (R,) slot of each robot's new pose
    scans: Array                # (R, padded_beams)
    cand: Array                 # (R,) own-graph loop candidate index
    attempt: Array              # (R,) bool own-graph closure attempts
    xrobot: Array               # (R,) cross-robot candidate owner
    xcand: Array                # (R,) cross-robot candidate pose index
    xattempt: Array             # (R,) bool cross-robot attempts


class _TickSense(NamedTuple):
    """The sense/act/move/match/fuse half of one tick (steps 1-7),
    composed from `_tick_move` / `_tick_est` / `_tick_map`. The
    megabatch vmaps this half wholesale — its per-lane bit-stability
    is exactly what bounds `tenancy.megabatch.EXACT_BUCKETS` (the
    odometry rk2 and matcher fine-stage arithmetic vectorize with
    different FMA/SIMD choices past that ladder, measured ~3e-10 est
    drift at power-of-two tenant counts >= 4). The graph-growth half
    (`_tick_graph`) is split out because ITS `pose_between` edge
    arithmetic drifts (~1e-9) under a tenant vmap even at ladder
    buckets in edge-heavy missions — the megabatch runs that half
    per-lane under `lax.map` instead."""

    sim2: thymio.FleetSimState
    pol: PolicyOut
    fr: F.FrontierResult
    res: M.MatchResult
    est: Array
    is_key: Array
    grid: Array
    scans: Array


class _TickMove(NamedTuple):
    """Steps 1-3: sense, frontier-driven policy, simulated motion."""

    sim2: thymio.FleetSimState
    measured: Array             # (R, 2) measured wheel speeds
    pol: PolicyOut
    fr: F.FrontierResult
    scans: Array


def _tick_move(cfg: SlamConfig, state: FleetState, world_res_m: float,
               world: Array) -> _TickMove:
    dt = 1.0 / cfg.robot.control_rate_hz
    n_samples = int(cfg.scan.range_max_m / (world_res_m * 0.5))

    # 1. Sense: scans + IR from ground truth.
    scans = lidar.simulate_scans(cfg.scan, world, world_res_m, n_samples,
                                 state.sim.poses)
    prox = lidar.ir_proximity(world, world_res_m, state.sim.poses)

    # 2. Act: frontier assignment on the current map drives the policy.
    fr = F.compute_frontiers(cfg.frontier, cfg.grid, state.grid,
                             state.est_poses)
    goals = fr.targets[jnp.clip(fr.assignment, 0)]
    goal_valid = fr.assignment >= 0
    if cfg.frontier.planned_goals:
        # Planned steering: a waypoint along the min-plus shortest path
        # to the assigned target (frontier.assigned_waypoints) replaces
        # the straight-line bearing wherever a plan exists.
        wps, wvalid = F.assigned_waypoints(cfg.frontier, cfg.grid,
                                           state.grid, state.est_poses,
                                           fr.targets, fr.assignment)
        goals = jnp.where(wvalid[:, None], wps, goals)
    pol = frontier_policy(cfg.robot, cfg.scan, state.est_poses, goals,
                          goal_valid, scans, prox, state.exploring)

    # 3. Move the simulated fleet; read measured wheel speeds.
    sim2, measured = thymio.step_fleet(cfg.robot, state.sim,
                                       pol.targets.astype(jnp.float32), dt)
    return _TickMove(sim2=sim2, measured=measured, pol=pol, fr=fr,
                     scans=scans)


def _tick_est(cfg: SlamConfig, est_poses: Array,
              measured: Array) -> Array:
    """Step 4: odometry propagate estimates from measured speeds."""
    dt = 1.0 / cfg.robot.control_rate_hz
    return jax.vmap(lambda p, w: rk2_step(cfg.robot, p, w[0], w[1], dt))(
        est_poses, measured)


def _tick_map(cfg: SlamConfig, state: FleetState, est: Array,
              scans: Array):
    """Steps 5-7: key gate, correlative correction, fusion. Returns
    (res, est, is_key, grid)."""
    # 5. Key-scan gate (slam_config.yaml:37-38): matching, fusion, and
    # graph growth only for robots that moved enough.
    d = jnp.linalg.norm(est[:, :2] - state.last_key_poses[:, :2], axis=-1)
    dth = jnp.abs(wrap_angle(est[:, 2] - state.last_key_poses[:, 2]))
    is_key = (d > cfg.matcher.min_travel_m) | \
        (dth > cfg.matcher.min_heading_rad)

    # 6. Correlative correction against the shared map (key robots only).
    res = M.match_batch(cfg.grid, cfg.scan, cfg.matcher, state.grid,
                        scans, est)
    est = jnp.where((is_key & res.accepted)[:, None], res.pose, est)

    if cfg.mode == "localization":
        # Frozen-map mode (models/slam.slam_step's key_branch analog for
        # the batch path): the matcher's corrections stand, nothing
        # fuses. Graph growth and closures are compiled out in
        # `_tick_graph`.
        grid = state.grid
    else:
        # 7. Fuse this tick's key scans (masked batched fold, exact under
        # overlap; sub-gate robots add nothing).
        grid = G.fuse_scans_masked(cfg.grid, cfg.scan, state.grid, scans,
                                   est, is_key)
    return res, est, is_key, grid


def _tick_sense(cfg: SlamConfig, state: FleetState, world_res_m: float,
                world: Array) -> _TickSense:
    """Steps 1-7 of the fleet tick: sense, frontier-driven policy,
    move, odometry, key gate, correlative match, fusion."""
    mv = _tick_move(cfg, state, world_res_m, world)
    est = _tick_est(cfg, state.est_poses, mv.measured)
    res, est, is_key, grid = _tick_map(cfg, state, est, mv.scans)
    return _TickSense(sim2=mv.sim2, pol=mv.pol, fr=mv.fr, res=res,
                      est=est, is_key=is_key, grid=grid,
                      scans=mv.scans)


def _tick_graph(cfg: SlamConfig, graphs: PG.PoseGraph, rings: Array,
                est: Array, is_key: Array, scans: Array,
                accepted: Array):
    """Step 8, the graph-growth half of one tick: key-pose append +
    odometry edges + ring updates + own/cross loop-closure candidates.
    Returns (graphs, rings, k_idx, cand, attempt, xrobot, xcand,
    xattempt); localization mode compiles the whole phase out (dead
    zeros the caller never reads)."""
    R = est.shape[0]
    if cfg.mode == "localization":
        zi = jnp.zeros((R,), jnp.int32)
        zb = jnp.zeros((R,), bool)
        return graphs, rings, zi, zi, zb, zi, zi, zb

    graphs, rings, k_idx = _update_graphs(cfg, graphs, est, is_key,
                                          scans, rings)
    cand, cand_found = jax.vmap(
        lambda g, q: PG.loop_candidate(cfg.loop, g, q))(graphs, k_idx)
    attempt = is_key & cand_found & bool(cfg.loop.enabled)
    # Cross-robot closure for key robots without an own candidate,
    # gated on the robot being LOST: its narrow-window match against
    # the shared map was rejected. A robot matching happily is
    # already coupled to the fleet through the shared grid;
    # cross-verification is the wide-window relocalization against a
    # fleet-mate's chain for the drifted one.
    xrobot, xcand, xfound = _cross_candidates(cfg, graphs, est)
    xattempt = is_key & ~accepted & xfound & ~attempt & \
        bool(cfg.loop.enabled) & bool(cfg.loop.cross_robot)
    return (graphs, rings, k_idx, cand, attempt, xrobot, xcand,
            xattempt)


def _tick_pre(cfg: SlamConfig, state: FleetState, world_res_m: float,
              world: Array) -> _TickPre:
    """Steps 1-8 of the fleet tick up to (but excluding) the closure
    cond; trace-identical to the historical `fleet_step` prefix."""
    sense = _tick_sense(cfg, state, world_res_m, world)
    (graphs, rings, k_idx, cand, attempt, xrobot, xcand,
     xattempt) = _tick_graph(cfg, state.graphs, state.scan_rings,
                             sense.est, sense.is_key, sense.scans,
                             sense.res.accepted)
    return _TickPre(sim2=sense.sim2, pol=sense.pol, fr=sense.fr,
                    match_response=sense.res.response, est=sense.est,
                    is_key=sense.is_key, grid=sense.grid,
                    graphs=graphs, rings=rings, k_idx=k_idx,
                    scans=sense.scans, cand=cand, attempt=attempt,
                    xrobot=xrobot, xcand=xcand, xattempt=xattempt)


def _tick_finish(cfg: SlamConfig, state: FleetState, pre: _TickPre,
                 grid: Array, graphs: PG.PoseGraph, est: Array,
                 closed: Array) -> tuple[FleetState, FleetDiag]:
    """Fold the (possibly closure-repaired) results back into the next
    FleetState + FleetDiag; trace-identical to the historical
    `fleet_step` suffix."""
    last_key = jnp.where(pre.is_key[:, None], est, state.last_key_poses)
    state2 = FleetState(sim=pre.sim2, est_poses=est, grid=grid,
                        exploring=state.exploring, last_key_poses=last_key,
                        graphs=graphs, scan_rings=pre.rings,
                        n_loops=state.n_loops + closed.astype(jnp.int32),
                        t=state.t + 1)
    diag = FleetDiag(policy=pre.pol, frontiers=pre.fr,
                     match_response=pre.match_response,
                     pose_err=jnp.linalg.norm(
                         est[:, :2] - pre.sim2.poses[:, :2], axis=-1),
                     is_key=pre.is_key, loop_closed=closed)
    return state2, diag


def _fleet_step_impl(cfg: SlamConfig, state: FleetState,
                     world_res_m: float, world: Array
                     ) -> tuple[FleetState, FleetDiag]:
    """The un-jitted fleet tick: pre -> batch-level closure cond ->
    finish. `fleet_step` jits it; the tenant megabatch vmaps the pre/
    finish halves and hoists the cond above the tenant axis."""
    ensure_valid_mode(cfg)
    pre = _tick_pre(cfg, state, world_res_m, world)
    if cfg.mode == "localization":
        grid, graphs, est = pre.grid, pre.graphs, pre.est
        closed = jnp.zeros_like(pre.is_key)
    else:
        graphs, grid, est, closed = jax.lax.cond(
            (pre.attempt | pre.xattempt).any(),
            lambda args: _close_loops(cfg, *args),
            lambda args: (args[0], args[1], args[3],
                          jnp.zeros_like(pre.attempt)),
            (pre.graphs, pre.grid, pre.rings, pre.est, pre.scans,
             pre.k_idx, pre.cand, pre.attempt, pre.xrobot, pre.xcand,
             pre.xattempt))
    return _tick_finish(cfg, state, pre, grid, graphs, est, closed)


@functools.partial(jax.jit, static_argnums=(0, 2))
def fleet_step(cfg: SlamConfig, state: FleetState, world_res_m: float,
               world: Array) -> tuple[FleetState, FleetDiag]:
    """One synchronous fleet tick (the reference's 10 Hz loop, batched)."""
    return _fleet_step_impl(cfg, state, world_res_m, world)
