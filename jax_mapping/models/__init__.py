"""Composed pipelines: single-robot SLAM, multi-robot fleet, explorers."""
