"""Simulated Thymio fleet + synthetic LD06 LiDAR, all on device."""
