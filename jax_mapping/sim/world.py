"""Ground-truth worlds for the simulated fleet.

The reference was validated physically in workshop courses built from wooden
planks (report.pdf §IV, SURVEY.md §4); the framework equivalent is a
procedural world generator producing boolean occupancy bitmaps: a bounded
arena with random axis-aligned walls/boxes — the same courses, simulated.
World grids use the same centred indexing as the map grid (row = y, col = x).
"""

from __future__ import annotations

import numpy as np


def empty_arena(size_cells: int, resolution_m: float,
                wall_cells: int = 2) -> np.ndarray:
    """Closed rectangular arena: walls around the border."""
    w = np.zeros((size_cells, size_cells), bool)
    t = wall_cells
    w[:t, :] = True
    w[-t:, :] = True
    w[:, :t] = True
    w[:, -t:] = True
    return w


def plank_course(size_cells: int, resolution_m: float, n_planks: int = 12,
                 seed: int = 0, margin_m: float = 0.6) -> np.ndarray:
    """Arena + random 'wooden planks': thin axis-aligned wall segments,
    keeping a clear margin around the centre so robots can start there."""
    rng = np.random.default_rng(seed)
    w = empty_arena(size_cells, resolution_m)
    res = resolution_m
    margin_c = int(margin_m / res)
    c = size_cells // 2
    for _ in range(n_planks):
        length = rng.integers(int(0.5 / res), int(2.0 / res))
        thick = max(1, int(0.04 / res))
        r0 = rng.integers(2, size_cells - 2 - length)
        c0 = rng.integers(2, size_cells - 2 - length)
        horiz = rng.random() < 0.5
        if horiz:
            rr = slice(r0, r0 + thick)
            cc = slice(c0, c0 + length)
        else:
            rr = slice(r0, r0 + length)
            cc = slice(c0, c0 + thick)
        # Keep the spawn zone clear.
        if abs((rr.start + rr.stop) / 2 - c) < margin_c and \
                abs((cc.start + cc.stop) / 2 - c) < margin_c:
            continue
        w[rr, cc] = True
    return w


def rooms_world(size_cells: int, resolution_m: float,
                seed: int = 1) -> np.ndarray:
    """Arena split into rooms with door gaps — loop-closure friendly."""
    rng = np.random.default_rng(seed)
    w = empty_arena(size_cells, resolution_m)
    res = resolution_m
    door = max(3, int(0.5 / res))
    for frac in (0.33, 0.66):
        pos = int(size_cells * frac)
        gap = rng.integers(door, size_cells - 2 * door)
        w[pos:pos + 2, :] = True
        w[pos:pos + 2, gap:gap + door] = False
        gap = rng.integers(door, size_cells - 2 * door)
        w[:, pos:pos + 2] = True
        w[gap:gap + door, pos:pos + 2] = False
    return w
