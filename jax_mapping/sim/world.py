"""Ground-truth worlds for the simulated fleet.

The reference was validated physically in workshop courses built from wooden
planks (report.pdf §IV, SURVEY.md §4); the framework equivalent is a
procedural world generator producing boolean occupancy bitmaps: a bounded
arena with random axis-aligned walls/boxes — the same courses, simulated.
World grids use the same centred indexing as the map grid (row = y, col = x).
"""

from __future__ import annotations

import numpy as np


def empty_arena(size_cells: int, resolution_m: float,
                wall_cells: int = 2) -> np.ndarray:
    """Closed rectangular arena: walls around the border."""
    w = np.zeros((size_cells, size_cells), bool)
    t = wall_cells
    w[:t, :] = True
    w[-t:, :] = True
    w[:, :t] = True
    w[:, -t:] = True
    return w


def plank_course(size_cells: int, resolution_m: float, n_planks: int = 12,
                 seed: int = 0, margin_m: float = 0.6) -> np.ndarray:
    """Arena + random 'wooden planks': thin axis-aligned wall segments,
    keeping a clear margin around the centre so robots can start there."""
    rng = np.random.default_rng(seed)
    w = empty_arena(size_cells, resolution_m)
    res = resolution_m
    margin_c = int(margin_m / res)
    c = size_cells // 2
    for _ in range(n_planks):
        length = rng.integers(int(0.5 / res), int(2.0 / res))
        thick = max(1, int(0.04 / res))
        r0 = rng.integers(2, size_cells - 2 - length)
        c0 = rng.integers(2, size_cells - 2 - length)
        horiz = rng.random() < 0.5
        if horiz:
            rr = slice(r0, r0 + thick)
            cc = slice(c0, c0 + length)
        else:
            rr = slice(r0, r0 + length)
            cc = slice(c0, c0 + thick)
        # Keep the spawn zone clear.
        if abs((rr.start + rr.stop) / 2 - c) < margin_c and \
                abs((cc.start + cc.stop) / 2 - c) < margin_c:
            continue
        w[rr, cc] = True
    return w


def arena_with_door(size_cells: int, resolution_m: float,
                    wall_frac: float = 0.62,
                    door_m: float = 0.5) -> tuple:
    """Arena split by one vertical wall with a centred door gap; the
    scripted-scenario workhorse (scenarios/dynamics.py): the door sits
    in direct line of sight of centre-spawned robots, so a closed →
    mapped → re-opened cycle is re-observed without luck.

    Returns (world, doors): `world` has the door OPEN (a gap in the
    wall); each door is a dict {name, r0, r1, c0, c1} naming the
    half-open cell rectangle a `door_close` scenario event fills with
    wall. The dict form keeps this module scenario-agnostic —
    `scenarios.dynamics.DoorSpec` consumes it."""
    w = empty_arena(size_cells, resolution_m)
    res = resolution_m
    door = max(3, int(door_m / res))
    thick = 2
    col = int(size_cells * wall_frac)
    w[:, col:col + thick] = True
    r0 = size_cells // 2 - door // 2
    w[r0:r0 + door, col:col + thick] = False
    doors = [{"name": "door0", "r0": r0, "r1": r0 + door,
              "c0": col, "c1": col + thick}]
    return w, doors


def rooms_with_doors(size_cells: int, resolution_m: float,
                     seed: int = 1) -> tuple:
    """`rooms_world` that also REPORTS its door gaps: returns
    (world, doors) with one named rectangle per gap (dict form, see
    `arena_with_door`) so a scenario script can close and re-open the
    exact doors the generator carved."""
    rng = np.random.default_rng(seed)
    w = empty_arena(size_cells, resolution_m)
    res = resolution_m
    door = max(3, int(0.5 / res))
    doors = []
    for k, frac in enumerate((0.33, 0.66)):
        pos = int(size_cells * frac)
        gap = int(rng.integers(door, size_cells - 2 * door))
        w[pos:pos + 2, :] = True
        w[pos:pos + 2, gap:gap + door] = False
        doors.append({"name": f"door_h{k}", "r0": pos, "r1": pos + 2,
                      "c0": gap, "c1": gap + door})
        gap = int(rng.integers(door, size_cells - 2 * door))
        w[:, pos:pos + 2] = True
        w[gap:gap + door, pos:pos + 2] = False
        doors.append({"name": f"door_v{k}", "r0": gap, "r1": gap + door,
                      "c0": pos, "c1": pos + 2})
    return w, doors


def corridor_course(size_cells: int, resolution_m: float,
                    corridor_w_m: float = 1.2, n_rooms: int = 4,
                    seed: int = 2) -> tuple:
    """Long east-west corridor through an otherwise solid slab, with
    `n_rooms` side rooms hanging off it behind door gaps the generator
    REPORTS (dict form, see `arena_with_door`) — the lifelong
    bounded-memory soak's world. Unlike the compact arenas above,
    exploring this world forces TRAVEL: the corridor spans the full
    extent, so traveled distance — and with it the sliding window's
    shift/eviction pressure — grows with mission length instead of
    saturating near the spawn. Robots spawn mid-corridor (the centre
    cell is always carved).

    Returns (world, doors)."""
    rng = np.random.default_rng(seed)
    w = np.ones((size_cells, size_cells), bool)
    res = resolution_m
    half = max(2, int(corridor_w_m / res) // 2)
    c = size_cells // 2
    w[c - half:c + half, 2:size_cells - 2] = False
    door = max(3, int(0.5 / res))
    thick = 2
    doors = []
    for k in range(n_rooms):
        cx = int((k + 1) * size_cells / (n_rooms + 1))
        room = max(door + 4,
                   int(rng.integers(int(1.2 / res), int(2.0 / res))))
        if k % 2 == 0:                       # rooms alternate sides
            wall_r0 = c + half
            r0, r1 = wall_r0 + thick, min(size_cells - 2,
                                          wall_r0 + thick + room)
        else:
            wall_r0 = c - half - thick
            r1, r0 = wall_r0, max(2, wall_r0 - room)
        c0 = max(2, cx - room // 2)
        c1 = min(size_cells - 2, cx + room // 2)
        w[r0:r1, c0:c1] = False              # the room
        g0 = min(max(c0 + 1, cx - door // 2), c1 - door - 1)
        w[wall_r0:wall_r0 + thick, g0:g0 + door] = False  # the doorway
        doors.append({"name": f"room{k}", "r0": wall_r0,
                      "r1": wall_r0 + thick, "c0": g0, "c1": g0 + door})
    return w, doors


def stamp_disc(world: np.ndarray, row: float, col: float,
               radius_cells: float) -> np.ndarray:
    """Stamp a filled occupied disc (a crowd blob) into `world` IN
    PLACE, clipped to the extent; returns `world`. Cheap bounding-box
    mask — the crowd path recomputes every step."""
    nr, nc = world.shape
    r0 = max(0, int(row - radius_cells) - 1)
    r1 = min(nr, int(row + radius_cells) + 2)
    c0 = max(0, int(col - radius_cells) - 1)
    c1 = min(nc, int(col + radius_cells) + 2)
    if r1 <= r0 or c1 <= c0:
        return world
    rr = np.arange(r0, r1, dtype=np.float32)[:, None] - row
    cc = np.arange(c0, c1, dtype=np.float32)[None, :] - col
    world[r0:r1, c0:c1] |= (rr * rr + cc * cc) <= radius_cells ** 2
    return world


def rooms_world(size_cells: int, resolution_m: float,
                seed: int = 1) -> np.ndarray:
    """Arena split into rooms with door gaps — loop-closure friendly.
    Same world `rooms_with_doors` builds (identical RNG draws), minus
    the door report."""
    return rooms_with_doors(size_cells, resolution_m, seed)[0]
