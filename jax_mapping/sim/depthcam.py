"""Synthetic pinhole depth camera: device-native rendering against the
2.5D world (BASELINE.json configs[4]: "simulated depth cam").

The reference has no depth sensor; this renders the same generated worlds
the LiDAR sim uses (sim/world.py bitmaps) extruded to 3D — walls of
`wall_height_m` standing on an infinite floor at z = 0. TPU-first like
sim/lidar.py: no per-ray marching loops. Every pixel samples its ray at S
fixed euclidean steps (one big gather against the world bitmap + pure
math for the floor), and the first hit falls out of an argmax over the
boolean hit profile. vmap over pixels and poses; everything static-shape.

Returned images follow the real-sensor convention ops/voxel.py consumes:
depth = z along the OPTICAL AXIS (not euclidean ray length), 0 = no
return (ray left the world or exceeded range_max).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax_mapping.config import DepthCamConfig
from jax_mapping.ops.voxel import camera_pose

Array = jax.Array


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 5))
def render_depth(cam: DepthCamConfig, world: Array, world_res_m: float,
                 n_samples: int, pose_xyyaw: Array,
                 wall_height_m: float = 0.5) -> Array:
    """One (H, W) float32 depth image from a planar robot pose [x, y, yaw].

    `world` is the (Hw, Ww) boolean obstacle bitmap with centred indexing
    (the sim/lidar.py convention). Walls span 0 <= z <= wall_height_m;
    the floor plane z = 0 returns everywhere (a real depth cam sees the
    floor). Pixels whose ray exits the world sideways or runs past
    range_max report 0.0 (no return).
    """
    Hw, Ww = world.shape
    H, W = cam.height_px, cam.width_px
    pos, R = camera_pose(pose_xyyaw[0], pose_xyyaw[1], pose_xyyaw[2], cam)

    # Per-pixel unit ray directions in the camera frame (z optical).
    us = (jnp.arange(W, dtype=jnp.float32) - cam.cx) / cam.fx
    vs = (jnp.arange(H, dtype=jnp.float32) - cam.cy) / cam.fy
    dx_c = jnp.broadcast_to(us[None, :], (H, W))
    dy_c = jnp.broadcast_to(vs[:, None], (H, W))
    dz_c = jnp.ones((H, W), jnp.float32)
    norm = jnp.sqrt(dx_c ** 2 + dy_c ** 2 + dz_c ** 2)
    d_cam = jnp.stack([dx_c, dy_c, dz_c], axis=-1) / norm[..., None]
    d_world = jnp.einsum("ij,hwj->hwi", R, d_cam)            # (H, W, 3)
    # Optical-axis component of the unit ray: converts euclidean sample
    # distance t to projective depth z = t * cos(angle to axis).
    cos_axis = d_cam[..., 2]                                  # (H, W)

    # Euclidean sample distances; max stretched so oblique rays can still
    # reach range_max in projective depth.
    t_max = cam.range_max_m / jnp.maximum(cos_axis.min(), 0.05)
    ts = jnp.linspace(cam.range_min_m, t_max, n_samples)      # (S,)
    # Sample positions: (H, W, S, 3) built lazily by broadcasting.
    px = pos[0] + d_world[..., 0:1] * ts                      # (H, W, S)
    py = pos[1] + d_world[..., 1:2] * ts
    pz = pos[2] + d_world[..., 2:3] * ts

    col = jnp.round(px / world_res_m + Ww / 2 - 0.5).astype(jnp.int32)
    row = jnp.round(py / world_res_m + Hw / 2 - 0.5).astype(jnp.int32)
    inb = (row >= 0) & (row < Hw) & (col >= 0) & (col < Ww)
    wall = world[jnp.clip(row, 0, Hw - 1), jnp.clip(col, 0, Ww - 1)] \
        & inb & (pz >= 0.0) & (pz <= wall_height_m)
    floor = pz <= 0.0
    hit = wall | floor

    any_hit = hit.any(axis=-1)
    first = jnp.argmax(hit, axis=-1)                          # (H, W)
    t_hit = ts[first]
    depth = t_hit * cos_axis                                  # projective z
    ok = any_hit & (depth >= cam.range_min_m) & (depth <= cam.range_max_m)
    return jnp.where(ok, depth, 0.0).astype(jnp.float32)


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 5))
def render_depths(cam: DepthCamConfig, world: Array, world_res_m: float,
                  n_samples: int, poses_xyyaw: Array,
                  wall_height_m: float = 0.5) -> Array:
    """vmap over a (B, 3) pose batch -> (B, H, W) depth images."""
    return jax.vmap(
        lambda p: render_depth(cam, world, world_res_m, n_samples, p,
                               wall_height_m)
    )(poses_xyyaw)
