"""Synthetic LD06 LiDAR: device-native raycasting against a world bitmap.

The real pipeline's sensor is the LD06 driver publishing ~360-beam
counterclockwise scans (`/root/reference/pi/src/thymio_project/launch/
pi_hardware.launch.py:13-21`). The simulator reproduces that contract on
device — but TPU-first: no per-ray marching loops. Every beam samples the
world at S fixed radial steps (one big gather), and the first hit distance
falls out of an argmax over the boolean hit profile. vmap over beams and
robots; everything static-shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax_mapping.config import ScanConfig

Array = jax.Array


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def simulate_scan(scan_cfg: ScanConfig, world: Array, world_res_m: float,
                  n_samples: int, pose: Array, noise_key=None,
                  noise_std_m: float = 0.0) -> Array:
    """One scan from `pose` against boolean `world` (centred indexing).

    Returns (padded_beams,) ranges in metres; beams that exit the world or
    exceed range_max report 0.0 — the LD06's "no return" code, which the
    ingest path treats as an outlier (`server/.../main.py:152`).
    """
    H, W = world.shape
    B = scan_cfg.padded_beams
    idx = jnp.arange(B, dtype=jnp.float32)
    ang = pose[2] + scan_cfg.angle_min_rad + idx * scan_cfg.angle_increment_rad
    if not scan_cfg.counterclockwise:
        ang = pose[2] - (scan_cfg.angle_min_rad
                         + idx * scan_cfg.angle_increment_rad)

    # Radial sample distances: (S,) from just past the robot to range_max.
    rs = jnp.linspace(scan_cfg.range_min_m, scan_cfg.range_max_m, n_samples)
    xs = pose[0] + jnp.cos(ang)[:, None] * rs[None, :]       # (B, S)
    ys = pose[1] + jnp.sin(ang)[:, None] * rs[None, :]
    col = jnp.round(xs / world_res_m + W / 2 - 0.5).astype(jnp.int32)
    row = jnp.round(ys / world_res_m + H / 2 - 0.5).astype(jnp.int32)
    inb = (row >= 0) & (row < H) & (col >= 0) & (col < W)
    hit = world[jnp.clip(row, 0, H - 1), jnp.clip(col, 0, W - 1)] & inb

    any_hit = hit.any(axis=1)
    first = jnp.argmax(hit, axis=1)                          # (B,)
    r = jnp.where(any_hit, rs[first], 0.0)
    if noise_key is not None:
        # noise_std_m is TRACED (not in static_argnums): comparing it in
        # Python would concretize the tracer, so gate inside the where.
        r = jnp.where(any_hit & (noise_std_m > 0.0),
                      r + noise_std_m * jax.random.normal(noise_key, r.shape),
                      r)
    # Padded tail beams report nothing.
    live = jnp.arange(B) < scan_cfg.n_beams
    return jnp.where(live, r, 0.0).astype(jnp.float32)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def simulate_scans(scan_cfg: ScanConfig, world: Array, world_res_m: float,
                   n_samples: int, poses: Array) -> Array:
    """vmap over a (R, 3) pose batch -> (R, padded_beams) scans."""
    return jax.vmap(
        lambda p: simulate_scan(scan_cfg, world, world_res_m, n_samples, p)
    )(poses)


def apply_lidar_miscal(poses, offset_rad):
    """Adversarial-fault boundary (`lidar_miscal`): a sensor mount
    rotated by `offset_rad` reports beam k's range for the world angle
    theta + offset + k*increment while still LABELLING it beam k — the
    exact effect of raycasting from a pose whose heading is offset.
    poses (R, 3), offset_rad (R,); returns the raycast poses (numpy)."""
    import numpy as np
    out = np.array(poses, np.float32, copy=True)
    out[:, 2] += np.asarray(offset_rad, np.float32)
    return out


def apply_ghost_returns(scan_cfg: ScanConfig, ranges, frac, rng,
                        short_max_m: float = 0.5):
    """Adversarial-fault boundary (`ghost_returns`): replace a seeded
    `frac` of the LIVE beams with spurious short ranges in
    [range_min, short_max_m] — dust, multipath, or a hostile reflector
    painting phantom walls right in front of the robot. Deterministic
    per (seed, step, robot) via the caller-owned `rng`.

    ranges (padded_beams,) float32, modified copy returned."""
    import numpy as np
    out = np.array(ranges, np.float32, copy=True)
    n = scan_cfg.n_beams
    mask = rng.random(n) < frac
    ghosts = rng.uniform(scan_cfg.range_min_m, short_max_m, n)
    out[:n] = np.where(mask, ghosts.astype(np.float32), out[:n])
    return out


def ir_proximity(world: Array, world_res_m: float, poses: Array,
                 max_dist_m: float = 0.12, n_samples: int = 16) -> Array:
    """Simulated Thymio front IR sensors: 5 horizontal proximity readings.

    The real robot reports prox.horizontal[0:5] across ~+-40 degrees with
    values up to ~4500 near contact (`server/.../main.py:98,125-137`). The
    sim maps obstacle distance linearly to that scale.
    """
    angles = jnp.linspace(-0.7, 0.7, 5)                       # sensor bearings
    H, W = world.shape

    def one(pose):
        a = pose[2] + angles                                  # (5,)
        rs = jnp.linspace(0.02, max_dist_m, n_samples)
        xs = pose[0] + jnp.cos(a)[:, None] * rs[None, :]
        ys = pose[1] + jnp.sin(a)[:, None] * rs[None, :]
        col = jnp.round(xs / world_res_m + W / 2 - 0.5).astype(jnp.int32)
        row = jnp.round(ys / world_res_m + H / 2 - 0.5).astype(jnp.int32)
        inb = (row >= 0) & (row < H) & (col >= 0) & (col < W)
        hit = world[jnp.clip(row, 0, H - 1), jnp.clip(col, 0, W - 1)] & inb
        any_hit = hit.any(axis=1)
        d = jnp.where(any_hit, rs[jnp.argmax(hit, axis=1)], max_dist_m)
        return jnp.where(any_hit,
                         4500.0 * (1.0 - d / max_dist_m), 0.0)

    return jax.vmap(one)(poses)
