"""Simulated Thymio fleet: actuation lag, wheel noise, kinematics.

Models both reference odometry regimes (SURVEY.md Appendix B): the server
reads *measured* wheel speeds (`server/.../main.py:96-97`) while the pi
variant integrated motor *targets* (`pi/src/.../main.py:188-191`) —
here motors follow targets through a first-order lag, and the "measured"
speeds are the lagged values plus calibration noise (report.pdf §V.B: 13%
coefficient of variation on K_d).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import RobotConfig
from jax_mapping.ops.odometry import rk2_step

Array = jax.Array


class FleetSimState(NamedTuple):
    poses: Array          # (R, 3) ground-truth poses
    wheel_speeds: Array   # (R, 2) actual [left, right] in thymio units
    key: Array            # PRNG


def init_fleet(robot: RobotConfig, key: Array, n_robots: int,
               spawn_radius_m: float = 0.5) -> FleetSimState:
    """Spawn robots on a ring near the origin, facing outward."""
    k1, k2 = jax.random.split(key)
    ang = jnp.linspace(0, 2 * jnp.pi, n_robots, endpoint=False)
    r = spawn_radius_m * (0.5 + 0.5 * jax.random.uniform(k1, (n_robots,)))
    poses = jnp.stack([r * jnp.cos(ang), r * jnp.sin(ang), ang], axis=-1)
    return FleetSimState(poses=poses,
                         wheel_speeds=jnp.zeros((n_robots, 2)),
                         key=k2)


@functools.partial(jax.jit, static_argnums=(0,))
def step_fleet(robot: RobotConfig, state: FleetSimState, targets: Array,
               dt: float, speed_noise_frac: float = 0.05
               ) -> tuple[FleetSimState, Array]:
    """Advance every robot dt seconds toward its (R, 2) wheel targets.

    Returns (new_state, measured_speeds): measured speeds are what the
    odometry path sees — actual wheel speeds with multiplicative noise.
    """
    key, k1 = jax.random.split(state.key)
    alpha = 1.0 - jnp.exp(-dt / robot.motor_lag_tau_s)
    actual = state.wheel_speeds + alpha * (targets - state.wheel_speeds)

    poses = jax.vmap(
        lambda p, w: rk2_step(robot, p, w[0], w[1], dt)
    )(state.poses, actual)

    noise = 1.0 + speed_noise_frac * jax.random.normal(k1, actual.shape)
    measured = actual * noise
    return FleetSimState(poses=poses, wheel_speeds=actual, key=key), measured


def apply_wheel_slip(measured, slip_factor):
    """Adversarial-fault boundary (resilience/faultplan.py `wheel_slip`):
    bias the MEASURED wheel speeds by a per-robot factor while ground
    truth motion is untouched — the odometry chain integrates motion the
    robot did not make, exactly what a slipping or miscalibrated wheel
    does to the hand-measured SPEED_COEFF (report.pdf §V.B: 13% CV).

    measured (R, 2) float; slip_factor (R,) float, 1.0 = healthy.
    numpy in, numpy out (the SimNode host boundary, pre-uint16 wire
    encoding)."""
    import numpy as np
    return np.asarray(measured) * np.asarray(slip_factor,
                                             np.float32)[:, None]


def step_robots_keyed(robot: RobotConfig, poses: Array, wheel_speeds: Array,
                      keys: Array, targets: Array, dt: float,
                      speed_noise_frac: float = 0.05):
    """Per-robot-keyed variant for shard_map (no cross-robot PRNG state):
    poses (R,3), wheel_speeds (R,2), keys (R,) PRNG keys, targets (R,2).
    Returns (poses, wheel_speeds, keys, measured)."""
    def one(pose, w, key, tgt):
        k_next, k1 = jax.random.split(key)
        alpha = 1.0 - jnp.exp(-dt / robot.motor_lag_tau_s)
        actual = w + alpha * (tgt - w)
        p2 = rk2_step(robot, pose, actual[0], actual[1], dt)
        measured = actual * (1.0 + speed_noise_frac
                             * jax.random.normal(k1, (2,)))
        return p2, actual, k_next, measured

    return jax.vmap(one)(poses, wheel_speeds, keys, targets)
